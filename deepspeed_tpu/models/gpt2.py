"""GPT-2 model family — the flagship training target.

The reference has no model zoo for training (users bring Megatron/HF
modules); its test fixtures use tiny nn.Modules (tests/unit/simple_model.py)
and the BASELINE targets are GPT-2 125M/350M/1.3B. Here the model is a
first-class citizen so the engine can be exercised end-to-end without torch.

TPU-first design decisions:
  * Layers are STACKED (leading layer dim) and iterated with ``lax.scan`` —
    one compiled block regardless of depth, fast XLA compiles at 1.3B+.
  * Tensor parallelism is *declarative*: ``partition_specs`` assigns the
    Megatron column/row split to the 'tensor' mesh axis and the forward
    inserts ``with_sharding_constraint`` on activations; GSPMD emits the
    psum/all_gathers (reference achieves this imperatively via an external
    mpu + module_inject/auto_tp.py:188).
  * Ulysses sequence parallelism is likewise declarative: inputs arrive
    sequence-sharded on the 'seq' axis, and attention constrains the heads
    dim onto 'seq' instead — XLA emits exactly the head-scatter/seq-gather
    all_to_all pair of the reference's DistributedAttention
    (deepspeed/sequence/layer.py:60).
  * Activation checkpointing = ``jax.checkpoint`` on the scanned block
    (reference runtime/activation_checkpointing/checkpointing.py:485).
  * bf16 params/activations, fp32 LayerNorm and loss, MXU-friendly dims.
"""

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..utils.groups import BATCH_AXES
from .common import (chunked_softmax_xent, constrain_fn, fused_linear_xent,
                     next_token_xent,
                     resolve_remat_policy)


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304          # 50257 padded to a multiple of 128 (MXU)
    max_seq_len: int = 1024
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    dropout: float = 0.0
    dtype: str = "bfloat16"
    remat: bool = True
    remat_policy: str = "nothing_saveable"
    # pallas flash kernel: "auto" (default) = on when running on TPU,
    # dense path elsewhere; True/False force. The benchmarked fast path
    # is the default — users no longer opt in via env/config.
    use_flash_attention: object = "auto"
    # pallas attention tile sizes. Each block knob (and flash_bwd_qmajor
    # below) also accepts "auto": the kernel then resolves it at trace
    # time against the persistent autotune winner cache for this
    # (device_kind, seq-bucket, head_dim, dtype) — falling back to the
    # r05-proven values below on a cache miss (ops/pallas/_common.
    # dispatch; see the README "Kernel autotuning" section)
    flash_block_q: object = 128
    flash_block_k: object = 128
    flash_block_h: object = 2          # (batch*head) instances per grid step
    flash_block_q_bwd: object = 0      # 0 = same as flash_block_q/_k; the
    flash_block_k_bwd: object = 0      # fused bwd pass may prefer smaller
    # feed the flash kernel (B, H, hd, T) operands (T in lanes) — the qkv
    # einsum's natural output layout, eliminating the relayout copies XLA
    # otherwise inserts at every kernel boundary (~46 ms/step at 350M)
    flash_qkv_t: bool = True
    # 'dense': GSPMD Ulysses resharding (all_to_all pair) when seq-sharded.
    # 'ring': ring/context-parallel attention (sequence/ring.py) — KV blocks
    #         rotate over the 'seq' axis; no head-count constraint.
    attention_backend: str = "dense"
    # pipeline parallelism (GPT2Pipe): microbatches in flight; 0 = auto
    # (2x the pipe axis size, amortizing the fill/drain bubble)
    pipe_microbatches: int = 0
    # pipeline training schedule: 'gpipe' (all-forward then autodiff
    # backward; residual memory grows with microbatch count), '1f1b'
    # (interleaved forward/backward, live activations bounded by
    # O(stages) — runtime/pipe/spmd.py pipeline_1f1b_grads), or 'zb'
    # (zero-bubble: 1F1B with the backward W/B split so weight-grad
    # work fills the drain ticks — pipeline_zb_grads; same memory
    # class, strictly lower executor bubble). The engine's pipeline
    # config block can override this when its schedule != 'auto'.
    pipe_schedule: str = "gpipe"
    # chunked cross entropy: unembed+CE computed per loss_chunk tokens
    # under remat so the full (B, T, V) fp32 logits never materialize
    # (0 = off). Big-vocab memory saver; exact same loss value.
    loss_chunk: int = 0
    # fused linear+CE with gradients computed IN FORWARD (the scalar-loss
    # custom_vjp trick — common.fused_linear_xent): removes the backward
    # logits-recompute matmul and a softmax pass vs the remat'd chunked
    # path. Requires loss_chunk > 0; same loss value.
    fused_loss: bool = False
    # + the Pallas unembed/online-stats kernel (ops/pallas/fused_ce.py):
    # fp32 logits never touch HBM; logz/gold exact, d_logits from the
    # bf16 logits (the MXU's own operand truncation)
    fused_loss_kernel: bool = False
    # lax.scan unroll over layers (1 = compact single-block program;
    # higher trades compile time/code size for cross-layer overlap)
    scan_unroll: int = 1
    # MLP activation: 'gelu' (gpt2) or 'relu' (opt)
    activation: str = "gelu"
    # gpt-neo knobs (reference module_inject/containers/gptneo.py):
    # scale_attn=False — HF GPT-Neo does NOT divide scores by sqrt(hd);
    # attn_layer_windows — per-layer sliding window from the config's
    # attention_types pattern (0 = global); non-empty forces the dense
    # attention path (the window is a per-layer scan operand)
    scale_attn: bool = True
    attn_layer_windows: tuple = ()
    # layout-owning Pallas MLP projection matmul (ops/pallas/
    # mlp_matmul.py; reference csrc/transformer/cublas_wrappers.cu —
    # the epilogue-fusing GEMM tier). Attacks the measured T-minor
    # wdown emitter penalty (~13 ms/step at 350M: XLA's
    # EmitOutputBatchInLanesKernelOutputFeatureInLanes half-rates the
    # down projection under the flash path's T-in-lanes layout
    # pressure) by giving the projection a kernel that consumes the
    # einsum's natural T-minor activation and emits the residual-add
    # layout directly, with the backward dx emitted in the activation's
    # own orientation and dw's fp32-accumulate + weight-dtype cast
    # fused. Values: False (XLA, default) | 'auto' (the autotune winner
    # cache's measured choice of path + tiles + epilogue for this
    # device/shape/dtype; r05-proven XLA einsums on a cache miss) |
    # 'down' (down projection only) | 'both' (up emits T-minor via the
    # kernel too). Not used when seq-sharded (Ulysses keeps the XLA
    # path).
    mlp_kernel: object = False
    # False leaves the weight grad to XLA (inside the layer scan it
    # fuses into the grad-stacking DUS at full MXU rate — the round-3
    # trace finding); True uses the kernel's fused fp32-accum dw
    mlp_kernel_fuse_dw: bool = True
    # q-major fused flash backward (ops/pallas/flash_attention.py
    # _bwd_kernel_t_qmajor): dq written once per grid step in the model
    # dtype (no fp32 HBM round trip + cast copy) and dk/dv accumulated
    # VMEM-resident across the sequential grid — the trick that won
    # -38 ms on dq, applied to the dkv side. qkv_t layouts only;
    # biased/ALiBi paths keep the k-major kernel. Accepts "auto"
    # (autotune winner cache, False on a miss).
    flash_bwd_qmajor: object = False
    # fused one-pass LayerNorm Pallas kernel (ops/pallas/layernorm.py;
    # reference csrc/transformer/normalize_kernels.cu). Measured SLOWER
    # than XLA's fused jnp layernorm inside the 350M training step (the
    # custom-call boundary breaks surrounding elementwise fusions and
    # pins layouts XLA wants freedom over: 727 -> 785 ms/step), so the
    # default is off; the kernel stays available for standalone use.
    # 'auto' = the autotune winner cache's measured jnp/fused/hybrid
    # choice (+ row tiling) for this device/shape/dtype, r05-proven jnp
    # on a cache miss; True forces the fused kernel.
    fused_layernorm: object = False

    @property
    def flash_on(self):
        """Resolved use_flash_attention (see common.resolve_flash)."""
        from .common import resolve_flash
        return resolve_flash(self.use_flash_attention)

    @property
    def d_head(self):
        return self.d_model // self.n_head

    @property
    def d_ff(self):
        return 4 * self.d_model

    def num_params(self):
        wte = self.vocab_size * self.d_model
        wpe = self.max_seq_len * self.d_model
        block = (4 * self.d_model  # ln scales/biases
                 + self.d_model * 3 * self.d_model + 3 * self.d_model
                 + self.d_model * self.d_model + self.d_model
                 + 2 * self.d_model * self.d_ff + self.d_ff + self.d_model)
        return wte + wpe + self.n_layer * block + 2 * self.d_model

    def flops_per_token(self):
        """6*N + attention flops per token (training fwd+bwd)."""
        n = self.num_params() - self.vocab_size * self.d_model
        return 6 * n + 12 * self.n_layer * self.d_model * self.max_seq_len


# BASELINE.md model points
GPT2_TINY = GPT2Config(n_layer=2, n_head=4, d_model=128, max_seq_len=128,
                       vocab_size=1024)
GPT2_125M = GPT2Config(n_layer=12, n_head=12, d_model=768)
GPT2_350M = GPT2Config(n_layer=24, n_head=16, d_model=1024)
GPT2_1_3B = GPT2Config(n_layer=24, n_head=32, d_model=2048)
# the GPT-3 13B shape (40 x 5120, 40 heads): the pipeline + host-offload
# target — does not fit one small-pod chip's HBM without pp>=2 and the
# offload tiers (ROADMAP item 4's measured point)
GPT2_13B = GPT2Config(n_layer=40, n_head=40, d_model=5120,
                      max_seq_len=2048)

PRESETS = {"tiny": GPT2_TINY, "125M": GPT2_125M, "350M": GPT2_350M,
           "1.3B": GPT2_1_3B, "13B": GPT2_13B}


def _dtype(cfg):
    return jnp.dtype(cfg.dtype)


class GPT2:
    """Functional model: ``init(rng) -> params``; ``loss(params, batch, rng)``.

    Params layout (all block tensors carry a leading n_layer dim):
      wte (V,D) | wpe (T,D) | lnf_{scale,bias} (D,)
      blocks: ln1_{scale,bias} (L,D), wqkv (L,D,3D), bqkv (L,3D),
              wo (L,D,D), bo (L,D), ln2_{scale,bias} (L,D),
              wup (L,D,F), bup (L,F), wdown (L,F,D), bdown (L,D)
    """

    def __init__(self, config: GPT2Config):
        self.config = config

    # --- init ---
    def init(self, rng):
        cfg = self.config
        dt = _dtype(cfg)
        k = iter(jax.random.split(rng, 16))
        std = 0.02
        # GPT-2 residual-projection scaling: std/sqrt(2L)
        res_std = std / math.sqrt(2 * cfg.n_layer)
        L, D, F, V, T = (cfg.n_layer, cfg.d_model, cfg.d_ff, cfg.vocab_size,
                         cfg.max_seq_len)

        def nrm(key, shape, s):
            return (jax.random.normal(key, shape, jnp.float32) * s).astype(dt)

        params = {
            "wte": nrm(next(k), (V, D), std),
            "wpe": nrm(next(k), (T, D), std),
            "lnf_scale": jnp.ones((D,), dt),
            "lnf_bias": jnp.zeros((D,), dt),
            "blocks": {
                "ln1_scale": jnp.ones((L, D), dt),
                "ln1_bias": jnp.zeros((L, D), dt),
                "wqkv": nrm(next(k), (L, D, 3 * D), std),
                "bqkv": jnp.zeros((L, 3 * D), dt),
                "wo": nrm(next(k), (L, D, D), res_std),
                "bo": jnp.zeros((L, D), dt),
                "ln2_scale": jnp.ones((L, D), dt),
                "ln2_bias": jnp.zeros((L, D), dt),
                "wup": nrm(next(k), (L, D, F), std),
                "bup": jnp.zeros((L, F), dt),
                "wdown": nrm(next(k), (L, F, D), res_std),
                "bdown": jnp.zeros((L, D), dt),
            },
        }
        return params

    # --- sharding rules ---
    def partition_specs(self, topology=None):
        """Megatron TP split on 'tensor' (reference module_inject/auto_tp.py
        does this by module-name heuristics; here it is the source of truth).
        Column-parallel: wqkv/wup (out dim); row-parallel: wo/wdown (in dim).
        Embeddings/LN replicated over 'tensor'."""
        return {
            "wte": P(),
            "wpe": P(),
            "lnf_scale": P(),
            "lnf_bias": P(),
            "blocks": {
                "ln1_scale": P(None, None),
                "ln1_bias": P(None, None),
                "wqkv": P(None, None, "tensor"),
                "bqkv": P(None, "tensor"),
                "wo": P(None, "tensor", None),
                "bo": P(None, None),
                "ln2_scale": P(None, None),
                "ln2_bias": P(None, None),
                "wup": P(None, None, "tensor"),
                "bup": P(None, "tensor"),
                "wdown": P(None, "tensor", None),
                "bdown": P(None, None),
            },
        }

    # --- forward ---
    moe_loss_coeff = 0.0  # overridden by GPT2MoE

    def apply(self, params, input_ids, *, rng=None, train=False,
              seq_sharded=False):
        """Return logits (B, T, V) fp32 (aux loss dropped)."""
        logits, _ = self.apply_with_aux(params, input_ids, rng=rng,
                                        train=train, seq_sharded=seq_sharded)
        return logits

    def _apply_ltd(self, params, input_ids, ltd_keep, *, rng, train,
                   constrain, act_spec):
        """Random-LTD forward (reference runtime/data_pipeline/
        data_routing + csrc/random_ltd/): first and last blocks see the
        full sequence; the middle blocks see ``ltd_keep`` random tokens
        (sorted indices preserve order/position), with dropped positions
        flowing through the skip connection. ``ltd_keep`` is static —
        distinct values are distinct programs, bounded by the schedule's
        seq_step quantization."""
        from ..runtime.data_pipeline.random_ltd import (token_drop,
                                                        token_restore)
        cfg = self.config
        if cfg.n_layer < 3:
            raise ValueError("random-LTD needs n_layer >= 3 (first and "
                             "last blocks stay full-sequence)")
        if cfg.attn_layer_windows:
            # windowed distances are undefined over LTD's gathered
            # (non-contiguous) token subsets — refuse loudly rather than
            # silently train all layers global
            raise ValueError("random-LTD is not supported with per-layer "
                             "local attention windows (attn_layer_windows)")
        T = input_ids.shape[1]
        x = self.embed(params, input_ids, rng=rng, train=train,
                       constrain=constrain, act_spec=act_spec)
        causal = jnp.tril(jnp.ones((T, T), jnp.bool_))
        base_rng = rng if rng is not None else jax.random.key(0)
        layer_rngs = jax.random.split(base_rng, cfg.n_layer)
        blocks = params["blocks"]
        first = jax.tree.map(lambda a: a[0], blocks)
        last = jax.tree.map(lambda a: a[-1], blocks)
        mid = jax.tree.map(lambda a: a[1:-1], blocks)

        x, aux0 = self.block_forward(
            x, first, layer_rngs[0], causal=causal, constrain=constrain,
            act_spec=act_spec, seq_sharded=False, train=train)
        x_keep, idx = token_drop(x, ltd_keep,
                                 jax.random.fold_in(base_rng, 0x17D))
        # gathered causal mask: kept token i attends kept token j iff
        # their ORIGINAL positions are causal
        mask = idx[:, :, None] >= idx[:, None, :]

        def mid_block(h, layer, lrng):
            return self.block_forward(
                h, layer, lrng, causal=mask, constrain=constrain,
                act_spec=act_spec, seq_sharded=False, train=train)

        block_fn = mid_block
        if cfg.remat:
            block_fn = jax.checkpoint(
                mid_block, policy=resolve_remat_policy(cfg.remat_policy))

        def scan_body(carry, xs):
            layer, lrng = xs
            h, aux = block_fn(carry, layer, lrng)
            return h, aux

        x_keep, auxs = lax.scan(scan_body, x_keep,
                                (mid, layer_rngs[1:-1]))
        x = token_restore(x_keep, idx, x)
        x, auxL = self.block_forward(
            x, last, layer_rngs[-1], causal=causal, constrain=constrain,
            act_spec=act_spec, seq_sharded=False, train=train)
        return x, aux0 + jnp.sum(auxs) + auxL

    def apply_with_aux(self, params, input_ids, *, rng=None, train=False,
                       seq_sharded=False, return_hidden=False):
        """Return (logits (B, T, V) fp32, summed aux loss) — aux is the MoE
        load-balance loss (0 for dense models). ``return_hidden`` skips the
        unembed and returns the (B, T, D) hidden states instead (the
        chunked-loss path).

        ``seq_sharded``: inputs/activations carry T on the 'seq' mesh axis
        (Ulysses). Attention re-constrains heads onto 'seq' so XLA emits the
        all_to_all pair.
        """
        cfg = self.config
        T = input_ids.shape[1]

        constrain = self._constrain_fn()
        act_spec = P(BATCH_AXES, "seq" if seq_sharded else None, None)
        x = self.embed(params, input_ids, rng=rng, train=train,
                       constrain=constrain, act_spec=act_spec)

        # causal mask built once; fp32 scores
        causal = jnp.tril(jnp.ones((T, T), jnp.bool_))

        def block(x, layer, lrng, window=None):
            return self.block_forward(x, layer, lrng, causal=causal,
                                      constrain=constrain, act_spec=act_spec,
                                      seq_sharded=seq_sharded, train=train,
                                      window=window)

        block_fn = block
        if cfg.attn_layer_windows and cfg.remat \
                and cfg.remat_policy == "split_attn":
            raise ValueError(
                "attn_layer_windows is not supported with "
                "remat_policy='split_attn' (the split block does not "
                "thread the per-layer window)")
        if cfg.remat and cfg.remat_policy == "split_attn":
            # jax NEVER stores custom_vjp residuals across a checkpoint
            # inside scan — a whole-block remat re-runs the flash forward
            # kernel in backward. Splitting the remat boundary keeps
            # attention OUTSIDE any checkpoint: its residuals (q, k, v, o,
            # lse) become ordinary scan residuals (saved), while the
            # cheap-to-recompute pre (ln1+qkv) and post (wo/ln2/MLP)
            # segments remat. Backward then runs zero extra flash kernels
            # and recomputes only matmul-light segments.
            def split_block(x, layer, lrng):
                hm = cfg.flash_on and not seq_sharded
                pre = jax.checkpoint(partial(
                    self.block_qkv, constrain=constrain, act_spec=act_spec,
                    heads_major=hm))
                q, kk, v = pre(x, layer)
                attn = self.block_attn(q, kk, v, causal=causal,
                                       constrain=constrain,
                                       seq_sharded=seq_sharded)
                post = jax.checkpoint(partial(
                    self.block_post, constrain=constrain, act_spec=act_spec,
                    seq_sharded=seq_sharded, train=train, heads_major=hm))
                return post(x, attn, layer, lrng)
            block_fn = split_block
        elif cfg.remat:
            block_fn = jax.checkpoint(
                block, policy=resolve_remat_policy(cfg.remat_policy))

        layer_rngs = jax.random.split(
            rng if rng is not None else jax.random.key(0), cfg.n_layer)

        # comm-overlap prefetch hint (engine-installed): unroll >= 2 puts
        # consecutive layers in one scan body so layer i+1's param gather
        # has layer i's matmuls to hide under (the explicit double buffer
        # XLA's ag-pipelining pass then rotates across iterations)
        unroll = max(cfg.scan_unroll,
                     getattr(self, "_scan_unroll_min", 0) or 0)

        if cfg.attn_layer_windows:
            # per-layer local windows ride the scan as an operand (not a
            # param: the optimizer never sees them)
            windows = jnp.asarray(cfg.attn_layer_windows, jnp.int32)

            def scan_body(carry, xs):
                layer, lrng, w = xs
                x, aux = block_fn(carry, layer, lrng, w)
                return x, aux

            x, auxs = lax.scan(scan_body, x,
                               (params["blocks"], layer_rngs, windows),
                               unroll=unroll)
        else:
            def scan_body(carry, xs):
                layer, lrng = xs
                x, aux = block_fn(carry, layer, lrng)
                return x, aux

            x, auxs = lax.scan(scan_body, x, (params["blocks"], layer_rngs),
                               unroll=unroll)
        if return_hidden:
            return x, jnp.sum(auxs)
        return self.head(params, x), jnp.sum(auxs)

    def _constrain_fn(self):
        return constrain_fn()

    def _ln(self, x, scale, bias):
        """LayerNorm dispatch: 'bwd' = jnp forward + one-pass Pallas
        backward (layernorm_fused_bwd); True = fully fused Pallas
        kernel; False = jnp; 'auto' = the autotune winner cache's
        measured choice for this (device, rows, D) — falling back to
        the r05-proven jnp form on a cache miss (XLA's fused layernorm
        measured faster inside real programs on v5e)."""
        use = self.config.fused_layernorm
        block_rows = "auto"
        if use == "auto":
            import math as _math
            from ..autotuning.kernel_registry import LN_DEFAULTS
            from ..ops.pallas._common import dispatch, dtype_name, \
                ln_bucket
            win = dispatch(
                "layernorm",
                ln_bucket(_math.prod(x.shape[:-1]), x.shape[-1]),
                dtype_name(x.dtype), LN_DEFAULTS)
            variant = win["variant"]
            if x.shape[-1] % 128:
                variant = "jnp"     # Pallas row-blocked kernels need
            use = {"jnp": False,    # a lane-tileable feature dim
                   "fused": True, "bwd": "bwd"}.get(variant, False)
            block_rows = int(win["block_rows"])
        if use == "bwd":
            from ..ops.pallas.layernorm import layernorm_fused_bwd
            return layernorm_fused_bwd(x, scale, bias,
                                       block_rows=block_rows)
        if use:
            from ..ops.pallas.layernorm import fused_layernorm
            return fused_layernorm(x, scale, bias,
                                   block_rows=block_rows)
        return _layernorm(x, scale, bias)

    def embed(self, params, input_ids, *, rng, train, constrain, act_spec):
        """Token + position embedding (B, T) -> (B, T, D); validates the
        train rng. Shared by the dense and pipelined paths."""
        cfg = self.config
        if train and rng is None and self._requires_train_rng():
            # without this, the key(0) fallback in apply_with_aux would
            # silently make dropout/noisy gating deterministic across steps
            raise ValueError(
                "train=True requires rng= (model uses stochastic "
                "dropout/routing)")
        T = input_ids.shape[1]
        pos = jnp.arange(T)[None, :]
        x = params["wte"][input_ids] + params["wpe"][pos]
        x = constrain(x.astype(_dtype(cfg)), act_spec)
        if train and cfg.dropout > 0 and rng is not None:
            x = _dropout(x, cfg.dropout, jax.random.fold_in(rng, 0))
        return x

    def head(self, params, x):
        """Final LN + tied-embedding unembed: (B, T, D) -> fp32 logits."""
        x = self._ln(x, params["lnf_scale"], params["lnf_bias"])
        return jnp.einsum("btd,vd->btv", x, params["wte"],
                          preferred_element_type=jnp.float32)

    def block_qkv(self, x, layer, *, constrain, act_spec,
                  heads_major=False):
        """ln1 + qkv projection: (B, T, D) -> q, k, v each (B, T, H, hd).
        With ``heads_major``: (B, H, hd, T) when cfg.flash_qkv_t (the
        default — the flash kernel's transposed-operand layout, matching
        the einsum's natural T-minor output so no relayout copy exists
        between the projection and the kernel), else (B, H, T, hd).
        Cheap to recompute in backward (one matmul whose output no grad
        rule needs — only ln1_out is, and that's VPU work)."""
        cfg = self.config
        B, T = x.shape[0], x.shape[1]
        H, hd = cfg.n_head, cfg.d_head
        h = self._ln(x, layer["ln1_scale"], layer["ln1_bias"])
        if heads_major:
            w = layer["wqkv"].reshape(x.shape[-1], 3, H, hd)
            b = layer["bqkv"].reshape(3, H, hd)
            if cfg.flash_qkv_t:
                # (B, H, hd, T): T-minor — the layout XLA prefers for the
                # einsum output (hd=64 fills only half a lane register),
                # consumed by the flash kernel with no relayout copy.
                # Three separate projections (not one (3, ...) einsum):
                # the fused form pays ~16 ms/step of repack fusions
                # splitting its output into q/k/v
                return tuple(
                    jnp.einsum("btd,dhe->bhet", h, w[:, i])
                    + b[i][:, :, None]
                    for i in range(3))
            qkv = jnp.einsum("btd,dshe->sbhte", h, w) \
                + b[:, None, :, None, :]
            return qkv[0], qkv[1], qkv[2]
        qkv = h @ layer["wqkv"] + layer["bqkv"]
        qkv = qkv.reshape(B, T, 3, H, hd)
        return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    def block_attn(self, q, kk, v, *, causal, constrain, seq_sharded,
                   force_dense=False, window=None):
        """Attention backend dispatch: (B, T, H, hd) x3 -> (B, T, H, hd).
        ``causal`` may carry a batch dim (B, t, s) — the random-LTD
        middle segment attends gathered (non-contiguous) positions, which
        also forces the dense path (``force_dense``). ``window``: traced
        per-layer sliding window (gpt-neo local attention; 0 = global),
        dense path only."""
        cfg = self.config
        dt = _dtype(cfg)
        if window is not None and causal.ndim == 2:
            T_ = causal.shape[-1]
            qp, kp = jnp.arange(T_)[:, None], jnp.arange(T_)[None, :]
            causal = causal & ((window == 0) | (qp - kp < window))
        if (seq_sharded and cfg.attention_backend == "ring"
                and not jax.sharding.get_abstract_mesh().empty):
            if window is not None or not cfg.scale_attn:
                raise ValueError(
                    "ring attention supports neither per-layer local "
                    "windows nor unscaled (gpt-neo) scores")
            # context parallel: KV rotates the 'seq' ring (ppermute).
            # Layout/kernel/overlap knobs come from the engine-installed
            # runtime config 'sequence' block (zigzag + blockwise flash
            # kernel + double-buffered rotation by default)
            from ..runtime.config import SequenceConfig
            from ..sequence.ring import ring_attention_sharded
            scfg = getattr(self, "_sequence_cfg", None) or SequenceConfig()
            attn = ring_attention_sharded(
                q, kk, v, jax.sharding.get_abstract_mesh(),
                batch_spec=P(BATCH_AXES), head_axis="tensor",
                layout=scfg.layout, block_kernel=scfg.block_kernel,
                double_buffer=scfg.double_buffer,
                rotate_chunks=getattr(scfg, "rotate_chunks", "auto"))
        elif cfg.flash_on and not seq_sharded and not force_dense:
            # pallas fused attention: O(T) memory, fp32 accumulation
            # (ops/pallas/flash_attention.py). Heads shard over 'tensor'.
            # Inputs arrive from block_qkv as (B, H, hd, T) when
            # cfg.flash_qkv_t (default), else heads-major (B, H, T, hd).
            from ..ops.pallas.flash_attention import flash_attention
            head_spec = P(BATCH_AXES, "tensor", None, None)
            q = constrain(q, head_spec)
            kk = constrain(kk, head_spec)
            v = constrain(v, head_spec)
            attn = flash_attention(
                q, kk, v, causal=True,
                scale=None if cfg.scale_attn else 1.0,
                block_q=cfg.flash_block_q,
                block_k=cfg.flash_block_k,
                block_h=cfg.flash_block_h,
                block_q_bwd=cfg.flash_block_q_bwd or None,
                block_k_bwd=cfg.flash_block_k_bwd or None,
                heads_major=not cfg.flash_qkv_t,
                qkv_t=cfg.flash_qkv_t,
                bwd_qmajor=cfg.flash_bwd_qmajor).astype(dt)
            from jax.ad_checkpoint import checkpoint_name
            attn = checkpoint_name(attn, "attn_out")
        else:
            if seq_sharded:
                # Ulysses: heads onto 'seq', sequence gathered
                head_spec = P(BATCH_AXES, None, "seq", None)
            else:
                head_spec = P(BATCH_AXES, None, "tensor", None)
            q = constrain(q, head_spec)
            kk = constrain(kk, head_spec)
            v = constrain(v, head_spec)

            scores = jnp.einsum("bthd,bshd->bhts", q, kk,
                                preferred_element_type=jnp.float32)
            if cfg.scale_attn:
                scores = scores / math.sqrt(self.config.d_head)
            mask = causal[None, None] if causal.ndim == 2 \
                else causal[:, None]
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(dt)
            attn = jnp.einsum("bhts,bshd->bthd", probs, v)
            from jax.ad_checkpoint import checkpoint_name
            attn = checkpoint_name(attn, "attn_out")
        return attn

    def block_post(self, x, attn, layer, lrng, *, constrain, act_spec,
                   seq_sharded, train, heads_major=False):
        """Output projection residual + ln2 + MLP residual. ``attn`` is
        (B, T, H, hd), or (B, H, T, hd) when ``heads_major`` (flash path
        — the wo projection contracts (h, e) directly, no transpose)."""
        cfg = self.config
        B, T = x.shape[0], x.shape[1]
        if heads_major:
            wo = layer["wo"].reshape(cfg.n_head, cfg.d_head, cfg.d_model)
            x = x + jnp.einsum("bhte,hed->btd", attn, wo) + layer["bo"]
        else:
            attn = attn.reshape(B, T, cfg.n_head * cfg.d_head)
            attn = constrain(attn, act_spec)
            x = x + attn @ layer["wo"] + layer["bo"]
        x = constrain(x, act_spec)
        from jax.ad_checkpoint import checkpoint_name
        # named so remat policies can keep the post-attention residual
        # stream (remat_policy='save_mid'/'save_mid_up'): backward then
        # recomputes only ln2 + the MLP instead of the attention half too
        x = checkpoint_name(x, "attn_mid")

        h = self._ln(x, layer["ln2_scale"], layer["ln2_bias"])
        mlp_out, aux = self._mlp(h, layer, lrng, train=train,
                                 seq_sharded=seq_sharded,
                                 constrain=constrain)
        x = x + mlp_out
        x = constrain(x, act_spec)
        # named block output: policies saving 'block_out' make each
        # layer's INPUT directly available in backward — without it, a
        # names-policy inside lax.scan reconstructs x_in_{l+1} by
        # replaying the whole l-th MLP forward (an extra ~2.4 ms/layer
        # wdown matmul on a layout XLA emits badly)
        x = checkpoint_name(x, "block_out")
        return x, aux

    def block_forward(self, x, layer, lrng, *, causal, constrain, act_spec,
                      seq_sharded, train, window=None):
        """One transformer block: (B, T, D) -> (B, T, D), plus aux loss.
        Shared by the dense scan path and the pipelined executor
        (models/gpt2_pipe.py)."""
        # engine-installed comm-overlap annotation (runtime/zero/
        # overlap.py): explicit ZeRO-3 gather of this layer's shard in
        # forward, per-scan-iteration grad reduce-scatter in backward
        hook = getattr(self, "_layer_comm_hook", None)
        if hook is not None:
            layer = hook(layer)
        from ..ops.int8_weights import dequant_tree
        layer = dequant_tree(layer, _dtype(self.config))
        # dense path for: random-LTD gathered masks and per-layer local
        # windows (a traced scan operand cannot pick a kernel per layer);
        # unscaled gpt-neo attention keeps the flash kernel via its
        # scale input
        force_dense = causal.ndim != 2 or window is not None
        hm = self.config.flash_on and not seq_sharded and not force_dense
        q, kk, v = self.block_qkv(x, layer, constrain=constrain,
                                  act_spec=act_spec, heads_major=hm)
        attn = self.block_attn(q, kk, v, causal=causal, constrain=constrain,
                               seq_sharded=seq_sharded,
                               force_dense=force_dense, window=window)
        return self.block_post(x, attn, layer, lrng, constrain=constrain,
                               act_spec=act_spec, seq_sharded=seq_sharded,
                               train=train, heads_major=hm)

    def _requires_train_rng(self):
        """True when a training forward is stochastic (overridden by
        GPT2MoE for noisy gating / top-2 sampling)."""
        return self.config.dropout > 0

    def _mlp_kernel_mode(self):
        """Resolved cfg.mlp_kernel: None (XLA path) | 'down' | 'both' |
        'auto' (= consult the autotune winner cache in _mlp, where the
        activation shape that keys the cache bucket is known; a miss
        falls back to the r05-proven XLA path)."""
        v = self.config.mlp_kernel
        if not v:
            return None
        if v == "auto":
            return "auto"
        return "down" if v is True else v

    def _mlp(self, h, layer, rng, *, train, seq_sharded, constrain):
        """Dense MLP; overridden by GPT2MoE with an expert-parallel MoE.
        Returns (output, aux_loss)."""
        from jax.ad_checkpoint import checkpoint_name
        acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}
        if self.config.activation not in acts:
            raise ValueError(
                f"unknown activation {self.config.activation!r}; "
                f"expected one of {sorted(acts)}")
        from ..ops.int8_weights import _is_q
        if _is_q(layer["wup"]):
            # weight-only quantized serving FFN (engine weight_quant):
            # dequant fused into the projection kernel's flush epilogue
            from ..ops.pallas.mlp_matmul import wq_matmul
            u = wq_matmul(h, layer["wup"]) + layer["bup"]
            up = acts[self.config.activation](u)
            out = wq_matmul(up, layer["wdown"]) + layer["bdown"]
            return out, jnp.zeros((), jnp.float32)
        q8 = getattr(self, "_int8_matmul", False)
        if q8 == "auto" and not seq_sharded:
            # measured W8A8 lever (quantize.int8_matmul="auto"): the
            # 'mlp_int8' winner for this shape bucket — winners must
            # pass the registry parity gate before caching, and a cold
            # cache keeps the exact fp program
            from ..ops.pallas._common import dispatch, dtype_name, \
                mlp_bucket
            D, F = layer["wup"].shape
            q8 = bool(dispatch("mlp_int8", mlp_bucket(h.shape[1], D, F),
                               dtype_name(h.dtype), {"int8": 0})["int8"])
        if q8 and q8 != "auto":
            # W8A8 compute: dynamic rowwise activation codes x
            # channelwise weight codes, int32 accumulate, straight-
            # through fp grads (ops/pallas/quantization.int8_matmul)
            from ..ops.pallas.quantization import int8_matmul
            u = checkpoint_name(int8_matmul(h, layer["wup"])
                                + layer["bup"], "mlp_up")
            up = acts[self.config.activation](u)
            up = constrain(up, P(BATCH_AXES,
                                 "seq" if seq_sharded else None, "tensor"))
            return (int8_matmul(up, layer["wdown"]) + layer["bdown"],
                    jnp.zeros((), jnp.float32))
        mode = self._mlp_kernel_mode() if not seq_sharded else None
        mm_kw = dict(fuse_dw=self.config.mlp_kernel_fuse_dw)
        if mode == "auto":
            # measured dispatch: the cached winner for this (device,
            # tokens, D, F) picks the projection path AND its tile/
            # epilogue knobs; a miss keeps the r05-proven XLA einsums
            from ..autotuning.kernel_registry import MLP_DEFAULTS
            from ..ops.pallas._common import dispatch, dtype_name, \
                mlp_bucket
            D, F = layer["wup"].shape
            win = dispatch(
                "mlp_matmul", mlp_bucket(h.shape[1], D, F),
                dtype_name(h.dtype),
                {**MLP_DEFAULTS,
                 "fuse_dw": self.config.mlp_kernel_fuse_dw})
            mode = None if win["mode"] == "xla" else win["mode"]
            mm_kw = dict(fuse_dw=bool(win["fuse_dw"]),
                         block_t=int(win["block_t"]),
                         block_o=int(win["block_o"]),
                         block_k=int(win["block_k"]))
        if mode:
            # layout-owning projection kernels: the pre-activation is
            # carried (B, F, T) — the up einsum's NATURAL T-minor output
            # (no transpose anywhere) — and the down kernel consumes it
            # directly, emitting the residual-add (B, T, D) layout, so
            # neither XLA's half-rate T-minor wdown emitter nor the
            # backward relayout copies exist on this path
            from ..ops.pallas.mlp_matmul import mlp_matmul
            if mode == "both":
                u = mlp_matmul(h, layer["wup"], out_t=True, **mm_kw)
            else:
                u = jnp.einsum("btd,df->bft", h, layer["wup"])
            u = checkpoint_name(u + layer["bup"][None, :, None], "mlp_up")
            up = acts[self.config.activation](u)
            up = constrain(up, P(BATCH_AXES, "tensor", None))
            out = mlp_matmul(up, layer["wdown"], x_t=True, **mm_kw)
            return out + layer["bdown"], jnp.zeros((), jnp.float32)
        # named pre-activation: saving it skips the wup matmul recompute in
        # backward (gelu' needs this tensor; gelu_out is one VPU op away)
        u = checkpoint_name(h @ layer["wup"] + layer["bup"], "mlp_up")
        up = acts[self.config.activation](u)
        up = constrain(up, P(BATCH_AXES, "seq" if seq_sharded else None,
                             "tensor"))
        return (up @ layer["wdown"] + layer["bdown"],
                jnp.zeros((), jnp.float32))

    # --- KV-cache inference path (reference ops/transformer/inference/
    #     ds_attention.py:16 + inference_context.h workspace mgmt; here the
    #     cache is an explicit pytree threaded through jitted steps) ---
    def init_cache(self, batch_size, max_len, dtype=None):
        """Allocate the KV cache: {'k','v'}: (L, B, max_len, H, hd)."""
        cfg = self.config
        dt = jnp.dtype(dtype) if dtype is not None else _dtype(cfg)
        shape = (cfg.n_layer, batch_size, max_len, cfg.n_head, cfg.d_head)
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def cache_specs(self, batch_axes=BATCH_AXES):
        """Sharding for the KV cache: batch over data axes, heads over
        'tensor' (matches the attention TP split)."""
        spec = P(None, batch_axes, None, "tensor", None)
        return {"k": spec, "v": spec}

    def _block_core(self, x, layer, attn_fn):
        """Shared block scaffolding for every cache-backed inference path:
        ln1 -> qkv projection -> ``attn_fn`` -> output projection residual
        -> ln2 -> mlp residual. ``attn_fn((B,T,H,hd) q, k, v) -> (attn
        (B,T,H,hd), carry)`` owns masking and any cache reads/writes.
        Returns (x_out, carry)."""
        cfg = self.config
        from ..ops.int8_weights import dequant_tree
        keep = self._WQ_KEEP \
            if getattr(self, "_weight_quant_fused", False) else ()
        layer = dequant_tree(layer, _dtype(cfg), keep=keep)
        B, T = x.shape[0], x.shape[1]
        H, hd = cfg.n_head, cfg.d_head
        h = self._ln(x, layer["ln1_scale"], layer["ln1_bias"])
        qkv = (h @ layer["wqkv"] + layer["bqkv"]).reshape(B, T, 3, H, hd)
        attn, carry = attn_fn(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
        x = x + attn.reshape(B, T, H * hd) @ layer["wo"] + layer["bo"]
        h = self._ln(x, layer["ln2_scale"], layer["ln2_bias"])
        mlp_out, _ = self._mlp(h, layer, None, train=False,
                               seq_sharded=False,
                               constrain=lambda t, s: t)
        return x + mlp_out, carry

    def block_forward_cached(self, x, layer, k_cache, v_cache, slot,
                             valid_mask, window=None):
        """One block over new tokens with a KV cache.

        x: (B, T, D) new-token activations, written at cache slots
        [slot, slot+T). k_cache/v_cache: (B, Tmax, H, hd).
        valid_mask: (B, Tmax) bool — True where the cache holds a real
        token AFTER this write (left-padded prompts carry False slots).
        ``window``: traced per-layer local window (gpt-neo; 0 = global).
        Returns (x_out, k_cache, v_cache).
        """
        cfg = self.config
        dt = _dtype(cfg)
        T = x.shape[1]
        hd = cfg.d_head
        Tmax = k_cache.shape[1]

        def attn_fn(q, kk, v):
            kc = lax.dynamic_update_slice(k_cache, kk.astype(k_cache.dtype),
                                          (0, slot, 0, 0))
            vc = lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype),
                                          (0, slot, 0, 0))
            scores = jnp.einsum("bthd,bshd->bhts", q, kc,
                                preferred_element_type=jnp.float32)
            if cfg.scale_attn:
                scores = scores / math.sqrt(hd)
            # slot-causal: query at slot s_q = slot+t sees slots s <= s_q
            # that hold valid tokens (pads masked out forever)
            s_idx = jnp.arange(Tmax)[None, None, None, :]
            q_idx = (slot + jnp.arange(T))[None, None, :, None]
            mask = (s_idx <= q_idx) & valid_mask[:, None, None, :]
            if window is not None:
                mask = mask & ((window == 0) | (q_idx - s_idx < window))
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(dt)
            return jnp.einsum("bhts,bshd->bthd", probs, vc), (kc, vc)

        x, (kc, vc) = self._block_core(x, layer, attn_fn)
        return x, kc, vc

    def apply_cached(self, params, input_ids, pos_ids, cache, slot,
                     valid_mask, last_token_only=False):
        """Forward T new tokens through all layers with the KV cache.

        input_ids: (B, T); pos_ids: (B, T) absolute position-embedding
        indices (left-padded prompts offset these); slot: scalar cache
        write offset; valid_mask: (B, Tmax) validity AFTER the write.
        Returns (logits (B, T, V) fp32, new cache); ``last_token_only``
        unembeds just the final position (prefill only samples there —
        skips the (B, T, V) fp32 logits materialization).
        """
        x = (params["wte"][input_ids]
             + params["wpe"][pos_ids]).astype(_dtype(self.config))

        if self.config.attn_layer_windows:
            windows = jnp.asarray(self.config.attn_layer_windows, jnp.int32)

            def body(carry, xs):
                layer, kc, vc, w = xs
                y, kc, vc = self.block_forward_cached(carry, layer, kc, vc,
                                                      slot, valid_mask, w)
                return y, (kc, vc)

            x, (kc, vc) = lax.scan(
                body, x, (params["blocks"], cache["k"], cache["v"], windows))
        else:
            def body(carry, xs):
                layer, kc, vc = xs
                y, kc, vc = self.block_forward_cached(carry, layer, kc, vc,
                                                      slot, valid_mask)
                return y, (kc, vc)

            x, (kc, vc) = lax.scan(body, x,
                                   (params["blocks"], cache["k"], cache["v"]))
        if last_token_only:
            x = x[:, -1:]
        return self.head(params, x), {"k": kc, "v": vc}

    # --- paged (blocked) KV-cache path for the v2 serving engine
    #     (reference inference/v2/kernels/ragged_ops blocked_flash +
    #     ragged/kv_cache.py BlockedKVCache; here the cache is a pool of
    #     fixed-size blocks indexed by per-sequence block tables) ---
    def init_paged_cache(self, num_blocks, block_size, dtype=None):
        """{'k','v'}: LISTS of per-layer (num_blocks, H, block_size, hd)
        pools, heads-major (the Pallas paged-decode kernel's (H, BS, hd)
        block needs no in-VMEM transpose). Separate per-layer buffers —
        not one stacked (L, ...) array — so each layer's new-token scatter
        updates its own donated buffer IN PLACE; a stacked array carried
        through lax.scan gets defensively copied every layer (custom-call
        operand + carry), ~the whole pool per layer. Block 0 is the
        scratch block (pad/inactive writes land there)."""
        cfg = self.config
        dt = jnp.dtype(dtype) if dtype is not None else _dtype(cfg)
        shape = (num_blocks, cfg.n_head, block_size, cfg.d_head)
        return {"k": [jnp.zeros(shape, dt) for _ in range(cfg.n_layer)],
                "v": [jnp.zeros(shape, dt) for _ in range(cfg.n_layer)]}

    def paged_cache_specs(self):
        spec = P(None, "tensor", None, None)
        L = self.config.n_layer
        return {"k": [spec] * L, "v": [spec] * L}

    # FFN weight keys the fused-dequant serving path keeps quantized
    # (engine_v2 sets _weight_quant_fused; _mlp routes them through
    # wq_matmul's fused epilogue)
    _WQ_KEEP = ("wup", "wdown")

    def _layer_slice(self, params, i):
        """Static per-layer view of the stacked block params (int8
        serving weights dequantize here, one layer at a time; under the
        fused weight-quant path the FFN weights stay quantized)."""
        from ..ops.int8_weights import dequant_tree
        sl = jax.tree.map(lambda a: a[i], params["blocks"])
        keep = self._WQ_KEEP \
            if getattr(self, "_weight_quant_fused", False) else ()
        return dequant_tree(sl, _dtype(self.config), keep=keep)

    def apply_paged_prefill(self, params, input_ids, cache, token_blocks,
                            token_offsets, length):
        """Prefill ONE sequence into the paged cache.

        input_ids: (1, T_pad) right-padded prompt; token_blocks/
        token_offsets: (T_pad,) destination block / in-block slot per
        position (pads point at scratch block 0); length: scalar true
        prompt length. Returns (logits (1, V) at position length-1, cache).

        The kernel path (engine ``paged_kernel``) runs the chunked
        paged kernel with ``start=0`` over the prompt's own blocks
        (table derived from the per-token destinations): causally-dead
        and beyond-length blocks are skipped instead of masked after a
        full (T, T) score matrix.
        """
        cfg = self.config
        dt = _dtype(cfg)
        T = input_ids.shape[1]
        hd = cfg.d_head
        pos = jnp.arange(T)[None, :]
        x = (params["wte"][input_ids] + params["wpe"][pos]).astype(dt)
        valid = (jnp.arange(T) < length)
        causal = jnp.tril(jnp.ones((T, T), jnp.bool_))
        mask = causal & valid[None, :]
        qp, kp = jnp.arange(T)[:, None], jnp.arange(T)[None, :]
        BS = cache["k"][0].shape[2]
        # every block the prompt touches, from its per-token placement
        # (tokens are laid contiguously from position 0, so position
        # m*BS's destination block IS table entry m; pads are scratch 0)
        prefill_table = token_blocks[::BS]
        from ..ops.pallas.paged_attention import (paged_chunk_attention,
                                                  resolve_paged_chunk)
        use_kernel, block_c = resolve_paged_chunk(
            getattr(self, "_paged_kernel", "auto"),
            getattr(self, "_paged_block_c", "auto"),
            T, prefill_table.shape[0], BS, cfg.n_head, 1, hd, dt)

        ks_out, vs_out = [], []
        for i in range(cfg.n_layer):
            layer = self._layer_slice(params, i)
            kc0, vc0 = cache["k"][i], cache["v"][i]
            w = cfg.attn_layer_windows[i] if cfg.attn_layer_windows else 0
            m = mask & (qp - kp < w) if w else mask

            def attn_fn(q, kk, v, kc0=kc0, vc0=vc0, m=m, w=w):
                # in-place scatter on this layer's own donated pool buffer
                kc = kc0.at[token_blocks, :, token_offsets].set(
                    kk[0].astype(kc0.dtype))
                vc = vc0.at[token_blocks, :, token_offsets].set(
                    v[0].astype(vc0.dtype))
                if use_kernel:
                    attn = paged_chunk_attention(
                        q[0], kc, vc, prefill_table, jnp.int32(0),
                        length, scale=None if cfg.scale_attn else 1.0,
                        window=w, block_c=block_c)
                    return attn[None], (kc, vc)
                scores = jnp.einsum("bthd,bshd->bhts", q, kk,
                                    preferred_element_type=jnp.float32)
                if cfg.scale_attn:
                    scores = scores / math.sqrt(hd)
                scores = jnp.where(m[None, None], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1).astype(dt)
                return jnp.einsum("bhts,bshd->bthd", probs, v), (kc, vc)

            x, (kc, vc) = self._block_core(x, layer, attn_fn)
            ks_out.append(kc)
            vs_out.append(vc)
        last = jnp.take_along_axis(
            x, jnp.maximum(length - 1, 0)[None, None, None], axis=1)
        return self.head(params, last)[:, 0], {"k": ks_out, "v": vs_out}

    def apply_paged_chunk(self, params, input_ids, cache, token_blocks,
                          token_offsets, start, true_len, table):
        """Prefill ONE CHUNK of one sequence into the paged cache (the
        Dynamic SplitFuse chunk program; see Llama.apply_paged_chunk —
        same contract, GPT-2's learned positions and full-head cache).

        On the kernel path (engine ``paged_kernel``; "auto" = the
        autotune winner cache's choice, kernel on TPU / dense-gather
        elsewhere on a cold cache) attention runs the Pallas
        chunked-prefill paged kernel reading K/V straight through the
        block table — the full (S, H, hd) gather never materializes."""
        cfg = self.config
        dt = _dtype(cfg)
        C = input_ids.shape[1]
        H, hd = cfg.n_head, cfg.d_head
        BS = cache["k"][0].shape[2]
        pos = jnp.minimum(start + jnp.arange(C), cfg.max_seq_len - 1)
        x = (params["wte"][input_ids]
             + params["wpe"][pos][None]).astype(dt)
        S = table.shape[0] * BS
        q_pos = (start + jnp.arange(C))[:, None]
        k_pos = jnp.arange(S)[None, :]
        mask = (k_pos <= q_pos) & (k_pos < start + true_len)
        from ..ops.pallas.paged_attention import (paged_chunk_attention,
                                                  resolve_paged_chunk)
        use_kernel, block_c = resolve_paged_chunk(
            getattr(self, "_paged_kernel", "auto"),
            getattr(self, "_paged_block_c", "auto"),
            C, table.shape[0], BS, H, 1, hd, dt)

        ks_out, vs_out = [], []
        for i in range(cfg.n_layer):
            layer = self._layer_slice(params, i)
            kc0, vc0 = cache["k"][i], cache["v"][i]
            w = cfg.attn_layer_windows[i] if cfg.attn_layer_windows else 0
            m = mask & (q_pos - k_pos < w) if w else mask

            def attn_fn(q, kk, v, kc0=kc0, vc0=vc0, m=m, w=w):
                kc = kc0.at[token_blocks, :, token_offsets].set(
                    kk[0].astype(kc0.dtype))
                vc = vc0.at[token_blocks, :, token_offsets].set(
                    v[0].astype(vc0.dtype))
                if use_kernel:
                    attn = paged_chunk_attention(
                        q[0], kc, vc, table, start, true_len,
                        scale=None if cfg.scale_attn else 1.0,
                        window=w, block_c=block_c)
                    return attn[None], (kc, vc)
                gk = kc[table].transpose(0, 2, 1, 3).reshape(S, H, hd)
                gv = vc[table].transpose(0, 2, 1, 3).reshape(S, H, hd)
                scores = jnp.einsum("bthd,shd->bhts", q, gk,
                                    preferred_element_type=jnp.float32)
                if cfg.scale_attn:
                    scores = scores / math.sqrt(hd)
                scores = jnp.where(m[None, None], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1).astype(dt)
                return jnp.einsum("bhts,shd->bthd", probs, gv), (kc, vc)

            x, (kc, vc) = self._block_core(x, layer, attn_fn)
            ks_out.append(kc)
            vs_out.append(vc)
        last = jnp.take_along_axis(
            x, jnp.maximum(true_len - 1, 0)[None, None, None], axis=1)
        return self.head(params, last)[:, 0], {"k": ks_out, "v": vs_out}

    def apply_paged_decode(self, params, tokens, lengths, cache,
                           block_tables):
        """One decode step for a fixed-size batch over the paged cache.

        tokens: (B,) next input token per slot; lengths: (B,) tokens
        already in cache (the new token's position); block_tables:
        (B, MB) int32 block ids (inactive slots point at scratch block 0).
        Returns (logits (B, V), cache).
        """
        cfg = self.config
        B = tokens.shape[0]
        BS = cache["k"][0].shape[2]

        pos = jnp.minimum(lengths, cfg.max_seq_len - 1)
        x = (params["wte"][tokens[:, None]]
             + params["wpe"][pos[:, None]]).astype(_dtype(cfg))
        dst_block = jnp.take_along_axis(
            block_tables, (lengths // BS)[:, None], axis=1)[:, 0]
        dst_off = lengths % BS
        from ..ops.pallas.paged_attention import resolve_paged_decode
        use_kernel = resolve_paged_decode(
            getattr(self, "_paged_kernel", "auto"), B,
            block_tables.shape[1], BS, cfg.n_head, 1, cfg.d_head,
            _dtype(cfg))

        ks_out, vs_out = [], []
        for i in range(cfg.n_layer):
            layer = self._layer_slice(params, i)
            kc0, vc0 = cache["k"][i], cache["v"][i]
            w = cfg.attn_layer_windows[i] if cfg.attn_layer_windows else 0

            def attn_fn(q, kk, v, kc0=kc0, vc0=vc0, w=w):
                # q/kk/v: (B, 1, H, hd) — the single new token per slot.
                # In-place write into this layer's donated pool, then the
                # Pallas paged kernel reads K/V straight through the block
                # table (no dense gather; reference
                # inference/v2/kernels/ragged_ops blocked_flash). The
                # dense-gather reference stays behind paged_kernel=False
                # as the parity/A-B fallback.
                from ..ops.pallas.paged_attention import (
                    paged_decode_attention,
                    paged_decode_attention_reference)
                kc = kc0.at[dst_block, :, dst_off].set(
                    kk[:, 0].astype(kc0.dtype))
                vc = vc0.at[dst_block, :, dst_off].set(
                    v[:, 0].astype(vc0.dtype))
                fn = paged_decode_attention if use_kernel \
                    else paged_decode_attention_reference
                attn = fn(
                    q[:, 0], kc, vc, block_tables, lengths,
                    scale=None if cfg.scale_attn else 1.0, window=w)
                return attn[:, None], (kc, vc)

            x, (kc, vc) = self._block_core(x, layer, attn_fn)
            ks_out.append(kc)
            vs_out.append(vc)
        return self.head(params, x)[:, 0], {"k": ks_out, "v": vs_out}

    def apply_paged_verify(self, params, tokens, lengths, cache,
                           block_tables):
        """Speculative-verify step: C tokens per slot in ONE pass.

        tokens: (B, C) — per slot, the last committed token followed by
        the draft proposals; lengths: (B,) tokens already in cache (the
        first input token's position, i.e. ``seen_tokens - 1``);
        block_tables: (B, MB) as in decode (inactive slots all-scratch
        with lengths 0). Returns (logits (B, C, V), cache) — logits at
        EVERY position, so the host can take the longest accepted
        prefix plus the bonus token.

        This is the batched split-fuse ride: each slot's C-token span is
        a chunk with ``start=lengths[b]``/``true_len=C`` through the
        same ``paged_chunk_attention`` kernel the prefill chunks use;
        the dense fallback is the batched gather the decode reference
        uses, with a per-slot causal frontier. Writes beyond a slot's
        committed frontier land in its already-allocated blocks and are
        either committed (accepted) or harmlessly overwritten next step
        (rejected) — callers guarantee every slot has k tokens of block
        budget left (the engine never speculates inside the tail).
        """
        cfg = self.config
        dt = _dtype(cfg)
        B, C = tokens.shape
        H, hd = cfg.n_head, cfg.d_head
        BS = cache["k"][0].shape[2]
        MB = block_tables.shape[1]
        S = MB * BS

        linpos = lengths[:, None] + jnp.arange(C)[None, :]       # (B, C)
        pos = jnp.minimum(linpos, cfg.max_seq_len - 1)
        x = (params["wte"][tokens] + params["wpe"][pos]).astype(dt)
        dst_block = jnp.take_along_axis(
            block_tables, jnp.minimum(linpos // BS, MB - 1), axis=1)
        dst_off = linpos % BS
        fb, fo = dst_block.reshape(-1), dst_off.reshape(-1)
        q_pos = linpos[:, :, None]                            # (B, C, 1)
        k_pos = jnp.arange(S)[None, None, :]                  # (1, 1, S)
        mask = (k_pos <= q_pos) \
            & (k_pos < (lengths + C)[:, None, None])
        from ..ops.pallas.paged_attention import (paged_chunk_attention,
                                                  resolve_paged_chunk)
        use_kernel, block_c = resolve_paged_chunk(
            getattr(self, "_paged_kernel", "auto"),
            getattr(self, "_paged_block_c", "auto"),
            C, MB, BS, H, 1, hd, dt)

        ks_out, vs_out = [], []
        for i in range(cfg.n_layer):
            layer = self._layer_slice(params, i)
            kc0, vc0 = cache["k"][i], cache["v"][i]
            w = cfg.attn_layer_windows[i] if cfg.attn_layer_windows else 0
            m = mask & (q_pos - k_pos < w) if w else mask

            def attn_fn(q, kk, v, kc0=kc0, vc0=vc0, m=m, w=w):
                kc = kc0.at[fb, :, fo].set(
                    kk.reshape(B * C, H, hd).astype(kc0.dtype))
                vc = vc0.at[fb, :, fo].set(
                    v.reshape(B * C, H, hd).astype(vc0.dtype))
                if use_kernel:
                    attn = jnp.stack([
                        paged_chunk_attention(
                            q[b], kc, vc, block_tables[b], lengths[b],
                            jnp.int32(C),
                            scale=None if cfg.scale_attn else 1.0,
                            window=w, block_c=block_c)
                        for b in range(B)])
                    return attn, (kc, vc)
                gk = kc[block_tables].transpose(0, 1, 3, 2, 4) \
                    .reshape(B, S, H, hd)
                gv = vc[block_tables].transpose(0, 1, 3, 2, 4) \
                    .reshape(B, S, H, hd)
                scores = jnp.einsum("bthd,bshd->bhts", q, gk,
                                    preferred_element_type=jnp.float32)
                if cfg.scale_attn:
                    scores = scores / math.sqrt(hd)
                scores = jnp.where(m[:, None], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1).astype(dt)
                return jnp.einsum("bhts,bshd->bthd", probs, gv), (kc, vc)

            x, (kc, vc) = self._block_core(x, layer, attn_fn)
            ks_out.append(kc)
            vs_out.append(vc)
        return self.head(params, x), {"k": ks_out, "v": vs_out}

    # --- loss ---
    def loss(self, params, batch, *, rng=None, train=True, seq_sharded=False,
             ltd_keep=None):
        """Next-token cross entropy. batch: {"input_ids": (B, T) int32}.
        ``ltd_keep``: random-LTD kept-token count for the middle layers
        (static; engine-scheduled — see runtime/engine.py)."""
        ids = batch["input_ids"]
        cfg = self.config
        T = ids.shape[1]
        chunk = cfg.loss_chunk
        if ltd_keep and train and not seq_sharded and ltd_keep < T:
            constrain = self._constrain_fn()
            act_spec = P(BATCH_AXES, None, None)
            x, aux = self._apply_ltd(params, ids, int(ltd_keep), rng=rng,
                                     train=train, constrain=constrain,
                                     act_spec=act_spec)
            if chunk and T - 1 > chunk:
                return self._chunked_head_loss(params, x[:, :-1],
                                               ids[:, 1:], chunk) \
                    + self.moe_loss_coeff * aux
            return next_token_xent(self.head(params, x), ids) \
                + self.moe_loss_coeff * aux
        if chunk and T - 1 > chunk and not seq_sharded:
            # chunked CE: never materialize the full (B, T, V) fp32 logits
            # (3.3 GB at B=16, T=1024, V=50k) — unembed + CE per sequence
            # chunk under remat, recomputed in backward
            x, aux = self.apply_with_aux(params, ids, rng=rng, train=train,
                                         seq_sharded=seq_sharded,
                                         return_hidden=True)
            return self._chunked_head_loss(params, x[:, :-1], ids[:, 1:],
                                           chunk) \
                + self.moe_loss_coeff * aux
        logits, aux = self.apply_with_aux(params, ids, rng=rng, train=train,
                                          seq_sharded=seq_sharded)
        return next_token_xent(logits, ids) + self.moe_loss_coeff * aux

    # head leaves the fused-CE d_params accumulator tracks (the subset
    # ``head`` reads; see common.fused_linear_xent)
    _head_keys = ("wte", "lnf_scale", "lnf_bias")

    def _chunked_head_loss(self, params, hidden, targets, chunk):
        """Dispatch the big-vocab head: fused grad-in-forward CE when
        cfg.fused_loss (optionally over the Pallas unembed/stats
        kernel), else the remat'd chunked path."""
        if self.config.fused_loss and self.config.fused_loss_kernel:
            from .common import fused_linear_xent_kernel

            def norm(np_, x):
                return self._ln(x, np_["lnf_scale"], np_["lnf_bias"])

            np_ = {k: params[k] for k in ("lnf_scale", "lnf_bias")}
            return fused_linear_xent_kernel(norm, chunk, np_,
                                            params["wte"], hidden,
                                            targets)
        if self.config.fused_loss:
            hp = {k: params[k] for k in self._head_keys}
            return fused_linear_xent(self.head, chunk, hp, hidden, targets)
        return chunked_softmax_xent(self.head, params, hidden, targets,
                                    chunk)



def _layernorm(x, scale, bias, eps=1e-5):
    from ..ops.pallas.layernorm import _ln_jnp
    return _ln_jnp(x, scale, bias, eps)


def _dropout(x, rate, rng):
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)
