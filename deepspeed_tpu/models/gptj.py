"""GPT-J family — parallel block, shared input LN, interleaved rotary.

Counterpart of the reference's GPT-J injection support
(module_inject/containers/gptj.py, replace_policy HFGPTJLayerPolicy).
Architecture on the shared Llama knob system: ONE LayerNorm feeds both
the attention and MLP branches of the parallel residual (tied at load,
like falcon-7b), partial rotary over ``rotary_dim`` lanes with the
rotate_every_two INTERLEAVED pairing (HF modeling_gptj.py — unlike the
llama/neox half-split), un-gated gelu_new MLP with biases, and a biased
untied lm_head. q/k/v/out projections carry no bias.
"""

from dataclasses import dataclass

from .llama import Llama, LlamaConfig


@dataclass(frozen=True)
class GPTJConfig(LlamaConfig):
    parallel_block: bool = True
    mlp_gated: bool = False              # fc_in/gelu/fc_out
    norm_type: str = "ln"
    mlp_bias: bool = True                # fc_in/fc_out biased
    head_bias: object = True             # lm_head.bias (o_proj stays plain)
    rotary_interleaved: bool = True      # rotate_every_two pairing
    rotary_pct: float = 0.25             # rotary_dim 64 of hd 256 (6B)
    vocab_size: int = 50400


GPTJ_TINY = GPTJConfig(n_layer=2, n_head=4, n_kv_heads=4, d_model=128,
                       max_seq_len=128, vocab_size=512, remat=False)
# gpt-j-6b point (config.json: 28 layers, 16 heads, hidden 4096,
# rotary_dim 64)
GPTJ_6B = GPTJConfig(n_layer=28, n_head=16, n_kv_heads=16, d_model=4096,
                     d_ff=16384, max_seq_len=2048, vocab_size=50400)

GPTJ_PRESETS = {"tiny": GPTJ_TINY, "gpt-j-6b": GPTJ_6B}


class GPTJ(Llama):
    """GPT-J on the shared Llama machinery (see module docstring)."""

    def __init__(self, config: GPTJConfig):
        super().__init__(config)
