"""GPT-NeoX / Pythia family — biased everything, two-LN parallel residual.

Counterpart of the reference's GPT-NeoX injection support
(module_inject/containers/gptneox.py, megatron-style fused qkv). On the
shared Llama knob system: LayerNorm with bias, partial rotary
(rotary_pct, llama/neox half-split pairing), un-gated EXACT-erf gelu
MLP, biases on qkv/dense/MLP but a plain (bias-free) untied embed_out,
and the use_parallel_residual block: x + attn(ln1 x) + mlp(ln2 x) with
TWO independent norms (unlike falcon-7b/gptj's shared one). Pythia
variants with use_parallel_residual=False load as sequential blocks.

The HF checkpoint's fused query_key_value is interleaved per head
((H, 3, hd) rows); the converter de-interleaves (checkpoint/hf.py).
"""

from dataclasses import dataclass

from .llama import Llama, LlamaConfig


@dataclass(frozen=True)
class GPTNeoXConfig(LlamaConfig):
    norm_type: str = "ln"
    mlp_gated: bool = False
    mlp_act: str = "gelu"                # nn.GELU default: exact erf
    qkv_bias: bool = True
    o_bias: bool = True
    mlp_bias: bool = True
    head_bias: object = False            # embed_out has no bias
    parallel_block: bool = True          # use_parallel_residual
    rotary_pct: float = 0.25
    vocab_size: int = 50432


GPTNEOX_TINY = GPTNeoXConfig(n_layer=2, n_head=4, n_kv_heads=4,
                             d_model=128, max_seq_len=128, vocab_size=512,
                             remat=False)
# gpt-neox-20b point (config.json: 44 layers, 64 heads, hidden 6144)
GPTNEOX_20B = GPTNeoXConfig(n_layer=44, n_head=64, n_kv_heads=64,
                            d_model=6144, d_ff=24576, max_seq_len=2048,
                            vocab_size=50432)

GPTNEOX_PRESETS = {"tiny": GPTNEOX_TINY, "gpt-neox-20b": GPTNEOX_20B}


class GPTNeoX(Llama):
    """GPT-NeoX on the shared Llama machinery (see module docstring)."""

    def __init__(self, config: GPTNeoXConfig):
        super().__init__(config)
