"""Bloom model family — ALiBi position bias, LN everywhere, tied head.

Counterpart of the reference's Bloom support
(module_inject/containers/bloom.py,
model_implementations/transformers/ds_bloom.py): decoder-only
transformer with NO positional embeddings — attention carries a per-head
linear bias on key positions (ALiBi) — LayerNorm (with bias) for every
norm including one on the embedding output, biases on every projection,
a plain-GELU MLP, and the lm head tied to the word embeddings.

Everything — training, v1 contiguous-cache decode, v2 paged serving —
inherits from :class:`~.llama.Llama` through its architecture knobs
(``alibi``/``embed_norm``/``norm_type``/``proj_bias``); the family is
the config point. The attention paths add ``slope_h * k_pos`` to the
scores (softmax-shift equivalent to the textbook
``slope_h * (k_pos - q_pos)``, matching HF bloom), and the v2 paged
decode kernel takes the slopes as a static argument
(ops/pallas/paged_attention.py), and training/prefill ride the flash
kernel's additive-bias input (ops/pallas/flash_attention.py ``alibi=``)
— no dense (B, H, T, T) score materialization on any path.
"""

from dataclasses import dataclass

from .llama import Llama, LlamaConfig


@dataclass(frozen=True)
class BloomConfig(LlamaConfig):
    alibi: bool = True                   # the family's defining knob
    embed_norm: bool = True              # word_embeddings_layernorm
    norm_type: str = "ln"
    mlp_gated: bool = False              # plain gelu MLP
    qkv_bias: bool = True
    proj_bias: bool = True
    tie_embeddings: bool = True
    vocab_size: int = 250880


BLOOM_TINY = BloomConfig(n_layer=2, n_head=4, n_kv_heads=4, d_model=128,
                         max_seq_len=128, vocab_size=512, remat=False)
# bloom-560m point (config.json: 24 layers, 16 heads, hidden 1024)
BLOOM_560M = BloomConfig(n_layer=24, n_head=16, n_kv_heads=16,
                         d_model=1024, d_ff=4096, max_seq_len=2048)
# bloom-7b1 point (30 layers, 32 heads, hidden 4096)
BLOOM_7B1 = BloomConfig(n_layer=30, n_head=32, n_kv_heads=32,
                        d_model=4096, d_ff=16384, max_seq_len=2048)

BLOOM_PRESETS = {"tiny": BLOOM_TINY, "bloom-560m": BLOOM_560M,
                 "bloom-7b1": BLOOM_7B1}


class Bloom(Llama):
    """Bloom: ALiBi LN model on the shared Llama machinery (see module
    docstring)."""

    def __init__(self, config: BloomConfig):
        if not config.alibi or not config.embed_norm:
            raise ValueError(
                "Bloom requires alibi=True and embed_norm=True")
        super().__init__(config)
