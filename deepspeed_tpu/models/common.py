"""Helpers shared by the model zoo (GPT2, Llama, ...)."""

import jax
import jax.numpy as jnp
from jax import lax


def resolve_flash(value):
    """Resolve a use_flash_attention config value: "auto" -> pallas flash
    on TPU, dense elsewhere; True/False force."""
    import jax
    if value == "auto":
        return jax.default_backend() == "tpu"
    return bool(value)


def constrain_fn():
    """Sharding constraints are advisory: no-ops without an active mesh
    (single-device tests / eager use) and under fully-manual meshes
    (inside shard_map, e.g. the 1-bit trainer), GSPMD directives
    otherwise."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh.empty:
        return lambda x, spec: x
    axis_types = getattr(mesh, "axis_types", None)
    if axis_types is None:        # older jax (compat shim): the ambient
        return lax.with_sharding_constraint   # mesh is always GSPMD-auto
    from jax.sharding import AxisType
    if not any(t == AxisType.Auto for t in axis_types):
        return lambda x, spec: x
    return lax.with_sharding_constraint


def resolve_remat_policy(name):
    """Model remat_policy name -> jax.checkpoint policy.

    Under ``jax.checkpoint`` inside ``lax.scan`` jax does NOT keep a
    custom_vjp's residuals — a whole-block remat re-runs the flash
    forward kernel in backward. The flash fwd rule therefore names its
    output/residual tensors ('flash_o'/'flash_lse'), and policies that
    save them let the backward reassemble the flash residuals from saved
    o/lse plus recomputed q/k/v (one cheap qkv matmul) with ZERO extra
    flash kernel runs:
      'save_attn'    keep checkpoint_name('attn_out') tensors
      'save_mid'     keep the post-attention residual stream ('attn_mid'):
                     backward recomputes only ln2+MLP, not the attention
                     half (+50 MB/layer at 350M bs=24)
      'save_mid_up'  also keep the MLP pre-activation ('mlp_up'): backward
                     recomputes only layernorms/gelu, no matmuls
                     (+250 MB/layer)
      'save_flash'   'save_mid' + the flash o/lse residuals: no flash
                     fwd re-run in backward (+50 MB/layer over save_mid)
      'save_carry_flash'  keep the block OUTPUT ('block_out') + flash
                     o/lse instead of attn_mid; 'save_both_flash' keeps
                     both. Measured at 350M bs=24: save_flash 751 ms,
                     save_both_flash 752 ms, save_carry_flash 777 ms —
                     'save_flash' is the bench default; the variants
                     stay for other model/batch points.
    """
    named = {
        "save_attn": ("attn_out",),
        "save_mid": ("attn_mid",),
        "save_mid_up": ("attn_mid", "mlp_up"),
        "save_flash": ("attn_mid", "flash_o", "flash_lse"),
        "save_carry_flash": ("block_out", "flash_o", "flash_lse"),
        "save_both_flash": ("block_out", "attn_mid", "flash_o", "flash_lse"),
        "save_flash_up": ("attn_mid", "flash_o", "flash_lse", "mlp_up"),
        # + saved q/k/v kernel operands: no ln1+qkv-projection recompute
        # in backward (+144 MB/layer at 350M bs=24)
        "save_flash_qkv": ("attn_mid", "flash_o", "flash_lse",
                           "flash_q", "flash_k", "flash_v"),
    }
    if name in named:
        return jax.checkpoint_policies.save_only_these_names(*named[name])
    return getattr(jax.checkpoint_policies, name, None)


def next_token_xent(logits, ids):
    """Mean next-token cross entropy from dense (B, T, V) fp32 logits."""
    targets = ids[:, 1:]
    logits = logits[:, :-1]
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def _xent_chunks(hidden, targets, chunk):
    """Pad + reshape (B, T, D)/(B, T) into per-chunk scan operands:
    xs (n, B, c, D), ts (n, B, c), valid (n, 1, c)."""
    B, T, D = hidden.shape
    n = -(-T // chunk)
    pad = n * chunk - T
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
    valid = (jnp.arange(n * chunk) < T).reshape(n, 1, chunk)
    xs = hidden.reshape(B, n, chunk, D).swapaxes(0, 1)
    ts = targets.reshape(B, n, chunk).swapaxes(0, 1)
    return xs, ts, valid, n


def fused_linear_xent(head_fn, chunk, head_params, hidden, targets):
    """Mean next-token CE over (B, T, D) hidden states with the head's
    gradients computed IN FORWARD (the reference's fused CE plays the
    same trick on GPU; see also Liger-style fused linear cross entropy).

    Because the op's output is a scalar, its backward receives a scalar
    cotangent g — so the forward can compute pre-scaled d_hidden and
    d_head_params via per-chunk ``jax.vjp`` and the backward is just a
    multiply by g. vs. the remat'd chunked path this removes one full
    unembed-matmul pass (the backward logits recompute) and one softmax
    pass; logits never materialize beyond one (B, chunk, V) block.

    Under plain evaluation (no AD) the primal path computes the loss
    only — no gradient work.

    head_fn(head_params, x_chunk) -> fp32 logits must read only the
    leaves present in ``head_params`` (the caller passes the subset of
    the model tree the head touches, so the d_params accumulator is
    head-sized, not model-sized).
    """
    return _fused_xent(head_fn, chunk, head_params, hidden, targets)


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _fused_xent(head_fn, chunk, head_params, hidden, targets):
    B, T, D = hidden.shape
    xs, ts, valid, _ = _xent_chunks(hidden, targets, chunk)

    def body(acc, xtm):
        x, t, m = xtm
        logits = head_fn(head_params, x)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, t[..., None], axis=-1)[..., 0]
        return acc + jnp.sum(jnp.where(m, logz - gold, 0.0)), None

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts, valid))
    return total / (B * T)


def _fused_xent_fwd(head_fn, chunk, head_params, hidden, targets):
    B, T, D = hidden.shape
    xs, ts, valid, n = _xent_chunks(hidden, targets, chunk)
    denom = B * T

    acc0 = (jnp.zeros((), jnp.float32),
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                         head_params))

    def body(carry, xtm):
        acc_loss, acc_hp = carry
        x, t, m = xtm
        logits, vjp = jax.vjp(head_fn, head_params, x)
        logz = jax.nn.logsumexp(logits, axis=-1)            # (B, c) f32
        gold = jnp.take_along_axis(logits, t[..., None], axis=-1)[..., 0]
        acc_loss = acc_loss + jnp.sum(jnp.where(m, logz - gold, 0.0))
        p = jnp.exp(logits - logz[..., None])
        onehot = t[..., None] == jnp.arange(logits.shape[-1])[None, None]
        d_logits = jnp.where(m[..., None], p - onehot, 0.0) / denom
        if hidden.dtype == jnp.bfloat16:
            # materialize d_logits in bf16: the consuming matmuls
            # truncate fp32 operands to bf16 on the MXU anyway (default
            # precision), so this halves its HBM traffic at zero
            # additional numeric cost. fp32 models keep fp32 exactness.
            d_logits = d_logits.astype(jnp.bfloat16).astype(logits.dtype)
        d_hp, d_x = vjp(d_logits)
        acc_hp = jax.tree.map(lambda a, d: a + d.astype(jnp.float32),
                              acc_hp, d_hp)
        return (acc_loss, acc_hp), d_x

    (total, d_hp), d_xs = lax.scan(body, acc0, (xs, ts, valid))
    d_hidden = d_xs.swapaxes(0, 1).reshape(B, n * chunk, D)[:, :T]
    d_hp = jax.tree.map(lambda d, p: d.astype(p.dtype), d_hp, head_params)
    res = (d_hp, d_hidden.astype(hidden.dtype), targets.shape)
    return total / denom, res


def _fused_xent_bwd(head_fn, chunk, res, g):
    import numpy as np
    d_hp, d_hidden, tshape = res
    scale = lambda t: (g * t.astype(jnp.float32)).astype(t.dtype)
    return (jax.tree.map(scale, d_hp), scale(d_hidden),
            np.zeros(tshape, jax.dtypes.float0))


_fused_xent.defvjp(_fused_xent_fwd, _fused_xent_bwd)


def fused_linear_xent_kernel(norm_fn, chunk, norm_params, w, hidden,
                             targets):
    """``fused_linear_xent`` with the unembed computed by the Pallas
    online-stats kernel (ops/pallas/fused_ce.py): fp32 logits never
    touch HBM — the kernel emits bf16 logits + exact fp32 logz/gold in
    one pass, and d_logits forms from the bf16 copy (identical numerics
    to the MXU's own bf16 operand truncation).

    norm_fn(norm_params, x) -> normed hidden (the pre-unembed final
    norm); w: the (V, D) unembed matrix (tied or not). Head bias is not
    supported here — callers fall back to the generic path."""
    return _fused_xent_k(norm_fn, chunk, norm_params, w, hidden, targets)


@_partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _fused_xent_k(norm_fn, chunk, norm_params, w, hidden, targets):
    # primal/eval path: loss only, no gradient work
    from ..ops.pallas.fused_ce import unembed_logits_stats
    B, T, D = hidden.shape
    xs, ts, valid, _ = _xent_chunks(hidden, targets, chunk)

    def body(acc, xtm):
        x, t, m = xtm
        h = norm_fn(norm_params, x)
        _, logz, gold = unembed_logits_stats(
            h.reshape(-1, D), w, t.reshape(-1))
        per = (logz - gold).reshape(x.shape[0], x.shape[1])
        return acc + jnp.sum(jnp.where(m, per, 0.0)), None

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32),
                        (xs, ts, valid))
    return total / (B * T)


def _fused_xent_k_fwd(norm_fn, chunk, norm_params, w, hidden, targets):
    from ..ops.pallas.fused_ce import unembed_logits_stats
    B, T, D = hidden.shape
    xs, ts, valid, n = _xent_chunks(hidden, targets, chunk)
    denom = B * T
    V = w.shape[0]

    acc0 = (jnp.zeros((), jnp.float32),
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                         norm_params),
            jnp.zeros(w.shape, jnp.float32))

    def body(carry, xtm):
        acc_loss, acc_np, acc_w = carry
        x, t, m = xtm
        c = x.shape[1]
        h, norm_vjp = jax.vjp(norm_fn, norm_params, x)
        hf = h.reshape(-1, D)
        tf = t.reshape(-1)
        logits, logz, gold = unembed_logits_stats(hf, w, tf)
        per = (logz - gold).reshape(x.shape[0], c)
        acc_loss = acc_loss + jnp.sum(jnp.where(m, per, 0.0))
        p = jnp.exp(logits.astype(jnp.float32) - logz[:, None])
        onehot = tf[:, None] == jnp.arange(V)[None]
        mflat = jnp.broadcast_to(m, (x.shape[0], c)).reshape(-1, 1)
        d_logits = (jnp.where(mflat, p - onehot, 0.0) / denom).astype(
            hidden.dtype)
        d_w = jnp.einsum("nv,nd->vd", d_logits, hf,
                         preferred_element_type=jnp.float32)
        d_h = jnp.einsum("nv,vd->nd", d_logits, w,
                         preferred_element_type=jnp.float32).astype(
            hidden.dtype).reshape(h.shape)
        d_np, d_x = norm_vjp(d_h)
        acc_np = jax.tree.map(lambda a, d: a + d.astype(jnp.float32),
                              acc_np, d_np)
        return (acc_loss, acc_np, acc_w + d_w), d_x

    (total, d_np, d_w), d_xs = lax.scan(body, acc0, (xs, ts, valid))
    d_hidden = d_xs.swapaxes(0, 1).reshape(B, n * chunk, D)[:, :T]
    d_np = jax.tree.map(lambda d, p: d.astype(p.dtype), d_np, norm_params)
    res = (d_np, d_w.astype(w.dtype), d_hidden.astype(hidden.dtype),
           targets.shape)
    return total / denom, res


def _fused_xent_k_bwd(norm_fn, chunk, res, g):
    import numpy as np
    d_np, d_w, d_hidden, tshape = res
    scale = lambda t: (g * t.astype(jnp.float32)).astype(t.dtype)
    return (jax.tree.map(scale, d_np), scale(d_w), scale(d_hidden),
            np.zeros(tshape, jax.dtypes.float0))


_fused_xent_k.defvjp(_fused_xent_k_fwd, _fused_xent_k_bwd)


def chunked_softmax_xent(head_fn, params, hidden, targets, chunk):
    """Mean next-token CE over (B, T, D) hidden states computed ``chunk``
    tokens at a time: ``head_fn(params, x_chunk)`` produces fp32 logits
    for just that chunk and remat recomputes them in backward, so peak
    logits memory is (B, chunk, V) instead of (B, T, V). Any T: the
    sequence is zero-padded to a chunk multiple and padded positions are
    masked out of the sum. Exact same value as the dense computation."""
    B, T, D = hidden.shape
    n = -(-T // chunk)
    pad = n * chunk - T
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
    valid = (jnp.arange(n * chunk) < T).reshape(n, 1, chunk)  # (n, 1, c)
    xs = hidden.reshape(B, n, chunk, D).swapaxes(0, 1)      # (n, B, c, D)
    ts = targets.reshape(B, n, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_loss(x, t, m):
        logits = head_fn(params, x)                         # (B, c, V) f32
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, t[..., None], axis=-1)[..., 0]
        return jnp.sum(jnp.where(m, logz - gold, 0.0))

    def body(acc, xtm):
        x, t, m = xtm
        return acc + chunk_loss(x, t, m), None

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32),
                        (xs, ts, valid))
    return total / (B * T)
