"""GPT2Pipe — GPT-2 with pipeline parallelism over the 'pipe' mesh axis.

The reference expresses pipelined GPT-style models as a PipelineModule of
LayerSpecs interpreted by PipelineEngine (runtime/pipe/module.py:87,
engine.py:56). Here the pipeline is *inside* the model's forward: the
stacked block params shard over 'pipe' (each stage owns n_layer/S layers)
and spmd_pipeline (runtime/pipe/spmd.py) rotates microbatch activations
through the stages with ppermute. Embedding and the LM head run outside the
pipelined region, replicated over 'pipe' — their grads psum across stages
automatically, which is exactly the reference's tied-weight allreduce
(pipe/engine.py:260 _exec_reduce_tied_grads) in declarative form.

Composes with the rest of the mesh: batch stays sharded over data/expert,
Megatron TP over 'tensor', and ZeRO partitioning applies on top of the
'pipe'-sharded layer dim (the reference needs a dedicated PipelineEngine +
grid for this; here it is the same DeepSpeedEngine).
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..runtime.pipe.spmd import (spmd_pipeline, split_microbatches,
                                 merge_microbatches)
from ..utils.groups import BATCH_AXES
from .gpt2 import GPT2


class GPT2Pipe(GPT2):
    """Same params / math / init as GPT2; pipelined forward when the active
    mesh has pipe > 1 (falls back to the dense scan otherwise, so one model
    object serves any topology)."""

    def __init__(self, config):
        if config.attn_layer_windows:
            # the pipelined executors do not thread the per-layer window
            # operand; refuse loudly rather than silently attend globally
            raise ValueError(
                "attn_layer_windows (gpt-neo local attention) is not "
                "supported by the pipelined executor")
        super().__init__(config)

    def partition_specs(self, topology=None):
        specs = super().partition_specs(topology)
        pipe = 1
        if topology is not None:
            pipe = topology.get_pipe_parallel_world_size()
        if pipe <= 1:
            return specs
        if self.config.n_layer % pipe:
            raise ValueError(
                f"n_layer {self.config.n_layer} not divisible by pipeline "
                f"stages {pipe}")
        blocks = {k: P(*(("pipe",) + tuple(s)[1:]))
                  for k, s in specs["blocks"].items()}
        specs = dict(specs)
        specs["blocks"] = blocks
        return specs

    def _pipe_size(self):
        mesh = jax.sharding.get_abstract_mesh()
        if mesh.empty or "pipe" not in mesh.shape:
            return 1
        return mesh.shape["pipe"]

    def _block_constrain(self):
        """Sharding constraints for the code INSIDE the pipelined
        region. On a pipe-only mesh (every non-pipe axis size 1) the
        constraints are semantic no-ops — and skipping them keeps the
        partial-manual shard_map program legal on legacy jaxlib, which
        has no shard_map replication rule for sharding_constraint (the
        reason the data>1 pipeline tests carry
        ``legacy_jax_pipeline_xfail``)."""
        mesh = jax.sharding.get_abstract_mesh()
        if not mesh.empty and all(
                n == 1 for a, n in mesh.shape.items() if a != "pipe"):
            return lambda x, spec: x
        return lax.with_sharding_constraint

    def _resolved_pipe(self, S):
        """(schedule, microbatches, offload) for this trace: the
        engine-installed ``_pipe_cfg`` (runtime/config.py
        PipelineConfig, resolved) wins where set; the model-config
        knobs are the no-engine fallback."""
        from ..runtime.pipe.spmd import PipeOffload
        cfg = self.config
        pc = getattr(self, "_pipe_cfg", None)
        schedule = (getattr(pc, "schedule", None)
                    or cfg.pipe_schedule)
        M = (getattr(pc, "micro_batches", 0)
             or cfg.pipe_microbatches or 2 * S)
        offload = PipeOffload(
            activations=bool(getattr(pc, "offload_activations", False)),
            double_buffer=bool(getattr(pc, "offload_double_buffer",
                                       True)))
        return schedule, M, offload

    def apply_with_aux(self, params, input_ids, *, rng=None, train=False,
                       seq_sharded=False, return_hidden=False):
        S = self._pipe_size()
        if S == 1:
            return super().apply_with_aux(params, input_ids, rng=rng,
                                          train=train,
                                          seq_sharded=seq_sharded,
                                          return_hidden=return_hidden)
        cfg = self.config
        if cfg.attention_backend == "ring":
            raise NotImplementedError(
                "ring attention inside the pipelined region (nested "
                "shard_map) is not supported; use Ulysses (dense) with pipe")
        if cfg.use_flash_attention is True:
            # explicit force only: "auto" resolves to the dense path
            # inside the pipelined region (pallas_call under a
            # partial-manual shard_map is not supported)
            raise NotImplementedError(
                "flash attention inside the pipelined region is not "
                "supported yet (pallas_call under a partial-manual "
                "shard_map); use the dense backend with pipe")
        B, T = input_ids.shape
        _, M, offload = self._resolved_pipe(S)
        if B % M:
            raise ValueError(f"batch {B} not divisible by "
                             f"pipe_microbatches {M}")

        act_spec = P(BATCH_AXES, "seq" if seq_sharded else None, None)
        mb_act_spec = P(None, BATCH_AXES, "seq" if seq_sharded else None,
                        None)
        constrain = self._block_constrain()

        # --- embedding (outside the pipe; replicated over 'pipe') ---
        x = self.embed(params, input_ids, rng=rng, train=train,
                       constrain=constrain, act_spec=act_spec)

        # --- pipelined blocks ---
        causal = jnp.tril(jnp.ones((T, T), jnp.bool_))

        if cfg.remat and cfg.remat_policy == "split_attn":
            # same split-boundary structure as GPT2.apply_with_aux: the
            # pre (ln1+qkv) and post (wo/ln2/MLP) segments remat, the
            # attention custom_vjp sits OUTSIDE any checkpoint so its
            # forward kernel is never re-run in backward
            from functools import partial

            def block_fn(x, layer_and_rng):
                layer, lrng = layer_and_rng
                pre = jax.checkpoint(partial(
                    self.block_qkv, constrain=constrain, act_spec=act_spec))
                q, kk, v = pre(x, layer)
                attn = self.block_attn(q, kk, v, causal=causal,
                                       constrain=constrain,
                                       seq_sharded=seq_sharded)
                post = jax.checkpoint(partial(
                    self.block_post, constrain=constrain,
                    act_spec=act_spec, seq_sharded=seq_sharded,
                    train=train))
                y, _aux = post(x, attn, layer, lrng)
                return y
        else:
            def block_fn(x, layer_and_rng):
                layer, lrng = layer_and_rng
                y, _aux = self.block_forward(
                    x, layer, lrng, causal=causal, constrain=constrain,
                    act_spec=act_spec, seq_sharded=seq_sharded, train=train)
                return y

            if cfg.remat:
                from .common import resolve_remat_policy
                policy = resolve_remat_policy(cfg.remat_policy)
                if offload.activations:
                    # GPipe keeps every in-flight microbatch's residuals
                    # live for autodiff — with offload on, save them
                    # into host memory instead of recomputing (the
                    # reference's cpu_checkpointing; swap_tensor tier)
                    from ..runtime.activation_checkpointing import (
                        checkpointing as ckpt)
                    policy = ckpt.offload_policy() or policy
                block_fn = jax.checkpoint(block_fn, policy=policy)

        layer_rngs = jax.random.split(
            rng if rng is not None else jax.random.key(0), cfg.n_layer)

        x_mb = split_microbatches(x, M)
        x_mb = constrain(x_mb, mb_act_spec)
        out_mb = spmd_pipeline(block_fn, (params["blocks"], layer_rngs),
                               x_mb)
        x = merge_microbatches(out_mb)
        x = constrain(x, act_spec)

        # --- head (outside the pipe) ---
        if return_hidden:
            return x, jnp.zeros((), jnp.float32)
        return self.head(params, x), jnp.zeros((), jnp.float32)

    def loss(self, params, batch, *, rng=None, train=True,
             seq_sharded=False):
        """Steady-state pipelined training loss when the resolved
        schedule is '1f1b' or 'zb' and the mesh pipelines: the
        interleaved executor computes loss AND grads in one pass with
        O(stages) live activations (pipeline_1f1b_grads /
        pipeline_zb_grads — the latter splits each backward into B/W
        passes so weight-grad work fills the drain ticks, optionally
        with the activation rings host-offloaded). Identical loss value
        to the GPipe path — parity-tested."""
        cfg = self.config
        S = self._pipe_size()
        schedule, M, offload = self._resolved_pipe(S)
        if S == 1 or schedule not in ("1f1b", "zb"):
            return super().loss(params, batch, rng=rng, train=train,
                                seq_sharded=seq_sharded)
        if cfg.use_flash_attention is True \
                or cfg.attention_backend == "ring":
            raise NotImplementedError(
                "flash/ring attention inside the pipelined region is not "
                "supported; use the dense backend with pipe")
        if getattr(self, "moe_loss_coeff", 0.0):
            # the 1F1B executor's block_fn drops per-block aux outputs —
            # silently losing the MoE load-balance loss; mirror the
            # explicit flash/ring errors rather than training wrong
            raise NotImplementedError(
                "MoE aux (load-balance) losses are not threaded through "
                "the 1f1b/zb schedules; use the GPipe schedule for MoE "
                "pipeline models")
        from ..runtime.pipe.spmd import pipeline_loss
        from .common import (chunked_softmax_xent, next_token_xent,
                             resolve_remat_policy)

        ids = batch["input_ids"]
        B, T = ids.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by "
                             f"pipe_microbatches {M}")
        act_spec = P(BATCH_AXES, "seq" if seq_sharded else None, None)
        constrain = self._block_constrain()
        x = self.embed(params, ids, rng=rng, train=train,
                       constrain=constrain, act_spec=act_spec)
        causal = jnp.tril(jnp.ones((T, T), jnp.bool_))

        if cfg.remat and cfg.remat_policy == "split_attn":
            # same split-boundary structure as apply_with_aux: pre/post
            # segments remat, attention sits outside any checkpoint
            from functools import partial

            def block_fn(x, layer, key_data):
                lrng = jax.random.wrap_key_data(key_data)
                pre = jax.checkpoint(partial(
                    self.block_qkv, constrain=constrain,
                    act_spec=act_spec))
                q, kk, v = pre(x, layer)
                attn = self.block_attn(q, kk, v, causal=causal,
                                       constrain=constrain,
                                       seq_sharded=seq_sharded)
                post = jax.checkpoint(partial(
                    self.block_post, constrain=constrain,
                    act_spec=act_spec, seq_sharded=seq_sharded,
                    train=train))
                y, _aux = post(x, attn, layer, lrng)
                return y
        else:
            def block_fn(x, layer, key_data):
                lrng = jax.random.wrap_key_data(key_data)
                y, _aux = self.block_forward(
                    x, layer, lrng, causal=causal, constrain=constrain,
                    act_spec=act_spec, seq_sharded=seq_sharded,
                    train=train)
                return y

            if cfg.remat:
                block_fn = jax.checkpoint(
                    block_fn,
                    policy=resolve_remat_policy(cfg.remat_policy))

        layer_rngs = jax.random.key_data(jax.random.split(
            rng if rng is not None else jax.random.key(0), cfg.n_layer))

        def head_loss(hp, y, tgt):
            # honors loss_chunk like the dense/GPipe path: never
            # materialize the full per-microbatch (b, T, V) fp32 logits
            if cfg.loss_chunk and y.shape[1] - 1 > cfg.loss_chunk:
                return chunked_softmax_xent(
                    self.head, hp, y[:, :-1], tgt[:, 1:], cfg.loss_chunk)
            return next_token_xent(self.head(hp, y), tgt)

        head_params = {"wte": params["wte"],
                       "lnf_scale": params["lnf_scale"],
                       "lnf_bias": params["lnf_bias"]}
        x_mb = split_microbatches(x, M)
        ids_mb = split_microbatches(ids, M)
        return pipeline_loss(
            block_fn, head_loss, "pipe", schedule,
            offload if schedule == "zb" else None,
            params["blocks"], layer_rngs, head_params, x_mb, ids_mb)
