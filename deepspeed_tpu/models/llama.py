"""Llama model family — RoPE + RMSNorm + SwiGLU + grouped-query attention.

Counterpart of the reference's llama support (inference
model_implementations/llama2, module_inject/containers/llama*.py,
csrc rms_norm/apply_rotary_pos_emb kernels) — here a first-class
trainable+servable model with the same functional surface as GPT2
(models/gpt2.py): ``init/loss/apply/partition_specs`` for the training
engine, ``init_cache/cache_specs/apply_cached`` for the v1 inference
engine, ``init_paged_cache/paged_cache_specs/apply_paged_*`` for the v2
serving engine. Same TPU-first choices: stacked layers under ``lax.scan``,
declarative Megatron TP on the 'tensor' axis, fp32 norms/logits.

GQA: ``n_kv_heads <= n_head`` — KV caches store only KV heads (the
serving memory win), queries repeat KV groups at attention time.
"""

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..utils.groups import BATCH_AXES
from .common import (chunked_softmax_xent, constrain_fn, fused_linear_xent,
                     next_token_xent)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 2048
    n_layer: int = 16
    n_head: int = 16
    n_kv_heads: int = 16
    d_model: int = 1024
    d_ff: int = 0               # 0 = round(8/3 * d_model) to multiple of 128
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = True
    remat_policy: str = "nothing_saveable"
    tie_embeddings: bool = False
    # chunked cross entropy (see gpt2.GPT2Config.loss_chunk); 0 = off
    loss_chunk: int = 0
    # grad-in-forward fused CE (common.fused_linear_xent); needs loss_chunk
    fused_loss: bool = False
    # "auto" (default) = pallas flash kernel on TPU, dense elsewhere
    use_flash_attention: object = "auto"
    flash_block_q: int = 512
    flash_block_k: int = 1024
    # architecture knobs covering the reference v2 model families
    # (model_implementations/{falcon,phi,qwen}): qkv projection bias
    # (qwen), rotary applied to only a fraction of each head (phi/neox
    # partial rotary), SwiGLU vs plain-gelu FFN (falcon/phi use gelu-MLP)
    qkv_bias: bool = False
    rotary_pct: float = 1.0
    mlp_gated: bool = True             # False: wup+gelu+wdown only
    # falcon/phi parallel residual: x + attn(ln1 x) + mlp(ln2 x) instead
    # of the sequential two-residual block
    parallel_block: bool = False
    # 'rms' (llama/qwen/mixtral) or 'ln' (falcon/phi LayerNorm with
    # learned bias; adds b1/b2/norm_f_b params)
    norm_type: str = "rms"
    # phi-style learned biases on the output projection, MLP and lm head
    # (adds bo/bup/bdown (+bgate) and lm_head_b params)
    proj_bias: bool = False
    # granular bias knobs for families where proj_bias is too broad
    # (reference module_inject/containers/{gptj,gptneox,internlm}.py):
    #   o_bias    — bo only (internlm: qkv+o biased, MLP not)
    #   mlp_bias  — bup/bdown (+bgate) only (gptj: fc biased, o not)
    #   head_bias — lm_head bias; "auto" follows proj_bias (gptj: biased
    #               head without o bias; gpt-neox: biased blocks, plain head)
    o_bias: bool = False
    mlp_bias: bool = False
    head_bias: object = "auto"
    # gptj rotate_every_two pairing: rotary pairs are (x0,x1),(x2,x3),...
    # instead of the llama/neox half-split (x_i, x_{i+rot/2})
    rotary_interleaved: bool = False
    # non-gated MLP activation: 'gelu_tanh' (HF gelu_new — gptj/phi) or
    # 'gelu' (exact erf gelu — gpt-neox/falcon nn.GELU default)
    mlp_act: str = "gelu_tanh"
    # mistral sliding-window attention: queries attend only the last
    # ``sliding_window`` positions (0 = full causal). Honored by every
    # path: dense training, flash kernel, v1 cached decode, v2 paged
    # prefill/decode.
    sliding_window: int = 0
    # bloom ALiBi: additive per-head linear position bias INSTEAD of
    # rotary embeddings (rope is skipped). Attention runs the dense path
    # (the flash kernel has no bias input).
    alibi: bool = False
    # falcon-rw quirk: HF falcon adds alibi BEFORE the 1/sqrt(hd) score
    # scaling (modeling_falcon.py:398/912) and quantizes the bias
    # through bf16 (:162), unlike bloom which adds it unscaled; models
    # trained that way need the same numerics
    alibi_inv_norm: bool = False
    # bloom word_embeddings_layernorm: LN applied to the embedding output
    # (adds embed_ln_s/embed_ln_b params)
    embed_norm: bool = False

    @property
    def flash_on(self):
        """Resolved use_flash_attention (see common.resolve_flash)."""
        from .common import resolve_flash
        return resolve_flash(self.use_flash_attention)

    @property
    def o_bias_on(self):
        return self.proj_bias or self.o_bias

    @property
    def mlp_bias_on(self):
        return self.proj_bias or self.mlp_bias

    @property
    def head_bias_on(self):
        return self.proj_bias if self.head_bias == "auto" \
            else bool(self.head_bias)

    @property
    def d_head(self):
        return self.d_model // self.n_head

    @property
    def ffn_dim(self):
        if self.d_ff:
            return self.d_ff
        return ((int(8 * self.d_model / 3) + 127) // 128) * 128

    def num_params(self):
        D, F, V = self.d_model, self.ffn_dim, self.vocab_size
        kvd = self.n_kv_heads * self.d_head
        block = (2 * D                      # rms scales
                 + D * D + 2 * D * kvd + D * D   # q, k, v, o
                 + (3 if self.mlp_gated else 2) * D * F)
        if self.qkv_bias:
            block += D + 2 * kvd
        if self.o_bias_on:
            block += D
        if self.mlp_bias_on:
            block += D + F * (2 if self.mlp_gated else 1)
        if self.norm_type == "ln":
            block += 2 * D                   # norm biases
        head = 0 if self.tie_embeddings else V * D
        if self.head_bias_on:
            head += V
        extra_f = D if self.norm_type == "ln" else 0
        if self.embed_norm:
            extra_f += 2 * D
        return V * D + self.n_layer * block + D + extra_f + head

    def flops_per_token(self):
        n = self.num_params() - self.vocab_size * self.d_model
        return 6 * n + 12 * self.n_layer * self.d_model * self.max_seq_len


LLAMA_TINY = LlamaConfig(n_layer=2, n_head=4, n_kv_heads=2, d_model=128,
                         max_seq_len=128, vocab_size=512, remat=False)
LLAMA2_7B = LlamaConfig(n_layer=32, n_head=32, n_kv_heads=32, d_model=4096,
                        max_seq_len=4096, vocab_size=32000)
MISTRAL_7B = LlamaConfig(n_layer=32, n_head=32, n_kv_heads=8, d_model=4096,
                         d_ff=14336, max_seq_len=8192, vocab_size=32000,
                         sliding_window=4096)

LLAMA_PRESETS = {"tiny": LLAMA_TINY, "llama2-7b": LLAMA2_7B,
                 "mistral-7b": MISTRAL_7B}


def _rms_norm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps)
            * scale.astype(jnp.float32)).astype(x.dtype)


def _layer_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _rope(x, pos, theta, interleaved=False):
    """x: (..., T, H, hd) with positions pos (..., T) -> rotated.

    ``interleaved`` (gptj rotate_every_two, HF modeling_gptj.py): pairs
    are adjacent lanes (x0,x1),(x2,x3),... instead of the llama/neox
    half-split (x_i, x_{i+hd/2}). Frequencies are identical."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = (pos.astype(jnp.float32)[..., None, None]
              * freqs[None, None, :])                  # (..., T, 1, half)
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    if interleaved:
        x1, x2 = x[..., 0::2], x[..., 1::2]
        out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                        axis=-1).reshape(x.shape)
    else:
        x1, x2 = x[..., :half], x[..., half:]
        out = jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _repeat_kv(k, n_rep):
    """(B, T, KVH, hd) -> (B, T, KVH*n_rep, hd)."""
    return k if n_rep == 1 else jnp.repeat(k, n_rep, axis=2)




class Llama:
    """Params layout (block tensors stacked on n_layer):
      wte (V,D) | norm_f (D,) | lm_head (V,D) unless tied
      blocks: rms1 (L,D), wq (L,D,D), wk (L,D,KVD), wv (L,D,KVD),
              wo (L,D,D), rms2 (L,D), wgate (L,D,F), wup (L,D,F),
              wdown (L,F,D)
    """

    moe_loss_coeff = 0.0

    def __init__(self, config: LlamaConfig):
        self.config = config

    # ------------------------------------------------------------------ init
    def init(self, rng):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        L, D, F, V = cfg.n_layer, cfg.d_model, cfg.ffn_dim, cfg.vocab_size
        kvd = cfg.n_kv_heads * cfg.d_head
        k = iter(jax.random.split(rng, 12))
        std = 0.02
        res_std = std / math.sqrt(2 * L)

        def nrm(key, shape, s=std):
            return (jax.random.normal(key, shape, jnp.float32) * s).astype(dt)

        params = {
            "wte": nrm(next(k), (V, D)),
            "norm_f": jnp.ones((D,), dt),
            "blocks": {
                "rms1": jnp.ones((L, D), dt),
                "wq": nrm(next(k), (L, D, D)),
                "wk": nrm(next(k), (L, D, kvd)),
                "wv": nrm(next(k), (L, D, kvd)),
                "wo": nrm(next(k), (L, D, D), res_std),
                "rms2": jnp.ones((L, D), dt),
                "wup": nrm(next(k), (L, D, F)),
                "wdown": nrm(next(k), (L, F, D), res_std),
            },
        }
        if cfg.mlp_gated:
            params["blocks"]["wgate"] = nrm(next(k), (L, D, F))
        if cfg.qkv_bias:
            params["blocks"]["bq"] = jnp.zeros((L, D), dt)
            params["blocks"]["bk"] = jnp.zeros((L, kvd), dt)
            params["blocks"]["bv"] = jnp.zeros((L, kvd), dt)
        if cfg.o_bias_on:
            params["blocks"]["bo"] = jnp.zeros((L, D), dt)
        if cfg.mlp_bias_on:
            params["blocks"]["bup"] = jnp.zeros((L, F), dt)
            params["blocks"]["bdown"] = jnp.zeros((L, D), dt)
            if cfg.mlp_gated:
                params["blocks"]["bgate"] = jnp.zeros((L, F), dt)
        if cfg.head_bias_on:
            params["lm_head_b"] = jnp.zeros((V,), dt)
        if cfg.norm_type == "ln":
            params["blocks"]["b1"] = jnp.zeros((L, D), dt)
            params["blocks"]["b2"] = jnp.zeros((L, D), dt)
            params["norm_f_b"] = jnp.zeros((D,), dt)
        if cfg.embed_norm:
            params["embed_ln_s"] = jnp.ones((D,), dt)
            params["embed_ln_b"] = jnp.zeros((D,), dt)
        if not cfg.tie_embeddings:
            params["lm_head"] = nrm(next(k), (V, D))
        return params

    # -------------------------------------------------------------- sharding
    def partition_specs(self, topology=None):
        """Column-parallel: wq/wk/wv/wgate/wup (out dim on 'tensor');
        row-parallel: wo/wdown (in dim). Embeddings/norms replicated."""
        specs = {
            "wte": P(),
            "norm_f": P(),
            "blocks": {
                "rms1": P(None, None),
                "wq": P(None, None, "tensor"),
                "wk": P(None, None, "tensor"),
                "wv": P(None, None, "tensor"),
                "wo": P(None, "tensor", None),
                "rms2": P(None, None),
                "wup": P(None, None, "tensor"),
                "wdown": P(None, "tensor", None),
            },
        }
        if self.config.mlp_gated:
            specs["blocks"]["wgate"] = P(None, None, "tensor")
        if self.config.qkv_bias:
            specs["blocks"]["bq"] = P(None, "tensor")
            specs["blocks"]["bk"] = P(None, "tensor")
            specs["blocks"]["bv"] = P(None, "tensor")
        if self.config.o_bias_on:
            specs["blocks"]["bo"] = P(None, None)
        if self.config.mlp_bias_on:
            specs["blocks"]["bup"] = P(None, "tensor")
            specs["blocks"]["bdown"] = P(None, None)
            if self.config.mlp_gated:
                specs["blocks"]["bgate"] = P(None, "tensor")
        if self.config.head_bias_on:
            specs["lm_head_b"] = P()
        if self.config.norm_type == "ln":
            specs["blocks"]["b1"] = P(None, None)
            specs["blocks"]["b2"] = P(None, None)
            specs["norm_f_b"] = P()
        if self.config.embed_norm:
            specs["embed_ln_s"] = P()
            specs["embed_ln_b"] = P()
        if not self.config.tie_embeddings:
            specs["lm_head"] = P()
        return specs

    # --------------------------------------------------------------- forward
    def _constrain_fn(self):
        return constrain_fn()

    def _norm(self, x, layer, which):
        """Block norm dispatch: 'rms' (llama) or 'ln' (falcon/phi)."""
        cfg = self.config
        if cfg.norm_type == "ln":
            return _layer_norm(x, layer[f"rms{which}"], layer[f"b{which}"],
                               cfg.rms_eps)
        return _rms_norm(x, layer[f"rms{which}"], cfg.rms_eps)

    def head(self, params, x):
        if self.config.norm_type == "ln":
            x = _layer_norm(x, params["norm_f"], params["norm_f_b"],
                            self.config.rms_eps)
        else:
            x = _rms_norm(x, params["norm_f"], self.config.rms_eps)
        w = params["wte"] if self.config.tie_embeddings else \
            params["lm_head"]
        logits = jnp.einsum("btd,vd->btv", x, w,
                            preferred_element_type=jnp.float32)
        if self.config.head_bias_on:
            logits = logits + params["lm_head_b"].astype(jnp.float32)
        return logits

    def _attn_proj(self, x, layer):
        cfg = self.config
        B, T = x.shape[0], x.shape[1]
        H, KVH, hd = cfg.n_head, cfg.n_kv_heads, cfg.d_head
        h = self._norm(x, layer, 1)
        q = h @ layer["wq"]
        kk = h @ layer["wk"]
        v = h @ layer["wv"]
        if cfg.qkv_bias:                      # qwen-style attention bias
            q = q + layer["bq"]
            kk = kk + layer["bk"]
            v = v + layer["bv"]
        return (q.reshape(B, T, H, hd), kk.reshape(B, T, KVH, hd),
                v.reshape(B, T, KVH, hd))

    def _rope(self, x, pos):
        """Rotary with optional partial application (phi/neox
        rotary_pct < 1: only the leading fraction of each head
        rotates). ALiBi models carry no rotary at all."""
        cfg = self.config
        if cfg.alibi:
            return x
        pct = cfg.rotary_pct
        il = cfg.rotary_interleaved
        if pct >= 1.0:
            return _rope(x, pos, cfg.rope_theta, interleaved=il)
        hd = x.shape[-1]
        rot = max(2, int(hd * pct)) // 2 * 2
        return jnp.concatenate(
            [_rope(x[..., :rot], pos, cfg.rope_theta, interleaved=il),
             x[..., rot:]],
            axis=-1)

    def _alibi_bias(self, k_pos):
        """(H, ...) additive score bias: slope_h * k_pos (softmax-shift
        equivalent to slope_h * (k_pos - q_pos); matches HF bloom).
        ``alibi_inv_norm`` (falcon-rw): bf16-quantized and divided by
        sqrt(hd), matching HF falcon's pre-scaling addition."""
        from ..ops.pallas.paged_attention import alibi_slopes
        cfg = self.config
        slopes = jnp.asarray(alibi_slopes(cfg.n_head), jnp.float32)
        bias = slopes.reshape(-1, *([1] * k_pos.ndim)) \
            * k_pos.astype(jnp.float32)[None]
        if cfg.alibi_inv_norm:
            bias = bias.astype(jnp.bfloat16).astype(jnp.float32) \
                / math.sqrt(cfg.d_head)
        return bias

    def _window_mask(self, mask, q_pos, k_pos):
        """AND a sliding-window constraint into a boolean mask
        (broadcastable q_pos/k_pos position index arrays)."""
        w = self.config.sliding_window
        if not w:
            return mask
        return mask & (q_pos - k_pos < w)

    def _wo(self, attn, layer):
        """Output projection (+ bias when proj_bias/o_bias)."""
        out = attn @ layer["wo"]
        if self.config.o_bias_on:
            out = out + layer["bo"]
        return out

    def _mlp(self, x, layer):
        cfg = self.config
        h = self._norm(x, layer, 2)
        pb = cfg.mlp_bias_on
        from ..ops.int8_weights import _is_q
        if _is_q(layer["wup"]):
            # weight-only quantized serving FFN (engine weight_quant):
            # int8/int4 weight tiles stream HBM->VMEM with dequant fused
            # into the projection kernel's flush epilogue — no
            # dequantized weight tensor materializes
            from ..ops.pallas.mlp_matmul import wq_matmul
            if not cfg.mlp_gated:
                u = wq_matmul(h, layer["wup"])
                if pb:
                    u = u + layer["bup"]
                act = jax.nn.gelu(u, approximate=cfg.mlp_act == "gelu_tanh")
                out = wq_matmul(act, layer["wdown"])
                return out + layer["bdown"] if pb else out
            g = wq_matmul(h, layer["wgate"])
            u = wq_matmul(h, layer["wup"])
            if pb:
                g = g + layer["bgate"]
                u = u + layer["bup"]
            out = wq_matmul(jax.nn.silu(g) * u, layer["wdown"])
            return out + layer["bdown"] if pb else out
        if not cfg.mlp_gated:                 # falcon/phi plain-gelu MLP
            u = h @ layer["wup"]
            if pb:
                u = u + layer["bup"]
            act = jax.nn.gelu(u, approximate=cfg.mlp_act == "gelu_tanh")
            out = act @ layer["wdown"]
            return out + layer["bdown"] if pb else out
        g = h @ layer["wgate"]
        u = h @ layer["wup"]
        if pb:
            g = g + layer["bgate"]
            u = u + layer["bup"]
        out = (jax.nn.silu(g) * u) @ layer["wdown"]
        return out + layer["bdown"] if pb else out

    def block_forward(self, x, layer, pos, *, causal, constrain, act_spec):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        from ..ops.int8_weights import dequant_tree
        layer = dequant_tree(layer, dt)
        B, T = x.shape[0], x.shape[1]
        H, KVH, hd = cfg.n_head, cfg.n_kv_heads, cfg.d_head
        q, kk, v = self._attn_proj(x, layer)
        q = self._rope(q, pos)
        kk = self._rope(kk, pos)
        head_spec = P(BATCH_AXES, None, "tensor", None)
        q = constrain(q, head_spec)
        kk = constrain(kk, head_spec)
        v = constrain(v, head_spec)
        kk = _repeat_kv(kk, H // KVH)
        v = _repeat_kv(v, H // KVH)
        if cfg.flash_on:
            from ..ops.pallas.flash_attention import flash_attention
            alibi_arg = None
            if cfg.alibi:
                # ALiBi is computed in-kernel from the slopes (slope_h *
                # k_pos, softmax-shift equivalent to the relative form);
                # alibi_inv_norm reproduces HF falcon's pre-scaled
                # bf16-quantized variant (see _alibi_bias)
                from ..ops.pallas.paged_attention import alibi_slopes
                alibi_arg = alibi_slopes(H)
            attn = flash_attention(
                q, kk, v, causal=True,
                block_q=cfg.flash_block_q,
                block_k=cfg.flash_block_k,
                window=cfg.sliding_window,
                alibi=alibi_arg,
                alibi_scale=(1.0 / math.sqrt(hd)
                             if cfg.alibi_inv_norm else 1.0),
                alibi_bf16=cfg.alibi_inv_norm).astype(dt)
            attn = attn.reshape(B, T, H * hd)
        else:
            scores = jnp.einsum("bthd,bshd->bhts", q, kk,
                                preferred_element_type=jnp.float32)
            scores = scores / math.sqrt(hd)
            if cfg.alibi:
                scores = scores + self._alibi_bias(
                    jnp.arange(T))[None, :, None, :]
            scores = jnp.where(causal[None, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(dt)
            attn = jnp.einsum("bhts,bshd->bthd", probs,
                              v).reshape(B, T, H * hd)
        attn_out = self._wo(constrain(attn, act_spec), layer)
        if cfg.parallel_block:
            # falcon/phi: attention and MLP branch from the same input
            x = x + attn_out + self._mlp(x, layer)
        else:
            x = x + attn_out
            x = constrain(x, act_spec)
            x = x + self._mlp(x, layer)
        return constrain(x, act_spec)

    def apply(self, params, input_ids, *, rng=None, train=False,
              seq_sharded=False, return_hidden=False):
        cfg = self.config
        T = input_ids.shape[1]
        constrain = self._constrain_fn()
        act_spec = P(BATCH_AXES, "seq" if seq_sharded else None, None)
        x = params["wte"][input_ids].astype(jnp.dtype(cfg.dtype))
        if cfg.embed_norm:
            x = _layer_norm(x, params["embed_ln_s"], params["embed_ln_b"],
                            cfg.rms_eps)
        x = constrain(x, act_spec)
        pos = jnp.broadcast_to(jnp.arange(T)[None, :], input_ids.shape)
        causal = jnp.tril(jnp.ones((T, T), jnp.bool_))
        causal = self._window_mask(causal, jnp.arange(T)[:, None],
                                   jnp.arange(T)[None, :])

        def block(x, layer):
            return self.block_forward(x, layer, pos, causal=causal,
                                      constrain=constrain,
                                      act_spec=act_spec)

        block_fn = block
        if cfg.remat:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy,
                             None)
            block_fn = jax.checkpoint(block, policy=policy)

        x, _ = lax.scan(lambda c, l: (block_fn(c, l), None), x,
                        params["blocks"])
        if return_hidden:
            return x
        return self.head(params, x)

    def apply_with_aux(self, params, input_ids, **kw):
        return self.apply(params, input_ids, **kw), jnp.zeros((),
                                                              jnp.float32)

    def _head_keys(self):
        """Param leaves ``head`` reads (the fused-CE d_params subset)."""
        cfg = self.config
        keys = ["norm_f"]
        if cfg.norm_type == "ln":
            keys.append("norm_f_b")
        keys.append("wte" if cfg.tie_embeddings else "lm_head")
        if cfg.head_bias_on:
            keys.append("lm_head_b")
        return keys

    def loss(self, params, batch, *, rng=None, train=True,
             seq_sharded=False):
        ids = batch["input_ids"]
        T = ids.shape[1]
        chunk = self.config.loss_chunk
        if chunk and T - 1 > chunk and not seq_sharded:
            x = self.apply(params, ids, rng=rng, train=train,
                           seq_sharded=seq_sharded, return_hidden=True)
            if self.config.fused_loss:
                hp = {k: params[k] for k in self._head_keys()}
                return fused_linear_xent(self.head, chunk, hp,
                                         x[:, :-1], ids[:, 1:])
            return chunked_softmax_xent(self.head, params, x[:, :-1],
                                        ids[:, 1:], chunk)
        logits = self.apply(params, ids, rng=rng, train=train,
                            seq_sharded=seq_sharded)
        return next_token_xent(logits, ids)

    # ------------------------------------------------- v1 KV-cache decoding
    def init_cache(self, batch_size, max_len, dtype=None):
        cfg = self.config
        dt = jnp.dtype(dtype) if dtype is not None else jnp.dtype(cfg.dtype)
        shape = (cfg.n_layer, batch_size, max_len, cfg.n_kv_heads,
                 cfg.d_head)
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def cache_specs(self, batch_axes=BATCH_AXES):
        spec = P(None, batch_axes, None, "tensor", None)
        return {"k": spec, "v": spec}

    def apply_cached(self, params, input_ids, pos_ids, cache, slot,
                     valid_mask, last_token_only=False):
        """Same contract as GPT2.apply_cached; KV cache stores KV heads
        only (GQA)."""
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        B, T = input_ids.shape
        H, KVH, hd = cfg.n_head, cfg.n_kv_heads, cfg.d_head
        x = params["wte"][input_ids].astype(dt)
        if cfg.embed_norm:
            x = _layer_norm(x, params["embed_ln_s"], params["embed_ln_b"],
                            cfg.rms_eps)
        Tmax = cache["k"].shape[2]

        def body(carry, xs):
            layer, kc, vc = xs
            from ..ops.int8_weights import dequant_tree
            layer = dequant_tree(layer, dt)
            x = carry
            q, kk, v = self._attn_proj(x, layer)
            # self._rope honors rotary_pct (phi partial rotary) — the
            # module-level _rope would silently diverge v1 decode from
            # training/prefill/v2 for those families
            q = self._rope(q, pos_ids)
            kk = self._rope(kk, pos_ids)
            kc = lax.dynamic_update_slice(kc, kk.astype(kc.dtype),
                                          (0, slot, 0, 0))
            vc = lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                          (0, slot, 0, 0))
            ku = _repeat_kv(kc, H // KVH)
            vu = _repeat_kv(vc, H // KVH)
            scores = jnp.einsum("bthd,bshd->bhts", q, ku,
                                preferred_element_type=jnp.float32)
            scores = scores / math.sqrt(hd)
            s_idx = jnp.arange(Tmax)[None, None, None, :]
            q_idx = (slot + jnp.arange(T))[None, None, :, None]
            mask = (s_idx <= q_idx) & valid_mask[:, None, None, :]
            mask = self._window_mask(mask, q_idx, s_idx)
            if cfg.alibi:
                scores = scores + self._alibi_bias(
                    jnp.arange(Tmax))[None, :, None, :]
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(dt)
            attn = jnp.einsum("bhts,bshd->bthd", probs, vu)
            attn_out = self._wo(attn.reshape(B, T, H * hd), layer)
            if cfg.parallel_block:
                x = x + attn_out + self._mlp(x, layer)
            else:
                x = x + attn_out
                x = x + self._mlp(x, layer)
            return x, (kc, vc)

        x, (kc, vc) = lax.scan(body, x,
                               (params["blocks"], cache["k"], cache["v"]))
        if last_token_only:
            x = x[:, -1:]
        return self.head(params, x), {"k": kc, "v": vc}

    # ------------------------------------------------- v2 paged decoding
    def init_paged_cache(self, num_blocks, block_size, dtype=None):
        """LISTS of per-layer heads-major pools (NB, KVH, BS, hd) — the
        layout the Pallas paged-decode kernel consumes without
        transposes; separate per-layer buffers so the new-token scatter
        updates each donated pool IN PLACE (see GPT2.init_paged_cache)."""
        cfg = self.config
        dt = jnp.dtype(dtype) if dtype is not None else jnp.dtype(cfg.dtype)
        shape = (num_blocks, cfg.n_kv_heads, block_size, cfg.d_head)
        return {"k": [jnp.zeros(shape, dt) for _ in range(cfg.n_layer)],
                "v": [jnp.zeros(shape, dt) for _ in range(cfg.n_layer)]}

    def paged_cache_specs(self):
        spec = P(None, "tensor", None, None)
        L = self.config.n_layer
        return {"k": [spec] * L, "v": [spec] * L}

    # FFN weight keys the fused-dequant serving path keeps quantized
    # (engine_v2 sets _weight_quant_fused; _mlp consumes them via
    # wq_matmul / grouped_swiglu_wq)
    _WQ_KEEP = ("wgate", "wup", "wdown")

    def _layer_slice(self, params, i):
        from ..ops.int8_weights import dequant_tree
        sl = jax.tree.map(lambda a: a[i], params["blocks"])
        # ZeRO-Inference weight-only serving: int8 block weights
        # dequantize one layer at a time (identity on bf16 trees);
        # under the fused path the FFN weights stay quantized and the
        # projection kernels dequantize in their epilogues
        keep = self._WQ_KEEP \
            if getattr(self, "_weight_quant_fused", False) else ()
        return dequant_tree(sl, jnp.dtype(self.config.dtype), keep=keep)

    def apply_paged_prefill(self, params, input_ids, cache, token_blocks,
                            token_offsets, length):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        T = input_ids.shape[1]
        H, KVH, hd = cfg.n_head, cfg.n_kv_heads, cfg.d_head
        x = params["wte"][input_ids].astype(dt)
        if cfg.embed_norm:
            x = _layer_norm(x, params["embed_ln_s"], params["embed_ln_b"],
                            cfg.rms_eps)
        pos = jnp.arange(T)[None, :]
        valid = (jnp.arange(T) < length)
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_)) & valid[None, :]
        mask = self._window_mask(mask, jnp.arange(T)[:, None],
                                 jnp.arange(T)[None, :])
        BS = cache["k"][0].shape[2]
        prefill_table = token_blocks[::BS]   # see GPT2.apply_paged_prefill
        from ..ops.pallas.paged_attention import (paged_chunk_attention,
                                                  resolve_paged_chunk)
        # ALiBi stays dense: the chunk kernel has no per-head bias
        # input (forced-off BEFORE dispatch, so no search is paid for
        # a kernel tile the model can never use)
        use_kernel, block_c = resolve_paged_chunk(
            False if cfg.alibi else getattr(self, "_paged_kernel",
                                            "auto"),
            getattr(self, "_paged_block_c", "auto"),
            T, prefill_table.shape[0], BS, KVH, H // KVH, hd, dt)

        ks_out, vs_out = [], []
        for i in range(cfg.n_layer):
            layer = self._layer_slice(params, i)
            kc0, vc0 = cache["k"][i], cache["v"][i]
            q, kk, v = self._attn_proj(x, layer)
            q = self._rope(q, pos)
            kk = self._rope(kk, pos)
            # in-place scatter on this layer's own donated pool buffer
            kc = kc0.at[token_blocks, :, token_offsets].set(
                kk[0].astype(kc0.dtype))
            vc = vc0.at[token_blocks, :, token_offsets].set(
                v[0].astype(vc0.dtype))
            if use_kernel:
                # GQA-native blocked stream over the prompt's own
                # blocks (no repeat_kv, no (T, T) full-score pass)
                attn = paged_chunk_attention(
                    q[0], kc, vc, prefill_table, jnp.int32(0), length,
                    window=cfg.sliding_window, block_c=block_c)[None]
            else:
                ku = _repeat_kv(kk, H // KVH)
                vu = _repeat_kv(v, H // KVH)
                scores = jnp.einsum("bthd,bshd->bhts", q, ku,
                                    preferred_element_type=jnp.float32)
                scores = scores / math.sqrt(hd)
                if cfg.alibi:
                    scores = scores + self._alibi_bias(
                        jnp.arange(T))[None, :, None, :]
                scores = jnp.where(mask[None, None], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1).astype(dt)
                attn = jnp.einsum("bhts,bshd->bthd", probs, vu)
            attn_out = self._wo(attn.reshape(1, T, H * hd), layer)
            if cfg.parallel_block:
                x = x + attn_out + self._mlp(x, layer)
            else:
                x = x + attn_out
                x = x + self._mlp(x, layer)
            ks_out.append(kc)
            vs_out.append(vc)
        last = jnp.take_along_axis(
            x, jnp.maximum(length - 1, 0)[None, None, None], axis=1)
        return self.head(params, last)[:, 0], {"k": ks_out, "v": vs_out}

    def apply_paged_chunk(self, params, input_ids, cache, token_blocks,
                          token_offsets, start, true_len, table):
        """Prefill ONE CHUNK of one sequence into the paged cache
        (Dynamic SplitFuse: long prompts stream through a fixed-size
        chunk program instead of one bucketed prefill per prompt —
        reference blogs/deepspeed-fastgen §3B, inference/v2/ragged/).

        input_ids: (1, C) chunk tokens (right-padded); token_blocks/
        token_offsets: (C,) destination block/slot per chunk position
        (pads point at scratch block 0); start: scalar absolute position
        of the chunk's first token; true_len: scalar number of real
        tokens in the chunk; table: (MB,) the sequence's full block
        table (scratch-padded). Queries attend the sequence's PRIOR
        cache plus the in-chunk causal prefix — K/V are scattered first,
        then gathered back through the table, so the attention sees one
        contiguous [0, start + true_len) key range.
        Returns (logits (1, V) at chunk position true_len - 1, cache).
        """
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        C = input_ids.shape[1]
        H, KVH, hd = cfg.n_head, cfg.n_kv_heads, cfg.d_head
        BS = cache["k"][0].shape[2]
        x = params["wte"][input_ids].astype(dt)
        if cfg.embed_norm:
            x = _layer_norm(x, params["embed_ln_s"], params["embed_ln_b"],
                            cfg.rms_eps)
        pos = start + jnp.arange(C)[None, :]
        S = table.shape[0] * BS
        q_pos = (start + jnp.arange(C))[:, None]       # (C, 1)
        k_pos = jnp.arange(S)[None, :]                 # (1, S)
        mask = (k_pos <= q_pos) & (k_pos < start + true_len)
        mask = self._window_mask(mask, q_pos, k_pos)
        from ..ops.pallas.paged_attention import (paged_chunk_attention,
                                                  resolve_paged_chunk)
        use_kernel, block_c = resolve_paged_chunk(
            False if cfg.alibi else getattr(self, "_paged_kernel",
                                            "auto"),   # no bias input
            getattr(self, "_paged_block_c", "auto"),
            C, table.shape[0], BS, KVH, H // KVH, hd, dt)

        ks_out, vs_out = [], []
        for i in range(cfg.n_layer):
            layer = self._layer_slice(params, i)
            kc0, vc0 = cache["k"][i], cache["v"][i]
            q, kk, v = self._attn_proj(x, layer)
            q = self._rope(q, pos)
            kk = self._rope(kk, pos)
            kc = kc0.at[token_blocks, :, token_offsets].set(
                kk[0].astype(kc0.dtype))
            vc = vc0.at[token_blocks, :, token_offsets].set(
                v[0].astype(vc0.dtype))
            if use_kernel:
                # blocked-flash chunk kernel: each KV block streams
                # through VMEM once, located via the block table; the
                # (S, H, hd) gather + repeat_kv copies never exist
                attn = paged_chunk_attention(
                    q[0], kc, vc, table, start, true_len,
                    window=cfg.sliding_window, block_c=block_c)[None]
            else:
                # gather the sequence's full K/V range through its
                # table: (MB, KVH, BS, hd) -> (S, KVH, hd); in-cache
                # layout is heads-major, so one transpose per row
                gk = kc[table].transpose(0, 2, 1, 3).reshape(S, KVH, hd)
                gv = vc[table].transpose(0, 2, 1, 3).reshape(S, KVH, hd)
                gk = _repeat_kv(gk[None], H // KVH)[0]
                gv = _repeat_kv(gv[None], H // KVH)[0]
                scores = jnp.einsum("bthd,shd->bhts", q, gk,
                                    preferred_element_type=jnp.float32)
                scores = scores / math.sqrt(hd)
                if cfg.alibi:
                    scores = scores + self._alibi_bias(
                        jnp.arange(S))[None, :, None, :]
                scores = jnp.where(mask[None, None], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1).astype(dt)
                attn = jnp.einsum("bhts,shd->bthd", probs, gv)
            attn_out = self._wo(attn.reshape(1, C, H * hd), layer)
            if cfg.parallel_block:
                x = x + attn_out + self._mlp(x, layer)
            else:
                x = x + attn_out
                x = x + self._mlp(x, layer)
            ks_out.append(kc)
            vs_out.append(vc)
        last = jnp.take_along_axis(
            x, jnp.maximum(true_len - 1, 0)[None, None, None], axis=1)
        return self.head(params, last)[:, 0], {"k": ks_out, "v": vs_out}

    def apply_paged_decode(self, params, tokens, lengths, cache,
                           block_tables):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        B = tokens.shape[0]
        H, hd = cfg.n_head, cfg.d_head
        BS = cache["k"][0].shape[2]
        pos = jnp.minimum(lengths, cfg.max_seq_len - 1)
        x = params["wte"][tokens[:, None]].astype(dt)
        if cfg.embed_norm:
            x = _layer_norm(x, params["embed_ln_s"], params["embed_ln_b"],
                            cfg.rms_eps)
        dst_block = jnp.take_along_axis(
            block_tables, (lengths // BS)[:, None], axis=1)[:, 0]
        dst_off = lengths % BS
        from ..ops.pallas.paged_attention import resolve_paged_decode
        # ALiBi families keep the kernel regardless of the mode switch
        # (the dense fallback lacks the falcon bf16-quantized variant)
        use_kernel = cfg.alibi or resolve_paged_decode(
            getattr(self, "_paged_kernel", "auto"), tokens.shape[0],
            block_tables.shape[1], BS, cfg.n_kv_heads,
            H // cfg.n_kv_heads, hd, dt)

        ks_out, vs_out = [], []
        for i in range(cfg.n_layer):
            layer = self._layer_slice(params, i)
            kc0, vc0 = cache["k"][i], cache["v"][i]
            q, kk, v = self._attn_proj(x, layer)       # (B, 1, ., hd)
            q = self._rope(q, pos[:, None])
            kk = self._rope(kk, pos[:, None])
            kc = kc0.at[dst_block, :, dst_off].set(
                kk[:, 0].astype(kc0.dtype))
            vc = vc0.at[dst_block, :, dst_off].set(
                v[:, 0].astype(vc0.dtype))
            # Pallas paged kernel: GQA-native (no repeat_kv copies), K/V
            # read straight through the block table (reference
            # inference/v2/kernels/ragged_ops blocked_flash); dense
            # gather behind paged_kernel=False as the parity fallback
            from ..ops.pallas.paged_attention import (
                alibi_slopes, paged_decode_attention,
                paged_decode_attention_reference)
            if use_kernel:
                attn = paged_decode_attention(
                    q[:, 0], kc, vc, block_tables, lengths,
                    window=cfg.sliding_window,
                    alibi_slopes=(alibi_slopes(H) if cfg.alibi
                                  else None),
                    alibi_scale=(1.0 / math.sqrt(hd)
                                 if cfg.alibi_inv_norm else 1.0),
                    alibi_bf16=cfg.alibi_inv_norm)
            else:
                attn = paged_decode_attention_reference(
                    q[:, 0], kc, vc, block_tables, lengths,
                    window=cfg.sliding_window)
            attn_out = self._wo(attn.reshape(B, 1, H * hd), layer)
            if cfg.parallel_block:
                x = x + attn_out + self._mlp(x, layer)
            else:
                x = x + attn_out
                x = x + self._mlp(x, layer)
            ks_out.append(kc)
            vs_out.append(vc)
        return self.head(params, x)[:, 0], {"k": ks_out, "v": vs_out}

    def apply_paged_verify(self, params, tokens, lengths, cache,
                           block_tables):
        """Speculative-verify step: C tokens per slot in ONE pass (see
        GPT2.apply_paged_verify — same contract; llama families add
        RoPE at each slot's absolute positions, GQA-native kernel reads,
        and the ALiBi/sliding-window biases of the chunk path).

        tokens: (B, C); lengths: (B,) = first input token's position;
        block_tables: (B, MB). Returns (logits (B, C, V), cache)."""
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        B, C = tokens.shape
        H, KVH, hd = cfg.n_head, cfg.n_kv_heads, cfg.d_head
        BS = cache["k"][0].shape[2]
        MB = block_tables.shape[1]
        S = MB * BS
        linpos = lengths[:, None] + jnp.arange(C)[None, :]       # (B, C)
        pos = jnp.minimum(linpos, cfg.max_seq_len - 1)
        x = params["wte"][tokens].astype(dt)
        if cfg.embed_norm:
            x = _layer_norm(x, params["embed_ln_s"], params["embed_ln_b"],
                            cfg.rms_eps)
        dst_block = jnp.take_along_axis(
            block_tables, jnp.minimum(linpos // BS, MB - 1), axis=1)
        dst_off = linpos % BS
        fb, fo = dst_block.reshape(-1), dst_off.reshape(-1)
        q_pos = linpos[:, :, None]                            # (B, C, 1)
        k_pos = jnp.arange(S)[None, None, :]                  # (1, 1, S)
        mask = (k_pos <= q_pos) \
            & (k_pos < (lengths + C)[:, None, None])
        mask = self._window_mask(mask, q_pos, k_pos)
        from ..ops.pallas.paged_attention import (paged_chunk_attention,
                                                  resolve_paged_chunk)
        use_kernel, block_c = resolve_paged_chunk(
            False if cfg.alibi else getattr(self, "_paged_kernel",
                                            "auto"),   # no bias input
            getattr(self, "_paged_block_c", "auto"),
            C, MB, BS, KVH, H // KVH, hd, dt)

        ks_out, vs_out = [], []
        for i in range(cfg.n_layer):
            layer = self._layer_slice(params, i)
            kc0, vc0 = cache["k"][i], cache["v"][i]
            q, kk, v = self._attn_proj(x, layer)       # (B, C, ., hd)
            q = self._rope(q, pos)
            kk = self._rope(kk, pos)
            kc = kc0.at[fb, :, fo].set(
                kk.reshape(B * C, KVH, hd).astype(kc0.dtype))
            vc = vc0.at[fb, :, fo].set(
                v.reshape(B * C, KVH, hd).astype(vc0.dtype))
            if use_kernel:
                attn = jnp.stack([
                    paged_chunk_attention(
                        q[b], kc, vc, block_tables[b], lengths[b],
                        jnp.int32(C), window=cfg.sliding_window,
                        block_c=block_c)
                    for b in range(B)])
            else:
                gk = kc[block_tables].transpose(0, 1, 3, 2, 4) \
                    .reshape(B, S, KVH, hd)
                gv = vc[block_tables].transpose(0, 1, 3, 2, 4) \
                    .reshape(B, S, KVH, hd)
                gk = _repeat_kv(gk, H // KVH)
                gv = _repeat_kv(gv, H // KVH)
                scores = jnp.einsum("bthd,bshd->bhts", q, gk,
                                    preferred_element_type=jnp.float32)
                scores = scores / math.sqrt(hd)
                if cfg.alibi:
                    scores = scores + self._alibi_bias(
                        jnp.arange(S))[None, :, None, :]
                scores = jnp.where(mask[:, None], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1).astype(dt)
                attn = jnp.einsum("bhts,bshd->bthd", probs, gv)
            attn_out = self._wo(attn.reshape(B, C, H * hd), layer)
            if cfg.parallel_block:
                x = x + attn_out + self._mlp(x, layer)
            else:
                x = x + attn_out
                x = x + self._mlp(x, layer)
            ks_out.append(kc)
            vs_out.append(vc)
        return self.head(params, x), {"k": ks_out, "v": vs_out}
