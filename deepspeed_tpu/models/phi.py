"""Phi model family — parallel attention/MLP block, partial rotary, LN.

Counterpart of the reference's Phi serving support
(inference/v2/model_implementations/phi/{model,policy}.py): LayerNorm
with bias (not RMSNorm), rotary applied to only a fraction of each head
(phi-2: 0.4), a plain-GELU (non-gated) MLP, and the PARALLEL residual
form x + attn(ln x) + mlp(ln x) — phi shares one input LayerNorm
between the two branches, realized here by pointing both branch norms
at the same parameters at load time (init keeps them separate but
identical; the math is identical while they remain tied).

Training, v1 decoding, and v2 paged serving all inherit from
:class:`~.llama.Llama` through its architecture knobs
(parallel_block/rotary_pct/mlp_gated/norm_type) — the family is the
config point.
"""

from dataclasses import dataclass

from .llama import Llama, LlamaConfig


@dataclass(frozen=True)
class PhiConfig(LlamaConfig):
    parallel_block: bool = True
    rotary_pct: float = 0.4              # phi-2 partial rotary factor
    mlp_gated: bool = False              # plain gelu MLP
    norm_type: str = "ln"                # LayerNorm with bias
    qkv_bias: bool = True                # phi projects with bias
    proj_bias: bool = True               # ...including wo/MLP/lm_head


PHI_TINY = PhiConfig(n_layer=2, n_head=4, n_kv_heads=4, d_model=128,
                     max_seq_len=128, vocab_size=512, remat=False)
# phi-2 point (config.json: 32 layers, 32 heads, hidden 2560,
# intermediate 10240, rotary over 32 of 80 dims)
PHI_2 = PhiConfig(n_layer=32, n_head=32, n_kv_heads=32, d_model=2560,
                  d_ff=10240, max_seq_len=2048, vocab_size=51200)

PHI_PRESETS = {"tiny": PHI_TINY, "phi-2": PHI_2}


class Phi(Llama):
    """Phi: parallel-block partial-rotary LN model on the shared Llama
    machinery (see module docstring)."""

    def __init__(self, config: PhiConfig):
        if not config.parallel_block:
            raise ValueError("Phi requires parallel_block=True")
        super().__init__(config)
