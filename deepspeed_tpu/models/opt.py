"""OPT model family — GPT-2 architecture with ReLU MLP.

Counterpart of the reference's OPT serving support
(inference/v2/model_implementations/opt/{model,policy}.py,
module_inject/containers/opt.py): decoder-only transformer with learned
absolute position embeddings, pre-LayerNorm blocks, and a ReLU (not
GELU) feed-forward — i.e. the GPT-2 machinery with the activation
swapped, which is exactly how the reference's OPT container maps onto
its GPT-ish kernel set. (HF OPT offsets position ids by 2 padding slots
— a checkpoint-conversion detail, not an architecture one: handle it in
the loader by slicing the first two wpe rows off.) Training, v1 cached
decode, and v2 paged serving all inherit from :class:`~.gpt2.GPT2`.
"""

from dataclasses import dataclass, replace

from .gpt2 import GPT2, GPT2Config


@dataclass(frozen=True)
class OPTConfig(GPT2Config):
    activation: str = "relu"             # the family's distinguishing knob
    vocab_size: int = 50272


OPT_TINY = OPTConfig(n_layer=2, n_head=4, d_model=128, max_seq_len=128,
                     vocab_size=512, remat=False)
# opt-1.3b point (config.json: 24 layers, 32 heads, hidden 2048)
OPT_1_3B = OPTConfig(n_layer=24, n_head=32, d_model=2048,
                     max_seq_len=2048)

OPT_PRESETS = {"tiny": OPT_TINY, "opt-1.3b": OPT_1_3B}


class OPT(GPT2):
    """OPT: GPT-2 forward/caching/serving with a ReLU MLP via config."""

    def __init__(self, config: OPTConfig):
        if config.activation != "relu":
            raise ValueError("OPT uses a ReLU feed-forward")
        super().__init__(config)
