"""GPT-Neo family — gpt2-style blocks, unscaled attention, local layers.

Counterpart of the reference's GPT-Neo injection support
(module_inject/containers/gptneo.py, HFGPTNEOLayerPolicy). On the GPT2
family (learned positions, sequential LN blocks, tied unembed) with two
quirks expressed as GPT2Config knobs:

  * ``scale_attn=False`` — HF GPT-Neo never divides scores by
    sqrt(head_dim) (modeling_gpt_neo.py GPTNeoSelfAttention);
  * ``attn_layer_windows`` — the config's ``attention_types`` pattern
    alternates global and LOCAL (sliding-window, ``window_size``)
    attention per layer; the per-layer window rides the layer scan as an
    operand (0 = global).

q/k/v projections carry no bias (loaded as zero rows of the fused
bqkv); out_proj and the MLP are biased, weights are nn.Linear (out, in)
— transposed at load, unlike gpt2's Conv1D.
"""

from dataclasses import dataclass

from .gpt2 import GPT2, GPT2Config


@dataclass(frozen=True)
class GPTNeoConfig(GPT2Config):
    scale_attn: bool = False


GPTNEO_TINY = GPTNeoConfig(n_layer=2, n_head=4, d_model=128,
                           max_seq_len=128, vocab_size=512, remat=False,
                           attn_layer_windows=(0, 64))
# gpt-neo-1.3B point (24 layers alternating global/local window 256)
GPTNEO_1_3B = GPTNeoConfig(n_layer=24, n_head=16, d_model=2048,
                           max_seq_len=2048, vocab_size=50257,
                           attn_layer_windows=tuple(
                               0 if i % 2 == 0 else 256
                               for i in range(24)))

GPTNEO_PRESETS = {"tiny": GPTNEO_TINY, "gpt-neo-1.3b": GPTNEO_1_3B}


class GPTNeo(GPT2):
    """GPT-Neo on the GPT2 machinery (see module docstring)."""

    def __init__(self, config: GPTNeoConfig):
        super().__init__(config)
