"""Qwen model family — Llama-architecture with attention-projection bias.

Counterpart of the reference's Qwen serving support
(inference/v2/model_implementations/qwen_v2/{model,policy}.py and
module_inject/containers for Qwen): RMSNorm + RoPE + SwiGLU + GQA like
Llama, plus learned biases on the q/k/v projections (the reference's
qwen containers split exactly those bias tensors for TP). Everything —
training, v1 contiguous-cache decoding, v2 paged serving on the Pallas
paged-attention kernel — inherits from :class:`~.llama.Llama`; the
family is the config point, which is the honest TPU translation of the
reference's per-family policy classes (they exist to map HF module
trees; here the functional model IS the tree).
"""

from dataclasses import dataclass

from .llama import Llama, LlamaConfig


@dataclass(frozen=True)
class QwenConfig(LlamaConfig):
    qkv_bias: bool = True                # the family's distinguishing knob
    rope_theta: float = 1000000.0        # qwen2 long-context base
    vocab_size: int = 151936


QWEN_TINY = QwenConfig(n_layer=2, n_head=4, n_kv_heads=2, d_model=128,
                       max_seq_len=128, vocab_size=512, remat=False)
# Qwen2-1.5B point (config.json: 28 layers, 12 heads, 2 KV heads,
# hidden 1536, intermediate 8960)
QWEN2_1_5B = QwenConfig(n_layer=28, n_head=12, n_kv_heads=2, d_model=1536,
                        d_ff=8960, max_seq_len=32768, tie_embeddings=True)
QWEN2_7B = QwenConfig(n_layer=28, n_head=28, n_kv_heads=4, d_model=3584,
                      d_ff=18944, max_seq_len=32768)

QWEN_PRESETS = {"tiny": QWEN_TINY, "qwen2-1.5b": QWEN2_1_5B,
                "qwen2-7b": QWEN2_7B}


class Qwen(Llama):
    """Qwen: Llama forward/caching/serving with qkv bias enabled via
    config; subclass exists so engines and tooling can name the family
    (mirrors the reference's per-family model_implementations)."""

    def __init__(self, config: QwenConfig):
        if not config.qkv_bias:
            raise ValueError("Qwen requires qkv_bias=True")
        super().__init__(config)
