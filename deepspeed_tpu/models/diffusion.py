"""Diffusion model family: UNet2D (conditioned) + VAE decoder.

Counterpart of the reference's diffusers serving containers
(module_inject/containers/unet.py, vae.py +
model_implementations/diffusers/{unet,vae}.py DSUNet/DSVAE): the
reference wraps HF diffusers modules to capture them in CUDA graphs and
inject fused spatial ops. TPU redesign: the models are FUNCTIONAL jax
modules compiled once per shape under ``jit`` — the compile cache IS
the CUDA-graph property — built on:

  * ``ops/spatial.py`` fused bias adds (opt_bias_add / _add_add / _res
    — the csrc/spatial op surface) for every conv bias + residual join;
  * the Pallas flash kernel for the spatial self-attention at
    resolutions where the token count is lane-tileable (dense fallback
    elsewhere — cross-attention over short text contexts is dense by
    design: T_ctx ~ 77 tokens is below kernel break-even);
  * NHWC convs via ``lax.conv_general_dilated`` (XLA tiles these onto
    the MXU natively — no im2col, no custom kernel).

``DSUNet`` / ``DSVAE`` mirror the reference wrapper API: __call__
dispatches to the jitted forward, compiled once per input shape.
"""

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.spatial import opt_bias_add, opt_bias_add_add, opt_bias_add_res

__all__ = ["UNet2DConfig", "UNet2D", "VAEDecoderConfig", "VAEDecoder",
           "DSUNet", "DSVAE"]


# ----------------------------------------------------------------- helpers
def _conv(x, w, b=None, stride=1, padding="SAME"):
    """NHWC conv; w: (kh, kw, cin, cout)."""
    y = lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return opt_bias_add(y, b) if b is not None else y


def _group_norm(x, scale, bias, groups=32, eps=1e-5):
    """GroupNorm over NHWC channels (fp32 stats)."""
    N, H, W, C = x.shape
    g = min(groups, C)
    while C % g:
        g -= 1
    x32 = x.astype(jnp.float32).reshape(N, H, W, g, C // g)
    mu = jnp.mean(x32, axis=(1, 2, 4), keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=(1, 2, 4), keepdims=True)
    x32 = (x32 - mu) * lax.rsqrt(var + eps)
    x32 = x32.reshape(N, H, W, C)
    return (x32 * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _timestep_embedding(t, dim):
    """Sinusoidal timestep embedding (diffusers get_timestep_embedding)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _attention(q, k, v, n_heads):
    """(B, T, C) x3 -> (B, T, C) multi-head attention. Uses the Pallas
    flash kernel for self-attention shapes it tiles well (T % 128 == 0,
    head_dim >= 32); dense softmax otherwise."""
    B, T, C = q.shape
    S = k.shape[1]
    hd = C // n_heads
    qh = q.reshape(B, T, n_heads, hd)
    kh = k.reshape(B, S, n_heads, hd)
    vh = v.reshape(B, S, n_heads, hd)
    use_flash = (jax.default_backend() == "tpu" and T == S
                 and T % 128 == 0 and hd >= 32)
    if use_flash:
        from ..ops.pallas.flash_attention import flash_attention
        out = flash_attention(qh, kh, vh, causal=False)
        return out.reshape(B, T, C)
    s = jnp.einsum("bthd,bshd->bhts", qh, kh,
                   preferred_element_type=jnp.float32) / math.sqrt(hd)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", p, vh).reshape(B, T, C)


# ------------------------------------------------------------------- UNet
@dataclass(frozen=True)
class UNet2DConfig:
    in_channels: int = 4
    out_channels: int = 4
    channels: tuple = (64, 128)       # per resolution level
    n_heads: int = 4
    cross_dim: int = 128              # text-conditioning width
    groups: int = 32
    dtype: str = "float32"


class UNet2D:
    """Conditioned UNet: conv_in -> down levels (resnet + attn,
    downsample) -> mid (resnet, attn, resnet) -> up levels (skip concat)
    -> groupnorm/silu/conv_out. Spatial attention flattens (H*W) tokens;
    cross-attention attends the text context. Sized like the reference's
    DSUNet role: the serving wrapper's compute body, not a training
    reimplementation of diffusers."""

    def __init__(self, config: UNet2DConfig):
        self.config = config

    # ------------------------------------------------------------- params
    def init(self, rng):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        cnt = [0]

        def nxt():
            cnt[0] += 1
            return jax.random.fold_in(rng, cnt[0])

        def conv_w(kh, kw, cin, cout, s=0.02):
            return {"w": (jax.random.normal(nxt(), (kh, kw, cin, cout),
                                            jnp.float32) * s).astype(dt),
                    "b": jnp.zeros((cout,), dt)}

        def lin(cin, cout, s=0.02):
            return {"w": (jax.random.normal(nxt(), (cin, cout),
                                            jnp.float32) * s).astype(dt),
                    "b": jnp.zeros((cout,), dt)}

        def gn(c):
            return {"s": jnp.ones((c,), dt), "b": jnp.zeros((c,), dt)}

        def resnet(cin, cout):
            return {"gn1": gn(cin), "conv1": conv_w(3, 3, cin, cout),
                    "temb": lin(cfg.channels[0] * 4, cout),
                    "gn2": gn(cout), "conv2": conv_w(3, 3, cout, cout),
                    "skip": (conv_w(1, 1, cin, cout)
                             if cin != cout else None)}

        def attn_block(c):
            return {"gn": gn(c),
                    "to_q": lin(c, c), "to_k": lin(c, c),
                    "to_v": lin(c, c), "to_out": lin(c, c),
                    "xq": lin(c, c), "xk": lin(cfg.cross_dim, c),
                    "xv": lin(cfg.cross_dim, c), "xout": lin(c, c)}

        ch = cfg.channels
        temb_dim = ch[0] * 4
        params = {
            "temb1": lin(ch[0], temb_dim),
            "temb2": lin(temb_dim, temb_dim),
            "conv_in": conv_w(3, 3, cfg.in_channels, ch[0]),
            "down": [], "up": [],
            "gn_out": gn(ch[0]),
            "conv_out": conv_w(3, 3, ch[0], cfg.out_channels),
        }
        cin = ch[0]
        for c in ch:
            params["down"].append({
                "res": resnet(cin, c), "attn": attn_block(c),
                "ds": conv_w(3, 3, c, c)})
            cin = c
        params["mid"] = {"res1": resnet(cin, cin),
                         "attn": attn_block(cin),
                         "res2": resnet(cin, cin)}
        for c in reversed(ch):
            params["up"].append({
                # us runs BEFORE the skip concat: channels stay cin
                "res": resnet(cin + c, c), "attn": attn_block(c),
                "us": conv_w(3, 3, cin, cin)})
            cin = c
        return params

    # ------------------------------------------------------------ forward
    def _resnet(self, p, x, temb):
        h = _conv(jax.nn.silu(_group_norm(x, p["gn1"]["s"], p["gn1"]["b"],
                                          self.config.groups)),
                  p["conv1"]["w"], p["conv1"]["b"])
        t = jax.nn.silu(temb) @ p["temb"]["w"]
        # fused bias + broadcast time-emb add (opt_bias_add_add role)
        h = opt_bias_add_add(h, p["temb"]["b"], t[:, None, None, :])
        h = _conv(jax.nn.silu(_group_norm(h, p["gn2"]["s"], p["gn2"]["b"],
                                          self.config.groups)),
                  p["conv2"]["w"])
        skip = x if p["skip"] is None else _conv(x, p["skip"]["w"])
        skip_b = None if p["skip"] is None else p["skip"]["b"]
        # fused conv-bias + residual join (opt_res_add_bias_add role)
        return opt_bias_add_res(h, p["conv2"]["b"], skip, skip_b)

    def _attn(self, p, x, ctx):
        cfg = self.config
        B, H, W, C = x.shape
        h = _group_norm(x, p["gn"]["s"], p["gn"]["b"], cfg.groups)
        normed = h.reshape(B, H * W, C)
        q = opt_bias_add(normed @ p["to_q"]["w"], p["to_q"]["b"])
        k = opt_bias_add(normed @ p["to_k"]["w"], p["to_k"]["b"])
        v = opt_bias_add(normed @ p["to_v"]["w"], p["to_v"]["b"])
        a = _attention(q, k, v, cfg.n_heads)
        tokens = x.reshape(B, H * W, C) \
            + opt_bias_add(a @ p["to_out"]["w"], p["to_out"]["b"])
        if ctx is not None:
            ctx = ctx.astype(tokens.dtype)
            q = opt_bias_add(tokens @ p["xq"]["w"], p["xq"]["b"])
            k = opt_bias_add(ctx @ p["xk"]["w"], p["xk"]["b"])
            v = opt_bias_add(ctx @ p["xv"]["w"], p["xv"]["b"])
            a = _attention(q, k, v, cfg.n_heads)
            tokens = tokens + opt_bias_add(a @ p["xout"]["w"],
                                           p["xout"]["b"])
        return tokens.reshape(B, H, W, C)

    def apply(self, params, latents, timesteps, context=None):
        """latents (B, H, W, Cin) NHWC; timesteps (B,); context
        (B, T_ctx, cross_dim) or None -> (B, H, W, Cout)."""
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        x = latents.astype(dt)
        temb = _timestep_embedding(timesteps, cfg.channels[0]).astype(dt)
        temb = opt_bias_add(temb @ params["temb1"]["w"],
                            params["temb1"]["b"])
        temb = opt_bias_add(jax.nn.silu(temb) @ params["temb2"]["w"],
                            params["temb2"]["b"])
        x = _conv(x, params["conv_in"]["w"], params["conv_in"]["b"])
        skips = []
        for lvl in params["down"]:
            x = self._resnet(lvl["res"], x, temb)
            x = self._attn(lvl["attn"], x, context)
            skips.append(x)
            x = _conv(x, lvl["ds"]["w"], lvl["ds"]["b"], stride=2)
        x = self._resnet(params["mid"]["res1"], x, temb)
        x = self._attn(params["mid"]["attn"], x, context)
        x = self._resnet(params["mid"]["res2"], x, temb)
        for lvl in params["up"]:
            B, H, W, C = x.shape
            x = jax.image.resize(x, (B, H * 2, W * 2, C), "nearest")
            x = _conv(x, lvl["us"]["w"], lvl["us"]["b"])
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = self._resnet(lvl["res"], x, temb)
            x = self._attn(lvl["attn"], x, context)
        x = jax.nn.silu(_group_norm(x, params["gn_out"]["s"],
                                    params["gn_out"]["b"], cfg.groups))
        return _conv(x, params["conv_out"]["w"], params["conv_out"]["b"])


# -------------------------------------------------------------------- VAE
@dataclass(frozen=True)
class VAEDecoderConfig:
    latent_channels: int = 4
    out_channels: int = 3
    channels: tuple = (128, 64)       # decoder levels, latent -> image
    groups: int = 32
    scaling_factor: float = 0.18215   # SD latent scaling
    dtype: str = "float32"


class VAEDecoder:
    """Latent -> image decoder (the reference DSVAE's decode path):
    conv_in -> resnets with nearest-upsample between levels ->
    groupnorm/silu/conv_out."""

    def __init__(self, config: VAEDecoderConfig):
        self.config = config

    def init(self, rng):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        cnt = [0]

        def nxt():
            cnt[0] += 1
            return jax.random.fold_in(rng, cnt[0])

        def conv_w(kh, kw, cin, cout, s=0.02):
            return {"w": (jax.random.normal(nxt(), (kh, kw, cin, cout),
                                            jnp.float32) * s).astype(dt),
                    "b": jnp.zeros((cout,), dt)}

        def gn(c):
            return {"s": jnp.ones((c,), dt), "b": jnp.zeros((c,), dt)}

        def resnet(cin, cout):
            return {"gn1": gn(cin), "conv1": conv_w(3, 3, cin, cout),
                    "gn2": gn(cout), "conv2": conv_w(3, 3, cout, cout),
                    "skip": (conv_w(1, 1, cin, cout)
                             if cin != cout else None)}

        ch = cfg.channels
        params = {"conv_in": conv_w(3, 3, cfg.latent_channels, ch[0]),
                  "levels": [],
                  "gn_out": gn(ch[-1]),
                  "conv_out": conv_w(3, 3, ch[-1], cfg.out_channels)}
        cin = ch[0]
        for c in ch:
            params["levels"].append({"res": resnet(cin, c),
                                     "us": conv_w(3, 3, c, c)})
            cin = c
        return params

    def _resnet(self, p, x):
        g = self.config.groups
        h = _conv(jax.nn.silu(_group_norm(x, p["gn1"]["s"], p["gn1"]["b"],
                                          g)),
                  p["conv1"]["w"], p["conv1"]["b"])
        h = _conv(jax.nn.silu(_group_norm(h, p["gn2"]["s"], p["gn2"]["b"],
                                          g)),
                  p["conv2"]["w"])
        skip = x if p["skip"] is None else _conv(x, p["skip"]["w"])
        skip_b = None if p["skip"] is None else p["skip"]["b"]
        return opt_bias_add_res(h, p["conv2"]["b"], skip, skip_b)

    def apply(self, params, latents):
        cfg = self.config
        x = (latents / cfg.scaling_factor).astype(jnp.dtype(cfg.dtype))
        x = _conv(x, params["conv_in"]["w"], params["conv_in"]["b"])
        for lvl in params["levels"]:
            x = self._resnet(lvl["res"], x)
            B, H, W, C = x.shape
            x = jax.image.resize(x, (B, H * 2, W * 2, C), "nearest")
            x = _conv(x, lvl["us"]["w"], lvl["us"]["b"])
        x = jax.nn.silu(_group_norm(x, params["gn_out"]["s"],
                                    params["gn_out"]["b"], cfg.groups))
        return _conv(x, params["conv_out"]["w"], params["conv_out"]["b"])


# -------------------------------------------------- serving wrappers
class _JitWrapper:
    """Compile-once-per-shape dispatch — the TPU stand-in for the
    reference wrappers' CUDA-graph capture (DSUNet/DSVAE
    enable_cuda_graph): first call per input shape traces+compiles, all
    later calls replay the cached executable."""

    def __init__(self, model, params):
        self.model = model
        self.params = params
        self._fn = jax.jit(model.apply)
        self.compiles = 0
        self._shapes = set()

    def _note(self, *args):
        key = tuple(getattr(a, "shape", None) for a in args)
        if key not in self._shapes:
            self._shapes.add(key)
            self.compiles += 1


class DSUNet(_JitWrapper):
    """reference model_implementations/diffusers/unet.py DSUNet."""

    def __call__(self, latents, timesteps, context=None):
        self._note(latents, timesteps, context)
        return self._fn(self.params, latents, timesteps, context)


class DSVAE(_JitWrapper):
    """reference model_implementations/diffusers/vae.py DSVAE (decode)."""

    def __call__(self, latents):
        self._note(latents)
        return self._fn(self.params, latents)
