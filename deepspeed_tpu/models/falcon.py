"""Falcon model family — parallel block, LayerNorm, multi-query attention.

Counterpart of the reference's Falcon serving support
(inference/v2/model_implementations/falcon/{model,policy}.py,
module_inject/containers/falcon): RoPE + LayerNorm (with bias) + plain
GELU MLP + the parallel residual x + attn(ln x) + mlp(ln x), and
falcon-7b's multi-query attention (ONE shared KV head — the extreme of
GQA, n_kv_heads=1). All paths — training, v1 contiguous-cache decode,
v2 paged serving on the Pallas paged-attention kernel — inherit from
:class:`~.llama.Llama` through its architecture knobs; the family is
the config point. Falcon-7b shares a single input LayerNorm between the
branches; as with Phi, tie the two branch norms at load time (init
keeps them separate but identical — identical math while tied).
"""

from dataclasses import dataclass

from .llama import Llama, LlamaConfig


@dataclass(frozen=True)
class FalconConfig(LlamaConfig):
    parallel_block: bool = True
    mlp_gated: bool = False              # plain gelu MLP
    mlp_act: str = "gelu"                # HF FalconMLP: exact-erf nn.GELU
    norm_type: str = "ln"                # LayerNorm with bias
    n_kv_heads: int = 1                  # multi-query attention
    vocab_size: int = 65024


FALCON_TINY = FalconConfig(n_layer=2, n_head=4, n_kv_heads=1, d_model=128,
                           max_seq_len=128, vocab_size=512, remat=False)
# falcon-7b point (config.json: 32 layers, 71 heads, hidden 4544, MQA)
FALCON_7B = FalconConfig(n_layer=32, n_head=71, n_kv_heads=1, d_model=4544,
                         d_ff=4 * 4544, max_seq_len=2048,
                         tie_embeddings=True)

FALCON_PRESETS = {"tiny": FALCON_TINY, "falcon-7b": FALCON_7B}


class Falcon(Llama):
    """Falcon: LN model on the shared Llama machinery (see module
    docstring). The family spans three generations — 7b (parallel block
    + MQA), new-decoder-arch 40b/180b (parallel block + GQA, two input
    norms), and falcon-rw (sequential block, per-head attention, ALiBi,
    biases) — all expressed as config knobs; no per-variant subclass."""

    def __init__(self, config: FalconConfig):
        super().__init__(config)
