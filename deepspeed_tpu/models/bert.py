"""BERT-style encoder model over DeepSpeedTransformerLayer.

Counterpart of the reference's transformer-kernel validation target: the
fused encoder layer (ops/transformer/transformer.py:296, csrc/transformer/)
is exercised there against a vendored HF BERT
(tests/unit/modeling.py + the transformer-kernel parity tests under
tests/unit/ops/transformer/). Here the encoder is a first-class model —
embeddings (token + position + segment, post-embedding LayerNorm) over a
stack of DeepSpeedTransformerLayer blocks with a tied-embedding MLM head —
so the fused layer trains end to end through the engine
(`initialize(model=Bert(cfg), ...)`) and its numerics are pinned fwd+bwd
against an independent dense reference (tests/unit/test_bert.py).
"""

from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.transformer.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer)
from ..utils.groups import BATCH_AXES


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30528
    max_seq_len: int = 512
    type_vocab_size: int = 2
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    intermediate_size: int = 0         # 0 = 4 * d_model
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = False       # classic BERT is post-LN
    dropout: float = 0.0
    dtype: str = "float32"
    mlm_mask_ratio: float = 0.15       # MLM training objective
    use_flash_attention: bool = False  # encoder: bidirectional flash

    def layer_config(self):
        return DeepSpeedTransformerConfig(
            hidden_size=self.d_model, heads=self.n_head,
            intermediate_size=self.intermediate_size,
            num_hidden_layers=self.n_layer,
            layer_norm_eps=self.layer_norm_eps,
            pre_layer_norm=self.pre_layer_norm,
            attn_dropout_ratio=self.dropout,
            hidden_dropout_ratio=self.dropout,
            use_flash_attention=self.use_flash_attention,
            dtype=self.dtype)

    def num_params(self):
        D = self.d_model
        F = self.intermediate_size or 4 * D
        block = (4 * D + D * 3 * D + 3 * D + D * D + D
                 + D * F + F + F * D + D)
        embed = (self.vocab_size + self.max_seq_len
                 + self.type_vocab_size) * D + 2 * D
        return embed + self.n_layer * block


BERT_TINY = BertConfig(vocab_size=512, max_seq_len=128, n_layer=2,
                       n_head=4, d_model=64)
BERT_BASE = BertConfig()

BERT_PRESETS = {"tiny": BERT_TINY, "bert-base": BERT_BASE}


class Bert:
    """Functional encoder: ``init``, ``apply`` (hidden states), ``loss``
    (masked-LM), ``partition_specs`` — the engine surface."""

    moe_loss_coeff = 0.0

    def __init__(self, config: BertConfig):
        self.config = config
        self.layer = DeepSpeedTransformerLayer(config.layer_config())

    def init(self, rng):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        D = cfg.d_model
        k_embed, k_layers = jax.random.split(rng)
        std = 0.02

        def nrm(key, shape):
            return (jax.random.normal(key, shape, jnp.float32)
                    * std).astype(dt)

        ke = iter(jax.random.split(k_embed, 4))
        params = {
            "wte": nrm(next(ke), (cfg.vocab_size, D)),
            "wpe": nrm(next(ke), (cfg.max_seq_len, D)),
            "wtt": nrm(next(ke), (cfg.type_vocab_size, D)),
            "embed_ln_scale": jnp.ones((D,), jnp.float32),
            "embed_ln_bias": jnp.zeros((D,), jnp.float32),
            # per-layer DeepSpeedTransformerLayer params, stacked on L
            "layers": jax.tree.map(
                lambda *xs: jnp.stack(xs),
                *[self.layer.init(k)
                  for k in jax.random.split(k_layers, cfg.n_layer)]),
        }
        return params

    def partition_specs(self, topology=None):
        """Megatron TP on the layer projections (column: wqkv/wi, row:
        wo/wout); embeddings/norms replicated."""
        layer_specs = {
            "ln1_scale": P(None, None), "ln1_bias": P(None, None),
            "wqkv": P(None, None, "tensor"), "bqkv": P(None, "tensor"),
            "wo": P(None, "tensor", None), "bo": P(None, None),
            "ln2_scale": P(None, None), "ln2_bias": P(None, None),
            "wi": P(None, None, "tensor"), "bi": P(None, "tensor"),
            "wout": P(None, "tensor", None), "bout": P(None, None),
        }
        return {
            "wte": P(), "wpe": P(), "wtt": P(),
            "embed_ln_scale": P(), "embed_ln_bias": P(),
            "layers": layer_specs,
        }

    # ------------------------------------------------------------- forward
    def apply(self, params, input_ids, *, attention_mask=None,
              token_type_ids=None, rng=None, train=False,
              seq_sharded=False):
        """(B, T) -> (B, T, D) final hidden states. attention_mask:
        (B, T) validity (1 = real token)."""
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        B, T = input_ids.shape
        pos = jnp.arange(T)[None, :]
        tt = (jnp.zeros_like(input_ids) if token_type_ids is None
              else token_type_ids)
        x = (params["wte"][input_ids] + params["wpe"][pos]
             + params["wtt"][tt])
        from ..ops.transformer.transformer import _ln
        x = _ln(x.astype(dt), params["embed_ln_scale"],
                params["embed_ln_bias"], cfg.layer_norm_eps)

        mask = attention_mask
        rngs = jax.random.split(
            rng if rng is not None else jax.random.key(0), cfg.n_layer)

        def body(h, xs):
            layer_params, lrng = xs
            return self.layer(layer_params, h, mask=mask,
                              rng=lrng if train else None,
                              train=train), None

        x, _ = jax.lax.scan(body, x, (params["layers"], rngs))
        return x

    def apply_with_aux(self, params, input_ids, **kw):
        return self.apply(params, input_ids, **kw), jnp.zeros(
            (), jnp.float32)

    # ---------------------------------------------------------------- loss
    def loss(self, params, batch, *, rng=None, train=True,
             seq_sharded=False):
        """Masked-LM: mask ``mlm_mask_ratio`` of positions (replaced by
        the [MASK]-like id 0), predict the original token through the
        tied-embedding head. batch: {"input_ids": (B, T)} (+ optional
        "attention_mask", "token_type_ids")."""
        cfg = self.config
        ids = batch["input_ids"]
        B, T = ids.shape
        base = rng if rng is not None else jax.random.key(0)
        mask_rng = jax.random.fold_in(base, 0xB_E_57)
        mlm_mask = jax.random.bernoulli(mask_rng, cfg.mlm_mask_ratio,
                                        (B, T))
        am = batch.get("attention_mask")
        if am is not None:
            # never mask (or count in the loss denominator) padding
            mlm_mask = jnp.logical_and(mlm_mask, am.astype(jnp.bool_))
        inputs = jnp.where(mlm_mask, 0, ids)
        x = self.apply(params, inputs,
                       attention_mask=batch.get("attention_mask"),
                       token_type_ids=batch.get("token_type_ids"),
                       rng=base, train=train)
        logits = jnp.einsum("btd,vd->btv", x, params["wte"],
                            preferred_element_type=jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, ids[..., None],
                                   axis=-1)[..., 0]
        per_tok = logz - gold
        denom = jnp.maximum(jnp.sum(mlm_mask), 1)
        return jnp.sum(jnp.where(mlm_mask, per_tok, 0.0)) / denom
