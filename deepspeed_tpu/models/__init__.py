from .gpt2 import (GPT2, GPT2Config, PRESETS, GPT2_TINY, GPT2_125M,
                   GPT2_350M, GPT2_1_3B)
from .gpt2_moe import GPT2MoE, GPT2MoEConfig
from .gpt2_pipe import GPT2Pipe
from .llama import (Llama, LlamaConfig, LLAMA_PRESETS, LLAMA_TINY,
                    LLAMA2_7B, MISTRAL_7B)
from .mixtral import Mixtral, MixtralConfig, MIXTRAL_TINY, MIXTRAL_8X7B
from .bloom import Bloom, BloomConfig, BLOOM_PRESETS
from .qwen import Qwen, QwenConfig, QWEN_PRESETS
from .phi import Phi, PhiConfig, PHI_PRESETS
from .falcon import Falcon, FalconConfig, FALCON_PRESETS
from .opt import OPT, OPTConfig, OPT_PRESETS
from .gptj import GPTJ, GPTJConfig, GPTJ_PRESETS
from .gpt_neo import GPTNeo, GPTNeoConfig, GPTNEO_PRESETS
from .gpt_neox import GPTNeoX, GPTNeoXConfig, GPTNEOX_PRESETS
from .internlm import InternLM, InternLMConfig, INTERNLM_PRESETS
from .diffusion import (UNet2D, UNet2DConfig, VAEDecoder,
                        VAEDecoderConfig, DSUNet, DSVAE)
