"""GPT-2 with Mixture-of-Experts MLPs (expert parallelism flagship).

Counterpart of the reference's MoE training targets (deepspeed/moe/layer.py
MoE wrapping an expert MLP; test fixture tests/unit/simple_model.py
SimpleMoEModel). Every block's dense MLP is replaced by a top-k routed MoE;
expert weights carry a leading (L, E, ...) layout so the same ``lax.scan``
block iteration works, and the 'expert' mesh axis shards E (EP) while
'tensor' shards the FFN dim (TP) — EP x TP experts like the reference's
module_inject MoE sharding.
"""

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..moe.layer import MoE
from .gpt2 import GPT2, GPT2Config


@dataclass(frozen=True)
class GPT2MoEConfig(GPT2Config):
    num_experts: int = 8
    moe_top_k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: str = None        # None | 'RSample' | 'Jitter'
    moe_loss_coeff: float = 0.01
    moe_drop_tokens: bool = True
    # 'dense' = GShard capacity dispatch (EP-shaped); 'ragged' = dropless
    # grouped GEMM for DP/TP meshes (EP via the shard_map all_to_all)
    moe_backend: str = "dense"
    # ragged backend's expert-product engine: "auto" (the
    # 'moe_grouped_mm' autotune winner cache; cold cache = ragged_dot) |
    # True (Pallas grouped-GEMM kernel) | False (lax.ragged_dot)
    moe_grouped_kernel: object = "auto"


    def num_params(self):
        dense = super().num_params()
        # replace per-layer dense MLP params with E experts + gate
        mlp = 2 * self.d_model * self.d_ff + self.d_ff + self.d_model
        moe = (self.num_experts * mlp + self.d_model * self.num_experts)
        return dense + self.n_layer * (moe - mlp)


class GPT2MoE(GPT2):
    def __init__(self, config: GPT2MoEConfig):
        super().__init__(config)
        self.moe_loss_coeff = config.moe_loss_coeff
        self.moe = MoE(
            hidden_size=config.d_model, ffn_hidden_size=config.d_ff,
            num_experts=config.num_experts, k=config.moe_top_k,
            capacity_factor=config.capacity_factor,
            eval_capacity_factor=config.eval_capacity_factor,
            min_capacity=config.min_capacity,
            noisy_gate_policy=config.noisy_gate_policy,
            drop_tokens=config.moe_drop_tokens,
            dtype=jnp.dtype(config.dtype), backend=config.moe_backend,
            grouped_kernel=config.moe_grouped_kernel)

    def init(self, rng):
        import math
        params = super().init(rng)
        cfg = self.config
        blocks = dict(params["blocks"])
        for k in ("wup", "bup", "wdown", "bdown"):
            del blocks[k]
        moe_params = self.moe.init(
            jax.random.fold_in(rng, 17), stack=cfg.n_layer,
            out_std=0.02 / math.sqrt(2 * cfg.n_layer))
        blocks["moe"] = moe_params
        params["blocks"] = blocks
        return params

    def partition_specs(self, topology=None):
        specs = super().partition_specs(topology)
        blocks = dict(specs["blocks"])
        for k in ("wup", "bup", "wdown", "bdown"):
            del blocks[k]
        blocks["moe"] = self.moe.partition_specs(stacked=True)
        specs["blocks"] = blocks
        return specs

    def _requires_train_rng(self):
        cfg = self.config
        if self.moe.gate is None:  # ragged backend: deterministic routing
            return super()._requires_train_rng()
        return (super()._requires_train_rng()
                or cfg.noisy_gate_policy is not None
                or (cfg.moe_top_k == 2
                    and self.moe.gate.top2_2nd_expert_sampling))

    def _mlp(self, h, layer, rng, *, train, seq_sharded, constrain):
        # an EXPLICIT engine-config 'moe' block setting (non-"auto")
        # overrides the model-config knob; otherwise the model config
        # stands (both default "auto" — the winner cache decides)
        moe_cfg = getattr(self, "_moe_cfg", None)
        override = (moe_cfg.grouped_kernel
                    if moe_cfg is not None
                    and moe_cfg.grouped_kernel != "auto" else None)
        y, aux, _ = self.moe.apply(layer["moe"], h, rng=rng, train=train,
                                   seq_sharded=seq_sharded,
                                   grouped_kernel=override)
        return y, aux
