"""AutoTP — policy-free tensor-parallel sharding by name heuristics.

Counterpart of reference ``module_inject/auto_tp.py:188 AutoTP`` (and
``tp_shard.py``): models without a hand-written policy get Megatron-style
TP from MODULE-NAME heuristics. Here modules are param-tree paths: the
same name tables decide column-parallel (output dim on 'tensor'),
row-parallel (input dim), or replicated, with shape-divisibility guards.
In-repo models override this with exact ``partition_specs``; AutoTP is
the fallback for imported/converted param trees (e.g. HF weight dumps).
"""

import re

import jax
from jax.sharding import PartitionSpec as P

# name fragments -> parallel style (reference auto_tp.py maintains the
# same kind of allow/deny lists)
COLUMN_PATTERNS = ("wq", "wk", "wv", "wqkv", "q_proj", "k_proj", "v_proj",
                   "query", "key", "value", "qkv", "wup", "up_proj",
                   "wgate", "gate_proj", "fc1", "w1", "w3", "intermediate",
                   "dense_h_to_4h")
ROW_PATTERNS = ("wo", "o_proj", "out_proj", "wdown", "down_proj", "fc2",
                "w2", "dense_4h_to_h", "attention.dense", "self_output")
REPLICATED_PATTERNS = ("embed", "wte", "wpe", "norm", "ln", "rms", "bias",
                       "lm_head", "scale")


def _leaf_name(path):
    parts = [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]
    return "/".join(parts), parts[-1] if parts else ""


def _style_for(name):
    # paths are '/'-joined; reference pattern tables use '.' — normalize
    low = name.lower().replace("/", ".")
    for pat in REPLICATED_PATTERNS:
        if pat in low:
            return "replicate"
    for pat in ROW_PATTERNS:
        if re.search(rf"(^|[._/]){re.escape(pat)}($|[._/])", low) \
                or low.endswith(pat):
            return "row"
    for pat in COLUMN_PATTERNS:
        if re.search(rf"(^|[._/]){re.escape(pat)}($|[._/])", low) \
                or low.endswith(pat):
            return "column"
    return "replicate"


def autotp_partition_specs(params, tp_size, axis_name="tensor"):
    """Param pytree -> PartitionSpec pytree. Column-parallel shards the
    LAST dim, row-parallel the SECOND-TO-LAST (matrices may carry leading
    stacked-layer dims); anything indivisible or unmatched replicates."""

    def visit(path, leaf):
        ndim = getattr(leaf, "ndim", 0)
        shape = getattr(leaf, "shape", ())
        full, last = _leaf_name(path)
        if ndim < 2 or tp_size <= 1:
            return P()
        style = _style_for(full)
        spec = [None] * ndim
        if style == "column" and shape[-1] % tp_size == 0:
            spec[-1] = axis_name
        elif style == "row" and shape[-2] % tp_size == 0:
            spec[-2] = axis_name
        return P(*spec)

    return jax.tree.map_with_path(visit, params)


class AutoTP:
    """reference AutoTP class surface: ``AutoTP(model_or_params).
    partition_specs(topology)`` so an arbitrary param tree can drive the
    training engine / inference engines like a zoo model."""

    def __init__(self, params):
        self.params = params

    def partition_specs(self, topology=None):
        tp = (topology.get_model_parallel_world_size()
              if topology is not None else 1)
        return autotp_partition_specs(self.params, tp)

    def report(self, topology=None):
        """{path: style} summary (debugging, reference prints the same)."""
        specs = self.partition_specs(topology)
        out = {}
        for path, spec in jax.tree.leaves_with_path(
                specs, is_leaf=lambda x: isinstance(x, P)):
            full, _ = _leaf_name(path)
            if any(e is not None for e in spec):
                idx = [i for i, e in enumerate(spec) if e is not None][0]
                out[full] = ("column" if idx == len(spec) - 1 else "row")
            else:
                out[full] = "replicate"
        return out
