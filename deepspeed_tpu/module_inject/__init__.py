from .auto_tp import AutoTP, autotp_partition_specs
