from .config import DeepSpeedInferenceConfig
from .engine import InferenceEngine
from .v2 import (InferenceEngineV2, RaggedInferenceEngineConfig,
                 BlockedAllocator, DSStateManager)
