from .config import DeepSpeedInferenceConfig
from .engine import InferenceEngine
from .v2 import (InferenceEngineV2, RaggedInferenceEngineConfig,
                 BlockedAllocator, DSStateManager)


def build_hf_engine(path, config=None, dtype="bfloat16", v2=True,
                    **kwargs):
    """Serve a HuggingFace checkpoint directory.

    Counterpart of the reference's engine factory
    (/root/reference/deepspeed/inference/v2/engine_factory.py:66
    ``build_hf_engine``): reads config.json + safetensors via
    checkpoint.hf.load_pretrained, then builds the v2 continuous-batching
    engine (or the v1 engine with ``v2=False``) over the real weights.
    """
    from ..checkpoint.hf import load_pretrained
    model, params = load_pretrained(path, dtype=dtype)
    cls = InferenceEngineV2 if v2 else InferenceEngine
    return cls(model, config=config, params=params, **kwargs)
