"""Shared inference-engine helpers."""

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def shard_params(model, mesh, dtype, params=None, seed=0, topology=None,
                 quantize=False):
    """Build NamedShardings from the model's ``partition_specs`` and place
    (or initialize) params under them, cast to ``dtype``.

    ``quantize=True``: ZeRO-Inference weight-only int8 — block weights
    are quantized HOST-SIDE (HBM never holds the bf16 copy) and placed
    as Int8Weight pytree nodes; serving paths dequantize one layer at a
    time (ops/int8_weights.py; reference inference/quantization/).

    Returns (params, param_shardings)."""
    specs = model.partition_specs(topology)
    if quantize:
        from ..ops.int8_weights import (quantize_tree, quantized_shardings)
        if params is None:
            # init on HOST: the whole point is a model whose bf16 weights
            # exceed device memory — the fp32 init tree must never touch
            # the accelerator
            cpus = jax.local_devices(backend="cpu")
            with jax.default_device(cpus[0]):
                params = model.init(jax.random.key(seed))
        # consume-as-you-quantize: fp32 source leaves free one at a
        # time, so peak host memory is ~the source tree + one leaf
        # (not source + a full quantized copy)
        if not isinstance(params, dict):
            params = dict(params)
        qtree = quantize_tree(params, consume=True)
        del params
        # cast the un-quantized leaves (embeds/norms/biases) to dtype
        from ..ops.int8_weights import Int8Weight

        def cast_leaf(x):
            if isinstance(x, Int8Weight):
                return x
            a = np.asarray(x)
            return a.astype(np.dtype(dtype)) if np.issubdtype(
                a.dtype, np.floating) else a
        qtree = jax.tree.map(cast_leaf, qtree,
                             is_leaf=lambda x: isinstance(x, Int8Weight))
        shardings = quantized_shardings(specs, qtree, mesh)
        with jax.set_mesh(mesh):
            params = jax.tree.map(jax.device_put, qtree, shardings)
        return params, shardings
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    with jax.set_mesh(mesh):
        if params is None:
            params = jax.jit(
                lambda r: jax.tree.map(lambda x: x.astype(dtype),
                                       model.init(r)),
                out_shardings=shardings)(jax.random.key(seed))
        else:
            params = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(dtype), p),
                out_shardings=shardings)(params)
    return params, shardings
