"""Shared inference-engine helpers."""

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def shard_params(model, mesh, dtype, params=None, seed=0, topology=None,
                 quantize=False):
    """Build NamedShardings from the model's ``partition_specs`` and place
    (or initialize) params under them, cast to ``dtype``.

    ``quantize``: ZeRO-Inference weight-only quantization — ``True`` /
    ``"int8"`` for W8, ``"int4"`` for W4 (two codes per byte, packed
    along the contracted dim). Block weights are quantized HOST-SIDE
    (HBM never holds the bf16 copy) and placed as Int8Weight /
    Int4Weight pytree nodes; serving paths dequantize one layer at a
    time, or keep the FFN weights quantized for the fused-dequant
    kernels when the engine sets ``_weight_quant_fused``
    (ops/int8_weights.py; reference inference/quantization/).

    Returns (params, param_shardings)."""
    specs = model.partition_specs(topology)
    if quantize not in (False, None, True, "int8", "int4"):
        raise ValueError(
            f"quantize must be False|True|'int8'|'int4', got "
            f"{quantize!r}")
    if quantize:
        bits = 4 if quantize == "int4" else 8
        from ..ops.int8_weights import (quantize_tree, quantized_shardings)
        if params is None:
            # init on HOST: the whole point is a model whose bf16 weights
            # exceed device memory — the fp32 init tree must never touch
            # the accelerator
            cpus = jax.local_devices(backend="cpu")
            with jax.default_device(cpus[0]):
                params = model.init(jax.random.key(seed))
        # consume-as-you-quantize: fp32 source leaves free one at a
        # time, so peak host memory is ~the source tree + one leaf
        # (not source + a full quantized copy)
        if not isinstance(params, dict):
            params = dict(params)
        qtree = quantize_tree(params, consume=True, bits=bits)
        del params
        # cast the un-quantized leaves (embeds/norms/biases) to dtype;
        # router weights stay fp32 (the same exclusion quantize_tree
        # honors — downcasting them to bf16 here would undo the
        # precision the exclusion exists to keep)
        from ..ops.int8_weights import Int8Weight, cast_unquantized
        qtree = cast_unquantized(qtree, dtype)
        shardings = quantized_shardings(specs, qtree, mesh)
        with jax.set_mesh(mesh):
            params = jax.tree.map(jax.device_put, qtree, shardings)
        return params, shardings
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    with jax.set_mesh(mesh):
        if params is None:
            params = jax.jit(
                lambda r: jax.tree.map(lambda x: x.astype(dtype),
                                       model.init(r)),
                out_shardings=shardings)(jax.random.key(seed))
        else:
            # leafwise device_put: host (numpy) leaves transfer shard-by-
            # shard straight to their placement — the full tree never
            # materializes on one device (TP serving of > 1-chip models)
            import jax.numpy as jnp

            def place(x, s):
                # jnp.issubdtype, not np.: host bf16 (ml_dtypes) is not
                # a np.floating subdtype
                if not isinstance(x, jax.Array):
                    a = np.asarray(x)
                    if jnp.issubdtype(a.dtype, jnp.floating):
                        a = a.astype(np.dtype(dtype), copy=False)
                    return jax.device_put(a, s)
                return jax.device_put(x.astype(dtype) if jnp.issubdtype(
                    x.dtype, jnp.floating) else x, s)
            params = jax.tree.map(place, params, shardings)
    return params, shardings
