"""Shared inference-engine helpers."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def shard_params(model, mesh, dtype, params=None, seed=0, topology=None):
    """Build NamedShardings from the model's ``partition_specs`` and place
    (or initialize) params under them, cast to ``dtype``.

    Returns (params, param_shardings)."""
    specs = model.partition_specs(topology)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    with jax.set_mesh(mesh):
        if params is None:
            params = jax.jit(
                lambda r: jax.tree.map(lambda x: x.astype(dtype),
                                       model.init(r)),
                out_shardings=shardings)(jax.random.key(seed))
        else:
            params = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(dtype), p),
                out_shardings=shardings)(params)
    return params, shardings
