"""Fault-tolerant serving front-end: the replica router.

Owns THE request queue and dispatches to N :class:`Replica` engines.
Four robustness layers, each reusing an existing repo discipline:

1. **Admission control + load shedding.** The queue is bounded
   (``router_queue_depth``, "auto" = 4x the aggregate decode slots of
   the live replicas); a full queue rejects at ``put()`` with a typed
   :class:`Overloaded`. Overload detection — a sustained queue-depth
   watermark breach or a sustained p99 TTFT/TPOT SLO breach read from
   each replica's ``ServingTelemetry`` — sheds queued requests by
   class (``shed_policy``, "auto" = lowest class first, newest first
   within the class) down to the low watermark instead of letting
   latency collapse for everyone. Sheds are typed, counted, and
   surfaced through ``get()`` — never silent.
2. **Deadline enforcement.** Per-request TTFT/total deadlines are
   checked at the dispatch boundaries (before dispatch and after every
   step). Expired in-flight requests are withdrawn through the
   engine's ``cancel()`` -> ``DSStateManager.flush()`` path (unrefs
   without tree insert, pool accounting closes) and surfaced as typed
   :class:`DeadlineExceeded` — counted, never silently served late.
3. **Failover.** Replica health is a live/draining/dead state machine
   (replica.py); a dead replica's in-flight requests re-enqueue at the
   FRONT of the queue (original order preserved, partial tokens
   discarded) and replay on a survivor. Greedy (temperature 0) decode
   is rng-independent, so replayed outputs are byte-identical to an
   uninterrupted run; prefix-affinity dispatch (route to the replica
   whose radix tree holds the longest prefix of the prompt) makes the
   re-prefill cheap when the survivor has seen the prefix.
4. **Drained scale-down.** ``drain(replica)`` mirrors the elastic
   agent's SIGTERM contract: stop admitting, finish in-flight (no
   replay), then remove from the rotation.

Counters flow through the linted tag schema as ``Serve/Router/*``
(stepped by completed router requests); with the router off, engine
telemetry snapshots are byte-identical to pre-router serving — the
router adds a layer, it never changes the engine.
"""

import time
from collections import deque
from dataclasses import dataclass

import numpy as np

from ...utils import fault_injection
from ...utils.logging import log_dist
from ...monitor.telemetry import percentile
from . import kv_transfer
from .replica import Replica, ReplicaDead


class Overloaded(RuntimeError):
    """Typed admission/shedding rejection: the router refused (or
    withdrew) the request to protect the admitted classes' SLOs. The
    client owns the retry/backoff decision."""

    def __init__(self, msg, klass=0, queue_depth=0):
        super().__init__(msg)
        self.klass = klass
        self.queue_depth = queue_depth


class DeadlineExceeded(RuntimeError):
    """Typed deadline rejection: the request's TTFT or total deadline
    passed before it could be served; it was flushed (queued: dropped;
    in-flight: engine ``cancel()`` unref path), never served late."""

    def __init__(self, msg, klass=0, which="total"):
        super().__init__(msg)
        self.klass = klass
        self.which = which                 # "ttft" | "total"


@dataclass
class RouterConfig:
    """Router knobs. The three "auto" knobs carry planner KNOB_TABLE
    rows (router.*) and are probed by the construction lint in
    tests/unit/test_planner_lint.py — same contract as the serving
    engine's auto knobs: accept "auto", validate junk loudly."""

    # bounded queue depth: "auto" = 4x aggregate decode slots across
    # live replicas (Router.resolved_queue_depth), int forces
    router_queue_depth: object = "auto"
    # which queued requests overload shedding drops: "auto" resolves to
    # lowest-class (shed the numerically highest class, newest first
    # within it — least sunk wait); "newest-first" ignores class
    shed_policy: str = "auto"
    # route to the replica whose radix tree holds the longest prompt
    # prefix: "auto" = on iff any replica runs a prefix cache
    # (Router._affinity_on); True/False force
    prefix_affinity: object = "auto"
    # disaggregated prefill/decode serving: "auto" = on iff both a
    # prefill-role AND a decode-role replica are live
    # (Router._disagg_on — the fleet degrades to colocated behavior
    # when either side is gone); True forces (construction raises
    # unless both roles are present); False keeps every replica
    # colocated whatever its role says
    disaggregate: object = "auto"
    # overload detection: sustained p99 SLO breach (0 = disabled; the
    # queue-depth watermark below is always armed) over breach_rounds
    # consecutive router steps
    slo_ttft_ms: float = 0.0
    slo_tpot_ms: float = 0.0
    breach_rounds: int = 3
    # queue watermarks as pct of the resolved depth: shedding starts
    # when depth sustains >= high and stops once depth <= low
    shed_high_pct: int = 75
    shed_low_pct: int = 50
    # consecutive serve_step failures before a replica's heartbeat is
    # declared broken (replica.py health machine)
    max_step_failures: int = 3
    # Serve/Router/* fan-out cadence (completed router requests)
    emit_interval: int = 8

    def __post_init__(self):
        if self.router_queue_depth != "auto" and (
                not isinstance(self.router_queue_depth, int)
                or isinstance(self.router_queue_depth, bool)
                or self.router_queue_depth < 1):
            raise ValueError(
                f"router_queue_depth must be 'auto' or an int >= 1, got "
                f"{self.router_queue_depth!r}")
        if self.shed_policy not in ("auto", "lowest-class",
                                    "newest-first"):
            raise ValueError(
                f"shed_policy must be 'auto'|'lowest-class'|"
                f"'newest-first', got {self.shed_policy!r}")
        if self.prefix_affinity not in (True, False, "auto"):
            raise ValueError(
                f"prefix_affinity must be true|false|'auto', got "
                f"{self.prefix_affinity!r}")
        if self.disaggregate not in (True, False, "auto"):
            raise ValueError(
                f"disaggregate must be true|false|'auto', got "
                f"{self.disaggregate!r}")
        for name in ("slo_ttft_ms", "slo_tpot_ms"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) \
                    or isinstance(v, bool) or v < 0:
                raise ValueError(f"{name} must be a number >= 0, "
                                 f"got {v!r}")
        for name in ("breach_rounds", "max_step_failures",
                     "emit_interval"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(f"{name} must be an int >= 1, "
                                 f"got {v!r}")
        for name in ("shed_high_pct", "shed_low_pct"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) \
                    or not 0 <= v <= 100:
                raise ValueError(f"{name} must be an int in [0, 100], "
                                 f"got {v!r}")
        if self.shed_low_pct > self.shed_high_pct:
            raise ValueError(
                f"shed_low_pct ({self.shed_low_pct}) must not exceed "
                f"shed_high_pct ({self.shed_high_pct})")


# request lifecycle: queued -> inflight -> done, with the typed exits
# queued/inflight -> shed | expired (error holds the typed exception)
@dataclass
class RouterRequest:
    uid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: int
    klass: int
    ttft_deadline_ms: object            # float ms or None
    deadline_ms: object                 # float ms or None
    t_submit: float
    state: str = "queued"
    replica: str = None                 # serving replica name
    tokens: np.ndarray = None           # final output (done)
    error: Exception = None             # typed rejection (shed/expired)
    replays: int = 0                    # failover replays survived
    t_first: float = None               # first token of current attempt
    t_last: float = None
    n_tokens: int = 0
    ttft_recorded: bool = False         # one TTFT sample per request,
                                        # even across replays


def _new_class_stats():
    return {"admitted": 0, "completed": 0, "shed": 0, "expired": 0,
            "replayed": 0, "ttft_ms": [], "tpot_ms": []}


class Router:
    """``put()`` requests, ``step()`` the fleet, ``get(uid)`` results
    (typed exceptions for shed/expired). See the module docstring for
    the four robustness layers."""

    def __init__(self, replicas, config=None, monitor=None,
                 kv_transport=None, **kwargs):
        if isinstance(config, dict):
            config = RouterConfig(**{**config, **kwargs})
        elif config is None:
            config = RouterConfig(**kwargs)
        self.config = config
        self.replicas = []
        for i, rep in enumerate(replicas):
            if not isinstance(rep, Replica):
                rep = Replica(f"r{i}", rep,
                              max_step_failures=config.max_step_failures)
            self.replicas.append(rep)
        if not self.replicas:
            raise ValueError("Router needs at least one replica")
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        roles = {r.role for r in self.replicas}
        if config.disaggregate is True \
                and not {"prefill", "decode"} <= roles:
            raise ValueError(
                f"disaggregate=True needs at least one prefill-role "
                f"and one decode-role replica; fleet roles: "
                f"{sorted(roles)}")
        # handoff byte transport: in-process queue by default (the
        # tier-1-testable fallback); multi-host fleets pass
        # kv_transfer.DcnRingTransport
        self._kv_transport = kv_transport if kv_transport is not None \
            else kv_transfer.InProcQueueTransport()
        # per-round cache of _disagg_on(), re-resolved at the top of
        # every step so role changes (deaths, drains) take effect
        self._disagg = False
        self.monitor = monitor
        self._queue = deque()             # RouterRequest, FIFO
        self._reqs = {}                   # uid -> RouterRequest
        self._uid_next = 0
        self._rr = 0                      # round-robin tie-break cursor
        self._breach_rounds = 0
        self._emitted_at = 0
        self._now = time.monotonic        # tests override for fake time
        self.counters = {"admitted": 0, "completed": 0, "shed": 0,
                         "expired": 0, "replayed": 0, "failovers": 0,
                         "dispatch_retries": 0, "handoffs": 0,
                         "kv_stream_bytes": 0, "kv_stream_ms": 0.0,
                         "kv_stream_retries": 0}
        self._class_stats = {}
        log_dist(f"router ready: {len(self.replicas)} replicas, "
                 f"queue_depth={config.router_queue_depth}", ranks=[0])

    # ------------------------------------------------------------ resolve
    def resolved_queue_depth(self):
        """"auto" = 4x the aggregate decode slots of the non-dead
        replicas (capacity-proportional back-pressure: losing a replica
        shrinks what the router will buffer)."""
        d = self.config.router_queue_depth
        if d != "auto":
            return d
        slots = sum(r.slots for r in self.replicas if not r.dead)
        return max(1, 4 * slots)

    def _affinity_on(self):
        aff = self.config.prefix_affinity
        if aff != "auto":
            return aff
        return any(r.engine.prefix_cache is not None
                   for r in self.replicas if not r.dead)

    def _resolved_shed_policy(self):
        pol = self.config.shed_policy
        return "lowest-class" if pol == "auto" else pol

    def _disagg_on(self):
        """Disaggregated dispatch is active iff configured on AND both
        phase roles are live — a fleet that loses its last decode (or
        prefill) replica degrades to colocated behavior (roles become
        preferences, not partitions) instead of deadlocking parked
        sequences. Re-resolved every router round."""
        if self.config.disaggregate is False:
            return False
        alive = [r for r in self.replicas if not r.dead]
        return any(r.role == "prefill" for r in alive) \
            and any(r.role == "decode" for r in alive)

    def _cstat(self, klass):
        if klass not in self._class_stats:
            self._class_stats[klass] = _new_class_stats()
        return self._class_stats[klass]

    # ------------------------------------------------------------ requests
    def put(self, prompt, max_new_tokens=32, eos_token_id=-1, klass=0,
            ttft_deadline_ms=None, deadline_ms=None):
        """Admit one request (class 0 = highest priority; higher ints
        are shed first). Raises :class:`Overloaded` when the bounded
        queue is full — the admission-control boundary. Returns the
        router uid."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        alive = [r for r in self.replicas if not r.dead]
        if not alive:
            raise RuntimeError("no live replicas remain")
        if not any(r.fits(len(prompt), max_new_tokens) for r in alive):
            raise ValueError(
                f"prompt+max_new={len(prompt) + max_new_tokens} can "
                f"never fit any replica (context or pool capacity)")
        depth = len(self._queue)
        if depth >= self.resolved_queue_depth():
            self.counters["shed"] += 1
            self._cstat(klass)["shed"] += 1
            raise Overloaded(
                f"router queue full ({depth} >= "
                f"{self.resolved_queue_depth()}); class {klass} request "
                f"rejected", klass=klass, queue_depth=depth)
        uid = self._uid_next
        self._uid_next += 1
        req = RouterRequest(
            uid=uid, prompt=prompt, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id, klass=int(klass),
            ttft_deadline_ms=ttft_deadline_ms, deadline_ms=deadline_ms,
            t_submit=self._now())
        self._reqs[uid] = req
        self._queue.append(req)
        self.counters["admitted"] += 1
        self._cstat(req.klass)["admitted"] += 1
        return uid

    def is_done(self, uid):
        return self._reqs[uid].state in ("done", "shed", "expired")

    def get(self, uid, flush=True):
        """Tokens for a finished request; raises the stored typed
        exception (:class:`Overloaded` / :class:`DeadlineExceeded`) for
        shed/expired requests — a rejected request is never returned as
        a success. In-flight/queued requests return an empty array."""
        req = self._reqs[uid]
        if req.state == "done":
            if flush:
                del self._reqs[uid]
            return req.tokens
        if req.state in ("shed", "expired"):
            err = req.error
            if flush:
                del self._reqs[uid]
            raise err
        return np.zeros((0,), np.int32)

    @property
    def has_work(self):
        return bool(self._queue) or any(r.inflight for r in self.replicas)

    def drain(self, replica):
        """Scale-down: stop admitting to ``replica`` (name or handle);
        its in-flight requests finish normally (no replay), then the
        router removes it from the rotation."""
        rep = replica if isinstance(replica, Replica) else \
            next((r for r in self.replicas if r.name == replica), None)
        if rep is None or rep not in self.replicas:
            raise KeyError(f"unknown replica {replica!r}")
        rep.drain()
        self._finish_drains()             # empty replica: remove now

    # ---------------------------------------------------------------- step
    def step(self):
        """One router round: expire deadlines, detect overload + shed,
        dispatch, step every busy replica (failing dead ones over),
        collect finished requests, complete drains. Returns the
        (uid, token) pairs produced this round."""
        now = self._now()
        self._disagg = self._disagg_on()
        for rep in self.replicas:
            if not rep.dead:
                rep.set_disaggregated(self._disagg)
        self._expire_queued(now)
        self._maybe_shed()
        self._dispatch(now)
        out = []
        for rep in list(self.replicas):
            if rep.dead or not rep.has_work:
                continue
            try:
                pairs = rep.step()
            except ReplicaDead:
                self._failover(rep)
                continue
            now = self._now()
            for uid, tok in pairs:
                req = self._reqs.get(uid)
                if req is None or req.state != "inflight":
                    continue
                if req.t_first is None:
                    req.t_first = now
                    if not req.ttft_recorded:
                        req.ttft_recorded = True
                        self._cstat(req.klass)["ttft_ms"].append(
                            (now - req.t_submit) * 1e3)
                req.t_last = now
                req.n_tokens += 1
                out.append((uid, tok))
            self._collect_finished(rep)
        self._do_handoffs()
        self._expire_inflight(self._now())
        self._finish_drains()
        if not any(not r.dead for r in self.replicas) and self.has_work:
            raise RuntimeError(
                f"no live replicas remain; "
                f"{len(self._queue)} queued + "
                f"{sum(len(r.inflight) for r in self.replicas)} "
                f"in-flight requests stranded")
        self._maybe_emit()
        return out

    # ------------------------------------------------------------ deadlines
    def _deadline_exceeded(self, req, now):
        """Returns "ttft"/"total"/None — which deadline has passed."""
        el_ms = (now - req.t_submit) * 1e3
        if req.deadline_ms is not None and el_ms > req.deadline_ms:
            return "total"
        if req.t_first is None and req.ttft_deadline_ms is not None \
                and el_ms > req.ttft_deadline_ms:
            return "ttft"
        return None

    def _expire(self, req, which, where):
        req.state = "expired"
        req.replica = None
        req.error = DeadlineExceeded(
            f"request {req.uid} (class {req.klass}) {which} deadline "
            f"exceeded {where}", klass=req.klass, which=which)
        self.counters["expired"] += 1
        self._cstat(req.klass)["expired"] += 1

    def _expire_queued(self, now):
        if not self._queue:
            return
        keep = deque()
        for req in self._queue:
            which = self._deadline_exceeded(req, now)
            if which:
                self._expire(req, which, "before dispatch")
            else:
                keep.append(req)
        self._queue = keep

    def _expire_inflight(self, now):
        for rep in self.replicas:
            if rep.dead:
                continue
            for uid in list(rep.inflight):
                req = self._reqs[uid]
                which = self._deadline_exceeded(req, now)
                if which:
                    # the flush()/unref path: blocks return to the pool
                    # with NO tree insert, accounting closes
                    rep.cancel(uid)
                    self._expire(req, which, f"in flight on {rep.name}")

    # ------------------------------------------------------------- overload
    def _overloaded(self):
        """Sustained queue-watermark or SLO breach => shed this round.
        The ``router_overload`` fault point injects a forced round
        (advisory: counted, never propagates, never touches a
        replica)."""
        forced = False
        try:
            fault_injection.fire("router_overload")
        except fault_injection.FaultError:
            forced = True
        depth = len(self._queue)
        cap = self.resolved_queue_depth()
        breach = depth >= max(1, cap * self.config.shed_high_pct // 100)
        cfg = self.config
        if not breach and (cfg.slo_ttft_ms or cfg.slo_tpot_ms):
            for rep in self.replicas:
                if rep.dead:
                    continue
                snap = rep.engine.telemetry_snapshot()
                if snap is None:
                    continue
                ttft, tpot = snap.get("ttft_ms_p99"), \
                    snap.get("tpot_ms_p99")
                if (cfg.slo_ttft_ms and ttft is not None
                        and ttft > cfg.slo_ttft_ms) or \
                        (cfg.slo_tpot_ms and tpot is not None
                         and tpot > cfg.slo_tpot_ms):
                    breach = True
                    break
        self._breach_rounds = self._breach_rounds + 1 if breach else 0
        return forced or self._breach_rounds >= cfg.breach_rounds

    def _shed_victim(self):
        """Pick one queued request per the resolved shed policy."""
        if not self._queue:
            return None
        if self._resolved_shed_policy() == "newest-first":
            return self._queue[-1]
        worst = max(req.klass for req in self._queue)
        for req in reversed(self._queue):    # newest within the class
            if req.klass == worst:
                return req
        return None

    def _maybe_shed(self):
        if not self._overloaded() or not self._queue:
            return
        target = self.resolved_queue_depth() \
            * self.config.shed_low_pct // 100
        while len(self._queue) > target:
            victim = self._shed_victim()
            if victim is None:
                break
            self._queue.remove(victim)
            victim.state = "shed"
            victim.error = Overloaded(
                f"request {victim.uid} (class {victim.klass}) shed "
                f"under overload", klass=victim.klass,
                queue_depth=len(self._queue))
            self.counters["shed"] += 1
            self._cstat(victim.klass)["shed"] += 1

    # ------------------------------------------------------------- dispatch
    def _pick_replica(self, req):
        cands = [r for r in self.replicas
                 if (not self._disagg or r.role != "decode")
                 and r.can_accept(len(req.prompt), req.max_new_tokens,
                                  prompt=req.prompt)]
        if not cands:
            return None
        if self._affinity_on():
            scores = {r.name: r.prefix_score(req.prompt) for r in cands}
            best = max(scores.values())
            if best > 0:
                cands = [r for r in cands if scores[r.name] == best]
        n = len(self.replicas)
        idx = {r.name: i for i, r in enumerate(self.replicas)}
        cands.sort(key=lambda r: (len(r.inflight),
                                  (idx[r.name] - self._rr) % n))
        self._rr += 1
        return cands[0]

    def _dispatch(self, now):
        """Head-of-line dispatch: no skip-ahead (fairness within class
        order is FIFO; determinism for the chaos tests). Each replica
        accepts at most one request per round — can_accept's pool math
        only covers admitted sequences, not its pending queue."""
        while self._queue:
            req = self._queue[0]
            which = self._deadline_exceeded(req, now)
            if which:                      # the dispatch-boundary check
                self._queue.popleft()
                self._expire(req, which, "at dispatch")
                continue
            rep = self._pick_replica(req)
            if rep is None:
                break
            self._queue.popleft()
            try:
                rep.submit(req.uid, req.prompt, req.max_new_tokens,
                           req.eos_token_id, klass=req.klass)
            except fault_injection.FaultError:
                # retryable dispatch fault: nothing partial happened —
                # back to the front, re-route next round
                self.counters["dispatch_retries"] += 1
                self._queue.appendleft(req)
                break
            req.state = "inflight"
            req.replica = rep.name

    # ------------------------------------------------------------- handoffs
    def _pick_decode(self, req):
        """Least-loaded live decode-role replica with slot + pool
        capacity for the handed-off sequence (round-robin tie-break,
        like _pick_replica). None = back-pressure: the sequence stays
        parked on its prefill replica and retries next round."""
        cands = [r for r in self.replicas
                 if r.role == "decode"
                 and r.can_accept(len(req.prompt), req.max_new_tokens)]
        if not cands:
            return None
        n = len(self.replicas)
        idx = {r.name: i for i, r in enumerate(self.replicas)}
        cands.sort(key=lambda r: (len(r.inflight),
                                  (idx[r.name] - self._rr) % n))
        self._rr += 1
        return cands[0]

    def _do_handoffs(self):
        """Stream prefill-complete sequences to decode replicas. The
        ordering makes every failure safe: the prefill replica keeps
        full ownership until the decode side confirms the import, so a
        ``kv_stream``/``kv_import`` fault retries next round from
        unchanged state, and a decode-replica death mid-transfer falls
        back to a front-of-queue replay (:meth:`_handoff_death`)."""
        if not self._disagg:
            return
        for rep in list(self.replicas):
            if rep.dead or rep.role != "prefill":
                continue
            for uid in rep.handoff_ready():
                req = self._reqs.get(uid)
                if req is None or req.state != "inflight":
                    continue
                dst = self._pick_decode(req)
                if dst is None:
                    continue          # back-pressure: stays parked
                t0 = self._now()
                try:
                    payload = rep.export_handoff(uid)
                    self._kv_transport.send(payload)
                    wire = self._kv_transport.recv()
                except fault_injection.FaultError:
                    # retryable stream fault: nothing moved
                    self.counters["kv_stream_retries"] += 1
                    continue
                try:
                    dst.import_handoff(wire)
                except fault_injection.FaultError:
                    # retryable import fault: fired before any
                    # decode-side mutation, nothing moved
                    self.counters["kv_stream_retries"] += 1
                    continue
                except ReplicaDead:
                    self._handoff_death(rep, dst, req)
                    return            # roles changed mid-round: stop
                rep.finish_handoff(uid)
                dst.inflight.append(uid)
                req.replica = dst.name
                self.counters["handoffs"] += 1
                self.counters["kv_stream_bytes"] += len(payload)
                self.counters["kv_stream_ms"] += \
                    (self._now() - t0) * 1e3

    def _handoff_death(self, src, dst, req):
        """``dst`` died importing ``req``'s KV mid-transfer. The import
        fires before any decode-side allocation, so ``dst`` holds
        nothing of ``req``; ``src`` still owns the sequence — cancel it
        there (the flush/unref path, pool accounting closes) and
        re-enqueue at the FRONT. ``dst``'s OTHER in-flight requests
        take the normal failover path. With the decode side gone the
        fleet degrades to colocated and the replay re-prefills —
        byte-identical by greedy construction."""
        src.cancel(req.uid)
        req.state = "queued"
        req.replica = None
        req.tokens = None
        req.t_first = None
        req.t_last = None
        req.n_tokens = 0
        req.replays += 1
        self.counters["replayed"] += 1
        self._cstat(req.klass)["replayed"] += 1
        self._queue.appendleft(req)
        self._failover(dst)
        self._disagg = self._disagg_on()
        for rep in self.replicas:
            if not rep.dead:
                rep.set_disaggregated(self._disagg)
        log_dist(f"router: decode replica {dst.name} died mid-transfer;"
                 f" request {req.uid} replayed from the front",
                 ranks=[0])

    # ------------------------------------------------------------- failover
    def _failover(self, rep):
        """``rep`` died: re-enqueue its in-flight requests at the FRONT
        (original dispatch order preserved) for replay on a survivor.
        Partial tokens are discarded — greedy decode is rng-independent,
        so the replay regenerates them byte-identically."""
        self.counters["failovers"] += 1
        moved = [self._reqs[uid] for uid in rep.inflight]
        rep.inflight = []
        for req in reversed(moved):
            req.state = "queued"
            req.replica = None
            req.tokens = None
            req.t_first = None
            req.t_last = None
            req.n_tokens = 0
            req.replays += 1
            self.counters["replayed"] += 1
            self._cstat(req.klass)["replayed"] += 1
            self._queue.appendleft(req)
        log_dist(f"router: replica {rep.name} died, replaying "
                 f"{len(moved)} in-flight requests", ranks=[0])

    def _collect_finished(self, rep):
        for uid in list(rep.inflight):
            if not rep.engine.is_done(uid):
                continue
            rep.inflight.remove(uid)
            req = self._reqs[uid]
            req.tokens = rep.engine.get(uid)
            req.state = "done"
            self.counters["completed"] += 1
            st = self._cstat(req.klass)
            st["completed"] += 1
            if req.n_tokens >= 2 and req.t_last > req.t_first:
                st["tpot_ms"].append(
                    (req.t_last - req.t_first) * 1e3
                    / (req.n_tokens - 1))

    def _finish_drains(self):
        for rep in self.replicas:
            if rep.draining and not rep.inflight \
                    and not rep.engine.has_work:
                rep.mark_dead("drained", drained=True)
                log_dist(f"router: replica {rep.name} drained and "
                         f"removed", ranks=[0])

    # ------------------------------------------------------------ telemetry
    def snapshot(self):
        """Counters + per-class latency percentiles for bench rows."""
        classes = {}
        for klass, st in sorted(self._class_stats.items()):
            classes[klass] = {
                "admitted": st["admitted"],
                "completed": st["completed"],
                "shed": st["shed"],
                "expired": st["expired"],
                "replayed": st["replayed"],
                "ttft_ms_p50": percentile(st["ttft_ms"], 50),
                "ttft_ms_p99": percentile(st["ttft_ms"], 99),
                "tpot_ms_p50": percentile(st["tpot_ms"], 50),
                "tpot_ms_p99": percentile(st["tpot_ms"], 99),
            }
        out = {
            **self.counters,
            "queue_depth": len(self._queue),
            "draining": sum(r.draining for r in self.replicas),
            "replicas": {r.name: r.state for r in self.replicas},
            "classes": classes,
        }
        # per-replica speculative acceptance EMA — only present when at
        # least one replica engine actually ran a verify round, so
        # spec-off fleets keep the pre-speculation snapshot shape
        spec = {r.name: round(r.spec_acceptance, 3)
                for r in self.replicas
                if getattr(r, "spec_acceptance", None) is not None}
        if spec:
            out["spec_acceptance_ema"] = spec
        # per-role fleet summary — only present when the fleet actually
        # declares phase roles, so all-colocated fleets keep the
        # pre-disaggregation snapshot shape byte-identical
        if any(r.role != "colocated" for r in self.replicas):
            out["roles"] = {r.name: r.role for r in self.replicas}
            out["prefill_inflight"] = sum(
                len(r.inflight) for r in self.replicas
                if r.role == "prefill")
            out["decode_inflight"] = sum(
                len(r.inflight) for r in self.replicas
                if r.role == "decode")
        return out

    def _maybe_emit(self):
        if self.monitor is None \
                or not getattr(self.monitor, "enabled", False):
            return
        done = self.counters["completed"]
        if done - self._emitted_at < self.config.emit_interval:
            return
        self._emitted_at = done
        step = done
        events = [
            ("Serve/Router/shed", self.counters["shed"], step),
            ("Serve/Router/expired", self.counters["expired"], step),
            ("Serve/Router/replayed", self.counters["replayed"], step),
            ("Serve/Router/failovers", self.counters["failovers"], step),
            ("Serve/Router/queue_depth", len(self._queue), step),
            ("Serve/Router/draining",
             sum(r.draining for r in self.replicas), step),
        ]
        if any(r.role != "colocated" for r in self.replicas):
            events += [
                ("Serve/Router/handoffs",
                 self.counters["handoffs"], step),
                ("Serve/Router/kv_stream_bytes",
                 self.counters["kv_stream_bytes"], step),
                ("Serve/Router/kv_stream_ms",
                 round(self.counters["kv_stream_ms"], 3), step),
                ("Serve/Router/prefill_inflight",
                 sum(len(r.inflight) for r in self.replicas
                     if r.role == "prefill"), step),
                ("Serve/Router/decode_inflight",
                 sum(len(r.inflight) for r in self.replicas
                     if r.role == "decode"), step),
            ]
        self.monitor.write_events(events)
