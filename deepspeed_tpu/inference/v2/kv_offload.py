"""Paged-KV host offload: a device-resident block cache over host RAM.

The other half of ZeRO-Inference (reference README.md:30 — "weight
quantization and KV-cache offload"; the async-tier pattern is the
reference's swap machinery,
runtime/swap_tensor/partitioned_param_swapper.py:40). The logical block
space — what the BlockedAllocator hands out, what sequences' block
tables reference — lives in HOST memory; the device holds a fixed pool
of ``device_blocks`` physical slots managed as an LRU cache. Context
length x concurrent streams is then bounded by host RAM, not HBM.

Mechanics:
  * ``ensure(cache, logical_ids)`` makes a set of logical blocks
    device-resident: LRU-evicts victims (dirty ones are fetched back to
    host first), uploads the missing blocks for EVERY layer in one
    stacked H2D transfer + one jitted donated scatter, and returns the
    logical -> device slot translation for building dispatch tables.
  * Dispatches reference DEVICE slots; the engine translates each
    step's block tables through ``translate``.
  * Blocks a dispatch writes (prefill scatter positions, decode tail
    blocks) are marked ``dirty``; their device copy is authoritative
    until eviction writes them back.
  * Prefetch: ``prepare(logical_ids)`` host-gathers and device_puts the
    upload payload WITHOUT the scatter — JAX transfers are async, so
    issuing the next dispatch group's prepare before the current
    group's compute overlaps H2D with the decode (the reference
    overlaps its swap-in the same way, via aio + compute streams).
  * Device slot 0 is pinned to logical block 0 (the scratch block every
    padded table position points at) and is never evicted.
"""

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["OffloadKVPool"]


class OffloadKVPool:
    def __init__(self, model, num_logical, device_blocks, block_size,
                 dtype, cache_shardings, mesh):
        if device_blocks < 2:
            raise ValueError("device_kv_blocks must be >= 2 (slot 0 is "
                             "the pinned scratch block)")
        self.model = model
        self.NL = int(num_logical)
        self.D = int(device_blocks)
        self.block_size = block_size
        self.dtype = jnp.dtype(dtype)
        self.mesh = mesh
        self._cache_sh = cache_shardings

        mcfg = model.config
        L = mcfg.n_layer
        self.n_layer = L
        # host store mirrors the per-layer device pool layout
        # (NL, KVH, BS, hd); one numpy array per layer per k/v
        probe = jax.eval_shape(
            lambda: model.init_paged_cache(1, block_size, dtype=dtype))
        self._blk_shape = tuple(probe["k"][0].shape[1:])
        np_dt = np.dtype(self.dtype)
        self.host = {
            kv: [np.zeros((self.NL,) + self._blk_shape, np_dt)
                 for _ in range(L)]
            for kv in ("k", "v")}

        # slot maps: device slot -> logical block (or -1), and inverse
        self.logical_of = np.full((self.D,), -1, np.int64)
        self.slot_of = np.full((self.NL,), -1, np.int64)
        self.dirty = np.zeros((self.D,), bool)
        self.last_used = np.zeros((self.D,), np.int64)
        self._tick = 0
        # pin scratch
        self.logical_of[0] = 0
        self.slot_of[0] = 0

        self._scatter_jit = None
        self._gather_jit = None
        self.swapped_in = 0           # blocks uploaded (stats)
        self.swapped_out = 0          # dirty blocks written back

    # ---------------------------------------------------------- jitted ops
    def _get_scatter(self):
        if self._scatter_jit is None:
            def scatter(cache, slots, blk_k, blk_v):
                # blk_k/blk_v: (L, n, KVH, BS, hd) stacked uploads
                k = [c.at[slots].set(blk_k[i])
                     for i, c in enumerate(cache["k"])]
                v = [c.at[slots].set(blk_v[i])
                     for i, c in enumerate(cache["v"])]
                return {"k": k, "v": v}
            self._scatter_jit = jax.jit(
                scatter, donate_argnums=(0,),
                in_shardings=(self._cache_sh, None, None, None),
                out_shardings=self._cache_sh)
        return self._scatter_jit

    def _get_gather(self):
        if self._gather_jit is None:
            def gather(cache, slots):
                k = jnp.stack([c[slots] for c in cache["k"]])
                v = jnp.stack([c[slots] for c in cache["v"]])
                return k, v
            self._gather_jit = jax.jit(
                gather,
                in_shardings=(self._cache_sh, None),
                out_shardings=(None, None))
        return self._gather_jit

    # ------------------------------------------------------------ prefetch
    def prepare(self, logical_ids, skip_upload=()):
        """Host-gather + async device_put of the upload payload for the
        blocks in ``logical_ids`` that are NOT yet resident. Returns an
        opaque handle ``ensure`` accepts (None when nothing to upload).
        Does not touch the slot maps — call ``ensure`` with the handle
        to commit. ``skip_upload``: blocks the coming dispatch fully
        overwrites (never-written prefill/chunk destinations) — they
        are excluded here and get bare slot assignments in ``ensure``,
        skipping the pointless H2D of garbage host contents."""
        skip = {int(b) for b in skip_upload}
        missing = [b for b in dict.fromkeys(int(b) for b in logical_ids)
                   if self.slot_of[b] < 0 and b not in skip]
        if not missing:
            return None
        # pad the upload to a power-of-two bucket so the scatter program
        # compiles once per bucket, not once per distinct miss count;
        # pad rows land in the scratch slot (contents never attended)
        n = len(missing)
        n_pad = 1 << (n - 1).bit_length()
        midx = np.asarray(missing + [0] * (n_pad - n), np.int64)
        blk_k = np.stack([h[midx] for h in self.host["k"]])
        blk_v = np.stack([h[midx] for h in self.host["v"]])
        # async H2D: returns immediately, overlaps in-flight compute
        return (missing, jax.device_put(blk_k), jax.device_put(blk_v))

    # -------------------------------------------------------------- ensure
    def ensure(self, cache, logical_ids, prepared=None, skip_upload=()):
        """Make every block in ``logical_ids`` device-resident.
        Returns the updated cache. ``prepared``: a matching
        ``prepare`` handle (uploads already in flight). ``skip_upload``:
        see ``prepare`` — such blocks get slots but no data transfer
        (the dispatch fully overwrites them / never attends their
        stale positions)."""
        need = list(dict.fromkeys(int(b) for b in logical_ids))
        self._tick += 1
        if prepared is None:
            prepared = self.prepare(need, skip_upload)
        skip = [b for b in dict.fromkeys(int(b) for b in skip_upload)
                if self.slot_of[b] < 0 and b in set(need)]
        missing, blk_k, blk_v = prepared if prepared is not None \
            else ([], None, None)
        if not missing and not skip:
            self._check_resident(need)
            for b in need:
                self.last_used[self.slot_of[b]] = self._tick
            return cache
        if len(need) > self.D - 1:
            raise ValueError(
                f"dispatch references {len(need)} KV blocks but the "
                f"device pool holds only {self.D - 1} (+scratch); raise "
                f"device_kv_blocks or lower concurrency/context")

        # victims: LRU over slots not referenced by this ensure, slot 0
        # excluded
        needed_slots = {int(self.slot_of[b]) for b in need
                        if self.slot_of[b] >= 0}
        free = [s for s in range(1, self.D)
                if self.logical_of[s] < 0 and s not in needed_slots]
        evictable = sorted(
            (s for s in range(1, self.D)
             if self.logical_of[s] >= 0 and s not in needed_slots),
            key=lambda s: self.last_used[s])

        def take_slot():
            if free:
                return free.pop()
            if evictable:
                return evictable.pop(0)
            raise ValueError(
                "KV device pool exhausted mid-ensure (should be "
                "unreachable given the size check above)")

        slots = [take_slot() for _ in missing]
        skip_slots = [take_slot() for _ in skip]

        # write back dirty victims before their slots are overwritten
        dirty_slots = [s for s in slots + skip_slots
                       if self.logical_of[s] >= 0 and self.dirty[s]]
        if dirty_slots:
            cache = self._writeback(cache, dirty_slots)
        for s in slots + skip_slots:
            old = self.logical_of[s]
            if old >= 0:
                self.slot_of[old] = -1
            self.logical_of[s] = -1
            self.dirty[s] = False

        if missing:
            # the upload was padded to a power-of-two bucket: route the
            # pad rows at the scratch slot (never attended)
            pad_slots = [0] * (blk_k.shape[1] - len(slots))
            sl = jnp.asarray(np.asarray(slots + pad_slots, np.int32))
            with jax.set_mesh(self.mesh):
                cache = self._get_scatter()(cache, sl, blk_k, blk_v)
        for b, s in zip(list(missing) + skip, slots + skip_slots):
            self.logical_of[s] = b
            self.slot_of[b] = s
        # a stale ``prepared`` handle (built for a different block list)
        # can leave a needed block without a slot — translate() would
        # then silently route its reads to the scratch slot and the
        # dispatch would attend garbage; fail loudly instead
        self._check_resident(need)
        for b in need:
            self.last_used[self.slot_of[b]] = self._tick
        self.swapped_in += len(missing)
        return cache

    def _check_resident(self, need):
        stale = [b for b in need if self.slot_of[b] < 0]
        if stale:
            raise RuntimeError(
                f"ensure() commit left blocks {stale} without device "
                "slots — the prepared handle was built for a different "
                "block list (stale prepare()); re-prepare with the "
                "dispatch's actual blocks")

    def _writeback(self, cache, slots):
        # pad to the same power-of-two buckets as the upload path so the
        # gather program compiles once per bucket, not per victim count
        # (pad rows re-read slot 0 and are discarded below)
        n = len(slots)
        n_pad = 1 << (n - 1).bit_length()
        padded = list(slots) + [0] * (n_pad - n)
        with jax.set_mesh(self.mesh):
            k, v = self._get_gather()(cache,
                                      jnp.asarray(padded, jnp.int32))
        k = np.asarray(k)
        v = np.asarray(v)
        for j, s in enumerate(slots):
            b = int(self.logical_of[s])
            for li in range(self.n_layer):
                self.host["k"][li][b] = k[li, j]
                self.host["v"][li][b] = v[li, j]
            self.dirty[s] = False
        self.swapped_out += len(slots)
        return cache

    # ------------------------------------------------------------- helpers
    def translate(self, logical_ids):
        """logical block ids (any numpy shape) -> device slot ids.
        Unresident blocks map to scratch 0 — callers must ``ensure``
        everything a dispatch actually reads/writes first."""
        ids = np.asarray(logical_ids, np.int64)
        out = self.slot_of[ids]
        return np.where(out < 0, 0, out).astype(np.int32)

    def mark_dirty(self, logical_ids):
        for b in dict.fromkeys(int(b) for b in np.asarray(
                logical_ids).reshape(-1)):
            s = self.slot_of[b]
            if s > 0:
                self.dirty[s] = True

    def release(self, logical_ids):
        """A retired sequence's blocks: drop residency, nothing to keep."""
        for b in dict.fromkeys(int(b) for b in logical_ids):
            s = self.slot_of[b]
            if s > 0:
                self.logical_of[s] = -1
                self.slot_of[b] = -1
                self.dirty[s] = False
