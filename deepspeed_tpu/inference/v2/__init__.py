from .blocked_allocator import BlockedAllocator
from .ragged import DSSequenceDescriptor, DSStateManager, RaggedBatchWrapper
from .engine_v2 import InferenceEngineV2, RaggedInferenceEngineConfig
