from .blocked_allocator import BlockedAllocator
from .ragged import DSSequenceDescriptor, DSStateManager, RaggedBatchWrapper
from .prefix_cache import PrefixCache, PrefixMatch
from .engine_v2 import InferenceEngineV2, RaggedInferenceEngineConfig
from .replica import Replica, ReplicaDead
from .router import DeadlineExceeded, Overloaded, Router, RouterConfig
