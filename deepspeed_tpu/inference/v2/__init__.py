from .blocked_allocator import BlockedAllocator
from .ragged import DSSequenceDescriptor, DSStateManager, RaggedBatchWrapper
from .prefix_cache import PrefixCache, PrefixMatch
from .engine_v2 import InferenceEngineV2, RaggedInferenceEngineConfig
