"""Replica handle + health state machine for the serving front-end.

One :class:`Replica` wraps one :class:`InferenceEngineV2` (in-process
replica handles for now; the worker-process transport rides the
``ring_exchange_bytes``/fs idioms later) and owns the health contract
the router dispatches against:

  * state machine ``live -> draining -> dead`` (``live -> dead`` on
    failure). Draining replicas finish their in-flight requests but
    admit nothing new — the SIGTERM-drain contract of the elastic
    agent applied to serving scale-down.
  * heartbeat = recent ``step()`` progress: every completed scheduler
    iteration stamps ``last_progress``; ``max_step_failures``
    CONSECUTIVE injected/IO step failures (the ``serve_step`` fault
    point) mean the heartbeat is broken and the replica declares
    itself dead.
  * ``replica_death`` (fatal blast radius) fires at the top of every
    step — arming it models the replica worker dying mid-decode. The
    failure propagates as :class:`ReplicaDead`; the ROUTER is the
    supervising recovery layer that catches it and replays the
    replica's in-flight requests on a survivor (the elastic-agent
    pattern for host_loss, applied to serving).

The fault points deliberately live HERE, at the replica boundary, not
inside engine_v2: the engine is shared with single-replica serving and
must stay byte-identical with the router off.
"""

import time

import numpy as np

from ...utils import fault_injection
from ...utils.logging import log_dist

LIVE = "live"
DRAINING = "draining"
DEAD = "dead"

# Phase roles for disaggregated serving. "colocated" replicas run
# prefill and decode interleaved through split-fuse (the pre-disagg
# behavior and the default). In an actively disaggregated fleet (the
# router turns it on iff both phase roles are live), "prefill"
# replicas run chunked prefill to the last prompt token, post the
# first generated token, then park the sequence for a KV handoff
# instead of decoding; "decode" replicas take no fresh dispatches and
# admit handed-off sequences directly into their decode batch.
ROLES = ("colocated", "prefill", "decode")


class ReplicaDead(RuntimeError):
    """Terminal replica failure. Raised out of :meth:`Replica.step` —
    the fatal blast-radius contract: nothing below the router may
    swallow it. The router catches it, fails the replica out of the
    rotation, and replays its in-flight requests on a survivor."""

    def __init__(self, name, reason):
        super().__init__(f"replica {name!r} died: {reason}")
        self.name = name
        self.reason = reason


class Replica:
    """Health-tracked handle around one in-process replica engine."""

    def __init__(self, name, engine, max_step_failures=3,
                 role="colocated"):
        if role not in ROLES:
            raise ValueError(
                f"role must be one of {ROLES}, got {role!r}")
        self.name = name
        self.engine = engine
        self.role = role
        # router-driven: the prefill-role park/handoff behavior engages
        # only while the FLEET is actually disaggregated (both phase
        # roles live) — the router re-resolves this every round, so a
        # fleet that loses its last decode replica degrades to
        # colocated behavior instead of deadlocking held sequences
        self._disaggregated = False
        self.state = LIVE
        # True when the terminal state was reached via a clean drain
        # (finished in-flight, nothing replayed) rather than a failure
        self.drained = False
        self.inflight = []            # router uids in dispatch order
        self.steps = 0                # completed scheduler iterations
        self.step_failures = 0        # injected/IO step failures survived
        self._consecutive_failures = 0
        self.max_step_failures = max(1, int(max_step_failures))
        self.last_progress = time.monotonic()

    # -------------------------------------------------------------- state
    @property
    def live(self):
        return self.state == LIVE

    @property
    def draining(self):
        return self.state == DRAINING

    @property
    def dead(self):
        return self.state == DEAD

    @property
    def has_work(self):
        return bool(self.inflight) or self.engine.has_work

    @property
    def slots(self):
        return self.engine.config.max_batch_size

    def heartbeat_age(self, now=None):
        """Seconds since the last completed step() — the router's
        liveness signal (heartbeat = recent step progress)."""
        return (time.monotonic() if now is None else now) \
            - self.last_progress

    def drain(self):
        """Stop admitting; in-flight requests run to completion, then
        the router removes the replica from the rotation."""
        if self.state == LIVE:
            self.state = DRAINING
            log_dist(f"replica {self.name}: draining "
                     f"({len(self.inflight)} in flight)", ranks=[0])

    def mark_dead(self, reason, drained=False):
        self.state = DEAD
        self.drained = drained
        if not drained:
            log_dist(f"replica {self.name}: DEAD ({reason})", ranks=[0])

    # --------------------------------------------------------- dispatching
    def fits(self, prompt_len, max_new_tokens):
        """Whether the request could EVER be served here (context +
        pool capacity), regardless of current load."""
        eng = self.engine
        if prompt_len + max_new_tokens > eng.max_seq_len:
            return False
        mgr = eng.state_mgr
        return mgr.blocks_needed(prompt_len + max_new_tokens) \
            <= mgr.allocator.total_blocks

    def can_accept(self, prompt_len, max_new_tokens, prompt=None):
        """Admission probe the router dispatches against: live, no
        request already parked in the engine's own pending queue (whose
        blocks can_admit cannot see yet), and the state manager has the
        slot + pool capacity to admit NOW."""
        if self.state != LIVE:
            return False
        eng = self.engine
        if eng._pending:
            return False
        if eng.state_mgr.free_slots == 0:
            # cheap probe before can_admit's pool/radix capacity scan
            return False
        if not self.fits(prompt_len, max_new_tokens):
            return False
        return eng.state_mgr.can_admit(prompt_len, max_new_tokens,
                                       prompt=prompt)

    def prefix_score(self, prompt):
        """Longest cached prefix (tokens) this replica's radix tree
        holds for ``prompt`` — the router's prefix-affinity key. Uses
        the PURE ``match()`` probe: no refs, no stats, no LRU touch, so
        affinity probing never skews the cache's hit accounting."""
        pc = self.engine.prefix_cache
        if pc is None:
            return 0
        return int(pc.match(np.asarray(prompt, np.int32)).cached_len)

    @property
    def spec_acceptance(self):
        """Speculative-decoding acceptance EMA of this replica's engine
        (global, [0, 1]) — None when the engine has no draft model, no
        telemetry, or has not run a verify round yet. The router's
        health snapshot surfaces it per replica."""
        tel = getattr(self.engine, "telemetry", None)
        if tel is None:
            return None
        fn = getattr(tel, "spec_acceptance_ema", None)
        return fn() if fn is not None else None

    def submit(self, uid, prompt, max_new_tokens, eos_token_id=-1,
               klass=0):
        """Hand one admitted request to the engine. ``serve_dispatch``
        fires FIRST (retryable): an injected dispatch failure leaves no
        partial state and the router re-queues the request. ``klass``
        rides through to the engine so serving telemetry can key its
        acceptance EMAs by request class."""
        fault_injection.fire("serve_dispatch")
        self.engine.put(prompt, max_new_tokens=max_new_tokens,
                        eos_token_id=eos_token_id, uid=uid, klass=klass)
        if self._disaggregated:
            # prefill role: the sequence prefills here, posts its first
            # token, then waits for the KV handoff instead of decoding
            self.engine.hold_decode(uid)
        self.inflight.append(uid)

    def cancel(self, uid):
        """Withdraw one in-flight request (deadline expiry): the engine
        flushes it through the unref-without-insert path."""
        if uid in self.inflight:
            self.inflight.remove(uid)
            self.engine.cancel(uid)

    # ------------------------------------- disaggregated prefill/decode
    def set_disaggregated(self, on):
        """Router hook, called every round with the fleet-wide verdict.
        Only a prefill-role replica ever engages; flipping OFF releases
        every parked sequence so it resumes decoding HERE (the
        colocated-degradation path when the decode side is gone)."""
        on = bool(on) and self.role == "prefill"
        if self._disaggregated and not on:
            self.engine.release_decode_hold()
        self._disaggregated = on

    def handoff_ready(self):
        """uids parked after completing prefill (first token posted) —
        the router streams these to a decode replica. Empty unless this
        is a prefill replica in an actively disaggregated fleet."""
        if not self._disaggregated or self.dead:
            return []
        eng = self.engine
        ready = []
        for uid in self.inflight:
            if uid not in eng._decode_hold:
                continue    # finished at its first token, or not parked
            seq = eng.state_mgr._seqs.get(uid)
            if seq is not None and seq.generated:
                ready.append(uid)
        return ready

    def export_handoff(self, uid):
        """Serialize ``uid``'s KV blocks + descriptor state to wire
        bytes. The sequence stays owned here until
        :meth:`finish_handoff` — a failed stream retries from unchanged
        state."""
        from . import kv_transfer
        return kv_transfer.export_sequence(self.engine, uid)

    def import_handoff(self, payload):
        """Decode side of the handoff. ``replica_death`` fires first —
        arming it here models the decode replica dying MID-TRANSFER;
        the router observes :class:`ReplicaDead` and re-enqueues the
        request at the front for a colocated / re-prefill replay
        (byte-identical by greedy construction). The retryable
        ``kv_import`` point fires inside the import path BEFORE any
        decode-side mutation. Returns the imported uid; the router owns
        the in-flight bookkeeping."""
        if self.state == DEAD:
            raise ReplicaDead(self.name, "handoff import after death")
        try:
            fault_injection.fire("replica_death")
        except fault_injection.FaultError as e:
            self.mark_dead("injected replica death mid-transfer")
            raise ReplicaDead(self.name, str(e)) from e
        from . import kv_transfer
        return kv_transfer.import_sequence(self.engine, payload)

    def finish_handoff(self, uid):
        """The decode side confirmed the import: release the sequence
        here (prefix insert + pool close, no rejection counted) and
        drop it from this replica's in-flight list."""
        if uid in self.inflight:
            self.inflight.remove(uid)
        self.engine.release_handoff(uid)

    # --------------------------------------------------------------- step
    def step(self):
        """One engine scheduler iteration. Fires ``replica_death``
        (fatal: propagates as :class:`ReplicaDead`) and ``serve_step``
        (retryable: counted; ``max_step_failures`` consecutive failures
        break the heartbeat and the replica dies). Returns the engine's
        (uid, token) pairs."""
        if self.state == DEAD:
            raise ReplicaDead(self.name, "stepped after death")
        try:
            fault_injection.fire("replica_death")
        except fault_injection.FaultError as e:
            self.mark_dead("injected replica death")
            raise ReplicaDead(self.name, str(e)) from e
        # SimulatedKill (kill=True) is deliberately NOT caught: it is a
        # BaseException modeling SIGKILL of the whole front-end process
        # — no layer may convert it into a recoverable event.
        try:
            fault_injection.fire("serve_step")
            if getattr(self.engine, "spec_pending", False):
                # the next step would run a speculative verify dispatch:
                # ``serve_verify`` (retryable) models a failure landing
                # exactly there, while proposals are tentatively
                # appended — the engine's rollback must leave no trace
                # and the failover replay must stay byte-identical
                fault_injection.fire("serve_verify")
            out = self.engine.step()
        except fault_injection.FaultError as e:
            self.step_failures += 1
            self._consecutive_failures += 1
            if self._consecutive_failures >= self.max_step_failures:
                self.mark_dead(
                    f"no step progress after "
                    f"{self._consecutive_failures} consecutive failures")
                raise ReplicaDead(self.name, str(e)) from e
            return []
        self._consecutive_failures = 0
        self.steps += 1
        self.last_progress = time.monotonic()
        return out
