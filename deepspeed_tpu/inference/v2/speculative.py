"""Speculative-decoding policy: knob resolution and acceptance math.

Draft-model speculation in the v2 engine (Leviathan et al. 2023 /
DeepSpeed-FastGen style, greedy-only): a small draft model proposes
``spec_k`` tokens per greedy sequence per step, and the target verifies
all ``k+1`` positions in one batched multi-token pass through the
paged-attention verify program. Acceptance is exact-greedy: a proposal
survives only while it equals the target's argmax at the same position,
and the first divergence is replaced by the target's own argmax (the
"bonus" token) — every committed token is a target-argmax output, so
greedy streams are byte-identical to plain decode.

This module is the host-side policy half: what "auto" resolves to for
the ``spec_draft`` / ``spec_k`` engine knobs (winner-cache consulted,
same dispatch discipline as prefix_cache.py), the EMA constants for the
per-sequence acceptance floor, and the ``longest_accept`` kernel of the
acceptance rule. The device programs and scheduling live in
engine_v2.py; block bookkeeping in ragged.py.
"""

# Hand-set policy defaults — what "auto" resolves to on a COLD winner
# cache. Unlike prefix_cache, ``enabled: 1`` is the safe cold default
# here because speculation has a second, explicit opt-in gate: the
# engine only speculates when a ``draft_model`` was passed to the
# constructor. With no draft model the resolver is never consulted and
# every compiled program is byte-identical to the pre-speculation
# engine; with one, the caller has already asked for speculation and
# the knobs only shape it. The registry op (autotuning/kernel_registry
# "spec_decode") re-exports these as its defaults.
SPEC_DEFAULTS = {
    "enabled": 1,
    "spec_k": 4,
    "floor_pct": 35,     # acceptance-EMA floor, percent of spec_k
}

# Per-sequence acceptance EMA: ema <- (1-a)*ema + a*(accepted/k) after
# every verify round. A sequence latches to plain decode once its EMA
# sits below the floor after at least SPEC_MIN_ROUNDS rounds — enough
# rounds that one unlucky round can't latch a healthy sequence, few
# enough that adversarial (random-token) traffic stops paying the
# draft+verify overhead almost immediately.
SPEC_EMA_ALPHA = 0.25
SPEC_MIN_ROUNDS = 3


def spec_bucket(B, NB, BS):
    """Winner-cache bucket for the speculation policy op: batch slots,
    pool blocks (power-of-two rounded — the draft pool mirrors the
    target pool, so pool pressure gates whether a draft cache fits),
    exact block size."""
    from ...ops.pallas._common import pow2_bucket
    return f"B{pow2_bucket(B)},NB{pow2_bucket(NB)},BS{int(BS)}"


def resolve_spec(spec_draft, spec_k, B, NB, BS, dtype):
    """Resolve engine ``spec_draft`` / ``spec_k``: "auto" consults the
    autotune winner cache for this pool-shape bucket (falling back to
    :data:`SPEC_DEFAULTS` on a miss); True/False and ints force.
    Returns (enabled, k, floor) with ``floor`` the acceptance-EMA
    fallback threshold in [0, 1]."""
    win = None
    if spec_draft == "auto" or spec_k == "auto":
        from ...ops.pallas._common import dispatch, dtype_name
        win = dispatch("spec_decode", spec_bucket(B, NB, BS),
                       dtype_name(dtype), dict(SPEC_DEFAULTS))
    enabled = bool(win["enabled"]) if spec_draft == "auto" \
        else bool(spec_draft)
    k = int(win["spec_k"]) if spec_k == "auto" else int(spec_k)
    floor_pct = int(win["floor_pct"]) if win is not None \
        else SPEC_DEFAULTS["floor_pct"]
    if k < 1:
        enabled = False
    return enabled, k, floor_pct / 100.0


def longest_accept(proposed, target_next):
    """Greedy acceptance: length of the longest prefix of ``proposed``
    (k draft tokens) matching ``target_next`` (k+1 target argmaxes,
    where ``target_next[j]`` is the target's prediction *after* seeing
    proposal j tokens of context). Position j is accepted iff
    ``proposed[j] == target_next[j]``; the first mismatch — whose
    correct replacement is ``target_next[a]`` — ends the round."""
    a = 0
    for j in range(len(proposed)):
        if int(proposed[j]) != int(target_next[j]):
            break
        a += 1
    return a
