"""KV-block allocator: free list + per-block reference counts.

Counterpart of reference ``inference/v2/ragged/blocked_allocator.py:11
BlockedAllocator`` (a torch-tensor linked list on the host). Here: a plain
python free list — the allocator is host-side bookkeeping either way; the
device only ever sees block-id arrays.

Block 0 is RESERVED as the scratch block: pad tokens and inactive batch
slots write their KV there, so the allocator never hands it out.

Reference counting (prefix_cache.py's contract): ``allocate`` hands out
blocks at refcount 1; the radix tree and every sequence sharing a cached
prefix take additional refs with :meth:`ref` and drop them with
:meth:`unref` — the block returns to the free list only at zero. A block
with refcount > 1 is SHARED and must never be written in place (the
writer goes copy-on-write); :meth:`free` is the strict whole-ownership
release and raises on double-free AND on free-while-referenced, so a
scheduler bug corrupts loudly instead of silently cross-wiring two
sequences' KV.

An optional *evictor* (the prefix cache) extends the pool: when
``allocate`` would fail, cold zero-ref tree leaves are reclaimed first —
"free" means free-or-evictable (:attr:`available_blocks`).
"""


class BlockedAllocator:
    SCRATCH = 0

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (1 scratch + 1 usable)")
        self._num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, 0, -1))  # pop() -> block 1
        self._refs = {}        # block id -> refcount (allocated blocks only)
        self._evictor = None   # .evictable_blocks / .evict(n) (prefix cache)

    @property
    def total_blocks(self):
        return self._num_blocks - 1  # scratch excluded

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def available_blocks(self):
        """Free-or-evictable: what admission control may count on —
        ``allocate`` reclaims cold evictor blocks before refusing."""
        n = len(self._free)
        if self._evictor is not None:
            n += self._evictor.evictable_blocks
        return n

    def set_evictor(self, evictor):
        """Register the reclaim hook (``evictable_blocks`` property +
        ``evict(n) -> freed``); None detaches."""
        self._evictor = evictor

    def refcount(self, block):
        """Current refcount (0 = free / never allocated)."""
        return self._refs.get(block, 0)

    def allocate(self, n: int):
        """-> list of n block ids at refcount 1; evicts from the
        registered evictor under pressure; raises if still short."""
        if n > len(self._free) and self._evictor is not None:
            self._evictor.evict(n - len(self._free))
        if n > len(self._free):
            raise RuntimeError(
                f"out of KV blocks: want {n}, have {len(self._free)}")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def ref(self, block):
        """Take an additional reference on an allocated block."""
        if block not in self._refs:
            raise ValueError(
                f"ref of block {block} that is not allocated")
        self._refs[block] += 1

    def unref(self, block):
        """Drop one reference; the block returns to the free list at
        zero. Returns True if this call freed it. Raises on a block
        that holds no references (the unref-side double-free)."""
        c = self._refs.get(block)
        if c is None:
            raise ValueError(
                f"unref of block {block} that holds no references "
                f"(double-free)")
        if c == 1:
            del self._refs[block]
            self._free.append(block)
            return True
        self._refs[block] = c - 1
        return False

    def free(self, blocks):
        """Strict whole-ownership release: every block must be
        allocated exactly once (refcount 1). Validates the entire list
        before mutating anything, so a bad id never half-applies."""
        seen = set()
        for b in blocks:
            if b == self.SCRATCH:
                raise ValueError("cannot free the scratch block")
            if b in seen or not (0 < b < self._num_blocks) \
                    or b not in self._refs:
                raise ValueError(f"double-free / bad block {b}")
            if self._refs[b] > 1:
                raise ValueError(
                    f"free of block {b} with refcount {self._refs[b]} — "
                    f"still referenced (shared prefix block? unref "
                    f"instead)")
            seen.add(b)
        for b in blocks:
            del self._refs[b]
        self._free.extend(blocks)
