"""KV-block free-list allocator.

Counterpart of reference ``inference/v2/ragged/blocked_allocator.py:11
BlockedAllocator`` (a torch-tensor linked list on the host). Here: a plain
python free list — the allocator is host-side bookkeeping either way; the
device only ever sees block-id arrays.

Block 0 is RESERVED as the scratch block: pad tokens and inactive batch
slots write their KV there, so the allocator never hands it out.
"""


class BlockedAllocator:
    SCRATCH = 0

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (1 scratch + 1 usable)")
        self._num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, 0, -1))  # pop() -> block 1

    @property
    def total_blocks(self):
        return self._num_blocks - 1  # scratch excluded

    @property
    def free_blocks(self):
        return len(self._free)

    def allocate(self, n: int):
        """-> list of n block ids; raises if not enough free."""
        if n > len(self._free):
            raise RuntimeError(
                f"out of KV blocks: want {n}, have {len(self._free)}")
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, blocks):
        seen = set(self._free)
        for b in blocks:
            if b == self.SCRATCH:
                raise ValueError("cannot free the scratch block")
            if b in seen or not (0 < b < self._num_blocks):
                raise ValueError(f"double-free / bad block {b}")
            seen.add(b)
        self._free.extend(blocks)
