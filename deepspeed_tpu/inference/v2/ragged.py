"""Ragged-batch state management for the v2 serving engine.

Counterparts of reference ``inference/v2/ragged/``:
  * ``DSSequenceDescriptor`` (sequence_descriptor.py:59) — one live
    sequence: tokens seen, KV blocks held, generation state.
  * ``RaggedBatchWrapper`` (ragged_wrapper.py:31) — the fixed-shape
    device-facing metadata for one engine step (token ids, lengths, block
    tables). The reference fills pinned host buffers; here plain numpy
    arrays handed to a jitted program (the XLA transfer is the H2D copy).
  * ``DSStateManager`` (ragged_manager.py:19) — owns the allocator and the
    id -> descriptor map, builds RaggedBatchWrapper for each step.
"""

from dataclasses import dataclass, field

import numpy as np

from .blocked_allocator import BlockedAllocator


@dataclass
class DSSequenceDescriptor:
    uid: int
    prompt: np.ndarray                    # (T,) int32
    max_new_tokens: int
    eos_token_id: int = -1
    temperature: float = 0.0              # per-request sampling params
    top_k: int = 0                        # (FastGen per-request config)
    blocks: list = field(default_factory=list)
    generated: list = field(default_factory=list)
    done: bool = False
    # Dynamic SplitFuse: prompt tokens already written to the cache; a
    # sequence decodes only once the whole prompt is in (the legacy
    # bucketed prefill writes it all at once)
    prefill_offset: int = 0
    # Prefix cache: leading prompt tokens whose KV came from the radix
    # tree (prefill_offset starts here — those tokens are never
    # recomputed); ``cow`` = (src_block, dst_block, plen) when the
    # matched tail is partial and the engine owes a device-side
    # copy-on-write of the first plen tokens before prefill resumes
    cached_len: int = 0
    cow: tuple = None
    # Speculative decoding (draft-model propose + batched verify):
    # ``spec_on`` is the per-sequence eligibility latch — the engine
    # clears it permanently when the acceptance EMA falls below the
    # floor or the draft pool cannot hold the sequence, and the
    # sequence rides plain decode from then on. ``draft_blocks`` is the
    # sequence's slice of the DRAFT allocator (always whole-owned: the
    # draft cache never feeds the prefix cache, so rollback/free is a
    # strict free). ``draft_len`` counts COMMITTED tokens whose KV the
    # draft cache holds (positions 0..draft_len-1); the propose
    # program's re-ingest step covers a one-token gap, so the sequence
    # is spec-eligible while draft_len >= seen_tokens - 2.
    # ``spec_inflight`` brackets a proposal span tentatively appended
    # to ``generated`` between begin_spec and rollback_spec.
    spec_on: bool = True
    spec_inflight: int = 0
    draft_blocks: list = field(default_factory=list)
    draft_len: int = 0
    spec_ema: float = None
    spec_rounds: int = 0
    spec_accepted: int = 0

    @property
    def seen_tokens(self):
        return len(self.prompt) + len(self.generated)

    def cur_allocated_capacity(self, block_size):
        return len(self.blocks) * block_size


@dataclass
class RaggedBatchWrapper:
    """Fixed-shape step metadata (B = engine max_batch)."""
    tokens: np.ndarray        # (B,) int32 — next input token per slot
    lengths: np.ndarray       # (B,) int32 — tokens already in cache
    block_tables: np.ndarray  # (B, MB) int32 — scratch-0 padded
    active: np.ndarray        # (B,) bool
    temps: np.ndarray = None  # (B,) f32 — per-slot temperature (0=greedy)
    top_ks: np.ndarray = None  # (B,) int32 — per-slot top-k (0=off)


class DSStateManager:
    def __init__(self, num_blocks, block_size, max_batch, max_blocks_per_seq):
        self.allocator = BlockedAllocator(num_blocks)
        self.block_size = block_size
        self.max_batch = max_batch
        self.max_blocks_per_seq = max_blocks_per_seq
        self._seqs = {}                  # uid -> descriptor
        self._slots = [None] * max_batch  # batch slot -> uid
        # engine-attached radix tree (prefix_cache.py); when set, admit
        # matches prompts against it and retire inserts finished
        # prefixes back — all block lifetimes then run through
        # refcounts (unref) instead of strict whole-ownership free()
        self.prefix_cache = None
        # engine-attached DRAFT-pool allocator (speculative decoding):
        # when set, retire/flush also release each sequence's
        # draft_blocks so no exit path (EOS, cancel, deadline
        # withdrawal mid-speculation) can leak draft blocks
        self.draft_allocator = None

    # ------------------------------------------------------------- tracking
    @property
    def n_active(self):
        return sum(s is not None for s in self._slots)

    @property
    def free_slots(self):
        """Open batch slots — the router's cheap per-replica load
        probe (can_admit answers "this request now"; this answers
        "how loaded")."""
        return sum(s is None for s in self._slots)

    def get_sequence(self, uid):
        return self._seqs[uid]

    def free_slot(self):
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def blocks_needed(self, n_tokens):
        return -(-n_tokens // self.block_size)

    def can_admit(self, prompt_len, max_new, prompt=None):
        total = prompt_len + max_new
        if total > self.max_blocks_per_seq * self.block_size:
            return False  # can never fit; admit() would raise
        if self.free_slot() is None:
            return False
        needed = self.blocks_needed(total)
        avail = self.allocator.free_blocks
        if self.prefix_cache is not None:
            if prompt is not None:
                # matched blocks are reused, not allocated; the rest of
                # the pool counts free-or-evictable, minus the match
                # itself (its blocks may be the evictable ones, and
                # claiming pins them)
                k = len(self.prefix_cache.match(prompt).blocks)
                needed -= k
                avail += max(
                    0, self.prefix_cache.evictable_blocks - k)
            else:
                avail = self.allocator.available_blocks
        return avail >= needed

    def admit(self, uid, prompt, max_new_tokens, eos_token_id=-1,
              temperature=0.0, top_k=0):
        """Allocate blocks for the full prompt+generation budget and bind
        the sequence to a batch slot. With a prefix cache attached, the
        prompt's longest cached prefix is claimed first (refcount bumps,
        no allocation) and only the remainder is allocated; prefill then
        starts at ``cached_len``. Returns (slot, descriptor)."""
        slot = self.free_slot()
        assert slot is not None, "no free batch slot"
        prompt = np.asarray(prompt, np.int32)
        total = len(prompt) + max_new_tokens
        cap = self.max_blocks_per_seq * self.block_size
        if total > cap:
            raise ValueError(f"prompt+max_new={total} exceeds per-sequence "
                             f"KV capacity {cap}")
        seq = DSSequenceDescriptor(uid=uid, prompt=prompt,
                                   max_new_tokens=max_new_tokens,
                                   eos_token_id=eos_token_id,
                                   temperature=temperature, top_k=top_k)
        m = None
        if self.prefix_cache is not None:
            m = self.prefix_cache.match(prompt)
            self.prefix_cache.claim(m)   # refs matched blocks + stats
        if m is not None and m.hit:
            k = len(m.blocks)
            fresh = self.allocator.allocate(self.blocks_needed(total) - k)
            seq.blocks = list(m.blocks) + fresh
            seq.cached_len = m.cached_len
            seq.prefill_offset = m.cached_len
            if m.cow_src is not None:
                # the partial tail lands in the first fresh block; the
                # engine copies the matched slice there on device
                seq.cow = (m.cow_src, seq.blocks[k], m.cow_plen)
        else:
            seq.blocks = self.allocator.allocate(self.blocks_needed(total))
        self._seqs[uid] = seq
        self._slots[slot] = uid
        return slot, seq

    def admit_imported(self, uid, prompt, generated, max_new_tokens,
                       blocks, eos_token_id=-1, temperature=0.0,
                       top_k=0):
        """Bind a handed-off sequence (disaggregated prefill/decode):
        the prompt's KV was prefilled on ANOTHER replica and just
        landed in ``blocks`` — allocated from THIS pool's allocator and
        whole-owned (refcount 1) — so the descriptor enters the decode
        batch directly: ``prefill_offset`` covers the full prompt and
        ``generated`` already holds the first token produced by the
        prefill side. ``cached_len`` stays 0: the blocks were imported,
        not claimed from this replica's radix tree (retire will insert
        the verified prefix into the local tree like any other
        sequence). Returns (slot, descriptor)."""
        slot = self.free_slot()
        assert slot is not None, "no free batch slot"
        assert uid not in self._seqs, f"uid {uid} already live here"
        seq = DSSequenceDescriptor(
            uid=uid, prompt=np.asarray(prompt, np.int32),
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            temperature=temperature, top_k=top_k)
        seq.blocks = list(blocks)
        seq.generated = [int(t) for t in generated]
        seq.prefill_offset = len(seq.prompt)
        self._seqs[uid] = seq
        self._slots[slot] = uid
        return slot, seq

    def cow_complete(self, seq):
        """The engine's device-side CoW slice copy landed: drop the
        claim's temporary ref on the source block."""
        src, _dst, _plen = seq.cow
        self.prefix_cache.cow_release(src)
        seq.cow = None

    def retire(self, uid):
        """Release the sequence's blocks and slot; keep the descriptor
        (the caller reads .generated) until ``flush``. With a prefix
        cache, the finished prompt+generation prefix is inserted into
        the tree and every block is unreffed exactly once (tree-adopted
        blocks live on; the rest return to the free list). generated[-1]
        is excluded from the insert: the final sampled token's KV write
        may not have landed (it is written — if at all — by the
        dispatch's over-decode)."""
        seq = self._seqs[uid]
        if self.prefix_cache is not None:
            tokens = seq.prompt if not seq.generated else np.concatenate(
                [seq.prompt, np.asarray(seq.generated[:-1], np.int32)])
            self.prefix_cache.release(tokens, seq.blocks)
        else:
            self.allocator.free(seq.blocks)
        seq.blocks = []
        self.drop_draft(seq)
        seq.done = True
        self._slots[self._slots.index(uid)] = None

    def flush(self, uid):
        seq = self._seqs.pop(uid)
        self.drop_draft(seq)
        if seq.blocks:
            if self.prefix_cache is not None:
                # cancelled mid-flight: cache contents past the prefill
                # frontier are unverified — drop refs without inserting
                for b in seq.blocks:
                    self.allocator.unref(b)
            else:
                self.allocator.free(seq.blocks)
            if self._slots.count(uid):
                self._slots[self._slots.index(uid)] = None

    # ------------------------------------------------------- speculation
    def alloc_draft(self, seq):
        """Reserve the sequence's DRAFT-pool blocks (same block count as
        its target budget — the draft cache mirrors the sequence's
        position range). Returns False (and latches ``spec_on`` off)
        when the draft pool cannot hold it; the sequence then rides
        plain decode, it is never an admission failure."""
        if self.draft_allocator is None or not seq.spec_on:
            return False
        needed = len(seq.blocks)
        if self.draft_allocator.free_blocks < needed:
            seq.spec_on = False
            return False
        seq.draft_blocks = self.draft_allocator.allocate(needed)
        return True

    def drop_draft(self, seq):
        """Return the sequence's draft blocks (fallback latch, retire,
        cancel — every path that ends speculation frees here, so the
        draft allocator closes at zero leaked blocks)."""
        if seq.draft_blocks:
            self.draft_allocator.free(seq.draft_blocks)
            seq.draft_blocks = []

    def begin_spec(self, seq, proposals):
        """Tentatively append the draft's proposals: ``seen_tokens``
        includes the in-flight span for the duration of the verify
        dispatch, and ``rollback_spec`` unwinds it. Target/prefix-cache
        block state is deliberately untouched — rollback must never
        disturb refcounts (the sequence's blocks cover its full budget
        up-front, so a speculative span never allocates)."""
        assert seq.spec_inflight == 0, "nested speculation span"
        seq.generated.extend(int(t) for t in proposals)
        seq.spec_inflight = len(proposals)

    def rollback_spec(self, seq, keep=0):
        """Unwind the in-flight span down to its first ``keep`` accepted
        tokens: rejected tokens leave ``generated``/``seen_tokens``, and
        the cache positions they wrote are now past the committed
        frontier — masked dead by every attention path and overwritten
        when real tokens land there. Returns the number unwound."""
        drop = seq.spec_inflight - keep
        assert drop >= 0
        if drop:
            del seq.generated[-drop:]
        seq.spec_inflight = 0
        return drop

    # ---------------------------------------------------------- step builds
    def token_placement(self, seq):
        """(token_blocks, token_offsets) for prefilling ``seq``'s prompt
        padded to T_pad (caller pads); positions past the prompt map to the
        scratch block."""
        T = len(seq.prompt)
        idx = np.arange(T)
        blocks = np.asarray(seq.blocks, np.int32)[idx // self.block_size]
        offs = (idx % self.block_size).astype(np.int32)
        return blocks, offs

    def decode_batch(self, uids=None, exclude=None):
        """RaggedBatchWrapper for one decode step over all active slots.
        ``uids``: optional subset — the speculative scheduler splits a
        step into a spec set and a plain set, and the plain set's decode
        dispatch must carry only its own slots. ``exclude``: uids parked
        out of decode entirely — a prefill-role replica holds finished
        prefills here until their KV handoff lands on a decode replica."""
        B, MB = self.max_batch, self.max_blocks_per_seq
        tokens = np.zeros((B,), np.int32)
        lengths = np.zeros((B,), np.int32)
        tables = np.zeros((B, MB), np.int32)   # scratch
        active = np.zeros((B,), bool)
        temps = np.zeros((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        for slot, uid in enumerate(self._slots):
            if uid is None or (uids is not None and uid not in uids) \
                    or (exclude is not None and uid in exclude):
                continue
            seq = self._seqs[uid]
            if not seq.generated:
                # still prefilling (SplitFuse chunks in flight): no
                # first token yet, nothing to decode
                continue
            active[slot] = True
            temps[slot] = seq.temperature
            top_ks[slot] = seq.top_k
            # input token = last generated (prefill produced the first);
            # it is not yet in the cache, so its write position is
            # seen_tokens - 1
            tokens[slot] = seq.generated[-1]
            lengths[slot] = seq.seen_tokens - 1
            nb = len(seq.blocks)
            tables[slot, :nb] = seq.blocks
        return RaggedBatchWrapper(tokens=tokens, lengths=lengths,
                                  block_tables=tables, active=active,
                                  temps=temps, top_ks=top_ks)

    def propose_batch(self, uids):
        """Draft-side metadata for one propose dispatch over the spec
        set: tokens (B, 2) = [re-ingest token (position seen-2), start
        token (position seen-1)], lengths (B,) = seen_tokens - 2, block
        tables over the DRAFT pool. The re-ingest token erases the
        draft's one-token catch-up gap: after a fully-accepted round
        the draft never saw its own last proposal's KV, and after a
        partial round the rewrite is byte-idempotent — so eligibility
        never needs per-sequence gap bookkeeping beyond draft_len."""
        B, MB = self.max_batch, self.max_blocks_per_seq
        tokens = np.zeros((B, 2), np.int32)
        lengths = np.zeros((B,), np.int32)
        tables = np.zeros((B, MB), np.int32)
        active = np.zeros((B,), bool)
        for slot, uid in enumerate(self._slots):
            if uid is None or uid not in uids:
                continue
            seq = self._seqs[uid]
            hist = (seq.prompt[-1] if len(seq.generated) < 2
                    else seq.generated[-2])
            tokens[slot] = (int(hist), int(seq.generated[-1]))
            lengths[slot] = seq.seen_tokens - 2
            nb = len(seq.draft_blocks)
            tables[slot, :nb] = seq.draft_blocks
        return RaggedBatchWrapper(tokens=tokens, lengths=lengths,
                                  block_tables=tables, active=active)

    def verify_batch(self, proposals, k):
        """Target-side metadata for one verify dispatch: tokens
        (B, k+1) = [last committed token, then the k proposals],
        lengths (B,) = seen_tokens - 1 (the first input token's write
        position, exactly the plain-decode contract), target block
        tables. ``proposals``: {uid: [k draft tokens]}. Build this
        BEFORE begin_spec — the last committed token must not be a
        proposal."""
        B, MB = self.max_batch, self.max_blocks_per_seq
        tokens = np.zeros((B, k + 1), np.int32)
        lengths = np.zeros((B,), np.int32)
        tables = np.zeros((B, MB), np.int32)
        active = np.zeros((B,), bool)
        for slot, uid in enumerate(self._slots):
            if uid is None or uid not in proposals:
                continue
            seq = self._seqs[uid]
            assert seq.spec_inflight == 0, \
                "verify_batch must precede begin_spec"
            active[slot] = True
            tokens[slot, 0] = seq.generated[-1]
            tokens[slot, 1:] = proposals[uid]
            lengths[slot] = seq.seen_tokens - 1
            nb = len(seq.blocks)
            tables[slot, :nb] = seq.blocks
        return RaggedBatchWrapper(tokens=tokens, lengths=lengths,
                                  block_tables=tables, active=active)
