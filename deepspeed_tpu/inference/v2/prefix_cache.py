"""Radix-tree prefix cache over the paged KV block pool.

The SGLang-RadixAttention / vLLM-prefix-caching idea applied to the v2
engine's paged cache: production traffic is repetitive (shared system
prompts, few-shot templates, multi-turn chat), and the KV a finished
sequence computed for its prompt+generation prefix is bit-for-bit the KV
any later request with the same token prefix would recompute. So keep
it: a token-keyed radix tree whose nodes own *full* KV blocks (one node
= one ``block_size``-token block, edge label = the block's token tuple),
each holding one reference in the :class:`BlockedAllocator`.

On admission the scheduler matches the new prompt against the tree
(block-granular longest prefix, plus a token-granular partial tail for
copy-on-write); matched blocks slot directly into the sequence's block
table with refcount bumps and chunked prefill starts *after* the cached
length. Shared blocks (refcount > 1) are never written in place — the
one write that could land in a shared block is the partial tail, and
the engine copies the matched slice into a fresh block on device first
(the CoW copy). On release the finished prefix is inserted back;
eviction is LRU over zero-reference leaves and runs inside
``BlockedAllocator.allocate`` under admission pressure, so "free" means
free-or-evictable.

Correctness invariants (tests/unit/test_prefix_cache.py):
  * a match never exceeds ``len(prompt) - 1`` tokens — the last prompt
    token is always recomputed so the first sampled token comes from a
    real forward, never from a cache lookup;
  * tree nodes hold exactly one allocator ref each; sequences add one
    ref per shared block; eviction only touches refcount-1 leaves, and
    an in-use path is pinned transitively (a matched child implies a
    matched — hence reffed — parent);
  * greedy decode is byte-identical cache-on vs cache-off.
"""

from dataclasses import dataclass, field

# Hand-set policy defaults — what "auto" resolves to on a COLD winner
# cache. ``enabled: 0`` is deliberate: with no measured evidence the
# engine's admission path (and therefore every compiled program) stays
# byte-identical to prefix_cache=False; a measured search that proves
# the cache on a shared-prefix trace flips the cached winner, never the
# cold default. The registry op (autotuning/kernel_registry.py
# "prefix_cache") re-exports these as its defaults.
PREFIX_CACHE_DEFAULTS = {
    "enabled": 0,
    "min_match_blocks": 1,
    "evict_watermark_pct": 0,     # 0 = evict on demand inside allocate
}


def prefix_cache_bucket(B, NB, BS):
    """Winner-cache bucket for the prefix-cache policy op: batch slots,
    pool blocks (power-of-two rounded — the policy knee tracks pool
    pressure), exact block size (it gates match granularity)."""
    from ...ops.pallas._common import pow2_bucket
    return f"B{pow2_bucket(B)},NB{pow2_bucket(NB)},BS{int(BS)}"


def resolve_prefix_cache(setting, min_match, B, NB, BS, dtype):
    """Resolve engine ``prefix_cache`` / ``prefix_cache_min_match``:
    "auto" consults the autotune winner cache for this pool-shape
    bucket (falling back to :data:`PREFIX_CACHE_DEFAULTS` on a miss);
    True/False and ints force. Returns
    (enabled, min_match_blocks, evict_watermark_pct)."""
    win = None
    if setting == "auto" or min_match == "auto":
        from ...ops.pallas._common import dispatch, dtype_name
        win = dispatch("prefix_cache", prefix_cache_bucket(B, NB, BS),
                       dtype_name(dtype), dict(PREFIX_CACHE_DEFAULTS))
    enabled = bool(win["enabled"]) if setting == "auto" \
        else bool(setting)
    mm = int(win["min_match_blocks"]) if min_match == "auto" \
        else int(min_match)
    wm = int(win["evict_watermark_pct"]) if win is not None else 0
    return enabled, mm, wm


@dataclass
class PrefixMatch:
    """Result of matching one prompt against the tree.

    ``blocks`` are the fully-matched block ids in prompt order
    (``cached_len`` covers them plus the partial tail). When
    ``cow_src`` is set, the first ``cow_plen`` tokens of the next block
    also match an existing block: the admitter must allocate a fresh
    destination block and device-copy that slice (the CoW path) before
    prefill resumes at ``cached_len``.
    """
    blocks: list = field(default_factory=list)
    nodes: list = field(default_factory=list)
    cached_len: int = 0
    cow_src: int = None        # block id to copy the tail slice from
    cow_plen: int = 0          # tokens of that block that match
    cow_node: object = None

    @property
    def hit(self):
        return self.cached_len > 0


class _Node:
    __slots__ = ("key", "block", "children", "parent", "last_used")

    def __init__(self, key, block, parent):
        self.key = key            # tuple of block_size token ints
        self.block = block        # KV block id (tree holds one ref)
        self.children = {}        # key tuple -> _Node
        self.parent = parent
        self.last_used = 0


class PrefixCache:
    """Host-side radix tree + eviction policy. Single-threaded like the
    scheduler that owns it; every method is plain python bookkeeping —
    the device only ever sees the block ids it hands out."""

    def __init__(self, allocator, block_size, min_match_blocks=1,
                 max_blocks=0, evict_watermark_pct=0):
        self.allocator = allocator
        self.block_size = int(block_size)
        self.min_match_blocks = max(1, int(min_match_blocks))
        # 0 = bounded only by the pool; > 0 caps tree-held blocks
        self.max_blocks = max(0, int(max_blocks))
        # > 0: after each release, evict cold leaves until at least this
        # percentage of the pool is on the free list (keeps admission
        # from paying eviction latency inside allocate)
        self.evict_watermark_pct = max(0, min(100, int(evict_watermark_pct)))
        self.root = _Node(key=None, block=None, parent=None)
        self.tree_blocks = 0
        self._clock = 0           # LRU tick (monotonic, deterministic)
        # telemetry counters (ServingTelemetry reads stats())
        self.lookups = 0
        self.hits = 0
        self.cached_tokens = 0
        self.evicted_blocks = 0
        self.cow_copies = 0
        self.inserted_blocks = 0
        allocator.set_evictor(self)

    # -------------------------------------------------------------- matching
    def _keys(self, tokens, n):
        BS = self.block_size
        return [tuple(int(t) for t in tokens[i * BS:(i + 1) * BS])
                for i in range(n)]

    def match(self, prompt):
        """Longest-prefix match of ``prompt`` (1-D int tokens) against
        the tree. Pure: no refs taken, no stats, no LRU updates — safe
        to call from admission-control probes (``can_admit``); the
        admit path makes it effective with :meth:`claim`."""
        BS = self.block_size
        T = len(prompt)
        m = PrefixMatch()
        if T < 2 or self.tree_blocks == 0:
            return m
        # full blocks matchable under the "last prompt token is always
        # recomputed" cap: block i is usable only if (i+1)*BS <= T-1
        node = self.root
        max_full = min(len(prompt) // BS, (T - 1) // BS)
        for key in self._keys(prompt, max_full):
            child = node.children.get(key)
            if child is None:
                break
            node = child
            m.nodes.append(child)
            m.blocks.append(child.block)
        k = len(m.blocks)
        if k < self.min_match_blocks:
            return PrefixMatch()
        m.cached_len = k * BS
        # token-granular partial tail: the next block may share a strict
        # prefix with an existing child (divergence mid-block, or a
        # fully-cached prompt hitting the T-1 cap) — matched via CoW
        max_plen = min(BS, T - 1 - m.cached_len)
        if max_plen > 0:
            rest = [int(t) for t in
                    prompt[m.cached_len:m.cached_len + BS]]
            best, best_plen = None, 0
            for key, child in node.children.items():
                plen = 0
                for a, b in zip(rest, key):
                    if a != b:
                        break
                    plen += 1
                if plen > best_plen:
                    best, best_plen = child, plen
            if best is not None:
                m.cow_node = best
                m.cow_src = best.block
                m.cow_plen = min(best_plen, max_plen)
                m.cached_len += m.cow_plen
        return m

    def claim(self, m):
        """Make a match effective for an admitted sequence: one
        allocator ref per matched block (pins them against eviction and
        marks them shared — nobody writes them in place), plus one on
        the CoW source until the device copy lands
        (:meth:`cow_release`). Also the stats/LRU point: called exactly
        once per admission, hit or miss."""
        self.lookups += 1
        if not m.hit:
            return
        self.hits += 1
        self.cached_tokens += m.cached_len
        self._clock += 1
        for node in m.nodes:
            self.allocator.ref(node.block)
            node.last_used = self._clock
        if m.cow_node is not None:
            self.allocator.ref(m.cow_node.block)
            m.cow_node.last_used = self._clock

    def cow_release(self, block):
        """Drop the claim ref on a CoW source once the slice copy is on
        device (the copy made the fresh block self-contained)."""
        self.allocator.unref(block)
        self.cow_copies += 1

    # ------------------------------------------------------------- insertion
    def insert(self, tokens, blocks):
        """Walk/extend the tree with the full blocks of ``tokens``
        backed by the sequence's ``blocks``. Existing nodes are reused
        (the sequence's duplicate block is simply not adopted and dies
        with the caller's unref); new nodes take one allocator ref.
        Partial tail blocks are never inserted — tree nodes are always
        full, so matched prefixes never need per-token masks."""
        self._clock += 1
        node = self.root
        nfull = len(tokens) // self.block_size
        for i, key in enumerate(self._keys(tokens, nfull)):
            child = node.children.get(key)
            if child is None:
                if self.max_blocks and self.tree_blocks >= self.max_blocks:
                    self.evict(1 + self.tree_blocks - self.max_blocks)
                    if self.tree_blocks >= self.max_blocks:
                        break     # everything left is in use; stop here
                b = blocks[i]
                self.allocator.ref(b)
                child = _Node(key=key, block=b, parent=node)
                node.children[key] = child
                self.tree_blocks += 1
                self.inserted_blocks += 1
            node = child
            node.last_used = self._clock
        return node

    def release(self, tokens, blocks):
        """Sequence release: insert the finished prompt+generation
        prefix, then drop the sequence's own reference on EVERY block
        exactly once (tree-adopted blocks live on at refcount >= 1;
        unshared scratch tails return to the free list)."""
        if len(blocks) > 0:
            self.insert(tokens, blocks)
        for b in blocks:
            self.allocator.unref(b)
        if self.evict_watermark_pct:
            want = (self.allocator.total_blocks
                    * self.evict_watermark_pct) // 100
            if self.allocator.free_blocks < want:
                self.evict(want - self.allocator.free_blocks)

    # -------------------------------------------------------------- eviction
    def _walk(self):
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    @property
    def evictable_blocks(self):
        """Blocks reclaimable under pressure: tree nodes nobody but the
        tree references. (Closed downward: a reffed child implies a
        reffed parent, so repeated leaf eviction reaches all of them.)"""
        return sum(1 for n in self._walk()
                   if self.allocator.refcount(n.block) == 1)

    def evict(self, n):
        """LRU eviction of zero-ref leaves until ``n`` blocks are freed
        or nothing evictable remains. Returns blocks freed. Called by
        ``BlockedAllocator.allocate`` under admission pressure (the
        free-or-evictable contract) and by the watermark policy."""
        freed = 0
        while freed < n:
            best = None
            for cand in self._walk():
                if cand.children \
                        or self.allocator.refcount(cand.block) != 1:
                    continue
                if best is None or cand.last_used < best.last_used:
                    best = cand
            if best is None:
                break
            del best.parent.children[best.key]
            self.allocator.unref(best.block)
            self.tree_blocks -= 1
            self.evicted_blocks += 1
            freed += 1
        return freed

    # ------------------------------------------------------------- telemetry
    def stats(self):
        return {
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate_pct": round(100.0 * self.hits / self.lookups, 2)
            if self.lookups else 0.0,
            "cached_tokens": self.cached_tokens,
            "tree_blocks": self.tree_blocks,
            "inserted_blocks": self.inserted_blocks,
            "evicted_blocks": self.evicted_blocks,
            "cow_copies": self.cow_copies,
        }
