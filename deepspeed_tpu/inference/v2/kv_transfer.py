"""KV-block handoff for disaggregated prefill/decode serving.

A prefill-role replica runs chunked prefill to the last prompt token,
posts the first generated token, and then hands the sequence off
instead of decoding: this module serializes the sequence's paged KV
blocks plus its descriptor state (token history, ``cached_len``,
sampling params, the ORIGINAL submit timestamp) into the same
length-prefixed CRC'd wire format the checkpoint hot tier already
uses, streams the payload prefill -> decode, and imports it into the
decode replica's ``BlockedAllocator`` + block table through one jitted
donated scatter program (the ``_get_cow_copy`` idiom — see
``engine_v2.InferenceEngineV2.import_handoff``).

Wire format::

    [4s magic "DSKV"][u16 version][u64 body_len][u32 crc32(body)][body]

where ``body`` is a ``serialization.save_file`` image (npz + JSON
header) of the per-layer KV tree ``{"k": [...], "v": [...]}`` sliced
to the blocks the sequence actually wrote, with the descriptor state
riding in ``extra_meta={"handoff": state}``. The inner image carries
its own per-entry CRC manifest, so corruption is detected at BOTH
framing and tensor granularity and surfaces as the typed
:class:`KVWireError` — a corrupt handoff is refused, never imported.

Transports mirror the hot tier's fs/dcn duality:

* :class:`InProcQueueTransport` — an in-process byte queue, the
  tier-1-testable fallback. Single-host multi-replica fleets (and
  every unit test) run on this; sender and receiver share one clock
  domain, so the submit stamp carried for TTFT anchoring is exact.
* :class:`DcnRingTransport` — the payload rides
  ``comm.ring_exchange_bytes`` across slices (the PR-7 DCN path).
  Cross-process ``time.perf_counter`` domains are NOT comparable:
  latency windows anchored on a remote stamp are advisory there
  (counters stay exact); see ``ServingTelemetry.on_handoff_in``.

Failure semantics: the ``kv_stream`` fault point fires once per
payload send and ``kv_import`` once per import, both BEFORE any state
moves — the prefill replica keeps full ownership until the decode
side confirms the import, so a failed stream or import retries next
router round from unchanged state (both points are ``retryable`` in
``fault_injection.BLAST_RADIUS``). A decode-replica death mid-transfer
(``replica_death`` armed at ``Replica.import_handoff``) is handled
above this module: the router re-enqueues the request at the front for
a colocated / re-prefill replay — byte-identical by greedy
construction, since the handoff moves KV bytes and never changes the
program.
"""

import collections
import io
import struct
import zlib

import numpy as np

from ...comm import comm as dist
from ...runtime.checkpoint_engine import serialization as ser
from ...utils import fault_injection

MAGIC = b"DSKV"
WIRE_VERSION = 1

# magic, version, body length, crc32(body)
_HEADER = struct.Struct("<4sHQI")


class KVWireError(ValueError):
    """The payload is not a well-formed handoff image (truncated frame,
    bad magic/version, CRC mismatch, or a KV tree whose layout does not
    match the importing engine's cache). A corrupt handoff is refused
    before any decode-side state changes."""


class KVTransferError(RuntimeError):
    """Transport misuse (receive on an empty queue, DCN transport in a
    single-process world) — a wiring bug, not a data fault."""


# ---------------------------------------------------------------- wire

def pack_handoff(state, kv_tree):
    """Serialize ``(descriptor state, per-layer KV tree)`` into one
    framed byte payload. ``state`` must be JSON-serializable (ints,
    floats, lists, None); ``kv_tree`` leaves are host ndarrays sliced
    to the blocks the sequence wrote."""
    # npz round-trips only numpy-native dtypes: extension dtypes like
    # bfloat16 (kind 'V') come back as raw void bytes, so their names
    # ride the header and unpack_handoff views the bytes back
    flat, _ = ser.flatten_state(kv_tree)
    kv_dtypes = {k: np.asarray(v).dtype.name for k, v in flat.items()
                 if np.asarray(v).dtype.kind == "V"}
    body_io = io.BytesIO()
    ser.save_file(body_io, kv_tree,
                  extra_meta={"handoff": state, "kv_dtypes": kv_dtypes})
    body = body_io.getvalue()
    return _HEADER.pack(MAGIC, WIRE_VERSION, len(body),
                        zlib.crc32(body) & 0xFFFFFFFF) + body


def unpack_handoff(payload):
    """Inverse of :func:`pack_handoff`: verify framing + CRC and return
    ``(state, flat)`` where ``flat`` maps tree paths (``"k/0"``, ...)
    to host arrays. Raises :class:`KVWireError` on any corruption."""
    if len(payload) < _HEADER.size:
        raise KVWireError(
            f"handoff payload truncated: {len(payload)} bytes < "
            f"{_HEADER.size}-byte header")
    magic, version, body_len, crc = _HEADER.unpack_from(payload)
    if magic != MAGIC:
        raise KVWireError(f"bad handoff magic {magic!r}")
    if version != WIRE_VERSION:
        raise KVWireError(
            f"handoff wire version {version} != {WIRE_VERSION}")
    body = payload[_HEADER.size:]
    if len(body) != body_len:
        raise KVWireError(
            f"handoff body length {len(body)} != framed {body_len}")
    if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
        raise KVWireError("handoff body CRC mismatch")
    try:
        flat, header = ser.load_file(io.BytesIO(body))
    except ser.CheckpointCorruptionError as e:
        raise KVWireError(f"handoff tensor image corrupt: {e}") from e
    state = header.get("extra", {}).get("handoff")
    if state is None:
        raise KVWireError("handoff payload carries no descriptor state")
    for k, name in header.get("extra", {}).get("kv_dtypes", {}).items():
        try:
            flat[k] = flat[k].view(np.dtype(name))
        except (KeyError, TypeError) as e:
            raise KVWireError(
                f"handoff dtype map names {k!r}/{name!r} the tensor "
                f"image cannot satisfy: {e}") from e
    return state, flat


# ----------------------------------------------------------- transports

class InProcQueueTransport:
    """In-process byte queue — the tier-1-testable transport (the hot
    tier's ``fs`` analogue). FIFO; ``send`` fires the retryable
    ``kv_stream`` fault point before the payload is enqueued, so an
    injected stream failure moves nothing."""

    def __init__(self):
        self._q = collections.deque()
        self.sent_bytes = 0

    def send(self, payload):
        fault_injection.fire("kv_stream")
        self._q.append(bytes(payload))
        self.sent_bytes += len(payload)

    def recv(self):
        if not self._q:
            raise KVTransferError("recv on empty handoff queue")
        return self._q.popleft()


class DcnRingTransport:
    """Cross-slice transport over ``comm.ring_exchange_bytes`` (the hot
    tier's ``dcn`` analogue). ``send`` is COLLECTIVE — every process
    must call it in the same order; the payload received from the ring
    peer is stashed for the matching ``recv``. Payloads are bounded by
    ``comm.MAX_PAYLOAD_BYTES`` (typed ``CommPayloadError`` beyond it);
    zero-length payloads are legal. Cross-process clock domains make
    remote submit stamps advisory for latency windows — see the module
    docstring."""

    def __init__(self, shift=1):
        self.shift = int(shift)
        self._q = collections.deque()
        self.sent_bytes = 0

    def send(self, payload):
        fault_injection.fire("kv_stream")
        received, _origin = dist.ring_exchange_bytes(
            bytes(payload), shift=self.shift)
        if received is None:
            raise KVTransferError(
                "DcnRingTransport needs a multi-process world "
                "(jax.process_count() > 1); single-host fleets use "
                "InProcQueueTransport")
        self._q.append(received)
        self.sent_bytes += len(payload)

    def recv(self):
        if not self._q:
            raise KVTransferError("recv on empty handoff queue")
        return self._q.popleft()


# ------------------------------------------------------- engine bridge

def export_sequence(engine, uid):
    """Serialize ``uid``'s KV blocks + descriptor state out of
    ``engine`` (the prefill side). The sequence is NOT removed — the
    caller releases it only after the decode side confirms the
    import, so a failed stream retries from unchanged state."""
    state, kv_host = engine.export_handoff(uid)
    return pack_handoff(state, kv_host)


def import_sequence(engine, payload):
    """Import a handoff payload into ``engine`` (the decode side) and
    return the sequence uid. Fires the retryable ``kv_import`` fault
    point BEFORE unpacking — an injected import failure leaves both
    replicas unchanged."""
    fault_injection.fire("kv_import")
    state, flat = unpack_handoff(payload)
    return engine.import_handoff(state, flat)
