"""InferenceEngineV2 — continuous-batching serving over a paged KV cache.

Counterpart of reference ``inference/v2/engine_v2.py:30 InferenceEngineV2``
(FastGen). TPU redesign:
  * The blocked KV cache is ONE device pytree {'k','v'}:
    (L, num_blocks, H_kv, block_size, hd), heads-major; per-sequence block
    tables index it (reference BlockedKVCache, kv_cache.py:40). Heads
    shard over 'tensor'.
  * Two compiled programs replace most of the ragged kernel zoo: a
    per-bucket prefill (one sequence, causal over its prompt, KV scattered
    into its blocks) and a fixed-shape decode (whole batch, one token
    each) whose attention is the Pallas paged kernel
    (ops/pallas/paged_attention.py) reading K/V straight through the
    block table — the blocked_flash role. Fixed shapes mean exactly two
    XLA compilations per bucket — the CUDA-graph-like property FastGen
    gets from its kernel design.
  * Scheduling (reference DSStateManager + the put/schedule loop in
    mii/ragged batching): admit pending requests while slots+blocks allow,
    prefill them, then batched decode steps; sequences retire on EOS or
    max_new_tokens and their blocks return to the free list immediately —
    the continuous-batching property.
"""

from collections import deque
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...utils import groups
from ...utils.groups import TopologyConfig
from ...utils.logging import log_dist
from ..utils import shard_params
from .ragged import DSStateManager, RaggedBatchWrapper


@dataclass
class RaggedInferenceEngineConfig:
    """Reference config_v2.py RaggedInferenceEngineConfig (condensed)."""
    dtype: str = "bfloat16"
    tensor_parallel: int = 1
    # EP-sharded MoE serving (reference module_inject/layers.py EP+TP
    # inference MoE): experts shard over the 'expert' mesh axis in the
    # decode/prefill programs (Mixtral partition_specs put moe_w* on
    # ('expert', 'tensor'))
    expert_parallel: int = 1
    max_batch_size: int = 8          # concurrent sequences
    kv_block_size: int = 64
    num_kv_blocks: int = 0           # 0 = auto from max_seq_len * max_batch
    prompt_bucket: int = 64
    temperature: float = 0.0         # 0 = greedy
    top_k: int = 0
    seed: int = 0
    # decode steps fused into one device program (host sync + dispatch
    # amortize over this many tokens; scheduling granularity coarsens)
    decode_steps_per_dispatch: int = 8
    # Dynamic SplitFuse (reference blogs/deepspeed-fastgen §3B): > 0 =
    # prompts stream through fixed-size chunks of this many tokens,
    # FUSED with the running decodes in one program per dispatch — long
    # prompts neither stall running decodes (no head-of-line blocking)
    # nor compile per-length bucket programs. 0 = legacy bucketed
    # whole-prompt prefill.
    splitfuse_tokens: int = 0
    # ZeRO-Inference weight-only int8 (reference README.md:30,
    # inference/quantization/): block weights live in HBM as int8 +
    # per-channel scales, dequantized one layer at a time in-program —
    # ~2x weight-capacity over bf16, serving models bf16 cannot fit
    quantize_weights: bool = False
    # Fused weight-only low-precision serving (W8A16 / W4A16): the
    # param pool is quantized ONCE at engine build (per-output-channel
    # scales; int4 packs two codes per byte along the contracted dim)
    # and the FFN weights stay quantized through the paged programs —
    # dequant happens inside the matmul kernels' flush epilogue
    # (ops/pallas/mlp_matmul.wq_matmul, grouped_matmul.grouped_swiglu_wq)
    # so HLO never materializes a dequantized weight tensor.
    #   "auto" (default): resolves OFF on a cold cache — every compiled
    #     program stays byte-identical to weight_quant=False. (Reserved
    #     for a measured HBM-pressure heuristic; today auto == off.)
    #   "int8" / "int4" force W8A16 / W4A16. False forces off.
    # Distinct from quantize_weights (ZeRO-Inference capacity mode):
    # that path dequantizes whole layers in-program; this one keeps the
    # FFN weights quantized end-to-end for bandwidth. When both are
    # set, weight_quant wins.
    weight_quant: object = "auto"
    # ZeRO-Inference KV host offload (reference README.md:30 "and
    # KV-cache offload"): the logical block space lives in host RAM,
    # the device holds an LRU-cached pool of device_kv_blocks slots;
    # decode dispatches run in groups whose working set fits the pool,
    # with the next group's H2D uploads prefetched under the current
    # group's compute (inference/v2/kv_offload.py)
    kv_host_offload: bool = False
    device_kv_blocks: int = 0        # required > 1 when kv_host_offload
    # Pallas paged-attention kernels on the serving hot path (the
    # reference's ragged_ops blocked_flash role): governs BOTH the
    # decode step and the SplitFuse chunk/prefill programs.
    #   "auto" (default): the autotune winner cache's measured choice
    #     per decode-shape bucket; a cold cache keeps the proven
    #     defaults (decode kernel everywhere; chunk kernel on TPU,
    #     dense-gather elsewhere).
    #   True/False force the kernel / the dense-gather parity fallback.
    # ALiBi model families keep the decode kernel regardless (the dense
    # fallback lacks the falcon bf16-quantized bias variant).
    paged_kernel: object = "auto"
    # chunk-kernel q-tile (tokens per grid step): "auto" = the winner
    # cache's tile for this (chunk, blocks, kv-heads, dtype) bucket,
    # int forces
    paged_block_c: object = "auto"
    # Radix-tree prefix cache (inference/v2/prefix_cache.py): finished
    # prompt+generation prefixes keep their KV blocks in a token-keyed
    # tree; later requests sharing a prefix skip its prefill entirely
    # (refcounted blocks, copy-on-write at the divergence point, LRU
    # eviction of cold leaves under admission pressure).
    #   "auto" (default): the winner cache's measured choice for this
    #     pool-shape bucket; a COLD cache keeps the hand-set default —
    #     DISABLED — so the admission path and every compiled program
    #     stay byte-identical to prefix_cache=False.
    #   True/False force. True raises on model/config combinations the
    #   cache cannot serve correctly (sliding-window attention, KV host
    #   offload); "auto" resolves them off silently.
    prefix_cache: object = "auto"
    # cap on tree-held blocks (0 = bounded only by the pool)
    prefix_cache_blocks: int = 0
    # minimum matched FULL blocks for a hit to be taken ("auto" = the
    # winner cache's measured knee; below it, scheduling + CoW overhead
    # beats the skipped prefill). Cold default: 1 block.
    prefix_cache_min_match: object = "auto"
    # Draft-model speculative decoding (ROADMAP 1(b)): a narrow draft
    # model proposes ``spec_k`` tokens per greedy sequence per round
    # and the target verifies all k+1 positions in ONE batched pass
    # riding the split-fuse chunk kernel; greedy acceptance keeps the
    # output streams byte-identical to plain decode. The OPT-IN is the
    # ``draft_model`` argument to the engine constructor — with no
    # draft model, scheduling and every compiled program are unchanged
    # whatever these knobs say (the PR 13 cold-cache discipline).
    #   spec_draft: "auto" (the winner cache's choice for this pool
    #     bucket; cold default ON once a draft model is present) |
    #     True (raises without a draft model, or under kv_host_offload
    #     — the draft pool has no offload tier) | False
    #   spec_k: "auto" (winner cache; cold default 4) | int >= 1
    spec_draft: object = "auto"
    spec_k: object = "auto"
    # serving-side autotune dispatch state, applied COMPLETE at engine
    # construction and at this engine's program traces ("" = env/default
    # resolution — DSTPU_AUTOTUNE, default cache_only; an earlier
    # engine's explicit setting never leaks in): off | cache_only |
    # on_first_use | search, and the winner-cache file path
    # ("" = DSTPU_AUTOTUNE_CACHE / default path). Same convention as
    # the training engine's ``autotune`` config block: dispatch state
    # is process-global and the last engine to construct (or, for v2,
    # to trace) owns it — a process mixing engines with DIFFERENT
    # explicit autotune settings should give each its own process.
    autotune_mode: str = ""
    autotune_cache: str = ""
    # per-request TTFT/TPOT accounting (monitor/telemetry.py
    # ServingTelemetry): bounded sample windows, dispatch-amortized
    # TPOT; with a monitor passed to the engine, Serve/Telemetry/*
    # events flow through the same MonitorMaster fan-out as training,
    # every telemetry_interval completed requests
    telemetry: bool = True
    telemetry_interval: int = 32

    def __post_init__(self):
        if self.paged_kernel not in (True, False, "auto"):
            raise ValueError(
                f"paged_kernel must be true|false|'auto', got "
                f"{self.paged_kernel!r}")
        if self.paged_block_c != "auto" and (
                not isinstance(self.paged_block_c, int)
                or self.paged_block_c < 1):
            raise ValueError(
                f"paged_block_c must be 'auto' or a positive int, got "
                f"{self.paged_block_c!r}")
        if self.weight_quant not in (False, "auto", "int8", "int4"):
            raise ValueError(
                f"weight_quant must be false|'auto'|'int8'|'int4', got "
                f"{self.weight_quant!r}")
        if self.prefix_cache not in (True, False, "auto"):
            raise ValueError(
                f"prefix_cache must be true|false|'auto', got "
                f"{self.prefix_cache!r}")
        if self.prefix_cache_min_match != "auto" and (
                not isinstance(self.prefix_cache_min_match, int)
                or isinstance(self.prefix_cache_min_match, bool)
                or self.prefix_cache_min_match < 1):
            raise ValueError(
                f"prefix_cache_min_match must be 'auto' or an int >= 1, "
                f"got {self.prefix_cache_min_match!r}")
        if not isinstance(self.prefix_cache_blocks, int) \
                or isinstance(self.prefix_cache_blocks, bool) \
                or self.prefix_cache_blocks < 0:
            raise ValueError(
                f"prefix_cache_blocks must be an int >= 0, got "
                f"{self.prefix_cache_blocks!r}")
        if self.spec_draft not in (True, False, "auto"):
            raise ValueError(
                f"spec_draft must be true|false|'auto', got "
                f"{self.spec_draft!r}")
        if self.spec_k != "auto" and (
                not isinstance(self.spec_k, int)
                or isinstance(self.spec_k, bool)
                or self.spec_k < 1):
            raise ValueError(
                f"spec_k must be 'auto' or an int >= 1, got "
                f"{self.spec_k!r}")
        if self.prefix_cache is True and self.kv_host_offload:
            raise ValueError(
                "prefix_cache=True is incompatible with kv_host_offload: "
                "tree-held blocks would pin host/device residency the "
                "offload pool cannot track — use one or the other")
        if self.autotune_mode not in ("", "off", "cache_only",
                                      "on_first_use", "search"):
            raise ValueError(
                f"autotune_mode must be ''|off|cache_only|on_first_use|"
                f"search, got {self.autotune_mode!r}")
        if self.splitfuse_tokens < 0:
            raise ValueError(
                f"splitfuse_tokens must be >= 0, got "
                f"{self.splitfuse_tokens}")
        if not isinstance(self.telemetry_interval, int) \
                or self.telemetry_interval < 1:
            raise ValueError(
                f"telemetry_interval must be an int >= 1, got "
                f"{self.telemetry_interval!r}")


@dataclass
class _Request:
    uid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: int = -1
    temperature: float = 0.0
    top_k: int = 0


class InferenceEngineV2:
    """``put(uid, prompt)`` then ``step()`` until ``is_done(uid)``;
    ``get(uid)`` returns the generated tokens."""

    def __init__(self, model, config=None, params=None, topology=None,
                 monitor=None, draft_model=None, draft_params=None,
                 **kwargs):
        if isinstance(config, dict):
            config = RaggedInferenceEngineConfig(**{**config, **kwargs})
        elif config is None:
            config = RaggedInferenceEngineConfig(**kwargs)
        self.config = config
        self.model = model
        # serving-side telemetry: TTFT/TPOT histograms exported through
        # the same MonitorMaster fan-out as training when ``monitor``
        # (a monitor.Monitor / MonitorMaster) is given; always readable
        # via telemetry_snapshot() for serve_bench
        self.telemetry = None
        if config.telemetry:
            from ...monitor.telemetry import ServingTelemetry
            self.telemetry = ServingTelemetry(
                monitor=monitor, interval=config.telemetry_interval)
        mcfg = model.config
        self.max_seq_len = mcfg.max_seq_len

        # fused weight-only quant mode for this engine ("auto" resolves
        # OFF — cold-cache programs byte-identical to weight_quant=False;
        # reserved for a measured HBM-pressure heuristic)
        self._weight_quant = (
            config.weight_quant if config.weight_quant in ("int8", "int4")
            else False)

        # serving-side measured dispatch: apply the engine's autotune
        # fields + paged-kernel knobs once now, and again at the top of
        # every program TRACE (_install_trace_state) — the knobs live
        # on the (possibly shared) model object and in process-global
        # dispatch state, and traces are lazy, so without the re-install
        # a later-constructed engine sharing this model would silently
        # steer this engine's (re-)traces
        self._install_trace_state()

        if topology is None:
            topology = groups.initialize(TopologyConfig(
                tensor_parallel_size=config.tensor_parallel,
                expert_parallel_size=config.expert_parallel))
        self.topology = topology
        self.mesh = topology.mesh

        BS = config.kv_block_size
        self.max_blocks_per_seq = -(-self.max_seq_len // BS)
        num_blocks = config.num_kv_blocks or (
            1 + config.max_batch_size * self.max_blocks_per_seq)
        self.state_mgr = DSStateManager(
            num_blocks=num_blocks, block_size=BS,
            max_batch=config.max_batch_size,
            max_blocks_per_seq=self.max_blocks_per_seq)

        # radix-tree prefix cache over the block pool (host-side
        # scheduling policy: the compiled programs never change, so
        # disabled == byte-identical to the pre-cache engine)
        self.prefix_cache = None
        pc_on, pc_min_match, pc_watermark = self._resolve_prefix_cache(
            mcfg, num_blocks)
        if pc_on:
            from .prefix_cache import PrefixCache
            self.prefix_cache = PrefixCache(
                self.state_mgr.allocator, BS,
                min_match_blocks=pc_min_match,
                max_blocks=config.prefix_cache_blocks,
                evict_watermark_pct=pc_watermark)
            self.state_mgr.prefix_cache = self.prefix_cache
            if self.telemetry is not None:
                self.telemetry.attach_prefix_cache(self.prefix_cache)

        dtype = jnp.dtype(config.dtype)
        self.dtype = dtype
        self.params, self.param_shardings = shard_params(
            model, self.mesh, dtype, params=params, seed=config.seed,
            topology=topology,
            quantize=self._weight_quant or config.quantize_weights)
        cache_sh = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), model.paged_cache_specs(),
            is_leaf=lambda x: isinstance(x, P))
        self._cache_sh = cache_sh
        self.kv_pool = None
        device_blocks = num_blocks
        if config.kv_host_offload:
            if config.device_kv_blocks < 2:
                raise ValueError(
                    "kv_host_offload requires device_kv_blocks >= 2")
            from .kv_offload import OffloadKVPool
            device_blocks = config.device_kv_blocks
            self.kv_pool = OffloadKVPool(
                model, num_blocks, device_blocks, BS, dtype,
                cache_sh, self.mesh)
        with jax.set_mesh(self.mesh):
            self.cache = jax.jit(
                lambda: model.init_paged_cache(device_blocks, BS,
                                               dtype=dtype),
                out_shardings=cache_sh)()

        # --- draft-model speculative decoding (ROADMAP 1(b)) ---
        # own allocator + cache pool over the same block geometry; the
        # draft is narrow, so the pool is a small fraction of the
        # target's. With no draft model nothing below exists and the
        # engine is byte-identical to the pre-speculation engine.
        self.draft_model = None
        self._spec_k = 0
        self._spec_floor = 0.0
        if config.spec_draft is True and draft_model is None:
            raise ValueError(
                "spec_draft=True requires a draft model (pass "
                "draft_model= to the engine)")
        if draft_model is not None and config.kv_host_offload:
            if config.spec_draft is True:
                raise ValueError(
                    "spec_draft=True is incompatible with "
                    "kv_host_offload: the draft pool has no offload "
                    "tier to keep residency honest — use one or the "
                    "other")
            draft_model = None            # "auto"/False resolve off
        if draft_model is not None:
            from .speculative import resolve_spec
            spec_on, spec_k, spec_floor = resolve_spec(
                config.spec_draft, config.spec_k,
                B=config.max_batch_size, NB=num_blocks, BS=BS,
                dtype=config.dtype)
            if spec_on:
                if draft_model.config.vocab_size != mcfg.vocab_size:
                    raise ValueError(
                        f"draft/target vocab mismatch: "
                        f"{draft_model.config.vocab_size} vs "
                        f"{mcfg.vocab_size} — speculation verifies "
                        f"draft token ids against target argmax, the "
                        f"vocabularies must be the same")
                from .blocked_allocator import BlockedAllocator
                self.draft_model = draft_model
                self._spec_k = spec_k
                self._spec_floor = spec_floor
                self.state_mgr.draft_allocator = BlockedAllocator(
                    num_blocks)
                self.draft_params, self._draft_param_sh = shard_params(
                    draft_model, self.mesh, dtype, params=draft_params,
                    seed=config.seed + 1, topology=topology)
                self._draft_cache_sh = jax.tree.map(
                    lambda s: NamedSharding(self.mesh, s),
                    draft_model.paged_cache_specs(),
                    is_leaf=lambda x: isinstance(x, P))
                with jax.set_mesh(self.mesh):
                    self.draft_cache = jax.jit(
                        lambda: draft_model.init_paged_cache(
                            num_blocks, BS, dtype=dtype),
                        out_shardings=self._draft_cache_sh)()
                self._propose_jit = None
                self._verify_jit = None
                self._draft_chunk_jit = None
                self._install_trace_state()   # now covers the draft

        self._pending = deque()
        self._results = {}            # uid -> generated tokens (finished)
        self._rng = jax.random.key(config.seed + 23)
        self._prefill_jit = None
        self._decode_jit = None
        self._splitfuse_jit = None
        self._chunk_jit = None        # chunk-only (no decoders running)
        self._cow_jit = None          # prefix-cache partial-tail copy
        self._prefill_q = deque()     # uids mid-chunked-prefill (SplitFuse)
        # disaggregated prefill/decode handoff (kv_transfer.py): uids
        # parked out of every decode dispatch until their KV streams to
        # a decode replica, plus the export gather / donated import
        # scatter programs (lazy, the _get_cow_copy idiom)
        self._decode_hold = set()
        self._kv_export_jit = None
        self._kv_import_jit = None
        self._uid_next = 0
        log_dist(
            f"v2 engine ready: tp={config.tensor_parallel} blocks="
            f"{num_blocks}x{BS} max_batch={config.max_batch_size}",
            ranks=[0])

    # ------------------------------------------------------------- requests
    def put(self, prompt, max_new_tokens=32, eos_token_id=-1, uid=None,
            temperature=None, top_k=None, klass=0):
        """Queue a generation request (sampling params per request, like
        FastGen; None = the engine-config defaults; ``klass`` = the
        router's request class, keying the per-class acceptance EMAs in
        serving telemetry). Returns its uid."""
        if uid is None:
            uid = self._uid_next
            self._uid_next += 1
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt) + max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt+max_new={total} exceeds "
                f"model max_seq_len={self.max_seq_len}")
        mgr = self.state_mgr
        if mgr.blocks_needed(total) > mgr.allocator.total_blocks:
            raise ValueError(
                f"request needs {mgr.blocks_needed(total)} KV blocks but "
                f"the pool only has {mgr.allocator.total_blocks}; raise "
                "num_kv_blocks")
        if self.kv_pool is not None \
                and mgr.blocks_needed(total) > self.kv_pool.D - 1:
            raise ValueError(
                f"request needs {mgr.blocks_needed(total)} KV blocks but "
                f"the device pool holds {self.kv_pool.D - 1} (+scratch); "
                "a single sequence's working set must fit on device — "
                "raise device_kv_blocks")
        self._pending.append(_Request(
            uid, prompt, max_new_tokens, eos_token_id,
            temperature=(self.config.temperature if temperature is None
                         else float(temperature)),
            top_k=(self.config.top_k if top_k is None else int(top_k))))
        if self.telemetry is not None:
            # TTFT clock starts at submit; the class keys acceptance EMAs
            self.telemetry.on_submit(uid, klass=klass)
        return uid

    def is_done(self, uid):
        if uid in self._results:
            return True
        if any(r.uid == uid for r in self._pending):
            return False
        if uid in self.state_mgr._seqs:
            return False
        raise KeyError(f"unknown uid {uid} (never submitted or already "
                       "fetched with get())")

    def get(self, uid, flush=True):
        """Generated tokens for a finished request (``flush`` forgets the
        result afterwards; in-flight requests return their tokens so far)."""
        if uid in self._results:
            return self._results.pop(uid) if flush else self._results[uid]
        if any(r.uid == uid for r in self._pending):
            return np.zeros((0,), np.int32)  # queued, nothing yet
        try:
            seq = self.state_mgr.get_sequence(uid)
        except KeyError:
            raise KeyError(
                f"unknown uid {uid} (never submitted, or already fetched "
                f"with get(flush=True))") from None
        return np.asarray(seq.generated, np.int32)

    def cancel(self, uid):
        """Withdraw a request (the router's deadline/shed path): queued
        requests are dropped; in-flight sequences are flushed through
        the prefix-cache-safe unref path — NO tree insert, because
        cache contents past the prefill frontier are unverified — so
        the pool accounting closes; a finished-but-unfetched result is
        forgotten. Serving telemetry excludes the request from the
        TTFT/TPOT windows (``on_reject``): a cancelled request has no
        dispatch boundary to amortize against and would poison the
        percentiles. Returns True when the uid was known."""
        self._decode_hold.discard(uid)
        for i, r in enumerate(self._pending):
            if r.uid == uid:
                del self._pending[i]
                if self.telemetry is not None:
                    self.telemetry.on_reject(uid)
                return True
        if uid in self._results:
            # finished before the cancel landed: telemetry already
            # counted the completion; just forget the result
            del self._results[uid]
            return True
        if uid not in self.state_mgr._seqs:
            return False
        try:
            self._prefill_q.remove(uid)
        except ValueError:
            pass
        seq = self.state_mgr.get_sequence(uid)
        if seq.cow is not None:
            # admitted but the CoW slice copy never ran: drop the
            # claim's temporary source ref before the unref sweep
            self.state_mgr.cow_complete(seq)
        if self.kv_pool is not None:
            self.kv_pool.release(seq.blocks)
        self.state_mgr.flush(uid)
        if self.telemetry is not None:
            self.telemetry.on_reject(uid)
        return True

    @property
    def has_work(self):
        return bool(self._pending) or self.state_mgr.n_active > 0

    # ------------------------------------------------------------- programs
    def _resolve_prefix_cache(self, mcfg, num_blocks):
        """Resolve (enabled, min_match_blocks, evict_watermark_pct) for
        the prefix cache. Model/config combinations the cache cannot
        serve correctly refuse LOUDLY when forced on and resolve off
        under "auto"; the "auto" spelling consults the winner cache for
        this pool-shape bucket with cold-cache defaults equal to the
        hand-set values (disabled, min-match 1, on-demand eviction), so
        a cold-cache engine is byte-identical to prefix_cache=False."""
        cfg = self.config
        windows = tuple(getattr(mcfg, "attn_layer_windows", ()) or ())
        if any(windows):
            if cfg.prefix_cache is True:
                raise ValueError(
                    "prefix_cache=True on a sliding-window model "
                    "(attn_layer_windows set): a cached block's KV is "
                    "position-valid only inside each layer's window, so "
                    "reusing it under a shifted suffix serves wrong "
                    "attention — disable prefix_cache for this model")
            return False, 1, 0
        if cfg.prefix_cache is False or cfg.kv_host_offload:
            # explicit off, or offload (True+offload raised in config
            # validation; "auto" resolves off)
            return False, 1, 0
        from .prefix_cache import resolve_prefix_cache
        return resolve_prefix_cache(
            cfg.prefix_cache, cfg.prefix_cache_min_match,
            B=cfg.max_batch_size, NB=num_blocks,
            BS=cfg.kv_block_size, dtype=cfg.dtype)

    def _install_trace_state(self):
        """(Re)apply THIS engine's kernel/autotune knobs: the model
        attributes the paged paths read and the process dispatch state
        ("" = env/default; an earlier engine's explicit mode or cache
        path never leaks in). Called in __init__ and — because jax
        re-traces lazily per shape bucket — at trace time inside every
        program, so engines sharing one model object each trace under
        their own config (pure python side effect; nothing lands in
        the compiled program)."""
        from ...autotuning import kernel_dispatch
        kernel_dispatch.configure_serving(
            mode=self.config.autotune_mode,
            cache_path=self.config.autotune_cache)
        self.model._paged_kernel = self.config.paged_kernel
        self.model._paged_block_c = self.config.paged_block_c
        # fused W8A16/W4A16: _layer_slice keeps the FFN weights
        # quantized (model._WQ_KEEP) and _mlp routes them through the
        # fused-dequant kernels; False = every path dequantizes whole
        # slices as before
        self.model._weight_quant_fused = self._weight_quant
        draft = getattr(self, "draft_model", None)
        if draft is not None:
            # the draft traces under the same kernel knobs but never
            # under fused weight-quant (its params shard unquantized)
            draft._paged_kernel = self.config.paged_kernel
            draft._paged_block_c = self.config.paged_block_c
            draft._weight_quant_fused = False

    @staticmethod
    def _sample_per_slot(logits, rng, temps, top_ks, all_greedy=False):
        """Vectorized per-request sampling (FastGen carries sampling
        params per sequence): logits (B, V), temps (B,) f32 (0 = greedy),
        top_ks (B,) int32 (0 = off). Traced — one program serves any mix
        of greedy and sampled requests."""
        B, V = logits.shape
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if all_greedy:
            # static fast path: no full-vocab sort/categorical in the
            # compiled program when every live request is greedy
            return greedy
        lt = logits / jnp.maximum(temps, 1e-6)[:, None]
        # per-row top-k: mask everything below each row's k-th largest
        sorted_desc = -jnp.sort(-lt, axis=-1)
        kth_idx = jnp.clip(top_ks - 1, 0, V - 1)[:, None]
        kth_val = jnp.take_along_axis(sorted_desc, kth_idx, axis=1)
        masked = jnp.where((top_ks[:, None] > 0) & (lt < kth_val),
                           -1e30, lt)
        sampled = jax.random.categorical(rng, masked, axis=-1).astype(
            jnp.int32)
        return jnp.where(temps > 0, sampled, greedy)

    def _get_prefill(self):
        # one jit object; jax specializes per T_pad bucket shape itself
        if self._prefill_jit is None:
            model = self.model

            def prefill(params, cache, ids, tb, to, length, rng, temp,
                        top_k, all_greedy):
                self._install_trace_state()
                logits, cache = model.apply_paged_prefill(
                    params, ids, cache, tb, to, length)
                tok = self._sample_per_slot(logits, rng, temp, top_k,
                                            all_greedy)
                return tok, cache

            self._prefill_jit = jax.jit(
                prefill, donate_argnums=(1,), static_argnums=(9,),
                in_shardings=(self.param_shardings, self._cache_sh,
                              None, None, None, None, None, None, None),
                out_shardings=(None, self._cache_sh))
        return self._prefill_jit

    def _get_decode(self):
        if self._decode_jit is None:
            model = self.model
            n = max(1, self.config.decode_steps_per_dispatch)

            def decode(params, cache, tokens, lengths, tables, rng,
                       temps, top_ks, all_greedy):
                self._install_trace_state()
                # n decode steps in ONE program: the sampled token feeds
                # the next step in-trace, so the host round trip (token
                # sync + batch re-upload + dispatch latency) amortizes
                # over n tokens. Unrolled (not lax.scan): the cache pools
                # must stay per-layer donated buffers updated in place —
                # carrying them through a scan defensively copies them.
                all_toks = []
                for t in range(n):
                    logits, cache = model.apply_paged_decode(
                        params, tokens, lengths, cache, tables)
                    tokens = self._sample_per_slot(
                        logits, jax.random.fold_in(rng, t), temps,
                        top_ks, all_greedy)
                    lengths = lengths + 1
                    all_toks.append(tokens)
                return jnp.stack(all_toks), cache

            self._decode_jit = jax.jit(
                decode, donate_argnums=(1,), static_argnums=(8,),
                in_shardings=(self.param_shardings, self._cache_sh,
                              None, None, None, None, None, None),
                out_shardings=(None, self._cache_sh))
        return self._decode_jit

    def _get_splitfuse(self):
        """ONE fused fixed-shape program per dispatch: a C-token prompt
        chunk for the head-of-queue prefilling sequence PLUS n decode
        steps for every running sequence — the Dynamic SplitFuse
        composition (reference blogs/deepspeed-fastgen §3B; the ragged
        kernels' role). Shapes are static (C, B, MB), so exactly one
        compilation serves every prompt length and batch mix."""
        if self._splitfuse_jit is None:
            model = self.model
            n = max(1, self.config.decode_steps_per_dispatch)

            def fused(params, cache, c_ids, c_tb, c_to, c_start, c_len,
                      c_table, c_temp, c_topk, d_tokens, d_lengths,
                      d_tables, rng, d_temps, d_topks, all_greedy):
                self._install_trace_state()
                c_logits, cache = model.apply_paged_chunk(
                    params, c_ids, cache, c_tb, c_to, c_start, c_len,
                    c_table)
                c_tok = self._sample_per_slot(
                    c_logits, jax.random.fold_in(rng, 7919), c_temp,
                    c_topk, all_greedy)
                toks = []
                for t in range(n):
                    logits, cache = model.apply_paged_decode(
                        params, d_tokens, d_lengths, cache, d_tables)
                    d_tokens = self._sample_per_slot(
                        logits, jax.random.fold_in(rng, t), d_temps,
                        d_topks, all_greedy)
                    d_lengths = d_lengths + 1
                    toks.append(d_tokens)
                return c_tok, jnp.stack(toks), cache

            self._splitfuse_jit = jax.jit(
                fused, donate_argnums=(1,), static_argnums=(16,),
                in_shardings=(self.param_shardings, self._cache_sh)
                + (None,) * 14,
                out_shardings=(None, None, self._cache_sh))
        return self._splitfuse_jit

    def _get_chunk_only(self):
        """Chunk program WITHOUT the fused decode steps — used when no
        sequence is decoding (e.g. a long prompt arriving at an idle
        engine), so prefill never pays scratch-write decode forwards."""
        if self._chunk_jit is None:
            model = self.model

            def chunk(params, cache, c_ids, c_tb, c_to, c_start, c_len,
                      c_table, c_temp, c_topk, rng, all_greedy):
                self._install_trace_state()
                c_logits, cache = model.apply_paged_chunk(
                    params, c_ids, cache, c_tb, c_to, c_start, c_len,
                    c_table)
                c_tok = self._sample_per_slot(
                    c_logits, jax.random.fold_in(rng, 7919), c_temp,
                    c_topk, all_greedy)
                return c_tok, cache

            self._chunk_jit = jax.jit(
                chunk, donate_argnums=(1,), static_argnums=(11,),
                in_shardings=(self.param_shardings, self._cache_sh)
                + (None,) * 9,
                out_shardings=(None, self._cache_sh))
        return self._chunk_jit

    def _get_cow_copy(self):
        """Prefix-cache copy-on-write: copy the first ``plen`` token
        rows of block ``src`` into block ``dst`` across every layer's
        K and V pools. A shared (refcount > 1) block is never written in
        place — the sequence diverging inside it gets its matched slice
        copied into a fresh block, then prefill resumes there. Block ids
        and the slice length are traced operands, so every divergence
        point shares ONE compiled program."""
        if self._cow_jit is None:
            BS = self.config.kv_block_size

            def cow(cache, src, dst, plen):
                keep = (jnp.arange(BS) < plen)[None, :, None]
                return jax.tree.map(
                    lambda p: p.at[dst].set(
                        jnp.where(keep, p[src], p[dst])), cache)

            self._cow_jit = jax.jit(
                cow, donate_argnums=(0,),
                in_shardings=(self._cache_sh, None, None, None),
                out_shardings=self._cache_sh)
        return self._cow_jit

    def _get_draft_chunk(self):
        """Draft-side catch-up chunk: ingest a span of COMMITTED tokens
        into the draft cache — the draft's prefill. It replays the real
        token history from the descriptor, so prefix-cache-served
        prompt tokens (which the target never recomputed) and any
        plain-decoded stretch before speculation engaged are covered by
        the same program. Logits are discarded — proposals only come
        from the propose program."""
        if self._draft_chunk_jit is None:
            draft = self.draft_model

            def dchunk(params, cache, ids, tb, to, start, tlen, table):
                self._install_trace_state()
                _logits, cache = draft.apply_paged_chunk(
                    params, ids, cache, tb, to, start, tlen, table)
                return cache

            self._draft_chunk_jit = jax.jit(
                dchunk, donate_argnums=(1,),
                in_shardings=(self._draft_param_sh, self._draft_cache_sh)
                + (None,) * 6,
                out_shardings=self._draft_cache_sh)
        return self._draft_chunk_jit

    def _get_propose(self):
        """ONE program: a re-ingest step + ``spec_k`` greedy draft
        decode steps, each proposal feeding the next in-trace (the
        draft-side analogue of the fused decode dispatch). The
        re-ingest writes the second-to-last committed token's KV at its
        own position: after a fully-accepted round that position holds
        nothing (the draft never saw its own last proposal fed back),
        and after a partial round the rewrite is byte-idempotent — so
        the draft needs no per-round gap bookkeeping."""
        if self._propose_jit is None:
            draft = self.draft_model
            k = self._spec_k

            def propose(params, cache, tokens2, lengths, tables):
                self._install_trace_state()
                _lg, cache = draft.apply_paged_decode(
                    params, tokens2[:, 0], lengths, cache, tables)
                cur = tokens2[:, 1]
                lengths = lengths + 1
                props = []
                for _ in range(k):
                    logits, cache = draft.apply_paged_decode(
                        params, cur, lengths, cache, tables)
                    # only greedy sequences speculate, so the draft is
                    # always greedy too
                    cur = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    lengths = lengths + 1
                    props.append(cur)
                return jnp.stack(props, axis=1), cache

            self._propose_jit = jax.jit(
                propose, donate_argnums=(1,),
                in_shardings=(self._draft_param_sh, self._draft_cache_sh,
                              None, None, None),
                out_shardings=(None, self._draft_cache_sh))
        return self._propose_jit

    def _get_verify(self):
        """Batched verify: all k+1 positions of every speculating slot
        in ONE pass through the split-fuse chunk kernel
        (apply_paged_verify), returning the target's greedy next token
        at every position — the host takes the longest accepted prefix
        plus the bonus token."""
        if self._verify_jit is None:
            model = self.model

            def verify(params, cache, tokens, lengths, tables):
                self._install_trace_state()
                logits, cache = model.apply_paged_verify(
                    params, tokens, lengths, cache, tables)
                return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                        cache)

            self._verify_jit = jax.jit(
                verify, donate_argnums=(1,),
                in_shardings=(self.param_shardings, self._cache_sh,
                              None, None, None),
                out_shardings=(None, self._cache_sh))
        return self._verify_jit

    def _apply_cow(self, seq):
        fn = self._get_cow_copy()
        src, dst, plen = seq.cow
        with jax.set_mesh(self.mesh):
            self.cache = fn(self.cache, np.int32(src), np.int32(dst),
                            np.int32(plen))
        self.state_mgr.cow_complete(seq)   # drops the claim ref on src

    # -------------------------------- disaggregated prefill/decode handoff
    def hold_decode(self, uid):
        """Park ``uid`` out of every decode dispatch. A prefill-role
        replica holds each sequence here once submitted: it runs
        chunked prefill to the last prompt token, posts the first
        generated token, and then waits for its KV handoff to a decode
        replica instead of decoding locally."""
        self._decode_hold.add(uid)

    def release_decode_hold(self, uid=None):
        """Release one park (or all of them with ``uid=None`` — the
        router flips a fleet back to colocated when its last decode
        replica dies, and every held sequence must resume decoding
        HERE rather than deadlock)."""
        if uid is None:
            self._decode_hold.clear()
        else:
            self._decode_hold.discard(uid)

    def _get_kv_export(self):
        """Handoff export: gather one sequence's KV block payloads out
        of the paged cache in ONE compiled program. The block-id vector
        is a traced operand padded to the per-sequence table shape, so
        every handoff shares the program. NOT donated — the prefill
        replica keeps serving from its cache, and export must be
        repeatable for stream-failure retries."""
        if self._kv_export_jit is None:
            def gather(cache, src):
                return jax.tree.map(lambda p: p[src], cache)

            self._kv_export_jit = jax.jit(
                gather, in_shardings=(self._cache_sh, None))
        return self._kv_export_jit

    def _get_kv_import(self):
        """Handoff import: scatter received block payloads into freshly
        allocated block ids in place — the donated ``_get_cow_copy``
        idiom, so the import never copies the whole cache. Pad rows of
        the destination vector map to block 0, the scratch block, which
        every dispatch overwrites by design."""
        if self._kv_import_jit is None:
            def scatter(cache, kv, dst):
                return jax.tree.map(
                    lambda p, s: p.at[dst].set(s), cache, kv)

            self._kv_import_jit = jax.jit(
                scatter, donate_argnums=(0,),
                in_shardings=(self._cache_sh, None, None),
                out_shardings=self._cache_sh)
        return self._kv_import_jit

    def export_handoff(self, uid):
        """Export half of the handoff: -> (descriptor state dict, host
        KV tree sliced to the blocks the sequence wrote). The sequence
        is NOT removed — :meth:`release_handoff` runs only after the
        decode side confirms the import, so a failed stream retries
        from unchanged state.

        Byte-identity is by construction: the gathered blocks hold
        positions ``0..seen_tokens-2`` — exactly the cache state a
        colocated decode dispatch would attend, because the last
        generated token's KV is written by the decode step that
        consumes it."""
        if self.kv_pool is not None:
            raise RuntimeError(
                "KV handoff is incompatible with kv_host_offload: "
                "block payloads live in the host pool, not the device "
                "cache — run prefill-role replicas without offload")
        mgr = self.state_mgr
        seq = mgr.get_sequence(uid)
        if not seq.generated:
            raise RuntimeError(
                f"uid {uid} has no first token yet — only "
                f"prefill-complete sequences hand off")
        n = mgr.blocks_needed(seq.seen_tokens - 1)
        src = np.zeros((self.max_blocks_per_seq,), np.int32)
        src[:len(seq.blocks)] = seq.blocks
        with jax.set_mesh(self.mesh):
            kv = self._get_kv_export()(self.cache, src)
        kv_host = jax.tree.map(lambda a: np.asarray(a)[:n], kv)
        t_submit = None
        klass = 0
        if self.telemetry is not None:
            t_submit = self.telemetry.submit_stamp(uid)
            klass = self.telemetry.klass_of(uid)
        state = {
            "uid": int(uid),
            "prompt": [int(t) for t in seq.prompt],
            "generated": [int(t) for t in seq.generated],
            "cached_len": int(seq.cached_len),
            "max_new_tokens": int(seq.max_new_tokens),
            "eos_token_id": int(seq.eos_token_id),
            "temperature": float(seq.temperature),
            "top_k": int(seq.top_k),
            "klass": int(klass),
            "t_submit": t_submit,
        }
        return state, kv_host

    def import_handoff(self, state, kv_flat):
        """Import half of the handoff: rebuild the wire's KV tree
        against this engine's cache template, validate the layout,
        allocate the sequence's full budget from THIS pool, scatter the
        received payloads in one donated program, and bind the
        descriptor straight into the decode batch
        (``prefill_offset = len(prompt)`` — every prompt position's KV
        just arrived). Serving telemetry registers the request anchored
        at the ORIGINAL submit stamp. Returns the uid."""
        from ...runtime.checkpoint_engine import serialization as ser
        from .kv_transfer import KVWireError
        if self.kv_pool is not None:
            raise RuntimeError(
                "KV handoff is incompatible with kv_host_offload: "
                "imported blocks would bypass residency tracking — run "
                "decode-role replicas without offload")
        mgr = self.state_mgr
        uid = int(state["uid"])
        if uid in mgr._seqs or uid in self._results:
            raise RuntimeError(f"handoff uid {uid} already live here")
        prompt = np.asarray(state["prompt"], np.int32)
        generated = [int(t) for t in state["generated"]]
        max_new = int(state["max_new_tokens"])
        kv = ser.unflatten_into(
            jax.tree.map(lambda _p: 0, self.cache), kv_flat)
        # layout guard: a gpt2-shaped payload must never scatter into a
        # llama (GQA) cache — per-block shapes and dtypes must match
        # the local cache exactly, and every leaf must carry the same
        # block count
        n_blocks = set()

        def _check(p, s):
            if not hasattr(s, "shape") or s.shape[1:] != p.shape[1:] \
                    or s.dtype != p.dtype:
                raise KVWireError(
                    f"handoff KV layout mismatch: payload block shape "
                    f"{getattr(s, 'shape', None)}/"
                    f"{getattr(s, 'dtype', None)} vs local cache "
                    f"{p.shape}/{p.dtype}")
            n_blocks.add(int(s.shape[0]))
            return p

        jax.tree.map(_check, self.cache, kv)
        if len(n_blocks) != 1:
            raise KVWireError(
                f"handoff KV payload has inconsistent block counts "
                f"across layers: {sorted(n_blocks)}")
        n = n_blocks.pop()
        total = len(prompt) + max_new
        need = mgr.blocks_needed(total)
        if need > self.max_blocks_per_seq or n > need \
                or total > self.max_seq_len:
            raise KVWireError(
                f"handoff sequence needs {need} blocks / {total} "
                f"tokens — beyond this engine's per-sequence capacity")
        if mgr.free_slot() is None or \
                mgr.allocator.available_blocks < need:
            raise RuntimeError(
                "decode replica cannot admit handoff (no free "
                "slot/blocks) — the router must back-pressure "
                "(can_accept) before streaming")
        blocks = mgr.allocator.allocate(need)
        MB = self.max_blocks_per_seq
        dst = np.zeros((MB,), np.int32)     # pads scatter into scratch
        dst[:n] = blocks[:n]

        def _pad(s):
            buf = np.zeros((MB,) + s.shape[1:], s.dtype)
            buf[:n] = s
            return buf

        kv_pad = jax.tree.map(_pad, kv)
        with jax.set_mesh(self.mesh):
            self.cache = self._get_kv_import()(self.cache, kv_pad, dst)
        mgr.admit_imported(
            uid, prompt, generated, max_new, blocks,
            eos_token_id=int(state["eos_token_id"]),
            temperature=float(state["temperature"]),
            top_k=int(state["top_k"]))
        if self.telemetry is not None:
            self.telemetry.on_handoff_in(
                uid, klass=int(state.get("klass", 0)),
                submit_ts=state.get("t_submit"))
        return uid

    def release_handoff(self, uid):
        """The decode side confirmed the import: drop the sequence
        HERE (the prefill side). ``retire`` inserts the verified
        prompt+first-token prefix into the local prefix cache — its KV
        was fully written by this replica's prefill — and releases
        blocks/slot; ``flush`` drops the descriptor without surfacing
        a result; telemetry forgets the request WITHOUT counting a
        rejection, keeping its TTFT sample (the first token was
        produced here) in the window."""
        self._decode_hold.discard(uid)
        self.state_mgr.retire(uid)
        self.state_mgr.flush(uid)
        if self.telemetry is not None:
            self.telemetry.on_handoff_out(uid)

    def _step_splitfuse_chunk(self):
        """Run one fused dispatch: the next chunk of the oldest
        prefilling sequence + n decode steps (chunk-only when nothing is
        decoding). Returns decode (uid, token) pairs. Prefix-cache hits
        ride this path even with SplitFuse off (chunk accounting already
        handles a nonzero start offset); the chunk size then falls back
        to the prompt bucket."""
        mgr = self.state_mgr
        C = self.config.splitfuse_tokens or self.config.prompt_bucket
        uid = self._prefill_q[0]
        seq = mgr.get_sequence(uid)
        off = seq.prefill_offset
        true_len = min(C, len(seq.prompt) - off)
        ids = np.zeros((1, C), np.int32)
        ids[0, :true_len] = seq.prompt[off:off + true_len]
        tb = np.zeros((C,), np.int32)
        to = np.zeros((C,), np.int32)
        fb, fo = mgr.token_placement(seq)
        tb[:true_len] = fb[off:off + true_len]
        to[:true_len] = fo[off:off + true_len]
        table = np.zeros((self.max_blocks_per_seq,), np.int32)
        table[:len(seq.blocks)] = seq.blocks

        if self.kv_pool is not None:
            # offload: chunk-only dispatch over the resident history +
            # destination blocks, then the grouped decode path keeps the
            # running sequences fed (the fused program would need the
            # union working set resident)
            live = seq.blocks[:mgr.blocks_needed(off + true_len)]
            # blocks starting at/after the chunk's first position hold
            # no prior tokens — this dispatch writes them from scratch,
            # so they need slots but no host upload
            first_fresh = -(-off // mgr.block_size)
            self.cache = self.kv_pool.ensure(
                self.cache, live, skip_upload=live[first_fresh:])
            dest = sorted({int(b) for b in tb[:true_len]})
            self._rng, sub = jax.random.split(self._rng)
            fn = self._get_chunk_only()
            with jax.set_mesh(self.mesh):
                c_tok, self.cache = fn(
                    self.params, self.cache, ids,
                    self.kv_pool.translate(tb), to, np.int32(off),
                    np.int32(true_len), self.kv_pool.translate(table),
                    np.asarray([seq.temperature], np.float32),
                    np.asarray([seq.top_k], np.int32), sub,
                    seq.temperature == 0.0)
            self.kv_pool.mark_dirty(dest)
            seq.prefill_offset = off + true_len
            if seq.prefill_offset >= len(seq.prompt):
                self._prefill_q.popleft()
                self._post_token(seq, int(np.asarray(c_tok)[0]))
            return self._step_offload_decode()

        batch = mgr.decode_batch(exclude=self._decode_hold)
        self._rng, sub = jax.random.split(self._rng)
        c_temp = np.asarray([seq.temperature], np.float32)
        c_topk = np.asarray([seq.top_k], np.int32)
        if not batch.active.any():
            fn = self._get_chunk_only()
            with jax.set_mesh(self.mesh):
                c_tok, self.cache = fn(
                    self.params, self.cache, ids, tb, to, np.int32(off),
                    np.int32(true_len), table, c_temp, c_topk, sub,
                    seq.temperature == 0.0)
            toks = np.zeros((0, self.config.max_batch_size), np.int32)
        else:
            all_greedy = (seq.temperature == 0.0
                          and not bool(batch.temps.any()))
            fn = self._get_splitfuse()
            with jax.set_mesh(self.mesh):
                c_tok, toks, self.cache = fn(
                    self.params, self.cache, ids, tb, to, np.int32(off),
                    np.int32(true_len), table, c_temp, c_topk,
                    batch.tokens, batch.lengths, batch.block_tables, sub,
                    batch.temps, batch.top_ks, all_greedy)
            toks = np.asarray(toks)
        seq.prefill_offset = off + true_len
        if seq.prefill_offset >= len(seq.prompt):
            self._prefill_q.popleft()
            self._post_token(seq, int(np.asarray(c_tok)[0]))
        return self._post_decode_tokens(batch, toks)

    # ----------------------------------------------------------------- step
    def _admit_pending(self):
        mgr = self.state_mgr
        bucket = self.config.prompt_bucket
        while self._pending:
            req = self._pending[0]
            if not mgr.can_admit(len(req.prompt), req.max_new_tokens,
                                 prompt=req.prompt):
                break
            self._pending.popleft()
            slot, seq = mgr.admit(req.uid, req.prompt, req.max_new_tokens,
                                  req.eos_token_id,
                                  temperature=req.temperature,
                                  top_k=req.top_k)
            if seq.cow is not None:
                # partial-tail prefix hit: device-copy the matched slice
                # into the fresh block before any prefill touches it
                self._apply_cow(seq)
            if self.config.splitfuse_tokens or seq.cached_len:
                # SplitFuse: the prompt streams through chunk dispatches
                # interleaved with decodes — no bucketed prefill here.
                # Prefix-cache hits take the same path regardless: the
                # chunk program's start/true_len accounting is what
                # skips the cached prefix (the bucketed prefill always
                # starts at 0)
                self._prefill_q.append(req.uid)
                continue
            T = len(req.prompt)
            T_pad = -(-max(T, 1) // bucket) * bucket
            ids = np.zeros((1, T_pad), np.int32)
            ids[0, :T] = req.prompt
            tb = np.zeros((T_pad,), np.int32)       # scratch for pads
            to = np.zeros((T_pad,), np.int32)
            tb[:T], to[:T] = mgr.token_placement(seq)
            prompt_blocks = seq.blocks[:mgr.blocks_needed(T)]
            if self.kv_pool is not None:
                # every prompt block is fully written by this dispatch:
                # slots only, no garbage H2D (code-review finding)
                self.cache = self.kv_pool.ensure(
                    self.cache, prompt_blocks, skip_upload=prompt_blocks)
                tb = self.kv_pool.translate(tb)
            self._rng, sub = jax.random.split(self._rng)
            fn = self._get_prefill()
            with jax.set_mesh(self.mesh):
                tok, self.cache = fn(
                    self.params, self.cache, ids, tb, to, np.int32(T), sub,
                    np.asarray([seq.temperature], np.float32),
                    np.asarray([seq.top_k], np.int32),
                    seq.temperature == 0.0)
            if self.kv_pool is not None:
                self.kv_pool.mark_dirty(prompt_blocks)
            self._post_token(seq, int(np.asarray(tok)[0]))

    def _post_token(self, seq, token):
        seq.generated.append(token)
        if self.telemetry is not None:
            self.telemetry.on_token(seq.uid)
        if ((seq.eos_token_id >= 0 and token == seq.eos_token_id)
                or len(seq.generated) >= seq.max_new_tokens):
            # a held sequence that finishes AT its first token (EOS or
            # max_new_tokens=1) never needs the handoff — drop the park
            self._decode_hold.discard(seq.uid)
            self._results[seq.uid] = np.asarray(seq.generated, np.int32)
            if self.telemetry is not None:
                self.telemetry.on_finish(seq.uid)
            if self.kv_pool is not None:
                # drop residency before the allocator recycles the ids
                self.kv_pool.release(seq.blocks)
            self.state_mgr.retire(seq.uid)
            self.state_mgr.flush(seq.uid)

    # ------------------------------------------------- KV host offload path
    def _seq_live_blocks(self, seq, n_steps=0):
        """Logical blocks a decode dispatch touches for ``seq``: the
        history it attends plus the tail blocks the next ``n_steps``
        writes land in."""
        last = seq.seen_tokens - 1 + max(0, n_steps - 1)
        hi = min(last // self.state_mgr.block_size, len(seq.blocks) - 1)
        return seq.blocks[:hi + 1]

    def _offload_decode_groups(self, batch, n_steps):
        """Greedy-pack active slots into dispatch groups whose combined
        working set fits the device pool."""
        mgr = self.state_mgr
        cap = self.kv_pool.D - 1
        groups = []
        cur, cur_blocks = [], set()
        for slot in np.nonzero(batch.active)[0]:
            seq = mgr.get_sequence(mgr._slots[slot])
            nb = set(self._seq_live_blocks(seq, n_steps))
            if cur and len(cur_blocks | nb) > cap:
                groups.append((cur, cur_blocks))
                cur, cur_blocks = [], set()
            cur.append(int(slot))
            cur_blocks |= nb
        if cur:
            groups.append((cur, cur_blocks))
        return groups

    def _step_offload_decode(self):
        """Grouped decode under KV host offload: each group's blocks are
        made device-resident (next group's H2D prefetched under the
        current group's compute), tables are translated to device slots,
        and tail blocks are marked dirty."""
        mgr = self.state_mgr
        pool = self.kv_pool
        n = max(1, self.config.decode_steps_per_dispatch)
        batch = mgr.decode_batch(exclude=self._decode_hold)
        if not batch.active.any():
            return []
        groups = self._offload_decode_groups(batch, n)
        fn = self._get_decode()
        out = []
        prepared = pool.prepare(sorted(groups[0][1])) if groups else None
        for gi, (slots_g, blocks_g) in enumerate(groups):
            self.cache = pool.ensure(self.cache, sorted(blocks_g),
                                     prepared)
            prepared = (pool.prepare(sorted(groups[gi + 1][1]))
                        if gi + 1 < len(groups) else None)
            sub_active = np.zeros_like(batch.active)
            sub_active[slots_g] = batch.active[slots_g]
            tables = np.zeros_like(batch.block_tables)
            tokens = np.where(sub_active, batch.tokens, 0)
            lengths = np.where(sub_active, batch.lengths, 0)
            for s in slots_g:
                tables[s] = pool.translate(batch.block_tables[s])
            self._rng, sub = jax.random.split(self._rng)
            with jax.set_mesh(self.mesh):
                toks, self.cache = fn(
                    self.params, self.cache, tokens,
                    lengths, tables, sub, batch.temps, batch.top_ks,
                    not bool(batch.temps[sub_active].any()))
            toks = np.asarray(toks)
            for s in slots_g:
                seq = mgr.get_sequence(mgr._slots[s])
                pool.mark_dirty(self._seq_live_blocks(seq, n)[
                    (batch.lengths[s]) // mgr.block_size:])
            sub_batch = RaggedBatchWrapper(
                tokens=tokens, lengths=lengths, block_tables=tables,
                active=sub_active, temps=batch.temps,
                top_ks=batch.top_ks)
            out.extend(self._post_decode_tokens(sub_batch, toks))
        return out

    def step(self):
        """One scheduler iteration (see :meth:`_step_inner`). The
        dispatch boundary is where serving telemetry amortizes this
        dispatch's wall time across the tokens it produced (per-token
        deltas inside one multi-step dispatch are meaningless)."""
        out = self._step_inner()
        if self.telemetry is not None:
            self.telemetry.on_dispatch(active=self.state_mgr.n_active)
            self.telemetry.maybe_emit()
        return out

    def telemetry_snapshot(self):
        """Current TTFT/TPOT percentiles + counters (None when serving
        telemetry is disabled)."""
        return None if self.telemetry is None else \
            self.telemetry.percentiles()

    def _step_inner(self):
        """One scheduler iteration: admit+prefill pending, then up to
        ``decode_steps_per_dispatch`` decode steps for every active
        sequence in one device program. Returns list of (uid, token)
        pairs produced this step.

        A sequence that hits EOS or its budget mid-dispatch keeps
        decoding until the dispatch ends (its extra tokens are discarded
        and its over-writes land in its own tail slots / the scratch
        block) — the FastGen trade of scheduling granularity for
        amortized launch overhead.
        """
        self._admit_pending()
        mgr = self.state_mgr
        if self._prefill_q:
            return self._step_splitfuse_chunk()
        if mgr.n_active == 0:
            return []
        if self.kv_pool is not None:
            return self._step_offload_decode()
        if self.draft_model is not None:
            return self._step_spec_decode()
        return self._plain_decode()

    def _plain_decode(self, uids=None):
        """The pre-speculation decode dispatch, unchanged: n fused
        decode steps over the given slots (all active slots when
        ``uids`` is None)."""
        mgr = self.state_mgr
        batch = mgr.decode_batch(uids, exclude=self._decode_hold)
        if not batch.active.any():
            return []
        self._rng, sub = jax.random.split(self._rng)
        fn = self._get_decode()
        with jax.set_mesh(self.mesh):
            toks, self.cache = fn(self.params, self.cache,
                                  batch.tokens, batch.lengths,
                                  batch.block_tables, sub,
                                  batch.temps, batch.top_ks,
                                  not bool(batch.temps.any()))
        return self._post_decode_tokens(batch, np.asarray(toks))

    # ------------------------------------------------- speculative decoding
    def _spec_candidate(self, seq):
        """Greedy, not floor-latched, and far enough from its budget
        tail that a full k-token span stays inside the blocks allocated
        up-front — tail sequences ride plain decode (at most k extra
        plain steps), so speculation never writes past a block table."""
        return (self.draft_model is not None and seq.spec_on
                and seq.temperature == 0.0
                and len(seq.prompt) + seq.max_new_tokens
                - seq.seen_tokens >= self._spec_k)

    @property
    def spec_pending(self):
        """True when the next step() would run a verify dispatch — the
        replica boundary gates its ``serve_verify`` chaos point on
        this, so chaos tests can target mid-speculation state."""
        if self.draft_model is None or self._prefill_q:
            return False
        mgr = self.state_mgr
        for uid in mgr._slots:
            if uid is None or uid in self._decode_hold:
                continue
            seq = mgr.get_sequence(uid)
            if seq.generated and self._spec_candidate(seq):
                return True
        return False

    def _step_spec_decode(self):
        """Acceptance-aware scheduling: partition the decoding slots
        into a SPEC set (greedy, latched on, draft pool has room) and a
        PLAIN set. The spec set runs propose -> batched verify -> host
        acceptance; the plain set runs the UNCHANGED decode program in
        its own dispatch — adversarial (low-acceptance) traffic latches
        off per sequence and pays exactly the plain-decode cost."""
        mgr = self.state_mgr
        spec, plain = [], []
        for uid in list(mgr._slots):
            if uid is None or uid in self._decode_hold:
                continue
            seq = mgr.get_sequence(uid)
            if not seq.generated:
                continue
            if not self._spec_candidate(seq):
                plain.append(uid)
                continue
            if not seq.draft_blocks and not mgr.alloc_draft(seq):
                plain.append(uid)     # draft pool full: plain decode
                continue
            while seq.draft_len < seq.seen_tokens - 2:
                self._draft_catchup(seq)
            spec.append(uid)
        out = []
        if spec:
            out.extend(self._spec_round(spec))
        if plain:
            out.extend(self._plain_decode(set(plain)))
        return out

    def _draft_catchup(self, seq):
        """Ingest one chunk of committed history into the draft cache
        (the draft's prefill, riding its own chunk program): tokens
        [draft_len, seen-1) from prompt+generated, written at their
        absolute positions in the sequence's draft blocks."""
        mgr = self.state_mgr
        BS = mgr.block_size
        C = self.config.splitfuse_tokens or self.config.prompt_bucket
        hist = (seq.prompt if not seq.generated else np.concatenate(
            [seq.prompt, np.asarray(seq.generated, np.int32)]))
        off = seq.draft_len
        true_len = min(C, seq.seen_tokens - 1 - off)
        ids = np.zeros((1, C), np.int32)
        ids[0, :true_len] = hist[off:off + true_len]
        idx = np.arange(off, off + true_len)
        tb = np.zeros((C,), np.int32)
        to = np.zeros((C,), np.int32)
        tb[:true_len] = np.asarray(seq.draft_blocks, np.int32)[idx // BS]
        to[:true_len] = (idx % BS).astype(np.int32)
        table = np.zeros((self.max_blocks_per_seq,), np.int32)
        table[:len(seq.draft_blocks)] = seq.draft_blocks
        fn = self._get_draft_chunk()
        with jax.set_mesh(self.mesh):
            self.draft_cache = fn(
                self.draft_params, self.draft_cache, ids, tb, to,
                np.int32(off), np.int32(true_len), table)
        seq.draft_len = off + true_len

    def _spec_round(self, uids):
        """One propose/verify round for the spec set. Each sequence
        commits its accepted prefix plus the target's bonus token —
        every committed token is a target-argmax output, which is what
        keeps greedy streams byte-identical to plain decode."""
        mgr, k = self.state_mgr, self._spec_k
        uid_set = set(uids)
        pb = mgr.propose_batch(uid_set)
        with jax.set_mesh(self.mesh):
            props, self.draft_cache = self._get_propose()(
                self.draft_params, self.draft_cache, pb.tokens,
                pb.lengths, pb.block_tables)
        props = np.asarray(props)                           # (B, k)
        proposals = {uid: props[slot]
                     for slot, uid in enumerate(mgr._slots)
                     if uid in uid_set}
        vb = mgr.verify_batch(proposals, k)
        for uid in uids:
            mgr.begin_spec(mgr.get_sequence(uid), proposals[uid])
        try:
            with jax.set_mesh(self.mesh):
                nxt, self.cache = self._get_verify()(
                    self.params, self.cache, vb.tokens, vb.lengths,
                    vb.block_tables)
            nxt = np.asarray(nxt)                           # (B, k+1)
        except BaseException:
            # an interrupted verify must not leave speculative tokens
            # in ``generated`` — unwind before the failure propagates,
            # or the replica/router retry would replay corrupt state
            for uid in uids:
                mgr.rollback_spec(mgr.get_sequence(uid))
            raise
        from .speculative import (SPEC_EMA_ALPHA, SPEC_MIN_ROUNDS,
                                  longest_accept)
        out = []
        for slot, uid in enumerate(list(mgr._slots)):
            if uid is None or uid not in uid_set:
                continue
            seq = mgr.get_sequence(uid)
            mgr.rollback_spec(seq)
            pre_seen = seq.seen_tokens
            d, t = proposals[uid], nxt[slot]
            a = longest_accept(d, t)
            commit = [int(x) for x in d[:a]] + [int(t[a])]
            seq.spec_rounds += 1
            seq.spec_accepted += a
            frac = a / k
            seq.spec_ema = frac if seq.spec_ema is None else \
                (1 - SPEC_EMA_ALPHA) * seq.spec_ema \
                + SPEC_EMA_ALPHA * frac
            if self.telemetry is not None:
                self.telemetry.on_spec_round(
                    uid, accepted=a, proposed=k, committed=len(commit))
            out.extend(self._post_tokens(seq, commit))
            if uid in self._results or uid not in mgr._seqs:
                continue                        # retired mid-span
            # the draft holds the committed history through seen-1 on
            # a partial round, seen-2 on a full one (its own last
            # proposal was never fed back; re-ingest covers the gap)
            seq.draft_len = pre_seen + (a if a < k else k - 1)
            if seq.spec_rounds >= SPEC_MIN_ROUNDS \
                    and seq.spec_ema < self._spec_floor:
                # acceptance floor: latch plain decode for this
                # sequence and return its over-allocated draft blocks
                seq.spec_on = False
                mgr.drop_draft(seq)
        return out

    def _post_tokens(self, seq, tokens):
        """Feed a committed multi-token span (accepted proposals +
        bonus) one at a time: EOS or budget retires mid-span and the
        tail is discarded, exactly like _post_decode_tokens discards
        post-finish dispatch tokens. Returns the accepted (uid, token)
        pairs."""
        out = []
        uid = seq.uid
        for tok in tokens:
            if uid in self._results:
                break
            self._post_token(seq, tok)
            out.append((uid, tok))
        return out

    def _post_decode_tokens(self, batch, toks):
        """Feed (n, B) decode outputs to their sequences; returns the
        accepted (uid, token) pairs."""
        mgr = self.state_mgr
        out = []
        slots = list(mgr._slots)  # snapshot: retire mutates
        for slot, uid in enumerate(slots):
            if uid is None or not batch.active[slot]:
                continue
            seq = mgr.get_sequence(uid)
            for t in range(toks.shape[0]):
                if uid in self._results:
                    break                            # finished mid-dispatch
                tok = int(toks[t, slot])
                self._post_token(seq, tok)
                out.append((uid, tok))
        return out

    def generate_all(self, prompts, max_new_tokens=32, eos_token_id=-1):
        """Convenience: run the scheduler to completion over a request
        list; returns generated-token arrays in submission order."""
        uids = [self.put(p, max_new_tokens, eos_token_id) for p in prompts]
        while self.has_work:
            self.step()
        return [self.get(u) for u in uids]
