"""Inference configuration.

Counterpart of reference ``inference/config.py DeepSpeedInferenceConfig``
(dtype, tensor_parallel, max_out_tokens, replace_with_kernel_inject).
Kernel injection has no TPU meaning — the model's ``partition_specs`` are
the declarative equivalent of module_inject — so the knob is accepted and
ignored for API compatibility.
"""

from dataclasses import dataclass, field


@dataclass
class TensorParallelConfig:
    tp_size: int = 1


@dataclass
class DeepSpeedInferenceConfig:
    dtype: str = "bfloat16"
    tensor_parallel: TensorParallelConfig = field(
        default_factory=TensorParallelConfig)
    max_out_tokens: int = 1024          # KV-cache capacity per sequence
    min_out_tokens: int = 1
    max_batch_size: int = 8
    replace_with_kernel_inject: bool = False   # accepted, no-op on TPU
    # sampling defaults (generate() kwargs override)
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    # pad prompt lengths up to a multiple of this to bound recompiles
    prompt_bucket: int = 64
    # ZeRO-Inference weight-only int8 serving (see
    # RaggedInferenceEngineConfig.quantize_weights)
    quantize_weights: bool = False

    @classmethod
    def from_dict(cls, d):
        d = dict(d or {})
        tp = d.pop("tensor_parallel", {})
        if isinstance(tp, int):
            tp = {"tp_size": tp}
        if "mp_size" in d:  # reference alias (init_inference(mp_size=N))
            tp = {"tp_size": d.pop("mp_size")}
        known = {k: v for k, v in d.items()
                 if k in cls.__dataclass_fields__}
        return cls(tensor_parallel=TensorParallelConfig(**tp), **known)
