"""InferenceEngine — TP-sharded generation with a KV cache.

Counterpart of reference ``inference/engine.py:39 InferenceEngine``:
  * TP group creation (:254) → a ('data','tensor') inference mesh; the
    model's ``partition_specs`` shard weights Megatron-style (the
    declarative equivalent of module_inject/auto_tp.py:188 AutoTP).
  * CUDA-graph capture/replay (:524,543) → ``jax.jit``: the whole
    prefill+decode loop is ONE compiled XLA program per shape bucket
    (prompt lengths round up to ``prompt_bucket`` so recompiles are
    bounded), with the decode loop as ``lax.scan`` — no per-token Python.
  * generate wrapper (:613) → ``generate()`` with greedy / temperature /
    top-k / top-p sampling and EOS early-stop masking.

Prompts are LEFT-padded into the cache so every sequence decodes at the
same cache slot; pad slots are masked out of attention forever
(models/gpt2.py block_forward_cached).
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils import groups
from ..utils.groups import TopologyConfig, BATCH_AXES
from ..utils.logging import log_dist
from .config import DeepSpeedInferenceConfig


def _sample(logits, rng, temperature, top_k, top_p, greedy):
    """logits: (B, V) fp32 -> (B,) int32.

    ``greedy`` is the ONLY static knob (argmax needs no sort and no
    rng); temperature/top_k/top_p are TRACED scalars, so one compiled
    program serves every sampling configuration of a shape bucket — the
    v2 engine's convention, closing the per-(temp, k, p) program
    explosion the v1 LRU cache only bounded. Cost of the unification:
    the sampling path always pays its two (B, V) sorts even when top-k
    and top-p are disabled (disabled values mask to no-ops).
    """
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    V = logits.shape[-1]
    logits = logits / jnp.maximum(temperature, 1e-6)
    # top-k with a traced k: threshold at the k-th largest via a dynamic
    # slice of the descending sort; k <= 0 disables
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    k = jnp.clip(top_k, 1, V).astype(jnp.int32)
    kth = lax.dynamic_slice_in_dim(sorted_desc, k - 1, 1, axis=1)
    apply_k = top_k > 0
    logits = jnp.where(apply_k & (logits < kth), -1e30, logits)
    # top-p on the (possibly k-masked) logits; top_p >= 1 keeps all.
    # The masked sort derives from the first one (masking values below
    # kth is order-preserving), saving the second (B, V) sort per token
    sorted_l = jnp.where(apply_k & (sorted_desc < kth), -1e30,
                         sorted_desc)
    probs = jax.nn.softmax(sorted_l, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep the smallest set with cumulative prob >= top_p
    keep = cum - probs < top_p
    cutoff = jnp.min(jnp.where(keep, sorted_l, jnp.inf), axis=-1,
                     keepdims=True)
    logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


class InferenceEngine:
    """``engine = init_inference(model, ...); engine.generate(ids)``.

    ``model`` is a functional model with ``init/apply/partition_specs`` and
    the cached-decode surface ``init_cache/cache_specs/apply_cached``
    (models/gpt2.py). ``params`` may be passed or freshly initialized.
    """

    def __init__(self, model, config=None, params=None, topology=None,
                 seed=0, **kwargs):
        if isinstance(config, dict):
            # explicit kwargs win over config-dict keys (reference
            # init_inference merges kwargs into the config the same way)
            config = DeepSpeedInferenceConfig.from_dict({**config, **kwargs})
        elif config is None:
            config = DeepSpeedInferenceConfig.from_dict(kwargs)
        self.config = config
        self.model = model
        # LRU-bounded program cache keyed on (shape bucket, greedy, eos)
        # ONLY — sampling params are traced (v2 parity), so the LRU
        # bounds genuinely distinct shapes, not request configurations
        from collections import OrderedDict
        self._generate_cache = OrderedDict()
        self._generate_cache_max = 32

        if topology is None:
            topology = groups.initialize(TopologyConfig(
                tensor_parallel_size=config.tensor_parallel.tp_size))
        self.topology = topology
        self.mesh = topology.mesh

        dtype = jnp.dtype(config.dtype)
        self.dtype = dtype
        from .utils import shard_params
        self.params, self.param_shardings = shard_params(
            model, self.mesh, dtype, params=params, seed=seed,
            quantize=self.config.quantize_weights,
            topology=topology)
        self._forward_jit = None
        self._rng = jax.random.key(seed + 17)
        log_dist(f"inference engine ready: tp={config.tensor_parallel.tp_size} "
                 f"dtype={config.dtype}", ranks=[0])

    # ------------------------------------------------------------------ fwd
    def forward(self, input_ids):
        """Full logits for a batch (no cache) — parity with calling the
        injected module directly."""
        ids = jnp.asarray(input_ids, jnp.int32)
        if self._forward_jit is None:
            self._forward_jit = jax.jit(self.model.apply)
        with jax.set_mesh(self.mesh):
            return self._forward_jit(self.params, ids)

    __call__ = forward

    # ------------------------------------------------------------- generate
    def _build_generate(self, B, T_pad, max_new, greedy, eos_id):
        model = self.model
        # shard the batch over the data axes only when it divides evenly
        # (generation batches are often 1); otherwise replicate
        dp = int(np.prod([self.mesh.shape[a] for a in BATCH_AXES]))
        batch_axes = BATCH_AXES if B % dp == 0 else None
        cache_specs = model.cache_specs(batch_axes=batch_axes)
        constrain = lax.with_sharding_constraint

        def gen(params, ids, lengths, rng, temperature, top_k, top_p):
            """ids: (B, T_pad) LEFT-padded prompts; lengths: (B,)."""
            B = ids.shape[0]
            Tmax = T_pad + max_new
            cache = model.init_cache(B, Tmax, dtype=self.dtype)
            cache = jax.tree.map(
                lambda c, s: constrain(c, s), cache, cache_specs,
                is_leaf=lambda x: isinstance(x, P))
            pad = T_pad - lengths  # (B,) left-pad counts
            valid = jnp.arange(Tmax)[None, :] >= pad[:, None]
            valid = valid & (jnp.arange(Tmax)[None, :] < T_pad)
            pos = jnp.maximum(jnp.arange(T_pad)[None, :] - pad[:, None], 0)
            logits, cache = model.apply_cached(
                params, ids, pos.astype(jnp.int32), cache, 0, valid,
                last_token_only=True)
            rng, sub = jax.random.split(rng)
            last = _sample(logits[:, -1], sub, temperature, top_k, top_p,
                           greedy)

            def step(carry, i):
                cache, tok, valid, done, rng = carry
                rng, sub = jax.random.split(rng)
                slot = T_pad + i
                valid = valid.at[:, slot].set(~done)
                pos_t = (slot - pad).astype(jnp.int32)[:, None]
                logits, cache = model.apply_cached(
                    params, tok[:, None], pos_t, cache, slot, valid)
                nxt = _sample(logits[:, -1], sub, temperature, top_k,
                              top_p, greedy)
                nxt = jnp.where(done, eos_id, nxt)
                done = done | (nxt == eos_id) if eos_id >= 0 else done
                return (cache, nxt, valid, done, rng), tok

            done0 = (last == eos_id) if eos_id >= 0 else jnp.zeros(
                (B,), jnp.bool_)
            (_, last_tok, _, _, _), toks = lax.scan(
                step, (cache, last, valid, done0, rng),
                jnp.arange(max_new - 1))
            # toks: (max_new-1, B) holds tokens 0..max_new-2; append final
            out = jnp.concatenate(
                [jnp.swapaxes(toks, 0, 1), last_tok[:, None]], axis=1)
            return out

        batch_spec = NamedSharding(self.mesh, P(batch_axes))
        rep = NamedSharding(self.mesh, P())
        return jax.jit(gen, in_shardings=(
            self.param_shardings, batch_spec, batch_spec, rep, rep, rep,
            rep))

    def generate(self, input_ids, max_new_tokens=32, temperature=None,
                 top_k=None, top_p=None, eos_token_id=-1, pad_token_id=0,
                 seed=None):
        """input_ids: (B, T) or list of variable-length prompts.
        Returns (B, max_new_tokens) int32 generated tokens (post-EOS
        positions filled with eos)."""
        cfg = self.config
        temperature = cfg.temperature if temperature is None else temperature
        top_k = cfg.top_k if top_k is None else top_k
        top_p = cfg.top_p if top_p is None else top_p
        if not 0.0 < top_p <= 1.0:
            # top_p is traced and its branch always executes: top_p <= 0
            # would silently mask EVERY logit and degenerate sampling to
            # uniform-over-vocab
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")

        if isinstance(input_ids, (list, tuple)):
            if input_ids and np.isscalar(input_ids[0]):
                seqs = [np.asarray(input_ids, np.int32)]  # one flat prompt
            else:
                seqs = [np.asarray(s, np.int32).reshape(-1)
                        for s in input_ids]
        else:
            arr = np.asarray(input_ids, np.int32)
            if arr.ndim == 1:
                arr = arr[None, :]
            seqs = [arr[i] for i in range(arr.shape[0])]
        lengths = np.array([len(s) for s in seqs], np.int32)
        bucket = cfg.prompt_bucket
        T_pad = int(-(-max(lengths.max(), 1) // bucket) * bucket)
        B = len(seqs)
        # position-embedding capacity guard: positions run to
        # max(len)+max_new-1 and wpe indexing would silently clamp past it
        model_cap = getattr(getattr(self.model, "config", None),
                            "max_seq_len", None)
        needed = int(lengths.max()) + max_new_tokens
        if model_cap is not None and needed > model_cap:
            raise ValueError(
                f"prompt_len+max_new_tokens={needed} exceeds the model's "
                f"max_seq_len={model_cap}")
        if T_pad + max_new_tokens > cfg.max_out_tokens:
            raise ValueError(
                f"padded_prompt+max_new_tokens={T_pad + max_new_tokens} "
                f"exceeds config.max_out_tokens={cfg.max_out_tokens}")
        ids = np.full((B, T_pad), pad_token_id, np.int32)
        for i, s in enumerate(seqs):  # LEFT pad
            ids[i, T_pad - len(s):] = s

        # sampling params are traced: the program key carries only the
        # shape bucket + the static greedy/eos structure (v2 parity);
        # the LRU now only bounds genuinely distinct shapes
        greedy = float(temperature) == 0.0
        key = (B, T_pad, max_new_tokens, greedy, int(eos_token_id))
        if key not in self._generate_cache:
            self._generate_cache[key] = self._build_generate(
                B, T_pad, max_new_tokens, greedy, int(eos_token_id))
            while len(self._generate_cache) > self._generate_cache_max:
                self._generate_cache.popitem(last=False)
        self._generate_cache.move_to_end(key)
        fn = self._generate_cache[key]

        if seed is not None:
            rng = jax.random.key(seed)
        else:
            self._rng, rng = jax.random.split(self._rng)
        with jax.set_mesh(self.mesh):
            out = fn(self.params, ids, lengths, rng,
                     jnp.float32(temperature), jnp.int32(top_k),
                     jnp.float32(top_p))
        return np.asarray(out)

    # ------------------------------------------------------------- weights
    def load_checkpoint(self, load_dir, tag=None):
        """Load a training checkpoint's master weights into the inference
        shardings (reference load_model_with_checkpoint:331 — MP-sharded
        load falls out of device_put with NamedShardings). Same recovery
        semantics as the training engine: CRC-verified shards, and a
        corrupt newest generation falls back to the previous durable
        tag (an explicit ``tag`` is never substituted)."""
        from ..runtime.checkpoint_engine import serialization as ser
        from ..runtime.checkpoint_engine import manager as ckpt_manager
        tag, flat, header = ckpt_manager.load_best(load_dir, tag)
        if tag is None:
            raise FileNotFoundError(f"no checkpoint under {load_dir}")
        abstract = jax.eval_shape(self.model.init, jax.random.key(0))
        tree = ser.unflatten_into({"master": abstract}, {
            k: v for k, v in flat.items() if k.startswith("master")
        }, header.get("meta"))["master"]
        with jax.set_mesh(self.mesh):
            self.params = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(self.dtype), p),
                out_shardings=self.param_shardings)(tree)
        return tag
