// Async file I/O thread pool.
//
// Counterpart of the reference's csrc/aio/ (deepspeed_aio_common.cpp libaio
// submit/poll loop + deepspeed_aio_thread.cpp pool + py_ds_aio.cpp
// binding): a pool of worker threads doing chunked pread/pwrite with
// optional fsync, addressed through a C ABI for ctypes (no pybind11).
// Plain p{read,write} instead of io_submit: TPU-host swap traffic is
// sequential bulk I/O where a thread pool saturates NVMe just as well,
// with no O_DIRECT alignment constraints on the caller's buffers.
//
// Request lifecycle: submit -> int64 id; wait(id) joins that request and
// returns its status (0 ok, -errno on failure). The caller must keep the
// buffer alive until wait() returns (the python binding pins it).

#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Task {
  int64_t id;
  bool is_write;
  std::string path;
  void *buf;
  int64_t nbytes;
  int do_fsync;
};

struct Pool {
  std::vector<std::thread> workers;
  std::deque<Task> queue;
  std::map<int64_t, int> done; // id -> status (0 / -errno)
  std::mutex mu;
  std::condition_variable cv_task;
  std::condition_variable cv_done;
  int64_t next_id = 1;
  int64_t block_size;
  bool stop = false;

  explicit Pool(int threads, int64_t block) : block_size(block) {
    for (int i = 0; i < threads; ++i)
      workers.emplace_back([this] { run(); });
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> l(mu);
      stop = true;
    }
    cv_task.notify_all();
    for (auto &w : workers)
      w.join();
  }

  int execute(const Task &t) {
    int flags = t.is_write ? (O_WRONLY | O_CREAT | O_TRUNC) : O_RDONLY;
    int fd = ::open(t.path.c_str(), flags, 0644);
    if (fd < 0)
      return -errno;
    int status = 0;
    int64_t off = 0;
    char *p = static_cast<char *>(t.buf);
    while (off < t.nbytes) {
      int64_t chunk = t.nbytes - off;
      if (block_size > 0 && chunk > block_size)
        chunk = block_size;
      ssize_t n = t.is_write ? ::pwrite(fd, p + off, chunk, off)
                             : ::pread(fd, p + off, chunk, off);
      if (n < 0) {
        if (errno == EINTR)
          continue;
        status = -errno;
        break;
      }
      if (n == 0) { // short file on read
        status = -EIO;
        break;
      }
      off += n;
    }
    if (status == 0 && t.is_write && t.do_fsync)
      if (::fsync(fd) != 0)
        status = -errno;
    ::close(fd);
    return status;
  }

  void run() {
    for (;;) {
      Task t;
      {
        std::unique_lock<std::mutex> l(mu);
        cv_task.wait(l, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty())
          return;
        t = queue.front();
        queue.pop_front();
      }
      int status = execute(t);
      {
        std::lock_guard<std::mutex> l(mu);
        done[t.id] = status;
      }
      cv_done.notify_all();
    }
  }

  int64_t submit(bool is_write, const char *path, void *buf, int64_t nbytes,
                 int do_fsync) {
    std::lock_guard<std::mutex> l(mu);
    int64_t id = next_id++;
    queue.push_back(Task{id, is_write, path, buf, nbytes, do_fsync});
    cv_task.notify_one();
    return id;
  }

  int wait(int64_t id) {
    std::unique_lock<std::mutex> l(mu);
    cv_done.wait(l, [this, id] { return done.count(id) > 0; });
    int status = done[id];
    done.erase(id);
    return status;
  }
};

} // namespace

extern "C" {

void *aio_create(int threads, int64_t block_size) {
  if (threads < 1)
    threads = 1;
  return new Pool(threads, block_size);
}

void aio_destroy(void *h) { delete static_cast<Pool *>(h); }

int64_t aio_submit_pwrite(void *h, const char *path, const void *buf,
                          int64_t nbytes, int do_fsync) {
  return static_cast<Pool *>(h)->submit(
      true, path, const_cast<void *>(buf), nbytes, do_fsync);
}

int64_t aio_submit_pread(void *h, const char *path, void *buf,
                         int64_t nbytes) {
  return static_cast<Pool *>(h)->submit(false, path, buf, nbytes, 0);
}

int aio_wait(void *h, int64_t id) { return static_cast<Pool *>(h)->wait(id); }

// blocking helpers (reference sync_pread/sync_pwrite)
int aio_pwrite(void *h, const char *path, const void *buf, int64_t nbytes,
               int do_fsync) {
  Pool *p = static_cast<Pool *>(h);
  return p->wait(p->submit(true, path, const_cast<void *>(buf), nbytes,
                           do_fsync));
}

int aio_pread(void *h, const char *path, void *buf, int64_t nbytes) {
  Pool *p = static_cast<Pool *>(h);
  return p->wait(p->submit(false, path, buf, nbytes, 0));
}

} // extern "C"
