// Shared worker-queue thread pool for the host-side C++ ops
// (ckpt_writer.cpp, cpu_adam.cpp; aio.cpp keeps its specialized pool with
// per-request completion tracking).
//
// ParallelFor: fan a [0, n) index range across the pool in contiguous
// slabs and BLOCK until every slab finished — completion state lives in a
// heap-shared block so a late-finishing worker can never touch stack
// memory after the caller returns (the use-after-scope class of bug).
#pragma once

#include <condition_variable>
#include <cstdint>
#include <functional>
#include <memory>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace dstpu {

class WorkerPool {
 public:
  explicit WorkerPool(int n_threads) {
    if (n_threads < 1) n_threads = 1;
    for (int i = 0; i < n_threads; ++i)
      workers_.emplace_back([this] { run(); });
  }

  ~WorkerPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto &w : workers_) w.join();
  }

  int n_threads() const { return static_cast<int>(workers_.size()); }

  void submit(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      q_.push(std::move(fn));
    }
    cv_.notify_one();
  }

  // Run body(begin, end) over [0, n) in n_threads slabs; waits for all.
  void parallel_for(int64_t n,
                    const std::function<void(int64_t, int64_t)> &body) {
    struct Done {
      std::mutex mu;
      std::condition_variable cv;
      int remaining = 0;
    };
    auto done = std::make_shared<Done>();
    const int64_t slab = (n + n_threads() - 1) / n_threads();
    for (int t = 0; t < n_threads(); ++t) {
      int64_t begin = static_cast<int64_t>(t) * slab;
      if (begin >= n) break;
      int64_t end = begin + slab < n ? begin + slab : n;
      {
        std::lock_guard<std::mutex> lk(done->mu);
        done->remaining += 1;
      }
      submit([done, begin, end, &body] {
        body(begin, end);
        std::lock_guard<std::mutex> lk(done->mu);
        done->remaining -= 1;
        if (done->remaining == 0) done->cv.notify_all();
      });
    }
    std::unique_lock<std::mutex> lk(done->mu);
    done->cv.wait(lk, [&] { return done->remaining == 0; });
  }

 private:
  void run() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !q_.empty(); });
        if (stop_ && q_.empty()) return;
        fn = std::move(q_.front());
        q_.pop();
      }
      fn();
    }
  }

  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> q_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_ = false;
};

}  // namespace dstpu
