// Parallel checkpoint writer pool.
//
// TPU-native counterpart of the reference's VELOC writer threads
// (csrc/veloc/deepspeed_py_veloc.cu: _h2f_trf at cu:94 pwrites device
// snapshots from a pinned host cache) and the AIO thread pool
// (csrc/aio/deepspeed_aio_thread.cpp:104). On TPU hosts the D2H staging is
// jax device_get (done python-side); this pool owns the disk half: chunked
// pwrite across N threads, optional fsync, so a multi-GB checkpoint hits
// disk at RAID/NVMe bandwidth instead of a single-threaded write() rate.
//
// C ABI only (loaded via ctypes; no pybind11 in this image).

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <unistd.h>
#include <vector>

#include "pool.h"

namespace {
using WriterPool = dstpu::WorkerPool;


int pwrite_full(int fd, const char* buf, int64_t count, int64_t offset) {
  while (count > 0) {
    ssize_t n = ::pwrite(fd, buf, static_cast<size_t>(count), offset);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    buf += n;
    offset += n;
    count -= n;
  }
  return 0;
}

}  // namespace

extern "C" {

void* ckpt_writer_create(int n_threads) { return new WriterPool(n_threads); }

void ckpt_writer_destroy(void* pool) {
  delete static_cast<WriterPool*>(pool);
}

// Write `nbytes` from `data` to `path`, chunked across the pool's threads.
// Returns 0 on success, -errno on the first failure. Synchronous w.r.t. the
// caller (python calls it from its own background thread), parallel inside.
int ckpt_writer_write(void* pool_ptr, const char* path, const void* data,
                      int64_t nbytes, int do_fsync) {
  auto* pool = static_cast<WriterPool*>(pool_ptr);
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  if (::ftruncate(fd, nbytes) != 0) {
    int err = -errno;
    ::close(fd);
    return err;
  }

  const int n_chunks = pool->n_threads();
  const int64_t chunk = (nbytes + n_chunks - 1) / n_chunks;
  std::atomic<int> err{0};
  std::atomic<int> remaining{0};
  std::mutex done_mu;
  std::condition_variable done_cv;

  const char* base = static_cast<const char*>(data);
  for (int i = 0; i < n_chunks; ++i) {
    int64_t off = static_cast<int64_t>(i) * chunk;
    if (off >= nbytes) break;
    int64_t len = std::min(chunk, nbytes - off);
    remaining.fetch_add(1);
    pool->submit([=, &err, &remaining, &done_mu, &done_cv] {
      int rc = pwrite_full(fd, base + off, len, off);
      if (rc != 0) {
        int expected = 0;
        err.compare_exchange_strong(expected, rc);
      }
      if (remaining.fetch_sub(1) == 1) {
        std::unique_lock<std::mutex> lk(done_mu);
        done_cv.notify_all();
      }
    });
  }
  {
    std::unique_lock<std::mutex> lk(done_mu);
    done_cv.wait(lk, [&] { return remaining.load() == 0; });
  }
  if (err.load() == 0 && do_fsync) {
    if (::fsync(fd) != 0) err.store(-errno);
  }
  ::close(fd);
  return err.load();
}

}  // extern "C"
