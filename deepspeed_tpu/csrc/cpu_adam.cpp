// Host-side Adam/AdamW for offloaded optimizer states.
//
// Counterpart of the reference's csrc/adam/cpu_adam_impl.cpp (+ simd.h
// AVX2/AVX512 intrinsics): ZeRO-Offload keeps optimizer state in host RAM
// and steps it on the CPU while the device trains. Plain C loops compiled
// -O3 -march=native: the compiler emits the same vector ISA the
// hand-written intrinsics target, without the per-ISA code paths. Parallel
// across the shared worker pool (pool.h) in contiguous slabs.
//
// AdamW semantics match torch.optim.AdamW: the decoupled decay is
// p -= lr * wd * p (NOT scaled by the bias-correction factor).
//
// C ABI (ctypes): fp32 params/m/v in place, fp32 or bf16-as-uint16 grads.

#include <cmath>
#include <cstdint>
#include <cstring>

#include "pool.h"

namespace {

struct AdamState {
  float lr, beta1, beta2, eps, weight_decay;
  int adamw;          // 1 = decoupled decay
  int bias_correction;
  int64_t step = 0;
  dstpu::WorkerPool *pool;
};

inline float bf16_to_f32(uint16_t v) {
  uint32_t u = static_cast<uint32_t>(v) << 16;
  float f;
  std::memcpy(&f, &u, 4);
  return f;
}

void adam_slab(AdamState *s, float *p, float *m, float *v, const void *g,
               int grad_is_bf16, int64_t begin, int64_t end) {
  const float b1 = s->beta1, b2 = s->beta2, eps = s->eps;
  const float wd = s->weight_decay;
  // guard: a step() misuse with increment_step=0 before any incrementing
  // call must not divide by (1 - b1^0) == 0
  const double t =
      static_cast<double>(s->step < 1 ? int64_t{1} : s->step);
  float step_size = s->lr;
  float bc2 = 1.0f;
  if (s->bias_correction) {
    step_size = s->lr / static_cast<float>(1.0 - std::pow(b1, t));
    bc2 = 1.0f / static_cast<float>(std::sqrt(1.0 - std::pow(b2, t)));
  }
  const float *gf = static_cast<const float *>(g);
  const uint16_t *gb = static_cast<const uint16_t *>(g);
  for (int64_t i = begin; i < end; ++i) {
    float grad = grad_is_bf16 ? bf16_to_f32(gb[i]) : gf[i];
    if (wd != 0.0f && !s->adamw) grad += wd * p[i];
    m[i] = b1 * m[i] + (1.0f - b1) * grad;
    v[i] = b2 * v[i] + (1.0f - b2) * grad * grad;
    float denom = std::sqrt(v[i]) * bc2 + eps;
    float update = step_size * (m[i] / denom);
    if (wd != 0.0f && s->adamw) update += s->lr * wd * p[i];
    p[i] -= update;
  }
}

} // namespace

extern "C" {

void *cpu_adam_create(float lr, float beta1, float beta2, float eps,
                      float weight_decay, int adamw, int bias_correction,
                      int threads) {
  auto *s = new AdamState{lr, beta1, beta2, eps, weight_decay, adamw,
                          bias_correction, 0, nullptr};
  s->pool = new dstpu::WorkerPool(threads);
  return s;
}

void cpu_adam_destroy(void *h) {
  auto *s = static_cast<AdamState *>(h);
  delete s->pool;
  delete s;
}

int64_t cpu_adam_get_step(void *h) {
  return static_cast<AdamState *>(h)->step;
}

// checkpoint restore: resume bias correction at the saved step count
void cpu_adam_set_step(void *h, int64_t step) {
  static_cast<AdamState *>(h)->step = step;
}

void cpu_adam_set_lr(void *h, float lr) {
  static_cast<AdamState *>(h)->lr = lr;
}

// One fused step over a flat slab. params/m/v: fp32 (n,); grads: fp32 or
// bf16 (grad_is_bf16). Increments the shared Adam step counter when
// `increment_step` (call once per optimizer step; extra tensors in the
// same step pass 0).
void cpu_adam_step(void *h, float *params, float *m, float *v,
                   const void *grads, int grad_is_bf16, int64_t n,
                   int increment_step) {
  auto *s = static_cast<AdamState *>(h);
  if (increment_step) s->step += 1;
  s->pool->parallel_for(n, [&](int64_t begin, int64_t end) {
    adam_slab(s, params, m, v, grads, grad_is_bf16, begin, end);
  });
}

} // extern "C"
