"""Compression manager — init_compression / redundancy_clean.

Counterpart of reference ``compression/compress.py``
(``init_compression:100`` swaps nn.Modules for ``LinearLayer_Compress``;
``redundancy_clean:148`` physically rewrites pruned modules). Functional
redesign: models are param pytrees, so compression is a PARAM TRANSFORM —
``manager.transform(params, step)`` returns the forward-visible params
(fake-quantized / masked through straight-through estimators) and
``manager.wrap(model)`` returns a model whose loss/apply transform params
first, so the engine trains masters while forward sees compressed values
(the same QAT structure the reference builds with autograd functions).
"""

import re

import jax
import jax.numpy as jnp

from ..utils.logging import logger
from . import ops
from .config import get_compression_config


def _path_str(path):
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def _match(path, patterns):
    """Patterns are real regexes fullmatched against the path ('.*'
    matches everything; a bare '*' is accepted as that glob-ism)."""
    return any(re.fullmatch(".*" if pat == "*" else pat, path)
               for pat in patterns)


class CompressionManager:
    def __init__(self, config, example_params=None):
        self.techniques = get_compression_config(config)
        # plan: path -> list of (technique, params dict); built lazily
        self._plan = None
        self._masks = {}
        if example_params is not None:
            self.build_plan(example_params)

    # ---------------------------------------------------------------- plan
    def build_plan(self, params):
        plan = {}
        for path, leaf in jax.tree.leaves_with_path(params):
            if getattr(leaf, "ndim", 0) < 2:
                continue  # reference compresses Linear/Embedding weights
            p = _path_str(path)
            for tech, cfg in self.techniques.items():
                for group in cfg["groups"]:
                    if _match(p, group["modules"]):
                        plan.setdefault(p, []).append(
                            (tech, {**cfg["shared"], **group["params"]}))
        self._plan = plan
        if plan:
            logger.info(f"compression plan covers {len(plan)} tensors: "
                        f"{sorted(plan)[:4]}...")
        return plan

    @property
    def plan(self):
        return self._plan or {}

    def _offset_ok(self, shared, step):
        """None -> True; python int -> bool; traced step -> traced bool
        (the caller selects with jnp.where so the gate works in jit)."""
        if step is None:
            return True
        return step >= shared.get("schedule_offset", 0)

    @staticmethod
    def _gated(ok, transformed, original):
        if ok is True:
            return transformed
        if ok is False:
            return original
        return jnp.where(ok, transformed, original)  # traced gate

    # ----------------------------------------------------------- transform
    def transform(self, params, step=None):
        """Forward-visible params: quantization/pruning applied via STE.
        ``step`` gates schedule_offset (None = always on)."""
        if self._plan is None:
            self.build_plan(params)

        def visit(path, leaf):
            p = _path_str(path)
            for tech, cfg in self._plan.get(p, []):
                ok = self._offset_ok(cfg, step)
                if ok is False:
                    continue
                if tech == "weight_quantization":
                    # quantize_groups is a group COUNT (reference
                    # semantics): 1 group = per-tensor scaling
                    n_groups = int(cfg.get("quantize_groups", 1))
                    if n_groups > 1 and leaf.size % n_groups != 0:
                        logger.warning(
                            f"quantize_groups={n_groups} does not divide "
                            f"{p} (size {leaf.size}); falling back to "
                            "per-tensor scaling")
                    gsize = (leaf.size // n_groups
                             if n_groups > 1 and leaf.size % n_groups == 0
                             else 0)
                    new = ops.quantize_weight(
                        leaf, bits=cfg.get("target_bits", 8),
                        symmetric=cfg.get("quantization_type",
                                          "symmetric") == "symmetric",
                        group_size=gsize)
                elif tech == "sparse_pruning":
                    new = ops.apply_mask(leaf, self._mask(
                        p, "sparse", leaf, lambda: ops.sparse_mask(
                            leaf, 1.0 - cfg.get("dense_ratio", 0.5))))
                elif tech == "row_pruning":
                    new = ops.apply_mask(leaf, self._mask(
                        p, "row", leaf, lambda: ops.row_mask(
                            leaf, 1.0 - cfg.get("dense_ratio", 0.5))))
                elif tech == "head_pruning":
                    new = ops.apply_mask(leaf, self._mask(
                        p, "head", leaf, lambda: ops.head_mask(
                            leaf, 1.0 - cfg.get("dense_ratio", 0.5),
                            num_heads=cfg["num_heads"])))
                else:
                    continue
                leaf = self._gated(ok, new, leaf)
            return leaf

        return jax.tree.map_with_path(visit, params)

    def _mask(self, path, kind, leaf, maker):
        """Concrete params (manager built with example_params, or eager
        use): the mask is computed ONCE and frozen, like the reference.
        Traced params (transform running inside a jitted train step): the
        mask is recomputed from the live masters each step — iterative
        magnitude pruning — and is NEVER cached, because caching a tracer
        would leak it into later retraces."""
        key = (path, kind)
        if key in self._masks:
            return self._masks[key]
        m = jax.lax.stop_gradient(maker())
        if not isinstance(leaf, jax.core.Tracer):
            self._masks[key] = m
        return m

    def quantize_activations(self, x):
        cfg = self.techniques.get("activation_quantization")
        if not cfg:
            return x
        shared = cfg["shared"]
        bits = (cfg["groups"][0]["params"].get("bits", 8)
                if cfg["groups"] else 8)
        return ops.quantize_activation(
            x, bits=bits,
            symmetric=shared.get("quantization_type",
                                 "symmetric") == "symmetric")

    # ---------------------------------------------------------------- wrap
    def wrap(self, model):
        """Model proxy whose loss()/apply() see transformed params."""
        return _CompressedModel(model, self)


class _CompressedModel:
    """``step=`` is accepted by loss() so the engine threads the traced
    global step through to schedule_offset gating (engine._model_loss
    passes it to any model whose loss signature has a ``step`` param)."""

    def __init__(self, model, manager):
        self._model = model
        self._manager = manager

    def __getattr__(self, name):
        return getattr(self._model, name)

    def loss(self, params, batch, step=None, **kw):
        return self._model.loss(
            self._manager.transform(params, step=step), batch, **kw)

    def apply(self, params, *args, step=None, **kw):
        return self._model.apply(
            self._manager.transform(params, step=step), *args, **kw)


def init_compression(model, ds_config, example_params=None, mpu=None):
    """reference compress.py:100 init_compression — returns
    (wrapped_model, manager)."""
    manager = CompressionManager(ds_config, example_params=example_params)
    return manager.wrap(model), manager


def redundancy_clean(params, manager):
    """reference compress.py:148 — bake the compression in: returns params
    with masks permanently applied and quantization materialized (no STE),
    ready for export/inference."""
    out = manager.transform(params)
    return jax.tree.map(jax.lax.stop_gradient, out)
