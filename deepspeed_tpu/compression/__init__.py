from .compress import init_compression, redundancy_clean, CompressionManager
from .config import get_compression_config
from . import ops
