"""Compression primitives: fake quantization and pruning masks.

Counterpart of reference ``compression/basic_layer.py`` (the compute inside
``LinearLayer_Compress:121`` / ``Embedding_Compress``) and
``compression/utils.py``. Functional: each op maps (param, step) -> param
with the compression applied through a straight-through estimator (STE) —
forward sees the quantized/pruned value, backward passes gradients to the
full-precision master (exactly what the reference's autograd functions do).
"""

import jax
import jax.numpy as jnp


def _ste(x, transformed):
    """Straight-through: forward = transformed, grad flows to x."""
    return x + jax.lax.stop_gradient(transformed - x)


# ------------------------------------------------------------ quantization
def quantize_weight(w, bits=8, symmetric=True, group_size=0):
    """Fake-quantize to ``bits`` with per-tensor (group_size=0) or
    per-group absmax/minmax scaling (reference quantize_weights,
    basic_layer.py qat path)."""
    orig_shape = w.shape
    wf = w.astype(jnp.float32)
    if group_size and w.size % group_size == 0:
        wf = wf.reshape(-1, group_size)
        axis, keep = 1, True
    else:
        wf = wf.reshape(1, -1)
        axis, keep = 1, True
    levels = 2 ** (bits - 1) - 1
    if symmetric:
        scale = jnp.max(jnp.abs(wf), axis=axis, keepdims=keep) / levels
        scale = jnp.where(scale > 0, scale, 1.0)
        q = jnp.clip(jnp.round(wf / scale), -levels - 1, levels) * scale
    else:
        lo = jnp.min(wf, axis=axis, keepdims=keep)
        hi = jnp.max(wf, axis=axis, keepdims=keep)
        span = jnp.maximum(hi - lo, 1e-8)
        steps = 2 ** bits - 1
        q = jnp.round((wf - lo) / span * steps) / steps * span + lo
    q = q.reshape(orig_shape).astype(w.dtype)
    return _ste(w, q)


def quantize_activation(x, bits=8, symmetric=True):
    """Dynamic per-tensor activation fake-quant (reference
    activation_quantization)."""
    levels = 2 ** (bits - 1) - 1 if symmetric else 2 ** bits - 1
    xf = x.astype(jnp.float32)
    if symmetric:
        scale = jnp.max(jnp.abs(xf)) / levels
        scale = jnp.where(scale > 0, scale, 1.0)
        q = jnp.clip(jnp.round(xf / scale), -levels - 1, levels) * scale
    else:
        lo, hi = jnp.min(xf), jnp.max(xf)
        span = jnp.maximum(hi - lo, 1e-8)
        q = jnp.round((xf - lo) / span * levels) / levels * span + lo
    return _ste(x, q.astype(x.dtype))


# ----------------------------------------------------------------- pruning
def sparse_mask(w, ratio):
    """Unstructured magnitude mask: zero the smallest ``ratio`` fraction
    (reference sparse_pruning, method 'l1')."""
    k = int(round(w.size * (1.0 - ratio)))
    flat = jnp.abs(w.reshape(-1))
    if k <= 0:
        return jnp.zeros_like(w, dtype=bool)
    thresh = jnp.sort(flat)[-k]
    return (jnp.abs(w) >= thresh)


def row_mask(w, ratio, axis=0):
    """Structured mask zeroing the lowest-L1 rows along ``axis``
    (reference row_pruning)."""
    other = tuple(i for i in range(w.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(w), axis=other)
    n = norms.shape[0]
    keep = max(1, int(round(n * (1.0 - ratio))))
    thresh = jnp.sort(norms)[-keep]
    mask1d = norms >= thresh
    shape = [1] * w.ndim
    shape[axis] = n
    return jnp.broadcast_to(mask1d.reshape(shape), w.shape)


def head_mask(w, ratio, num_heads, head_axis=-1):
    """Zero whole attention heads by L1 norm: w's ``head_axis`` dim is
    split into ``num_heads`` groups (reference head_pruning on the
    attention output projection)."""
    ax = head_axis % w.ndim
    d = w.shape[ax]
    assert d % num_heads == 0, (d, num_heads)
    hd = d // num_heads
    moved = jnp.moveaxis(w, ax, 0).reshape(num_heads, hd, -1)
    norms = jnp.sum(jnp.abs(moved), axis=(1, 2))
    keep = max(1, int(round(num_heads * (1.0 - ratio))))
    thresh = jnp.sort(norms)[-keep]
    mask_h = norms >= thresh                       # (H,)
    mask = jnp.broadcast_to(mask_h[:, None, None], moved.shape)
    mask = mask.reshape(num_heads * hd, -1)
    mask = jnp.moveaxis(mask.reshape((d,) + tuple(
        s for i, s in enumerate(jnp.moveaxis(w, ax, 0).shape) if i > 0)),
        0, ax)
    return mask


def apply_mask(w, mask):
    """STE-masked weight: forward zeroed, grads still reach the master
    (reference keeps the mask fixed and multiplies in forward)."""
    return _ste(w, w * mask.astype(w.dtype))
