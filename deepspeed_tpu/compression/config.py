"""Compression config parsing (reference compression/config.py +
constants.py, condensed to the knobs the functional ops support).

Layout (mirrors the reference's ``compression_training`` block):

    "compression_training": {
      "weight_quantization": {
        "shared_parameters": {"enabled": true, "quantizer_kernel": false,
          "schedule_offset": 0, "quantize_groups": 1,
          "quantization_type": "symmetric"},
        "different_groups": {
          "wq1": {"params": {"target_bits": 8},
                   "modules": ["blocks/wqkv", "blocks/w.*"]}}},
      "activation_quantization": {...},
      "sparse_pruning":   {... "params": {"dense_ratio": 0.5}},
      "row_pruning":      {...},
      "head_pruning":     {... "params": {"dense_ratio": 0.5,
                                           "num_heads": 12}}
    }

``modules`` are REGEX patterns matched against '/'-joined param-tree
paths (the functional analogue of module names).
"""

COMPRESSION_TRAINING = "compression_training"

TECHNIQUES = ("weight_quantization", "activation_quantization",
              "sparse_pruning", "row_pruning", "head_pruning")


def get_compression_config(ds_config):
    """-> {technique: {"shared": {...}, "groups": [ {name, params,
    modules} ]}} for enabled techniques."""
    block = (ds_config or {}).get(COMPRESSION_TRAINING, {})
    out = {}
    for tech in TECHNIQUES:
        sub = block.get(tech)
        if not sub:
            continue
        shared = dict(sub.get("shared_parameters", {}))
        if not shared.get("enabled", False):
            continue
        groups = []
        for name, g in sub.get("different_groups", {}).items():
            groups.append({"name": name,
                           "params": dict(g.get("params", {})),
                           "modules": list(g.get("modules", ["*"]))})
        out[tech] = {"shared": shared, "groups": groups}
    return out
