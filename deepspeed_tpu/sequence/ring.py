"""Zigzag ring attention: flash-kernel blockwise context parallelism.

NOT in the reference (SURVEY §2.5/§5.7: this DeepSpeed version's only long
-sequence tool is Ulysses + sparse attention) — built here because ring/
blockwise attention is the natural TPU extension: KV chunks rotate around
the 'seq' axis ring via ``ppermute`` (ICI neighbor traffic, overlapped
with the per-chunk attention compute), and softmax state is carried
flash-style, so no device ever materializes the full (T, T) score matrix
OR the full KV — sequence length scales linearly with ring size at
constant memory per chip.

Three fixes over the round-1 naive ring (dense per-step einsum over every
block pair, masked after the fact):

1. **Zigzag layout** (Ring Attention, Liu et al. 2023; Striped/zigzag,
   Brandon et al. 2023): each rank holds one EARLY chunk and its MIRRORED
   late chunk (rank r owns chunks r and 2R-1-r of 2R). Under causal
   attention this makes every rank's per-step work identical — with the
   contiguous layout rank 0 attends almost nothing while the last rank
   pays the full triangle — and, crucially for SPMD, makes the per-step
   mask mode STATIC: step 0 is exactly plain causal attention on the
   local [early|late] buffer, and every later step is two fully-visible
   (unmasked) equal-size chunk pairs. Fully-masked pairs are never
   computed at all (``ring_flops_info`` accounts them; the naive ring
   paid ~2x the causal FLOPs).
2. **Flash-kernel chunk pairs**: each surviving pair runs through the
   carry-in/carry-out blockwise Pallas kernel
   (ops/pallas/flash_attention.py ``flash_block_fwd``) chaining the
   running (m, l, acc) online-softmax state; the backward replays each
   pair through the existing fused flash backward with the global lse
   (``flash_block_bwd``). ``block_kernel=False`` keeps a dense-einsum
   block step with the identical state algebra (parity/reference path).
3. **Overlapped, fused KV rotation**: k and v travel as ONE stacked
   buffer (one collective per rotation, not two), the rotation for step
   i+1 is issued before step i's kernels so XLA's latency-hiding
   scheduler slides it under the compute (``double_buffer=True``), and
   the final step issues no dead rotation. In the backward, the dk/dv
   accumulators travel with the kv buffer and one extra rotation delivers
   them home.

Ulysses vs ring trade-off (why both exist): Ulysses needs head_count >=
ring size and moves activations twice through all-to-all; ring moves KV
P-1 times through neighbor exchange but has no head-count constraint and
composes with TP (``head_axis``) and any per-chunk kernel.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..utils.groups import BATCH_AXES

NEG_INF = -1e30


# ------------------------------------------------------------ zigzag layout

def _zig_owner(c, R):
    """Rank owning global chunk c (of 2R) under the zigzag layout."""
    return c if c < R else 2 * R - 1 - c


def zigzag_perms(R):
    """ppermute perms routing the contiguous layout's (2r, 2r+1) chunk
    pair to the zigzag owners: perm_even carries the even chunk 2r,
    perm_odd the odd chunk 2r+1. Both are rank bijections (an even chunk
    lands early on an even rank, late on an odd one — and vice versa)."""
    perm_even = [(r, _zig_owner(2 * r, R)) for r in range(R)]
    perm_odd = [(r, _zig_owner(2 * r + 1, R)) for r in range(R)]
    return perm_even, perm_odd


def _to_zigzag(x, axis_name, R, axis=1):
    """Contiguous-sharded local chunk (global [2r*C, (2r+2)*C)) ->
    zigzag local [chunk r | chunk 2R-1-r]. Two chunk-sized ppermutes;
    differentiable (ppermute transposes to the inverse permute)."""
    C = x.shape[axis] // 2
    pe, po = zigzag_perms(R)
    a = lax.ppermute(lax.slice_in_dim(x, 0, C, axis=axis), axis_name, pe)
    b = lax.ppermute(lax.slice_in_dim(x, C, 2 * C, axis=axis),
                     axis_name, po)
    even = (lax.axis_index(axis_name) % 2) == 0
    return jnp.where(even, jnp.concatenate([a, b], axis=axis),
                     jnp.concatenate([b, a], axis=axis))


def _from_zigzag(x, axis_name, R, axis=1):
    """Inverse of :func:`_to_zigzag`."""
    C = x.shape[axis] // 2
    pe, po = zigzag_perms(R)
    inv_e = [(d, s) for (s, d) in pe]
    inv_o = [(d, s) for (s, d) in po]
    early = lax.slice_in_dim(x, 0, C, axis=axis)
    late = lax.slice_in_dim(x, C, 2 * C, axis=axis)
    even = (lax.axis_index(axis_name) % 2) == 0
    a = lax.ppermute(jnp.where(even, early, late), axis_name, inv_e)
    b = lax.ppermute(jnp.where(even, late, early), axis_name, inv_o)
    return jnp.concatenate([a, b], axis=axis)


# ------------------------------------------------------------- block steps
# The per-chunk-pair step in two interchangeable backends sharing the
# exact (m, l, acc) state algebra: the Pallas carry-state flash kernel
# (the measured hot path) and a dense einsum reference.

def _fold(x):
    """(B, t, H, D) -> (B*H, t, D)."""
    B, t, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, t, D)


def _unfold(x, B, H):
    BH, t, D = x.shape
    return x.reshape(B, H, t, D).transpose(0, 2, 1, 3)


def _step_einsum(q, k, v, state, causal):
    """Dense-einsum block step, algebraically identical to the kernel:
    q (BH, T, d) pre-scaled; state (m, l, acc) fp32."""
    m, l, acc = state
    s = jnp.einsum("gtd,gsd->gts", q, k,
                   preferred_element_type=jnp.float32)
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        s = jnp.where(mask[None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l = l * alpha + jnp.sum(p, axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum(
        "gts,gsd->gtd", p, v.astype(jnp.float32))
    return m_new, l, acc


def _bwd_einsum(q, k, v, o, lse, do, causal):
    """Dense-einsum pair backward from the GLOBAL lse/o (the flash-bwd
    recompute): exact contributions, fp32 throughout."""
    s = jnp.einsum("gtd,gsd->gts", q.astype(jnp.float32),
                   k.astype(jnp.float32))
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        s = jnp.where(mask[None], s, NEG_INF)
    p = jnp.exp(s - lse[..., None])
    dof = do.astype(jnp.float32)
    of = o.astype(jnp.float32)
    delta = jnp.sum(dof * of, axis=-1)
    dv = jnp.einsum("gts,gtd->gsd", p, dof)
    dp = jnp.einsum("gtd,gsd->gts", dof, v.astype(jnp.float32))
    ds = p * (dp - delta[..., None])
    dk = jnp.einsum("gts,gtd->gsd", ds, q.astype(jnp.float32))
    dq = jnp.einsum("gts,gsd->gtd", ds, k.astype(jnp.float32))
    return dq, dk, dv


def _make_steps(use_kernel, bq, bk, bh, interpret):
    if not use_kernel:
        return _step_einsum, _bwd_einsum
    from ..ops.pallas.flash_attention import (flash_block_bwd,
                                              flash_block_fwd)

    def fwd(q, k, v, st, causal):
        return flash_block_fwd(q, k, v, st, causal=causal, block_q=bq,
                               block_k=bk, block_h=bh,
                               interpret=interpret)

    def bwd(q, k, v, o, lse, do, causal):
        return flash_block_bwd(q, k, v, o, lse, do, causal=causal,
                               block_q=bq, block_k=bk, block_h=bh,
                               interpret=interpret)
    return fwd, bwd


def _tree_where(pred, a, b):
    return tuple(jnp.where(pred, x, y) for x, y in zip(a, b))


# --------------------------------------------------------- rotation driver

def _rotate(x, axis_name, perm, chunks=1):
    """One ring rotation of the (stacked) KV buffer. ``chunks=1`` is the
    single fused ppermute — bit-identical to the pre-knob program.
    ``chunks>1`` splits the head dim into that many ppermutes so the
    first chunk can land (and feed the next kernel's first tiles) while
    the rest is still on the wire; whether the extra collective launches
    beat one fused transfer is a measured property of the ICI link (the
    'ring_rotate' autotune op / ``sequence.rotate_chunks`` knob). A
    non-dividing chunk count degrades to the fused rotation."""
    c = int(chunks)
    if c <= 1 or x.shape[-1] % c:
        return lax.ppermute(x, axis_name, perm)
    return jnp.concatenate(
        [lax.ppermute(p, axis_name, perm)
         for p in jnp.split(x, c, axis=-1)], axis=-1)


def _ring_scan(kv, state, step0_fn, step_fn, axis_name, R, double_buffer,
               rotate_chunks=1):
    """R compute steps, R-1 KV rotations, no dead last rotation.

    ``double_buffer=True`` issues each rotation BEFORE the compute it
    overlaps (the compute reads the previous buffer, so XLA's latency-
    hiding scheduler slides the collective-permute under the kernels);
    ``False`` is the serialized rotate-then-compute order (A/B lever).
    The rotation lives INSIDE the scan body either way — the placement
    ``engine.verify_comm_overlap`` reports."""
    if R == 1:
        return step0_fn(state, kv)
    perm = [(j, (j + 1) % R) for j in range(R)]
    if double_buffer:
        kv_nxt = _rotate(kv, axis_name, perm, rotate_chunks)  # overlaps step 0
        state = step0_fn(state, kv)

        def body(carry, s):
            st, kvb = carry
            kvn = _rotate(kvb, axis_name, perm, rotate_chunks)
            st = step_fn(st, kvb, s)
            return (st, kvn), None

        if R > 2:
            (state, kv_last), _ = lax.scan(
                body, (state, kv_nxt), jnp.arange(1, R - 1))
        else:
            kv_last = kv_nxt
        return step_fn(state, kv_last, R - 1)

    state = step0_fn(state, kv)

    def body(carry, s):
        st, kvb = carry
        kvb = _rotate(kvb, axis_name, perm, rotate_chunks)
        st = step_fn(st, kvb, s)
        return (st, kvb), None

    (state, _), _ = lax.scan(body, (state, kv), jnp.arange(1, R))
    return state


def _ring_bwd_scan(kv, dq0, dkv0, step_bwd, axis_name, R,
                   rotate_chunks=1):
    """Backward rotation driver: the dk/dv accumulators travel WITH the
    kv buffer (each rank adds its contribution to whatever kv it holds),
    and ONE extra rotation after the last step delivers them home."""
    if R == 1:
        return dq0, dkv0
    perm = [(j, (j + 1) % R) for j in range(R)]

    def body(carry, s):
        dq, kvb, dkvb = carry
        kvb = _rotate(kvb, axis_name, perm, rotate_chunks)
        dkvb = _rotate(dkvb, axis_name, perm, rotate_chunks)
        dq, dkvb = step_bwd(dq, kvb, dkvb, s)
        return (dq, kvb, dkvb), None

    (dq, _, dkv), _ = lax.scan(body, (dq0, kv, dkv0), jnp.arange(1, R))
    return dq, _rotate(dkv, axis_name, perm, rotate_chunks)


# ------------------------------------------------------ zigzag causal core

def _zig_step(st, kvb, s, *, qf, r, C, step):
    """One zigzag ring step s >= 1: always the (q_late x kv_early) full
    pair, plus ONE more full pair selected by the traced wrap predicate
    (s <= r: q_early x kv_early; else q_late x kv_late) — both branches
    identical in shape/cost, so SPMD stays a single static program and
    every rank does exactly two C x C unmasked pairs per step."""
    kf, vf = kvb[0], kvb[1]
    q_late = qf[:, C:]
    ke, ve = kf[:, :C], vf[:, :C]
    m, l, acc = st
    st_e = (m[:, :C], l[:, :C], acc[:, :C])
    st_l = (m[:, C:], l[:, C:], acc[:, C:])
    st_l = step(q_late, ke, ve, st_l, False)
    pred = s <= r
    qc = jnp.where(pred, qf[:, :C], q_late)
    kc = jnp.where(pred, ke, kf[:, C:])
    vc = jnp.where(pred, ve, vf[:, C:])
    st_out = step(qc, kc, vc, _tree_where(pred, st_e, st_l), False)
    st_e = _tree_where(pred, st_out, st_e)
    st_l = _tree_where(pred, st_l, st_out)
    return tuple(jnp.concatenate([a, b], axis=1)
                 for a, b in zip(st_e, st_l))


def _zig_step_bwd(dq, kvb, dkvb, s, *, qf, of, lsef, dof, r, C, bstep):
    kf, vf = kvb[0], kvb[1]
    dqa, dka, dva = bstep(qf[:, C:], kf[:, :C], vf[:, :C], of[:, C:],
                          lsef[:, C:], dof[:, C:], False)
    dq = dq.at[:, C:].add(dqa.astype(jnp.float32))
    dkvb = dkvb.at[:, :, :C].add(
        jnp.stack([dka, dva]).astype(jnp.float32))
    pred = s <= r
    qc = jnp.where(pred, qf[:, :C], qf[:, C:])
    kc = jnp.where(pred, kf[:, :C], kf[:, C:])
    vc = jnp.where(pred, vf[:, :C], vf[:, C:])
    oc = jnp.where(pred, of[:, :C], of[:, C:])
    lc = jnp.where(pred, lsef[:, :C], lsef[:, C:])
    dc = jnp.where(pred, dof[:, :C], dof[:, C:])
    dqc, dkc, dvc = bstep(qc, kc, vc, oc, lc, dc, False)
    dqc = dqc.astype(jnp.float32)
    z = jnp.zeros_like(dqc)
    dq = dq.at[:, :C].add(jnp.where(pred, dqc, z))
    dq = dq.at[:, C:].add(jnp.where(pred, z, dqc))
    dkv_c = jnp.stack([dkc, dvc]).astype(jnp.float32)
    z2 = jnp.zeros_like(dkv_c)
    dkvb = dkvb.at[:, :, :C].add(jnp.where(pred, dkv_c, z2))
    dkvb = dkvb.at[:, :, C:].add(jnp.where(pred, z2, dkv_c))
    return dq, dkvb


def _zig_fwd_impl(q, k, v, axis_name, R, scale, use_kernel, bq, bk, bh,
                  interpret, double_buffer, rotate_chunks):
    """Zigzag-local (B, 2C, H, D) q/k/v -> (o, lse folded). Step 0 is
    plain causal attention on the local buffer (the zigzag pair's local
    order IS the global causal order), later steps unmasked pairs."""
    from ..ops.pallas.flash_attention import (flash_block_finalize,
                                              flash_block_state)
    B, Tl, H, D = q.shape
    C = Tl // 2
    r = lax.axis_index(axis_name)
    step, _ = _make_steps(use_kernel, bq, bk, bh, interpret)
    qf = _fold(q) * jnp.asarray(scale, q.dtype)
    kv = jnp.stack([_fold(k), _fold(v)])         # fused rotation buffer
    state = flash_block_state(B * H, Tl, D)

    def step0(st, kvb):
        return step(qf, kvb[0], kvb[1], st, True)

    state = _ring_scan(
        kv, state, step0,
        functools.partial(_zig_step, qf=qf, r=r, C=C, step=step),
        axis_name, R, double_buffer, rotate_chunks)
    of, lse = flash_block_finalize(state)
    o = of.astype(q.dtype)
    return _unfold(o, B, H), (o, lse)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11, 12))
def _ring_zigzag(q, k, v, axis_name, R, scale, use_kernel, bq, bk, bh,
                 interpret, double_buffer, rotate_chunks):
    o, _ = _zig_fwd_impl(q, k, v, axis_name, R, scale, use_kernel, bq,
                         bk, bh, interpret, double_buffer, rotate_chunks)
    return o


def _ring_zigzag_fwd(q, k, v, axis_name, R, scale, use_kernel, bq, bk,
                     bh, interpret, double_buffer, rotate_chunks):
    o, (of, lsef) = _zig_fwd_impl(q, k, v, axis_name, R, scale,
                                  use_kernel, bq, bk, bh, interpret,
                                  double_buffer, rotate_chunks)
    return o, (q, k, v, of, lsef)


def _ring_zigzag_bwd(axis_name, R, scale, use_kernel, bq, bk, bh,
                     interpret, double_buffer, rotate_chunks, res, do):
    q, k, v, of, lsef = res
    B, Tl, H, D = q.shape
    C = Tl // 2
    r = lax.axis_index(axis_name)
    _, bstep = _make_steps(use_kernel, bq, bk, bh, interpret)
    qf = _fold(q) * jnp.asarray(scale, q.dtype)
    dof = _fold(do)
    kv = jnp.stack([_fold(k), _fold(v)])

    dq0a, dk0, dv0 = bstep(qf, kv[0], kv[1], of, lsef, dof, True)
    dq0 = dq0a.astype(jnp.float32)
    dkv0 = jnp.stack([dk0, dv0]).astype(jnp.float32)
    dq, dkv = _ring_bwd_scan(
        kv, dq0, dkv0,
        functools.partial(_zig_step_bwd, qf=qf, of=of, lsef=lsef,
                          dof=dof, r=r, C=C, bstep=bstep),
        axis_name, R, rotate_chunks)
    dq = dq * scale                   # q was pre-scaled into the kernels
    return (_unfold(dq, B, H).astype(q.dtype),
            _unfold(dkv[0], B, H).astype(k.dtype),
            _unfold(dkv[1], B, H).astype(v.dtype))


_ring_zigzag.defvjp(_ring_zigzag_fwd, _ring_zigzag_bwd)


# -------------------------------------------------- non-causal (full) core

def _full_fwd_impl(q, k, v, axis_name, R, scale, use_kernel, bq, bk, bh,
                   interpret, double_buffer, rotate_chunks):
    from ..ops.pallas.flash_attention import (flash_block_finalize,
                                              flash_block_state)
    B, Tl, H, D = q.shape
    step, _ = _make_steps(use_kernel, bq, bk, bh, interpret)
    qf = _fold(q) * jnp.asarray(scale, q.dtype)
    kv = jnp.stack([_fold(k), _fold(v)])
    state = flash_block_state(B * H, Tl, D)

    def pair(st, kvb):
        return step(qf, kvb[0], kvb[1], st, False)

    state = _ring_scan(kv, state, pair, lambda st, kvb, s: pair(st, kvb),
                       axis_name, R, double_buffer, rotate_chunks)
    of, lse = flash_block_finalize(state)
    o = of.astype(q.dtype)
    return _unfold(o, B, H), (o, lse)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11, 12))
def _ring_full(q, k, v, axis_name, R, scale, use_kernel, bq, bk, bh,
               interpret, double_buffer, rotate_chunks):
    o, _ = _full_fwd_impl(q, k, v, axis_name, R, scale, use_kernel, bq,
                          bk, bh, interpret, double_buffer, rotate_chunks)
    return o


def _ring_full_fwd(q, k, v, axis_name, R, scale, use_kernel, bq, bk, bh,
                   interpret, double_buffer, rotate_chunks):
    o, (of, lsef) = _full_fwd_impl(q, k, v, axis_name, R, scale,
                                   use_kernel, bq, bk, bh, interpret,
                                   double_buffer, rotate_chunks)
    return o, (q, k, v, of, lsef)


def _ring_full_bwd(axis_name, R, scale, use_kernel, bq, bk, bh, interpret,
                   double_buffer, rotate_chunks, res, do):
    q, k, v, of, lsef = res
    B, Tl, H, D = q.shape
    _, bstep = _make_steps(use_kernel, bq, bk, bh, interpret)
    qf = _fold(q) * jnp.asarray(scale, q.dtype)
    dof = _fold(do)
    kv = jnp.stack([_fold(k), _fold(v)])

    def pair_bwd(dq, kvb, dkvb, s):
        dqs, dks, dvs = bstep(qf, kvb[0], kvb[1], of, lsef, dof, False)
        dq = dq + dqs.astype(jnp.float32)
        dkvb = dkvb + jnp.stack([dks, dvs]).astype(jnp.float32)
        return dq, dkvb

    dq0, dkv0 = pair_bwd(jnp.zeros(qf.shape, jnp.float32), kv,
                         jnp.zeros(kv.shape, jnp.float32), 0)
    dq, dkv = _ring_bwd_scan(kv, dq0, dkv0, pair_bwd, axis_name, R,
                             rotate_chunks)
    dq = dq * scale
    return (_unfold(dq, B, H).astype(q.dtype),
            _unfold(dkv[0], B, H).astype(k.dtype),
            _unfold(dkv[1], B, H).astype(v.dtype))


_ring_full.defvjp(_ring_full_fwd, _ring_full_bwd)


# -------------------------------------------- contiguous causal (fallback)

def _ring_contiguous(q, k, v, axis_name, ring, scale):
    """The pre-zigzag dense path, kept for ``layout='contiguous'``: every
    block pair is computed and then positionally masked (the mask depends
    on the traced rank, so no pair can be statically skipped — the ~2x
    causal FLOPs overhead zigzag exists to remove). KV rotates as one
    fused stacked buffer."""
    my_block = lax.axis_index(axis_name)
    B, T, H, D = q.shape

    m0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    acc0 = jnp.zeros((B, T, H, D), jnp.float32)
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    @jax.checkpoint
    def accumulate(m, l, acc, kk, vv, i):
        # after i rotations this device holds block (my_block - i) mod ring
        src = (my_block - i) % ring
        scores = jnp.einsum("bthd,bshd->bhts", q, kk,
                            preferred_element_type=jnp.float32) * scale
        q_pos = my_block * T + jnp.arange(T)
        kv_pos = src * T + jnp.arange(T)
        mask = q_pos[:, None] >= kv_pos[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
        s_max = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, s_max)
        p = jnp.exp(scores - m_new[..., None])          # (B,H,T,S) fp32
        corr = jnp.exp(m - m_new)                       # (B,H,T)
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhts,bshd->bthd", p, vv.astype(jnp.float32))
        acc = acc * corr.transpose(0, 2, 1)[..., None] + pv
        return m_new, l, acc

    def step(carry, i):
        m, l, acc, kv = carry
        m, l, acc = accumulate(m, l, acc, kv[0], kv[1], i)
        kv = lax.ppermute(kv, axis_name, perm)
        return (m, l, acc, kv), None

    carry = (m0, l0, acc0, jnp.stack([k, v]))
    if ring > 1:
        # scan the first ring-1 blocks (rotation at step end); the final
        # block accumulates outside so no dead last rotation is issued
        carry, _ = lax.scan(step, carry, jnp.arange(ring - 1))
    m, l, acc, kv = carry
    m, l, acc = accumulate(m, l, acc, kv[0], kv[1], ring - 1)
    out = acc / jnp.clip(l, 1e-30, None).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# ------------------------------------------------------------- public API

def _resolve_blocks(block_kernel, chunk, D, dtype):
    """(use_kernel, bq, bk, bh): False -> einsum blocks; True -> the r05
    ring-block defaults; 'auto' -> the autotune winner cache's measured
    tiles for this (device, chunk-bucket, dtype) (kernel_registry op
    'ring_block'; r05 defaults on a miss)."""
    from ..ops.pallas.flash_attention import RING_TUNE_DEFAULTS
    if block_kernel is False:
        d = RING_TUNE_DEFAULTS
        return False, int(d["block_q"]), int(d["block_k"]), \
            int(d["block_h"])
    if block_kernel == "auto":
        from ..ops.pallas._common import dispatch, dtype_name, ring_bucket
        win = dispatch("ring_block", ring_bucket(chunk, D),
                       dtype_name(dtype), RING_TUNE_DEFAULTS)
    else:
        win = RING_TUNE_DEFAULTS
    return True, int(win["block_q"]), int(win["block_k"]), \
        int(win["block_h"])


def _resolve_rotate(rotate_chunks, R, chunk, D, dtype):
    """Per-rotation ppermute split count: 'auto' -> the autotune winner
    cache's measured choice for this (device, topology, ring-bucket)
    (kernel_registry op 'ring_rotate'; 1 = the fused single-ppermute
    default on a miss). A count that doesn't divide the head dim
    degrades to fused — never crash the trace over a tuning knob."""
    if R <= 1:
        return 1
    if rotate_chunks == "auto":
        from ..ops.pallas._common import (dispatch, dtype_name,
                                          ring_rotate_bucket)
        win = dispatch("ring_rotate", ring_rotate_bucket(R, chunk, D),
                       dtype_name(dtype), {"chunks": 1})
        rc = int(win["chunks"])
    else:
        rc = int(rotate_chunks or 1)
    if rc > 1 and D % rc:
        rc = 1
    return max(1, rc)


def ring_attention(q, k, v, axis_name="seq", causal=True, *,
                   layout="zigzag", block_kernel="auto",
                   double_buffer=True, rotate_chunks="auto",
                   interpret=None, scale=None):
    """Blockwise ring attention over an axis group; call inside shard_map.

    q, k, v: (B, T_local, H, D) — this device's sequence block(s).
    Returns (B, T_local, H, D) attention output, exact (not approximate):
    carried online-softmax state is algebraically identical to dense
    softmax attention.

    ``layout='zigzag'`` (causal only): rebalances the causal triangle so
    every rank does identical work and fully-masked chunk pairs are
    statically skipped; inputs/outputs stay CONTIGUOUS-sharded — the
    zigzag redistribution is internal (two chunk ppermutes each way).
    ``block_kernel``: 'auto' (Pallas blockwise flash kernel, tiles from
    the autotune winner cache) | True (kernel, r05 tiles) | False (dense
    einsum block steps — the reference/parity path).
    """
    ring = lax.psum(1, axis_name)
    B, Tl, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if interpret is None:
        from ..ops.pallas._common import interpret_default
        interpret = interpret_default()
    if not causal:
        chunk = Tl
        use_kernel, bq, bk, bh = _resolve_blocks(block_kernel, chunk, D,
                                                 q.dtype)
        rc = _resolve_rotate(rotate_chunks, int(ring), chunk, D, q.dtype)
        return _ring_full(q, k, v, axis_name, int(ring), float(scale),
                          use_kernel, bq, bk, bh, bool(interpret),
                          bool(double_buffer), rc)
    if ring == 1:
        use_kernel, bq, bk, bh = _resolve_blocks(block_kernel, Tl, D,
                                                 q.dtype)
        return _ring_zigzag(q, k, v, axis_name, 1, float(scale),
                            use_kernel, bq, bk, bh, bool(interpret),
                            bool(double_buffer), 1)
    if layout not in ("zigzag", "contiguous"):
        raise ValueError(
            f"ring layout must be 'zigzag'|'contiguous', got {layout!r}")
    if layout == "zigzag" and Tl % 2 == 0:
        C = Tl // 2
        use_kernel, bq, bk, bh = _resolve_blocks(block_kernel, C, D,
                                                 q.dtype)
        rc = _resolve_rotate(rotate_chunks, int(ring), C, D, q.dtype)
        qkv = _to_zigzag(jnp.stack([q, k, v]), axis_name, int(ring),
                         axis=2)
        o = _ring_zigzag(qkv[0], qkv[1], qkv[2], axis_name, int(ring),
                         float(scale), use_kernel, bq, bk, bh,
                         bool(interpret), bool(double_buffer), rc)
        return _from_zigzag(o, axis_name, int(ring), axis=1)
    if layout == "zigzag":
        # odd local chunk: the early/late split doesn't exist — loudly
        # degrade to the compute-then-mask path (~2x causal FLOPs, dense
        # fp32 score blocks) rather than silently, so an A/B that
        # believes it measured zigzag can see the cliff in its logs
        from ..utils.logging import logger
        logger.warning(
            f"ring zigzag needs an even per-rank chunk (got T_local="
            f"{Tl}); falling back to the contiguous masked-einsum path")
    return _ring_contiguous(q, k, v, axis_name, ring, scale)


def ring_flops_info(ring, T_local, causal=True, layout="zigzag"):
    """STATIC block-pair accounting for one rank, in C x C chunk-pair
    units (C = T_local // 2 under zigzag). ``computed_pairs`` counts
    kernel invocations' coverage (a diagonal-causal pair counts 1 unit
    of coverage but ~1/2 the FLOPs), ``skipped_pairs`` the fully-masked
    pairs the schedule never computes — the naive ring computed (then
    masked) every one of them. The causal-FLOPs acceptance assertion
    reads this alongside the lowered cost analysis."""
    R = int(ring)
    if R == 1 and causal:
        # one 2C x 2C causal call covers all 4 units (upper triangle
        # skipped in-kernel at block grain)
        return {"computed_pairs": 4, "diagonal_pairs": 4,
                "skipped_pairs": 0, "total_pairs": 4}
    if not causal:
        # every pair is live — nothing to skip
        return {"computed_pairs": 4 * R, "diagonal_pairs": 0,
                "skipped_pairs": 0, "total_pairs": 4 * R}
    if layout != "zigzag":
        return {"computed_pairs": 4 * R, "diagonal_pairs": 0,
                "skipped_pairs": 0, "total_pairs": 4 * R}
    # step 0: a 2C x 2C causal call covers 4 units (its upper triangle is
    # in-kernel skipped at block grain); steps 1..R-1: two C x C pairs
    computed = 4 + 2 * (R - 1)
    total = 4 * R
    return {"computed_pairs": computed, "diagonal_pairs": 4,
            "skipped_pairs": total - computed, "total_pairs": total}


def ring_attention_sharded(q, k, v, mesh, *, axis_name="seq", causal=True,
                           batch_spec=P(BATCH_AXES), head_axis=None,
                           layout="zigzag", block_kernel="auto",
                           double_buffer=True, rotate_chunks="auto",
                           interpret=None):
    """Global-array entry: q/k/v (B, T, H, D) sequence-sharded on
    ``axis_name``; exact causal attention over the full sequence.
    ``head_axis``: optionally shard heads too (ring-CP x TP composition).
    Layout/kernel knobs per the runtime config's ``sequence`` block
    (see :func:`ring_attention`)."""
    spec = P(*batch_spec, axis_name, head_axis, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal, layout=layout,
                          block_kernel=block_kernel,
                          double_buffer=double_buffer,
                          rotate_chunks=rotate_chunks,
                          interpret=interpret),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
