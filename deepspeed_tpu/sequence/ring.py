"""Ring attention: context parallelism for arbitrarily long sequences.

NOT in the reference (SURVEY §2.5/§5.7: this DeepSpeed version's only long
-sequence tool is Ulysses + sparse attention) — built here because ring/
blockwise attention is the natural TPU extension: KV blocks rotate around
the 'seq' axis ring via ``ppermute`` (ICI neighbor traffic, fully
overlappable with the per-block attention compute), and softmax is
accumulated online flash-style, so no device ever materializes the full
(T, T) score matrix OR the full KV — sequence length scales linearly with
ring size at constant memory per chip.

Ulysses vs ring trade-off (why both exist): Ulysses needs head_count >=
ring size and moves activations twice through all-to-all; ring moves KV
P-1 times through neighbor exchange but has no head-count constraint and
composes with any per-block kernel (e.g. the Pallas flash kernel).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..utils.groups import BATCH_AXES

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name="seq", causal=True):
    """Blockwise ring attention over an axis group; call inside shard_map.

    q, k, v: (B, T_local, H, D) — this device's sequence block.
    Returns (B, T_local, H, D) attention output, exact (not approximate):
    online-softmax accumulation is algebraically identical to dense
    softmax attention.
    """
    ring = lax.psum(1, axis_name)
    my_block = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = 1.0 / math.sqrt(D)

    m0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    acc0 = jnp.zeros((B, T, H, D), jnp.float32)
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    @jax.checkpoint
    def accumulate(m, l, acc, kk, vv, i):
        # after i rotations this device holds block (my_block - i) mod ring
        src = (my_block - i) % ring
        scores = jnp.einsum("bthd,bshd->bhts", q, kk,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = my_block * T + jnp.arange(T)
            kv_pos = src * T + jnp.arange(T)
            mask = q_pos[:, None] >= kv_pos[None, :]
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        s_max = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, s_max)
        p = jnp.exp(scores - m_new[..., None])          # (B,H,T,S) fp32
        corr = jnp.exp(m - m_new)                       # (B,H,T)
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhts,bshd->bthd", p, vv.astype(jnp.float32))
        acc = acc * corr.transpose(0, 2, 1)[..., None] + pv
        return m_new, l, acc

    def step(carry, i):
        m, l, acc, kk, vv = carry
        m, l, acc = accumulate(m, l, acc, kk, vv, i)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (m, l, acc, kk, vv), None

    carry = (m0, l0, acc0, k, v)
    if ring > 1:
        # scan the first ring-1 blocks (rotation at step end); the final
        # block accumulates outside so no dead last rotation is issued
        carry, _ = lax.scan(step, carry, jnp.arange(ring - 1))
    m, l, acc, kk, vv = carry
    m, l, acc = accumulate(m, l, acc, kk, vv, ring - 1)
    out = acc / jnp.clip(l, 1e-30, None).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, *, axis_name="seq", causal=True,
                           batch_spec=P(BATCH_AXES),
                           head_axis=None):
    """Global-array entry: q/k/v (B, T, H, D) sequence-sharded on
    ``axis_name``; exact causal attention over the full sequence.
    ``head_axis``: optionally shard heads too (ring-CP x TP composition)."""
    spec = P(*batch_spec, axis_name, head_axis, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
