"""Sequence/context parallelism for long sequences.

Counterpart of the reference's ``deepspeed/sequence/`` (Ulysses,
layer.py:60 DistributedAttention) plus ring attention — absent in the
reference (SURVEY §2.5 notes Ulysses-only) but first-class here."""

from .layer import DistributedAttention, single_all_to_all, ulysses_attention
from .ring import ring_attention, ring_attention_sharded

__all__ = ["DistributedAttention", "single_all_to_all", "ulysses_attention",
           "ring_attention", "ring_attention_sharded"]
