"""Ulysses sequence parallelism: head-scatter / seq-gather all-to-all.

Counterpart of the reference's ``deepspeed/sequence/layer.py``
(single_all_to_all :15, _SeqAllToAll :44, DistributedAttention :60). Same
dataflow — q/k/v arrive sequence-sharded, an all-to-all trades the head dim
for the full sequence, any local attention runs, and the reverse all-to-all
restores sequence sharding — but expressed as ``lax.all_to_all`` inside
``shard_map`` on the 'seq' mesh axis instead of torch.distributed
all_to_all_single on an SP process group. Autodiff differentiates through
the collective, so no hand-written backward (_SeqAllToAll.backward) is
needed.

GPT-2's declarative path (models/gpt2.py: resharding constraints) lets
GSPMD place the same pair automatically; this module is the *explicit*
form for wrapping arbitrary local-attention implementations (the
reference's use case: flash-attn under Ulysses).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..utils.groups import BATCH_AXES


def single_all_to_all(x, scatter_idx, gather_idx, axis_name):
    """All-to-all inside shard_map: split ``scatter_idx`` across the axis
    group, concatenate along ``gather_idx`` (reference sequence/layer.py:15;
    tiled=True matches its reshape+all_to_all_single layout)."""
    return lax.all_to_all(x, axis_name, split_axis=scatter_idx,
                          concat_axis=gather_idx, tiled=True)


class DistributedAttention:
    """Wrap a local attention fn for Ulysses SP (reference layer.py:60).

    ``local_attn(q, k, v, *args, **kwargs)`` operates on (B, T, H/P, D)
    full-sequence, head-sharded blocks. __call__ receives (B, T/P, H, D)
    sequence-sharded blocks (must run inside shard_map over ``axis_name``).
    """

    def __init__(self, local_attn, axis_name="seq", scatter_idx=2,
                 gather_idx=1):
        self.local_attn = local_attn
        self.axis_name = axis_name
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx

    def __call__(self, query, key, value, *args, **kwargs):
        s, g = self.scatter_idx, self.gather_idx
        q = single_all_to_all(query, s, g, self.axis_name)
        k = single_all_to_all(key, s, g, self.axis_name)
        v = single_all_to_all(value, s, g, self.axis_name)
        out = self.local_attn(q, k, v, *args, **kwargs)
        # reverse: scatter seq back, gather heads
        return single_all_to_all(out, g, s, self.axis_name)


def _dense_causal_attention(q, k, v):
    """Reference local attention: causal softmax(QK^T/sqrt(d))V, fp32
    scores. q/k/v: (B, T, H, D)."""
    T = q.shape[1]
    scores = jnp.einsum("bthd,bshd->bhts", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(q.shape[-1])
    causal = jnp.tril(jnp.ones((T, T), jnp.bool_))
    scores = jnp.where(causal[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def ulysses_attention(q, k, v, mesh, *, axis_name="seq", local_attn=None,
                      batch_spec=P(BATCH_AXES)):
    """Global-array entry: q/k/v (B, T, H, D) sequence-sharded on
    ``axis_name``; returns attention output with the same sharding."""
    local_attn = local_attn or _dense_causal_attention
    dist = DistributedAttention(local_attn, axis_name)
    spec = P(*batch_spec, axis_name, None, None)
    fn = jax.shard_map(dist, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
