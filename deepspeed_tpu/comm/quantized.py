"""Quantized collectives — ZeRO++ communication compression.

Counterpart of reference ``runtime/comm/coalesced_collectives.py:32
all_to_all_quant_reduce`` / ``reduce_scatter_coalesced`` and the
``csrc/quantization`` swizzled-quant + dequant-reduce kernels: gradients
cross the wire as int8 blocks + fp32 scales (4x less than fp32, 2x less
than bf16), reduced in fp32 after dequantization.

This module is the comm-layer surface: it adds comms-logger accounting and
the hierarchical two-stage composition on top of the transport primitives
in ``ops/pallas/quantization.py`` (quantized_all_gather /
quantized_psum_scatter — quantize/dequantize kernels + wire format live
there, in one place). Everything runs INSIDE ``shard_map`` bodies. The
hierarchical ``all_to_all_quant_reduce`` is the ZeRO++ two-stage scheme on
its natural TPU axes: stage 1 reduce-scatters over the inner 'data' axis
(ICI), stage 2 over 'data_outer' (DCN) — each hop quantized independently,
matching the reference's intra-node / inter-node split.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pallas import quantization as q8
from .logging import get_comms_logger


def _record_wire(op_name, n_elems, block, axis_name):
    """Log the ACTUAL bytes on the wire: int8 payload + one fp32 scale per
    block (logging the fp32 input would claim quantization saves nothing).
    """
    lg = get_comms_logger()
    if lg.enabled:
        nblocks = -(-n_elems // block)
        lg.append(op_name, n_elems + 4 * nblocks, axis_name)


def _resolve_pallas(use_pallas):
    """Inside shard_map the pallas CPU interpreter trips the varying-axes
    check, so default to the XLA fallback path off-TPU (numerically
    identical; the pallas kernel is a TPU-bandwidth optimization)."""
    if use_pallas is None:
        return jax.default_backend() == "tpu"
    return use_pallas


def quantized_reduce_scatter(x, axis_name, average=False,
                             block=q8.QUANT_BLOCK, use_pallas=None):
    """Reduce-scatter with int8-compressed exchange. x: (N, ...) with N
    divisible by the axis size W; returns this device's reduced
    (N // W, ...) fp32 piece (same piece order as ``lax.psum_scatter``)."""
    _record_wire("quantized_reduce_scatter", int(x.size), block,
                 axis_name)
    out = q8.quantized_psum_scatter(x.astype(jnp.float32), axis_name,
                                    block=block,
                                    use_pallas=_resolve_pallas(use_pallas))
    return out / lax.axis_size(axis_name) if average else out


def quantized_all_gather(x, axis_name, block=q8.QUANT_BLOCK,
                         use_pallas=None):
    """All-gather with int8-compressed exchange (reference quantized
    weight allgather, partition_parameters.py:725 CUDAQuantizer path).
    Returns the gathered array stacked on a leading axis, like
    ``lax.all_gather``."""
    _record_wire("quantized_all_gather", int(x.size), block, axis_name)
    return q8.quantized_all_gather(x, axis_name, block=block,
                                   use_pallas=_resolve_pallas(use_pallas))


def dcn_precision_clamp(x, block=q8.QUANT_BLOCK, use_pallas=None):
    """int8 block quantize->dequantize round trip — the ZeRO++ qgZ
    gradient numerics (reference csrc/quantization swizzled_quant before
    the inter-node hop). Used by the comm-overlap layer BETWEEN the two
    hierarchical stages — on the inner-(ICI-)reduced shard feeding the
    data_outer/DCN hop: under GSPMD the cross-slice collective itself is
    compiler-emitted, so this clamps the gradient VALUES crossing DCN to
    what an int8 wire would carry; byte-level int8 transport for
    explicitly-piped collectives is ``all_to_all_quant_reduce`` below."""
    if x.dtype == jnp.int8 or x.size == 0:
        return x
    _record_wire("dcn_precision_clamp", int(x.size), block, "data_outer")
    pallas = _resolve_pallas(use_pallas)
    q, s, meta = q8.quantize_blockwise(x.astype(jnp.float32), block=block,
                                       use_pallas=pallas)
    out = q8.dequantize_blockwise(q, s, meta, use_pallas=pallas)
    return out.astype(x.dtype)


def all_to_all_quant_reduce(x, inner_axis="data", outer_axis="data_outer",
                            average=False, block=q8.QUANT_BLOCK,
                            use_pallas=None):
    """Hierarchical quantized reduce-scatter (reference
    coalesced_collectives.py:32): stage 1 over the fast inner axis, stage 2
    over the slow outer axis, each hop int8-compressed.

    x: (N,) flat, N divisible by inner*outer. Returns this device's
    (N // (inner*outer),) fp32 chunk, ordered so device (o, i) holds
    global chunk ``o * Wi + i`` — the same layout a single reduce_scatter
    over the combined ('data_outer','data') axes (or a ZeRO plan
    partitioned over those axes) produces, so the result drops into
    hierarchically-partitioned optimizer shards directly."""
    Wi = lax.axis_size(inner_axis)
    Wo = lax.axis_size(outer_axis)
    N = x.shape[0]
    assert N % (Wi * Wo) == 0, (
        f"size {N} not divisible by {inner_axis}*{outer_axis}={Wi * Wo}")
    # Stage 1 keeps contiguous chunk i; stage 2 keeps sub-chunk o of it —
    # i.e. device (o,i) would end with chunk i*Wo+o. Pre-permute so the
    # final layout is o-major (o*Wi+i), matching combined-axis
    # reduce_scatter: group the Wo chunks {o*Wi+i : o} under stage-1
    # chunk i.
    M2 = N // (Wi * Wo)
    x = x.reshape(Wo, Wi, M2).transpose(1, 0, 2).reshape(N)
    stage1 = quantized_reduce_scatter(x, inner_axis, block=block,
                                      use_pallas=use_pallas)
    out = quantized_reduce_scatter(stage1, outer_axis, block=block,
                                   use_pallas=use_pallas)
    return out / (Wi * Wo) if average else out
