"""Collective communication over XLA CC ops.

Counterpart of the reference's ``deepspeed/comm/comm.py`` (module-level
collectives at comm/comm.py:222-521, ``init_distributed`` at :604). Two big
differences, both TPU-idiomatic:

1. There is no eager NCCL call to wrap. Collectives here are ``jax.lax``
   ops over *named mesh axes*; they are only legal inside a traced
   computation (``shard_map``/``pjit``). XLA lowers them onto ICI/DCN.
   Outside of traced code, GSPMD inserts collectives automatically from
   sharding annotations, so most runtime code never calls these directly —
   the pipeline engine, MoE dispatch and Ulysses attention do.

2. Instrumentation: the reference times each op with CUDA events
   (timed_op at comm/comm.py:101). Under jit, per-op host timing is
   meaningless; instead every wrapper *registers* (name, bytes) with the
   CommsLogger at trace time, giving exact per-step communication volumes
   (the quantity the reference's CommsLogger ultimately reports).
"""

import os
from functools import wraps

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import logger, log_dist
from .logging import get_comms_logger


def _nbytes(x):
    return int(np.prod(x.shape)) * x.dtype.itemsize if hasattr(x, "shape") else 0


def _axis_size(axis_name):
    return lax.axis_size(axis_name)


def _record(op_name, tensor, axis_name):
    lg = get_comms_logger()
    if lg.enabled:
        lg.append(op_name, _nbytes(tensor), axis_name)


def _traced_op(fn):
    @wraps(fn)
    def wrapper(tensor, axis_name, *args, **kwargs):
        _record(fn.__name__, tensor, axis_name)
        return fn(tensor, axis_name, *args, **kwargs)
    return wrapper


# --- in-trace collectives (shard_map bodies) -------------------------------
# Reference surface used by the runtime (SURVEY §5.8): all_reduce,
# reduce_scatter_tensor, all_gather_into_tensor, all_to_all_single,
# broadcast, send/recv (pipe), barrier.

@_traced_op
def all_reduce(tensor, axis_name, op="sum"):
    if op == "sum":
        return lax.psum(tensor, axis_name)
    if op == "avg":
        return lax.pmean(tensor, axis_name)
    if op == "max":
        return lax.pmax(tensor, axis_name)
    if op == "min":
        return lax.pmin(tensor, axis_name)
    raise ValueError(f"unsupported reduce op {op}")


@_traced_op
def reduce_scatter(tensor, axis_name, scatter_dimension=0):
    """reduce_scatter_tensor (reference comm/comm.py:246): sum then shard."""
    return lax.psum_scatter(tensor, axis_name,
                            scatter_dimension=scatter_dimension, tiled=True)


@_traced_op
def all_gather(tensor, axis_name, gather_dimension=0):
    """all_gather_into_tensor (reference comm/comm.py:315)."""
    return lax.all_gather(tensor, axis_name, axis=gather_dimension,
                          tiled=True)


@_traced_op
def all_to_all(tensor, axis_name, split_dimension, concat_dimension):
    """all_to_all_single (reference comm/comm.py: all_to_all_single) —
    Ulysses + MoE dispatch primitive."""
    return lax.all_to_all(tensor, axis_name, split_axis=split_dimension,
                          concat_axis=concat_dimension, tiled=True)


@_traced_op
def broadcast(tensor, axis_name, src=0):
    """Select src's value on every member of the axis. Mask-then-psum moves
    the minimum data (vs an all_gather which would materialize axis_size
    copies)."""
    mask = (lax.axis_index(axis_name) == src).astype(tensor.dtype)
    return lax.psum(tensor * mask, axis_name)


def ppermute(tensor, axis_name, perm):
    """Point-to-point ring shift — the pipe engine's send/recv
    (reference runtime/pipe/p2p.py:50,71) maps to collective_permute."""
    _record("ppermute", tensor, axis_name)
    return lax.ppermute(tensor, axis_name, perm)


def send_forward(tensor, axis_name):
    n = _axis_size(axis_name)
    return ppermute(tensor, axis_name, [(i, (i + 1) % n) for i in range(n)])


def send_backward(tensor, axis_name):
    n = _axis_size(axis_name)
    return ppermute(tensor, axis_name, [(i, (i - 1) % n) for i in range(n)])


def axis_index(axis_name):
    return lax.axis_index(axis_name)


# --- host-level API ---------------------------------------------------------

_INITIALIZED = False


def init_distributed(dist_backend="xla", timeout=None, init_method=None,
                     rank=-1, world_size=-1, auto_mpi_discovery=True,
                     verbose=True):
    """Counterpart of reference comm/comm.py:604.

    On TPU pods each host runs one process; ``jax.distributed.initialize``
    performs the rendezvous that MASTER_ADDR/RANK envs did for torch. On a
    single host this is a no-op — jax already sees all local devices.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coord = os.environ.get("COORDINATOR_ADDRESS")
    n_proc = os.environ.get("NUM_PROCESSES")
    pid = os.environ.get("PROCESS_ID")
    if coord and n_proc and pid:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=int(n_proc),
                                   process_id=int(pid))
        if verbose:
            log_dist(f"initialized jax.distributed: {coord} "
                     f"process {pid}/{n_proc}", ranks=[0])
    elif verbose:
        logger.info("init_distributed: single-process (no COORDINATOR_ADDRESS); "
                    f"local devices: {jax.local_device_count()}")
    _INITIALIZED = True


def is_initialized():
    return _INITIALIZED


def get_rank():
    return jax.process_index()


def get_world_size():
    return jax.process_count()


def get_local_device_count():
    return jax.local_device_count()


# Byte-transport payload ceiling. The padded ring buffer is ONE dense
# uint8 array materialized per process (and permuted in one collective)
# — an unbounded payload would silently turn a metadata hop into a
# multi-GiB device allocation sized by the LARGEST process's payload.
# Callers moving more than this (full KV caches, checkpoint shards)
# must chunk at a higher layer; the typed CommPayloadError is raised
# BEFORE the single-process early-return so the contract is testable
# everywhere.
MAX_PAYLOAD_BYTES = 1 << 30


class CommPayloadError(ValueError):
    """Payload exceeds the byte-transport contract
    (``MAX_PAYLOAD_BYTES``): refuse loudly instead of materializing an
    oversized padded ring buffer on every process."""


def _check_payload(payload, fn):
    n = len(payload)
    if n > MAX_PAYLOAD_BYTES:
        raise CommPayloadError(
            f"{fn}: payload of {n} bytes exceeds MAX_PAYLOAD_BYTES="
            f"{MAX_PAYLOAD_BYTES}; chunk at the caller")


def _padded_width(lengths):
    """Ring-wide padded buffer width: at least 1 so an all-empty
    exchange still builds a valid nonzero permute buffer (zero-length
    payloads are legal; a ``zeros((0,))`` global array is not a valid
    one-row-per-process collective operand)."""
    return max(1, int(np.max(lengths)))


def ring_exchange_bytes(payload, shift=1):
    """Host-level byte exchange around the PROCESS ring: send ``payload``
    to process ``(pid + shift) % nprocs`` over the accelerator fabric
    (ICI within a slice, DCN across slices — where a collective-permute
    between hosts lands), receive the peer ``shift`` behind us.

    -> (received_bytes, origin_process) — ``(None, None)`` in a
    single-process world (there is no peer; callers use a local/fs
    transport instead). Collective: every process must call with the
    same ``shift`` at the same point, like any other collective. The
    hot checkpoint tier (checkpoint_engine/hot_tier.py) uses this as
    its ``dcn`` replica transport; payloads are length-prefixed and
    padded to the ring-wide max so one permute moves everything.

    Payload contract: zero-length payloads are legal (the receiver gets
    ``b""`` from that origin — the padded buffer is floored at one
    byte); payloads above ``MAX_PAYLOAD_BYTES`` raise the typed
    :class:`CommPayloadError` before any collective runs.
    """
    _check_payload(payload, "ring_exchange_bytes")
    nproc = jax.process_count()
    if nproc <= 1:
        return None, None
    from jax.experimental import multihost_utils
    data = np.frombuffer(bytes(payload), dtype=np.uint8)
    # one length allgather sizes the padded buffer identically everywhere
    lengths = np.asarray(multihost_utils.process_allgather(
        np.asarray([data.size], np.int64))).reshape(-1)
    width = _padded_width(lengths)
    buf = np.zeros((width,), np.uint8)
    buf[:data.size] = data
    # one device per process, mesh axis 'proc': the permute between
    # devices of different hosts IS the DCN hop
    per_proc = {}
    for d in jax.devices():
        per_proc.setdefault(d.process_index, d)
    devices = [per_proc[i] for i in sorted(per_proc)]
    mesh = jax.sharding.Mesh(np.asarray(devices), ("proc",))
    perm = [(i, (i + shift) % nproc) for i in range(nproc)]

    def body(x):
        return lax.ppermute(x, "proc", perm)

    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("proc"))
    garr = jax.make_array_from_process_local_data(sharding, buf[None, :])
    shifted = jax.shard_map(
        body, mesh=mesh,
        in_specs=jax.sharding.PartitionSpec("proc"),
        out_specs=jax.sharding.PartitionSpec("proc"))(garr)
    local = np.asarray(shifted.addressable_shards[0].data).reshape(-1)
    origin = (jax.process_index() - shift) % nproc
    n = int(lengths[origin])
    return local[:n].tobytes(), origin


def allgather_bytes(payload):
    """Host-level byte allgather across the PROCESS ring: every process
    contributes ``payload``; returns the list of all processes' payloads
    in process order, or ``None`` in a single-process world.

    Same transport discipline as :func:`ring_exchange_bytes` (one
    length allgather sizes a padded buffer, then one data collective
    moves everything over the accelerator fabric) — the telemetry
    layer's cluster aggregation (monitor/telemetry.py) uses this to
    pool per-host step-time metrics at flush boundaries. Collective:
    every process must call at the same point.

    Same payload contract as :func:`ring_exchange_bytes`: zero-length
    payloads are legal, oversize payloads raise
    :class:`CommPayloadError` before any collective runs.
    """
    _check_payload(payload, "allgather_bytes")
    nproc = jax.process_count()
    if nproc <= 1:
        return None
    from jax.experimental import multihost_utils
    data = np.frombuffer(bytes(payload), dtype=np.uint8)
    lengths = np.asarray(multihost_utils.process_allgather(
        np.asarray([data.size], np.int64))).reshape(-1)
    width = _padded_width(lengths)
    buf = np.zeros((width,), np.uint8)
    buf[:data.size] = data
    stacked = np.asarray(multihost_utils.process_allgather(buf))
    return [stacked[i, :int(lengths[i])].tobytes()
            for i in range(nproc)]


def barrier(name="dstpu_barrier"):
    """Host-level barrier across all processes (works multi-host, where a
    naive jit over the global mesh would reject host-local inputs)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
    else:
        jax.effects_barrier()


def configure(config=None):
    """Enable/disable comms logging from config (reference comm.py:221 area)."""
    if config is not None and getattr(config, "comms_logger", None) is not None:
        get_comms_logger().configure(config.comms_logger)


def log_summary(show_straggler=False):
    get_comms_logger().log_summary(show_straggler=show_straggler)
