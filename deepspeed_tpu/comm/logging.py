"""Communication volume logger.

Counterpart of the reference's ``utils/comms_logging.py:67 CommsLogger`` and
``calc_bw_log`` (:34). Because collectives execute inside compiled XLA
programs, per-op wall times are not observable from Python; what *is* exact
is the traffic each traced op contributes. We record (op, bytes, axis) at
trace time and aggregate; ``log_summary`` mirrors the reference's table.
Pair with ``jax.profiler`` traces for on-device timing.
"""

from collections import defaultdict

from ..utils.logging import log_dist


class CommsLogger:
    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.prof_all = True
        self.comms_dict = defaultdict(lambda: defaultdict(lambda: [0, 0]))

    def configure(self, cfg):
        self.enabled = getattr(cfg, "enabled", False)
        self.verbose = getattr(cfg, "verbose", False)
        self.prof_all = getattr(cfg, "prof_all", True)

    def append(self, op_name, nbytes, axis_name):
        rec = self.comms_dict[op_name][str(axis_name)]
        rec[0] += 1
        rec[1] += nbytes
        if self.verbose:
            log_dist(f"comm op: {op_name} | axis: {axis_name} | bytes: {nbytes}",
                     ranks=[0])

    def reset(self):
        self.comms_dict.clear()

    def log_summary(self, show_straggler=False):
        log_dist("Communication summary (traced volumes per compilation):",
                 ranks=[0])
        header = f"{'Op':<20}{'Axis':<24}{'Count':>8}{'Total bytes':>16}"
        log_dist(header, ranks=[0])
        for op, axes in sorted(self.comms_dict.items()):
            for axis, (count, nbytes) in sorted(axes.items()):
                log_dist(f"{op:<20}{axis:<24}{count:>8}{nbytes:>16,}", ranks=[0])

    def total_bytes(self):
        return sum(nbytes for axes in self.comms_dict.values()
                   for (_, nbytes) in axes.values())


_LOGGER = CommsLogger()


def get_comms_logger():
    return _LOGGER
