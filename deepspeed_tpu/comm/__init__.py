from .comm import (all_reduce, reduce_scatter, all_gather, all_to_all,
                   broadcast, ppermute, send_forward, send_backward,
                   axis_index, init_distributed, is_initialized, get_rank,
                   get_world_size, get_local_device_count, barrier, configure,
                   log_summary)
from .logging import CommsLogger, get_comms_logger
from .quantized import (quantized_reduce_scatter, quantized_all_gather,
                        all_to_all_quant_reduce)
