"""Evoformer attention (DS4Science): biased attention for AlphaFold-style
models.

Counterpart of reference ``csrc/deepspeed4science/evoformer_attn/``
(``DS4Sci_EvoformerAttention`` — a CUTLASS fused kernel whose reason to
exist is O(N^2) score-matrix memory at MSA shapes). The TPU shape of the
same capability is the bias-capable flash kernel
(ops/pallas/flash_attention.py): scores NEVER materialize — the online
softmax streams key blocks — and the two reference bias operands ride as
kernel inputs (kernel_forward.h:986 bias1/bias2):

  bias1: (B, S, 1, 1, N)  — per-row residue mask, folded (B*S, N, N)
  bias2: (B, 1, H, N, N)  — pair-representation bias, folded (B*H, N, N)

Instances are folded in (batch, head, row) order so bias2's rows are
visited in one contiguous run each — that makes its in-kernel d_bias
accumulation valid (pair-bias GRADIENTS flow through the fused backward;
the reference kernel computes dB in kernel_backward.h the same way).
bias1 is mask-like and non-differentiable on the kernel path (its rows
revisit non-contiguously across heads); ``impl="xla"`` keeps the fully
differentiable chunked path for consumers that need d(mask).

API mirrors the reference:
  evoformer_attention(q, k, v, biases=(bias1, bias2))
with q/k/v (B, S, N, H, d) — batch, MSA rows, residues, heads, head_dim
— and biases broadcastable to the score shape (B, S, H, N, N). Returns
(B, S, N, H, d) in q's dtype.
"""

import math

import jax
import jax.numpy as jnp
from jax import lax


def evoformer_attention(q, k, v, biases=(), *, scale=None, chunk=0,
                        impl="kernel", block_q=256, block_k=256,
                        block_h=2):
    """Biased attention over (B, S, N, H, d) MSA-shaped inputs.

    ``biases``: additive terms broadcastable to (B, S, H, N, N) (the
    reference passes [bias1, bias2]). ``impl="kernel"`` (default)
    streams through the flash kernel — O(N) score memory, in-kernel
    d_bias for the pair bias; ``impl="xla"`` keeps the chunked dense
    path (fully differentiable incl. masks; ``chunk`` = rows of the
    flattened (B*S) dim per step, 0 = auto ~256 MB of scores)."""
    B, S, N, H, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    biases = tuple(biases)
    for b in biases:
        if b.ndim != 5:
            raise ValueError(
                f"bias must be 5D broadcastable to (B, S, H, N, N); got "
                f"shape {b.shape}")
    if impl == "xla":
        return _evoformer_xla(q, k, v, biases, scale, chunk)

    # ---- kernel path: fold instances (b, h, s) so bias2 rows are
    # visited in contiguous runs (grad-accumulation validity)
    def fold(x):                       # (B, S, N, H, d) -> (B, H*S, N, d)
        return x.transpose(0, 3, 1, 2, 4).reshape(B, H * S, N, d)

    folded = []
    for b in biases:
        Bb, Sb, Hb, Nq, Nk = b.shape
        if Nk != N or Nq not in (1, N):
            raise ValueError(
                f"bias key/query dims {b.shape} do not match N={N}")
        if Hb == 1:
            # row bias/mask (bias1): rows (B*S); expand query dim (the
            # kernel requires it) — (B*S, N, N) is still H x smaller
            # than the score tensor the dense path would materialize
            arr = jnp.broadcast_to(b, (B, S, 1, N, N)) \
                .reshape(B * S, N, N)
            cfg_fn = _row_bias_cfg(B, S, H)
            folded.append((arr, S, cfg_fn))
        elif Sb == 1:
            # pair bias (bias2): rows (B*H); differentiable — the fold
            # order gives each row one contiguous grid run
            arr = jnp.broadcast_to(b, (B, 1, H, N, N)) \
                .reshape(B * H, N, N)
            cfg_fn = _pair_bias_cfg(B, S, H)
            folded.append((arr, S, cfg_fn))
        else:
            # per-instance bias: identity row map
            arr = jnp.broadcast_to(b, (B, S, H, N, N)) \
                .transpose(0, 2, 1, 3, 4).reshape(B * H * S, N, N)
            folded.append((arr, None, _identity_cfg()))

    from .pallas.flash_attention import flash_attention
    out = flash_attention(
        fold(q), fold(k), fold(v), causal=False, scale=scale,
        heads_major=True, block_q=block_q, block_k=block_k,
        block_h=block_h, _folded_biases=folded)
    return out.reshape(B, H, S, N, d).transpose(0, 2, 3, 1, 4)


# cfg tuples: (per_rows, P, Q, R, tq_full, grad) with the row map
#   f(g) = (g*bh // P) * Q + ((g*bh) % R) // bh
# over the (b, h, s) instance fold — see flash_attention.py's bias notes.
def _row_bias_cfg(B, S, H):
    def cfg(bh):
        # rows (b*S + s): groups span s; b advances every H*S instances
        return (bh, H * S, S // bh, S, True, False)
    return cfg


def _pair_bias_cfg(B, S, H):
    def cfg(bh):
        # row (b*H + h) shared by the group's s-span: one contiguous
        # run of S//bh grid steps -> in-kernel d_bias accumulation
        return (1, S, 1, bh, True, True)
    return cfg


def _identity_cfg():
    def cfg(bh):
        return (bh, bh, 1, bh, True, True)
    return cfg


def _evoformer_xla(q, k, v, biases, scale, chunk):
    """Chunked dense path (the pre-kernel implementation): peak memory
    is one chunk's (chunk, H, N, N) scores; fully differentiable."""
    B, S, N, H, d = q.shape
    if chunk == 0:
        row_bytes = H * N * N * 4
        chunk = max(1, min(B * S, (256 << 20) // max(row_bytes, 1)))

    def attend(q_, k_, v_, bias_rows):
        s = jnp.einsum("cnhd,cmhd->chnm", q_, k_,
                       preferred_element_type=jnp.float32) * scale
        for br in bias_rows:
            s = s + br
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("chnm,cmhd->cnhd", p.astype(q_.dtype), v_)

    BS = B * S
    qf = q.reshape(BS, N, H, d)
    kf = k.reshape(BS, N, H, d)
    vf = v.reshape(BS, N, H, d)
    bflat = [jnp.broadcast_to(b, (B, S, H, N, N)).reshape(BS, H, N, N)
             for b in biases]

    if chunk >= BS:
        out = attend(qf, kf, vf, tuple(bflat))
        return out.reshape(B, S, N, H, d)

    n_chunks = -(-BS // chunk)
    pad = n_chunks * chunk - BS

    def padrows(x):
        return jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1)) \
            if pad else x

    qf, kf, vf = padrows(qf), padrows(kf), padrows(vf)
    bflat = [padrows(b) for b in bflat]

    def body(i):
        sl = lambda x: lax.dynamic_slice_in_dim(x, i * chunk, chunk, 0)
        return attend(sl(qf), sl(kf), sl(vf),
                      tuple(sl(b) for b in bflat))

    out = lax.map(body, jnp.arange(n_chunks))
    out = out.reshape(n_chunks * chunk, N, H, d)[:BS]
    return out.reshape(B, S, N, H, d)
