"""Evoformer attention (DS4Science): biased attention for AlphaFold-style
models.

Counterpart of reference ``csrc/deepspeed4science/evoformer_attn/``
(``DS4Sci_EvoformerAttention`` — a CUTLASS fused kernel whose reason to
exist is O(N^2) score-matrix memory at MSA shapes). The TPU shape of the
same capability: scores never materialize for the WHOLE batch at once —
the computation chunks over the leading (batch*seq) rows with
``lax.map``, each chunk a plain fp32-accumulated attention with the
additive biases, which XLA fuses; peak memory is one chunk's
(chunk, H, N, N) scores instead of the full (B, S, H, N, N).

API mirrors the reference:
  evoformer_attention(q, k, v, biases=(bias1, bias2), chunk=...)
with q/k/v (B, S, N, H, d) — batch, MSA rows, residues, heads, head_dim
— and biases broadcastable to the score shape (B, S, H, N, N):
  bias1: (B, S, 1, 1, N)  — per-row residue mask
  bias2: (B, 1, H, N, N)  — pair-representation bias
Returns (B, S, N, H, d) in q's dtype. Differentiable (jax autodiff
through the chunked map).
"""

import math

import jax
import jax.numpy as jnp
from jax import lax


def evoformer_attention(q, k, v, biases=(), *, scale=None, chunk=0):
    """Biased attention over (B, S, N, H, d) MSA-shaped inputs.

    ``biases``: additive fp32 terms broadcastable to (B, S, H, N, N)
    (the reference passes [bias1, bias2]). ``chunk``: rows of the
    flattened (B*S) dim processed per step (0 = auto: aim for ~256 MB of
    fp32 scores per chunk; 1 row of scores is H*N*N fp32)."""
    B, S, N, H, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    biases = tuple(biases)
    for b in biases:
        if b.ndim != 5:
            raise ValueError(
                f"bias must be 5D broadcastable to (B, S, H, N, N); got "
                f"shape {b.shape}")

    if chunk == 0:
        row_bytes = H * N * N * 4
        chunk = max(1, min(B * S, (256 << 20) // max(row_bytes, 1)))

    def attend(q_, k_, v_, bias_rows):
        # q_/k_/v_: (C, N, H, d); bias_rows: tuple of (C, H, N, N)
        s = jnp.einsum("cnhd,cmhd->chnm", q_, k_,
                       preferred_element_type=jnp.float32) * scale
        for br in bias_rows:
            s = s + br
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("chnm,cmhd->cnhd", p.astype(q_.dtype), v_)

    BS = B * S
    qf = q.reshape(BS, N, H, d)
    kf = k.reshape(BS, N, H, d)
    vf = v.reshape(BS, N, H, d)
    # biases broadcast to the flattened row dim; under jit the broadcast
    # stays lazy until consumed chunk-by-chunk in the map body (XLA
    # fuses the expansion into the score add — the memory property)
    bflat = [jnp.broadcast_to(b, (B, S, H, N, N)).reshape(BS, H, N, N)
             for b in biases]

    if chunk >= BS:
        out = attend(qf, kf, vf, tuple(bflat))
        return out.reshape(B, S, N, H, d)

    n_chunks = -(-BS // chunk)
    pad = n_chunks * chunk - BS

    def padrows(x):
        return jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1)) \
            if pad else x

    qf, kf, vf = padrows(qf), padrows(kf), padrows(vf)
    bflat = [padrows(b) for b in bflat]

    def body(i):
        sl = lambda x: lax.dynamic_slice_in_dim(x, i * chunk, chunk, 0)
        return attend(sl(qf), sl(kf), sl(vf),
                      tuple(sl(b) for b in bflat))

    out = lax.map(body, jnp.arange(n_chunks))
    out = out.reshape(n_chunks * chunk, N, H, d)[:BS]
    return out.reshape(B, S, N, H, d)
