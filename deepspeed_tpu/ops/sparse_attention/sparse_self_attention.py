"""Block-sparse attention op.

Counterpart of reference ``ops/sparse_attention/`` (Triton blocksparse
matmul/softmax + ``sparse_self_attention.py``). TPU realization: the
block LAYOUT becomes a block-resolution mask expanded inside the
attention computation — XLA fuses the mask into the softmax so masked
blocks contribute no probability mass; numerics match the reference's
blocksparse kernels exactly (same masked-softmax semantics). A Pallas
kernel that skips masked blocks at the MXU level (splash-attention style)
is the optimization path; the op's contract and layouts are what parity
requires.
"""

import math

import jax
import jax.numpy as jnp


def _expand_layout(layout, block, T):
    """(H, n, n) block layout -> (H, T, T) element mask."""
    n = T // block
    lay = jnp.asarray(layout[:, :n, :n])
    return jnp.repeat(jnp.repeat(lay, block, axis=1), block, axis=2)


def sparse_attention(q, k, v, layout, block, causal=False, scale=None):
    """q/k/v: (B, T, H, hd); layout: (H, T//block, T//block) bool.
    Returns (B, T, H, hd)."""
    B, T, H, hd = q.shape
    scale = scale or 1.0 / math.sqrt(hd)
    mask = _expand_layout(layout, block, T)            # (H, T, T)
    if causal:
        mask = mask & jnp.tril(jnp.ones((T, T), jnp.bool_))[None]
    scores = jnp.einsum("bthd,bshd->bhts", q, k,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(mask[None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    # fully-masked rows (possible in exotic layouts) -> zero output
    any_allowed = jnp.any(mask, axis=-1)               # (H, T)
    probs = jnp.where(any_allowed[None, :, :, None], probs, 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


class SparseSelfAttention:
    """reference ops/sparse_attention/sparse_self_attention.py: module
    bundling a SparsityConfig with the op; layout (and the Pallas
    kernel's block lists) built per seq len and cached.

    ``use_kernel=True`` (default) runs the Pallas block-sparse kernel
    (ops/pallas/block_sparse_attention.py) — compute scales with layout
    density, the reference's Triton blocksparse property. False falls
    back to the masked-dense op (the parity reference)."""

    def __init__(self, sparsity_config, causal=True, use_kernel=True):
        self.config = sparsity_config
        self.causal = causal
        self.use_kernel = use_kernel
        self._layouts = {}
        self._lists = {}

    def layout(self, seq_len):
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, q, k, v):
        T = q.shape[1]
        lay = self.layout(T)
        if not self.use_kernel:
            return sparse_attention(q, k, v, lay, self.config.block,
                                    causal=self.causal)
        from ..pallas.block_sparse_attention import (block_sparse_attention,
                                                     layout_lists)
        if T not in self._lists:
            import numpy as np
            n = T // self.config.block
            self._lists[T] = layout_lists(np.asarray(lay), self.causal,
                                          n, n)
        return block_sparse_attention(q, k, v, lay, self.config.block,
                                      causal=self.causal,
                                      lists=self._lists[T])

    def density(self, seq_len):
        lay = self.layout(seq_len)
        return float(lay.mean())
