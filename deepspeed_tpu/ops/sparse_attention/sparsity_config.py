"""Block-sparsity layout configs.

Counterpart of reference ``ops/sparse_attention/sparsity_config.py``:
each config builds a (num_heads, n_blocks, n_blocks) boolean LAYOUT — which
key blocks each query block attends — consumed by the block-sparse
attention op. Pure layout math, ported semantically.
"""

import numpy as np


class SparsityConfig:
    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(f"seq_len {seq_len} not divisible by block "
                             f"{self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=bool), n

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0:1]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """Full attention expressed as a layout (reference
    DenseSparsityConfig)."""

    def make_layout(self, seq_len):
        layout, n = self.setup_layout(seq_len)
        layout[:] = True
        return layout


class FixedSparsityConfig(SparsityConfig):
    """reference FixedSparsityConfig: local blocks within windows of
    ``num_local_blocks``, plus attention to the last
    ``num_global_blocks`` block(s) of each preceding window
    ('unidirectional') or chosen global blocks both ways
    ('bidirectional')."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def make_layout(self, seq_len):
        layout, n = self.setup_layout(seq_len)
        L, G = self.num_local_blocks, self.num_global_blocks
        heads = (self.num_heads if self.different_layout_per_head else 1)
        # global block indices: the last G blocks of every window
        gidx = [b for w0 in range(0, n, L)
                for b in range(max(w0 + L - G, w0), min(w0 + L, n))]
        for h in range(heads):
            for q in range(n):
                w = q // L
                # local window
                start = w * L
                end = min(start + L, n)
                layout[h, q, start:end] = True
                if self.attention == "unidirectional":
                    # global: last G blocks of every previous window
                    for pw in range(w):
                        ps = pw * L
                        pe = min(ps + L, n)
                        layout[h, q, max(pe - G, ps):pe] = True
            if self.attention == "bidirectional":
                # every query sees every global block (reference sets the
                # global columns for ALL rows)
                layout[h][:, gidx] = True
                if self.horizontal_global_attention:
                    layout[h][gidx, :] = True
        if self.attention == "unidirectional":
            tril = np.tril(np.ones((n, n), dtype=bool))
            layout &= tril[None]
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """reference BigBirdSparsityConfig: random + sliding window + global
    blocks."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional", seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len):
        layout, n = self.setup_layout(seq_len)
        rs = np.random.RandomState(self.seed)
        W = self.num_sliding_window_blocks
        heads = (self.num_heads if self.different_layout_per_head else 1)
        for h in range(heads):
            for q in range(n):
                lo = max(0, q - W // 2)
                layout[h, q, lo:min(n, q + W // 2 + 1)] = True
                # random blocks
                if self.attention == "unidirectional":
                    pool = np.arange(0, max(q, 1))
                else:
                    pool = np.arange(n)
                if len(pool) and self.num_random_blocks:
                    pick = rs.choice(pool, size=min(self.num_random_blocks,
                                                    len(pool)),
                                     replace=False)
                    layout[h, q, pick] = True
            # global blocks: first G rows/cols fully connected
            G = self.num_global_blocks
            layout[h, :G, :] = True
            layout[h, :, :G] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """reference BSLongformerSparsityConfig: sliding window + selected
    global block indices."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=(0,),
                 attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices)
        self.attention = attention

    def make_layout(self, seq_len):
        layout, n = self.setup_layout(seq_len)
        W = self.num_sliding_window_blocks
        heads = (self.num_heads if self.different_layout_per_head else 1)
        for h in range(heads):
            for q in range(n):
                lo = max(0, q - W // 2)
                layout[h, q, lo:min(n, q + W // 2 + 1)] = True
            for g in self.global_block_indices:
                if g < n:
                    layout[h, g, :] = True
                    layout[h, :, g] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return self.check_and_propagate_first_head_layout(layout)
