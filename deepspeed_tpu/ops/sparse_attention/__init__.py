from .sparsity_config import (SparsityConfig, DenseSparsityConfig,
                              FixedSparsityConfig, BigBirdSparsityConfig,
                              BSLongformerSparsityConfig)
from .sparse_self_attention import SparseSelfAttention, sparse_attention
