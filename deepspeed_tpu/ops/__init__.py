from .optimizers import (FusedAdam, FusedLamb, FusedLion, FusedAdagrad, SGD,
                         build_optimizer, OPTIMIZERS)
