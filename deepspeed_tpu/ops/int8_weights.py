"""Weight-only int8 quantization for serving (ZeRO-Inference).

Counterpart of the reference's inference-time quantization
(/root/reference/deepspeed/inference/quantization/quantization.py and
the ZeRO-Inference headline README.md:30 — weight quantization so models
larger than device memory can be served). TPU-first shape: weights live
in HBM as int8 with per-output-channel fp32 scales inside an
``Int8Weight`` pytree node; the serving paths dequantize ONE LAYER at a
time inside the jitted program (q.astype(bf16) * scale fuses into the
consuming matmul's prologue), so peak HBM holds the int8 tree plus a
single bf16 layer — a ~2x capacity win over bf16 weights (~4x over
fp32 masters).

Per-channel symmetric scheme: for a weight of shape (..., In, Out),
scale[..., 0, o] = absmax over In of column o / 127 — the standard
weight-only recipe (per-column scaling keeps matmul outputs calibrated
without per-block gather complexity, and the scale tensor shards
exactly like the weight's output dim).

W4A16 (``Int4Weight``): same per-output-channel scheme with a [-7, 7]
code range (scale = absmax / 7) and codes packed two-per-byte along the
CONTRACTED axis -2 — the layout documented in
``ops/pallas/quantization.py`` (``pack_int4``). Weights whose In dim is
odd fall back to int8. The fused-dequant kernels
(``mlp_matmul.wq_matmul`` / ``grouped_matmul.grouped_swiglu_wq``)
stream the packed bytes HBM->VMEM and unpack+rescale in-kernel.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class Int8Weight:
    """int8 weight + per-output-channel scale, as a pytree node so the
    quantized tree flows through tree.map / lax.scan / shardings
    untouched (slicing a stacked (L, ...) weight slices q and scale
    together)."""

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def dequant(self, dtype):
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"Int8Weight(q={self.q.shape}, scale={self.scale.shape})"


@jax.tree_util.register_pytree_node_class
class Int4Weight:
    """int4 weight (codes packed two-per-byte along the contracted
    axis -2, see pack_int4 layout in ops/pallas/quantization.py) +
    per-output-channel fp32 scale. ``q.shape[-2]`` is In // 2."""

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def dequant(self, dtype):
        from .pallas.quantization import unpack_int4
        codes = unpack_int4(jnp.asarray(self.q))
        return (codes.astype(jnp.float32) * self.scale).astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"Int4Weight(q={self.q.shape}, scale={self.scale.shape})"


def _is_q(x):
    return isinstance(x, (Int8Weight, Int4Weight))


def _pack_int4_np(q):
    lo = q[..., 0::2, :].astype(np.uint8) & 0xF
    hi = (q[..., 1::2, :].astype(np.uint8) & 0xF) << 4
    return (hi | lo).astype(np.int8)


def quantize_leaf(w, bits=8):
    """Host-side per-channel symmetric int8/int4 quantization of one
    weight. ``bits=4`` falls back to int8 when the contracted (-2) dim
    is odd (the two-per-byte packing needs it even)."""
    w = np.asarray(w, np.float32)
    if bits == 4 and w.shape[-2] % 2 == 0:
        absmax = np.max(np.abs(w), axis=-2, keepdims=True)
        scale = (absmax / 7.0).astype(np.float32)
        scale_safe = np.where(scale == 0, 1.0, scale)
        q = np.clip(np.rint(w / scale_safe), -7, 7).astype(np.int8)
        return Int4Weight(_pack_int4_np(q), scale)
    absmax = np.max(np.abs(w), axis=-2, keepdims=True)
    scale = (absmax / 127.0).astype(np.float32)
    scale_safe = np.where(scale == 0, 1.0, scale)
    q = np.clip(np.rint(w / scale_safe), -127, 127).astype(np.int8)
    return Int8Weight(q, scale)


def quantize_tree(params, min_size=1 << 16, consume=False,
                  exclude_keys=("moe_gate",), bits=8):
    """Quantize the ``blocks`` sub-tree's float weights with >= 2 dims
    and >= min_size elements (embeddings / norms / biases / the head
    stay in the model dtype — matching the reference's linear-layer-only
    weight quantization). MoE router weights (``exclude_keys``) are
    never quantized: routing is precision-sensitive — int8 router
    logits can flip top-k expert selection (the HF loaders keep
    ``moe_gate`` fp32 for the same reason). ``consume=True`` pops dict
    entries from the SOURCE tree as they are quantized, so the fp32
    originals free leaf-by-leaf — peak host memory stays ~the input
    tree + one leaf rather than input + full quantized copy (the
    big-model use case)."""
    import jax.numpy as jnp

    def walk(tree, in_blocks):
        if isinstance(tree, dict):
            out = {}
            for k in list(tree):
                if k in exclude_keys and not isinstance(tree[k], dict):
                    out[k] = np.asarray(tree[k]) if consume else tree[k]
                else:
                    out[k] = walk(tree[k], in_blocks or k == "blocks")
                if consume:
                    del tree[k]
            return out
        if isinstance(tree, (list, tuple)):
            return type(tree)(walk(v, in_blocks) for v in tree)
        arr = np.asarray(tree)
        # jnp.issubdtype: host bf16 (ml_dtypes) is floating too
        if (in_blocks and arr.ndim >= 2 and arr.size >= min_size
                and jnp.issubdtype(arr.dtype, jnp.floating)):
            return quantize_leaf(arr, bits=bits)
        return arr if consume else tree
    return walk(params, False)


def cast_unquantized(tree, dtype, exclude_keys=("moe_gate",)):
    """Cast a quantized tree's remaining float leaves (embeds / norms /
    biases) to the serving dtype, leaving Int8Weight nodes AND the
    ``exclude_keys`` leaves untouched — router weights keep full
    precision end to end (quantize_tree excludes them from int8 for the
    same reason; casting them to bf16 afterwards would undo that)."""
    import jax.numpy as jnp
    dt = np.dtype(jnp.dtype(dtype))

    def walk(tree):
        if isinstance(tree, dict):
            return {k: (tree[k] if k in exclude_keys
                        and not isinstance(tree[k], dict)
                        else walk(tree[k])) for k in tree}
        if isinstance(tree, (list, tuple)):
            return type(tree)(walk(v) for v in tree)
        if _is_q(tree):
            return tree
        a = np.asarray(tree)
        return a.astype(dt) if jnp.issubdtype(a.dtype, jnp.floating) \
            else a
    return walk(tree)


def dequant_tree(tree, dtype, keep=()):
    """Replace Int8Weight/Int4Weight nodes with dequantized ``dtype``
    arrays (identity on unquantized trees). ``keep`` names dict keys
    whose quantized nodes are passed through UNTOUCHED — the serving
    fused-dequant path keeps FFN weights quantized (the kernel streams
    int bytes and dequantizes in its flush epilogue) while everything
    else dequantizes per layer as before."""
    if not keep:
        return jax.tree.map(
            lambda x: x.dequant(dtype) if _is_q(x) else x, tree,
            is_leaf=_is_q)

    def walk(t):
        if isinstance(t, dict):
            return {k: (t[k] if k in keep and _is_q(t[k]) else walk(t[k]))
                    for k in t}
        if isinstance(t, (list, tuple)):
            return type(t)(walk(v) for v in t)
        return t.dequant(dtype) if _is_q(t) else t
    return walk(tree)


def has_quantized(tree):
    return any(_is_q(x) for x in jax.tree.leaves(tree, is_leaf=_is_q))


def quantized_shardings(specs, params, mesh):
    """Mirror a partition-spec tree onto a quantized param tree: an
    Int8Weight gets (spec for q, spec with the reduced (-2) dim unsharded
    for its per-channel scale)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def walk(spec, param):
        if _is_q(param):
            ndim = param.q.ndim
            entries = list(spec) + [None] * (ndim - len(spec))
            s_entries = list(entries)
            s_entries[-2] = None
            return type(param)(NamedSharding(mesh, P(*entries)),
                               NamedSharding(mesh, P(*s_entries)))
        return NamedSharding(mesh, spec)

    return jax.tree.map(walk, specs, params,
                        is_leaf=lambda x: isinstance(x, P) or _is_q(x))
