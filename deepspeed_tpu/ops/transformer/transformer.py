"""Standalone fused transformer encoder layer.

Counterpart of reference ``ops/transformer/transformer.py:296
DeepSpeedTransformerLayer`` (+ DeepSpeedTransformerConfig) backed by
``csrc/transformer/`` — the fused BERT-style encoder block with pre/post
LayerNorm variants. On TPU the fusion is XLA's job (plus the Pallas flash
kernel for attention); this module delivers the same drop-in surface:
config-driven, bidirectional (encoder) attention with an optional
additive mask, returning fp32-normed hidden states.

Functional like the model zoo: ``layer.init(rng) -> params``;
``layer(params, x, mask=None, rng=None, train=False)``.
"""

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass
class DeepSpeedTransformerConfig:
    """Reference config fields (transformer.py:30-120), TPU-relevant
    subset; the CUDA-workflow knobs (stream injection, fp16 flags,
    stochastic_mode) have no analogue and are accepted for parity."""
    batch_size: int = -1               # informational (shapes are dynamic)
    hidden_size: int = 768
    intermediate_size: int = 0         # 0 = 4 * hidden
    heads: int = 12
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = 1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = True
    normalize_invertible: bool = False   # accepted, no-op (remat instead)
    gelu_checkpoint: bool = False        # accepted, no-op
    stochastic_mode: bool = False        # accepted, no-op
    use_flash_attention: bool = False
    dtype: str = "float32"

    @property
    def d_ff(self):
        return self.intermediate_size or 4 * self.hidden_size


def _ln(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _dropout(x, rate, rng):
    if not rate or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


class DeepSpeedTransformerLayer:
    def __init__(self, config: DeepSpeedTransformerConfig):
        self.config = config

    def init(self, rng):
        cfg = self.config
        D, F = cfg.hidden_size, cfg.d_ff
        dt = jnp.dtype(cfg.dtype)
        ks = iter(jax.random.split(rng, 8))
        s = cfg.initializer_range

        def nrm(shape):
            return (jax.random.normal(next(ks), shape, jnp.float32)
                    * s).astype(dt)

        return {
            "ln1_scale": jnp.ones((D,), jnp.float32),
            "ln1_bias": jnp.zeros((D,), jnp.float32),
            "wqkv": nrm((D, 3 * D)), "bqkv": jnp.zeros((3 * D,), dt),
            "wo": nrm((D, D)), "bo": jnp.zeros((D,), dt),
            "ln2_scale": jnp.ones((D,), jnp.float32),
            "ln2_bias": jnp.zeros((D,), jnp.float32),
            "wi": nrm((D, F)), "bi": jnp.zeros((F,), dt),
            "wout": nrm((F, D)), "bout": jnp.zeros((D,), dt),
        }

    def __call__(self, params, x, mask=None, rng=None, train=False):
        """x: (B, T, D); mask: optional (B, T) validity or (B, 1, T, T)
        additive fp32 mask (BERT-style)."""
        cfg = self.config
        D, H = cfg.hidden_size, cfg.heads
        hd = D // H
        B, T = x.shape[0], x.shape[1]
        eps = cfg.layer_norm_eps
        r_attn = r_hidden = r_mlp = None
        if train and rng is not None:
            r_attn, r_hidden, r_mlp = jax.random.split(rng, 3)

        h = _ln(x, params["ln1_scale"], params["ln1_bias"], eps) \
            if cfg.pre_layer_norm else x
        qkv = (h @ params["wqkv"] + params["bqkv"]).reshape(B, T, 3, H, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn_drop = cfg.attn_dropout_ratio if train else 0.0
        add = None
        if mask is not None:
            if mask.ndim == 2:           # (B, T) validity -> additive
                add = jnp.where(mask[:, None, None, :], 0.0,
                                -1e30).astype(jnp.float32)
            else:
                add = mask
        # flash path has no probability-dropout hook: fall back to dense
        # whenever attn dropout must actually apply (never drop silently)
        if cfg.use_flash_attention and not attn_drop:
            # padding masks ride the kernel's additive-bias input
            # (reference softmax.cu:562 applies the mask in-kernel)
            from ..pallas.flash_attention import flash_attention
            attn = flash_attention(q, k, v, causal=False,
                                   bias=add).astype(x.dtype)
        else:
            scores = jnp.einsum("bthd,bshd->bhts", q, k,
                                preferred_element_type=jnp.float32)
            scores = scores / math.sqrt(hd)
            if add is not None:
                scores = scores + add
            probs = jax.nn.softmax(scores, axis=-1)
            probs = _dropout(probs.astype(x.dtype), attn_drop, r_attn)
            attn = jnp.einsum("bhts,bshd->bthd", probs, v)
        attn = attn.reshape(B, T, D) @ params["wo"] + params["bo"]
        attn = _dropout(attn, cfg.hidden_dropout_ratio if train else 0.0,
                        r_hidden)
        x = x + attn
        if not cfg.pre_layer_norm:
            x = _ln(x, params["ln1_scale"], params["ln1_bias"], eps)

        h = _ln(x, params["ln2_scale"], params["ln2_bias"], eps) \
            if cfg.pre_layer_norm else x
        inter = jax.nn.gelu(h @ params["wi"] + params["bi"])
        out = inter @ params["wout"] + params["bout"]
        out = _dropout(out, cfg.hidden_dropout_ratio if train else 0.0,
                       r_mlp)
        x = x + out
        if not cfg.pre_layer_norm:
            x = _ln(x, params["ln2_scale"], params["ln2_bias"], eps)
        return x
