"""Blockwise int8 quantization kernels (Pallas) + quantized collectives.

Counterpart of reference ``csrc/quantization/`` (pt_binding.cpp:298,
quantize_intX.cu, quant_reduce.cu, swizzled_quantize.cu): symmetric
per-block int8 quant used by ZeRO++ to compress weight all-gathers
(``zero_quantized_weights``, partition_parameters.py:725 CUDAQuantizer)
and gradient reduce-scatters (``zero_quantized_gradients``,
runtime/comm/coalesced_collectives.py:32 all_to_all_quant_reduce).

TPU design: one VPU pass computes per-block absmax scales and the scaled
round in VMEM; the collectives then move int8 (4x fewer bytes over
ICI/DCN) and dequantize on arrival. Off-TPU the same kernels run in
Pallas interpreter mode; `quantize_blockwise(..., use_pallas=False)` is
the jnp reference implementation (bitwise-identical math).
"""

import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import interpret_default as _interpret_default
from ._common import round_up as _round_up
from ._common import sds as _sds


QUANT_BLOCK = 2048  # elements per scale block (reference default group size)
# Rows per VMEM tile: 256 x 2048 el x 4 B = 2 MiB input, well under the
# ~16 MiB VMEM budget even with the int8+scale outputs resident.
_TILE_ROWS = 256


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)                  # (blocks, block)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale


def _dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = (q_ref[:].astype(jnp.float32) * s_ref[:]).astype(o_ref.dtype)


def _pad_reshape(flat, block):
    n = flat.shape[0]
    nblocks = -(-n // block)
    pad = nblocks * block - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(nblocks, block), pad


def quantize_blockwise(x, block=QUANT_BLOCK, use_pallas=True,
                       interpret=None):
    """x: any-shape float array -> (q int8 (nblocks, block), scales
    (nblocks, 1) f32, meta). Symmetric absmax scaling per block."""
    flat = x.reshape(-1)
    blocked, pad = _pad_reshape(flat, block)
    meta = {"shape": x.shape, "dtype": x.dtype, "pad": pad}
    if interpret is None:
        interpret = _interpret_default()
    if use_pallas:
        # Grid over row tiles so arbitrarily large tensors stream through
        # VMEM (a full ZeRO shard does not fit at once).
        nb = blocked.shape[0]
        rows = min(_TILE_ROWS, nb)
        nbp = _round_up(nb, rows)
        padded = (jnp.pad(blocked, ((0, nbp - nb), (0, 0)))
                  if nbp != nb else blocked)
        q, s = pl.pallas_call(
            _quant_kernel,
            grid=(nbp // rows,),
            in_specs=[pl.BlockSpec((rows, block), lambda i: (i, 0))],
            out_specs=[
                pl.BlockSpec((rows, block), lambda i: (i, 0)),
                pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            ],
            out_shape=[
                _sds((nbp, block), jnp.int8, padded),
                _sds((nbp, 1), jnp.float32, padded),
            ],
            interpret=interpret,
        )(padded)
        if nbp != nb:
            q, s = q[:nb], s[:nb]
    else:
        xf = blocked.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        s = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        q = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    return q, s, meta


def dequantize_blockwise(q, s, meta, use_pallas=True, interpret=None):
    """Inverse of quantize_blockwise."""
    if interpret is None:
        interpret = _interpret_default()
    if use_pallas:
        nb, block = q.shape
        rows = min(_TILE_ROWS, nb)
        nbp = _round_up(nb, rows)
        qp = jnp.pad(q, ((0, nbp - nb), (0, 0))) if nbp != nb else q
        sp = jnp.pad(s, ((0, nbp - nb), (0, 0))) if nbp != nb else s
        out = pl.pallas_call(
            _dequant_kernel,
            grid=(nbp // rows,),
            in_specs=[
                pl.BlockSpec((rows, block), lambda i: (i, 0)),
                pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((rows, block), lambda i: (i, 0)),
            out_shape=_sds((nbp, block), meta["dtype"], qp),
            interpret=interpret,
        )(qp, sp)
        if nbp != nb:
            out = out[:nb]
    else:
        out = (q.astype(jnp.float32) * s).astype(meta["dtype"])
    flat = out.reshape(-1)
    if meta["pad"]:
        flat = flat[:flat.shape[0] - meta["pad"]]
    return flat.reshape(meta["shape"])


def quantization_error(x, block=QUANT_BLOCK):
    """Max abs error of a quant/dequant round trip (diagnostics)."""
    q, s, meta = quantize_blockwise(x, block)
    return jnp.max(jnp.abs(dequantize_blockwise(q, s, meta) - x))


# ------------------------------------------------- quantized collectives
def quantized_all_gather(x, axis_name, block=QUANT_BLOCK, use_pallas=True):
    """all_gather moving int8+scales instead of full precision — the
    ZeRO++ quantized-weight gather (reference partition_parameters.py:1156
    all_gather_coalesced with quantization). Call inside shard_map.

    Returns the gathered array stacked on a leading axis (like
    lax.all_gather)."""
    q, s, meta = quantize_blockwise(x, block, use_pallas=use_pallas)
    qg = jax.lax.all_gather(q, axis_name)
    sg = jax.lax.all_gather(s, axis_name)
    return jax.vmap(lambda qq, ss: dequantize_blockwise(
        qq, ss, meta, use_pallas=use_pallas))(qg, sg)


def quantized_psum_scatter(x, axis_name, block=QUANT_BLOCK,
                           use_pallas=True):
    """reduce_scatter with int8 transport: quantize per destination piece,
    all_to_all, dequantize, sum locally — the single-hop form of the
    reference's all_to_all_quant_reduce (coalesced_collectives.py:32),
    which exists precisely because int8 cannot be summed over the wire
    without overflow: dequantize-then-reduce per hop. Call inside
    shard_map; returns this rank's reduced piece (shape x.shape[0]//world,
    *x.shape[1:])."""
    world = jax.lax.axis_size(axis_name)
    assert x.shape[0] % world == 0, (
        f"leading dim {x.shape[0]} not divisible by axis size {world}")
    piece_shape = (x.shape[0] // world,) + x.shape[1:]
    piece = x.reshape((world,) + piece_shape)

    def qfn(p):
        q, s, _ = quantize_blockwise(p, block, use_pallas=use_pallas)
        return q, s

    q, s = jax.vmap(qfn)(piece)            # (world, nb, block), (world, nb, 1)
    qx = jax.lax.all_to_all(q, axis_name, 0, 0)
    sx = jax.lax.all_to_all(s, axis_name, 0, 0)
    meta32 = {"shape": piece_shape, "dtype": jnp.float32,
              "pad": q.shape[1] * block - math.prod(piece_shape)}

    def dfn(qq, ss):
        return dequantize_blockwise(qq, ss, meta32, use_pallas=use_pallas)

    deq = jax.vmap(dfn)(qx, sx)            # (world,) + piece_shape, f32
    return jnp.sum(deq, axis=0).astype(x.dtype)
