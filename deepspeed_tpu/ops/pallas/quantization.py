"""Blockwise int8 quantization kernels (Pallas) + quantized collectives.

Counterpart of reference ``csrc/quantization/`` (pt_binding.cpp:298,
quantize_intX.cu, quant_reduce.cu, swizzled_quantize.cu): symmetric
per-block int8 quant used by ZeRO++ to compress weight all-gathers
(``zero_quantized_weights``, partition_parameters.py:725 CUDAQuantizer)
and gradient reduce-scatters (``zero_quantized_gradients``,
runtime/comm/coalesced_collectives.py:32 all_to_all_quant_reduce).

TPU design: one VPU pass computes per-block absmax scales and the scaled
round in VMEM; the collectives then move int8 (4x fewer bytes over
ICI/DCN) and dequantize on arrival. Off-TPU the same kernels run in
Pallas interpreter mode; `quantize_blockwise(..., use_pallas=False)` is
the jnp reference implementation (bitwise-identical math).

Weight-only serving additions (ISSUE 18):

* ``quantize_channelwise(w, bits=8|4)`` — symmetric per-output-channel
  scales (absmax over the contracted axis -2, /127 for int8, /7 for
  int4). Because the scale lives on the NON-contracted dim, dequant
  commutes with the K-accumulation and can be applied once in a matmul
  kernel's flush epilogue instead of per weight tile.

* int4 packing layout (``pack_int4``/``unpack_int4``): two signed
  4-bit values per int8 byte, packed along the CONTRACTED axis (-2) so
  a (bk, bm) weight tile reads as a contiguous (bk//2, bm) byte tile:

      byte[r, c] = (q[2r+1, c] << 4) | (q[2r, c] & 0xF)

  i.e. even source rows in the low nibble, odd rows in the high
  nibble. Unpacking is two arithmetic shifts — ``(b << 4) >> 4``
  sign-extends the low nibble, ``b >> 4`` the high one — then a
  stack+reshape restores row order. Values are clipped to the
  symmetric range [-7, 7] (-8 is unused) so negation round-trips.
  The contracted axis must be even; callers pad or fall back to int8.

* ``int8_matmul`` — dynamic activationxweight int8 compute (per-row
  activation scales, per-column weight scales, int32 accumulation)
  with a straight-through fp backward, used by the ``mlp_int8`` /
  ``moe_grouped_int8`` autotune candidate levers.
"""

import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import interpret_default as _interpret_default
from ._common import round_up as _round_up
from ._common import sds as _sds


QUANT_BLOCK = 2048  # elements per scale block (reference default group size)
# Rows per VMEM tile: 256 x 2048 el x 4 B = 2 MiB input, well under the
# ~16 MiB VMEM budget even with the int8+scale outputs resident.
_TILE_ROWS = 256


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)                  # (blocks, block)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale


def _dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = (q_ref[:].astype(jnp.float32) * s_ref[:]).astype(o_ref.dtype)


def _pad_reshape(flat, block):
    n = flat.shape[0]
    nblocks = -(-n // block)
    pad = nblocks * block - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(nblocks, block), pad


def quantize_blockwise(x, block=QUANT_BLOCK, use_pallas=True,
                       interpret=None):
    """x: any-shape float array -> (q int8 (nblocks, block), scales
    (nblocks, 1) f32, meta). Symmetric absmax scaling per block."""
    flat = x.reshape(-1)
    blocked, pad = _pad_reshape(flat, block)
    meta = {"shape": x.shape, "dtype": x.dtype, "pad": pad}
    if interpret is None:
        interpret = _interpret_default()
    if use_pallas:
        # Grid over row tiles so arbitrarily large tensors stream through
        # VMEM (a full ZeRO shard does not fit at once).
        nb = blocked.shape[0]
        rows = min(_TILE_ROWS, nb)
        nbp = _round_up(nb, rows)
        padded = (jnp.pad(blocked, ((0, nbp - nb), (0, 0)))
                  if nbp != nb else blocked)
        q, s = pl.pallas_call(
            _quant_kernel,
            grid=(nbp // rows,),
            in_specs=[pl.BlockSpec((rows, block), lambda i: (i, 0))],
            out_specs=[
                pl.BlockSpec((rows, block), lambda i: (i, 0)),
                pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            ],
            out_shape=[
                _sds((nbp, block), jnp.int8, padded),
                _sds((nbp, 1), jnp.float32, padded),
            ],
            interpret=interpret,
        )(padded)
        if nbp != nb:
            q, s = q[:nb], s[:nb]
    else:
        xf = blocked.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        s = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        q = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    return q, s, meta


def dequantize_blockwise(q, s, meta, use_pallas=True, interpret=None):
    """Inverse of quantize_blockwise."""
    if interpret is None:
        interpret = _interpret_default()
    if use_pallas:
        nb, block = q.shape
        rows = min(_TILE_ROWS, nb)
        nbp = _round_up(nb, rows)
        qp = jnp.pad(q, ((0, nbp - nb), (0, 0))) if nbp != nb else q
        sp = jnp.pad(s, ((0, nbp - nb), (0, 0))) if nbp != nb else s
        out = pl.pallas_call(
            _dequant_kernel,
            grid=(nbp // rows,),
            in_specs=[
                pl.BlockSpec((rows, block), lambda i: (i, 0)),
                pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((rows, block), lambda i: (i, 0)),
            out_shape=_sds((nbp, block), meta["dtype"], qp),
            interpret=interpret,
        )(qp, sp)
        if nbp != nb:
            out = out[:nb]
    else:
        out = (q.astype(jnp.float32) * s).astype(meta["dtype"])
    flat = out.reshape(-1)
    if meta["pad"]:
        flat = flat[:flat.shape[0] - meta["pad"]]
    return flat.reshape(meta["shape"])


def quantization_error(x, block=QUANT_BLOCK):
    """Max abs error of a quant/dequant round trip (diagnostics)."""
    q, s, meta = quantize_blockwise(x, block)
    return jnp.max(jnp.abs(dequantize_blockwise(q, s, meta) - x))


# ------------------------------------------- weight-only channel scales
def quantize_channelwise(w, bits=8):
    """Symmetric per-output-channel quantization of a weight
    ``(..., In, Out)``: scale[..., 0, o] = absmax over In of column o
    divided by the code range (127 for int8, 7 for int4).

    Returns ``(q int8 (..., In, Out), scale f32 (..., 1, Out))``. For
    ``bits=4`` the codes stay one-per-byte here; ``pack_int4`` packs
    them two-per-byte (the storage format the fused kernels stream).
    """
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits!r}")
    qmax = 127.0 if bits == 8 else 7.0
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -qmax, qmax).astype(jnp.int8)
    return q, scale


def dequantize_channelwise(q, scale, dtype):
    """Inverse of quantize_channelwise (codes one-per-byte)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def pack_int4(q):
    """Pack int4 codes (int8 storage, values in [-7, 7]) two-per-byte
    along axis -2: ``(..., In, Out) -> (..., In//2, Out)`` with
    ``byte[r] = (q[2r+1] << 4) | (q[2r] & 0xF)``. In must be even."""
    k = q.shape[-2]
    if k % 2:
        raise ValueError(f"int4 pack needs an even contracted dim, got {k}")
    lo = jnp.take(q, jnp.arange(0, k, 2), axis=-2).astype(jnp.uint8)
    hi = jnp.take(q, jnp.arange(1, k, 2), axis=-2).astype(jnp.uint8)
    return ((hi << 4) | (lo & 0xF)).astype(jnp.int8)


def unpack_int4(p):
    """Inverse of pack_int4: ``(..., In//2, Out) -> (..., In, Out)``
    int8 codes, sign-extended by arithmetic shifts."""
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)
    hi = jnp.right_shift(p, 4)
    stacked = jnp.stack([lo, hi], axis=-2)           # (..., In//2, 2, Out)
    shape = p.shape[:-2] + (2 * p.shape[-2],) + p.shape[-1:]
    return stacked.reshape(shape)


# ---------------------------------------------- dynamic int8 compute
def _rowwise_int8(x):
    """Per-row symmetric int8 codes for an activation ``(..., K)``."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


@jax.custom_vjp
def int8_matmul(x, w):
    """``x (..., K) @ w (K, M)`` computed as int8 x int8 -> int32 with
    per-row activation scales and per-column weight scales (fp32
    rescale at the end). Backward is straight-through in full
    precision, so the lever is usable in training steps and autotune
    make_steps without a bespoke gradient."""
    return _int8_matmul_fwd_val(x, w)


def _int8_matmul_fwd_val(x, w):
    qx, sx = _rowwise_int8(x)
    qw, sw = quantize_channelwise(w, bits=8)          # (K, M) -> (1, M)
    acc = jax.lax.dot_general(
        qx, qw, (((qx.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * sw).astype(x.dtype)


def _int8_matmul_fwd(x, w):
    return _int8_matmul_fwd_val(x, w), (x, w)


def _int8_matmul_bwd(res, dy):
    x, w = res
    dyf = dy.astype(jnp.float32)
    dx = jnp.einsum("...m,km->...k", dyf, w.astype(jnp.float32))
    dw = jnp.einsum("...k,...m->km", x.astype(jnp.float32), dyf)
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_matmul.defvjp(_int8_matmul_fwd, _int8_matmul_bwd)


@jax.custom_vjp
def grouped_int8_matmul(x, w, group_sizes):
    """Ragged grouped matmul ``x (S, K) x w (E, K, N)`` (rows grouped by
    expert via ``group_sizes``) with int8 x int8 -> int32 compute:
    per-row activation scales, per-(expert, column) weight scales.
    Straight-through fp backward (ragged_dot vjp)."""
    return _gi8_fwd_val(x, w, group_sizes)


def _gi8_fwd_val(x, w, group_sizes):
    qx, sx = _rowwise_int8(x)
    qw, sw = quantize_channelwise(w, bits=8)          # (E,K,N) -> (E,1,N)
    acc = jax.lax.ragged_dot(qx, qw, group_sizes,
                             preferred_element_type=jnp.int32)
    sw_rows = jnp.repeat(sw[:, 0, :], group_sizes, axis=0,
                         total_repeat_length=x.shape[0])
    return (acc.astype(jnp.float32) * sx * sw_rows).astype(x.dtype)


def _gi8_fwd(x, w, group_sizes):
    return _gi8_fwd_val(x, w, group_sizes), (x, w, group_sizes)


def _gi8_bwd(res, dy):
    x, w, group_sizes = res
    _, vjp = jax.vjp(
        lambda a, b: jax.lax.ragged_dot(a, b, group_sizes), x, w)
    dx, dw = vjp(dy.astype(x.dtype))
    return dx, dw, None


grouped_int8_matmul.defvjp(_gi8_fwd, _gi8_bwd)


# ------------------------------------------------- quantized collectives
def quantized_all_gather(x, axis_name, block=QUANT_BLOCK, use_pallas=True):
    """all_gather moving int8+scales instead of full precision — the
    ZeRO++ quantized-weight gather (reference partition_parameters.py:1156
    all_gather_coalesced with quantization). Call inside shard_map.

    Returns the gathered array stacked on a leading axis (like
    lax.all_gather)."""
    q, s, meta = quantize_blockwise(x, block, use_pallas=use_pallas)
    qg = jax.lax.all_gather(q, axis_name)
    sg = jax.lax.all_gather(s, axis_name)
    return jax.vmap(lambda qq, ss: dequantize_blockwise(
        qq, ss, meta, use_pallas=use_pallas))(qg, sg)


def quantized_psum_scatter(x, axis_name, block=QUANT_BLOCK,
                           use_pallas=True):
    """reduce_scatter with int8 transport: quantize per destination piece,
    all_to_all, dequantize, sum locally — the single-hop form of the
    reference's all_to_all_quant_reduce (coalesced_collectives.py:32),
    which exists precisely because int8 cannot be summed over the wire
    without overflow: dequantize-then-reduce per hop. Call inside
    shard_map; returns this rank's reduced piece (shape x.shape[0]//world,
    *x.shape[1:])."""
    world = jax.lax.axis_size(axis_name)
    assert x.shape[0] % world == 0, (
        f"leading dim {x.shape[0]} not divisible by axis size {world}")
    piece_shape = (x.shape[0] // world,) + x.shape[1:]
    piece = x.reshape((world,) + piece_shape)

    def qfn(p):
        q, s, _ = quantize_blockwise(p, block, use_pallas=use_pallas)
        return q, s

    q, s = jax.vmap(qfn)(piece)            # (world, nb, block), (world, nb, 1)
    qx = jax.lax.all_to_all(q, axis_name, 0, 0)
    sx = jax.lax.all_to_all(s, axis_name, 0, 0)
    meta32 = {"shape": piece_shape, "dtype": jnp.float32,
              "pad": q.shape[1] * block - math.prod(piece_shape)}

    def dfn(qq, ss):
        return dequantize_blockwise(qq, ss, meta32, use_pallas=use_pallas)

    deq = jax.vmap(dfn)(qx, sx)            # (world,) + piece_shape, f32
    return jnp.sum(deq, axis=0).astype(x.dtype)
