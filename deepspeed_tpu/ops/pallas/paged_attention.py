"""Paged (blocked-KV) decode attention as a Pallas TPU kernel.

Counterpart of the reference's FastGen ragged kernels
(``deepspeed/inference/v2/kernels/ragged_ops/`` — blocked flash over a
paged KV cache behind ``RaggedBatchWrapper``): one new token per sequence
slot attends over that sequence's KV blocks, located through a per-slot
block table.

The jnp fallback path gathers every slot's blocks into a dense
(B, S, H, d) copy and runs masked-dense attention — O(B * MB * BS) HBM
traffic in COPIES per layer, then attention over the fully padded length.
This kernel instead streams each KV block through VMEM exactly once,
indexed directly by the block table (scalar-prefetch index_map — the block
id picked per grid step comes from the table in SMEM), with online softmax
across blocks; blocks past the sequence's length are clamped to the
scratch block in the index map and fully masked, so padded table tails
cost no fresh DMA.

GQA is native: q heads fold to (KVH, G, d) and both dots batch over KVH —
no repeat_kv materialization.

Layout: q (B, H, d); cache (NB, KVH, BS, d) — heads-major so the kernel's
(KVH, BS, d) block needs no in-VMEM transpose; block_tables (B, MB) int32
(inactive/overflow entries point at scratch block 0); lengths (B,) int32 =
the new token's position (the kernel attends cache slots 0..lengths
inclusive, matching the dense path's semantics).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_default as _interpret_default

NEG_INF = -1e30

# ---------------------------------------------------------------- tunables
# Cold-cache (r05-style proven) parameters for the two serving autotune
# ops (autotuning/kernel_registry.py registers the search spaces):
#   paged_decode  mode: 'kernel' everywhere — the decode kernel has been
#                 the shipped path since it landed (interpret mode off-TPU)
#   paged_chunk   mode: 'kernel' on TPU (the blocked-flash chunk program),
#                 'dense' elsewhere — emulating the blocked stream in the
#                 Pallas interpreter is slower than one dense gather on
#                 CPU, and the dense path is the proven parity fallback
PAGED_DECODE_DEFAULTS = {"mode": "kernel"}
PAGED_CHUNK_BLOCK_C = 128


def paged_chunk_tune_defaults():
    """Cold-cache defaults for the 'paged_chunk' autotune op (the mode
    is backend-dependent; the winner cache is keyed by device_kind, so
    the split can never leak across chips)."""
    on_tpu = jax.default_backend() == "tpu"
    return {"mode": "kernel" if on_tpu else "dense",
            "block_c": PAGED_CHUNK_BLOCK_C}


def resolve_paged_decode(setting, B, MB, BS, KVH, G, d, dtype):
    """Resolve an engine/model ``paged_kernel`` setting for the decode
    step: "auto" consults the autotune winner cache for this
    decode-shape bucket (batch slots, blocks-per-seq, block size,
    kv-heads, GQA group, head dim); True/False force. Returns whether
    the Pallas kernel path is used."""
    if setting == "auto":
        from ._common import dispatch, dtype_name, paged_decode_bucket
        win = dispatch("paged_decode",
                       paged_decode_bucket(B, MB, BS, KVH, G, d),
                       dtype_name(dtype), dict(PAGED_DECODE_DEFAULTS))
        return win["mode"] == "kernel"
    return bool(setting)


def resolve_paged_chunk(setting, block_c, C, MB, BS, KVH, G, d, dtype):
    """Resolve the chunk-program kernel choice + its q-tile size.

    ``setting``: "auto" | True | False (engine ``paged_kernel``;
    callers pass False when the kernel path is statically impossible,
    e.g. ALiBi models); ``block_c``: "auto" | int (engine
    ``paged_block_c``). "auto" fields resolve against the winner cache
    for this chunk-shape bucket; cold-cache defaults come from
    :func:`paged_chunk_tune_defaults`. Returns (use_kernel, block_c).

    The dispatch (which may run a measured search under
    on_first_use/search) is only consulted when its answer can matter
    — a forced-off kernel never pays a search for a tile it will
    discard."""
    use = None if setting == "auto" else bool(setting)
    if use is False:
        return False, (PAGED_CHUNK_BLOCK_C if block_c == "auto"
                       else int(block_c))
    win = None
    if use is None or block_c == "auto":
        from ._common import dispatch, dtype_name, paged_chunk_bucket
        win = dispatch("paged_chunk",
                       paged_chunk_bucket(C, MB, BS, KVH, G, d),
                       dtype_name(dtype), paged_chunk_tune_defaults())
    if use is None:
        use = win["mode"] == "kernel"
    bc = int(win["block_c"]) if block_c == "auto" else int(block_c)
    return use, bc


def alibi_slopes(n_head):
    """Per-head ALiBi slopes (the bloom formula): for the leading
    power-of-two count cp, slope_h = 2^(-8(h+1)/cp); extra heads
    interleave the 2cp sequence: 2^(-4(2(h-cp)+1)/cp)."""
    cp = 2 ** math.floor(math.log2(n_head))
    return [2.0 ** (-8.0 * (h + 1) / cp) if h < cp
            else 2.0 ** (-4.0 * (2 * (h - cp) + 1) / cp)
            for h in range(n_head)]


alibi_slopes_formula = alibi_slopes


def _decode_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, BS, KVH, G, scale, window,
                   alibi, alibi_scale=1.0, alibi_bf16=False):
    b = pl.program_id(0)
    j = pl.program_id(1)
    H = KVH * G
    d = q_ref.shape[-1]
    L = len_ref[b]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = j * BS <= L
    if window:
        # sliding window: the query (at position L) only attends
        # positions > L - window; blocks entirely below that are dead
        live = live & (j * BS + BS > L - window + 1)

    @pl.when(live)
    def _step():
        kb = k_ref[0]                                     # (KVH, BS, d)
        vb = v_ref[0]
        # q arrives (1, KVH, G, d) — the caller reshaped (B, H, d) to
        # (B, KVH, G, d) OUTSIDE the kernel (in-kernel singleton reshapes
        # are unsupported shape casts in Mosaic, and a dot needs a
        # non-contracting lhs dim, which G provides even when == 1)
        q = q_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # (KVH, G, BS)
        pos = j * BS + jax.lax.broadcasted_iota(jnp.int32, (KVH, G, BS), 2)
        if alibi:
            # ALiBi: slope_h * k_pos (softmax-shift equivalent to
            # slope_h * (k_pos - q_pos); matches the dense paths).
            # Slopes are computed IN-KERNEL from the head index (a
            # captured constant array is rejected by pallas_call): the
            # bloom formula splits at the leading power of two cp.
            h = (jax.lax.broadcasted_iota(jnp.int32, (KVH, G, BS), 0) * G
                 + jax.lax.broadcasted_iota(jnp.int32, (KVH, G, BS), 1)
                 ).astype(jnp.float32)
            cp = float(2 ** math.floor(math.log2(H)))
            expo = jnp.where(h < cp, -(h + 1.0) * (8.0 / cp),
                             -(2.0 * (h - cp) + 1.0) * (4.0 / cp))
            ab = jnp.exp2(expo) * pos.astype(jnp.float32)
            if alibi_bf16:
                # HF falcon quantizes the alibi tensor through bf16 and
                # adds it pre-scaling (models/llama.py _alibi_bias)
                ab = ab.astype(jnp.bfloat16).astype(jnp.float32)
            if alibi_scale != 1.0:
                ab = ab * alibi_scale
            s = s + ab
        ok = pos <= L
        if window:
            ok = ok & (pos > L - window)
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_ref[..., 0]                            # (KVH, G)
        l_prev = l_ref[..., 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[..., None])                 # (KVH, G, BS) f32
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # (KVH, G, d)
        acc_ref[...] = acc_ref[...] * alpha[..., None] + pv
        m_ref[...] = jnp.broadcast_to(m_new[..., None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[..., None], l_ref.shape)

    l = jnp.maximum(l_ref[..., 0], 1e-30)                 # (KVH, G)
    o_ref[0] = (acc_ref[...] / l[..., None]).astype(o_ref.dtype)


def paged_decode_attention(q, k_cache, v_cache, block_tables, lengths, *,
                           scale=None, interpret=None, window=0,
                           alibi_slopes=None, alibi_scale=1.0,
                           alibi_bf16=False):
    """One decode step of attention over a paged KV cache.

    q: (B, H, d); k_cache/v_cache: (NB, KVH, BS, d) with H % KVH == 0;
    block_tables: (B, MB) int32; lengths: (B,) int32 = the new token's
    position. Returns (B, H, d) in q's dtype. The new token's K/V must
    already be written to the cache (the callers do the dynamic-slot
    write first). ``window`` > 0 restricts attention to the trailing
    ``window`` positions (mistral); ``alibi_slopes`` (len H floats) adds
    the bloom per-head linear position bias.

    Multi-layer pools: view (L, NB, ...) as (L*NB, ...) (a free reshape)
    and offset the tables by ``layer * NB`` — a lax.scan over layers then
    never slices the pool per layer, which would copy ~the whole cache
    every layer (scan xs/ys cannot alias).
    """
    B, H, d = q.shape
    NB, KVH, BS, _ = k_cache.shape
    MB = block_tables.shape[1]
    G = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_default()

    if alibi_slopes is not None:
        # the kernel recomputes slopes IN-KERNEL from the head count
        # (pallas rejects captured constant arrays); reject custom
        # slopes rather than silently ignoring them
        expect = alibi_slopes_formula(H)
        if len(alibi_slopes) != H or any(
                abs(a - b) > 1e-6 * max(abs(b), 1e-9)
                for a, b in zip(alibi_slopes, expect)):
            raise NotImplementedError(
                "paged_decode_attention computes bloom-formula ALiBi "
                "slopes in-kernel; custom per-head slopes are not "
                "supported")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, MB),
        in_specs=[
            pl.BlockSpec((1, KVH, G, d),
                         lambda b, j, tbl, lens: (b, 0, 0, 0)),
            pl.BlockSpec(
                (1, KVH, BS, d),
                lambda b, j, tbl, lens: (
                    jnp.where(j * BS <= lens[b], tbl[b, j],
                              tbl[b, 0]), 0, 0, 0)),
            pl.BlockSpec(
                (1, KVH, BS, d),
                lambda b, j, tbl, lens: (
                    jnp.where(j * BS <= lens[b], tbl[b, j],
                              tbl[b, 0]), 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, KVH, G, d),
                               lambda b, j, tbl, lens: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KVH, G, 128), jnp.float32),  # running max
            pltpu.VMEM((KVH, G, 128), jnp.float32),  # running denom
            pltpu.VMEM((KVH, G, d), jnp.float32),    # output accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, BS=BS, KVH=KVH, G=G,
                          scale=float(scale), window=int(window),
                          alibi=alibi_slopes is not None,
                          alibi_scale=float(alibi_scale),
                          alibi_bf16=bool(alibi_bf16)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, d), q.dtype),
        interpret=interpret,
    )(block_tables, lengths, q.reshape(B, KVH, G, d), k_cache, v_cache)
    return out.reshape(B, H, d)


def paged_decode_attention_reference(q, k_cache, v_cache, block_tables,
                                     lengths, *, scale=None, window=0,
                                     alibi_slopes=None):
    """Dense gather fallback (the pre-kernel path), for parity tests."""
    B, H, d = q.shape
    NB, KVH, BS, _ = k_cache.shape
    MB = block_tables.shape[1]
    S = MB * BS
    G = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    kc = k_cache.transpose(0, 2, 1, 3)                 # (NB, BS, KVH, d)
    vc = v_cache.transpose(0, 2, 1, 3)
    gk = kc[block_tables].reshape(B, S, KVH, d)
    gv = vc[block_tables].reshape(B, S, KVH, d)
    gk = jnp.repeat(gk, G, axis=2)
    gv = jnp.repeat(gv, G, axis=2)
    s = jnp.einsum("bhd,bshd->bhs", q, gk,
                   preferred_element_type=jnp.float32) * scale
    if alibi_slopes is not None:
        sl = jnp.asarray(alibi_slopes, jnp.float32)
        s = s + sl[None, :, None] * jnp.arange(S, dtype=jnp.float32)[
            None, None, :]
    mask = jnp.arange(S)[None, :] <= lengths[:, None]
    if window:
        mask = mask & (jnp.arange(S)[None, :]
                       > lengths[:, None] - window)
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhs,bshd->bhd", p, gv)


# ------------------------------------------------- chunked-prefill kernel


def _chunk_kernel(tbl_ref, meta_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, BS, KVH, G, BC, scale,
                  window):
    """One (q-tile, table-entry) grid step of the SplitFuse chunk
    program: q tile i (BC chunk tokens x G query heads per kv head,
    folded rows) against the KV block the table's j-th entry names.
    Causal masking is structural: a block entirely before the tile's
    first query (and inside the valid-key range) takes the mask-free
    fast path; only diagonal/limit-straddling blocks build the
    per-element mask."""
    i = pl.program_id(0)
    j = pl.program_id(1)
    start = meta_ref[0]
    limit = meta_ref[0] + meta_ref[1]            # keys < limit are real

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_lo = start + i * BC                        # tile's first q position
    q_hi = q_lo + BC - 1                         # tile's last q position
    k_lo = j * BS
    k_hi = k_lo + BS - 1
    # block liveness (mirrors the KV index map EXACTLY — a clamped
    # block must never be computed on): some key is real and causally
    # visible to some query of the tile
    live = (k_lo < limit) & (k_lo <= q_hi)
    if window:
        live = live & (k_hi > q_lo - window)
    # mask-free fast path: every key visible to every query
    full = (k_hi <= q_lo) & (k_hi < limit)
    if window:
        full = full & (k_lo > q_hi - window)

    def _accumulate(s, vb):
        """Online-softmax state update from scaled+masked scores
        s (KVH, BC*G, BS) fp32."""
        m_prev = m_ref[..., 0]                   # (KVH, BC*G)
        l_prev = l_ref[..., 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)  # (KVH, BC*G, d)
        acc_ref[...] = acc_ref[...] * alpha[..., None] + pv
        m_ref[...] = jnp.broadcast_to(m_new[..., None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[..., None], l_ref.shape)

    def _scores():
        kb = k_ref[0]                            # (KVH, BS, d)
        return jax.lax.dot_general(
            q_ref[...], kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale

    @pl.when(live & full)
    def _full_block():
        _accumulate(_scores(), v_ref[0])

    @pl.when(live & jnp.logical_not(full))
    def _masked_block():
        s = _scores()
        shape = s.shape                          # (KVH, BC*G, BS)
        # row r of the folded q dim is chunk token r // G
        qpos = q_lo + jax.lax.broadcasted_iota(jnp.int32, shape, 1) // G
        kpos = k_lo + jax.lax.broadcasted_iota(jnp.int32, shape, 2)
        ok = (kpos <= qpos) & (kpos < limit)
        if window:
            ok = ok & (kpos > qpos - window)
        _accumulate(jnp.where(ok, s, NEG_INF), v_ref[0])

    l = jnp.maximum(l_ref[..., 0], 1e-30)
    o_ref[...] = (acc_ref[...] / l[..., None]).astype(o_ref.dtype)


def paged_chunk_attention(q, k_cache, v_cache, table, start, true_len, *,
                          scale=None, window=0, block_c="auto",
                          interpret=None):
    """A C-token query chunk of ONE sequence attends over that
    sequence's paged KV blocks — the blocked-flash role of the
    reference's ragged_ops for the Dynamic SplitFuse chunk program.

    q: (C, H, d) chunk queries (positions start..start+C-1, right-pad
    rows are don't-care); k_cache/v_cache: (NB, KVH, BS, d) pools that
    ALREADY hold the chunk's own K/V (callers scatter first, exactly
    like the decode path); table: (MB,) int32 — the sequence's block
    table, scratch-padded; start/true_len: scalar int32. Returns
    (C, H, d) in q's dtype.

    Each KV block is located through the block table via a
    scalar-prefetch index map and streamed through VMEM once; blocks
    past ``start + true_len`` (and blocks causally dead for the whole
    q tile) are clamped to the tile's first table entry in the index
    map — consecutive repeats of one block id cost no fresh DMA — and
    skipped in-kernel. Blocks fully before the diagonal take a
    mask-free path; only straddling blocks build the per-element mask.
    ``window`` > 0 restricts attention to the trailing window
    (mistral). GQA is native: q folds to (KVH, C*G, d) and both dots
    batch over KVH — the dense path's repeat_kv copies never exist.
    ``block_c``: chunk-token tile ("auto" = the autotune winner cache's
    choice for this shape bucket; see autotuning/kernel_registry.py
    'paged_chunk').
    """
    C, H, d = q.shape
    NB, KVH, BS, _ = k_cache.shape
    MB = table.shape[0]
    G = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_default()
    if block_c == "auto":
        block_c = resolve_paged_chunk(
            True, "auto", C, MB, BS, KVH, G, d, q.dtype)[1]
    BC = max(1, min(int(block_c), C))
    NC = -(-C // BC)
    C_pad = NC * BC
    if C_pad != C:
        q = jnp.pad(q, ((0, C_pad - C), (0, 0), (0, 0)))
    # fold (chunk, group) query rows: (C_pad, KVH, G, d) -> (KVH, C_pad*G, d)
    qf = q.reshape(C_pad, KVH, G, d).transpose(1, 0, 2, 3) \
        .reshape(KVH, C_pad * G, d)
    meta = jnp.stack([jnp.asarray(start, jnp.int32),
                      jnp.asarray(true_len, jnp.int32)])

    def kv_index(i, j, tbl, meta):
        s0 = meta[0]
        limit = meta[0] + meta[1]
        q_lo = s0 + i * BC
        live = (j * BS < limit) & (j * BS <= q_lo + BC - 1)
        if window:
            live = live & (j * BS + BS - 1 > q_lo - window)
        return (jnp.where(live, tbl[j], tbl[0]), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(NC, MB),
        in_specs=[
            pl.BlockSpec((KVH, BC * G, d),
                         lambda i, j, tbl, meta: (0, i, 0)),
            pl.BlockSpec((1, KVH, BS, d), kv_index),
            pl.BlockSpec((1, KVH, BS, d), kv_index),
        ],
        out_specs=pl.BlockSpec((KVH, BC * G, d),
                               lambda i, j, tbl, meta: (0, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((KVH, BC * G, 128), jnp.float32),  # running max
            pltpu.VMEM((KVH, BC * G, 128), jnp.float32),  # running denom
            pltpu.VMEM((KVH, BC * G, d), jnp.float32),    # out accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_chunk_kernel, BS=BS, KVH=KVH, G=G, BC=BC,
                          scale=float(scale), window=int(window)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((KVH, C_pad * G, d), q.dtype),
        interpret=interpret,
    )(table, meta, qf, k_cache, v_cache)
    out = out.reshape(KVH, C_pad, G, d).transpose(1, 0, 2, 3) \
        .reshape(C_pad, H, d)
    return out[:C]


def paged_chunk_attention_reference(q, k_cache, v_cache, table, start,
                                    true_len, *, scale=None, window=0):
    """Dense-gather fallback (the pre-kernel chunk path): gather the
    sequence's whole key range through its table into one (S, H, d)
    array and run masked dense attention. Parity reference for the
    kernel and the registry's 'dense' mode."""
    C, H, d = q.shape
    NB, KVH, BS, _ = k_cache.shape
    MB = table.shape[0]
    S = MB * BS
    G = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    gk = k_cache[table].transpose(0, 2, 1, 3).reshape(S, KVH, d)
    gv = v_cache[table].transpose(0, 2, 1, 3).reshape(S, KVH, d)
    gk = jnp.repeat(gk, G, axis=1)
    gv = jnp.repeat(gv, G, axis=1)
    s = jnp.einsum("thd,shd->hts", q, gk,
                   preferred_element_type=jnp.float32) * scale
    q_pos = (start + jnp.arange(C))[:, None]
    k_pos = jnp.arange(S)[None, :]
    ok = (k_pos <= q_pos) & (k_pos < start + true_len)
    if window:
        ok = ok & (q_pos - k_pos < window)
    s = jnp.where(ok[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("hts,shd->thd", p, gv)
