"""Block-sparse attention as Pallas TPU kernels.

Counterpart of the reference's Triton blocksparse tier
(``ops/sparse_attention/matmul.py`` + ``softmax.py``): attention
restricted to a (H, nq, nk) boolean block LAYOUT (fixed / BigBird /
Longformer configs in ops/sparse_attention/sparsity_config.py). The
masked-dense realization (ops/sparse_attention/sparse_self_attention.py)
computes every block and masks — O(T^2) compute and bandwidth regardless
of density, which defeats the component's purpose. These kernels iterate
ONLY the present blocks of each row (forward, dq) / column (dk/dv):
compute scales with layout density, the entire point of block sparsity.

Mechanics: the layout is preprocessed (host-side numpy, cacheable) into
per-row present-block id lists `rows (H, nq, max_nnz)` + counts
`row_cnt (H, nq)` and the column-wise transpose for the backward; the
lists ride scalar prefetch (SMEM) and the in-kernel fori_loop runs
`cnt` iterations of the flash-style streaming softmax, dynamically
slicing K/V (VMEM-resident per head) at `ids[jj] * block`. Numerics
match the masked-dense reference (same fp32 softmax, fully-masked rows
produce zero output).
"""

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_default as _interpret_default

NEG_INF = -1e30


def layout_lists(layout, causal, nq, nk):
    """(H, nq, nk) bool layout -> row/col present-block lists.

    Returns dict of int32 arrays: rows (H, nq, mr), row_cnt (H, nq),
    cols (H, nk, mc), col_cnt (H, nk). With ``causal`` blocks above the
    diagonal are dropped here (block b_q attends b_k <= b_q)."""
    lay = np.asarray(layout[:, :nq, :nk], bool).copy()
    if causal:
        tri = np.tril(np.ones((nq, nk), bool))
        lay &= tri[None]
    H = lay.shape[0]
    mr = max(1, int(lay.sum(axis=2).max()))
    mc = max(1, int(lay.sum(axis=1).max()))
    rows = np.zeros((H, nq, mr), np.int32)
    row_cnt = np.zeros((H, nq), np.int32)
    cols = np.zeros((H, nk, mc), np.int32)
    col_cnt = np.zeros((H, nk), np.int32)
    for h in range(H):
        for i in range(nq):
            ids = np.nonzero(lay[h, i])[0]
            rows[h, i, :len(ids)] = ids
            row_cnt[h, i] = len(ids)
        for j in range(nk):
            ids = np.nonzero(lay[h, :, j])[0]
            cols[h, j, :len(ids)] = ids
            col_cnt[h, j] = len(ids)
    return {"rows": rows, "row_cnt": row_cnt,
            "cols": cols, "col_cnt": col_cnt}


def _causal_mask(qi, j, bq, bk, T_q, T_k):
    qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return qpos >= kpos


# ------------------------------------------------------------------ forward
def _fwd_kernel(rows_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                bq, bk, H, causal):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    h = bh % H
    q = q_ref[0]                                          # (bq, d) bf16/f32
    d = q.shape[-1]
    cnt = cnt_ref[h, qi]

    def body(jj, carry):
        acc, m, l = carry
        j = rows_ref[h, qi, jj]
        kb = k_ref[0, pl.ds(j * bk, bk), :]
        vb = v_ref[0, pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = jnp.where(_causal_mask(qi, j, bq, bk, None, None),
                          s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l

    acc = jnp.zeros((bq, d), jnp.float32)
    m = jnp.full((bq,), NEG_INF, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, cnt, body, (acc, m, l))
    # fully-masked rows (cnt==0 or causal-trimmed) -> zero output, like
    # the masked-dense reference
    safe_l = jnp.maximum(l, 1e-30)
    o_ref[0] = jnp.where(l[:, None] > 0, acc / safe_l[:, None],
                         0.0).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(
        jnp.where(l > 0, m + jnp.log(safe_l), NEG_INF)[:, None],
        (bq, lse_ref.shape[-1]))


# ----------------------------------------------------------------- backward
def _bwd_dq_kernel(rows_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, *, bq, bk, H, causal):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    h = bh % H
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, 0]
    delta = delta_ref[0][:, 0]
    d = q.shape[-1]
    cnt = cnt_ref[h, qi]

    def body(jj, dq):
        j = rows_ref[h, qi, jj]
        kb = k_ref[0, pl.ds(j * bk, bk), :]
        vb = v_ref[0, pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = jnp.where(_causal_mask(qi, j, bq, bk, None, None),
                          s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, cnt, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(cols_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, *, bq, bk, H,
                    causal):
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    h = bh % H
    kb = k_ref[0]                                         # (bk, d)
    vb = v_ref[0]
    d = kb.shape[-1]
    cnt = cnt_ref[h, ki]

    def body(ii, carry):
        dk, dv = carry
        i = cols_ref[h, ki, ii]
        q = q_ref[0, pl.ds(i * bq, bq), :]
        do = do_ref[0, pl.ds(i * bq, bq), :]
        lse = lse_ref[0, pl.ds(i * bq, bq), :][:, 0]
        delta = delta_ref[0, pl.ds(i * bq, bq), :][:, 0]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = jnp.where(_causal_mask(i, ki, bq, bk, None, None),
                          s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        pb = p.astype(do.dtype)
        dv = dv + jax.lax.dot_general(pb, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(q.dtype)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, cnt, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------- plumbing
def _fwd(q, k, v, lists, bq, bk, H, causal, interpret):
    BH, T, d = q.shape
    nq = T // bq
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, r, c: (b, i, 0)),
            pl.BlockSpec((1, T, d), lambda b, i, r, c: (b, 0, 0)),
            pl.BlockSpec((1, T, d), lambda b, i, r, c: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, r, c: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, i, r, c: (b, i, 0)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_fwd_kernel, bq=bq, bk=bk, H=H, causal=causal),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((BH, T, d), q.dtype),
                   jax.ShapeDtypeStruct((BH, T, 128), jnp.float32)],
        interpret=interpret,
    )(lists["rows"], lists["row_cnt"], q, k, v)


def _bwd(q, k, v, o, lse, do, lists, bq, bk, H, causal, interpret):
    BH, T, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)
    delta = jnp.broadcast_to(delta[..., None], lse.shape)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, bq=bq, bk=bk, H=H,
                          causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, T // bq),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda b, i, r, c: (b, i, 0)),
                pl.BlockSpec((1, T, d), lambda b, i, r, c: (b, 0, 0)),
                pl.BlockSpec((1, T, d), lambda b, i, r, c: (b, 0, 0)),
                pl.BlockSpec((1, bq, d), lambda b, i, r, c: (b, i, 0)),
                pl.BlockSpec((1, bq, 128), lambda b, i, r, c: (b, i, 0)),
                pl.BlockSpec((1, bq, 128), lambda b, i, r, c: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, bq, d),
                                   lambda b, i, r, c: (b, i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((BH, T, d), q.dtype),
        interpret=interpret,
    )(lists["rows"], lists["row_cnt"], q, k, v, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, bq=bq, bk=bk, H=H,
                          causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, T // bk),
            in_specs=[
                pl.BlockSpec((1, T, d), lambda b, j, c, n: (b, 0, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, c, n: (b, j, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, c, n: (b, j, 0)),
                pl.BlockSpec((1, T, d), lambda b, j, c, n: (b, 0, 0)),
                pl.BlockSpec((1, T, 128), lambda b, j, c, n: (b, 0, 0)),
                pl.BlockSpec((1, T, 128), lambda b, j, c, n: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, d), lambda b, j, c, n: (b, j, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, c, n: (b, j, 0)),
            ],
        ),
        out_shape=[jax.ShapeDtypeStruct((BH, T, d), q.dtype),
                   jax.ShapeDtypeStruct((BH, T, d), q.dtype)],
        interpret=interpret,
    )(lists["cols"], lists["col_cnt"], q, k, v, do, lse, delta)
    return dq, dk, dv


def block_sparse_attention(q, k, v, layout, block, *, causal=False,
                           scale=None, lists=None, interpret=None):
    """Attention restricted to a (H, T//block, T//block) bool layout.

    q/k/v: (B, T, H, d); T must divide by ``block``. ``lists`` may carry
    the precomputed :func:`layout_lists` (callers should cache it per
    (layout, T) — building it is host-side numpy). Matches
    sparse_self_attention.sparse_attention numerics (zero output for
    fully-masked rows). Differentiable: flash-style dq / dk+dv kernels
    over the row / column block lists."""
    B, T, H, d = q.shape
    assert T % block == 0, f"seq {T} not divisible by block {block}"
    nq = nk = T // block
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_default()
    if lists is None:
        lists = layout_lists(np.asarray(layout), causal, nq, nk)
    # static per-layout data: closed over as jaxpr constants so the
    # custom_vjp is over (q, k, v) only
    clists = {k2: jnp.asarray(np.asarray(v2), jnp.int32)
              for k2, v2 in lists.items()}
    bq = bk = block
    causal = bool(causal)
    interpret = bool(interpret)

    @jax.custom_vjp
    def bsa(qf, kf, vf):
        o, _ = _fwd(qf, kf, vf, clists, bq, bk, H, causal, interpret)
        return o

    def bsa_fwd(qf, kf, vf):
        o, lse = _fwd(qf, kf, vf, clists, bq, bk, H, causal, interpret)
        return o, (qf, kf, vf, o, lse)

    def bsa_bwd(res, do):
        qf, kf, vf, o, lse = res
        return _bwd(qf, kf, vf, o, lse, do, clists, bq, bk, H, causal,
                    interpret)

    bsa.defvjp(bsa_fwd, bsa_bwd)

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, d)

    q = q * jnp.asarray(scale, q.dtype)
    o = bsa(fold(q), fold(k), fold(v))
    return o.reshape(B, H, T, d).transpose(0, 2, 1, 3)
