"""Shared Pallas kernel helpers (counterpart of reference
``csrc/includes/`` — the template library every CUDA kernel includes),
plus the measured-dispatch layer: kernel wrappers whose tunable
parameters are set to ``"auto"`` resolve them here against the
persistent autotune winner cache (autotuning/kernel_dispatch.py) at
TRACE time — the chosen variant is baked into the jitted program, so a
warm cache costs zero per-step host work.
"""

import jax

# sentinel a kernel tunable takes to mean "resolve via the autotune
# winner cache" (models pass their config knobs through verbatim)
AUTO = "auto"


def dispatch(op, bucket, dtype, defaults):
    """Trace-time tunable resolution for kernel ``op``.

    Consults the autotune winner cache for
    (device_kind, op, shape-bucket, dtype) under the active autotune
    mode (runtime config ``autotune`` block / DSTPU_AUTOTUNE env):
    returns the cached winner's params merged over ``defaults``, runs a
    measured search first in the search modes, and falls back to
    ``defaults`` (the r05-proven hand-set values) on any miss/refusal.
    Pure Python at trace time — nothing here survives into the compiled
    program but the chosen constants."""
    from ...autotuning import kernel_dispatch
    return kernel_dispatch.resolve(op, bucket, dtype, defaults)


def dtype_name(dtype):
    """Canonical dtype string for cache keys ('bfloat16', 'float32')."""
    import jax.numpy as jnp
    return jnp.dtype(dtype).name


# ----------------------------------------------------- shape buckets
# One bucket string per op keys the winner cache: exact in the dims
# that pick kernel variants (feature/head/vocab dims — they gate block
# validity), power-of-two-rounded in the data-volume dims (tokens,
# rows) so nearby batch shapes share a winner instead of each paying a
# search.

def pow2_bucket(n):
    """Round ``n`` up to the next power of two (>= 1)."""
    return 1 << max(0, int(n) - 1).bit_length()


def flash_bucket(T, d, causal, qkv_t):
    return f"T{pow2_bucket(T)},d{int(d)},c{int(bool(causal))}," \
           f"q{int(bool(qkv_t))}"


def mlp_bucket(T, D, F):
    return f"T{pow2_bucket(T)},D{int(D)},F{int(F)}"


def ln_bucket(rows, D):
    return f"R{pow2_bucket(rows)},D{int(D)}"


def ring_bucket(T, d):
    """Ring-attention chunk-pair bucket: T is the per-step CHUNK length
    (T_global / (2 * ring) under zigzag), not the full sequence."""
    return f"T{pow2_bucket(T)},d{int(d)}"


def ce_bucket(N, D, V):
    return f"N{pow2_bucket(N)},D{int(D)},V{int(V)}"


def moe_grouped_bucket(S, E, M, F):
    """Grouped expert-FFN bucket: tokens-per-shard (rows entering the
    grouped product, incl. the k-replication) pow2-rounded; local expert
    count and model/FFN dims exact (they gate block validity and the
    kernel-vs-ragged crossover)."""
    return f"S{pow2_bucket(S)},E{int(E)},M{int(M)},F{int(F)}"


def paged_decode_bucket(B, MB, BS, KVH, G, d):
    """Serving decode-shape bucket: batch slots and blocks-per-seq
    pow2-rounded (nearby batch mixes share a winner); block size,
    kv-head count, GQA group and head dim exact (they gate kernel-block
    validity and the GQA fold)."""
    return f"B{pow2_bucket(B)},MB{pow2_bucket(MB)},BS{int(BS)}," \
           f"kh{int(KVH)},g{int(G)},d{int(d)}"


def pipe_bucket(S, B, T, D):
    """Pipeline-step bucket: stage count exact (it sets the tick count
    and the candidate microbatch grid), per-stage batch rows
    pow2-rounded, sequence pow2-rounded, model width exact (it gates
    the per-tick block cost)."""
    return f"S{int(S)},B{pow2_bucket(B)},T{pow2_bucket(T)},D{int(D)}"


def paged_chunk_bucket(C, MB, BS, KVH, G, d):
    """SplitFuse chunk-shape bucket: the chunk length C is exact (it
    gates block_c validity — one compiled chunk program per engine
    config anyway), blocks-per-seq pow2-rounded."""
    return f"C{int(C)},MB{pow2_bucket(MB)},BS{int(BS)}," \
           f"kh{int(KVH)},g{int(G)},d{int(d)}"


def interpret_default():
    """Kernels run in Pallas interpreter mode off-TPU (unit tests, the
    virtual CPU mesh)."""
    return jax.default_backend() != "tpu"


def sds(shape, dtype, like):
    """ShapeDtypeStruct whose varying-manual-axes match ``like`` — required
    when a kernel runs inside a shard_map region (e.g. quantized
    collectives, pipelined blocks)."""
    typeof = getattr(jax, "typeof", None)
    vma = getattr(typeof(like), "vma", None) if typeof is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def round_up(n, m):
    return -(-n // m) * m
