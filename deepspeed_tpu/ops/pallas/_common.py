"""Shared Pallas kernel helpers (counterpart of reference
``csrc/includes/`` — the template library every CUDA kernel includes),
plus the measured-dispatch layer: kernel wrappers whose tunable
parameters are set to ``"auto"`` resolve them here against the
persistent autotune winner cache (autotuning/kernel_dispatch.py) at
TRACE time — the chosen variant is baked into the jitted program, so a
warm cache costs zero per-step host work.
"""

import jax

# sentinel a kernel tunable takes to mean "resolve via the autotune
# winner cache" (models pass their config knobs through verbatim)
AUTO = "auto"


def dispatch(op, bucket, dtype, defaults):
    """Trace-time tunable resolution for kernel ``op``.

    Consults the autotune winner cache for
    (device_kind, op, shape-bucket, dtype) under the active autotune
    mode (runtime config ``autotune`` block / DSTPU_AUTOTUNE env):
    returns the cached winner's params merged over ``defaults``, runs a
    measured search first in the search modes, and falls back to
    ``defaults`` (the r05-proven hand-set values) on any miss/refusal.
    Pure Python at trace time — nothing here survives into the compiled
    program but the chosen constants."""
    from ...autotuning import kernel_dispatch
    return kernel_dispatch.resolve(op, bucket, dtype, defaults)


def dtype_name(dtype):
    """Canonical dtype string for cache keys ('bfloat16', 'float32')."""
    import jax.numpy as jnp
    return jnp.dtype(dtype).name


# ----------------------------------------------------- shape buckets
# One bucket string per op keys the winner cache: exact in the dims
# that pick kernel variants (feature/head/vocab dims — they gate block
# validity), power-of-two-rounded in the data-volume dims (tokens,
# rows) so nearby batch shapes share a winner instead of each paying a
# search.

def pow2_bucket(n):
    """Round ``n`` up to the next power of two (>= 1)."""
    return 1 << max(0, int(n) - 1).bit_length()


def flash_bucket(T, d, causal, qkv_t):
    return f"T{pow2_bucket(T)},d{int(d)},c{int(bool(causal))}," \
           f"q{int(bool(qkv_t))}"


def mlp_bucket(T, D, F):
    return f"T{pow2_bucket(T)},D{int(D)},F{int(F)}"


def ln_bucket(rows, D):
    return f"R{pow2_bucket(rows)},D{int(D)}"


def ring_bucket(T, d):
    """Ring-attention chunk-pair bucket: T is the per-step CHUNK length
    (T_global / (2 * ring) under zigzag), not the full sequence."""
    return f"T{pow2_bucket(T)},d{int(d)}"


def ce_bucket(N, D, V):
    return f"N{pow2_bucket(N)},D{int(D)},V{int(V)}"


def moe_grouped_bucket(S, E, M, F):
    """Grouped expert-FFN bucket: tokens-per-shard (rows entering the
    grouped product, incl. the k-replication) pow2-rounded; local expert
    count and model/FFN dims exact (they gate block validity and the
    kernel-vs-ragged crossover)."""
    return f"S{pow2_bucket(S)},E{int(E)},M{int(M)},F{int(F)}"


def paged_decode_bucket(B, MB, BS, KVH, G, d):
    """Serving decode-shape bucket: batch slots and blocks-per-seq
    pow2-rounded (nearby batch mixes share a winner); block size,
    kv-head count, GQA group and head dim exact (they gate kernel-block
    validity and the GQA fold)."""
    return f"B{pow2_bucket(B)},MB{pow2_bucket(MB)},BS{int(BS)}," \
           f"kh{int(KVH)},g{int(G)},d{int(d)}"


def pipe_bucket(S, B, T, D):
    """Pipeline-step bucket: stage count exact (it sets the tick count
    and the candidate microbatch grid), per-stage batch rows
    pow2-rounded, sequence pow2-rounded, model width exact (it gates
    the per-tick block cost)."""
    return f"S{int(S)},B{pow2_bucket(B)},T{pow2_bucket(T)},D{int(D)}"


def paged_chunk_bucket(C, MB, BS, KVH, G, d):
    """SplitFuse chunk-shape bucket: the chunk length C is exact (it
    gates block_c validity — one compiled chunk program per engine
    config anyway), blocks-per-seq pow2-rounded."""
    return f"C{int(C)},MB{pow2_bucket(MB)},BS{int(BS)}," \
           f"kh{int(KVH)},g{int(G)},d{int(d)}"


# ------------------------------------------- collective-op buckets
# Collective-bearing ops (autotuning/collective_ops.py) are winners per
# (device_kind, TOPOLOGY-SIGNATURE, shape-bucket): the mesh shape is
# folded into the bucket STRING itself, so the cache file format and the
# device-kind refusal rule are untouched — a winner measured on a
# dp=4,do=2 mesh can never steer a dp=8 flat mesh, exactly as a T=1024
# flash winner never steers T=128.

def topo_signature(mesh=None):
    """Compact mesh signature for collective bucket strings:
    'pp1,do1,dp4,ep1,sp1,tp1' (every axis exact — each size changes the
    collective's replica groups, so no two topologies may share a
    winner). Falls back to the all-ones signature when no topology has
    been initialized (single-chip/virtual runs)."""
    shape = {}
    if mesh is not None:
        shape = dict(mesh.shape)
    else:
        try:
            from ...utils import groups
            shape = dict(groups.get_mesh().shape)
        except Exception:  # noqa: BLE001 — pre-topology trace
            shape = {}
    g = lambda a: int(shape.get(a, 1))
    return (f"pp{g('pipe')},do{g('data_outer')},dp{g('data')},"
            f"ep{g('expert')},sp{g('seq')},tp{g('tensor')}")


def grad_comm_bucket(layer_mb, mesh=None):
    """Gradient-collective bucket (ops comm_bucket / grad_staging /
    dcn_quantize): topology signature + the per-layer gradient payload
    in MB, pow2-rounded (nearby layer sizes share a winner)."""
    return f"{topo_signature(mesh)},L{pow2_bucket(max(1, layer_mb))}"


def a2a_bucket(tokens, M, mesh=None):
    """Expert all_to_all bucket (op a2a_staging): topology signature +
    tokens-per-shard pow2-rounded + model width exact (it sets the
    payload row size the staged exchange re-buckets)."""
    return f"{topo_signature(mesh)},S{pow2_bucket(max(1, tokens))}," \
           f"M{int(M)}"


def ring_rotate_bucket(R, chunk, d, mesh=None):
    """Ring KV-rotation bucket (op ring_rotate): ring size exact (it is
    the perm), per-step chunk length pow2-rounded, head dim exact."""
    return f"{topo_signature(mesh)},R{int(R)},T{pow2_bucket(chunk)}," \
           f"d{int(d)}"


def scan_unroll_bucket(n_layer, D, mesh=None):
    """Layer-scan unroll bucket (op scan_unroll): layer count and model
    width exact — they set how much compute one unrolled body gives the
    prefetch gather to hide under."""
    return f"{topo_signature(mesh)},N{int(n_layer)},D{int(D)}"


def hot_replicas_bucket(shard_mb, mesh=None):
    """Hot-tier replication bucket (op hot_replicas): topology signature
    + per-host checkpoint shard payload in MB, pow2-rounded."""
    return f"{topo_signature(mesh)},G{pow2_bucket(max(1, shard_mb))}"


def interpret_default():
    """Kernels run in Pallas interpreter mode off-TPU (unit tests, the
    virtual CPU mesh)."""
    return jax.default_backend() != "tpu"


def sds(shape, dtype, like):
    """ShapeDtypeStruct whose varying-manual-axes match ``like`` — required
    when a kernel runs inside a shard_map region (e.g. quantized
    collectives, pipelined blocks)."""
    typeof = getattr(jax, "typeof", None)
    vma = getattr(typeof(like), "vma", None) if typeof is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def round_up(n, m):
    return -(-n // m) * m
