"""Shared Pallas kernel helpers (counterpart of reference
``csrc/includes/`` — the template library every CUDA kernel includes)."""

import jax


def interpret_default():
    """Kernels run in Pallas interpreter mode off-TPU (unit tests, the
    virtual CPU mesh)."""
    return jax.default_backend() != "tpu"


def sds(shape, dtype, like):
    """ShapeDtypeStruct whose varying-manual-axes match ``like`` — required
    when a kernel runs inside a shard_map region (e.g. quantized
    collectives, pipelined blocks)."""
    typeof = getattr(jax, "typeof", None)
    vma = getattr(typeof(like), "vma", None) if typeof is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def round_up(n, m):
    return -(-n // m) * m
