"""Layout-owning MLP projection matmul as Pallas TPU kernels.

Counterpart of the reference's epilogue-fusing GEMM wrappers
(``csrc/transformer/cublas_wrappers.cu`` + ``general_kernels.cu`` — the
GPU path earns its throughput by fusing what stock cuBLAS + eltwise
passes would materialize). The TPU-shape of the same problem is LAYOUT,
not epilogue math: at GPT-2 MLP shapes the qkv/attention tier emits
T-minor activations (T in lanes — hd=64 fills only half a 128-lane
register, so XLA propagates T-in-lanes pressure through the block
carry), and XLA's emitter for the down-projection under that layout
(``EmitOutputBatchInLanesKernelOutputFeatureInLanes``) runs the matmul
at roughly half rate — a measured ~13 ms/step at the 350M bench point —
while the backward pays transpose/cast copies re-laying the cotangents.

These kernels own both boundaries end to end:

  * the forward accepts the activation in EITHER orientation — (B, T, K)
    row-major, or (B, K, T) with T in lanes (the layout the surrounding
    einsums naturally emit; ``x_t=True``) — and emits the output in
    either orientation (``out_t``) with fp32 accumulation, so no
    relayout copy exists on either side of the projection;
  * the backward dx kernel emits the activation cotangent directly in
    the activation's own orientation (the transpose XLA would otherwise
    insert as a copy is the kernel's output indexing), and the dw kernel
    accumulates fp32 across the (batch, token) grid and casts to the
    weight dtype in its epilogue (no fp32 (K, M) HBM buffer + cast
    copy).

Off-TPU the kernels run in Pallas interpreter mode (unit tests); shapes
whose blocks cannot satisfy the TPU tiling rules fall back to a jnp
einsum with identical math (fp32 accumulation, output-dtype round).

The layout/epilogue choice itself (XLA einsums vs 'down' vs 'both',
fused-vs-XLA dw, tile sizes) is a MODEL-level decision and is
autotunable: ``models/gpt2.py`` resolves ``cfg.mlp_kernel="auto"``
against the persistent winner cache via the measured-dispatch layer
(``_common.dispatch``, registry op ``"mlp_matmul"`` in
``autotuning/kernel_registry.py``) and passes the winning mode and
block sizes into this module explicitly.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_default as _interpret_default
from ._common import sds as _sds


def _pick_block(dim, want, lane):
    """Largest divisor of ``dim`` that is <= want and tile-aligned
    (lane dims in 128 units, sublane dims in 8); ``dim`` itself (a
    single full block) is always acceptable. None = no valid block."""
    if dim <= want:
        return dim
    unit = 128 if lane else 8
    b = (want // unit) * unit
    while b >= unit:
        if dim % b == 0:
            return b
        b -= unit
    return None


# --------------------------------------------------------------- forward/dx
def _mm_kernel(a_ref, b_ref, o_ref, acc, *, a_t, b_t, out_t, nk):
    """One (n, m) output block: acc (f32) += a_blk . b_blk over the k
    grid (k innermost); write-out (cast to o dtype) at the last k step."""
    kk = pl.program_id(3)

    @pl.when(kk == 0)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    a = a_ref[0]                       # (bn, bk) | (bk, bn) when a_t
    b = b_ref[...]                     # (bk, bm) | (bm, bk) when b_t
    ca = 0 if a_t else 1               # a's contract dim
    cb = 1 if b_t else 0               # b's contract dim
    if out_t:                          # (bm, bn) = b . a
        acc[...] += lax.dot_general(
            b, a, (((cb,), (ca,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:                              # (bn, bm) = a . b
        acc[...] += lax.dot_general(
            a, b, (((ca,), (cb,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _flush():
        o_ref[0] = acc[...].astype(o_ref.dtype)


def _mm(a, b, *, a_t, b_t, out_t, bn, bm, bk, out_dtype, interpret):
    """Batched ``out[p, n, m] = sum_k a_log[p, n, k] * b_log[k, m]``.

    a: (P, N, K) (or (P, K, N) when ``a_t``); b: (K, M) (or (M, K) when
    ``b_t``); out: (P, N, M) (or (P, M, N) when ``out_t``). fp32
    accumulation, output cast in the kernel epilogue.
    """
    P = a.shape[0]
    if a_t:
        K, N = a.shape[1], a.shape[2]
    else:
        N, K = a.shape[1], a.shape[2]
    M = b.shape[0] if b_t else b.shape[1]
    grid = (P, N // bn, M // bm, K // bk)

    a_spec = pl.BlockSpec((1, bk, bn), lambda p, i, j, k: (p, k, i)) \
        if a_t else pl.BlockSpec((1, bn, bk), lambda p, i, j, k: (p, i, k))
    b_spec = pl.BlockSpec((bm, bk), lambda p, i, j, k: (j, k)) \
        if b_t else pl.BlockSpec((bk, bm), lambda p, i, j, k: (k, j))
    o_spec = pl.BlockSpec((1, bm, bn), lambda p, i, j, k: (p, j, i)) \
        if out_t else pl.BlockSpec((1, bn, bm), lambda p, i, j, k: (p, i, j))
    o_shape = (P, M, N) if out_t else (P, N, M)
    acc_shape = (bm, bn) if out_t else (bn, bm)

    return pl.pallas_call(
        functools.partial(_mm_kernel, a_t=a_t, b_t=b_t, out_t=out_t,
                          nk=K // bk),
        grid=grid,
        in_specs=[a_spec, b_spec],
        out_specs=o_spec,
        out_shape=_sds(o_shape, out_dtype, a),
        scratch_shapes=[pltpu.VMEM(acc_shape, jnp.float32)],
        interpret=interpret,
    )(a, b)


# --------------------------------------------------------------------- dw
def _dw_kernel(a_ref, g_ref, o_ref, acc, *, a_t, g_t, last_p, last_n):
    """One (bkK, bm) weight-grad block; accumulates f32 over the (p, n)
    grid steps (innermost dims — the output block index is constant
    across them) and casts to the weight dtype at the last step."""
    p = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(jnp.logical_and(p == 0, i == 0))
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    a = a_ref[0]                       # (bn, bkK) | (bkK, bn) when a_t
    g = g_ref[0]                       # (bn, bm)  | (bm, bn)  when g_t
    ca = 1 if a_t else 0               # contract the token dim
    cg = 1 if g_t else 0
    acc[...] += lax.dot_general(
        a, g, (((ca,), (cg,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(p == last_p, i == last_n))
    def _flush():
        o_ref[...] = acc[...].astype(o_ref.dtype)


def _dw(a, g, *, a_t, g_t, bkK, bm, bn, out_dtype, interpret):
    """dw[k, m] = sum_{p, n} a_log[p, n, k] * g_log[p, n, m] — the
    weight gradient with fp32 accumulation across the whole (batch,
    token) extent and the cast-to-weight-dtype epilogue fused."""
    P = a.shape[0]
    if a_t:
        K, N = a.shape[1], a.shape[2]
    else:
        N, K = a.shape[1], a.shape[2]
    M = g.shape[1] if g_t else g.shape[2]
    grid = (K // bkK, M // bm, P, N // bn)

    a_spec = pl.BlockSpec((1, bkK, bn), lambda k, j, p, i: (p, k, i)) \
        if a_t else pl.BlockSpec((1, bn, bkK), lambda k, j, p, i: (p, i, k))
    g_spec = pl.BlockSpec((1, bm, bn), lambda k, j, p, i: (p, j, i)) \
        if g_t else pl.BlockSpec((1, bn, bm), lambda k, j, p, i: (p, i, j))

    return pl.pallas_call(
        functools.partial(_dw_kernel, a_t=a_t, g_t=g_t, last_p=P - 1,
                          last_n=N // bn - 1),
        grid=grid,
        in_specs=[a_spec, g_spec],
        out_specs=pl.BlockSpec((bkK, bm), lambda k, j, p, i: (k, j)),
        out_shape=_sds((K, M), out_dtype, a),
        scratch_shapes=[pltpu.VMEM((bkK, bm), jnp.float32)],
        interpret=interpret,
    )(a, g)


# -------------------------------------------------------------- jnp fallback
def _ref_proj(x, w, x_t, out_t):
    """jnp reference with the kernels' exact numerics: fp32 accumulation,
    one round to the output dtype."""
    eq = ("bkt,km->b" + ("mt" if out_t else "tm")) if x_t \
        else ("btk,km->b" + ("mt" if out_t else "tm"))
    return jnp.einsum(eq, x, w,
                      preferred_element_type=jnp.float32).astype(x.dtype)


# ------------------------------------------------------------------ public
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8))
def _proj(x, w, x_t, out_t, bt, bo, bk, fuse_dw, interpret):
    return _mm(x, w, a_t=x_t, b_t=False, out_t=out_t, bn=bt, bm=bo,
               bk=bk, out_dtype=x.dtype, interpret=interpret)


def _proj_fwd(x, w, x_t, out_t, bt, bo, bk, fuse_dw, interpret):
    return _proj(x, w, x_t, out_t, bt, bo, bk, fuse_dw, interpret), (x, w)


def _proj_bwd(x_t, out_t, bt, bo, bk, fuse_dw, interpret, res, dy):
    x, w = res
    K, M = w.shape
    # dx[p, n, k] = sum_m dy[p, n, m] w[k, m]: contract M; emitted
    # straight in x's orientation — the backward transpose XLA inserts
    # on the einsum vjp is this kernel's output indexing instead
    dx = _mm(dy, w, a_t=out_t, b_t=True, out_t=x_t, bn=bt, bm=bk,
             bk=bo, out_dtype=x.dtype, interpret=interpret)
    if fuse_dw:
        dw = _dw(x, dy, a_t=x_t, g_t=out_t, bkK=bk, bm=bo, bn=bt,
                 out_dtype=w.dtype, interpret=interpret)
    else:
        # let XLA own the weight grad: inside the layer scan it fuses
        # this contraction into the grad-stacking DUS at full MXU rate
        # (the round-3 trace finding); the kernel variant exists for
        # points where that fusion does not form
        xe = "bkt" if x_t else "btk"
        ge = "bmt" if out_t else "btm"
        dw = jnp.einsum(f"{xe},{ge}->km", x, dy,
                        preferred_element_type=jnp.float32).astype(w.dtype)
    return dx, dw


_proj.defvjp(_proj_fwd, _proj_bwd)


def mlp_matmul(x, w, *, x_t=False, out_t=False, block_t=256,
               block_o=256, block_k=512, fuse_dw=True, interpret=None):
    """Batched projection ``y[b, t, m] = sum_k x[b, t, k] w[k, m]`` with
    kernel-owned operand/output layouts.

    x: (B, T, K), or (B, K, T) with the token dim in lanes when
    ``x_t=True`` (the layout the qkv/MLP einsums naturally emit); w:
    (K, M); returns (B, T, M), or (B, M, T) when ``out_t=True``. fp32
    accumulation, output rounded once to x.dtype (exactly what the MXU
    does for the jnp matmul). Differentiable: dx comes back in x's own
    orientation and dw accumulates fp32 with the weight-dtype cast
    fused (``fuse_dw=False`` leaves dw to XLA — inside a layer scan it
    fuses into the grad-stacking DUS at full rate).

    Shapes whose dims cannot form tile-aligned blocks fall back to a
    jnp einsum with identical math.
    """
    if x.ndim != 3 or w.ndim != 2:
        raise ValueError(
            f"mlp_matmul expects x (B, ., .) and w (K, M); got "
            f"{x.shape} / {w.shape}")
    K = x.shape[1] if x_t else x.shape[2]
    T = x.shape[2] if x_t else x.shape[1]
    if w.shape[0] != K:
        raise ValueError(
            f"contract dim mismatch: x carries K={K}, w is {w.shape}")
    M = w.shape[1]
    if interpret is None:
        interpret = _interpret_default()
    # every dim appears in lanes in at least one of the fwd/dx/dw
    # blocks, so all three use lane-unit (128) granularity unless they
    # are a single full block
    bt = _pick_block(T, block_t, lane=True)
    bo = _pick_block(M, block_o, lane=True)
    bk = _pick_block(K, block_k, lane=True)
    if None in (bt, bo, bk) or min(T, M, K) < 8:
        return _ref_proj(x, w, x_t, out_t)
    return _proj(x, w, bool(x_t), bool(out_t), bt, bo, bk,
                 bool(fuse_dw), bool(interpret))


# ----------------------------------------- weight-only quantized forward
def _unpack_int4_tile(p):
    """(bk//2, bm) packed int4 tile -> (bk, bm) int8 codes (layout in
    ops/pallas/quantization.py: low nibble = even row, high = odd)."""
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)
    hi = jnp.right_shift(p, 4)
    return jnp.stack([lo, hi], axis=1).reshape(2 * p.shape[0], p.shape[1])


def _mm_wq_kernel(a_ref, b_ref, s_ref, o_ref, acc, *, a_t, out_t, nk,
                  int4):
    """_mm_kernel with a quantized weight operand: the b tile arrives as
    int8 codes (or two-per-byte int4), is widened in VMEM, and the
    per-output-channel scale multiplies the f32 accumulator ONCE in the
    flush epilogue — legal because the scale lives on the non-contracted
    dim, so it commutes with the K accumulation. No dequantized (K, M)
    tensor ever exists in HBM."""
    kk = pl.program_id(3)

    @pl.when(kk == 0)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    a = a_ref[0].astype(jnp.float32)   # (bn, bk) | (bk, bn) when a_t
    b = b_ref[...]                     # (bk, bm) int8 | (bk//2, bm) packed
    if int4:
        b = _unpack_int4_tile(b)
    bf = b.astype(jnp.float32)
    ca = 0 if a_t else 1
    if out_t:                          # (bm, bn) = b . a
        acc[...] += lax.dot_general(
            bf, a, (((0,), (ca,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:                              # (bn, bm) = a . b
        acc[...] += lax.dot_general(
            a, bf, (((ca,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _flush():
        s = s_ref[0]                   # (bm,) per-output-channel scales
        scaled = acc[...] * (s[:, None] if out_t else s[None, :])
        o_ref[0] = scaled.astype(o_ref.dtype)


def _mm_wq(a, q, s, *, a_t, out_t, bn, bm, bk, out_dtype, int4,
           interpret):
    """Quantized-weight _mm: q is int8 (K, M) or packed int4 (K//2, M),
    s is the (1, M) per-output-channel scale."""
    P = a.shape[0]
    if a_t:
        K, N = a.shape[1], a.shape[2]
    else:
        N, K = a.shape[1], a.shape[2]
    M = s.shape[1]
    grid = (P, N // bn, M // bm, K // bk)

    a_spec = pl.BlockSpec((1, bk, bn), lambda p, i, j, k: (p, k, i)) \
        if a_t else pl.BlockSpec((1, bn, bk), lambda p, i, j, k: (p, i, k))
    b_blk = (bk // 2, bm) if int4 else (bk, bm)
    b_spec = pl.BlockSpec(b_blk, lambda p, i, j, k: (k, j))
    s_spec = pl.BlockSpec((1, bm), lambda p, i, j, k: (0, j))
    o_spec = pl.BlockSpec((1, bm, bn), lambda p, i, j, k: (p, j, i)) \
        if out_t else pl.BlockSpec((1, bn, bm), lambda p, i, j, k: (p, i, j))
    o_shape = (P, M, N) if out_t else (P, N, M)
    acc_shape = (bm, bn) if out_t else (bn, bm)

    return pl.pallas_call(
        functools.partial(_mm_wq_kernel, a_t=a_t, out_t=out_t,
                          nk=K // bk, int4=int4),
        grid=grid,
        in_specs=[a_spec, b_spec, s_spec],
        out_specs=o_spec,
        out_shape=_sds(o_shape, out_dtype, a),
        scratch_shapes=[pltpu.VMEM(acc_shape, jnp.float32)],
        interpret=interpret,
    )(a, q, s)


def _ref_proj_wq(x, w, x_t, out_t):
    """jnp fallback with the wq kernel's numerics (f32 codes x f32
    activation, one scale multiply, one output round). Materializes the
    dequantized weight for this call only — taken for shapes the tiling
    rules reject (e.g. tiny decode batches)."""
    wf = w.dequant(jnp.float32)
    eq = ("bkt,km->b" + ("mt" if out_t else "tm")) if x_t \
        else ("btk,km->b" + ("mt" if out_t else "tm"))
    return jnp.einsum(eq, x.astype(jnp.float32), wf,
                      preferred_element_type=jnp.float32).astype(x.dtype)


def wq_matmul(x, w, *, x_t=False, out_t=False, block_t=256, block_o=256,
              block_k=512, interpret=None):
    """Forward-only ``x @ dequant(w)`` for a quantized weight operand
    (``Int8Weight`` / ``Int4Weight`` from ``ops/int8_weights.py``):
    int8/int4 weight tiles stream HBM->VMEM, dequant is fused into the
    kernel epilogue (fp32 accumulate, scale-then-cast in the flush).
    Serving-only — no vjp; the training path keeps full-precision
    weights.

    x: (B, T, K) (or 2D (T, K), lifted to B=1); returns (B, T, M)
    honouring ``x_t``/``out_t`` exactly like ``mlp_matmul``.
    """
    from ..int8_weights import Int4Weight, Int8Weight
    if not isinstance(w, (Int8Weight, Int4Weight)):
        raise TypeError(f"wq_matmul needs Int8Weight/Int4Weight, "
                        f"got {type(w).__name__}")
    int4 = isinstance(w, Int4Weight)
    squeeze = x.ndim == 2
    if squeeze:
        x = x[None]
    K = x.shape[1] if x_t else x.shape[2]
    T = x.shape[2] if x_t else x.shape[1]
    M = w.scale.shape[-1]
    if interpret is None:
        interpret = _interpret_default()
    bt = _pick_block(T, block_t, lane=True)
    bo = _pick_block(M, block_o, lane=True)
    bk = _pick_block(K, block_k, lane=True)
    if None in (bt, bo, bk) or min(T, M, K) < 8 or (int4 and bk % 2):
        out = _ref_proj_wq(x, w, x_t, out_t)
    else:
        out = _mm_wq(x, w.q, w.scale.reshape(1, M), a_t=x_t, out_t=out_t,
                     bn=bt, bm=bo, bk=bk, out_dtype=x.dtype, int4=int4,
                     interpret=bool(interpret))
    return out[0] if squeeze else out
