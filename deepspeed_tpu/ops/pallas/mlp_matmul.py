"""Layout-owning MLP projection matmul as Pallas TPU kernels.

Counterpart of the reference's epilogue-fusing GEMM wrappers
(``csrc/transformer/cublas_wrappers.cu`` + ``general_kernels.cu`` — the
GPU path earns its throughput by fusing what stock cuBLAS + eltwise
passes would materialize). The TPU-shape of the same problem is LAYOUT,
not epilogue math: at GPT-2 MLP shapes the qkv/attention tier emits
T-minor activations (T in lanes — hd=64 fills only half a 128-lane
register, so XLA propagates T-in-lanes pressure through the block
carry), and XLA's emitter for the down-projection under that layout
(``EmitOutputBatchInLanesKernelOutputFeatureInLanes``) runs the matmul
at roughly half rate — a measured ~13 ms/step at the 350M bench point —
while the backward pays transpose/cast copies re-laying the cotangents.

These kernels own both boundaries end to end:

  * the forward accepts the activation in EITHER orientation — (B, T, K)
    row-major, or (B, K, T) with T in lanes (the layout the surrounding
    einsums naturally emit; ``x_t=True``) — and emits the output in
    either orientation (``out_t``) with fp32 accumulation, so no
    relayout copy exists on either side of the projection;
  * the backward dx kernel emits the activation cotangent directly in
    the activation's own orientation (the transpose XLA would otherwise
    insert as a copy is the kernel's output indexing), and the dw kernel
    accumulates fp32 across the (batch, token) grid and casts to the
    weight dtype in its epilogue (no fp32 (K, M) HBM buffer + cast
    copy).

Off-TPU the kernels run in Pallas interpreter mode (unit tests); shapes
whose blocks cannot satisfy the TPU tiling rules fall back to a jnp
einsum with identical math (fp32 accumulation, output-dtype round).

The layout/epilogue choice itself (XLA einsums vs 'down' vs 'both',
fused-vs-XLA dw, tile sizes) is a MODEL-level decision and is
autotunable: ``models/gpt2.py`` resolves ``cfg.mlp_kernel="auto"``
against the persistent winner cache via the measured-dispatch layer
(``_common.dispatch``, registry op ``"mlp_matmul"`` in
``autotuning/kernel_registry.py``) and passes the winning mode and
block sizes into this module explicitly.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_default as _interpret_default
from ._common import sds as _sds


def _pick_block(dim, want, lane):
    """Largest divisor of ``dim`` that is <= want and tile-aligned
    (lane dims in 128 units, sublane dims in 8); ``dim`` itself (a
    single full block) is always acceptable. None = no valid block."""
    if dim <= want:
        return dim
    unit = 128 if lane else 8
    b = (want // unit) * unit
    while b >= unit:
        if dim % b == 0:
            return b
        b -= unit
    return None


# --------------------------------------------------------------- forward/dx
def _mm_kernel(a_ref, b_ref, o_ref, acc, *, a_t, b_t, out_t, nk):
    """One (n, m) output block: acc (f32) += a_blk . b_blk over the k
    grid (k innermost); write-out (cast to o dtype) at the last k step."""
    kk = pl.program_id(3)

    @pl.when(kk == 0)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    a = a_ref[0]                       # (bn, bk) | (bk, bn) when a_t
    b = b_ref[...]                     # (bk, bm) | (bm, bk) when b_t
    ca = 0 if a_t else 1               # a's contract dim
    cb = 1 if b_t else 0               # b's contract dim
    if out_t:                          # (bm, bn) = b . a
        acc[...] += lax.dot_general(
            b, a, (((cb,), (ca,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:                              # (bn, bm) = a . b
        acc[...] += lax.dot_general(
            a, b, (((ca,), (cb,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _flush():
        o_ref[0] = acc[...].astype(o_ref.dtype)


def _mm(a, b, *, a_t, b_t, out_t, bn, bm, bk, out_dtype, interpret):
    """Batched ``out[p, n, m] = sum_k a_log[p, n, k] * b_log[k, m]``.

    a: (P, N, K) (or (P, K, N) when ``a_t``); b: (K, M) (or (M, K) when
    ``b_t``); out: (P, N, M) (or (P, M, N) when ``out_t``). fp32
    accumulation, output cast in the kernel epilogue.
    """
    P = a.shape[0]
    if a_t:
        K, N = a.shape[1], a.shape[2]
    else:
        N, K = a.shape[1], a.shape[2]
    M = b.shape[0] if b_t else b.shape[1]
    grid = (P, N // bn, M // bm, K // bk)

    a_spec = pl.BlockSpec((1, bk, bn), lambda p, i, j, k: (p, k, i)) \
        if a_t else pl.BlockSpec((1, bn, bk), lambda p, i, j, k: (p, i, k))
    b_spec = pl.BlockSpec((bm, bk), lambda p, i, j, k: (j, k)) \
        if b_t else pl.BlockSpec((bk, bm), lambda p, i, j, k: (k, j))
    o_spec = pl.BlockSpec((1, bm, bn), lambda p, i, j, k: (p, j, i)) \
        if out_t else pl.BlockSpec((1, bn, bm), lambda p, i, j, k: (p, i, j))
    o_shape = (P, M, N) if out_t else (P, N, M)
    acc_shape = (bm, bn) if out_t else (bn, bm)

    return pl.pallas_call(
        functools.partial(_mm_kernel, a_t=a_t, b_t=b_t, out_t=out_t,
                          nk=K // bk),
        grid=grid,
        in_specs=[a_spec, b_spec],
        out_specs=o_spec,
        out_shape=_sds(o_shape, out_dtype, a),
        scratch_shapes=[pltpu.VMEM(acc_shape, jnp.float32)],
        interpret=interpret,
    )(a, b)


# --------------------------------------------------------------------- dw
def _dw_kernel(a_ref, g_ref, o_ref, acc, *, a_t, g_t, last_p, last_n):
    """One (bkK, bm) weight-grad block; accumulates f32 over the (p, n)
    grid steps (innermost dims — the output block index is constant
    across them) and casts to the weight dtype at the last step."""
    p = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(jnp.logical_and(p == 0, i == 0))
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    a = a_ref[0]                       # (bn, bkK) | (bkK, bn) when a_t
    g = g_ref[0]                       # (bn, bm)  | (bm, bn)  when g_t
    ca = 1 if a_t else 0               # contract the token dim
    cg = 1 if g_t else 0
    acc[...] += lax.dot_general(
        a, g, (((ca,), (cg,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(p == last_p, i == last_n))
    def _flush():
        o_ref[...] = acc[...].astype(o_ref.dtype)


def _dw(a, g, *, a_t, g_t, bkK, bm, bn, out_dtype, interpret):
    """dw[k, m] = sum_{p, n} a_log[p, n, k] * g_log[p, n, m] — the
    weight gradient with fp32 accumulation across the whole (batch,
    token) extent and the cast-to-weight-dtype epilogue fused."""
    P = a.shape[0]
    if a_t:
        K, N = a.shape[1], a.shape[2]
    else:
        N, K = a.shape[1], a.shape[2]
    M = g.shape[1] if g_t else g.shape[2]
    grid = (K // bkK, M // bm, P, N // bn)

    a_spec = pl.BlockSpec((1, bkK, bn), lambda k, j, p, i: (p, k, i)) \
        if a_t else pl.BlockSpec((1, bn, bkK), lambda k, j, p, i: (p, i, k))
    g_spec = pl.BlockSpec((1, bm, bn), lambda k, j, p, i: (p, j, i)) \
        if g_t else pl.BlockSpec((1, bn, bm), lambda k, j, p, i: (p, i, j))

    return pl.pallas_call(
        functools.partial(_dw_kernel, a_t=a_t, g_t=g_t, last_p=P - 1,
                          last_n=N // bn - 1),
        grid=grid,
        in_specs=[a_spec, g_spec],
        out_specs=pl.BlockSpec((bkK, bm), lambda k, j, p, i: (k, j)),
        out_shape=_sds((K, M), out_dtype, a),
        scratch_shapes=[pltpu.VMEM((bkK, bm), jnp.float32)],
        interpret=interpret,
    )(a, g)


# -------------------------------------------------------------- jnp fallback
def _ref_proj(x, w, x_t, out_t):
    """jnp reference with the kernels' exact numerics: fp32 accumulation,
    one round to the output dtype."""
    eq = ("bkt,km->b" + ("mt" if out_t else "tm")) if x_t \
        else ("btk,km->b" + ("mt" if out_t else "tm"))
    return jnp.einsum(eq, x, w,
                      preferred_element_type=jnp.float32).astype(x.dtype)


# ------------------------------------------------------------------ public
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8))
def _proj(x, w, x_t, out_t, bt, bo, bk, fuse_dw, interpret):
    return _mm(x, w, a_t=x_t, b_t=False, out_t=out_t, bn=bt, bm=bo,
               bk=bk, out_dtype=x.dtype, interpret=interpret)


def _proj_fwd(x, w, x_t, out_t, bt, bo, bk, fuse_dw, interpret):
    return _proj(x, w, x_t, out_t, bt, bo, bk, fuse_dw, interpret), (x, w)


def _proj_bwd(x_t, out_t, bt, bo, bk, fuse_dw, interpret, res, dy):
    x, w = res
    K, M = w.shape
    # dx[p, n, k] = sum_m dy[p, n, m] w[k, m]: contract M; emitted
    # straight in x's orientation — the backward transpose XLA inserts
    # on the einsum vjp is this kernel's output indexing instead
    dx = _mm(dy, w, a_t=out_t, b_t=True, out_t=x_t, bn=bt, bm=bk,
             bk=bo, out_dtype=x.dtype, interpret=interpret)
    if fuse_dw:
        dw = _dw(x, dy, a_t=x_t, g_t=out_t, bkK=bk, bm=bo, bn=bt,
                 out_dtype=w.dtype, interpret=interpret)
    else:
        # let XLA own the weight grad: inside the layer scan it fuses
        # this contraction into the grad-stacking DUS at full MXU rate
        # (the round-3 trace finding); the kernel variant exists for
        # points where that fusion does not form
        xe = "bkt" if x_t else "btk"
        ge = "bmt" if out_t else "btm"
        dw = jnp.einsum(f"{xe},{ge}->km", x, dy,
                        preferred_element_type=jnp.float32).astype(w.dtype)
    return dx, dw


_proj.defvjp(_proj_fwd, _proj_bwd)


def mlp_matmul(x, w, *, x_t=False, out_t=False, block_t=256,
               block_o=256, block_k=512, fuse_dw=True, interpret=None):
    """Batched projection ``y[b, t, m] = sum_k x[b, t, k] w[k, m]`` with
    kernel-owned operand/output layouts.

    x: (B, T, K), or (B, K, T) with the token dim in lanes when
    ``x_t=True`` (the layout the qkv/MLP einsums naturally emit); w:
    (K, M); returns (B, T, M), or (B, M, T) when ``out_t=True``. fp32
    accumulation, output rounded once to x.dtype (exactly what the MXU
    does for the jnp matmul). Differentiable: dx comes back in x's own
    orientation and dw accumulates fp32 with the weight-dtype cast
    fused (``fuse_dw=False`` leaves dw to XLA — inside a layer scan it
    fuses into the grad-stacking DUS at full rate).

    Shapes whose dims cannot form tile-aligned blocks fall back to a
    jnp einsum with identical math.
    """
    if x.ndim != 3 or w.ndim != 2:
        raise ValueError(
            f"mlp_matmul expects x (B, ., .) and w (K, M); got "
            f"{x.shape} / {w.shape}")
    K = x.shape[1] if x_t else x.shape[2]
    T = x.shape[2] if x_t else x.shape[1]
    if w.shape[0] != K:
        raise ValueError(
            f"contract dim mismatch: x carries K={K}, w is {w.shape}")
    M = w.shape[1]
    if interpret is None:
        interpret = _interpret_default()
    # every dim appears in lanes in at least one of the fwd/dx/dw
    # blocks, so all three use lane-unit (128) granularity unless they
    # are a single full block
    bt = _pick_block(T, block_t, lane=True)
    bo = _pick_block(M, block_o, lane=True)
    bk = _pick_block(K, block_k, lane=True)
    if None in (bt, bo, bk) or min(T, M, K) < 8:
        return _ref_proj(x, w, x_t, out_t)
    return _proj(x, w, bool(x_t), bool(out_t), bt, bo, bk,
                 bool(fuse_dw), bool(interpret))
