"""Fused unembed + online-softmax-stats kernel for the training head.

Counterpart of the reference's fused softmax/logits kernels
(csrc/transformer/general_kernels.cu + softmax.cu — the GPU head fuses
what cuBLAS + eltwise passes would materialize). TPU motivation is HBM
traffic: XLA's chunked CE materializes the (rows, V) logits in fp32 and
re-reads them for logsumexp — ~15 GB per step at the 350M bench point.
This kernel computes the unembed matmul block-by-block over the vocab,
carrying the online max/sumexp (the flash-attention recurrence, over
vocab instead of keys) and the gold-logit readout in VMEM, and writes
the logits ONCE, in bf16 — the only HBM footprint. logz and the gold
logit come out exact (fp32 block scores before the bf16 round).

The grad-in-forward CE (models/common.fused_linear_xent_kernel) then
forms d_logits from the bf16 logits — identical numerics to what the
MXU would see anyway (bf16-truncated operands) — and feeds the two
backward matmuls.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import AUTO as _AUTO
from ._common import ce_bucket as _ce_bucket
from ._common import dispatch as _dispatch
from ._common import dtype_name as _dtype_name
from ._common import interpret_default as _interpret_default
from ._common import round_up as _round_up
from ._common import sds as _sds

NEG_INF = -1e30
STAT_LANES = 8

# r05-proven hand-set vocab-walk tiles; overridden by the autotune
# winner cache when callers leave block_m/block_n at "auto"
TUNE_DEFAULTS = {"block_m": 512, "block_n": 512}


def _ce_kernel(x_ref, w_ref, t_ref, logits_ref, logz_ref, gold_ref,
               m_scr, l_scr, g_scr, *, bn, V):
    j = pl.program_id(1)
    nv = pl.num_programs(1)
    x = x_ref[...]                                   # (bm, D) bf16
    w = w_ref[...]                                   # (bn, D) bf16
    s = lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)  # (bm, bn)
    bm = s.shape[0]
    col = j * bn + lax.broadcasted_iota(jnp.int32, (bm, bn), 1)
    s = jnp.where(col < V, s, NEG_INF)
    logits_ref[...] = s.astype(logits_ref.dtype)

    t = t_ref[...]                                   # (bm, 1) int32
    # col < V guard: targets landing in the padded tail [V, Vp) must
    # contribute 0, not the pad columns' NEG_INF
    gold_blk = jnp.sum(jnp.where((col == t) & (col < V), s, 0.0), axis=1)
    blk_max = jnp.max(s, axis=1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        g_scr[...] = jnp.zeros_like(g_scr)

    m_prev = m_scr[:, 0]
    l_prev = l_scr[:, 0]
    m_new = jnp.maximum(m_prev, blk_max)
    l_new = (l_prev * jnp.exp(m_prev - m_new)
             + jnp.sum(jnp.exp(s - m_new[:, None]), axis=1))
    m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)
    g_scr[...] = g_scr[...] + jnp.broadcast_to(gold_blk[:, None],
                                               g_scr.shape)

    @pl.when(j == nv - 1)
    def _final():
        logz = m_new + jnp.log(l_new)
        logz_ref[...] = jnp.broadcast_to(logz[:, None], logz_ref.shape)
        gold_ref[...] = g_scr[...]


def unembed_logits_stats(h, w, targets, *, block_m=_AUTO, block_n=_AUTO,
                         interpret=None):
    """h: (N, D) bf16 rows; w: (V, D); targets: (N,) int32.

    Returns (logits (N, V) in h.dtype, logz (N,) f32, gold (N,) f32) —
    logz and gold computed from the pre-round fp32 block scores.
    Rows of ``targets`` outside [0, V) contribute gold = 0.
    ``block_m``/``block_n`` left at "auto" (the default) resolve via the
    autotune winner cache at trace time, falling back to 512/512.
    """
    N, D = h.shape
    V = w.shape[0]
    if _AUTO in (block_m, block_n):
        win = _dispatch("fused_ce", _ce_bucket(N, D, V),
                        _dtype_name(h.dtype), TUNE_DEFAULTS)
        if block_m == _AUTO:
            block_m = int(win["block_m"])
        if block_n == _AUTO:
            block_n = int(win["block_n"])
    if interpret is None:
        interpret = _interpret_default()
    bm = min(block_m, N)
    while N % bm:
        bm //= 2
    Vp = _round_up(V, block_n)
    if Vp != V:
        w = jnp.pad(w, ((0, Vp - V), (0, 0)))
    grid = (N // bm, Vp // block_n)
    t2 = targets.astype(jnp.int32)[:, None]
    logits, logz, gold = pl.pallas_call(
        functools.partial(_ce_kernel, bn=block_n, V=V),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, D), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, D), lambda i, j: (j, 0)),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((bm, STAT_LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, STAT_LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            _sds((N, Vp), h.dtype, h),
            _sds((N, STAT_LANES), jnp.float32, h),
            _sds((N, STAT_LANES), jnp.float32, h),
        ],
        scratch_shapes=[
            pltpu.VMEM((bm, STAT_LANES), jnp.float32),
            pltpu.VMEM((bm, STAT_LANES), jnp.float32),
            pltpu.VMEM((bm, STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(h, w, t2)
    return logits[:, :V], logz[:, 0], gold[:, 0]
