"""Grouped (ragged) matmul over per-expert weight groups as Pallas TPU
kernels — the dropless-MoE expert FFN.

Counterpart of the reference's CUTLASS grouped ``moe_gemm``
(``inference/v2/kernels/cutlass_ops``) and the megablox ``gmm`` pattern:
rows are sorted by routed expert and ``group_sizes[e]`` rows multiply
expert ``e``'s weight block. The XLA path for this is ``lax.ragged_dot``
— one op per projection, each re-streaming the full (E, K, N) weight
tensor and re-deciding tiling generically. These kernels own the whole
grouped product in ONE launch:

  * the row dimension is cut into m-tiles and each tile is assigned to
    the group(s) whose rows it holds via scalar-prefetched tile maps
    (``group_ids``/``m_tile_ids`` — a tile straddling a group boundary
    is visited once per group, so compute stays proportional to rows,
    never to experts x rows); each expert's weight tile streams through
    VMEM exactly once per (m-tile, n-tile) visit;
  * a fused SwiGLU variant (``grouped_swiglu``) runs the whole
    w1/w3 -> silu*mul -> w2 expert chain with the gate/up products
    sharing one streamed activation tile and the silu*mul epilogue
    applied in-register (the g/u intermediates never hit HBM
    separately);
  * the backward accumulates dw PER GROUP in fp32 (``_tgmm``: out block
    keyed by group id, row-masked accumulation over the group's
    m-tiles, weight-dtype cast fused in the epilogue) and emits dx
    through the same grouped kernel with the weight operand transposed
    in its index map (no materialized (E, N, K) transpose).

Rows beyond ``sum(group_sizes)`` produce ZEROS (the ``lax.ragged_dot``
contract — MoE transport padding relies on it). Off-TPU the kernels run
in Pallas interpreter mode; shapes whose dims cannot form tile-aligned
blocks fall back to ``lax.ragged_dot`` with identical semantics.

The kernel-vs-ragged choice and the tile sizes are autotunable: the MoE
layers resolve ``"auto"`` against the persistent winner cache (registry
op ``"moe_grouped_mm"``, bucketed by tokens-per-shard | experts | model
dims) with ``TUNE_DEFAULTS`` — backend ``"ragged"`` — on a cold cache,
so a miss is byte-identical to the pre-kernel program.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_default as _interpret_default
from ._common import round_up as _round_up
from ._common import sds as _sds

# cold-cache dispatch default: the XLA ragged_dot path (current
# behavior); the kernel backend and its tile sweep are the measured
# candidates (autotuning/kernel_registry.py 'moe_grouped_mm')
TUNE_DEFAULTS = {"backend": "ragged",
                 "block_m": 128, "block_n": 128, "block_k": 128}


def _pick_block(dim, want):
    """Largest divisor of ``dim`` <= want in 128-lane units (K and N
    each sit in a lane position in at least one of the fwd/dx/dw
    kernels); ``dim`` itself always qualifies when it fits. None = no
    valid block (caller falls back to ragged_dot)."""
    if dim <= want:
        return dim
    b = (want // 128) * 128
    while b >= 128:
        if dim % b == 0:
            return b
        b -= 128
    return None


# ------------------------------------------------------------- metadata
def _group_metadata(group_sizes, m_pad, tm, E):
    """Logical-tile maps for a grouped matmul over rows padded to
    ``m_pad`` (a ``tm`` multiple).

    Returns (group_ids, m_tile_ids, starts, ends, num_tiles): logical
    tile i computes group ``group_ids[i]``'s rows inside physical m-tile
    ``m_tile_ids[i]``. Each group covers the tiles its row range
    [starts, ends) touches (a boundary tile shared by two groups is
    visited by both); empty groups are clamped to one (masked-empty)
    visit so their dw blocks still get written; the LAST group's range
    extends to ``m_pad`` so every physical tile is visited and padding
    rows come out zero. Static size ``tiles_m + E``; entries past
    ``num_tiles`` are masked no-ops in the kernels."""
    tiles_m = m_pad // tm
    G = tiles_m + E
    ends = jnp.cumsum(group_sizes).astype(jnp.int32)
    starts = ends - group_sizes.astype(jnp.int32)
    r_starts = jnp.minimum(starts // tm, tiles_m - 1)
    r_ends = -(-ends // tm)                       # ceil
    r_ends = r_ends.at[E - 1].set(tiles_m)        # tail coverage
    tiles_per = jnp.maximum(r_ends - r_starts, 1)
    gids = jnp.repeat(jnp.arange(E, dtype=jnp.int32), tiles_per,
                      total_repeat_length=G)
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(tiles_per)[:-1].astype(jnp.int32)])
    within = jnp.arange(G, dtype=jnp.int32) - offs[gids]
    mtids = jnp.minimum(r_starts[gids] + within, tiles_m - 1)
    num_tiles = jnp.sum(tiles_per).astype(jnp.int32).reshape(1)
    return gids, mtids, starts, ends, num_tiles


def _row_mask(mt, g, st_ref, en_ref, valid, tm):
    """(tm, 1) bool: rows of physical tile ``mt`` inside group ``g``'s
    row range — and nothing at all on a padded logical tile."""
    rows = mt * tm + lax.broadcasted_iota(jnp.int32, (tm, 1), 0)
    return (rows >= st_ref[g]) & (rows < en_ref[g]) & valid


# ------------------------------------------------------------- gmm fwd
def _gmm_kernel(gid_ref, mtid_ref, st_ref, en_ref, nt_ref,
                x_ref, w_ref, o_ref, acc, *, tm, nk, trans_w):
    i = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    x = x_ref[...]                     # (tm, tk)
    w = w_ref[0]                       # (tk, tn) | (tn, tk) when trans_w
    cw = 1 if trans_w else 0
    acc[...] += lax.dot_general(
        x, w, (((1,), (cw,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _flush():
        g = gid_ref[i]
        mt = mtid_ref[i]
        mask = _row_mask(mt, g, st_ref, en_ref, i < nt_ref[0], tm)
        prev_mt = jnp.where(i == 0, -1, mtid_ref[jnp.maximum(i - 1, 0)])
        prev = jnp.where(mt != prev_mt,
                         jnp.zeros_like(o_ref[...]), o_ref[...])
        o_ref[...] = jnp.where(mask, acc[...].astype(o_ref.dtype), prev)


def _gmm(x, w, group_sizes, *, tm, tn, tk, trans_w, interpret):
    """out[s, n] = sum_k x[s, k] w[g(s), k, n] (w (E, N, K) contracted on
    its last dim when ``trans_w``). x rows pre-padded to a tm multiple;
    rows outside every group come out zero."""
    M, K = x.shape
    E = w.shape[0]
    N = w.shape[1] if trans_w else w.shape[2]
    gids, mtids, starts, ends, num = _group_metadata(group_sizes, M, tm, E)
    G = int(gids.shape[0])
    grid = (N // tn, G, K // tk)

    w_spec = pl.BlockSpec((1, tn, tk),
                          lambda j, i, kk, gid, mtid, st, en, nt:
                          (gid[i], j, kk)) if trans_w else \
        pl.BlockSpec((1, tk, tn),
                     lambda j, i, kk, gid, mtid, st, en, nt:
                     (gid[i], kk, j))
    out = pl.pallas_call(
        functools.partial(_gmm_kernel, tm=tm, nk=K // tk, trans_w=trans_w),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, tk),
                             lambda j, i, kk, gid, mtid, st, en, nt:
                             (mtid[i], kk)),
                w_spec,
            ],
            out_specs=pl.BlockSpec(
                (tm, tn),
                lambda j, i, kk, gid, mtid, st, en, nt: (mtid[i], j)),
            scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        ),
        out_shape=_sds((M, N), x.dtype, x),
        interpret=interpret,
    )(gids, mtids, starts, ends, num, x, w)
    return out


# ------------------------------------------------- fused SwiGLU up chain
def _swiglu_up_kernel(gid_ref, mtid_ref, st_ref, en_ref, nt_ref,
                      x_ref, w1_ref, w3_ref, o_ref, gacc, uacc, *,
                      tm, nk):
    i = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _zero():
        gacc[...] = jnp.zeros_like(gacc)
        uacc[...] = jnp.zeros_like(uacc)

    x = x_ref[...]
    gacc[...] += lax.dot_general(x, w1_ref[0], (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    uacc[...] += lax.dot_general(x, w3_ref[0], (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _flush():
        g = gid_ref[i]
        mt = mtid_ref[i]
        mask = _row_mask(mt, g, st_ref, en_ref, i < nt_ref[0], tm)
        # silu*mul epilogue in fp32, one round to the output dtype
        gg = gacc[...]
        h = (gg * jax.nn.sigmoid(gg)) * uacc[...]
        prev_mt = jnp.where(i == 0, -1, mtid_ref[jnp.maximum(i - 1, 0)])
        prev = jnp.where(mt != prev_mt,
                         jnp.zeros_like(o_ref[...]), o_ref[...])
        o_ref[...] = jnp.where(mask, h.astype(o_ref.dtype), prev)


def _swiglu_up(x, w1, w3, group_sizes, *, tm, tn, tk, interpret):
    """h[s, f] = silu(x w1[g(s)])[s, f] * (x w3[g(s)])[s, f] in one
    launch — the gate and up products share each streamed x tile."""
    M, K = x.shape
    E, _, F = w1.shape
    gids, mtids, starts, ends, num = _group_metadata(group_sizes, M, tm, E)
    G = int(gids.shape[0])
    w_spec = pl.BlockSpec((1, tk, tn),
                          lambda j, i, kk, gid, mtid, st, en, nt:
                          (gid[i], kk, j))
    return pl.pallas_call(
        functools.partial(_swiglu_up_kernel, tm=tm, nk=K // tk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(F // tn, G, K // tk),
            in_specs=[
                pl.BlockSpec((tm, tk),
                             lambda j, i, kk, gid, mtid, st, en, nt:
                             (mtid[i], kk)),
                w_spec, w_spec,
            ],
            out_specs=pl.BlockSpec(
                (tm, tn),
                lambda j, i, kk, gid, mtid, st, en, nt: (mtid[i], j)),
            scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32),
                            pltpu.VMEM((tm, tn), jnp.float32)],
        ),
        out_shape=_sds((M, F), x.dtype, x),
        interpret=interpret,
    )(gids, mtids, starts, ends, num, x, w1, w3)


# ------------------------------------------------------------- dw (tgmm)
def _tgmm_kernel(gid_ref, mtid_ref, st_ref, en_ref, nt_ref,
                 x_ref, g_ref, o_ref, acc, *, tm, last_i):
    i = pl.program_id(2)

    gid = gid_ref[i]
    prev_g = jnp.where(i == 0, -1, gid_ref[jnp.maximum(i - 1, 0)])
    next_g = jnp.where(i == last_i, -1,
                       gid_ref[jnp.minimum(i + 1, last_i)])

    @pl.when(gid != prev_g)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    mask = _row_mask(mtid_ref[i], gid, st_ref, en_ref, i < nt_ref[0], tm)
    x = jnp.where(mask, x_ref[...], 0)            # rows outside the group
    acc[...] += lax.dot_general(                  # contribute nothing
        x, g_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(gid != next_g)
    def _flush():
        o_ref[0] = acc[...].astype(o_ref.dtype)


def _tgmm(x, dy, group_sizes, E, *, tm, tn, tk, out_dtype, interpret):
    """dw[e, k, n] = sum_{s in group e} x[s, k] dy[s, n] — the per-group
    weight-grad accumulation: the out block is keyed by group id, fp32
    accumulation runs over the group's row tiles (boundary tiles row-
    masked), and the weight-dtype cast lands in the flush epilogue.
    Empty groups write zeros (their single clamped visit is all-masked).
    """
    M, K = x.shape
    N = dy.shape[1]
    gids, mtids, starts, ends, num = _group_metadata(group_sizes, M, tm, E)
    G = int(gids.shape[0])
    return pl.pallas_call(
        functools.partial(_tgmm_kernel, tm=tm, last_i=G - 1),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(K // tk, N // tn, G),
            in_specs=[
                pl.BlockSpec((tm, tk),
                             lambda ki, ni, i, gid, mtid, st, en, nt:
                             (mtid[i], ki)),
                pl.BlockSpec((tm, tn),
                             lambda ki, ni, i, gid, mtid, st, en, nt:
                             (mtid[i], ni)),
            ],
            out_specs=pl.BlockSpec(
                (1, tk, tn),
                lambda ki, ni, i, gid, mtid, st, en, nt: (gid[i], ki, ni)),
            scratch_shapes=[pltpu.VMEM((tk, tn), jnp.float32)],
        ),
        out_shape=_sds((E, K, N), out_dtype, x),
        interpret=interpret,
    )(gids, mtids, starts, ends, num, x, dy)


# ---------------------------------------------------------------- public
def _blocks_fit(M, K, N, bm, bn, bk):
    """Resolve (tm, tn, tk) or None — K/N must form 128-aligned divisor
    blocks (each appears in a lane position in at least one of the
    fwd/dx/dw kernels); the row dim is padded to tm outside."""
    tn = _pick_block(N, bn)
    tk = _pick_block(K, bk)
    if tn is None or tk is None or min(M, K, N) < 8:
        return None
    tm = min(bm, _round_up(M, 8))
    return tm, tn, tk


def _pad_rows(x, tm):
    M = x.shape[0]
    pad = _round_up(M, tm) - M
    return (jnp.pad(x, ((0, pad), (0, 0))) if pad else x), M


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _gmm_diff(x, w, group_sizes, tm, tn, tk, interpret):
    xp, M = _pad_rows(x, tm)
    return _gmm(xp, w, group_sizes, tm=tm, tn=tn, tk=tk, trans_w=False,
                interpret=interpret)[:M]


def _gmm_diff_fwd(x, w, group_sizes, tm, tn, tk, interpret):
    return (_gmm_diff(x, w, group_sizes, tm, tn, tk, interpret),
            (x, w, group_sizes))


def _gmm_diff_bwd(tm, tn, tk, interpret, res, dy):
    x, w, group_sizes = res
    E = w.shape[0]
    xp, M = _pad_rows(x, tm)
    dyp, _ = _pad_rows(dy, tm)
    # dx contracts the OUT dim (tn) and emits the contract dim (tk):
    # same grouped kernel, weight operand transposed in its index map
    dx = _gmm(dyp, w, group_sizes, tm=tm, tn=tk, tk=tn, trans_w=True,
              interpret=interpret)[:M]
    dw = _tgmm(xp, dyp, group_sizes, E, tm=tm, tn=tn, tk=tk,
               out_dtype=w.dtype, interpret=interpret)
    return dx, dw, None


_gmm_diff.defvjp(_gmm_diff_fwd, _gmm_diff_bwd)


def grouped_matmul(x, w, group_sizes, *, block_m=128, block_n=128,
                   block_k=128, interpret=None):
    """``lax.ragged_dot`` drop-in: x (S, K) rows sorted by group, w
    (E, K, N), group_sizes (E,) int32 -> (S, N); rows beyond
    ``sum(group_sizes)`` are zero. Differentiable (dx through the
    transposed-weight kernel, dw through the per-group fp32 ``_tgmm``).
    Shapes whose dims cannot form tile-aligned blocks fall back to
    ``lax.ragged_dot`` with identical math.
    """
    if x.ndim != 2 or w.ndim != 3 or x.shape[1] != w.shape[1]:
        raise ValueError(
            f"grouped_matmul expects x (S, K) and w (E, K, N); got "
            f"{x.shape} / {w.shape}")
    fit = _blocks_fit(x.shape[0], x.shape[1], w.shape[2],
                      block_m, block_n, block_k)
    if fit is None:
        return lax.ragged_dot(x, w, group_sizes)
    tm, tn, tk = fit
    if interpret is None:
        interpret = _interpret_default()
    return _gmm_diff(x, w, group_sizes.astype(jnp.int32), tm, tn, tk,
                     bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _swiglu_diff(x, w1, w3, w2, group_sizes, tm, tn, tk, interpret):
    xp, M = _pad_rows(x, tm)
    h = _swiglu_up(xp, w1, w3, group_sizes, tm=tm, tn=tn, tk=tk,
                   interpret=interpret)
    return _gmm(h, w2, group_sizes, tm=tm, tn=tk, tk=tn, trans_w=False,
                interpret=interpret)[:M]


def _swiglu_diff_fwd(x, w1, w3, w2, group_sizes, tm, tn, tk, interpret):
    return (_swiglu_diff(x, w1, w3, w2, group_sizes, tm, tn, tk,
                         interpret),
            (x, w1, w3, w2, group_sizes))


def _swiglu_diff_bwd(tm, tn, tk, interpret, res, dy):
    """Backward with the flash-style remat trade: g and u are recomputed
    from x (two grouped products) instead of living in HBM between
    forward and backward; every matmul is the grouped kernel and each
    dw accumulates per group in fp32."""
    x, w1, w3, w2, group_sizes = res
    E = w1.shape[0]
    xp, M = _pad_rows(x, tm)
    dyp, _ = _pad_rows(dy, tm)
    kw = dict(tm=tm, interpret=interpret)
    g = _gmm(xp, w1, group_sizes, tn=tn, tk=tk, trans_w=False, **kw)
    u = _gmm(xp, w3, group_sizes, tn=tn, tk=tk, trans_w=False, **kw)
    gf = g.astype(jnp.float32)
    sg = jax.nn.sigmoid(gf)
    sil = (gf * sg).astype(x.dtype)
    dh = _gmm(dyp, w2, group_sizes, tn=tn, tk=tk, trans_w=True, **kw)
    dhf = dh.astype(jnp.float32)
    dg = (dhf * u.astype(jnp.float32)
          * (sg * (1 + gf * (1 - sg)))).astype(x.dtype)
    du = (dhf * sil.astype(jnp.float32)).astype(x.dtype)
    dx = (_gmm(dg, w1, group_sizes, tn=tk, tk=tn, trans_w=True, **kw)
          + _gmm(du, w3, group_sizes, tn=tk, tk=tn, trans_w=True,
                 **kw))[:M]
    dw1 = _tgmm(xp, dg, group_sizes, E, tn=tn, tk=tk,
                out_dtype=w1.dtype, **kw)
    dw3 = _tgmm(xp, du, group_sizes, E, tn=tn, tk=tk,
                out_dtype=w3.dtype, **kw)
    h = (sil.astype(jnp.float32) * u.astype(jnp.float32)).astype(x.dtype)
    dw2 = _tgmm(h, dyp, group_sizes, E, tn=tk, tk=tn,
                out_dtype=w2.dtype, **kw)
    return dx, dw1, dw3, dw2, None


_swiglu_diff.defvjp(_swiglu_diff_fwd, _swiglu_diff_bwd)


def grouped_swiglu(x, w1, w3, w2, group_sizes, *, block_m=128,
                   block_n=128, block_k=128, interpret=None):
    """The whole SwiGLU expert chain as grouped kernels:
    ``gmm(silu(gmm(x, w1)) * gmm(x, w3), w2)`` with the gate/up products
    fused into one launch (shared x tiles, in-register silu*mul
    epilogue). x (S, K); w1/w3 (E, K, F); w2 (E, F, K'); -> (S, K').
    Same fallback/zero-tail/backward contract as ``grouped_matmul``.
    """
    E, K, F = w1.shape
    if x.ndim != 2 or x.shape[1] != K or w3.shape != w1.shape or \
            w2.shape[:2] != (E, F):
        raise ValueError(
            f"grouped_swiglu shape mismatch: x {x.shape}, w1 {w1.shape}, "
            f"w3 {w3.shape}, w2 {w2.shape}")
    fit = _blocks_fit(x.shape[0], K, F, block_m, block_n, block_k)
    # the down projection re-uses the same tiles with roles swapped, so
    # its output dim (w2's last) must form blocks too
    fit_dn = fit and _pick_block(w2.shape[2], block_k)
    if fit is None or fit_dn is None or fit_dn != fit[2]:
        g = lax.ragged_dot(x, w1, group_sizes)
        u = lax.ragged_dot(x, w3, group_sizes)
        return lax.ragged_dot(jax.nn.silu(g) * u, w2, group_sizes)
    tm, tn, tk = fit
    if interpret is None:
        interpret = _interpret_default()
    return _swiglu_diff(x, w1, w3, w2, group_sizes.astype(jnp.int32),
                        tm, tn, tk, bool(interpret))


# ----------------------------------------- weight-only quantized forward
def _unpack4(p):
    """(tk//2, tn) packed int4 tile -> (tk, tn) int8 codes (layout in
    ops/pallas/quantization.py: low nibble = even row, high = odd)."""
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)
    hi = jnp.right_shift(p, 4)
    return jnp.stack([lo, hi], axis=1).reshape(2 * p.shape[0], p.shape[1])


def _gmm_wq_kernel(gid_ref, mtid_ref, st_ref, en_ref, nt_ref,
                   x_ref, w_ref, s_ref, o_ref, acc, *, tm, nk, int4):
    """_gmm_kernel with a quantized weight operand: int8/int4 expert
    tiles widen in VMEM and the per-(expert, output-channel) scale
    multiplies the f32 accumulator once in the flush — each logical
    tile writes only ITS group's rows, so a row tile straddling two
    experts still gets each expert's own scale."""
    i = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[0]
    if int4:
        w = _unpack4(w)
    acc[...] += lax.dot_general(
        x, w.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _flush():
        g = gid_ref[i]
        mt = mtid_ref[i]
        mask = _row_mask(mt, g, st_ref, en_ref, i < nt_ref[0], tm)
        s = s_ref[0, 0]                # (tn,) this expert's scales
        prev_mt = jnp.where(i == 0, -1, mtid_ref[jnp.maximum(i - 1, 0)])
        prev = jnp.where(mt != prev_mt,
                         jnp.zeros_like(o_ref[...]), o_ref[...])
        o_ref[...] = jnp.where(mask,
                               (acc[...] * s[None, :]).astype(o_ref.dtype),
                               prev)


def _gmm_wq(x, q, s, group_sizes, *, tm, tn, tk, int4, interpret):
    """Grouped matmul with quantized weights: q (E, K, N) int8 (or
    (E, K//2, N) packed int4), s (E, 1, N) per-channel scales."""
    M, K = x.shape
    E, _, N = s.shape[0], q.shape[1], s.shape[2]
    gids, mtids, starts, ends, num = _group_metadata(group_sizes, M, tm, E)
    G = int(gids.shape[0])
    w_blk = (1, tk // 2, tn) if int4 else (1, tk, tn)
    w_spec = pl.BlockSpec(w_blk,
                          lambda j, i, kk, gid, mtid, st, en, nt:
                          (gid[i], kk, j))
    s_spec = pl.BlockSpec((1, 1, tn),
                          lambda j, i, kk, gid, mtid, st, en, nt:
                          (gid[i], 0, j))
    return pl.pallas_call(
        functools.partial(_gmm_wq_kernel, tm=tm, nk=K // tk, int4=int4),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(N // tn, G, K // tk),
            in_specs=[
                pl.BlockSpec((tm, tk),
                             lambda j, i, kk, gid, mtid, st, en, nt:
                             (mtid[i], kk)),
                w_spec, s_spec,
            ],
            out_specs=pl.BlockSpec(
                (tm, tn),
                lambda j, i, kk, gid, mtid, st, en, nt: (mtid[i], j)),
            scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        ),
        out_shape=_sds((M, N), x.dtype, x),
        interpret=interpret,
    )(gids, mtids, starts, ends, num, x, q, s)


def _swiglu_up_wq_kernel(gid_ref, mtid_ref, st_ref, en_ref, nt_ref,
                         x_ref, w1_ref, s1_ref, w3_ref, s3_ref, o_ref,
                         gacc, uacc, *, tm, nk, int4):
    i = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _zero():
        gacc[...] = jnp.zeros_like(gacc)
        uacc[...] = jnp.zeros_like(uacc)

    x = x_ref[...].astype(jnp.float32)
    w1 = w1_ref[0]
    w3 = w3_ref[0]
    if int4:
        w1 = _unpack4(w1)
        w3 = _unpack4(w3)
    gacc[...] += lax.dot_general(x, w1.astype(jnp.float32),
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    uacc[...] += lax.dot_general(x, w3.astype(jnp.float32),
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _flush():
        g = gid_ref[i]
        mt = mtid_ref[i]
        mask = _row_mask(mt, g, st_ref, en_ref, i < nt_ref[0], tm)
        # dequant scales first (per accumulator), THEN silu*mul — the
        # epilogue nonlinearity sees the same values the fp math would
        gg = gacc[...] * s1_ref[0, 0][None, :]
        uu = uacc[...] * s3_ref[0, 0][None, :]
        h = (gg * jax.nn.sigmoid(gg)) * uu
        prev_mt = jnp.where(i == 0, -1, mtid_ref[jnp.maximum(i - 1, 0)])
        prev = jnp.where(mt != prev_mt,
                         jnp.zeros_like(o_ref[...]), o_ref[...])
        o_ref[...] = jnp.where(mask, h.astype(o_ref.dtype), prev)


def _swiglu_up_wq(x, q1, s1, q3, s3, group_sizes, *, tm, tn, tk, int4,
                  interpret):
    M, K = x.shape
    E, F = s1.shape[0], s1.shape[2]
    gids, mtids, starts, ends, num = _group_metadata(group_sizes, M, tm, E)
    G = int(gids.shape[0])
    w_blk = (1, tk // 2, tn) if int4 else (1, tk, tn)
    w_spec = pl.BlockSpec(w_blk,
                          lambda j, i, kk, gid, mtid, st, en, nt:
                          (gid[i], kk, j))
    s_spec = pl.BlockSpec((1, 1, tn),
                          lambda j, i, kk, gid, mtid, st, en, nt:
                          (gid[i], 0, j))
    return pl.pallas_call(
        functools.partial(_swiglu_up_wq_kernel, tm=tm, nk=K // tk,
                          int4=int4),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(F // tn, G, K // tk),
            in_specs=[
                pl.BlockSpec((tm, tk),
                             lambda j, i, kk, gid, mtid, st, en, nt:
                             (mtid[i], kk)),
                w_spec, s_spec, w_spec, s_spec,
            ],
            out_specs=pl.BlockSpec(
                (tm, tn),
                lambda j, i, kk, gid, mtid, st, en, nt: (mtid[i], j)),
            scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32),
                            pltpu.VMEM((tm, tn), jnp.float32)],
        ),
        out_shape=_sds((M, F), x.dtype, x),
        interpret=interpret,
    )(gids, mtids, starts, ends, num, x, q1, s1, q3, s3)


def grouped_swiglu_wq(x, w1, w3, w2, group_sizes, *, block_m=128,
                      block_n=128, block_k=128, interpret=None):
    """``grouped_swiglu`` with quantized expert weights (``Int8Weight``
    / ``Int4Weight``, all three the same width): int8/int4 tiles stream
    HBM->VMEM, per-(expert, channel) scales fold into the flush
    epilogues, fp32 accumulation throughout. Serving-only (no vjp).
    Shapes the tiling rules reject fall back to dequant + ragged_dot
    (materializing the dequantized experts for that call only)."""
    from ..int8_weights import Int4Weight, Int8Weight
    ws = (w1, w3, w2)
    if not all(isinstance(w, (Int8Weight, Int4Weight)) for w in ws):
        raise TypeError("grouped_swiglu_wq needs Int8Weight/Int4Weight "
                        "expert weights")
    int4s = [isinstance(w, Int4Weight) for w in ws]
    int4 = int4s[0]
    K = x.shape[1]
    F = w1.scale.shape[-1]
    Kd = w2.scale.shape[-1]
    fit = _blocks_fit(x.shape[0], K, F, block_m, block_n, block_k)
    fit_dn = fit and _pick_block(Kd, block_k)
    ok = (fit is not None and fit_dn is not None and fit_dn == fit[2]
          and all(i4 == int4 for i4 in int4s)
          and (not int4 or (fit[2] % 2 == 0 and fit[1] % 2 == 0)))
    if not ok:
        g = lax.ragged_dot(x, w1.dequant(x.dtype), group_sizes)
        u = lax.ragged_dot(x, w3.dequant(x.dtype), group_sizes)
        return lax.ragged_dot(jax.nn.silu(g) * u, w2.dequant(x.dtype),
                              group_sizes)
    tm, tn, tk = fit
    if interpret is None:
        interpret = _interpret_default()
    gs = group_sizes.astype(jnp.int32)
    xp, M = _pad_rows(x, tm)
    h = _swiglu_up_wq(xp, w1.q, w1.scale, w3.q, w3.scale, gs,
                      tm=tm, tn=tn, tk=tk, int4=int4,
                      interpret=bool(interpret))
    return _gmm_wq(h, w2.q, w2.scale, gs, tm=tm, tn=tk, tk=tn,
                   int4=int4, interpret=bool(interpret))[:M]
