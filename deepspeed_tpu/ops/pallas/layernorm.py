"""Fused LayerNorm (forward + backward) as Pallas TPU kernels.

Counterpart of the reference's fused normalize kernels
(csrc/transformer/normalize_kernels.cu:2134 fused bias-add-LN fwd/bwd,
the reason DeepSpeedTransformerLayer exists): LayerNorm expressed as
separate jnp mean/var reductions costs XLA three HBM passes over the
activations forward (mean pass, variance pass, normalize pass) and more
backward. Each kernel here holds a (rows, D) tile in VMEM and makes ONE
pass: forward reads x once and writes y once; backward reads x/dy once,
writes dx once, and accumulates dscale/dbias in a VMEM-resident block
across the sequential TPU grid (no cross-block atomics needed — grid
steps execute in order, unlike the reference's CUDA block reductions).

Statistics (mean/rstd) are NOT saved as residuals: the backward
recomputes them from the x tile it is already reading — pure VPU work,
zero extra HBM traffic, and nothing extra for `jax.checkpoint` inside
`lax.scan` to spill.

All statistics math runs fp32 on the VPU regardless of input dtype.
Off-TPU the kernels run in Pallas interpreter mode (parity tests).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import AUTO as _AUTO
from ._common import dispatch as _dispatch
from ._common import dtype_name as _dtype_name
from ._common import interpret_default as _interpret_default
from ._common import ln_bucket as _ln_bucket
from ._common import round_up as _round_up

# r05-proven hand-set row tiling; the autotune winner cache can override
# it when callers pass block_rows="auto" (the default)
TUNE_DEFAULTS = {"block_rows": 256}


def _resolve_block_rows(block_rows, x):
    """block_rows="auto" -> cached winner for this (rows, D) bucket,
    else the 256 default; explicit ints pass through untouched."""
    if block_rows != _AUTO:
        return block_rows
    win = _dispatch("layernorm",
                    _ln_bucket(math.prod(x.shape[:-1]), x.shape[-1]),
                    _dtype_name(x.dtype), TUNE_DEFAULTS)
    return int(win["block_rows"])


def _ln_fwd_kernel(x_ref, s_ref, b_ref, y_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                    # (R, D)
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps) * s_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)


def _ln_bwd_kernel(x_ref, s_ref, dy_ref, dx_ref, ds_ref, db_ref, *, eps):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)                    # (R, D)
    dy = dy_ref[...].astype(jnp.float32)
    D = x.shape[1]
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    g = dy * s_ref[...].astype(jnp.float32)
    mg = jnp.mean(g, axis=1, keepdims=True)
    mgx = jnp.mean(g * xhat, axis=1, keepdims=True)
    dx_ref[...] = (rstd * (g - mg - xhat * mgx)).astype(dx_ref.dtype)
    # dscale/dbias: reduce over ALL rows. The constant-index output block
    # stays resident in VMEM across the sequential grid — initialize on
    # the first step, accumulate on every step.
    @pl.when(i == 0)
    def _init():
        ds_ref[...] = jnp.zeros_like(ds_ref)
        db_ref[...] = jnp.zeros_like(db_ref)
    ds_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


def _run_fwd(x, scale, bias, eps, br, interpret):
    N, D = x.shape
    return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(N // br,),
        in_specs=[
            pl.BlockSpec((br, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), x.dtype),
        interpret=interpret,
    )(x, scale.reshape(1, D), bias.reshape(1, D))


def _run_bwd(x, scale, dy, eps, br, interpret):
    N, D = x.shape
    dx, ds, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps),
        grid=(N // br,),
        in_specs=[
            pl.BlockSpec((br, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
            pl.BlockSpec((br, D), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, D), x.dtype),
            jax.ShapeDtypeStruct((1, D), jnp.float32),
            jax.ShapeDtypeStruct((1, D), jnp.float32),
        ],
        interpret=interpret,
    )(x, scale.reshape(1, D), dy)
    return dx, ds[0], db[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln(x, scale, bias, eps, br, interpret):
    return _run_fwd(x, scale, bias, eps, br, interpret)


def _ln_fwd(x, scale, bias, eps, br, interpret):
    return _run_fwd(x, scale, bias, eps, br, interpret), (x, scale)


def _ln_bwd(eps, br, interpret, res, dy):
    x, scale = res
    dx, ds, db = _run_bwd(x, scale, dy, eps, br, interpret)
    return dx, ds.astype(scale.dtype), db.astype(scale.dtype)


_ln.defvjp(_ln_fwd, _ln_bwd)


def _ln_jnp(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln_hybrid(x, scale, bias, eps, br, interpret):
    return _ln_jnp(x, scale, bias, eps)


def _ln_hybrid_fwd(x, scale, bias, eps, br, interpret):
    return _ln_jnp(x, scale, bias, eps), (x, scale)


_ln_hybrid.defvjp(_ln_hybrid_fwd, _ln_bwd)


def _row_blocked(x, run, block_rows):
    """Shared scaffolding for one-pass row-blocked kernels over the last
    dim: (..., D) -> reshape (N, D), pad N to the row-block multiple,
    ``run(x2, br)`` produces (N_pad, D), unpad + reshape back.
    D must be lane-tileable (% 128)."""
    D = x.shape[-1]
    if D % 128:
        raise ValueError(f"fused norm kernels need D % 128 == 0, got {D}")
    lead = x.shape[:-1]
    x2 = x.reshape(-1, D)
    N = x2.shape[0]
    br = max(8, min(block_rows, _round_up(N, 8)))
    N_pad = _round_up(N, br)
    if N_pad != N:
        # zero-pad rows OUTSIDE any custom_vjp: sliced-output cotangents
        # arrive zero-padded, so padded rows add 0 to param grads and
        # their dx is dropped by the slice below
        x2 = jnp.pad(x2, ((0, N_pad - N), (0, 0)))
    y = run(x2, br)
    if N_pad != N:
        y = y[:N]
    return y.reshape(*lead, D)


def layernorm_fused_bwd(x, scale, bias, *, eps=1e-5, block_rows=_AUTO,
                        interpret=None):
    """Hybrid LayerNorm: plain-jnp forward (stays fusable with XLA's
    surrounding elementwise ops, leaves layout choices free) + the
    one-pass Pallas backward (dx + VMEM-accumulated dscale/dbias in a
    single read of x/dy). Same numerics as :func:`fused_layernorm`.
    ``block_rows="auto"`` (default) resolves via the autotune winner
    cache, falling back to 256."""
    block_rows = _resolve_block_rows(block_rows, x)
    if interpret is None:
        interpret = _interpret_default()
    return _row_blocked(
        x, lambda x2, br: _ln_hybrid(x2, scale, bias, float(eps), br,
                                     bool(interpret)), block_rows)


def fused_layernorm(x, scale, bias, *, eps=1e-5, block_rows=_AUTO,
                    interpret=None):
    """LayerNorm over the last dim of ``x`` (any leading shape), fp32
    statistics, output in ``x.dtype``. Differentiable (fused one-pass
    backward). Requires the feature dim to be a multiple of 128 (TPU lane
    tiling); callers should fall back to a jnp layernorm otherwise.
    ``block_rows="auto"`` (default) resolves via the autotune winner
    cache, falling back to 256."""
    block_rows = _resolve_block_rows(block_rows, x)
    if interpret is None:
        interpret = _interpret_default()
    return _row_blocked(
        x, lambda x2, br: _ln(x2, scale, bias, float(eps), br,
                              bool(interpret)), block_rows)


# ------------------------------------------------------------------ rmsnorm
def _rms_fwd_kernel(x_ref, s_ref, y_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                    # (R, D)
    var = jnp.mean(x * x, axis=1, keepdims=True)
    y_ref[...] = (x * jax.lax.rsqrt(var + eps)
                  * s_ref[...].astype(jnp.float32)).astype(y_ref.dtype)


def fused_rmsnorm(x, scale, *, eps=1e-5, block_rows=256, interpret=None):
    """One-pass RMSNorm Pallas kernel over the last dim (the serving
    models' norm; reference csrc/transformer/inference/csrc/rms_norm.cu).
    Forward-only: the jnp-vs-Pallas decision for the v1 serving tier is
    measured by benchmarks/kernel_microbench.py and recorded in
    PERF_NOTES — like fused_layernorm, XLA's fused jnp form wins inside
    real programs on v5e, so models default to jnp and this kernel
    documents the measured alternative."""
    if interpret is None:
        interpret = _interpret_default()
    D = x.shape[-1]

    def run(x2, br):
        return pl.pallas_call(
            functools.partial(_rms_fwd_kernel, eps=eps),
            grid=(x2.shape[0] // br,),
            in_specs=[
                pl.BlockSpec((br, D), lambda i: (i, 0)),
                pl.BlockSpec((1, D), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((br, D), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
            interpret=interpret,
        )(x2, scale.reshape(1, D))

    return _row_blocked(x, run, block_rows)
