"""Fused causal attention (flash attention) as a Pallas TPU kernel.

Counterpart of the reference's fused attention kernels: training softmax
(csrc/transformer/softmax_kernels.cu), inference attention
(csrc/transformer/inference/csrc/softmax.cu) and the memory-efficient
Evoformer kernel (csrc/deepspeed4science/evoformer_attn/) — all of which
exist because materializing the (T, T) score matrix is HBM-bound. Same
motivation here: the online-softmax streaming form never materializes
scores, so HBM traffic drops from O(T^2) to O(T * d) per head and the MXU
stays busy on the two matmuls.

Layout: (batch, seq, heads, head_dim) at the API (the model's layout);
kernels run per (batch*head) on (seq, head_dim) slabs, grid over query
blocks. K/V for one head live in VMEM whole (T*d*2B at bf16 — up to ~32k
tokens at d=128 inside the 16 MB budget); the backward recomputes
attention probabilities from the saved logsumexp instead of storing them
(the standard flash backward).

Off-TPU (unit tests / dryrun) the kernels run in Pallas interpreter mode.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import interpret_default as _interpret_default
from ._common import round_up as _round_up
from ._common import sds as _sds


def _block_sizes(T, block_q, block_k):
    """Pick block sizes and the padded sequence length.

    Any T works: rather than shrinking blocks to a divisor of T (which
    degenerates to tiny blocks that violate the TPU (8,128) tiling and
    explode the grid for prime T), the sequence is padded up to a common
    multiple of the blocks and padded keys are masked in-kernel."""
    bq = min(block_q, _round_up(T, 8))
    bk = min(block_k, _round_up(T, 8))
    T_pad = _round_up(T, math.lcm(bq, bk))
    return bq, bk, T_pad


NEG_INF = -1e30


# ------------------------------------------------------------------ forward
def _mask_scores(s, qi_start, kj_start, bq, bk, causal, t_real, T):
    """Apply causal and/or padded-key masking to a (bq, bk) score block.
    ``t_real < T`` means the sequence was padded; padded keys must never
    contribute. Static no-op when neither mask applies."""
    if not causal and t_real >= T:
        return s
    qpos = qi_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = kj_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    ok = None
    if causal:
        ok = qpos >= kpos
    if t_real < T:
        valid = kpos < t_real
        ok = valid if ok is None else jnp.logical_and(ok, valid)
    return jnp.where(ok, s, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, bq, bk, scale,
                causal, t_real):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale              # (bq, d)
    T = k_ref.shape[1]
    nk = T // bk
    # causal: query block qi attends k blocks 0..ceil((qi+1)*bq / bk)-1
    kmax = pl.cdiv((qi + 1) * bq, bk) if causal else nk

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = _mask_scores(s, qi * bq, j * bk, bq, bk, causal, t_real, T)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l

    d = q_ref.shape[-1]
    acc = jnp.zeros((bq, d), jnp.float32)
    m = jnp.full((bq,), NEG_INF, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, kmax, body, (acc, m, l))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    # lse carries a 128-wide lane dim (value replicated across lanes):
    # per-row scalars are not tileable on TPU, so like the official TPU
    # flash kernel we store (.., bq, 128) blocks
    lse_ref[0] = jnp.broadcast_to((m + jnp.log(l))[:, None],
                                  (bq, lse_ref.shape[-1]))


def _fwd(q, k, v, scale, causal, bq, bk, t_real, interpret):
    BH, T, d = q.shape
    grid = (BH, T // bq)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((BH, T, d), q.dtype, q),
            _sds((BH, T, 128), jnp.float32, q),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ----------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, bq, bk, scale, causal, t_real):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0]
    delta = delta_ref[0][:, 0]
    T = k_ref.shape[1]
    nk = T // bk
    kmax = pl.cdiv((qi + 1) * bq, bk) if causal else nk

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = _mask_scores(s, qi * bq, j * bk, bq, bk, causal, t_real, T)
        p = jnp.exp(s - lse[:, None])                       # (bq, bk)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])                      # (bq, bk)
        return dq + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    d = q_ref.shape[-1]
    dq = jax.lax.fori_loop(0, kmax, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, bq, bk, scale, causal, t_real):
    ki = pl.program_id(1)
    kb = k_ref[0].astype(jnp.float32)                       # (bk, d)
    vb = v_ref[0].astype(jnp.float32)
    T = q_ref.shape[1]
    nq = T // bq
    qmin = (ki * bk) // bq if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * bq, bq), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * bq, bq), :][:, 0]
        delta = delta_ref[0, pl.ds(i * bq, bq), :][:, 0]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = _mask_scores(s, i * bq, ki * bk, bq, bk, causal, t_real, T)
        p = jnp.exp(s - lse[:, None])                       # (bq, bk)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    d = q_ref.shape[-1]
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(qmin, nq, body, (dk, dv))
    # dk accumulated against scaled q: scale folded in already via q*scale
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, scale, causal, bq, bk, t_real, interpret):
    BH, T, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                # (BH, T)
    delta = jnp.broadcast_to(delta[..., None], lse.shape)   # lane dim
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real),
        grid=(BH, T // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
        out_shape=_sds((BH, T, d), q.dtype, q),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real),
        grid=(BH, T // bk),
        in_specs=[
            pl.BlockSpec((1, T, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, T, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, T, 128), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, T, 128), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            _sds((BH, T, d), q.dtype, q),
            _sds((BH, T, d), q.dtype, q),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------- public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, causal, bq, bk, t_real, interpret):
    o, _ = _fwd(q, k, v, scale, causal, bq, bk, t_real, interpret)
    return o


def _flash_fwd(q, k, v, scale, causal, bq, bk, t_real, interpret):
    o, lse = _fwd(q, k, v, scale, causal, bq, bk, t_real, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, bq, bk, t_real, interpret, res, do):
    q, k, v, o, lse = res
    return _bwd(q, k, v, o, lse, do, scale, causal, bq, bk, t_real,
                interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal=True, scale=None, block_q=128,
                    block_k=128, interpret=None):
    """Fused attention over (batch, seq, heads, head_dim) inputs.

    Equivalent math to softmax(scale * q k^T + causal_mask) v with fp32
    accumulation, O(T) memory. Differentiable (custom flash backward).
    Sequences that don't divide the block sizes are zero-padded and the
    padded keys masked in-kernel (slicing the output transposes to
    zero-padded cotangents, so the backward stays correct).
    """
    B, T, H, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_default()
    bq, bk, T_pad = _block_sizes(T, block_q, block_k)
    # TPU tiling wants the lane (last) dim in 128s: zero-pad small head
    # dims (zero columns add 0 to scores and produce zero output columns,
    # and zero cotangent columns backward — exact)
    d_pad = _round_up(d, 128)

    def fold(x):
        x = x.transpose(0, 2, 1, 3).reshape(B * H, T, d)
        if T_pad != T or d_pad != d:
            x = jnp.pad(x, ((0, 0), (0, T_pad - T), (0, d_pad - d)))
        return x

    o = _flash(fold(q), fold(k), fold(v), float(scale), bool(causal),
               bq, bk, T, bool(interpret))
    if T_pad != T or d_pad != d:
        o = o[:, :T, :d]
    return o.reshape(B, H, T, d).transpose(0, 2, 1, 3)


def attention_reference(q, k, v, *, causal=True, scale=None):
    """Dense reference used by parity tests (same fp32 score math)."""
    B, T, H, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bthd,bshd->bhts", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p.astype(q.dtype), v)
