"""Fused causal attention (flash attention) as a Pallas TPU kernel.

Counterpart of the reference's fused attention kernels: training softmax
(csrc/transformer/softmax_kernels.cu), inference attention
(csrc/transformer/inference/csrc/softmax.cu) and the memory-efficient
Evoformer kernel (csrc/deepspeed4science/evoformer_attn/) — all of which
exist because materializing the (T, T) score matrix is HBM-bound. Same
motivation here: the online-softmax streaming form never materializes
scores, so HBM traffic drops from O(T^2) to O(T * d) per head and the MXU
stays busy on the two matmuls.

Layout: (batch, seq, heads, head_dim) at the API (the model's layout).
Kernels process a GROUP of ``block_h`` (batch*head) instances per grid step
as batched dots — at GPT-2 head dims (64..128) a single head's (bq, d) x
(d, bk) dot is far too little work per grid step, and the sequential TPU
grid makes per-step overhead (DMA issue, semaphores) the bottleneck;
batching heads amortizes it. The MXU path keeps q/k/v/p in bf16 with fp32
accumulation (fp32 dot inputs run the MXU at 1/8 rate); softmax
bookkeeping stays fp32 on the VPU. The backward recomputes attention
probabilities from the saved logsumexp instead of storing them (the
standard flash backward).

Off-TPU (unit tests / dryrun) the kernels run in Pallas interpreter mode.
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import AUTO as _AUTO
from ._common import dispatch as _dispatch
from ._common import dtype_name as _dtype_name
from ._common import flash_bucket as _flash_bucket
from ._common import interpret_default as _interpret_default
from ._common import round_up as _round_up
from ._common import sds as _sds

# the r05-proven hand-set tile/variant defaults — what an "auto" tunable
# resolves to when the autotune winner cache has no entry for this
# (device_kind, shape-bucket, dtype)
TUNE_DEFAULTS = {"block_q": 128, "block_k": 128, "block_h": 2,
                 "block_q_bwd": 0, "block_k_bwd": 0, "bwd_qmajor": False}


def _block_sizes(T, block_q, block_k):
    """Pick block sizes and the padded sequence length.

    Any T works: rather than shrinking blocks to a divisor of T (which
    degenerates to tiny blocks that violate the TPU (8,128) tiling and
    explode the grid for prime T), the sequence is padded up to a common
    multiple of the blocks and padded keys are masked in-kernel."""
    bq = min(block_q, _round_up(T, 8))
    bk = min(block_k, _round_up(T, 8))
    T_pad = _round_up(T, math.lcm(bq, bk))
    return bq, bk, T_pad


NEG_INF = -1e30

# Trailing lane dim for per-row scalar tensors (lse, delta). Per-row
# scalars are not 2D-tileable at head-group sizes < 8, so they carry a
# small replicated lane dim. 8 lanes (not 128): the value lives in
# sublanes either side of the HBM round trip, so no in-kernel relayout,
# and the HBM footprint/traffic is 16x smaller than a full 128-lane
# block (201 MB -> 12.6 MB fp32 at 350M bs=24 shapes).
LSE_LANES = 8

# batched dot helpers: x (G, a, c) contract c against y's dim, batch over G
_DN_QK = (((2,), (2,)), ((0,), (0,)))    # (G,bq,d) x (G,bk,d) -> (G,bq,bk)
_DN_PV = (((2,), (1,)), ((0,), (0,)))    # (G,bq,bk) x (G,bk,d) -> (G,bq,d)
_DN_T = (((1,), (1,)), ((0,), (0,)))     # (G,bq,bk) x (G,bq,d) -> (G,bk,d)
# transposed-operand variants (q/k/v carried as (G, d, T) blocks, i.e. T in
# lanes — the layout the surrounding einsums prefer; see *_kernel_t)
_DN_QK_T = (((1,), (1,)), ((0,), (0,)))  # (G,d,bq) x (G,d,bk) -> (G,bq,bk)
_DN_PV_T = (((2,), (2,)), ((0,), (0,)))  # (G,bq,bk) x (G,d,bk) -> (G,bq,d)
_DN_DO_V = (((2,), (1,)), ((0,), (0,)))  # (G,bq,d) x (G,d,bk) -> (G,bq,bk)
_DN_DV_T = (((1,), (1,)), ((0,), (0,)))  # (G,bq,d) x (G,bq,bk) -> (G,d,bk)
_DN_DK_T = (((2,), (1,)), ((0,), (0,)))  # (G,d,bq) x (G,bq,bk) -> (G,d,bk)
_DN_DQ_T = (((2,), (2,)), ((0,), (0,)))  # (G,d,bk) x (G,bq,bk) -> (G,d,bq)


# ----------------------------------------------------------------- biases
# Additive score biases (ALiBi, padding masks, evoformer pair bias) ride
# as extra kernel operands shaped (rows, Tq|1, Tk) — never expanded to
# the (B*H, T, T) score shape. Which bias row(s) a grid group g needs is
# an affine map in block units:
#     f(g) = (g*bh // P) * Q + ((g*bh) % R) // bh
# parametrized per bias (a group of ``bh`` (b, h) instances shares one
# row, spans ``bh`` rows, or cycles rows with a period — all folds used
# by the models reduce to this form; see _bias_cfg). A cfg is the static
# tuple (per_rows, P, Q, R, tq_full, grad):
#   per_rows: rows the block carries (1 = whole group shares a row,
#             bh = one row per instance)
#   tq_full:  bias varies along the query dim (pair bias) vs broadcast
#             (key masks, ALiBi)
#   grad:     backward emits an accumulated d_bias output (evoformer
#             pair-bias training); requires a monotone f over the grid
_B_PER, _B_P, _B_Q, _B_R, _B_TQ, _B_GRAD = range(6)


def _bias_row(cfg, bh, g):
    """Block-row index of bias ``cfg`` for group ``g`` (traced or int)."""
    return (g * bh // cfg[_B_P]) * cfg[_B_Q] \
        + ((g * bh) % cfg[_B_R]) // bh


def _bias_cfg(Bb, Hb, B, H, bh, tq_full, grad, h_outer):
    """Cfg tuple for a (Bb, Hb, Tq, Tk) bias under the (b, h) fold
    (``h_outer``: the qkv_t kernels fold (H, B); others fold (B, H)).
    Bb in {1, B}; Hb in {1, H}. A size-1 model dim takes the broadcast
    branch (full and broadcast coincide there, but the full-branch row
    maps would index past the 1-row folded array)."""
    full_b, full_h = Bb == B > 1, Hb == H > 1
    if full_b and full_h:
        cfg = (bh, bh, 1, bh)
    elif h_outer:
        if full_b:                       # per-batch, group spans b
            cfg = (bh, 1, 0, B)
        elif full_h:                     # per-head, fixed within a group
            cfg = (1, B, 1, bh)
        else:
            cfg = (1, 1, 0, bh)
    else:
        if full_b:                       # per-batch, fixed within a group
            cfg = (1, H, 1, bh)
        elif full_h:                     # per-head, group spans h
            cfg = (bh, 1, 0, H)
        else:
            cfg = (1, 1, 0, bh)
    return cfg + (bool(tq_full), bool(grad))


def _bias_constraint(Bb, Hb, B, H, h_outer):
    """The number ``bh`` must DIVIDE so one bias block covers a group (a
    group must not straddle two rows of a shared dim), or None when the
    bias imposes no constraint. Note a divisor of 1 is a real
    constraint (bh = 1): e.g. a per-batch bias on an H == 1 model —
    groups span batch items there, so each instance needs its own
    row."""
    full_b = Bb == B and Bb > 1
    full_h = Hb == H and Hb > 1
    if (full_b and full_h) or (Bb == 1 and Hb == 1):
        return None
    if Bb > 1 and Hb == 1:          # per-batch bias
        return B if h_outer else H
    if Hb > 1 and Bb == 1:          # per-head bias
        return B if h_outer else H
    return None


def _fwd_bias_specs(cfgs, biases, bq, T_pad, bh):
    """Forward operand BlockSpecs: (per_rows, bq, T_pad); the kernel
    walks the key dim itself (k/v are full-T blocks too).

    Biases always carry a FULL query dim: a size-1 sublane dim
    broadcast inside the online-softmax carry loop crashes Mosaic's
    layout inference (verified on v5e), so the wrapper expands
    query-broadcast biases (key masks, ALiBi) to (rows, T, T) up
    front."""
    return [pl.BlockSpec(
        (cfg[_B_PER], bq, T_pad),
        lambda g, i, c=cfg: (_bias_row(c, bh, g), i, 0))
        for cfg, b in zip(cfgs, biases)]


def _bwd_bias_specs(cfgs, biases, bk, T_pad, bh):
    """Backward operand BlockSpecs: (per_rows, T_pad, bk); the kernel
    walks the query dim itself."""
    return [pl.BlockSpec(
        (cfg[_B_PER], T_pad, bk),
        lambda g, j, c=cfg: (_bias_row(c, bh, g), 0, j))
        for cfg, b in zip(cfgs, biases)]


def _fwd_bias_add(s, bias_refs, cfgs, j, bk):
    """s (G, bq, bk) += each bias's (rows, bq, bk) block, f32.

    The key dim is the LANE dim of the bias block: Mosaic needs dynamic
    lane offsets in 128 units, so the wrapper forces bk to a multiple of
    128 whenever biases are present (single-block refs load the static
    full block)."""
    for ref, cfg in zip(bias_refs, cfgs):
        blk = ref[...] if ref.shape[2] == bk \
            else ref[:, :, pl.ds(j * bk, bk)]
        s = s + blk.astype(jnp.float32)
    return s


def _bwd_bias_add(s, bias_refs, cfgs, i, bq):
    for ref, cfg in zip(bias_refs, cfgs):
        s = s + ref[:, pl.ds(i * bq, bq), :].astype(jnp.float32)
    return s


def _alibi_add(s, alibi_cfg, apos_blk, g, bh):
    """s (1, bq, bk) += slope_h * k_pos.

    The slope is evaluated in-kernel with the bloom formula from the
    instance's head index — a per-grid-step SCALAR (the wrapper forces
    block_h=1 under ALiBi). k_pos arrives as a tiny shared
    (1, T_pad, T_pad) f32 operand (``apos_blk`` is its (1, bq, bk)
    tile): Mosaic constant-folds iota->float chains into an f32
    ``tpu.iota`` that fails verification (and, unverified, crashes its
    layout pass) inside the softmax carry loop, so positions must come
    from a ref, exactly like the bias operands that compile fine. Net
    HBM cost is one O(T^2) array shared by every (batch, head) — not
    the (H, T, T) or (B, H, T, T) a materialized bias would need.
    alibi_cfg = (h_outer, H, B, scale, bf16) — see the wrapper."""
    h_outer, H, B, a_scale, a_bf16 = alibi_cfg
    idx = g * bh                              # bh == 1: instance index
    h = (idx // B if h_outer else idx % H).astype(jnp.float32)
    cp = float(2 ** math.floor(math.log2(H)))
    expo = jnp.where(h < cp, -(h + 1.0) * (8.0 / cp),
                     -(2.0 * (h - cp) + 1.0) * (4.0 / cp))
    slope = jnp.exp2(expo)                    # scalar
    ab = slope * apos_blk
    if a_bf16:
        # HF falcon quantizes the alibi tensor through bf16 and adds it
        # pre-scaling (models/llama.py _alibi_bias)
        ab = ab.astype(jnp.bfloat16).astype(jnp.float32)
    if a_scale != 1.0:
        ab = ab * a_scale
    return s + ab


def _mask_block(qi_start, kj_start, bq, bk, causal, t_real, T,
                window=0):
    """(bq, bk) boolean mask for causal / padded-key / sliding-window
    masking; None when none applies (static no-op)."""
    if not causal and t_real >= T and not window:
        return None
    qpos = qi_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = kj_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    ok = None
    if causal:
        ok = qpos >= kpos
    if window:
        win = qpos - kpos < window
        ok = win if ok is None else jnp.logical_and(ok, win)
    if t_real < T:
        valid = kpos < t_real
        ok = valid if ok is None else jnp.logical_and(ok, valid)
    return ok


def _apply_mask(s, ok):
    """s: (G, bq, bk); ok: (bq, bk) or None."""
    if ok is None:
        return s
    return jnp.where(ok[None], s, NEG_INF)


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, *rest, bq, bk, scale,
                causal, t_real, window=0, bias_cfgs=(),
                alibi_cfg=None):
    n_in = len(bias_cfgs) + (1 if alibi_cfg else 0)
    bias_refs = rest[:len(bias_cfgs)]
    apos_ref = rest[len(bias_cfgs)] if alibi_cfg else None
    o_ref, lse_ref = rest[n_in:]
    qi = pl.program_id(1)
    gi = pl.program_id(0)
    q = q_ref[...]                                        # (G, bq, d) bf16
    G = q.shape[0]
    T = k_ref.shape[1]
    nk = T // bk
    # causal: query block qi attends k blocks 0..ceil((qi+1)*bq / bk)-1.
    # Blocks fully below the diagonal skip mask generation entirely (the
    # iota/compare/select per element is real VPU cost in a VPU-bound
    # kernel); only the straddling blocks mask. With padded keys
    # (t_real < T) every block takes the masked path.
    kmax = pl.cdiv((qi + 1) * bq, bk) if causal else nk
    kfull = (qi * bq) // bk if (causal and t_real >= T) else (
        nk if (not causal and t_real >= T) else 0)
    kmin = 0
    if window:
        # blocks entirely below the window's lower edge are dead; every
        # live block takes the masked path (the window edge can cross
        # any of them)
        kmin = jnp.maximum(0, (qi * bq - window + 1) // bk)
        kfull = kmin

    def make_body(masked):
        def body(j, carry):
            acc, m, l = carry
            kb = k_ref[:, pl.ds(j * bk, bk), :]
            vb = v_ref[:, pl.ds(j * bk, bk), :]
            s = jax.lax.dot_general(q, kb, _DN_QK,
                                    preferred_element_type=jnp.float32)
            if scale != 1.0:
                s = s * scale
            if bias_cfgs:
                s = _fwd_bias_add(s, bias_refs, bias_cfgs, j, bk)
            if alibi_cfg:
                apb = apos_ref[...] if apos_ref.shape[2] == bk \
                    else apos_ref[:, :, pl.ds(j * bk, bk)]
                s = _alibi_add(s, alibi_cfg, apb, gi, G)
            if masked:
                s = _apply_mask(s, _mask_block(qi * bq, j * bk, bq, bk,
                                               causal, t_real, T,
                                               window))
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[..., None] + jax.lax.dot_general(
                p.astype(vb.dtype), vb, _DN_PV,
                preferred_element_type=jnp.float32)
            return acc, m_new, l
        return body

    d = q_ref.shape[-1]
    acc = jnp.zeros((G, bq, d), jnp.float32)
    m = jnp.full((G, bq), NEG_INF, jnp.float32)
    l = jnp.zeros((G, bq), jnp.float32)
    carry = jax.lax.fori_loop(kmin, kfull, make_body(False), (acc, m, l))
    acc, m, l = jax.lax.fori_loop(kfull, kmax, make_body(True), carry)
    o_ref[...] = (acc / l[..., None]).astype(o_ref.dtype)
    # lse replicated across LSE_LANES lanes (see constant above); the
    # wrapper trims to one lane before anything is saved
    lse_ref[...] = jnp.broadcast_to((m + jnp.log(l))[..., None],
                                    (G, bq, lse_ref.shape[-1]))


def _fwd(q, k, v, scale, causal, bq, bk, bh, t_real, interpret, window=0,
         biases=(), bias_cfgs=(), alibi_cfg=None):
    BH, T, d = q.shape
    grid = (BH // bh, T // bq)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real, window=window,
                          bias_cfgs=bias_cfgs, alibi_cfg=alibi_cfg),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bh, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, T, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((bh, T, d), lambda b, i: (b, 0, 0)),
        ] + _fwd_bias_specs(bias_cfgs, biases, bq, T, bh)
          + ([pl.BlockSpec((1, bq, T), lambda b, i: (0, i, 0))]
             if alibi_cfg else []),
        out_specs=[
            pl.BlockSpec((bh, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, bq, LSE_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((BH, T, d), q.dtype, q),
            _sds((BH, T, LSE_LANES), jnp.float32, q),
        ],
        interpret=interpret,
    )(q, k, v, *biases)
    return o, lse


# ------------------------------------------------- forward, transposed q/k/v
def _fwd_kernel_t(q_ref, k_ref, v_ref, *rest, bq, bk, scale,
                  causal, t_real, window=0, bias_cfgs=(),
                  alibi_cfg=None):
    """Forward with q/k/v blocked (G, d, T) — T in lanes.

    The surrounding qkv projection einsums emit T-minor layouts (hd=64
    fills only half a 128-lane register, so XLA puts T in lanes); the
    standard (G, T, d) operand forces a relayout copy per tensor per
    layer (~46 ms/step at 350M bs=24 counting forward, remat recompute
    and backward). Consuming the producer's layout directly makes those
    copies bitcasts. Score-space math is IDENTICAL to _fwd_kernel —
    softmax stats stay (G, bq) sublane vectors — only the q/k dots
    contract the sublane dim (MXU-native transposed matmul) and the pv
    dot contracts lanes x lanes. Output o stays (G, bq, d): its consumer
    (the wo projection) takes it without a copy either way.

    Biases are NOT transposed: score space is (bq, bk) in both layouts,
    so bias blocks are consumed in the standard orientation."""
    n_in = len(bias_cfgs) + (1 if alibi_cfg else 0)
    bias_refs = rest[:len(bias_cfgs)]
    apos_ref = rest[len(bias_cfgs)] if alibi_cfg else None
    o_ref, lse_ref = rest[n_in:]
    qi = pl.program_id(1)
    gi = pl.program_id(0)
    q = q_ref[...]                                        # (G, d, bq) bf16
    G = q.shape[0]
    T = k_ref.shape[2]
    nk = T // bk
    kmax = pl.cdiv((qi + 1) * bq, bk) if causal else nk
    kfull = (qi * bq) // bk if (causal and t_real >= T) else (
        nk if (not causal and t_real >= T) else 0)
    kmin = 0
    if window:
        kmin = jnp.maximum(0, (qi * bq - window + 1) // bk)
        kfull = kmin

    def make_body(masked):
        def body(j, carry):
            acc, m, l = carry
            kb = k_ref[:, :, pl.ds(j * bk, bk)]
            vb = v_ref[:, :, pl.ds(j * bk, bk)]
            s = jax.lax.dot_general(q, kb, _DN_QK_T,
                                    preferred_element_type=jnp.float32)
            if scale != 1.0:
                s = s * scale
            if bias_cfgs:
                s = _fwd_bias_add(s, bias_refs, bias_cfgs, j, bk)
            if alibi_cfg:
                apb = apos_ref[...] if apos_ref.shape[2] == bk \
                    else apos_ref[:, :, pl.ds(j * bk, bk)]
                s = _alibi_add(s, alibi_cfg, apb, gi, G)
            if masked:
                s = _apply_mask(s, _mask_block(qi * bq, j * bk, bq, bk,
                                               causal, t_real, T,
                                               window))
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[..., None] + jax.lax.dot_general(
                p.astype(vb.dtype), vb, _DN_PV_T,
                preferred_element_type=jnp.float32)
            return acc, m_new, l
        return body

    d = q_ref.shape[1]
    acc = jnp.zeros((G, bq, d), jnp.float32)
    m = jnp.full((G, bq), NEG_INF, jnp.float32)
    l = jnp.zeros((G, bq), jnp.float32)
    carry = jax.lax.fori_loop(kmin, kfull, make_body(False), (acc, m, l))
    acc, m, l = jax.lax.fori_loop(kfull, kmax, make_body(True), carry)
    o_ref[...] = (acc / l[..., None]).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to((m + jnp.log(l))[..., None],
                                    (G, bq, lse_ref.shape[-1]))


def _fwd_t(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
           window=0, biases=(), bias_cfgs=(), alibi_cfg=None):
    BH, d, T = q.shape
    grid = (BH // bh, T // bq)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_t, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real, window=window,
                          bias_cfgs=bias_cfgs, alibi_cfg=alibi_cfg),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bh, d, bq), lambda b, i: (b, 0, i)),
            pl.BlockSpec((bh, d, T), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((bh, d, T), lambda b, i: (b, 0, 0)),
        ] + _fwd_bias_specs(bias_cfgs, biases, bq, T, bh)
          + ([pl.BlockSpec((1, bq, T), lambda b, i: (0, i, 0))]
             if alibi_cfg else []),
        out_specs=[
            pl.BlockSpec((bh, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, bq, LSE_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((BH, T, d), q.dtype, q),
            _sds((BH, T, LSE_LANES), jnp.float32, q),
        ],
        interpret=interpret,
    )(q, k, v, *biases)
    return o, lse


# ----------------------------------------------------------------- backward
def _dbias_init(dbias_refs, grad_cfgs, bh, ki):
    """Zero dbias accumulator blocks at the right step. per_rows==bh
    blocks are fresh every grid step (injective index map); per_rows==1
    blocks persist across the run of grid steps sharing a bias row —
    zero at the run's first step (monotone maps only, enforced in the
    wrapper)."""
    g = pl.program_id(0)
    for ref, cfg in zip(dbias_refs, grad_cfgs):
        if cfg[_B_PER] == 1:
            gp = jnp.maximum(g - 1, 0)
            start = jnp.logical_or(
                g == 0, _bias_row(cfg, bh, g) != _bias_row(cfg, bh, gp))

            @pl.when(jnp.logical_and(ki == 0, start))
            def _init(ref=ref):
                ref[...] = jnp.zeros_like(ref)
        else:
            ref[...] = jnp.zeros_like(ref)


def _dbias_update(dbias_refs, grad_cfgs, ds_f, i, ki, bq, bk):
    """Accumulate ds (f32, pre-cast) into each grad bias's block,
    summing over whichever score dims the bias broadcasts (query-
    broadcast biases use 2D (rows, Tk) accumulators)."""
    for ref, cfg in zip(dbias_refs, grad_cfgs):
        contrib = ds_f
        if cfg[_B_PER] == 1:
            contrib = jnp.sum(contrib, axis=0, keepdims=True)
        if cfg[_B_PER] == 1:                  # full-k persistent block
            if ref.shape[2] == bk:            # single k block: static
                ref[:, pl.ds(i * bq, bq), :] += contrib
            else:
                ref[:, pl.ds(i * bq, bq), pl.ds(ki * bk, bk)] += contrib
        else:                                 # per-step (rows, T_pad, bk)
            ref[:, pl.ds(i * bq, bq), :] += contrib


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, od_ref,
                *rest, bq, bk, scale, causal, t_real,
                ext_delta, single_k, window=0, bias_cfgs=(),
                alibi_cfg=None):
    """Fused flash backward: dq, dk, dv from ONE s/p computation.

    Grid is (BH/bh, T/bk) over key blocks; an inner loop walks the query
    blocks this key block attends. The two-kernel formulation (separate
    dq and dk/dv passes, as in the reference's backward and round 2
    here) computes s = q k^T and p = exp(s - lse) TWICE; fusing halves
    the score-matrix work — the dominant VPU+MXU cost of the backward.

    dq accumulates ACROSS grid steps in a VMEM-resident fp32 block (the
    TPU grid is sequential; the constant-index output block persists),
    initialized at the first key block. dk/dv accumulate in registers
    over the inner loop.
    """
    n_bias = len(bias_cfgs)
    n_in = n_bias + (1 if alibi_cfg else 0)
    bias_refs = rest[:n_bias]
    apos_ref = rest[n_bias] if alibi_cfg else None
    dq_ref, dk_ref, dv_ref = rest[n_in:n_in + 3]
    dbias_refs = rest[n_in + 3:]
    grad_cfgs = tuple(c for c in bias_cfgs if c[_B_GRAD])
    ki = pl.program_id(1)
    gi = pl.program_id(0)
    kb = k_ref[...]                                         # (G, bk, d) bf16
    G = kb.shape[0]
    vb = v_ref[...]
    T = q_ref.shape[1]
    nq = T // bq
    if dbias_refs:
        _dbias_init(dbias_refs, grad_cfgs, G, ki)
    qmin = (ki * bk) // bq if causal else 0
    # q blocks straddling the diagonal need the causal mask; blocks fully
    # below it don't. With padded keys every block masks.
    qfull = pl.cdiv((ki + 1) * bk, bq) if (causal and t_real >= T) else (
        qmin if t_real >= T else nq)
    qend = nq
    if window:
        # highest q position attending this key block: (ki+1)*bk - 2 +
        # window; blocks above are dead, and every live block masks
        qend = jnp.minimum(nq, ((ki + 1) * bk - 2 + window) // bq + 1)
        qfull = qend

    if not single_k:
        @pl.when(ki == 0)
        def _init():
            dq_ref[...] = jnp.zeros_like(dq_ref)

    def make_body(masked):
        def body(i, carry):
            dk, dv = carry
            q = q_ref[:, pl.ds(i * bq, bq), :]
            do = do_ref[:, pl.ds(i * bq, bq), :]
            lse = lse_ref[:, pl.ds(i * bq, bq), :][..., 0]  # (G, bq)
            if ext_delta:
                # od_ref carries a precomputed (broadcast) delta — the
                # lse-cotangent path folds its shift in outside
                delta = od_ref[:, pl.ds(i * bq, bq), :][..., 0]
            else:
                # od_ref is o: delta = rowsum(do * o), computed on the
                # VPU from blocks already resident — no (BH, T, 128)
                # broadcast materialization, no separate reduce pass
                ob = od_ref[:, pl.ds(i * bq, bq), :]
                delta = jnp.sum(do.astype(jnp.float32)
                                * ob.astype(jnp.float32), axis=-1)
            s = jax.lax.dot_general(q, kb, _DN_QK,
                                    preferred_element_type=jnp.float32)
            if scale != 1.0:
                s = s * scale
            if bias_cfgs:
                s = _bwd_bias_add(s, bias_refs, bias_cfgs, i, bq)
            if alibi_cfg:
                apb = apos_ref[:, pl.ds(i * bq, bq), :]
                s = _alibi_add(s, alibi_cfg, apb, gi, G)
            if masked:
                s = _apply_mask(s, _mask_block(i * bq, ki * bk, bq, bk,
                                               causal, t_real, T,
                                               window))
            p = jnp.exp(s - lse[..., None])                 # (G, bq, bk) f32
            pb = p.astype(do.dtype)
            dv = dv + jax.lax.dot_general(pb, do, _DN_T,
                                          preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, vb, _DN_QK,
                                     preferred_element_type=jnp.float32)
            ds_f = p * (dp - delta[..., None])
            ds = ds_f.astype(q.dtype)
            if dbias_refs:
                # d(bias) = ds (bias enters s additively, post-scale)
                _dbias_update(dbias_refs, grad_cfgs, ds_f, i, ki, bq, bk)
            dk = dk + jax.lax.dot_general(ds, q, _DN_T,
                                          preferred_element_type=jnp.float32)
            dq_val = jax.lax.dot_general(ds, kb, _DN_PV,
                                         preferred_element_type=jnp.float32)
            if single_k:
                # one key block: each dq slice is written exactly once, so
                # the output can be emitted in the model dtype directly —
                # no fp32 (BH, T, d) HBM buffer + cast copy outside
                dq_ref[:, pl.ds(i * bq, bq), :] = dq_val.astype(dq_ref.dtype)
            else:
                dq_ref[:, pl.ds(i * bq, bq), :] += dq_val
            return dk, dv
        return body

    d = q_ref.shape[-1]
    dk = jnp.zeros((G, bk, d), jnp.float32)
    dv = jnp.zeros((G, bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(qmin, qfull, make_body(True), (dk, dv))
    dk, dv = jax.lax.fori_loop(qfull, qend, make_body(False), (dk, dv))
    # ds was computed from unscaled-q dots (scale applied to s post-dot),
    # so dk needs the scale factor once here (dq's lands in the wrapper)
    if scale != 1.0:
        dk = dk * scale
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _dbias_out(biases, bias_cfgs, bk, T_pad, bh, like):
    """(out_specs, out_shapes) for the grad biases' accumulators, and
    the post-call distributor mapping kernel outputs back to a
    per-bias cotangent list (zeros for non-grad biases)."""
    specs, shapes = [], []
    for b, cfg in zip(biases, bias_cfgs):
        if not cfg[_B_GRAD]:
            continue
        if cfg[_B_PER] == 1:
            # persistent accumulator: full (Tq, Tk) block per bias row
            specs.append(pl.BlockSpec(
                (1, b.shape[1], T_pad),
                lambda g, j, c=cfg: (_bias_row(c, bh, g), 0, 0)))
        else:
            specs.append(pl.BlockSpec(
                (cfg[_B_PER], b.shape[1], bk),
                lambda g, j, c=cfg: (_bias_row(c, bh, g), 0, j)))
        shapes.append(_sds(b.shape, jnp.float32, like))
    return specs, shapes


def _scatter_dbias(biases, bias_cfgs, grads):
    """Align kernel dbias outputs with the biases tuple (zeros for
    non-differentiable biases), cast to each bias's dtype."""
    out, it = [], iter(grads)
    for b, cfg in zip(biases, bias_cfgs):
        out.append(next(it).astype(b.dtype) if cfg[_B_GRAD]
                   else jnp.zeros(b.shape, b.dtype))
    return tuple(out)


def _bwd(q, k, v, o, lse_t, do, scale, causal, bq, bk, bh, t_real,
         interpret, dlse=None, window=0, biases=(), bias_cfgs=(),
         alibi_cfg=None):
    BH, T, d = q.shape
    # (BH, T, 1) -> LSE_LANES lanes for the operand block; XLA lowers
    # this to one small relayout/broadcast per layer (~8 ms/step total)
    lse = jnp.broadcast_to(lse_t, (BH, T, LSE_LANES))
    if dlse is not None:
        # lse cotangent shifts delta (see _flash_bwd): precompute the
        # shifted delta outside and broadcast to the operand lanes
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1) - dlse.astype(jnp.float32)
        od = jnp.broadcast_to(delta[..., None], (BH, T, LSE_LANES))
    else:
        # common case (lse output unused): the kernel computes delta
        # from o/do blocks in VMEM — no broadcast materialization
        od = o
    single_k = (T // bk) == 1
    db_specs, db_shapes = _dbias_out(biases, bias_cfgs, bk, T, bh, q)
    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real,
                          ext_delta=dlse is not None, single_k=single_k,
                          window=window, bias_cfgs=bias_cfgs,
                          alibi_cfg=alibi_cfg),
        grid=(BH // bh, T // bk),
        in_specs=[
            pl.BlockSpec((bh, T, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((bh, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((bh, T, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, T, LSE_LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, T, LSE_LANES if dlse is not None else d),
                         lambda b, j: (b, 0, 0)),
        ] + _bwd_bias_specs(bias_cfgs, biases, bk, T, bh)
          + ([pl.BlockSpec((1, T, bk), lambda b, j: (0, 0, j))]
             if alibi_cfg else []),
        out_specs=[
            pl.BlockSpec((bh, T, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((bh, bk, d), lambda b, j: (b, j, 0)),
        ] + db_specs,
        out_shape=[
            # dq accumulates fp32 across key-block grid steps; with a
            # single key block each slice is written once, so it is
            # emitted in the model dtype with no cast copy
            _sds((BH, T, d), q.dtype if single_k else jnp.float32, q),
            _sds((BH, T, d), q.dtype, q),
            _sds((BH, T, d), q.dtype, q),
        ] + db_shapes,
        interpret=interpret,
    )(q, k, v, do, lse, od, *biases)
    dq, dk, dv = outs[:3]
    dbiases = _scatter_dbias(biases, bias_cfgs, outs[3:])
    if alibi_cfg:
        # the trailing operand is the shared ALiBi position grid — a
        # constant with no gradient
        dbiases = dbiases + (jnp.zeros(biases[-1].shape,
                                       biases[-1].dtype),)
    if scale != 1.0:
        dq = dq * scale
    return dq.astype(q.dtype), dk, dv, dbiases


# ------------------------------------------------ backward, transposed q/k/v
def _bwd_kernel_t(q_ref, k_ref, v_ref, do_ref, lse_ref, od_ref,
                  *rest, bq, bk, scale, causal, t_real,
                  ext_delta, single_k, window=0, bias_cfgs=(),
                  alibi_cfg=None):
    """Fused backward with q/k/v, do AND dq/dk/dv blocked (G, d, T).

    Same structure as _bwd_kernel (key-block grid, inner loop over query
    blocks, one s/p computation feeding dq+dk+dv), with every seq-major
    tensor consumed/produced T-in-lanes so the surrounding einsums'
    preferred layouts connect via bitcasts, not copies.

    do and o stay in the natural (G, T, d) layout — the forward emits o
    that way and the cotangent arrives the same way — keeping
    delta = rowsum(do * o) a lane reduction (sublane-vector result).
    Measured alternatives at 350M bs=24 (both kept the step SLOWER):
    do consumed (G, d, T) + delta precomputed outside (+8 ms: the
    delta fusion/broadcast outweighs the saved do relayout), and the
    in-kernel softmax identity delta = sum_j p_ij dp_ij (+11 ms VPU in
    an already-VPU-bound kernel). ext_delta (as in _bwd_kernel): False = in-kernel
    rowsum(do * o) with od_ref carrying o; True = precomputed delta via
    od_ref (the lse-cotangent path folds -dlse in outside).

    Biases and dbias accumulators stay in score-space orientation
    (rows, Tq|1, Tk) — identical to _bwd_kernel.
    """
    n_bias = len(bias_cfgs)
    n_in = n_bias + (1 if alibi_cfg else 0)
    bias_refs = rest[:n_bias]
    apos_ref = rest[n_bias] if alibi_cfg else None
    dq_ref, dk_ref, dv_ref = rest[n_in:n_in + 3]
    dbias_refs = rest[n_in + 3:]
    grad_cfgs = tuple(c for c in bias_cfgs if c[_B_GRAD])
    ki = pl.program_id(1)
    gi = pl.program_id(0)
    kb = k_ref[...]                                         # (G, d, bk)
    G = kb.shape[0]
    vb = v_ref[...]
    T = q_ref.shape[2]
    nq = T // bq
    if dbias_refs:
        _dbias_init(dbias_refs, grad_cfgs, G, ki)
    qmin = (ki * bk) // bq if causal else 0
    qfull = pl.cdiv((ki + 1) * bk, bq) if (causal and t_real >= T) else (
        qmin if t_real >= T else nq)
    qend = nq
    if window:
        qend = jnp.minimum(nq, ((ki + 1) * bk - 2 + window) // bq + 1)
        qfull = qend

    if not single_k:
        @pl.when(ki == 0)
        def _init():
            dq_ref[...] = jnp.zeros_like(dq_ref)

    def make_body(masked):
        def body(i, carry):
            dk, dv = carry
            q = q_ref[:, :, pl.ds(i * bq, bq)]              # (G, d, bq)
            do = do_ref[:, pl.ds(i * bq, bq), :]            # (G, bq, d)
            lse = lse_ref[:, pl.ds(i * bq, bq), :][..., 0]  # (G, bq)
            if ext_delta:
                delta = od_ref[:, pl.ds(i * bq, bq), :][..., 0]
            else:
                ob = od_ref[:, pl.ds(i * bq, bq), :]        # (G, bq, d)
                delta = jnp.sum(do.astype(jnp.float32)
                                * ob.astype(jnp.float32), axis=-1)
            s = jax.lax.dot_general(q, kb, _DN_QK_T,
                                    preferred_element_type=jnp.float32)
            if scale != 1.0:
                s = s * scale
            if bias_cfgs:
                s = _bwd_bias_add(s, bias_refs, bias_cfgs, i, bq)
            if alibi_cfg:
                apb = apos_ref[:, pl.ds(i * bq, bq), :]
                s = _alibi_add(s, alibi_cfg, apb, gi, G)
            if masked:
                s = _apply_mask(s, _mask_block(i * bq, ki * bk, bq, bk,
                                               causal, t_real, T,
                                               window))
            p = jnp.exp(s - lse[..., None])                 # (G, bq, bk) f32
            pb = p.astype(do.dtype)
            dv = dv + jax.lax.dot_general(do, pb, _DN_DV_T,
                                          preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, vb, _DN_DO_V,
                                     preferred_element_type=jnp.float32)
            ds_f = p * (dp - delta[..., None])
            ds = ds_f.astype(q.dtype)
            if dbias_refs:
                _dbias_update(dbias_refs, grad_cfgs, ds_f, i, ki, bq, bk)
            dk = dk + jax.lax.dot_general(q, ds, _DN_DK_T,
                                          preferred_element_type=jnp.float32)
            dq_val = jax.lax.dot_general(kb, ds, _DN_DQ_T,
                                         preferred_element_type=jnp.float32)
            if single_k:
                dq_ref[:, :, pl.ds(i * bq, bq)] = dq_val.astype(dq_ref.dtype)
            else:
                dq_ref[:, :, pl.ds(i * bq, bq)] += dq_val
            return dk, dv
        return body

    d = q_ref.shape[1]
    dk = jnp.zeros((G, d, bk), jnp.float32)
    dv = jnp.zeros((G, d, bk), jnp.float32)
    dk, dv = jax.lax.fori_loop(qmin, qfull, make_body(True), (dk, dv))
    dk, dv = jax.lax.fori_loop(qfull, qend, make_body(False), (dk, dv))
    if scale != 1.0:
        dk = dk * scale
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_kernel_t_qmajor(q_ref, k_ref, v_ref, do_ref, lse_ref, od_ref,
                         dq_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, bq,
                         bk, scale, causal, t_real, ext_delta, window=0):
    """Fused backward, transposed layout, walked QUERY-major.

    The k-major kernel (_bwd_kernel_t) accumulates dq across grid steps
    in a VMEM-resident fp32 OUTPUT block — which must then round-trip
    HBM in fp32 and pay a cast copy outside. This variant applies the
    same VMEM-resident-accumulation trick to the dkv side instead: the
    grid walks query blocks (the forward's access pattern), dq for each
    block completes in ONE grid step and is written once, directly in
    the model dtype (no fp32 HBM buffer, no cast copy), while dk/dv
    accumulate in fp32 VMEM scratch across the sequential grid and cast
    in the final step's epilogue. delta = rowsum(do * o) is computed
    once per QUERY block (the k-major kernel recomputes it for every
    (q, k) pair when bk < T). Inner-loop bounds are exactly the forward
    kernel's causal/window/padding bounds. Bias operands are not
    supported here — biased paths keep the k-major kernel."""
    qi = pl.program_id(1)
    nq = pl.num_programs(1)
    q = q_ref[...]                                          # (G, d, bq)
    G = q.shape[0]
    kb_all = k_ref
    T = k_ref.shape[2]
    nk = T // bk
    kmax = pl.cdiv((qi + 1) * bq, bk) if causal else nk
    kfull = (qi * bq) // bk if (causal and t_real >= T) else (
        nk if (not causal and t_real >= T) else 0)
    kmin = 0
    if window:
        kmin = jnp.maximum(0, (qi * bq - window + 1) // bk)
        kfull = kmin

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    do = do_ref[...]                                        # (G, bq, d)
    lse = lse_ref[...][..., 0]                              # (G, bq)
    if ext_delta:
        delta = od_ref[...][..., 0]
    else:
        ob = od_ref[...]                                    # (G, bq, d)
        delta = jnp.sum(do.astype(jnp.float32)
                        * ob.astype(jnp.float32), axis=-1)

    def make_body(masked):
        def body(j, dq):
            kb = kb_all[:, :, pl.ds(j * bk, bk)]
            vb = v_ref[:, :, pl.ds(j * bk, bk)]
            s = jax.lax.dot_general(q, kb, _DN_QK_T,
                                    preferred_element_type=jnp.float32)
            if scale != 1.0:
                s = s * scale
            if masked:
                s = _apply_mask(s, _mask_block(qi * bq, j * bk, bq, bk,
                                               causal, t_real, T,
                                               window))
            p = jnp.exp(s - lse[..., None])                 # (G, bq, bk)
            pb = p.astype(do.dtype)
            dv_scr[:, :, pl.ds(j * bk, bk)] += jax.lax.dot_general(
                do, pb, _DN_DV_T, preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, vb, _DN_DO_V,
                                     preferred_element_type=jnp.float32)
            ds_f = p * (dp - delta[..., None])
            ds = ds_f.astype(q.dtype)
            dk_scr[:, :, pl.ds(j * bk, bk)] += jax.lax.dot_general(
                q, ds, _DN_DK_T, preferred_element_type=jnp.float32)
            return dq + jax.lax.dot_general(
                kb, ds, _DN_DQ_T, preferred_element_type=jnp.float32)
        return body

    d = q_ref.shape[1]
    dq = jnp.zeros((G, d, bq), jnp.float32)
    dq = jax.lax.fori_loop(kmin, kfull, make_body(False), dq)
    dq = jax.lax.fori_loop(kfull, kmax, make_body(True), dq)
    if scale != 1.0:
        dq = dq * scale
    dq_ref[...] = dq.astype(dq_ref.dtype)

    @pl.when(qi == nq - 1)
    def _flush():
        dk = dk_scr[...]
        if scale != 1.0:
            dk = dk * scale
        dk_ref[...] = dk.astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_t_qmajor(q, k, v, o, lse_t, do, scale, causal, bq, bk, bh,
                  t_real, interpret, dlse=None, window=0):
    BH, d, T = q.shape
    lse = jnp.broadcast_to(lse_t, (BH, T, LSE_LANES))
    if dlse is not None:
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1) - dlse.astype(jnp.float32)
        od = jnp.broadcast_to(delta[..., None], (BH, T, LSE_LANES))
    else:
        od = o
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel_t_qmajor, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real,
                          ext_delta=dlse is not None, window=window),
        grid=(BH // bh, T // bq),
        in_specs=[
            pl.BlockSpec((bh, d, bq), lambda b, i: (b, 0, i)),
            pl.BlockSpec((bh, d, T), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((bh, d, T), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((bh, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, bq, LSE_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, bq, LSE_LANES if dlse is not None else d),
                         lambda b, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bh, d, bq), lambda b, i: (b, 0, i)),
            pl.BlockSpec((bh, d, T), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((bh, d, T), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            # every output in the model dtype: dq slices are written
            # exactly once (their grid step), dk/dv cast from the fp32
            # VMEM accumulators in the last step's epilogue
            _sds((BH, d, T), q.dtype, q),
            _sds((BH, d, T), q.dtype, q),
            _sds((BH, d, T), q.dtype, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((bh, d, T), jnp.float32),
            pltpu.VMEM((bh, d, T), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, od)
    return dq, dk, dv, ()


def _bwd_t(q, k, v, o, lse_t, do, scale, causal, bq, bk, bh, t_real,
           interpret, dlse=None, window=0, biases=(), bias_cfgs=(),
           alibi_cfg=None):
    BH, d, T = q.shape
    lse = jnp.broadcast_to(lse_t, (BH, T, LSE_LANES))
    single_k = (T // bk) == 1
    if dlse is not None:
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1) - dlse.astype(jnp.float32)
        od = jnp.broadcast_to(delta[..., None], (BH, T, LSE_LANES))
    else:
        od = o
    db_specs, db_shapes = _dbias_out(biases, bias_cfgs, bk, T, bh, q)
    outs = pl.pallas_call(
        functools.partial(_bwd_kernel_t, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real,
                          ext_delta=dlse is not None, single_k=single_k,
                          window=window, bias_cfgs=bias_cfgs,
                          alibi_cfg=alibi_cfg),
        grid=(BH // bh, T // bk),
        in_specs=[
            pl.BlockSpec((bh, d, T), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, d, bk), lambda b, j: (b, 0, j)),
            pl.BlockSpec((bh, d, bk), lambda b, j: (b, 0, j)),
            pl.BlockSpec((bh, T, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, T, LSE_LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, T, LSE_LANES if dlse is not None else d),
                         lambda b, j: (b, 0, 0)),
        ] + _bwd_bias_specs(bias_cfgs, biases, bk, T, bh)
          + ([pl.BlockSpec((1, T, bk), lambda b, j: (0, 0, j))]
             if alibi_cfg else []),
        out_specs=[
            pl.BlockSpec((bh, d, T), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, d, bk), lambda b, j: (b, 0, j)),
            pl.BlockSpec((bh, d, bk), lambda b, j: (b, 0, j)),
        ] + db_specs,
        out_shape=[
            _sds((BH, d, T), q.dtype if single_k else jnp.float32, q),
            _sds((BH, d, T), q.dtype, q),
            _sds((BH, d, T), q.dtype, q),
        ] + db_shapes,
        interpret=interpret,
    )(q, k, v, do, lse, od, *biases)
    dq, dk, dv = outs[:3]
    dbiases = _scatter_dbias(biases, bias_cfgs, outs[3:])
    if alibi_cfg:
        # the trailing operand is the shared ALiBi position grid — a
        # constant with no gradient
        dbiases = dbiases + (jnp.zeros(biases[-1].shape,
                                       biases[-1].dtype),)
    if scale != 1.0:
        dq = dq * scale
    return dq.astype(q.dtype), dk, dv, dbiases


# ------------------------------------------------- blockwise (ring) variant
# Carry-in/carry-out blockwise flash step: one (q-chunk, kv-chunk) pair of a
# ring-attention schedule, chaining the running online-softmax state
# (m, l, acc) across chunk pairs instead of combining normalized partial
# outputs outside. The mask mode is STATIC per call — ``causal=True`` is the
# diagonal-causal pair (q and kv chunks share the same global offset),
# ``causal=False`` the fully-visible pair; fully-masked pairs are simply
# never called (sequence/ring.py computes the static schedule). The ring
# backward reuses the existing fused backward kernel per pair with the
# GLOBAL lse/o (``flash_block_bwd``), the standard flash-bwd recompute.

RING_TUNE_DEFAULTS = {"block_q": 128, "block_k": 128, "block_h": 2}


def _fwd_block_kernel(q_ref, k_ref, v_ref, mi_ref, li_ref, acci_ref,
                      mo_ref, lo_ref, acco_ref, *, bq, bk, causal, t_real):
    """_fwd_kernel with the softmax state as operands/results instead of
    locally initialized + finalized: m/l ride (G, bq, LSE_LANES) blocks
    (lane-replicated like lse), acc a (G, bq, d) fp32 block."""
    qi = pl.program_id(1)
    q = q_ref[...]                                        # (G, bq, d)
    G = q.shape[0]
    T = k_ref.shape[1]
    nk = T // bk
    kmax = pl.cdiv((qi + 1) * bq, bk) if causal else nk
    kfull = (qi * bq) // bk if (causal and t_real >= T) else (
        nk if (not causal and t_real >= T) else 0)
    m = mi_ref[...][..., 0]                               # (G, bq) f32
    l = li_ref[...][..., 0]
    acc = acci_ref[...]                                   # (G, bq, d) f32

    def make_body(masked):
        def body(j, carry):
            acc, m, l = carry
            kb = k_ref[:, pl.ds(j * bk, bk), :]
            vb = v_ref[:, pl.ds(j * bk, bk), :]
            s = jax.lax.dot_general(q, kb, _DN_QK,
                                    preferred_element_type=jnp.float32)
            if masked:
                s = _apply_mask(s, _mask_block(qi * bq, j * bk, bq, bk,
                                               causal, t_real, T))
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[..., None] + jax.lax.dot_general(
                p.astype(vb.dtype), vb, _DN_PV,
                preferred_element_type=jnp.float32)
            return acc, m_new, l
        return body

    carry = jax.lax.fori_loop(0, kfull, make_body(False), (acc, m, l))
    acc, m, l = jax.lax.fori_loop(kfull, kmax, make_body(True), carry)
    acco_ref[...] = acc
    mo_ref[...] = jnp.broadcast_to(m[..., None], (G, bq, mo_ref.shape[-1]))
    lo_ref[...] = jnp.broadcast_to(l[..., None], (G, bq, lo_ref.shape[-1]))


def _block_bh(block_h, BH):
    bh = max(1, min(block_h, BH))
    while BH % bh:
        bh -= 1
    return bh


def _block_pads(T, d, block_q, block_k):
    bq, bk, T_pad = _block_sizes(T, block_q, block_k)
    d_pad = _round_up(d, 64) if d <= 64 else _round_up(d, 128)
    return bq, bk, T_pad, d_pad


def flash_block_state(BH, T, d):
    """Fresh (m, l, acc) carry for ``flash_block_fwd``: per-query running
    max/sum-exp ((BH, T) fp32) and the unnormalized output accumulator
    ((BH, T, d) fp32)."""
    return (jnp.full((BH, T), NEG_INF, jnp.float32),
            jnp.zeros((BH, T), jnp.float32),
            jnp.zeros((BH, T, d), jnp.float32))


def flash_block_finalize(state):
    """(m, l, acc) -> (o fp32, lse fp32); call after the last chunk pair."""
    m, l, acc = state
    ls = jnp.clip(l, 1e-30, None)
    return acc / ls[..., None], m + jnp.log(ls)


def flash_block_fwd(q, k, v, state, *, causal=False, block_q=128,
                    block_k=128, block_h=2, interpret=None):
    """One ring chunk pair: q/k/v (BH, T, d) folded operands (q PRE-SCALED
    by the caller — the ring folds the softmax scale once), ``state`` from
    :func:`flash_block_state` (or a previous pair). Returns the updated
    state. ``causal=True`` = the diagonal-causal pair (equal chunk
    lengths, shared offset); fully-masked pairs must be skipped by the
    caller, that is the schedule's job."""
    BH, T, d = q.shape
    if k.shape[1] != T:
        raise ValueError(
            f"flash_block_fwd needs equal chunk lengths, got q {T} vs "
            f"kv {k.shape[1]} (the ring schedule pairs equal chunks)")
    if interpret is None:
        interpret = _interpret_default()
    m, l, acc = state
    bq, bk, T_pad, d_pad = _block_pads(T, d, block_q, block_k)
    bh = _block_bh(block_h, BH)

    def pad3(x):
        if T_pad == T and d_pad == d:
            return x
        return jnp.pad(x, ((0, 0), (0, T_pad - T), (0, d_pad - d)))

    def padl(x, fill):
        x = x if T_pad == T else jnp.pad(
            x, ((0, 0), (0, T_pad - T)), constant_values=fill)
        return jnp.broadcast_to(x[..., None], (BH, T_pad, LSE_LANES))

    grid = (BH // bh, T_pad // bq)
    mo, lo, acco = pl.pallas_call(
        functools.partial(_fwd_block_kernel, bq=bq, bk=bk, causal=causal,
                          t_real=T),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bh, bq, d_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, T_pad, d_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((bh, T_pad, d_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((bh, bq, LSE_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, bq, LSE_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, bq, d_pad), lambda b, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bh, bq, LSE_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, bq, LSE_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, bq, d_pad), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((BH, T_pad, LSE_LANES), jnp.float32, q),
            _sds((BH, T_pad, LSE_LANES), jnp.float32, q),
            _sds((BH, T_pad, d_pad), jnp.float32, q),
        ],
        interpret=interpret,
    )(pad3(q), pad3(k), pad3(v), padl(m, NEG_INF), padl(l, 0.0),
      pad3(acc))
    return (mo[..., 0][:, :T], lo[..., 0][:, :T], acco[:, :T, :d])


def flash_block_bwd(q, k, v, o, lse, do, *, causal=False, block_q=128,
                    block_k=128, block_h=2, interpret=None):
    """Ring chunk-pair backward via the existing fused backward kernel:
    given the GLOBAL per-query ``lse`` ((BH, T) fp32) and final ``o``, the
    kernel recomputes this pair's probabilities as exp(s - lse) and its
    in-VMEM delta = rowsum(do * o) IS the global delta, so (dq, dk, dv)
    are this pair's exact contributions. q pre-scaled like the forward."""
    BH, T, d = q.shape
    if interpret is None:
        interpret = _interpret_default()
    bq, bk, T_pad, d_pad = _block_pads(T, d, block_q, block_k)
    bh = _block_bh(block_h, BH)

    def pad3(x):
        if T_pad == T and d_pad == d:
            return x.astype(q.dtype) if x.dtype != q.dtype else x
        x = jnp.pad(x, ((0, 0), (0, T_pad - T), (0, d_pad - d)))
        return x.astype(q.dtype) if x.dtype != q.dtype else x

    lse_p = lse if T_pad == T else jnp.pad(lse, ((0, 0), (0, T_pad - T)))
    dq, dk, dv, _ = _bwd(pad3(q), pad3(k), pad3(v), pad3(o), lse_p[..., None],
                         pad3(do), 1.0, causal, bq, bk, bh, T, interpret)
    return dq[:, :T, :d], dk[:, :T, :d], dv[:, :T, :d]


# --------------------------------------------------------------- public API
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14,
                                    15, 16, 17))
def _flash(q, k, v, biases, scale, causal, bq, bk, bh, t_real, interpret,
           bwd_bq, bwd_bk, qkv_t=False, window=0, bias_cfgs=(),
           alibi_cfg=None, bwd_qmajor=False):
    fwd = _fwd_t if qkv_t else _fwd
    o, lse = fwd(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
                 window, biases, bias_cfgs, alibi_cfg)
    return o, lse[..., 0]


def _flash_fwd(q, k, v, biases, scale, causal, bq, bk, bh, t_real,
               interpret, bwd_bq, bwd_bk, qkv_t=False, window=0,
               bias_cfgs=(), alibi_cfg=None, bwd_qmajor=False):
    from jax.ad_checkpoint import checkpoint_name
    # symbolic_zeros=True wraps primal args in CustomVJPPrimal
    q, k, v = q.value, k.value, v.value
    biases = tuple(b.value for b in biases)
    fwd = _fwd_t if qkv_t else _fwd
    o, lse = fwd(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
                 window, biases, bias_cfgs, alibi_cfg)
    # Name o/lse HERE, inside the fwd rule, so the named vars are both
    # the primal outputs and the vjp residuals: under jax.checkpoint a
    # save-policy keeping 'flash_o'/'flash_lse' then satisfies the
    # backward's residual needs (q/k/v recompute from the cheap qkv
    # matmul) WITHOUT re-running this kernel — the remat re-run the
    # whole-block policies otherwise pay (~52 ms/step at 350M bs=24).
    # lse is trimmed to one lane so the saved residual is (BH, T, 1)
    # fp32 (keeping the full LSE_LANES block measured 80 ms/step WORSE
    # at 350M bs=24 — the fatter stacked residual perturbs XLA's
    # scheduling far beyond the ~8 ms relayout it saves).
    lse_t = lse[..., :1]
    o = checkpoint_name(o, "flash_o")
    lse_t = checkpoint_name(lse_t, "flash_lse")
    # q/k/v named as residuals too: the 'save_flash_qkv' policy keeps
    # them, so backward skips the ln1+qkv-projection recompute entirely
    # (at +3x48 MB/layer saved residuals; policies not listing these
    # names behave exactly as before)
    qr = checkpoint_name(q, "flash_q")
    kr = checkpoint_name(k, "flash_k")
    vr = checkpoint_name(v, "flash_v")
    return (o, lse_t[..., 0]), (qr, kr, vr, o, lse_t, biases)


def _flash_bwd(scale, causal, bq, bk, bh, t_real, interpret, bwd_bq,
               bwd_bk, qkv_t, window, bias_cfgs, alibi_cfg, bwd_qmajor,
               res, cts):
    # backward may run its own (smaller) blocks: the fused dq/dk/dv pass
    # is ~2x the forward's work, so causal above-diagonal skipping wins
    # more there than grid-step overhead costs
    bq, bk = bwd_bq or bq, bwd_bk or bk
    do, dlse = cts
    from jax.custom_derivatives import SymbolicZero
    # training drops the lse output -> its cotangent arrives symbolic
    # and the kernel takes the delta-from-o fast path
    if isinstance(dlse, SymbolicZero):
        dlse = None
    if isinstance(do, SymbolicZero):
        do = jnp.zeros(do.shape, do.dtype)
    q, k, v, o, lse_t, biases = res
    # lse is a real (differentiable) output: d lse_i / d s_ij = p_ij, so a
    # cotangent on lse enters the shared ds = p * (dp - delta) term as
    # ds += p * dlse — i.e. exactly a shift of delta by -dlse. Folding it
    # there costs zero extra kernel work.
    if bwd_qmajor and qkv_t and not biases and alibi_cfg is None:
        return _bwd_t_qmajor(
            q, k, v, o, lse_t, do, scale, causal, bq, bk, bh, t_real,
            interpret, dlse=dlse, window=window)
    bwd = _bwd_t if qkv_t else _bwd
    dq, dk, dv, dbiases = bwd(
        q, k, v, o, lse_t, do, scale, causal, bq, bk, bh, t_real,
        interpret, dlse=dlse, window=window, biases=biases,
        bias_cfgs=bias_cfgs, alibi_cfg=alibi_cfg)
    return dq, dk, dv, dbiases


_flash.defvjp(_flash_fwd, _flash_bwd, symbolic_zeros=True)


# o-only variant: training drops lse, but a custom_vjp output cannot be
# DCE'd out of the remat closed-call — the lane-trim slice alone measured
# ~6 ms/step at 350M bs=24. This twin never emits the lse output (the
# residual still saves it for the backward).
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14,
                                    15, 16, 17))
def _flash_o(q, k, v, biases, scale, causal, bq, bk, bh, t_real,
             interpret, bwd_bq, bwd_bk, qkv_t=False, window=0,
             bias_cfgs=(), alibi_cfg=None, bwd_qmajor=False):
    fwd = _fwd_t if qkv_t else _fwd
    o, _ = fwd(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
               window, biases, bias_cfgs, alibi_cfg)
    return o


def _flash_o_fwd(q, k, v, biases, scale, causal, bq, bk, bh, t_real,
                 interpret, bwd_bq, bwd_bk, qkv_t=False, window=0,
                 bias_cfgs=(), alibi_cfg=None, bwd_qmajor=False):
    (o, _), res = _flash_fwd(q, k, v, biases, scale, causal, bq, bk, bh,
                             t_real, interpret, bwd_bq, bwd_bk, qkv_t,
                             window, bias_cfgs, alibi_cfg, bwd_qmajor)
    return o, res


def _flash_o_bwd(scale, causal, bq, bk, bh, t_real, interpret, bwd_bq,
                 bwd_bk, qkv_t, window, bias_cfgs, alibi_cfg, bwd_qmajor,
                 res, do):
    from jax.custom_derivatives import SymbolicZero
    bq, bk = bwd_bq or bq, bwd_bk or bk
    if isinstance(do, SymbolicZero):
        do = jnp.zeros(do.shape, do.dtype)
    q, k, v, o, lse_t, biases = res
    if bwd_qmajor and qkv_t and not biases and alibi_cfg is None:
        return _bwd_t_qmajor(
            q, k, v, o, lse_t, do, scale, causal, bq, bk, bh, t_real,
            interpret, dlse=None, window=window)
    bwd = _bwd_t if qkv_t else _bwd
    dq, dk, dv, dbiases = bwd(
        q, k, v, o, lse_t, do, scale, causal, bq, bk, bh, t_real,
        interpret, dlse=None, window=window, biases=biases,
        bias_cfgs=bias_cfgs, alibi_cfg=alibi_cfg)
    return dq, dk, dv, dbiases


_flash_o.defvjp(_flash_o_fwd, _flash_o_bwd, symbolic_zeros=True)


def flash_attention_with_lse(q, k, v, *, causal=True, scale=None,
                             block_q=128, block_k=128, block_h=2,
                             interpret=None, heads_major=False,
                             block_q_bwd=None, block_k_bwd=None,
                             qkv_t=False, window=0, bias=None,
                             bias_grad=False, alibi=None,
                             alibi_scale=1.0, alibi_bf16=False,
                             bwd_qmajor=False, _folded_biases=None,
                             _with_lse=True):
    """Fused attention over (batch, seq, heads, head_dim) inputs, returning
    ``(o, lse)`` where lse is the per-query logsumexp, (B, H, T) fp32.

    ``heads_major=True``: inputs/outputs are (batch, heads, seq, head_dim)
    — the kernel's native layout. The fold becomes a pure reshape (no
    transpose), and no T-minor layout pressure propagates into the
    caller's matmuls (XLA otherwise warps the producing matmul's output
    layout to feed the custom call, costing ~2x on its emitter).

    Additive score biases (counterpart of the reference's bias-taking
    attention kernels — evoformer_attn kernel_forward.h:986 bias1/bias2,
    inference softmax.cu:562 alibi+mask):
      ``bias``: (B|1, H|1, T|1, T) added to the scaled scores before the
        softmax, never expanded to the (B, H, T, T) score shape (kernel
        operands carry only the given dims; the broadcast happens on
        score tiles in VMEM). WITHOUT ``bias_grad=True`` the bias is a
        CONSTANT (stop-gradient): differentiating through it yields
        zeros — set ``bias_grad=True`` for learned biases (evoformer
        pair bias), which makes the fused backward accumulate d_bias
        in-kernel.
      ``alibi``: (H,) ALiBi slopes — validated against the bloom formula
        and computed IN-KERNEL per score tile as slope_h * k_pos from
        iotas (softmax-shift-equivalent to the relative form): no HBM
        bias array at all, like the paged decode kernel.
        ``alibi_scale``/``alibi_bf16`` reproduce HF falcon's pre-scaling
        bf16-quantized variant (models/llama.py _alibi_bias).
      Masked positions (causal/window/padding) override any bias.

    Equivalent math to softmax(scale * q k^T + bias + causal_mask) v with
    fp32 accumulation, O(T) memory. Differentiable (custom flash
    backward). Sequences that don't divide the block sizes are
    zero-padded and the padded keys masked in-kernel (slicing the output
    transposes to zero-padded cotangents, so the backward stays correct).
    ``block_h`` (b, h) instances are processed per grid step (clamped to
    a divisor of batch*heads, and of any dim a bias shares).

    lse is exposed (rather than kept as a hidden vjp residual) so callers
    under ``jax.checkpoint`` can tag o/lse/q/k/v with ``checkpoint_name``
    and a save-policy can keep exactly the flash residuals — making the
    backward reuse them instead of recomputing the forward kernel.
    """
    if qkv_t:
        # transposed operands: (batch, heads, head_dim, seq) — the qkv
        # projection einsum's natural T-minor layout; the kernel consumes
        # it directly so no relayout copies exist at the call boundary
        B, H, d, T = q.shape
    elif heads_major:
        B, H, T, d = q.shape
    else:
        B, T, H, d = q.shape
    if _AUTO in (block_q, block_k, block_h, block_q_bwd, block_k_bwd,
                 bwd_qmajor):
        # measured dispatch: tunables set to "auto" take the cached
        # winner for this (device_kind, shape-bucket, dtype); explicit
        # values always win over the cache, and a miss falls back to
        # the r05-proven defaults. Trace-time only.
        win = _dispatch("flash_attention",
                        _flash_bucket(T, d, causal, qkv_t),
                        _dtype_name(q.dtype), TUNE_DEFAULTS)
        if block_q == _AUTO:
            block_q = int(win["block_q"])
        if block_k == _AUTO:
            block_k = int(win["block_k"])
        if block_h == _AUTO:
            block_h = int(win["block_h"])
        if block_q_bwd == _AUTO:
            block_q_bwd = int(win["block_q_bwd"]) or None
        if block_k_bwd == _AUTO:
            block_k_bwd = int(win["block_k_bwd"]) or None
        if bwd_qmajor == _AUTO:
            bwd_qmajor = bool(win["bwd_qmajor"])
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_default()
    bq, bk, T_pad = _block_sizes(T, block_q, block_k)
    # backward may use its own blocks; T must pad to a common multiple of
    # ALL block sizes or the backward grid would not cover every key
    # block (silently dropping dk/dv contributions)
    bwd_bq, bwd_bk, _ = _block_sizes(T, block_q_bwd or bq,
                                     block_k_bwd or bk)
    T_pad = _round_up(T, math.lcm(bq, bk, bwd_bq, bwd_bk))
    if qkv_t and any(x % 128 for x in (T_pad, bq, bk, bwd_bq, bwd_bk)):
        # In the transposed layout T (and every block) sits in the LANE
        # dim, which Mosaic requires in 128 units — shapes/blocks that
        # don't comply fall back to the standard kernel (one transpose;
        # correctness over the layout win at tiny T or small blocks)
        q, k, v = (jnp.swapaxes(x, -1, -2) for x in (q, k, v))
        return flash_attention_with_lse(
            q, k, v, causal=causal, scale=scale, block_q=block_q,
            block_k=block_k, block_h=block_h, interpret=interpret,
            heads_major=True, block_q_bwd=block_q_bwd,
            block_k_bwd=block_k_bwd, qkv_t=False, window=window,
            bias=bias, bias_grad=bias_grad, alibi=alibi,
            alibi_scale=alibi_scale, alibi_bf16=alibi_bf16,
            bwd_qmajor=False, _folded_biases=_folded_biases,
            _with_lse=_with_lse)

    # -------- bias descriptors -> bh constraints (before bh is picked)
    descs = []                                  # (arr4d, grad)
    alibi_cfg = None
    if alibi is not None:
        # the kernels evaluate the bloom slope formula in-kernel from
        # each instance's head index (_alibi_add); reject custom slopes
        # rather than silently ignoring them (the paged kernel's rule)
        from .paged_attention import alibi_slopes_formula
        expect = alibi_slopes_formula(H)
        got = [float(x) for x in np.asarray(alibi).reshape(-1)] \
            if not isinstance(alibi, (list, tuple)) else list(alibi)
        if len(got) != H or any(
                abs(a - b) > 1e-6 * max(abs(b), 1e-9)
                for a, b in zip(got, expect)):
            raise NotImplementedError(
                "flash_attention computes bloom-formula ALiBi slopes "
                "in-kernel; custom per-head slopes are not supported "
                "(pass them as a bias instead)")
        alibi_cfg = (bool(qkv_t), H, B, float(alibi_scale),
                     bool(alibi_bf16))
    if bias is not None:
        if bias.ndim != 4:
            raise ValueError(
                f"bias must be 4D (B|1, H|1, T|1, T); got {bias.shape}")
        Bb, Hb, Tqb, Tk = bias.shape
        if Bb not in (1, B) or Hb not in (1, H) or Tqb not in (1, T) \
                or Tk != T:
            raise ValueError(
                f"bias shape {bias.shape} not broadcastable to "
                f"({B}, {H}, {T}, {T})")
        descs.append((bias, bool(bias_grad)))
    constraints = [_bias_constraint(a.shape[0], a.shape[1], B, H, qkv_t)
                   for a, _ in descs]
    constraints += [c for _, c, _ in (_folded_biases or [])]
    if descs or _folded_biases or alibi_cfg is not None:
        # bias blocks (and the ALiBi position grid) carry the key dim in
        # LANES; in-kernel dynamic lane offsets must be 128-aligned on
        # Mosaic, so multi-block key walks need 128-multiple key blocks
        # (single-block refs load statically). Fixpoint: rounding one
        # pass's block can grow T_pad and turn the OTHER pass's block
        # multi-block — recheck until both are either single-block or
        # 128-aligned.
        while True:
            T_pad = _round_up(T, math.lcm(bq, bk, bwd_bq, bwd_bk))
            if bk < T_pad and bk % 128:
                bk = _round_up(bk, 128)
            elif bwd_bk < T_pad and bwd_bk % 128:
                bwd_bk = _round_up(bwd_bk, 128)
            else:
                break

    bh = max(1, min(block_h, B * H))
    if alibi_cfg is not None:
        bh = 1          # scalar-slope ALiBi path (see _alibi_add)
    while (B * H) % bh or any(c is not None and c % bh
                              for c in constraints):
        bh -= 1
    # TPU tiling wants the lane (last) dim in 64/128 units: zero-pad other
    # head dims (zero columns add 0 to scores and produce zero output
    # columns, and zero cotangent columns backward — exact). d <= 64 pads
    # to 64, kept native: the smaller DMA footprint beats the MXU's
    # preference for 128 (evoformer's d=32 pays 2x, not 4x). The rule
    # applies under qkv_t too: d moves to sublanes for q/k/v but stays
    # the lane dim of the o output block.
    d_pad = _round_up(d, 64) if d <= 64 else _round_up(d, 128)

    def fold(x):
        if qkv_t:
            # flatten (H, B) — not (B, H): XLA lays the qkv einsum output
            # out with b inner of the two (b stride < h stride), so the
            # (H*B) flatten is a free bitcast while (B*H) is an interleave
            # copy (~1 ms/layer/tensor at 350M). The kernel's G dim is
            # order-agnostic.
            x = jnp.swapaxes(x, 0, 1).reshape(H * B, d, T)
            if T_pad != T or d_pad != d:
                x = jnp.pad(x, ((0, 0), (0, d_pad - d), (0, T_pad - T)))
            return x
        if not heads_major:
            x = x.transpose(0, 2, 1, 3)
        x = x.reshape(B * H, T, d)
        if T_pad != T or d_pad != d:
            x = jnp.pad(x, ((0, 0), (0, T_pad - T), (0, d_pad - d)))
        return x

    # -------- fold + pad biases; build their static cfgs
    biases_folded, cfgs = [], []
    for arr, grad in descs:
        Bb, Hb, Tqb, Tk = arr.shape
        if Tqb == 1:
            # Expand query-broadcast biases (key masks, ALiBi) to a full
            # query dim: a size-1 sublane broadcast inside the softmax
            # carry loop crashes Mosaic's layout inference (verified on
            # v5e — see _fwd_bias_specs). Costs (rows, T, T) HBM for
            # what is logically (rows, T); acceptable at mask/ALiBi
            # scales and still far below the dense path's (B, H, T, T)
            # score materialization.
            arr = jnp.broadcast_to(arr, (Bb, Hb, T, Tk))
            Tqb = T
        cfg = _bias_cfg(Bb, Hb, B, H, bh, True, grad, bool(qkv_t))
        if qkv_t and Bb == B and Hb == H:
            arr = arr.swapaxes(0, 1)     # match the kernels' (H, B) fold
        f = arr.reshape(Bb * Hb, Tqb, Tk)
        if Tk != T_pad or Tqb != T_pad:
            f = jnp.pad(f, ((0, 0), (0, T_pad - Tqb),
                            (0, T_pad - Tk)))
        biases_folded.append(f)
        cfgs.append(cfg)
    for arr, _c, cfg_fn in (_folded_biases or []):
        # pre-folded biases (the evoformer adapter): 3D (rows, Tq, Tk),
        # full query dim required (expand upstream — see above)
        cfg = cfg_fn(bh)
        rows, Tqb, Tk = arr.shape
        if Tqb != T:
            raise ValueError(
                f"folded bias must carry a full query dim ({T}); got "
                f"{arr.shape} — expand query-broadcast biases upstream")
        if Tk != T_pad or Tqb != T_pad:
            arr = jnp.pad(arr, ((0, 0), (0, T_pad - Tqb),
                                (0, T_pad - Tk)))
        biases_folded.append(arr)
        cfgs.append(cfg)
    for cfg in cfgs:
        if cfg[_B_GRAD]:
            # grad accumulation relies on the row map visiting each bias
            # block in one contiguous run (per_rows==1) or exactly once
            # (per_rows==bh) — check statically over the real grid
            fs = [_bias_row(cfg, bh, g) for g in range((B * H) // bh)]
            mono = all(a <= b for a, b in zip(fs, fs[1:]))
            if cfg[_B_PER] != 1:
                mono = mono and len(set(fs)) == len(fs)
            if not mono:
                raise ValueError(
                    "bias_grad unsupported for this broadcast pattern "
                    "(bias rows revisited non-contiguously across the "
                    "grid); materialize the bias per (batch, head) "
                    "instead")

    if alibi_cfg is not None:
        # shared ALiBi position grid P[i, j] = j, one O(T^2) f32 array
        # for ALL (batch, head) instances — the kernels scale it by the
        # per-instance slope in VMEM (see _alibi_add)
        biases_folded.append(jnp.broadcast_to(
            jnp.arange(T_pad, dtype=jnp.float32)[None, :],
            (T_pad, T_pad))[None])

    # fold the softmax scale into q OUTSIDE the kernel (and the custom_vjp,
    # so autodiff chains dq): one (BH, T, d) multiply instead of a
    # per-score-element multiply inside a VPU-bound kernel
    if window and not causal:
        raise ValueError("sliding window requires causal attention")
    q = q * jnp.asarray(scale, q.dtype)
    # q-major backward: transposed-operand, bias-free paths only (the
    # biased kernels need the k-major dbias accumulation structure)
    qmaj = bool(bwd_qmajor) and bool(qkv_t) and not biases_folded \
        and alibi_cfg is None
    args = (fold(q), fold(k), fold(v), tuple(biases_folded), 1.0,
            bool(causal), bq, bk, bh, T, bool(interpret), bwd_bq, bwd_bk,
            bool(qkv_t), int(window), tuple(cfgs), alibi_cfg, qmaj)
    if _with_lse:
        o, lse = _flash(*args)
    else:
        # o-only twin: a custom_vjp output can't be DCE'd out of the
        # remat closed-call, so the dropped lse (and its lane-trim
        # slice, ~6 ms/step at 350M) must never be emitted at all
        o, lse = _flash_o(*args), None
    if T_pad != T or d_pad != d:
        o = o[:, :T, :d]
        lse = lse[:, :T] if lse is not None else None
    if qkv_t:
        # (H, B, ...) is the kernel's fold order; swap back to the
        # conventional (B, H, ...). (Exposing the (H, B, ...) form to the
        # caller measured neutral at 350M: it removes this interleave
        # copy but the hbte wo einsum pays it back in a worse emitter.)
        o = o.reshape(H, B, T, d).swapaxes(0, 1)
        return o, (lse.reshape(H, B, T).swapaxes(0, 1)
                   if lse is not None else None)
    o = o.reshape(B, H, T, d)
    if not heads_major:
        o = o.transpose(0, 2, 1, 3)
    return o, lse.reshape(B, H, T) if lse is not None else None


def flash_attention(q, k, v, *, causal=True, scale=None, block_q=128,
                    block_k=128, block_h=2, interpret=None,
                    heads_major=False, block_q_bwd=None,
                    block_k_bwd=None, qkv_t=False, window=0, bias=None,
                    bias_grad=False, alibi=None, alibi_scale=1.0,
                    alibi_bf16=False, bwd_qmajor=False,
                    _folded_biases=None):
    """Fused attention over (batch, seq, heads, head_dim); see
    :func:`flash_attention_with_lse` (this never emits the lse output).
    ``window`` > 0 = mistral sliding-window attention (causal only);
    ``bias``/``alibi`` = additive score biases (ALiBi, padding masks,
    pair biases) applied in-kernel. ``bwd_qmajor``: query-major fused
    backward (dq written once in the model dtype, dk/dv VMEM-resident;
    qkv_t bias-free paths only — silently k-major otherwise)."""
    o, _ = flash_attention_with_lse(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, block_h=block_h, interpret=interpret,
        heads_major=heads_major, block_q_bwd=block_q_bwd,
        block_k_bwd=block_k_bwd, qkv_t=qkv_t, window=window, bias=bias,
        bias_grad=bias_grad, alibi=alibi, alibi_scale=alibi_scale,
        alibi_bf16=alibi_bf16, bwd_qmajor=bwd_qmajor,
        _folded_biases=_folded_biases, _with_lse=False)
    return o


def attention_reference(q, k, v, *, causal=True, scale=None, bias=None):
    """Dense reference used by parity tests (same fp32 score math).
    ``bias``: (B|1, H|1, T|1, T) additive, pre-mask."""
    B, T, H, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bthd,bshd->bhts", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p.astype(q.dtype), v)
