"""Fused causal attention (flash attention) as a Pallas TPU kernel.

Counterpart of the reference's fused attention kernels: training softmax
(csrc/transformer/softmax_kernels.cu), inference attention
(csrc/transformer/inference/csrc/softmax.cu) and the memory-efficient
Evoformer kernel (csrc/deepspeed4science/evoformer_attn/) — all of which
exist because materializing the (T, T) score matrix is HBM-bound. Same
motivation here: the online-softmax streaming form never materializes
scores, so HBM traffic drops from O(T^2) to O(T * d) per head and the MXU
stays busy on the two matmuls.

Layout: (batch, seq, heads, head_dim) at the API (the model's layout).
Kernels process a GROUP of ``block_h`` (batch*head) instances per grid step
as batched dots — at GPT-2 head dims (64..128) a single head's (bq, d) x
(d, bk) dot is far too little work per grid step, and the sequential TPU
grid makes per-step overhead (DMA issue, semaphores) the bottleneck;
batching heads amortizes it. The MXU path keeps q/k/v/p in bf16 with fp32
accumulation (fp32 dot inputs run the MXU at 1/8 rate); softmax
bookkeeping stays fp32 on the VPU. The backward recomputes attention
probabilities from the saved logsumexp instead of storing them (the
standard flash backward).

Off-TPU (unit tests / dryrun) the kernels run in Pallas interpreter mode.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import interpret_default as _interpret_default
from ._common import round_up as _round_up
from ._common import sds as _sds


def _block_sizes(T, block_q, block_k):
    """Pick block sizes and the padded sequence length.

    Any T works: rather than shrinking blocks to a divisor of T (which
    degenerates to tiny blocks that violate the TPU (8,128) tiling and
    explode the grid for prime T), the sequence is padded up to a common
    multiple of the blocks and padded keys are masked in-kernel."""
    bq = min(block_q, _round_up(T, 8))
    bk = min(block_k, _round_up(T, 8))
    T_pad = _round_up(T, math.lcm(bq, bk))
    return bq, bk, T_pad


NEG_INF = -1e30

# Trailing lane dim for per-row scalar tensors (lse, delta). Per-row
# scalars are not 2D-tileable at head-group sizes < 8, so they carry a
# small replicated lane dim. 8 lanes (not 128): the value lives in
# sublanes either side of the HBM round trip, so no in-kernel relayout,
# and the HBM footprint/traffic is 16x smaller than a full 128-lane
# block (201 MB -> 12.6 MB fp32 at 350M bs=24 shapes).
LSE_LANES = 8

# batched dot helpers: x (G, a, c) contract c against y's dim, batch over G
_DN_QK = (((2,), (2,)), ((0,), (0,)))    # (G,bq,d) x (G,bk,d) -> (G,bq,bk)
_DN_PV = (((2,), (1,)), ((0,), (0,)))    # (G,bq,bk) x (G,bk,d) -> (G,bq,d)
_DN_T = (((1,), (1,)), ((0,), (0,)))     # (G,bq,bk) x (G,bq,d) -> (G,bk,d)
# transposed-operand variants (q/k/v carried as (G, d, T) blocks, i.e. T in
# lanes — the layout the surrounding einsums prefer; see *_kernel_t)
_DN_QK_T = (((1,), (1,)), ((0,), (0,)))  # (G,d,bq) x (G,d,bk) -> (G,bq,bk)
_DN_PV_T = (((2,), (2,)), ((0,), (0,)))  # (G,bq,bk) x (G,d,bk) -> (G,bq,d)
_DN_DO_V = (((2,), (1,)), ((0,), (0,)))  # (G,bq,d) x (G,d,bk) -> (G,bq,bk)
_DN_DV_T = (((1,), (1,)), ((0,), (0,)))  # (G,bq,d) x (G,bq,bk) -> (G,d,bk)
_DN_DK_T = (((2,), (1,)), ((0,), (0,)))  # (G,d,bq) x (G,bq,bk) -> (G,d,bk)
_DN_DQ_T = (((2,), (2,)), ((0,), (0,)))  # (G,d,bk) x (G,bq,bk) -> (G,d,bq)


def _mask_block(qi_start, kj_start, bq, bk, causal, t_real, T,
                window=0):
    """(bq, bk) boolean mask for causal / padded-key / sliding-window
    masking; None when none applies (static no-op)."""
    if not causal and t_real >= T and not window:
        return None
    qpos = qi_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = kj_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    ok = None
    if causal:
        ok = qpos >= kpos
    if window:
        win = qpos - kpos < window
        ok = win if ok is None else jnp.logical_and(ok, win)
    if t_real < T:
        valid = kpos < t_real
        ok = valid if ok is None else jnp.logical_and(ok, valid)
    return ok


def _apply_mask(s, ok):
    """s: (G, bq, bk); ok: (bq, bk) or None."""
    if ok is None:
        return s
    return jnp.where(ok[None], s, NEG_INF)


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, bq, bk, scale,
                causal, t_real, window=0):
    qi = pl.program_id(1)
    q = q_ref[...]                                        # (G, bq, d) bf16
    G = q.shape[0]
    T = k_ref.shape[1]
    nk = T // bk
    # causal: query block qi attends k blocks 0..ceil((qi+1)*bq / bk)-1.
    # Blocks fully below the diagonal skip mask generation entirely (the
    # iota/compare/select per element is real VPU cost in a VPU-bound
    # kernel); only the straddling blocks mask. With padded keys
    # (t_real < T) every block takes the masked path.
    kmax = pl.cdiv((qi + 1) * bq, bk) if causal else nk
    kfull = (qi * bq) // bk if (causal and t_real >= T) else (
        nk if (not causal and t_real >= T) else 0)
    kmin = 0
    if window:
        # blocks entirely below the window's lower edge are dead; every
        # live block takes the masked path (the window edge can cross
        # any of them)
        kmin = jnp.maximum(0, (qi * bq - window + 1) // bk)
        kfull = kmin

    def make_body(masked):
        def body(j, carry):
            acc, m, l = carry
            kb = k_ref[:, pl.ds(j * bk, bk), :]
            vb = v_ref[:, pl.ds(j * bk, bk), :]
            s = jax.lax.dot_general(q, kb, _DN_QK,
                                    preferred_element_type=jnp.float32)
            if scale != 1.0:
                s = s * scale
            if masked:
                s = _apply_mask(s, _mask_block(qi * bq, j * bk, bq, bk,
                                               causal, t_real, T,
                                               window))
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[..., None] + jax.lax.dot_general(
                p.astype(vb.dtype), vb, _DN_PV,
                preferred_element_type=jnp.float32)
            return acc, m_new, l
        return body

    d = q_ref.shape[-1]
    acc = jnp.zeros((G, bq, d), jnp.float32)
    m = jnp.full((G, bq), NEG_INF, jnp.float32)
    l = jnp.zeros((G, bq), jnp.float32)
    carry = jax.lax.fori_loop(kmin, kfull, make_body(False), (acc, m, l))
    acc, m, l = jax.lax.fori_loop(kfull, kmax, make_body(True), carry)
    o_ref[...] = (acc / l[..., None]).astype(o_ref.dtype)
    # lse replicated across LSE_LANES lanes (see constant above); the
    # wrapper trims to one lane before anything is saved
    lse_ref[...] = jnp.broadcast_to((m + jnp.log(l))[..., None],
                                    (G, bq, lse_ref.shape[-1]))


def _fwd(q, k, v, scale, causal, bq, bk, bh, t_real, interpret, window=0):
    BH, T, d = q.shape
    grid = (BH // bh, T // bq)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real, window=window),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bh, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, T, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((bh, T, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bh, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, bq, LSE_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((BH, T, d), q.dtype, q),
            _sds((BH, T, LSE_LANES), jnp.float32, q),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ------------------------------------------------- forward, transposed q/k/v
def _fwd_kernel_t(q_ref, k_ref, v_ref, o_ref, lse_ref, *, bq, bk, scale,
                  causal, t_real, window=0):
    """Forward with q/k/v blocked (G, d, T) — T in lanes.

    The surrounding qkv projection einsums emit T-minor layouts (hd=64
    fills only half a 128-lane register, so XLA puts T in lanes); the
    standard (G, T, d) operand forces a relayout copy per tensor per
    layer (~46 ms/step at 350M bs=24 counting forward, remat recompute
    and backward). Consuming the producer's layout directly makes those
    copies bitcasts. Score-space math is IDENTICAL to _fwd_kernel —
    softmax stats stay (G, bq) sublane vectors — only the q/k dots
    contract the sublane dim (MXU-native transposed matmul) and the pv
    dot contracts lanes x lanes. Output o stays (G, bq, d): its consumer
    (the wo projection) takes it without a copy either way."""
    qi = pl.program_id(1)
    q = q_ref[...]                                        # (G, d, bq) bf16
    G = q.shape[0]
    T = k_ref.shape[2]
    nk = T // bk
    kmax = pl.cdiv((qi + 1) * bq, bk) if causal else nk
    kfull = (qi * bq) // bk if (causal and t_real >= T) else (
        nk if (not causal and t_real >= T) else 0)
    kmin = 0
    if window:
        kmin = jnp.maximum(0, (qi * bq - window + 1) // bk)
        kfull = kmin

    def make_body(masked):
        def body(j, carry):
            acc, m, l = carry
            kb = k_ref[:, :, pl.ds(j * bk, bk)]
            vb = v_ref[:, :, pl.ds(j * bk, bk)]
            s = jax.lax.dot_general(q, kb, _DN_QK_T,
                                    preferred_element_type=jnp.float32)
            if scale != 1.0:
                s = s * scale
            if masked:
                s = _apply_mask(s, _mask_block(qi * bq, j * bk, bq, bk,
                                               causal, t_real, T,
                                               window))
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[..., None] + jax.lax.dot_general(
                p.astype(vb.dtype), vb, _DN_PV_T,
                preferred_element_type=jnp.float32)
            return acc, m_new, l
        return body

    d = q_ref.shape[1]
    acc = jnp.zeros((G, bq, d), jnp.float32)
    m = jnp.full((G, bq), NEG_INF, jnp.float32)
    l = jnp.zeros((G, bq), jnp.float32)
    carry = jax.lax.fori_loop(kmin, kfull, make_body(False), (acc, m, l))
    acc, m, l = jax.lax.fori_loop(kfull, kmax, make_body(True), carry)
    o_ref[...] = (acc / l[..., None]).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to((m + jnp.log(l))[..., None],
                                    (G, bq, lse_ref.shape[-1]))


def _fwd_t(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
           window=0):
    BH, d, T = q.shape
    grid = (BH // bh, T // bq)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_t, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real, window=window),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bh, d, bq), lambda b, i: (b, 0, i)),
            pl.BlockSpec((bh, d, T), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((bh, d, T), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bh, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bh, bq, LSE_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((BH, T, d), q.dtype, q),
            _sds((BH, T, LSE_LANES), jnp.float32, q),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ----------------------------------------------------------------- backward
def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, od_ref,
                dq_ref, dk_ref, dv_ref, *, bq, bk, scale, causal, t_real,
                ext_delta, single_k, window=0):
    """Fused flash backward: dq, dk, dv from ONE s/p computation.

    Grid is (BH/bh, T/bk) over key blocks; an inner loop walks the query
    blocks this key block attends. The two-kernel formulation (separate
    dq and dk/dv passes, as in the reference's backward and round 2
    here) computes s = q k^T and p = exp(s - lse) TWICE; fusing halves
    the score-matrix work — the dominant VPU+MXU cost of the backward.

    dq accumulates ACROSS grid steps in a VMEM-resident fp32 block (the
    TPU grid is sequential; the constant-index output block persists),
    initialized at the first key block. dk/dv accumulate in registers
    over the inner loop.
    """
    ki = pl.program_id(1)
    kb = k_ref[...]                                         # (G, bk, d) bf16
    G = kb.shape[0]
    vb = v_ref[...]
    T = q_ref.shape[1]
    nq = T // bq
    qmin = (ki * bk) // bq if causal else 0
    # q blocks straddling the diagonal need the causal mask; blocks fully
    # below it don't. With padded keys every block masks.
    qfull = pl.cdiv((ki + 1) * bk, bq) if (causal and t_real >= T) else (
        qmin if t_real >= T else nq)
    qend = nq
    if window:
        # highest q position attending this key block: (ki+1)*bk - 2 +
        # window; blocks above are dead, and every live block masks
        qend = jnp.minimum(nq, ((ki + 1) * bk - 2 + window) // bq + 1)
        qfull = qend

    if not single_k:
        @pl.when(ki == 0)
        def _init():
            dq_ref[...] = jnp.zeros_like(dq_ref)

    def make_body(masked):
        def body(i, carry):
            dk, dv = carry
            q = q_ref[:, pl.ds(i * bq, bq), :]
            do = do_ref[:, pl.ds(i * bq, bq), :]
            lse = lse_ref[:, pl.ds(i * bq, bq), :][..., 0]  # (G, bq)
            if ext_delta:
                # od_ref carries a precomputed (broadcast) delta — the
                # lse-cotangent path folds its shift in outside
                delta = od_ref[:, pl.ds(i * bq, bq), :][..., 0]
            else:
                # od_ref is o: delta = rowsum(do * o), computed on the
                # VPU from blocks already resident — no (BH, T, 128)
                # broadcast materialization, no separate reduce pass
                ob = od_ref[:, pl.ds(i * bq, bq), :]
                delta = jnp.sum(do.astype(jnp.float32)
                                * ob.astype(jnp.float32), axis=-1)
            s = jax.lax.dot_general(q, kb, _DN_QK,
                                    preferred_element_type=jnp.float32)
            if scale != 1.0:
                s = s * scale
            if masked:
                s = _apply_mask(s, _mask_block(i * bq, ki * bk, bq, bk,
                                               causal, t_real, T,
                                               window))
            p = jnp.exp(s - lse[..., None])                 # (G, bq, bk) f32
            pb = p.astype(do.dtype)
            dv = dv + jax.lax.dot_general(pb, do, _DN_T,
                                          preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, vb, _DN_QK,
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta[..., None])).astype(q.dtype)
            dk = dk + jax.lax.dot_general(ds, q, _DN_T,
                                          preferred_element_type=jnp.float32)
            dq_val = jax.lax.dot_general(ds, kb, _DN_PV,
                                         preferred_element_type=jnp.float32)
            if single_k:
                # one key block: each dq slice is written exactly once, so
                # the output can be emitted in the model dtype directly —
                # no fp32 (BH, T, d) HBM buffer + cast copy outside
                dq_ref[:, pl.ds(i * bq, bq), :] = dq_val.astype(dq_ref.dtype)
            else:
                dq_ref[:, pl.ds(i * bq, bq), :] += dq_val
            return dk, dv
        return body

    d = q_ref.shape[-1]
    dk = jnp.zeros((G, bk, d), jnp.float32)
    dv = jnp.zeros((G, bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(qmin, qfull, make_body(True), (dk, dv))
    dk, dv = jax.lax.fori_loop(qfull, qend, make_body(False), (dk, dv))
    # ds was computed from unscaled-q dots (scale applied to s post-dot),
    # so dk needs the scale factor once here (dq's lands in the wrapper)
    if scale != 1.0:
        dk = dk * scale
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse_t, do, scale, causal, bq, bk, bh, t_real,
         interpret, dlse=None, window=0):
    BH, T, d = q.shape
    # (BH, T, 1) -> LSE_LANES lanes for the operand block; XLA lowers
    # this to one small relayout/broadcast per layer (~8 ms/step total)
    lse = jnp.broadcast_to(lse_t, (BH, T, LSE_LANES))
    if dlse is not None:
        # lse cotangent shifts delta (see _flash_bwd): precompute the
        # shifted delta outside and broadcast to the operand lanes
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1) - dlse.astype(jnp.float32)
        od = jnp.broadcast_to(delta[..., None], (BH, T, LSE_LANES))
    else:
        # common case (lse output unused): the kernel computes delta
        # from o/do blocks in VMEM — no broadcast materialization
        od = o
    single_k = (T // bk) == 1
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real,
                          ext_delta=dlse is not None, single_k=single_k,
                          window=window),
        grid=(BH // bh, T // bk),
        in_specs=[
            pl.BlockSpec((bh, T, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((bh, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((bh, T, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, T, LSE_LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, T, LSE_LANES if dlse is not None else d),
                         lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bh, T, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((bh, bk, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            # dq accumulates fp32 across key-block grid steps; with a
            # single key block each slice is written once, so it is
            # emitted in the model dtype with no cast copy
            _sds((BH, T, d), q.dtype if single_k else jnp.float32, q),
            _sds((BH, T, d), q.dtype, q),
            _sds((BH, T, d), q.dtype, q),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, od)
    if scale != 1.0:
        dq = dq * scale
    return dq.astype(q.dtype), dk, dv


# ------------------------------------------------ backward, transposed q/k/v
def _bwd_kernel_t(q_ref, k_ref, v_ref, do_ref, lse_ref, od_ref,
                  dq_ref, dk_ref, dv_ref, *, bq, bk, scale, causal, t_real,
                  ext_delta, single_k, window=0):
    """Fused backward with q/k/v, do AND dq/dk/dv blocked (G, d, T).

    Same structure as _bwd_kernel (key-block grid, inner loop over query
    blocks, one s/p computation feeding dq+dk+dv), with every seq-major
    tensor consumed/produced T-in-lanes so the surrounding einsums'
    preferred layouts connect via bitcasts, not copies.

    do and o stay in the natural (G, T, d) layout — the forward emits o
    that way and the cotangent arrives the same way — keeping
    delta = rowsum(do * o) a lane reduction (sublane-vector result).
    Measured alternatives at 350M bs=24 (both kept the step SLOWER):
    do consumed (G, d, T) + delta precomputed outside (+8 ms: the
    delta fusion/broadcast outweighs the saved do relayout), and the
    in-kernel softmax identity delta = sum_j p_ij dp_ij (+11 ms VPU in
    an already-VPU-bound kernel). ext_delta (as in _bwd_kernel): False = in-kernel
    rowsum(do * o) with od_ref carrying o; True = precomputed delta via
    od_ref (the lse-cotangent path folds -dlse in outside).
    """
    ki = pl.program_id(1)
    kb = k_ref[...]                                         # (G, d, bk)
    G = kb.shape[0]
    vb = v_ref[...]
    T = q_ref.shape[2]
    nq = T // bq
    qmin = (ki * bk) // bq if causal else 0
    qfull = pl.cdiv((ki + 1) * bk, bq) if (causal and t_real >= T) else (
        qmin if t_real >= T else nq)
    qend = nq
    if window:
        qend = jnp.minimum(nq, ((ki + 1) * bk - 2 + window) // bq + 1)
        qfull = qend

    if not single_k:
        @pl.when(ki == 0)
        def _init():
            dq_ref[...] = jnp.zeros_like(dq_ref)

    def make_body(masked):
        def body(i, carry):
            dk, dv = carry
            q = q_ref[:, :, pl.ds(i * bq, bq)]              # (G, d, bq)
            do = do_ref[:, pl.ds(i * bq, bq), :]            # (G, bq, d)
            lse = lse_ref[:, pl.ds(i * bq, bq), :][..., 0]  # (G, bq)
            if ext_delta:
                delta = od_ref[:, pl.ds(i * bq, bq), :][..., 0]
            else:
                ob = od_ref[:, pl.ds(i * bq, bq), :]        # (G, bq, d)
                delta = jnp.sum(do.astype(jnp.float32)
                                * ob.astype(jnp.float32), axis=-1)
            s = jax.lax.dot_general(q, kb, _DN_QK_T,
                                    preferred_element_type=jnp.float32)
            if scale != 1.0:
                s = s * scale
            if masked:
                s = _apply_mask(s, _mask_block(i * bq, ki * bk, bq, bk,
                                               causal, t_real, T,
                                               window))
            p = jnp.exp(s - lse[..., None])                 # (G, bq, bk) f32
            pb = p.astype(do.dtype)
            dv = dv + jax.lax.dot_general(do, pb, _DN_DV_T,
                                          preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, vb, _DN_DO_V,
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta[..., None])).astype(q.dtype)
            dk = dk + jax.lax.dot_general(q, ds, _DN_DK_T,
                                          preferred_element_type=jnp.float32)
            dq_val = jax.lax.dot_general(kb, ds, _DN_DQ_T,
                                         preferred_element_type=jnp.float32)
            if single_k:
                dq_ref[:, :, pl.ds(i * bq, bq)] = dq_val.astype(dq_ref.dtype)
            else:
                dq_ref[:, :, pl.ds(i * bq, bq)] += dq_val
            return dk, dv
        return body

    d = q_ref.shape[1]
    dk = jnp.zeros((G, d, bk), jnp.float32)
    dv = jnp.zeros((G, d, bk), jnp.float32)
    dk, dv = jax.lax.fori_loop(qmin, qfull, make_body(True), (dk, dv))
    dk, dv = jax.lax.fori_loop(qfull, qend, make_body(False), (dk, dv))
    if scale != 1.0:
        dk = dk * scale
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_t(q, k, v, o, lse_t, do, scale, causal, bq, bk, bh, t_real,
           interpret, dlse=None, window=0):
    BH, d, T = q.shape
    lse = jnp.broadcast_to(lse_t, (BH, T, LSE_LANES))
    single_k = (T // bk) == 1
    if dlse is not None:
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1) - dlse.astype(jnp.float32)
        od = jnp.broadcast_to(delta[..., None], (BH, T, LSE_LANES))
    else:
        od = o
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel_t, bq=bq, bk=bk, scale=scale,
                          causal=causal, t_real=t_real,
                          ext_delta=dlse is not None, single_k=single_k,
                          window=window),
        grid=(BH // bh, T // bk),
        in_specs=[
            pl.BlockSpec((bh, d, T), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, d, bk), lambda b, j: (b, 0, j)),
            pl.BlockSpec((bh, d, bk), lambda b, j: (b, 0, j)),
            pl.BlockSpec((bh, T, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, T, LSE_LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, T, LSE_LANES if dlse is not None else d),
                         lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bh, d, T), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((bh, d, bk), lambda b, j: (b, 0, j)),
            pl.BlockSpec((bh, d, bk), lambda b, j: (b, 0, j)),
        ],
        out_shape=[
            _sds((BH, d, T), q.dtype if single_k else jnp.float32, q),
            _sds((BH, d, T), q.dtype, q),
            _sds((BH, d, T), q.dtype, q),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, od)
    if scale != 1.0:
        dq = dq * scale
    return dq.astype(q.dtype), dk, dv


# --------------------------------------------------------------- public API
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13))
def _flash(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
           bwd_bq, bwd_bk, qkv_t=False, window=0):
    fwd = _fwd_t if qkv_t else _fwd
    o, lse = fwd(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
                 window)
    return o, lse[..., 0]


def _flash_fwd(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
               bwd_bq, bwd_bk, qkv_t=False, window=0):
    from jax.ad_checkpoint import checkpoint_name
    # symbolic_zeros=True wraps primal args in CustomVJPPrimal
    q, k, v = q.value, k.value, v.value
    fwd = _fwd_t if qkv_t else _fwd
    o, lse = fwd(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
                 window)
    # Name o/lse HERE, inside the fwd rule, so the named vars are both
    # the primal outputs and the vjp residuals: under jax.checkpoint a
    # save-policy keeping 'flash_o'/'flash_lse' then satisfies the
    # backward's residual needs (q/k/v recompute from the cheap qkv
    # matmul) WITHOUT re-running this kernel — the remat re-run the
    # whole-block policies otherwise pay (~52 ms/step at 350M bs=24).
    # lse is trimmed to one lane so the saved residual is (BH, T, 1)
    # fp32 (keeping the full LSE_LANES block measured 80 ms/step WORSE
    # at 350M bs=24 — the fatter stacked residual perturbs XLA's
    # scheduling far beyond the ~8 ms relayout it saves).
    lse_t = lse[..., :1]
    o = checkpoint_name(o, "flash_o")
    lse_t = checkpoint_name(lse_t, "flash_lse")
    return (o, lse_t[..., 0]), (q, k, v, o, lse_t)


def _flash_bwd(scale, causal, bq, bk, bh, t_real, interpret, bwd_bq,
               bwd_bk, qkv_t, window, res, cts):
    # backward may run its own (smaller) blocks: the fused dq/dk/dv pass
    # is ~2x the forward's work, so causal above-diagonal skipping wins
    # more there than grid-step overhead costs
    bq, bk = bwd_bq or bq, bwd_bk or bk
    do, dlse = cts
    from jax.custom_derivatives import SymbolicZero
    # training drops the lse output -> its cotangent arrives symbolic
    # and the kernel takes the delta-from-o fast path
    if isinstance(dlse, SymbolicZero):
        dlse = None
    if isinstance(do, SymbolicZero):
        do = jnp.zeros(do.shape, do.dtype)
    q, k, v, o, lse_t = res
    # lse is a real (differentiable) output: d lse_i / d s_ij = p_ij, so a
    # cotangent on lse enters the shared ds = p * (dp - delta) term as
    # ds += p * dlse — i.e. exactly a shift of delta by -dlse. Folding it
    # there costs zero extra kernel work.
    bwd = _bwd_t if qkv_t else _bwd
    return bwd(q, k, v, o, lse_t, do, scale, causal, bq, bk, bh, t_real,
               interpret, dlse=dlse, window=window)


_flash.defvjp(_flash_fwd, _flash_bwd, symbolic_zeros=True)


# o-only variant: training drops lse, but a custom_vjp output cannot be
# DCE'd out of the remat closed-call — the lane-trim slice alone measured
# ~6 ms/step at 350M bs=24. This twin never emits the lse output (the
# residual still saves it for the backward).
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13))
def _flash_o(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
             bwd_bq, bwd_bk, qkv_t=False, window=0):
    fwd = _fwd_t if qkv_t else _fwd
    o, _ = fwd(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
               window)
    return o


def _flash_o_fwd(q, k, v, scale, causal, bq, bk, bh, t_real, interpret,
                 bwd_bq, bwd_bk, qkv_t=False, window=0):
    (o, _), res = _flash_fwd(q, k, v, scale, causal, bq, bk, bh, t_real,
                             interpret, bwd_bq, bwd_bk, qkv_t, window)
    return o, res


def _flash_o_bwd(scale, causal, bq, bk, bh, t_real, interpret, bwd_bq,
                 bwd_bk, qkv_t, window, res, do):
    from jax.custom_derivatives import SymbolicZero
    bq, bk = bwd_bq or bq, bwd_bk or bk
    if isinstance(do, SymbolicZero):
        do = jnp.zeros(do.shape, do.dtype)
    q, k, v, o, lse_t = res
    bwd = _bwd_t if qkv_t else _bwd
    return bwd(q, k, v, o, lse_t, do, scale, causal, bq, bk, bh, t_real,
               interpret, dlse=None, window=window)


_flash_o.defvjp(_flash_o_fwd, _flash_o_bwd, symbolic_zeros=True)


def flash_attention_with_lse(q, k, v, *, causal=True, scale=None,
                             block_q=128, block_k=128, block_h=2,
                             interpret=None, heads_major=False,
                             block_q_bwd=None, block_k_bwd=None,
                             qkv_t=False, window=0, _with_lse=True):
    """Fused attention over (batch, seq, heads, head_dim) inputs, returning
    ``(o, lse)`` where lse is the per-query logsumexp, (B, H, T) fp32.

    ``heads_major=True``: inputs/outputs are (batch, heads, seq, head_dim)
    — the kernel's native layout. The fold becomes a pure reshape (no
    transpose), and no T-minor layout pressure propagates into the
    caller's matmuls (XLA otherwise warps the producing matmul's output
    layout to feed the custom call, costing ~2x on its emitter).

    Equivalent math to softmax(scale * q k^T + causal_mask) v with fp32
    accumulation, O(T) memory. Differentiable (custom flash backward).
    Sequences that don't divide the block sizes are zero-padded and the
    padded keys masked in-kernel (slicing the output transposes to
    zero-padded cotangents, so the backward stays correct). ``block_h``
    (b, h) instances are processed per grid step (clamped to a divisor
    of batch*heads).

    lse is exposed (rather than kept as a hidden vjp residual) so callers
    under ``jax.checkpoint`` can tag o/lse/q/k/v with ``checkpoint_name``
    and a save-policy can keep exactly the flash residuals — making the
    backward reuse them instead of recomputing the forward kernel.
    """
    if qkv_t:
        # transposed operands: (batch, heads, head_dim, seq) — the qkv
        # projection einsum's natural T-minor layout; the kernel consumes
        # it directly so no relayout copies exist at the call boundary
        B, H, d, T = q.shape
    elif heads_major:
        B, H, T, d = q.shape
    else:
        B, T, H, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_default()
    bq, bk, T_pad = _block_sizes(T, block_q, block_k)
    # backward may use its own blocks; T must pad to a common multiple of
    # ALL block sizes or the backward grid would not cover every key
    # block (silently dropping dk/dv contributions)
    bwd_bq, bwd_bk, _ = _block_sizes(T, block_q_bwd or bq,
                                     block_k_bwd or bk)
    T_pad = _round_up(T, math.lcm(bq, bk, bwd_bq, bwd_bk))
    if qkv_t and any(x % 128 for x in (T_pad, bq, bk, bwd_bq, bwd_bk)):
        # In the transposed layout T (and every block) sits in the LANE
        # dim, which Mosaic requires in 128 units — shapes/blocks that
        # don't comply fall back to the standard kernel (one transpose;
        # correctness over the layout win at tiny T or small blocks)
        q, k, v = (jnp.swapaxes(x, -1, -2) for x in (q, k, v))
        return flash_attention_with_lse(
            q, k, v, causal=causal, scale=scale, block_q=block_q,
            block_k=block_k, block_h=block_h, interpret=interpret,
            heads_major=True, block_q_bwd=block_q_bwd,
            block_k_bwd=block_k_bwd, qkv_t=False, window=window,
            _with_lse=_with_lse)
    bh = max(1, min(block_h, B * H))
    while (B * H) % bh:
        bh -= 1
    # TPU tiling wants the lane (last) dim in 64/128 units: zero-pad other
    # head dims (zero columns add 0 to scores and produce zero output
    # columns, and zero cotangent columns backward — exact). d=64 is kept
    # native: the smaller DMA footprint beats the MXU's preference for 128.
    # The rule applies under qkv_t too: d moves to sublanes for q/k/v but
    # stays the lane dim of the o output block.
    d_pad = d if d in (64, 128) else _round_up(d, 128)

    def fold(x):
        if qkv_t:
            # flatten (H, B) — not (B, H): XLA lays the qkv einsum output
            # out with b inner of the two (b stride < h stride), so the
            # (H*B) flatten is a free bitcast while (B*H) is an interleave
            # copy (~1 ms/layer/tensor at 350M). The kernel's G dim is
            # order-agnostic.
            x = jnp.swapaxes(x, 0, 1).reshape(H * B, d, T)
            if T_pad != T or d_pad != d:
                x = jnp.pad(x, ((0, 0), (0, d_pad - d), (0, T_pad - T)))
            return x
        if not heads_major:
            x = x.transpose(0, 2, 1, 3)
        x = x.reshape(B * H, T, d)
        if T_pad != T or d_pad != d:
            x = jnp.pad(x, ((0, 0), (0, T_pad - T), (0, d_pad - d)))
        return x

    # fold the softmax scale into q OUTSIDE the kernel (and the custom_vjp,
    # so autodiff chains dq): one (BH, T, d) multiply instead of a
    # per-score-element multiply inside a VPU-bound kernel
    if window and not causal:
        raise ValueError("sliding window requires causal attention")
    q = q * jnp.asarray(scale, q.dtype)
    args = (fold(q), fold(k), fold(v), 1.0, bool(causal),
            bq, bk, bh, T, bool(interpret), bwd_bq, bwd_bk, bool(qkv_t),
            int(window))
    if _with_lse:
        o, lse = _flash(*args)
    else:
        # o-only twin: a custom_vjp output can't be DCE'd out of the
        # remat closed-call, so the dropped lse (and its lane-trim
        # slice, ~6 ms/step at 350M) must never be emitted at all
        o, lse = _flash_o(*args), None
    if T_pad != T or d_pad != d:
        o = o[:, :T, :d]
        lse = lse[:, :T] if lse is not None else None
    if qkv_t:
        # (H, B, ...) is the kernel's fold order; swap back to the
        # conventional (B, H, ...). (Exposing the (H, B, ...) form to the
        # caller measured neutral at 350M: it removes this interleave
        # copy but the hbte wo einsum pays it back in a worse emitter.)
        o = o.reshape(H, B, T, d).swapaxes(0, 1)
        return o, (lse.reshape(H, B, T).swapaxes(0, 1)
                   if lse is not None else None)
    o = o.reshape(B, H, T, d)
    if not heads_major:
        o = o.transpose(0, 2, 1, 3)
    return o, lse.reshape(B, H, T) if lse is not None else None


def flash_attention(q, k, v, *, causal=True, scale=None, block_q=128,
                    block_k=128, block_h=2, interpret=None,
                    heads_major=False, block_q_bwd=None,
                    block_k_bwd=None, qkv_t=False, window=0):
    """Fused attention over (batch, seq, heads, head_dim); see
    :func:`flash_attention_with_lse` (this never emits the lse output).
    ``window`` > 0 = mistral sliding-window attention (causal only)."""
    o, _ = flash_attention_with_lse(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, block_h=block_h, interpret=interpret,
        heads_major=heads_major, block_q_bwd=block_q_bwd,
        block_k_bwd=block_k_bwd, qkv_t=qkv_t, window=window,
        _with_lse=False)
    return o


def attention_reference(q, k, v, *, causal=True, scale=None):
    """Dense reference used by parity tests (same fp32 score math)."""
    B, T, H, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bthd,bshd->bhts", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p.astype(q.dtype), v)
