"""Fused optimizers: Adam/AdamW, LAMB, Lion, Adagrad, SGD.

Counterparts of the reference's native optimizer tier (csrc/adam/
multi_tensor_adam.cu:168 FusedAdam, csrc/lamb/fused_lamb_cuda_kernel.cu:478,
csrc/lion/multi_tensor_lion.cu:126, csrc/adagrad/cpu_adagrad.cpp:256, and the
Python wrappers ops/adam/fused_adam.py:18 etc.).

On TPU the multi-tensor-apply trick is unnecessary: updates are elementwise
jnp expressions over the (sharded) param pytree, XLA fuses each leaf's
update chain into one kernel, and sharded leaves update shard-locally —
which *is* the ZeRO partitioned-optimizer behavior when the engine shards
master params/optimizer state over the DP axis. (A separate Pallas kernel
would buy nothing here: the update is bandwidth-bound and XLA already
emits one fused read-modify-write pass per leaf.)

Protocol (self-contained; optax-style but torch-free):
    opt.init(params)                      -> state pytree
    opt.update(grads, state, params, lr)  -> (new_params, new_state)
params/grads fp32 (master weights); ``lr`` a traced scalar so schedules
don't trigger recompiles.
"""

import jax
import jax.numpy as jnp


def _tree_zeros_like(tree):
    return jax.tree.map(jnp.zeros_like, tree)


class FusedAdam:
    """Adam/AdamW (reference ops/adam/fused_adam.py:18; adam_w_mode=True
    gives AdamW decoupled weight decay, matching the reference default).

    ``moments_dtype``: storage dtype for m/v (e.g. "bfloat16" — halves
    optimizer-state HBM, the lever that lets GPT-2 1.3B ZeRO-3 training
    state fit a single 16 GB chip). The update itself always computes in
    fp32 from the upcast moments; None (default) stores them in the
    master-param dtype (fp32), bitwise-identical to the prior behavior
    for fp32 inputs."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, bias_correction=True, adam_w_mode=True,
                 moments_dtype=None):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.adam_w_mode = adam_w_mode
        self.moments_dtype = None if moments_dtype is None \
            else jnp.dtype(moments_dtype)

    def _moments_like(self, params):
        if self.moments_dtype is None:
            return _tree_zeros_like(params)
        return jax.tree.map(
            lambda p: jnp.zeros(p.shape, self.moments_dtype), params)

    def init(self, params):
        return {"step": jnp.zeros((), jnp.int32),
                "m": self._moments_like(params),
                "v": self._moments_like(params)}

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        step = state["step"] + 1
        b1, b2 = self.b1, self.b2
        if self.bias_correction:
            c1 = 1.0 - b1 ** step.astype(jnp.float32)
            c2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            c1 = c2 = 1.0

        def leaf(p, g, m, v):
            mdt = m.dtype
            g = g.astype(jnp.float32)
            m = m.astype(jnp.float32)
            v = v.astype(jnp.float32)
            if not self.adam_w_mode and self.weight_decay:
                g = g + self.weight_decay * p  # classic L2
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * jnp.square(g)
            upd = (m / c1) / (jnp.sqrt(v / c2) + self.eps)
            if self.adam_w_mode and self.weight_decay:
                upd = upd + self.weight_decay * p
            # params keep their own dtype (fp32 update math must not
            # promote a bf16 master-less param tree)
            return (p - lr * upd).astype(p.dtype), \
                m.astype(mdt), v.astype(mdt)

        out = jax.tree.map(leaf, params, grads, state["m"], state["v"])
        new_params = jax.tree.map(lambda t: t[0], out,
                                  is_leaf=lambda t: isinstance(t, tuple))
        new_m = jax.tree.map(lambda t: t[1], out,
                             is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree.map(lambda t: t[2], out,
                             is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"step": step, "m": new_m, "v": new_v}


class FusedLamb:
    """LAMB (reference ops/lamb/fused_lamb.py): Adam update rescaled by the
    per-leaf trust ratio ||p|| / ||update||. Norms over sharded leaves are
    global under GSPMD (psum inserted automatically)."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-6,
                 weight_decay=0.0, max_coeff=10.0, min_coeff=0.01):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff

    def init(self, params):
        return {"step": jnp.zeros((), jnp.int32),
                "m": _tree_zeros_like(params),
                "v": _tree_zeros_like(params)}

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        step = state["step"] + 1
        b1, b2 = self.b1, self.b2
        c1 = 1.0 - b1 ** step.astype(jnp.float32)
        c2 = 1.0 - b2 ** step.astype(jnp.float32)

        def leaf(p, g, m, v):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * jnp.square(g)
            upd = (m / c1) / (jnp.sqrt(v / c2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p
            p_norm = jnp.linalg.norm(p)
            u_norm = jnp.linalg.norm(upd)
            trust = jnp.where(
                (p_norm > 0) & (u_norm > 0),
                jnp.clip(p_norm / u_norm, self.min_coeff, self.max_coeff),
                1.0)
            return p - lr * trust * upd, m, v

        out = jax.tree.map(leaf, params, grads, state["m"], state["v"])
        unzip = lambda i: jax.tree.map(lambda t: t[i], out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        return unzip(0), {"step": step, "m": unzip(1), "v": unzip(2)}


class FusedLion:
    """Lion (reference ops/lion/fused_lion.py): sign of the interpolated
    momentum; half the optimizer memory of Adam."""

    def __init__(self, lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0):
        self.lr = lr
        self.b1, self.b2 = betas
        self.weight_decay = weight_decay

    def init(self, params):
        return {"step": jnp.zeros((), jnp.int32),
                "m": _tree_zeros_like(params)}

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.b1, self.b2

        def leaf(p, g, m):
            upd = jnp.sign(b1 * m + (1 - b1) * g)
            if self.weight_decay:
                upd = upd + self.weight_decay * p
            return p - lr * upd, b2 * m + (1 - b2) * g

        out = jax.tree.map(leaf, params, grads, state["m"])
        unzip = lambda i: jax.tree.map(lambda t: t[i], out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        return unzip(0), {"step": state["step"] + 1, "m": unzip(1)}


class FusedAdagrad:
    """Adagrad (reference csrc/adagrad/cpu_adagrad.cpp:256)."""

    def __init__(self, lr=1e-2, eps=1e-10, weight_decay=0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay

    def init(self, params):
        return {"step": jnp.zeros((), jnp.int32),
                "v": _tree_zeros_like(params)}

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr

        def leaf(p, g, v):
            if self.weight_decay:
                g = g + self.weight_decay * p
            v = v + jnp.square(g)
            return p - lr * g / (jnp.sqrt(v) + self.eps), v

        out = jax.tree.map(leaf, params, grads, state["v"])
        unzip = lambda i: jax.tree.map(lambda t: t[i], out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        return unzip(0), {"step": state["step"] + 1, "v": unzip(1)}


class SGD:
    def __init__(self, lr=1e-2, momentum=0.0, weight_decay=0.0):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay

    def init(self, params):
        if self.momentum:
            return {"step": jnp.zeros((), jnp.int32),
                    "m": _tree_zeros_like(params)}
        return {"step": jnp.zeros((), jnp.int32)}

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        if self.weight_decay:
            grads = jax.tree.map(lambda g, p: g + self.weight_decay * p,
                                 grads, params)
        if self.momentum:
            new_m = jax.tree.map(lambda m, g: self.momentum * m + g,
                                 state["m"], grads)
            new_p = jax.tree.map(lambda p, m: p - lr * m, params, new_m)
            return new_p, {"step": state["step"] + 1, "m": new_m}
        new_p = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_p, {"step": state["step"] + 1}


# registry used by the engine's _configure_basic_optimizer
# (reference runtime/engine.py:1294; names at engine.py:39-41)
OPTIMIZERS = {
    "adam": FusedAdam,
    "adamw": FusedAdam,
    "fusedadam": FusedAdam,
    "lamb": FusedLamb,
    "fusedlamb": FusedLamb,
    "lion": FusedLion,
    "fusedlion": FusedLion,
    "adagrad": FusedAdagrad,
    "sgd": SGD,
}


def build_optimizer(name, params_cfg):
    key = name.lower()
    if key not in OPTIMIZERS:
        raise ValueError(
            f"unknown optimizer '{name}'; available: {sorted(set(OPTIMIZERS))}")
    cls = OPTIMIZERS[key]
    kwargs = dict(params_cfg)
    if key in ("adam", "fusedadam"):
        kwargs.setdefault("adam_w_mode", True)
    elif key == "adamw":
        kwargs["adam_w_mode"] = True
    kwargs.pop("torch_adam", None)
    return cls(**kwargs)
