"""Spatial (diffusers UNet/VAE) fused bias ops.

Counterpart of reference ``csrc/spatial/opt_bias_add.cu:149`` (the
``spatial_inference`` op builder): fused NHWC bias-add variants used by
the diffusers UNet/VAE injection containers
(module_inject/containers/unet.py, vae.py). On TPU these are single
XLA fusions — the value of this module is the stable API surface the
reference exposes (opt_bias_add / opt_bias_add_add / opt_bias_add_res),
not a custom kernel; XLA emits one fused elementwise pass per call
(SURVEY §2.6: "XLA fusion suffices").

x is NHWC (batch, height, width, channels) or any (..., C) layout;
``bias`` is (C,). The model side lives in ``models/diffusion.py``:
UNet2D / VAEDecoder call these at every conv-bias and residual join,
and DSUNet / DSVAE wrap them with the compile-once-per-shape dispatch
that plays the reference wrappers' CUDA-graph role.
"""

import jax.numpy as jnp


def _check(x, bias):
    if bias.ndim != 1 or x.shape[-1] != bias.shape[0]:
        raise ValueError(
            f"bias must be (C,) matching x's channel dim; got x "
            f"{x.shape}, bias {bias.shape}")


def opt_bias_add(x, bias):
    """y = x + bias (reference opt_bias_add)."""
    _check(x, bias)
    return x + bias.astype(x.dtype)


def opt_bias_add_add(x, bias, other):
    """y = (x + bias) + other — the UNet dual-stream add
    (reference opt_bias_add_add)."""
    _check(x, bias)
    return x + bias.astype(x.dtype) + other


def opt_bias_add_res(x, bias, residual, residual_bias=None):
    """y = (x + bias) + (residual [+ residual_bias]) — the residual
    variant (reference opt_res_add_bias_add)."""
    _check(x, bias)
    out = x + bias.astype(x.dtype) + residual
    if residual_bias is not None:
        _check(residual, residual_bias)
        out = out + residual_bias.astype(x.dtype)
    return out
