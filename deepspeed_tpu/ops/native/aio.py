"""ctypes binding for the C++ async-IO pool (csrc/aio.cpp).

Counterpart of reference ``csrc/aio/py_lib/py_ds_aio.cpp`` binding the
``aio_handle``: sync_pread / sync_pwrite / async_pread / async_pwrite /
wait — the op behind NVMe parameter/optimizer swapping
(op_builder/async_io.py AsyncIOBuilder).
"""

import ctypes
import os

import numpy as np


class AsyncIOHandle:
    """``aio_handle(block_size, queue_depth, single_submit,
    overlap_events, num_threads)`` signature kept for parity; queue_depth/
    single_submit/overlap_events are libaio tuning knobs with no analogue
    in the pread/pwrite pool and are accepted unused."""

    def __init__(self, block_size=1 << 20, queue_depth=32,
                 single_submit=False, overlap_events=False, num_threads=4):
        from ...op_builder.builder import create_op_builder
        self._lib = create_op_builder("async_io").load()
        self._lib.aio_create.restype = ctypes.c_void_p
        self._lib.aio_create.argtypes = [ctypes.c_int, ctypes.c_int64]
        self._lib.aio_destroy.argtypes = [ctypes.c_void_p]
        for name, res in (("aio_submit_pwrite", ctypes.c_int64),
                          ("aio_submit_pread", ctypes.c_int64),
                          ("aio_pwrite", ctypes.c_int),
                          ("aio_pread", ctypes.c_int)):
            fn = getattr(self._lib, name)
            fn.restype = res
        self._lib.aio_wait.restype = ctypes.c_int
        self._lib.aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        self._h = self._lib.aio_create(int(num_threads), int(block_size))
        self.block_size = block_size
        self.num_threads = num_threads
        self._inflight = {}   # req id -> buffer keepalive

    # -------------------------------------------------------------- helpers
    @staticmethod
    def _buf(arr, writable):
        arr = np.ascontiguousarray(arr) if not writable else arr
        if writable:
            assert isinstance(arr, np.ndarray) and arr.flags.c_contiguous \
                and arr.flags.writeable, "read target must be a writable " \
                "contiguous numpy array"
        ptr = arr.ctypes.data_as(ctypes.c_void_p) if isinstance(
            arr, np.ndarray) else ctypes.cast(arr, ctypes.c_void_p)
        return arr, ptr, arr.nbytes

    @staticmethod
    def _check(status, path):
        if status != 0:
            raise OSError(-status, os.strerror(-status), str(path))

    # ------------------------------------------------------------ sync API
    def sync_pwrite(self, buffer, filename, fsync=False):
        buffer, ptr, nbytes = self._buf(buffer, writable=False)
        self._check(self._lib.aio_pwrite(
            ctypes.c_void_p(self._h), str(filename).encode(), ptr,
            ctypes.c_int64(nbytes), 1 if fsync else 0), filename)
        return nbytes

    def sync_pread(self, buffer, filename):
        buffer, ptr, nbytes = self._buf(buffer, writable=True)
        self._check(self._lib.aio_pread(
            ctypes.c_void_p(self._h), str(filename).encode(), ptr,
            ctypes.c_int64(nbytes)), filename)
        return nbytes

    # ----------------------------------------------------------- async API
    def async_pwrite(self, buffer, filename, fsync=False):
        buffer, ptr, nbytes = self._buf(buffer, writable=False)
        req = self._lib.aio_submit_pwrite(
            ctypes.c_void_p(self._h), str(filename).encode(), ptr,
            ctypes.c_int64(nbytes), 1 if fsync else 0)
        self._inflight[req] = (buffer, filename)
        return req

    def async_pread(self, buffer, filename):
        buffer, ptr, nbytes = self._buf(buffer, writable=True)
        req = self._lib.aio_submit_pread(
            ctypes.c_void_p(self._h), str(filename).encode(), ptr,
            ctypes.c_int64(nbytes))
        self._inflight[req] = (buffer, filename)
        return req

    def wait(self, req=None):
        """Wait one request (or all inflight). Returns completed count.
        Waiting an unknown/already-waited id raises (the C++ pool would
        otherwise block forever on an id it has no record of)."""
        if req is not None and req not in self._inflight:
            raise KeyError(f"aio request {req} is not in flight "
                           "(already waited or never issued)")
        reqs = [req] if req is not None else list(self._inflight)
        n = 0
        for r in reqs:
            status = self._lib.aio_wait(ctypes.c_void_p(self._h),
                                        ctypes.c_int64(r))
            _, path = self._inflight.pop(r)
            self._check(status, path)
            n += 1
        return n

    def close(self):
        if getattr(self, "_h", None):
            self.wait()
            self._lib.aio_destroy(ctypes.c_void_p(self._h))
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
