"""ctypes binding for the C++ checkpoint writer pool (csrc/ckpt_writer.cpp).

Counterpart of the reference's py_ds_veloc.cpp pybind layer."""

import ctypes

from ...utils import fault_injection


class Writer:
    def __init__(self, threads=4, fsync=False):
        from ...op_builder.builder import create_op_builder
        self._lib = create_op_builder("ckpt_writer").load()
        self._lib.ckpt_writer_create.restype = ctypes.c_void_p
        self._lib.ckpt_writer_create.argtypes = [ctypes.c_int]
        self._lib.ckpt_writer_destroy.argtypes = [ctypes.c_void_p]
        self._lib.ckpt_writer_write.restype = ctypes.c_int
        self._lib.ckpt_writer_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int]
        self._pool = self._lib.ckpt_writer_create(int(threads))
        self._fsync = 1 if fsync else 0

    def write(self, path, data):
        """data: bytes-like (memoryview/bytes/bytearray)."""
        # chaos harness hook: a 'write' fault here models the C++ pool
        # failing (full disk, dead thread) so the engine's retry/degrade
        # path — not the training step — absorbs it
        fault_injection.fire("write")
        mv = memoryview(data)
        if not mv.c_contiguous:
            mv = memoryview(bytes(mv))
        try:
            # zero-copy when the buffer is writable (BytesIO.getbuffer())
            buf = (ctypes.c_char * mv.nbytes).from_buffer(mv)
        except TypeError:
            buf = (ctypes.c_char * mv.nbytes).from_buffer_copy(mv)
        rc = self._lib.ckpt_writer_write(
            self._pool, str(path).encode(), buf, mv.nbytes, self._fsync)
        if rc != 0:
            import os
            raise OSError(-rc, os.strerror(-rc), str(path))

    def close(self):
        if getattr(self, "_pool", None):
            self._lib.ckpt_writer_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
