"""ctypes binding for the host-side C++ Adam (csrc/cpu_adam.cpp).

Counterpart of reference ``deepspeed/ops/adam/cpu_adam.py:13
DeepSpeedCPUAdam`` (backed by csrc/adam/cpu_adam_impl.cpp SIMD kernels):
steps fp32 optimizer state living in HOST RAM — the ZeRO-Offload
pattern where the device computes grads and the CPU owns the update.
Pairs with runtime/swap_tensor for NVMe-backed state.
"""

import ctypes

import numpy as np


class DeepSpeedCPUAdam:
    """Flat-tensor API: state tensors are caller-owned numpy fp32 arrays
    updated IN PLACE (like the reference updates torch CPU tensors).

        opt = DeepSpeedCPUAdam(lr=1e-3)
        st = opt.create_state(n)                # {'m','v'} fp32
        opt.step(params, grads, st)             # params updated in place
    """

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adamw_mode=True, bias_correction=True,
                 num_threads=4):
        from ...op_builder.builder import create_op_builder
        self._lib = create_op_builder("cpu_adam").load()
        self._lib.cpu_adam_create.restype = ctypes.c_void_p
        self._lib.cpu_adam_create.argtypes = [
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int, ctypes.c_int]
        self._lib.cpu_adam_destroy.argtypes = [ctypes.c_void_p]
        self._lib.cpu_adam_set_lr.argtypes = [ctypes.c_void_p,
                                              ctypes.c_float]
        self._lib.cpu_adam_get_step.restype = ctypes.c_int64
        self._lib.cpu_adam_get_step.argtypes = [ctypes.c_void_p]
        self._lib.cpu_adam_set_step.argtypes = [ctypes.c_void_p,
                                                ctypes.c_int64]
        self._lib.cpu_adam_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int]
        self._h = self._lib.cpu_adam_create(
            lr, betas[0], betas[1], eps, weight_decay,
            1 if adamw_mode else 0, 1 if bias_correction else 0,
            num_threads)
        self.lr = lr

    def set_lr(self, lr):
        self.lr = lr
        self._lib.cpu_adam_set_lr(ctypes.c_void_p(self._h), float(lr))

    def get_step(self):
        return int(self._lib.cpu_adam_get_step(ctypes.c_void_p(self._h)))

    def set_step(self, step):
        """Checkpoint restore: resume bias correction at the saved count."""
        self._lib.cpu_adam_set_step(ctypes.c_void_p(self._h), int(step))

    @staticmethod
    def create_state(n):
        return {"m": np.zeros(n, np.float32), "v": np.zeros(n, np.float32)}

    @staticmethod
    def _ptr(a):
        return a.ctypes.data_as(ctypes.c_void_p)

    def step(self, params, grads, state, increment_step=True):
        """params: fp32 contiguous numpy (updated in place); grads: fp32
        or bfloat16 numpy of the same length."""
        assert params.dtype == np.float32 and params.flags.c_contiguous
        assert params.flags.writeable
        n = params.size
        grads = np.ascontiguousarray(grads)
        if grads.dtype == np.float32:
            is_bf16 = 0
        else:
            # ml_dtypes bfloat16 (2-byte) -> reinterpret as uint16
            assert grads.dtype.itemsize == 2, (
                f"grads must be fp32 or bf16, got {grads.dtype}")
            grads = grads.view(np.uint16)
            is_bf16 = 1
        assert grads.size == n and state["m"].size == n \
            and state["v"].size == n, "state/grads size mismatch"
        assert state["m"].dtype == np.float32 \
            and state["v"].dtype == np.float32
        self._lib.cpu_adam_step(
            ctypes.c_void_p(self._h), self._ptr(params),
            self._ptr(state["m"]), self._ptr(state["v"]), self._ptr(grads),
            is_bf16, ctypes.c_int64(n), 1 if increment_step else 0)
        return params

    def close(self):
        if getattr(self, "_h", None):
            self._lib.cpu_adam_destroy(ctypes.c_void_p(self._h))
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
