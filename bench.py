"""Headline benchmark: GPT-2 350M ZeRO-2 bf16 training throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

Target (BASELINE.json): tokens/sec/chip within 15% of 8xA100 running the
reference DeepSpeed. The reference tree publishes no number for this config
(BASELINE.md: "published" is empty), so the baseline is the analytic
per-chip A100 figure: 312 TFLOP/s bf16 peak x 40% MFU (a strong DeepSpeed
ZeRO-2 MFU at 350M scale) / flops-per-token. vs_baseline > 1.0 beats it.

Runs on however many chips are visible (the driver gives one v5e chip);
throughput is reported per chip.
"""

import json
import os
import sys

# autotuning protocol (dstpu --autotuning, launcher/runner.py): a trial
# passes its knobs as --exp '{"BENCH_MICRO_BS": 16, ...}'; they apply as
# the equivalent env overrides BEFORE the bench reads them
if "--exp" in sys.argv:
    _exp = json.loads(sys.argv[sys.argv.index("--exp") + 1])
    os.environ.update({k: str(v) for k, v in _exp.items()})

# measured win on v5e at the 350M point (571 vs 577 ms/step): a 2x
# scoped-VMEM budget lets XLA form deeper fusions; 40 MB+ regresses.
# Must be set before libtpu initializes (first device touch).
os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=32768")

import time

import numpy as np
import jax

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "benchmarks"))
from bench_engine import build_bench_engine  # noqa: E402


def main():
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    stage = int(os.environ.get("BENCH_ZERO_STAGE", "2"))
    offload = os.environ.get("BENCH_OFFLOAD", "")

    # tuned v5e config: pallas flash attention with a full-KV inner
    # loop + per-layer save_flash remat, grad-in-forward fused CE over
    # the Pallas unembed/online-stats kernel (fp32 logits never in
    # HBM). ONE config source shared with profile_step/hlo_dump:
    # benchmarks/bench_engine.py reads every BENCH_* knob.
    engine, batch = build_bench_engine()
    cfg = engine.model.config
    preset = os.environ.get("BENCH_PRESET", "350M")
    seq_len = cfg.max_seq_len
    n_dev = len(jax.devices())
    bsz = engine.config.train_batch_size

    def sync():
        # force completion via host materialization: on some transports
        # (axon tunnel) block_until_ready does not actually block.
        return float(np.asarray(engine.state["step"]))

    for _ in range(warmup):
        loss = engine.train_batch(batch)
    sync()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch)
    sync()
    dt = time.perf_counter() - t0

    # on-chip Pallas kernel parity gate (real-Mosaic numerics vs the
    # dense references; CI only exercises interpreter mode). Runs after
    # timing so its compiles never pollute the measurement.
    kernels_parity = "skipped"
    if os.environ.get("BENCH_KERNEL_PARITY", "1") == "1" \
            and jax.default_backend() != "cpu":
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "benchmarks"))
        try:
            from kernel_parity import run as _kernel_parity
            kernels_parity = _kernel_parity()
        except Exception as e:          # report, don't hide the bench
            kernels_parity = f"FAILED: {type(e).__name__}: {e}"[:300]

    tokens = bsz * seq_len * steps
    tok_per_sec_chip = tokens / dt / n_dev
    flops_per_token = cfg.flops_per_token()
    mfu_peak = {"tpu": 197e12}.get("tpu")  # v5e bf16 peak per chip
    achieved_flops = tok_per_sec_chip * flops_per_token
    mfu = achieved_flops / mfu_peak

    a100_baseline = 312e12 * 0.40 / flops_per_token  # tokens/sec/chip
    print(json.dumps({
        "metric": (f"gpt2-{preset} zero{stage}"
                   + (f"-offload-{offload}" if offload else "")
                   + " bf16 training throughput"),
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tok_per_sec_chip / a100_baseline, 3),
        "extras": {
            "devices": n_dev, "seq_len": seq_len, "global_batch": bsz,
            "steps": steps, "step_time_s": round(dt / steps, 4),
            "mfu_vs_v5e_peak": round(mfu, 3),
            "final_loss": float(loss),
            "baseline_tokens_per_sec_chip_8xA100_est": round(a100_baseline, 1),
            "kernels_parity": kernels_parity,
        },
    }))


if __name__ == "__main__":
    main()
