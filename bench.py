"""Headline benchmark: GPT-2 350M ZeRO-2 bf16 training throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

Target (BASELINE.json): tokens/sec/chip within 15% of 8xA100 running the
reference DeepSpeed. The reference tree publishes no number for this config
(BASELINE.md: "published" is empty), so the baseline is the analytic
per-chip A100 figure: 312 TFLOP/s bf16 peak x 40% MFU (a strong DeepSpeed
ZeRO-2 MFU at 350M scale) / flops-per-token. vs_baseline > 1.0 beats it.

Runs on however many chips are visible (the driver gives one v5e chip);
throughput is reported per chip.

After the headline, ``extras.variants`` measures the round-6 levers —
each rebuilt+retimed under its own env overrides, failures isolated so a
variant can never cost the headline number:
  mlp_kernel_down  the layout-owning Pallas wdown projection
                   (BENCH_MLP_KERNEL=down)
  flash_bwd_qmajor the query-major fused flash backward
                   (BENCH_FLASH_BWD_QMAJOR=1)
  gpt2_1.3B_zero3  the BASELINE.md row-3 model point (ZeRO-3, bf16
                   moments+grad accumulation to fit one 16 GB chip),
                   where per-step fixed costs amortize
  comm_overlap_on/off  the comm-overlap program annotations
                   (BENCH_COMM_OVERLAP=1/0; runtime/zero/overlap.py)
                   A/B'd at whatever dp the driver exposes
  autotune_on/off  the measured kernel dispatch (BENCH_AUTOTUNE=1/0;
                   autotuning/kernel_dispatch.py): _on searches cold
                   keys at first trace and runs on the cached winners,
                   _off pins the r05 hand-set defaults; the winner
                   table lands in extras.autotune
  ring_on/off      long-context A/B at seq 4096 (BENCH_ATTN_BACKEND=
                   ring + BENCH_SP=auto vs the standard flash path;
                   sequence/ring.py zigzag context parallelism — real
                   ring numbers need >1 chip, at 1 chip the pair is a
                   long-seq baseline)
  moe_kernel_on/off  dropless-MoE expert-FFN A/B (BENCH_MODEL=moe +
                   BENCH_MOE_KERNEL=1/0): GPT2MoE ragged routing with
                   the Pallas grouped-GEMM kernel (ops/pallas/
                   grouped_matmul.py) vs lax.ragged_dot
  weight_quant_on/off  the training-side int8 compute A/B
                   (BENCH_INT8_MATMUL=1/0; quantize.int8_matmul routes
                   both MLP projections through ops/pallas/
                   quantization.int8_matmul — dynamic rowwise activation
                   codes x per-channel weight codes, int32 accumulate)
  pipe_zb/gpipe/zb_offload  the pp=2 schedule + host-offload pair
                   (benchmarks/pipeline_probe.py subprocess on a
                   virtual pipe mesh — zero-bubble vs gpipe wall time,
                   offload-on host-copy/memory read; BENCH_PIPE_PROBE=0
                   skips)
Disable with BENCH_VARIANTS=none, or pick a subset
(BENCH_VARIANTS=mlp_down,bwd_qmajor,1.3B,overlap,autotune,ring_on,
moe_on,moe_off,pipe — 'pipe' selects the subprocess probe rows).

``extras.telemetry`` embeds the observability layer's own read of a
measured run (ISSUE 9): single-chip MFU (cost_analysis flops), goodput,
step percentiles from ``engine.telemetry_report()``, and the pod-wide
straggler delta from a 2-host virtual-mesh probe
(benchmarks/telemetry_probe.py). BENCH_TELEMETRY=0 skips it.

The full report is also ALWAYS written into the tree as
``BENCH_local.json`` (the r06/r07 driver artifacts vanished; a lost
driver artifact must never again erase a round's measurements).
"""

import gc
import json
import os
import sys

# autotuning protocol (dstpu --autotuning, launcher/runner.py): a trial
# passes its knobs as --exp '{"BENCH_MICRO_BS": 16, ...}'; they apply as
# the equivalent env overrides BEFORE the bench reads them
if "--exp" in sys.argv:
    _exp = json.loads(sys.argv[sys.argv.index("--exp") + 1])
    os.environ.update({k: str(v) for k, v in _exp.items()})

# measured win on v5e at the 350M point (571 vs 577 ms/step): a 2x
# scoped-VMEM budget lets XLA form deeper fusions; 40 MB+ regresses.
# Must be set before libtpu initializes (first device touch).
os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=32768")

import time

import numpy as np
import jax

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "benchmarks"))
from bench_engine import build_bench_engine  # noqa: E402

A100_PEAK_MFU = 312e12 * 0.40     # the BASELINE.md per-chip bar
V5E_PEAK = 197e12                 # bf16 peak per chip


def _measure(steps, warmup):
    """Build the engine for the CURRENT env knobs and time ``steps``.
    Returns the raw numbers a caller folds into its own report shape."""
    engine, batch = build_bench_engine()
    cfg = engine.model.config
    n_dev = len(jax.devices())
    bsz = engine.config.train_batch_size

    def sync():
        # force completion via host materialization: on some transports
        # (axon tunnel) block_until_ready does not actually block.
        return float(np.asarray(engine.state["step"]))

    loss = None
    for _ in range(warmup):
        loss = engine.train_batch(batch)
    sync()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch)
    sync()
    dt = time.perf_counter() - t0

    tokens = bsz * cfg.max_seq_len * steps
    tok_per_sec_chip = tokens / dt / n_dev
    fpt = cfg.flops_per_token()
    out = {
        "_fpt": fpt,                  # popped by main(); not serialized
        "tokens_per_sec_chip": round(tok_per_sec_chip, 1),
        "step_time_s": round(dt / steps, 4),
        "vs_baseline": round(tok_per_sec_chip / (A100_PEAK_MFU / fpt), 3),
        "mfu_vs_v5e_peak": round(tok_per_sec_chip * fpt / V5E_PEAK, 3),
        "final_loss": float(loss),
        "devices": n_dev,
        "seq_len": cfg.max_seq_len,
        "global_batch": bsz,
        "steps": steps,
    }
    del engine, batch
    gc.collect()
    return out


# the round-6 lever configs; each is measured in isolation on top of
# whatever knobs the headline ran with. bwd_qmajor_512: at full-T
# backward blocks the q-major and k-major kernels coincide (one grid
# step per group); the q-major design's win — causal skipping at finer
# grain WITHOUT the k-major multi-block fp32-dq HBM round trip — only
# shows at sub-T blocks, so both points are measured.
_VARIANTS = {
    "mlp_down": ("mlp_kernel_down", {"BENCH_MLP_KERNEL": "down"}),
    "bwd_qmajor": ("flash_bwd_qmajor", {"BENCH_FLASH_BWD_QMAJOR": "1"}),
    "bwd_qmajor_512": ("flash_bwd_qmajor_512",
                       {"BENCH_FLASH_BWD_QMAJOR": "1",
                        "BENCH_FLASH_BQ_BWD": "512",
                        "BENCH_FLASH_BK_BWD": "512"}),
    "1.3B": ("gpt2_1.3B_zero3", {"BENCH_PRESET": "1.3B",
                                 "BENCH_ZERO_STAGE": "3"}),
    # comm-overlap A/B at whatever dp the driver exposes (the BENCH_DP
    # pair): 'overlap' forces the program-level annotations on (per-layer
    # in-scan grad reduction + ZeRO-3 gather prefetch; at dp=1 this
    # measures their pure overhead), 'overlap_off' pins them off (== the
    # headline at default 'auto', a drift sentinel at dp>1). XLA flags
    # only land when the driver also sets BENCH_COMM_OVERLAP=1 /
    # DSTPU_COMM_OVERLAP=1 before the process starts — in-process
    # variants inherit the headline's flags; the full-flag A/B lives in
    # the multichip artifact (__graft_entry__.measured_multichip).
    "overlap": ("comm_overlap_on", {"BENCH_COMM_OVERLAP": "1"}),
    "overlap_off": ("comm_overlap_off", {"BENCH_COMM_OVERLAP": "0"}),
    # measured kernel dispatch A/B: 'autotune' flips every tunable
    # kernel knob to "auto" and lets on_first_use search fill the winner
    # cache at first trace (search compiles land in warmup, not the
    # timed section); 'autotune_off' pins dispatch off — the r05-default
    # drift sentinel the tuned number is read against. The winner table
    # itself is embedded in this artifact (extras.autotune) so tuned
    # defaults finally travel with the measurements.
    "autotune": ("autotune_on", {"BENCH_AUTOTUNE": "1"}),
    "autotune_off": ("autotune_off", {"BENCH_AUTOTUNE": "0"}),
    # training-side W8A8 compute A/B (quantize.int8_matmul forced
    # on/off; ops/pallas/quantization.int8_matmul in both MLP
    # projections — dynamic rowwise activation codes x channelwise
    # weight codes, int32 accumulate). _off pins the quantize block to
    # false explicitly so an ambient BENCH_INT8_MATMUL can't silently
    # turn the A/B into int8-vs-int8.
    "weight_quant_on": ("weight_quant_on", {"BENCH_INT8_MATMUL": "1"}),
    "weight_quant_off": ("weight_quant_off", {"BENCH_INT8_MATMUL": "0"}),
    # long-context A/B at 4x the headline sequence (micro bs scaled down
    # to fit): 'ring_on' routes attention through the zigzag ring
    # (sequence/ring.py) with the seq axis spanning every visible device
    # (BENCH_SP=auto; at 1 chip sp=1 and the ring path degrades to the
    # flash kernel, making the pair a long-seq baseline — the real ring
    # number needs the multichip driver), 'ring_off' the standard flash
    # path at the same shape.
    "ring_on": ("ring_on", {"BENCH_ATTN_BACKEND": "ring",
                            "BENCH_SP": "auto", "BENCH_SEQ": "4096",
                            "BENCH_MICRO_BS": "4"}),
    # ring_off pins the baseline backend explicitly (like autotune_off /
    # overlap_off) so an ambient BENCH_ATTN_BACKEND=ring can't silently
    # turn the A/B into ring-vs-ring
    "ring_off": ("ring_off", {"BENCH_ATTN_BACKEND": "dense",
                              "BENCH_SP": "1", "BENCH_SEQ": "4096",
                              "BENCH_MICRO_BS": "4"}),
    # dropless-MoE expert-FFN A/B: GPT2MoE (preset dims, 4 experts,
    # top-2, ragged dropless routing) with the expert product through
    # the Pallas grouped-GEMM kernel (_on) vs lax.ragged_dot (_off) —
    # the moe_grouped_mm lever measured in a real train step. ZeRO-3 +
    # bf16 moments/grads because 4x-expert MLPs put the point near the
    # 1.3B memory envelope on one 16 GB chip.
    "moe_on": ("moe_kernel_on", {"BENCH_MODEL": "moe",
                                 "BENCH_MOE_KERNEL": "1",
                                 "BENCH_ZERO_STAGE": "3",
                                 "BENCH_MICRO_BS": "8",
                                 "BENCH_MOMENTS_DTYPE": "bfloat16",
                                 "BENCH_GRAD_DTYPE": "bf16"}),
    "moe_off": ("moe_kernel_off", {"BENCH_MODEL": "moe",
                                   "BENCH_MOE_KERNEL": "0",
                                   "BENCH_ZERO_STAGE": "3",
                                   "BENCH_MICRO_BS": "8",
                                   "BENCH_MOMENTS_DTYPE": "bfloat16",
                                   "BENCH_GRAD_DTYPE": "bf16"}),
    # measured-dispatch MoE: moe_grouped_kernel="auto" under
    # on_first_use, so the moe_grouped_mm bucket gets a real search on
    # this chip and its winner lands in the extras.autotune table
    "moe_autotune": ("moe_autotune", {"BENCH_MODEL": "moe",
                                      "BENCH_AUTOTUNE": "1",
                                      "BENCH_ZERO_STAGE": "3",
                                      "BENCH_MICRO_BS": "8",
                                      "BENCH_MOMENTS_DTYPE": "bfloat16",
                                      "BENCH_GRAD_DTYPE": "bf16"}),
}


def _run_variants(names, steps, warmup):
    out = {}
    for name in names:
        if name not in _VARIANTS:
            out[name] = {"error": f"unknown variant {name!r}"}
            continue
        label, overrides = _VARIANTS[name]
        saved = {k: os.environ.get(k) for k in overrides}
        os.environ.update(overrides)
        try:
            out[label] = _measure(steps, warmup)
            out[label].pop("_fpt", None)
        except Exception as e:       # isolate: a variant OOM/compile
            out[label] = {"error":   # failure must not cost the headline
                          f"{type(e).__name__}: {e}"[:300]}
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            gc.collect()
    return out


def _pipeline_variants():
    """The CPU-sized pp variant pair (ISSUE 10): a pp=2 pipe-only mesh
    in a subprocess (the telemetry-probe pattern — pipeline needs >= 2
    devices, the driver gives one chip) A/B-ing the zero-bubble
    schedule vs gpipe and the host-offload lever. Rows land in
    extras.variants as pipe_*; failures are isolated like every
    variant. BENCH_PIPE_PROBE=0 skips; real-pod numbers come from the
    multichip artifact's pp row (__graft_entry__.measured_multichip)."""
    import subprocess
    import sys as _sys
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"   # virtual pipe mesh: the pair is a
    # scheduling read on one chip; pod-scale numbers live in MULTICHIP
    env.pop("XLA_FLAGS", None)
    out = {}
    try:
        probe = subprocess.run(
            [_sys.executable,
             os.path.join(here, "benchmarks", "pipeline_probe.py"),
             "--pipe", os.environ.get("BENCH_PIPE", "2"),
             "--steps", os.environ.get("BENCH_PIPE_STEPS", "3"),
             "--warmup", "1",
             "--rows", "zb,gpipe,zb_offload"],
            env=env, capture_output=True, text=True, timeout=900)
        parsed = json.loads(probe.stdout.strip().splitlines()[-1])
        for name, row in parsed.get("rows", {}).items():
            out[f"pipe_{name}"] = row
        out["pipe_meta"] = {k: parsed.get(k) for k in
                            ("pipe", "backend", "host_kind", "preset",
                             "seq_len", "global_batch")}
    except Exception as e:  # noqa: BLE001 - isolate, like variants
        out["pipe_probe"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    return out


def _telemetry_extras(steps, warmup):
    """``extras.telemetry`` (ISSUE 9): the telemetry layer's own read
    of a measured run — single-chip MFU/goodput/step percentiles from
    ``engine.telemetry_report()`` (tiny preset so it never competes
    with the headline for HBM), plus the pod-wide straggler-delta
    aggregation from a 2-host virtual-mesh probe
    (benchmarks/telemetry_probe.py). Failures are isolated like every
    variant: telemetry must never cost the headline number."""
    import subprocess
    import sys as _sys
    out = {}
    saved = {k: os.environ.get(k)
             for k in ("BENCH_TELEMETRY", "BENCH_PRESET",
                       "BENCH_MICRO_BS", "BENCH_SEQ")}
    os.environ.update({"BENCH_TELEMETRY": "1", "BENCH_PRESET": "tiny",
                       "BENCH_MICRO_BS": "8", "BENCH_SEQ": "128"})
    try:
        engine, batch = build_bench_engine()
        for _ in range(warmup):
            engine.train_batch(batch)
        engine.telemetry.reset_window()     # compile out of the window
        for _ in range(steps):
            engine.train_batch(batch)
        engine.telemetry.drain()
        snap = engine.telemetry_report() or {}
        out["local"] = {k: snap.get(k) for k in (
            "mfu_pct", "flops_source", "goodput_pct",
            "tokens_per_sec_chip", "step_time_ms_p50",
            "step_time_ms_p99", "collectives", "exposed_comm_pct",
            "peak_assumed")}
        del engine, batch
        gc.collect()
    except Exception as e:  # noqa: BLE001 - isolate, like variants
        out["local"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        probe = subprocess.run(
            [_sys.executable,
             os.path.join(here, "benchmarks", "telemetry_probe.py"),
             "--hosts", "2", "--steps", "5", "--warmup", "2"],
            capture_output=True, text=True, timeout=600)
        line = probe.stdout.strip().splitlines()[-1]
        parsed = json.loads(line)
        out["cluster"] = parsed.get("cluster")
        out["cluster_hosts"] = parsed.get("hosts")
    except Exception as e:  # noqa: BLE001
        out["cluster"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    return out


def _reconcile_extras(steps, warmup):
    """``extras.reconcile`` (ISSUE 13): a step-ranged profiler capture
    on the tiny preset, parsed into a StepDecomposition and reconciled
    against the planner's ``_score`` breakdown for the mesh the run
    actually used. The artifact carries the drift summary (which term
    the cost model gets most wrong on this chip) and the measured term
    split. Isolated like every variant — reconcile must never cost the
    headline number."""
    out = {}
    saved = {k: os.environ.get(k)
             for k in ("BENCH_TELEMETRY", "BENCH_PRESET",
                       "BENCH_MICRO_BS", "BENCH_SEQ",
                       "DSTPU_PROFILE_STEPS")}
    # arm the capture BEFORE engine build (ProfilerControl reads the
    # env at construction): trace the two steps after warmup
    os.environ.update({
        "BENCH_TELEMETRY": "1", "BENCH_PRESET": "tiny",
        "BENCH_MICRO_BS": "8", "BENCH_SEQ": "128",
        "DSTPU_PROFILE_STEPS": f"{warmup + 1}:{warmup + 3}"})
    try:
        engine, batch = build_bench_engine()
        for _ in range(max(steps, warmup + 4)):
            engine.train_batch(batch)
        engine.telemetry.drain()            # reconcile runs pool-side
        snap = engine.telemetry_report() or {}
        out["summary"] = snap.get("reconcile")
        rep = engine.reconcile_report()
        if rep is not None:
            dec = rep.get("decomposition") or {}
            out["terms_measured_ms"] = dec.get("terms")
            out["coverage_pct"] = dec.get("coverage_pct")
            out["cpu_fallback"] = dec.get("cpu_fallback")
            drift = rep.get("drift") or {}
            out["drift_rows"] = drift.get("rows")
            out["modeled_wall_ms"] = drift.get("modeled_wall_ms")
            out["measured_wall_ms"] = drift.get("measured_wall_ms")
        del engine, batch
        gc.collect()
    except Exception as e:  # noqa: BLE001 - isolate, like variants
        out["error"] = f"{type(e).__name__}: {e}"[:300]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def main():
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    stage = int(os.environ.get("BENCH_ZERO_STAGE", "2"))
    offload = os.environ.get("BENCH_OFFLOAD", "")
    preset = os.environ.get("BENCH_PRESET", "350M")

    # tuned v5e config: pallas flash attention with a full-KV inner
    # loop + per-layer save_flash remat, grad-in-forward fused CE over
    # the Pallas unembed/online-stats kernel (fp32 logits never in
    # HBM). ONE config source shared with profile_step/hlo_dump:
    # benchmarks/bench_engine.py reads every BENCH_* knob.
    head = _measure(steps, warmup)
    head_fpt = head.pop("_fpt")

    # on-chip Pallas kernel parity gate (real-Mosaic numerics vs the
    # dense references; CI only exercises interpreter mode). Runs after
    # timing so its compiles never pollute the measurement. Returns a
    # dict enumerating every shipped kernel path.
    kernels_parity = "skipped"
    if os.environ.get("BENCH_KERNEL_PARITY", "1") == "1" \
            and jax.default_backend() != "cpu":
        try:
            from kernel_parity import run as _kernel_parity
            kernels_parity = _kernel_parity()
        except Exception as e:          # report, don't hide the bench
            kernels_parity = f"FAILED: {type(e).__name__}: {e}"[:300]

    variants = {}
    vnames = os.environ.get(
        "BENCH_VARIANTS",
        "mlp_down,bwd_qmajor,bwd_qmajor_512,1.3B,overlap,overlap_off,"
        "autotune,autotune_off,ring_on,ring_off,moe_on,moe_off,"
        "moe_autotune,weight_quant_on,weight_quant_off,pipe")
    if vnames and vnames != "none":
        # 'pipe' selects the subprocess probe below, not an in-process
        # re-timing — keep it out of the env-override variant loop
        variants = _run_variants(
            [v for v in vnames.split(",") if v and v != "pipe"],
            int(os.environ.get("BENCH_VARIANT_STEPS", "5")),
            int(os.environ.get("BENCH_VARIANT_WARMUP", "2")))

    # the pp=2 schedule/offload pair (subprocess virtual mesh): rides
    # extras.variants like every lever — and obeys the same subset
    # mechanism ('pipe' must be in the BENCH_VARIANTS selection;
    # BENCH_PIPE_PROBE=0 is the independent off switch)
    if os.environ.get("BENCH_PIPE_PROBE", "1") == "1" \
            and vnames != "none" and "pipe" in vnames.split(","):
        variants.update(_pipeline_variants())

    # the tuned winner table travels WITH the artifact: whatever the
    # autotune variants (or a pre-warmed cache) measured on this chip is
    # readable from the bench JSON alone — no separate cache file needed
    # to flip defaults next round
    autotune_info = {"cache_path": None, "table": {}}
    try:
        from deepspeed_tpu.autotuning import kernel_dispatch
        dk = kernel_dispatch.device_kind()
        autotune_info = {"cache_path": kernel_dispatch.cache_path(),
                         "table": kernel_dispatch.table(),
                         # the device-kind refusal rule, made legible in
                         # the artifact itself: winners measured on CPU
                         # (interpret-mode emulation) exercise code paths
                         # but must never steer a real TPU's defaults
                         "device_kind": dk,
                         "cpu_artifact": dk.lower() == "cpu"}
    except Exception as e:          # report, don't hide the bench
        autotune_info["error"] = f"{type(e).__name__}: {e}"[:200]

    # telemetry self-measurement (MFU/goodput + the 2-host virtual-mesh
    # straggler probe) — the trajectory artifacts pick the new metrics
    # up from here automatically. BENCH_TELEMETRY=0 skips.
    telemetry_info = {}
    if os.environ.get("BENCH_TELEMETRY", "") != "0":
        telemetry_info = _telemetry_extras(
            int(os.environ.get("BENCH_TELEMETRY_STEPS", "6")),
            int(os.environ.get("BENCH_TELEMETRY_WARMUP", "2")))

    # modeled-vs-measured reconciliation (ISSUE 13): profile a short
    # tiny-preset run and diff the planner's term breakdown against the
    # trace's step decomposition. BENCH_RECONCILE=0 skips.
    reconcile_info = {}
    if os.environ.get("BENCH_RECONCILE", "1") != "0":
        reconcile_info = _reconcile_extras(
            int(os.environ.get("BENCH_RECONCILE_STEPS", "6")),
            int(os.environ.get("BENCH_RECONCILE_WARMUP", "2")))

    report = {
        "metric": (f"gpt2-{preset} zero{stage}"
                   + (f"-offload-{offload}" if offload else "")
                   + " bf16 training throughput"),
        "value": head["tokens_per_sec_chip"],
        "unit": "tokens/sec/chip",
        "vs_baseline": head["vs_baseline"],
        "extras": {
            "devices": head["devices"], "seq_len": head["seq_len"],
            "global_batch": head["global_batch"],
            "steps": head["steps"], "step_time_s": head["step_time_s"],
            "mfu_vs_v5e_peak": head["mfu_vs_v5e_peak"],
            "final_loss": head["final_loss"],
            "baseline_tokens_per_sec_chip_8xA100_est": round(
                A100_PEAK_MFU / head_fpt, 1),
            "kernels_parity": kernels_parity,
            "variants": variants,
            "autotune": autotune_info,
            "telemetry": telemetry_info,
            "reconcile": reconcile_info,
        },
    }

    # always ALSO write the artifact into the tree: the r06 and r07
    # driver artifacts both vanished (PERF_NOTES rounds 7-8), erasing
    # two rounds of measurements — a tree-local copy means a lost
    # driver artifact can never again erase a round
    try:
        local = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_local.json")
        with open(local, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    except OSError as e:
        report["extras"]["local_artifact_error"] = str(e)[:200]

    print(json.dumps(report))


if __name__ == "__main__":
    main()
