"""Test harness configuration.

The reference simulates multi-node as multi-process single-host with a real
NCCL/GLOO backend (tests/unit/common.py:105 DistributedExec). The TPU-native
equivalent: a *virtual 8-device CPU mesh* via
``--xla_force_host_platform_device_count`` so every collective XLA emits is
real (ring algorithms on host), just not timed. The provisioning recipe is
shared with the driver gate (``__graft_entry__._provision``) so the test mesh
and the gate mesh can't diverge.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _provision  # noqa: E402

_provision(8)

import deepspeed_tpu  # noqa: E402, F401  (installs older-jax compat shims
#                       before test modules do `from jax import shard_map`)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_topology():
    """Each test builds its own mesh topology."""
    yield
    from deepspeed_tpu.utils import groups
    groups.reset()
