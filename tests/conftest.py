"""Test harness configuration.

The reference simulates multi-node as multi-process single-host with a real
NCCL/GLOO backend (tests/unit/common.py:105 DistributedExec). The TPU-native
equivalent: a *virtual 8-device CPU mesh* via
``--xla_force_host_platform_device_count`` so every collective XLA emits is
real (ring algorithms on host), just not timed. Must be set before jax
imports anything.
"""

import os

# Overwrite (the ambient env may pin JAX_PLATFORMS to the real TPU tunnel);
# unit tests always run on the virtual CPU mesh. jax may already be imported
# at interpreter startup with config captured from env, so set both the env
# vars and the live config.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_topology():
    """Each test builds its own mesh topology."""
    yield
    from deepspeed_tpu.utils import groups
    groups.reset()
