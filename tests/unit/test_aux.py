"""Aux-subsystem tests: monitor, flops profiler, activation checkpointing,
data pipeline (reference tests/unit/monitor, profiling, runtime/
activation_checkpointing, data_efficiency)."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.monitor import MonitorMaster, DeepSpeedMonitorConfig
from deepspeed_tpu.profiling import FlopsProfiler, get_model_profile
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler,
                                                 DeepSpeedDataSampler,
                                                 RandomLTDScheduler,
                                                 token_drop)
from deepspeed_tpu.runtime.data_pipeline.random_ltd import token_restore


TINY = GPT2Config(n_layer=2, n_head=2, d_model=32, max_seq_len=32,
                  vocab_size=64, remat=False, dtype="float32")


class TestMonitor:
    def test_csv_monitor_writes(self, tmp_path):
        cfg = DeepSpeedMonitorConfig.from_dict({
            "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                            "job_name": "job"}})
        m = MonitorMaster(cfg)
        assert m.enabled
        m.write_events([("Train/loss", 1.5, 1), ("Train/loss", 1.2, 2),
                        ("Train/lr", 0.1, 1)])
        m.flush()
        loss_f = tmp_path / "job" / "Train_loss.csv"
        assert loss_f.read_text() == "1,1.5\n2,1.2\n"
        assert (tmp_path / "job" / "Train_lr.csv").exists()

    def test_disabled_is_noop(self):
        m = MonitorMaster(DeepSpeedMonitorConfig.from_dict({}))
        assert not m.enabled
        m.write_events([("a", 1, 1)])  # no crash

    def test_engine_writes_monitor_events(self, tmp_path):
        from deepspeed_tpu.utils import groups
        groups.reset()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(TINY),
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "steps_per_print": 0,
                    "csv_monitor": {"enabled": True,
                                    "output_path": str(tmp_path),
                                    "job_name": "t"}})
        batch = {"input_ids": np.zeros(
            (engine.config.train_batch_size, 16), np.int32)}
        engine.train_batch(batch)
        engine.monitor.flush()
        text = (tmp_path / "t" / "Train_Samples_train_loss.csv").read_text()
        assert text.startswith("1,")


class TestFlopsProfiler:
    def test_forward_flops_close_to_analytic(self):
        model = GPT2(TINY)
        batch = {"input_ids": np.zeros((2, 32), np.int32)}
        flops, macs, params = get_model_profile(model, batch)
        assert params == TINY.num_params()
        # forward flops ~ 2*N*B*T plus attention; XLA count must be within
        # 3x of the analytic estimate (counts norms/softmax too)
        analytic = 2 * (TINY.num_params() - TINY.vocab_size * TINY.d_model
                        ) * 2 * 32
        assert flops > analytic * 0.5
        assert flops < analytic * 20
        assert macs == flops / 2

    def test_profile_fn_accumulates_and_prints(self, capsys):
        prof = FlopsProfiler()
        prof.start_profile()
        a = jnp.ones((64, 64))
        prof.profile_fn(lambda x: x @ x, a, name="mm")
        assert prof.get_total_flops() > 0
        assert prof.get_total_duration() > 0
        prof.print_model_profile()
        out = capsys.readouterr().out
        assert "mm" in out and "flops" in out

    def test_engine_train_step_profile(self):
        from deepspeed_tpu.utils import groups
        groups.reset()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2(TINY),
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "steps_per_print": 0})
        batch = {"input_ids": np.zeros(
            (engine.config.train_batch_size, 16), np.int32)}
        prof = engine.get_flops_profile(batch)
        # cost_analysis is per device: the step sees batch/8 per chip.
        # fwd+bwd+opt on (1, 16) must cost more than a forward on (1, 16)
        fwd, _, _ = get_model_profile(
            GPT2(TINY), {"input_ids": np.zeros((1, 16), np.int32)})
        assert prof.get_total_flops() > fwd


class TestActivationCheckpointing:
    def setup_method(self):
        checkpointing.reset()

    def test_checkpoint_preserves_value_and_grad(self):
        def f(x):
            return jnp.sum(jnp.sin(x) ** 2)

        x = jnp.arange(8.0)
        direct_v, direct_g = jax.value_and_grad(f)(x)
        ck_v, ck_g = jax.value_and_grad(
            lambda y: checkpointing.checkpoint(f, y))(x)
        np.testing.assert_allclose(np.asarray(ck_v), np.asarray(direct_v),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(ck_g), np.asarray(direct_g),
                                   rtol=1e-6)

    def test_configure_policy_applies(self):
        checkpointing.configure(policy="dots_saveable")
        assert checkpointing.is_configured()
        # still numerically identical
        f = lambda x: jnp.sum((x @ x) ** 2)
        x = jnp.eye(4) * 1.5
        a = jax.grad(lambda y: checkpointing.checkpoint(f, y))(x)
        b = jax.grad(f)(x)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    def test_unknown_policy_raises(self):
        with pytest.raises(ValueError, match="unknown remat policy"):
            checkpointing.resolve_policy("not_a_policy")

    def test_rng_tracker_fork_streams(self):
        checkpointing.model_parallel_rng_seed(123, tp_rank=0)
        tr = checkpointing.get_cuda_rng_tracker()
        with tr.fork() as k1:
            a = jax.random.normal(k1, (4,))
        with tr.fork() as k2:
            b = jax.random.normal(k2, (4,))
        assert not np.allclose(np.asarray(a), np.asarray(b))
        # same seed/rank replays the same stream
        checkpointing.model_parallel_rng_seed(123, tp_rank=0)
        with tr.fork() as k3:
            c = jax.random.normal(k3, (4,))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
        # different tp rank -> different stream
        checkpointing.model_parallel_rng_seed(123, tp_rank=1)
        with tr.fork() as k4:
            d = jax.random.normal(k4, (4,))
        assert not np.allclose(np.asarray(a), np.asarray(d))


class TestCurriculum:
    def test_fixed_linear(self):
        s = CurriculumScheduler({
            "curriculum_type": "seqlen", "min_difficulty": 8,
            "max_difficulty": 64, "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8}})
        assert s.get_difficulty(1) == 8
        assert s.get_difficulty(50) == 32  # halfway, quantized to 8
        assert s.get_difficulty(100) == 64
        assert s.get_difficulty(1000) == 64

    def test_fixed_root_faster_early(self):
        lin = CurriculumScheduler({
            "curriculum_type": "seqlen", "min_difficulty": 0,
            "max_difficulty": 100, "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 1}})
        root = CurriculumScheduler({
            "curriculum_type": "seqlen", "min_difficulty": 0,
            "max_difficulty": 100, "schedule_type": "fixed_root",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 1, "root_degree": 2}})
        assert root.get_difficulty(25) > lin.get_difficulty(25)
        assert root.get_difficulty(100) == lin.get_difficulty(100) == 100

    def test_fixed_discrete(self):
        s = CurriculumScheduler({
            "curriculum_type": "seqlen", "min_difficulty": 8,
            "max_difficulty": 64, "schedule_type": "fixed_discrete",
            "schedule_config": {"difficulty": [8, 32, 64],
                                "max_step": [10, 20]}})
        assert s.get_difficulty(5) == 8
        assert s.get_difficulty(15) == 32
        assert s.get_difficulty(99) == 64

    def test_missing_key_raises(self):
        with pytest.raises(ValueError):
            CurriculumScheduler({"min_difficulty": 1,
                                 "max_difficulty": 2})


class TestDataSampler:
    def test_ranks_partition_batch(self):
        samplers = [DeepSpeedDataSampler(
            total_samples=64, micro_batch_size=2, data_parallel_rank=r,
            data_parallel_size=4, gradient_accumulation_steps=2,
            seed=7) for r in range(4)]
        iters = [iter(s) for s in samplers]
        step = [next(it) for it in iters]
        # each rank gets micro*gas=4 samples, disjoint, union = global batch
        allidx = np.concatenate(step)
        assert len(allidx) == 16
        assert len(set(allidx.tolist())) == 16

    def test_resume_reproduces(self):
        s1 = DeepSpeedDataSampler(40, 2, 0, 2, seed=3)
        it1 = iter(s1)
        first = [next(it1) for _ in range(3)]
        consumed = s1.consumed_samples
        s2 = DeepSpeedDataSampler(40, 2, 0, 2, seed=3)
        s2.set_consumed_samples(consumed - 4)  # rewind one step
        np.testing.assert_array_equal(next(iter(s2)), first[-1])

    def test_epoch_reshuffles(self):
        s = DeepSpeedDataSampler(8, 2, 0, 1, seed=5)
        it = iter(s)
        e1 = np.concatenate([next(it) for _ in range(4)])
        e2 = np.concatenate([next(it) for _ in range(4)])
        assert sorted(e1.tolist()) == sorted(e2.tolist()) == list(range(8))
        assert e1.tolist() != e2.tolist()

    def test_curriculum_difficulty_advances(self):
        cs = CurriculumScheduler({
            "curriculum_type": "seqlen", "min_difficulty": 8,
            "max_difficulty": 32, "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4,
                                "difficulty_step": 8}})
        s = DeepSpeedDataSampler(64, 2, 0, 1, curriculum_scheduler=cs)
        it = iter(s)
        diffs = []
        for _ in range(5):
            next(it)
            diffs.append(s.curriculum_difficulty)
        assert diffs[0] < diffs[-1] <= 32


class TestRandomLTD:
    def test_token_drop_restore_roundtrip(self):
        x = jnp.arange(2 * 8 * 4, dtype=jnp.float32).reshape(2, 8, 4)
        kept, idx = token_drop(x, keep=5, rng=jax.random.key(0))
        assert kept.shape == (2, 5, 4)
        # kept indices strictly increasing (order preserved)
        assert (np.diff(np.asarray(idx), axis=1) > 0).all()
        restored = token_restore(kept * 2, idx, x)
        # kept positions doubled, dropped untouched
        for b in range(2):
            for t in range(8):
                if t in np.asarray(idx[b]):
                    np.testing.assert_array_equal(
                        np.asarray(restored[b, t]), np.asarray(x[b, t] * 2))
                else:
                    np.testing.assert_array_equal(
                        np.asarray(restored[b, t]), np.asarray(x[b, t]))

    def test_scheduler_ramp(self):
        s = RandomLTDScheduler({
            "random_ltd_min_value": 16, "random_ltd_max_value": 128,
            "random_ltd_schedule": {"seq_step": 16, "require_steps": 10}})
        assert s.update_seq(0) == 16
        mid = s.update_seq(5)
        assert 16 < mid < 128 and mid % 16 == 0
        assert s.update_seq(10) == 128
        assert s.update_seq(100) == 128


class TestIndexedDataset:
    def test_build_and_mmap_roundtrip(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline import (
            IndexedDatasetBuilder, MMapIndexedDataset, FixedSeqDataset)
        prefix = str(tmp_path / "corpus")
        docs = [np.arange(n, dtype=np.uint16) for n in (5, 17, 3, 64)]
        b = IndexedDatasetBuilder(prefix, dtype=np.uint16)
        for d in docs:
            b.add_item(d)
        assert b.finalize() == 4
        ds = MMapIndexedDataset(prefix)
        assert len(ds) == 4 and ds.total_tokens() == 89
        for i, d in enumerate(docs):
            np.testing.assert_array_equal(np.asarray(ds[i]), d)
        # packed fixed-seq view feeds the engine directly
        fixed = FixedSeqDataset(ds, seq_len=16)
        assert len(fixed) == 5
        item = fixed[1]
        assert item["input_ids"].shape == (16,)
        np.testing.assert_array_equal(
            item["input_ids"],
            np.concatenate([d for d in docs])[16:32].astype(np.int32))

    def test_bad_magic_raises(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline import MMapIndexedDataset
        (tmp_path / "x.idx").write_bytes(b'{"magic": "nope"}\n')
        (tmp_path / "x.bin").write_bytes(b"")
        with pytest.raises(ValueError, match="bad magic"):
            MMapIndexedDataset(str(tmp_path / "x"))

    def test_truncated_corpus_raises(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline import (
            IndexedDatasetBuilder, MMapIndexedDataset)
        prefix = str(tmp_path / "t")
        b = IndexedDatasetBuilder(prefix, dtype=np.uint16)
        b.add_item(np.arange(100, dtype=np.uint16))
        b.finalize()
        # truncate the data file
        with open(prefix + ".bin", "r+b") as f:
            f.truncate(50)
        with pytest.raises(ValueError, match="truncated or mismatched"):
            MMapIndexedDataset(prefix)


class TestModelBasedTuner:
    """reference tuner/model_based_tuner.py:19 + cost_model.py:14."""

    def test_cost_model_ranks_configs(self):
        from deepspeed_tpu.autotuning.tuner import CostModel
        exps = [{"micro_bs": b, "stage": s}
                for b in (1, 2, 4, 8) for s in (0, 2)]
        # ground truth: throughput grows with micro_bs, stage 2 cheaper
        metric = [e["micro_bs"] * (1.2 if e["stage"] == 2 else 1.0)
                  for e in exps]
        cm = CostModel().fit(exps, metric)
        preds = cm.predict([{"micro_bs": 8, "stage": 2},
                            {"micro_bs": 1, "stage": 0}])
        assert preds[0] > preds[1]

    def test_model_based_tuner_finds_best(self):
        from deepspeed_tpu.autotuning.tuner import ModelBasedTuner
        space = {"micro_bs": [1, 2, 4, 8, 16], "stage": [0, 1, 2]}
        truth = lambda e: e["micro_bs"] * (1.0 + 0.1 * e["stage"])
        tuner = ModelBasedTuner(space, seed=0, max_trials=10)
        for exp in tuner:
            tuner.record(exp, truth(exp))
        best_exp, best_val = tuner.best()
        # 10 of 15 trials guided by the model must find the optimum
        assert best_exp == {"micro_bs": 16, "stage": 2}

    def test_requires_recording(self):
        from deepspeed_tpu.autotuning.tuner import ModelBasedTuner
        tuner = ModelBasedTuner({"a": [1, 2]}, max_trials=2)
        it = iter(tuner)
        next(it)  # not recording is fine for warmup picks
        next(it)

    def test_no_duplicate_yields_without_record(self):
        """Skipping record() must not hand the same config back: yielded-
        but-unrecorded experiments are excluded from the untried pool."""
        from deepspeed_tpu.autotuning.tuner import ModelBasedTuner
        space = {"a": [1, 2, 3], "b": [10, 20]}
        tuner = ModelBasedTuner(space, max_trials=6, warmup_trials=100)
        seen = [tuple(sorted(e.items())) for e in tuner]
        assert len(seen) == len(set(seen)), seen

    def test_model_picks_need_observations(self):
        from deepspeed_tpu.autotuning.tuner import ModelBasedTuner
        import pytest
        tuner = ModelBasedTuner({"a": [1, 2, 3]}, warmup_trials=0,
                                explore_eps=0.0)
        with pytest.raises(RuntimeError):
            next(iter(tuner))


class TestPerModuleFlops:
    """reference print_model_profile per-module tree (jaxpr-walk
    realization)."""

    def test_gpt2_breakdown(self):
        from deepspeed_tpu.models import GPT2, GPT2Config
        from deepspeed_tpu.profiling.flops_profiler import (
            per_module_flops)
        cfg = GPT2Config(n_layer=2, n_head=2, d_model=64, max_seq_len=32,
                         vocab_size=128, remat=False, dtype="float32")
        m = GPT2(cfg)
        params = m.init(jax.random.key(0))
        ids = np.zeros((2, 32), np.int32)
        groups = per_module_flops(
            lambda p: m.loss(p, {"input_ids": ids}, train=False), params)
        names = set(groups)
        assert any("_mlp" in n for n in names), names
        assert any("block_qkv" in n for n in names), names
        assert any("head" in n for n in names), names
        # MLP flops must match the analytic count: L * 2 matmuls each
        # 2*B*T*D*4D, both fwd-only here
        mlp = sum(v for k, v in groups.items() if "_mlp" in k)
        expect = cfg.n_layer * 2 * (2 * 2 * 32 * 64 * 256)
        assert abs(mlp - expect) / expect < 0.05, (mlp, expect)

    def test_scan_scaling(self):
        """Flops inside lax.scan scale by trip count."""
        from deepspeed_tpu.profiling.flops_profiler import (
            per_module_flops)
        w = jnp.ones((16, 16))

        def fn(x):
            def body(c, _):
                return c @ w, None
            y, _ = jax.lax.scan(body, x, None, length=7)
            return y
        groups = per_module_flops(fn, jnp.ones((16, 16)),
                                  code_root="test_aux")
        total = sum(groups.values())
        assert abs(total - 7 * 2 * 16 ** 3) / (7 * 2 * 16 ** 3) < 0.01


class TestDataAnalyzer:
    """reference data_sampling/data_analyzer.py:444."""

    def _dataset(self):
        rng = np.random.RandomState(0)
        data = []
        for i in range(20):
            n = rng.randint(4, 30)
            data.append(rng.randint(1, 50, (n,)).astype(np.int32))
        return data

    def test_indexes_written_and_sorted(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            DataAnalyzer, CurriculumIndex)
        ds = self._dataset()
        summary = DataAnalyzer(ds, num_workers=2).run(str(tmp_path))
        assert summary["num_samples"] == 20
        assert set(summary["metrics"]) == {"seqlen", "vocab_rarity"}
        scores = np.load(tmp_path / "seqlen_sample_to_metric.npy")
        np.testing.assert_array_equal(
            scores, np.asarray([len(d) for d in ds], np.float32))
        vals = np.load(tmp_path / "seqlen_metric_values.npy")
        assert (np.diff(vals) >= 0).all()

    def test_curriculum_consumption(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            DataAnalyzer, CurriculumIndex)
        ds = self._dataset()
        DataAnalyzer(ds, num_workers=1).run(str(tmp_path))
        idx = CurriculumIndex(str(tmp_path), "seqlen")
        easy = idx.samples_up_to(10)
        assert all(len(ds[i]) <= 10 for i in easy)
        # every admissible sample is present
        assert len(easy) == sum(1 for d in ds if len(d) <= 10)
        assert len(idx.samples_up_to(1000)) == 20

    def test_vocab_rarity_orders_rare_higher(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            DataAnalyzer)
        # sample 0 = common tokens, sample 1 = rare tokens
        ds = [np.asarray([1, 1, 1, 1] * 10, np.int32),
              np.asarray([40, 41], np.int32)] + \
             [np.asarray([1, 2, 3], np.int32)] * 5
        DataAnalyzer(ds, num_workers=1).run(str(tmp_path))
        scores = np.load(tmp_path / "vocab_rarity_sample_to_metric.npy")
        assert scores[1] > scores[0]
