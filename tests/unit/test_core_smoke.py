"""Fast core smoke (the `pytest -m "not slow"` set's engine/ZeRO
representation): one tiny model through initialize/train_batch across
ZeRO stages with loss parity — the full engine matrices live in the
slow-marked suites (test_engine/test_checkpoint/...)."""

import numpy as np
import jax

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import groups

CFG = GPT2Config(n_layer=2, n_head=2, d_model=32, max_seq_len=32,
                 vocab_size=128, remat=False, dtype="float32")


def _losses(stage, steps=3):
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(CFG),
        config={"train_micro_batch_size_per_gpu": 1,
                "steps_per_print": 0,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": stage}})
    rng = np.random.RandomState(0)
    bsz = engine.config.train_batch_size
    batch = {"input_ids": rng.randint(0, 128, (bsz, 32)).astype(np.int32)}
    return [float(engine.train_batch(batch)) for _ in range(steps)]


def test_zero_stages_loss_parity_and_training():
    l0 = _losses(0)
    l2 = _losses(2)
    np.testing.assert_allclose(l0, l2, rtol=1e-4, atol=1e-4)
    assert l0[-1] < l0[0]


def test_zero3_bf16_moments_and_grad_accum_dtype():
    """The 1.3B-fit memory knobs: ZeRO-3 + bf16 Adam moments + bf16 grad
    accumulation still trains (loss decreasing), with the moments
    actually stored bf16 on device."""
    import jax.numpy as jnp
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(GPT2Config(n_layer=2, n_head=2, d_model=32,
                              max_seq_len=32, vocab_size=128,
                              remat=False)),
        config={"train_micro_batch_size_per_gpu": 1,
                "steps_per_print": 0,
                "optimizer": {"type": "AdamW",
                              "params": {"lr": 1e-3,
                                         "moments_dtype": "bfloat16"}},
                "bf16": {"enabled": True},
                "data_types": {"grad_accum_dtype": "bf16"},
                "zero_optimization": {"stage": 3}})
    rng = np.random.RandomState(0)
    bsz = engine.config.train_batch_size
    batch = {"input_ids": rng.randint(0, 128, (bsz, 32)).astype(np.int32)}
    losses = [float(engine.train_batch(batch)) for _ in range(4)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    m0 = jax.tree.leaves(engine.state["opt"]["m"])[0]
    assert m0.dtype == jnp.bfloat16


def test_gas_accumulation_respects_grad_dtype():
    """gas > 1: the accumulation buffer is allocated in the configured
    grad dtype (bf16 halves the only O(model) fp32 transient)."""
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(GPT2Config(n_layer=2, n_head=2, d_model=32,
                              max_seq_len=32, vocab_size=128,
                              remat=False)),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 2,
                "steps_per_print": 0,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "data_types": {"grad_accum_dtype": "bf16"},
                "zero_optimization": {"stage": 2}})
    rng = np.random.RandomState(0)
    bsz = engine.config.train_batch_size
    batch = {"input_ids": rng.randint(0, 128, (bsz, 32)).astype(np.int32)}
    losses = [float(engine.train_batch(batch)) for _ in range(3)]
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
