"""Pallas kernel parity tests (interpret mode on the CPU mesh).

Mirrors the reference's per-kernel numerics tests (tests/unit/ops/: adam,
quantizer, transformer vs torch references — SURVEY §4): each kernel is
checked against a dense jnp reference implementation.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.pallas.flash_attention import (flash_attention,
                                                      attention_reference)
from deepspeed_tpu.ops.pallas.quantization import (
    quantize_blockwise, dequantize_blockwise, quantized_all_gather,
    quantized_psum_scatter)
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



class TestFlashAttention:
    def _qkv(self, B=2, T=128, H=4, d=32, dtype=jnp.float32, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda s: jnp.asarray(rng.randn(B, T, H, d), dtype) * 0.3
        return mk(0), mk(1), mk(2)

    def test_forward_matches_dense(self):
        q, k, v = self._qkv()
        o = flash_attention(q, k, v, block_q=64, block_k=64)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_non_causal(self):
        q, k, v = self._qkv(T=64)
        o = flash_attention(q, k, v, causal=False, block_q=32, block_k=32)
        ref = attention_reference(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_uneven_blocks(self):
        # block_q != block_k exercises the causal block-boundary logic
        q, k, v = self._qkv(T=128)
        o = flash_attention(q, k, v, block_q=32, block_k=64)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        o = flash_attention(q, k, v, block_q=64, block_k=32)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_non_multiple_of_block_seq_len(self):
        # T=96 < the default 128 block: single exact block (no padding)
        q, k, v = self._qkv(T=96)
        o = flash_attention(q, k, v)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        # T=101 rounds up to a 104-wide block: exercises the padded-tail
        # masking path
        q, k, v = self._qkv(T=101)
        o = flash_attention(q, k, v)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_prime_seq_len_padded(self, causal):
        # T=101 (prime): zero-padding + in-kernel key masking, fwd + bwd
        q, k, v = self._qkv(T=101)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=32, block_k=32) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

        np.testing.assert_allclose(float(loss_flash(q, k, v)),
                                   float(loss_ref(q, k, v)), rtol=1e-5)
        g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_grads_match_dense(self):
        q, k, v = self._qkv(T=64)

        def loss_f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, block_q=32,
                                           block_k=32) ** 2)

        def loss_r(q, k, v):
            return jnp.sum(attention_reference(q, k, v) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_lse_grad_matches_dense(self):
        """lse is a real differentiable output (z-loss style consumers):
        its cotangent folds into the shared delta term."""
        from deepspeed_tpu.ops.pallas.flash_attention import (
            flash_attention_with_lse)
        import math
        q, k, v = self._qkv(T=64)

        def loss_f(q, k, v):
            o, lse = flash_attention_with_lse(q, k, v, block_q=32,
                                              block_k=32)
            return jnp.sum(o ** 2) + jnp.sum(lse ** 2)

        def loss_r(q, k, v):
            d = q.shape[-1]
            s = jnp.einsum("bthd,bshd->bhts", q, k,
                           preferred_element_type=jnp.float32)
            s = s / math.sqrt(d)
            mask = jnp.tril(jnp.ones(s.shape[-2:], bool))
            s = jnp.where(mask[None, None], s, -1e30)
            lse = jax.nn.logsumexp(s, axis=-1)          # (B, H, T)
            p = jnp.exp(s - lse[..., None])
            o = jnp.einsum("bhts,bshd->bthd", p.astype(q.dtype), v)
            return jnp.sum(o ** 2) + jnp.sum(lse ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3)

    def test_in_model(self):
        """GPT2(use_flash_attention=True) is loss- and grad-identical to
        the dense model."""
        from dataclasses import replace
        from deepspeed_tpu.models import GPT2, GPT2Config
        cfg = GPT2Config(n_layer=2, n_head=4, d_model=64, max_seq_len=64,
                         vocab_size=256, dtype="float32", remat=False)
        dense, flash = GPT2(cfg), GPT2(replace(cfg,
                                               use_flash_attention=True))
        params = dense.init(jax.random.key(0))
        ids = np.random.RandomState(0).randint(0, 256, (2, 64)).astype(
            np.int32)
        l0 = float(dense.loss(params, {"input_ids": ids}, train=False))
        l1 = float(flash.loss(params, {"input_ids": ids}, train=False))
        assert l1 == pytest.approx(l0, rel=1e-6)
        g0 = jax.grad(lambda p: dense.loss(p, {"input_ids": ids},
                                           train=False))(params)
        g1 = jax.grad(lambda p: flash.loss(p, {"input_ids": ids},
                                           train=False))(params)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestFlashAttentionBias:
    """Additive-bias operands (ALiBi / masks / pair biases) — the
    counterpart of the reference kernels' bias inputs
    (csrc/deepspeed4science/evoformer_attn/kernel_forward.h:986,
    csrc/transformer/inference/csrc/softmax.cu:562)."""

    def _qkv(self, B=2, T=128, H=4, d=32, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda: jnp.asarray(rng.randn(B, T, H, d),
                                 jnp.float32) * 0.3
        return mk(), mk(), mk()

    @pytest.mark.parametrize("shape", [
        (2, 4, 128, 128),   # per-(batch, head)
        (2, 1, 1, 128),     # per-batch key mask
        (1, 4, 1, 128),     # per-head key bias
        (1, 4, 128, 128),   # per-head pair bias
        (2, 4, 1, 128),     # per-instance key bias
        (1, 1, 1, 128),     # shared key bias
    ])
    def test_bias_broadcast_parity(self, shape):
        q, k, v = self._qkv()
        bias = jnp.asarray(np.random.RandomState(1).randn(*shape),
                           jnp.float32) * 0.5
        o = flash_attention(q, k, v, bias=bias, block_q=64, block_k=64)
        ref = attention_reference(q, k, v, bias=bias)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_bias_h1_model(self):
        # regression: a size-1 model dim must take the broadcast branch
        # (the full-dim row maps would read past the folded array)
        q, k, v = self._qkv(B=4, H=1)
        for shape in [(1, 1, 128, 128), (4, 1, 128, 128), (4, 1, 1, 128)]:
            bias = jnp.asarray(np.random.RandomState(2).randn(*shape),
                               jnp.float32) * 0.5
            o = flash_attention(q, k, v, bias=bias, block_q=64,
                                block_k=64, block_h=2)
            ref = attention_reference(q, k, v, bias=bias)
            np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)

    def test_bias_qkv_grads(self):
        q, k, v = self._qkv(T=64)
        bias = jnp.asarray(np.random.RandomState(3).randn(1, 4, 1, 64),
                           jnp.float32) * 0.5
        gf = jax.grad(lambda *a: jnp.sum(flash_attention(
            *a, bias=bias, block_q=32, block_k=32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(attention_reference(
            *a, bias=bias) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("shape,causal", [
        ((2, 4, 64, 64), True),     # per-(b, h): injective row map
        ((2, 1, 1, 64), True),      # per-batch: accumulated over heads
        ((2, 1, 64, 64), False),    # per-batch pair bias
    ])
    def test_dbias_matches_dense(self, shape, causal):
        q, k, v = self._qkv(T=64)
        bias = jnp.asarray(np.random.RandomState(4).randn(*shape),
                           jnp.float32) * 0.3
        db_f = jax.grad(lambda b: jnp.sum(flash_attention(
            q, k, v, bias=b, bias_grad=True, causal=causal, block_q=32,
            block_k=32) ** 2))(bias)
        db_r = jax.grad(lambda b: jnp.sum(attention_reference(
            q, k, v, bias=b, causal=causal) ** 2))(bias)
        np.testing.assert_allclose(np.asarray(db_f), np.asarray(db_r),
                                   rtol=1e-4, atol=1e-4)

    def test_dbias_nonmonotone_rejected(self):
        # per-head grad bias under the standard fold revisits rows
        # non-contiguously -> loud error, not silent corruption
        q, k, v = self._qkv()
        bias = jnp.zeros((1, 4, 128, 128), jnp.float32)
        with pytest.raises(ValueError, match="bias_grad unsupported"):
            flash_attention(q, k, v, bias=bias, bias_grad=True,
                            block_q=64, block_k=64, block_h=2)

    def test_alibi_in_kernel(self):
        from deepspeed_tpu.ops.pallas.paged_attention import alibi_slopes
        q, k, v = self._qkv(H=6)            # non-power-of-two heads
        sl = alibi_slopes(6)
        ab = jnp.asarray(sl, jnp.float32)[None, :, None, None] \
            * jnp.arange(128, dtype=jnp.float32)[None, None, None, :]
        o = flash_attention(q, k, v, alibi=sl, block_q=64, block_k=64)
        ref = attention_reference(q, k, v, bias=ab)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        # falcon-rw quirk: bf16-quantized, pre-scaled
        o = flash_attention(q, k, v, alibi=sl, alibi_scale=0.25,
                            alibi_bf16=True, block_q=64, block_k=64)
        abq = ab.astype(jnp.bfloat16).astype(jnp.float32) * 0.25
        ref = attention_reference(q, k, v, bias=abq)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_alibi_grads(self):
        from deepspeed_tpu.ops.pallas.paged_attention import alibi_slopes
        q, k, v = self._qkv(T=64)
        sl = alibi_slopes(4)
        ab = jnp.asarray(sl, jnp.float32)[None, :, None, None] \
            * jnp.arange(64, dtype=jnp.float32)[None, None, None, :]
        gf = jax.grad(lambda *a: jnp.sum(flash_attention(
            *a, alibi=sl, block_q=32, block_k=32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(attention_reference(
            *a, bias=ab) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_alibi_custom_slopes_rejected(self):
        q, k, v = self._qkv()
        with pytest.raises(NotImplementedError, match="bloom-formula"):
            flash_attention(q, k, v, alibi=[0.1, 0.2, 0.3, 0.4])

    def test_bias_with_ragged_seq(self):
        # padded keys must stay masked even with a bias present
        q, k, v = self._qkv(T=100)
        bias = jnp.asarray(np.random.RandomState(5).randn(2, 4, 1, 100),
                           jnp.float32) * 0.5
        o = flash_attention(q, k, v, bias=bias, block_q=64, block_k=64)
        ref = attention_reference(q, k, v, bias=bias)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_bloom_model_flash_matches_dense(self):
        from dataclasses import replace
        from deepspeed_tpu.models.bloom import Bloom, BLOOM_TINY
        cfg = replace(BLOOM_TINY, dtype="float32")
        dense = Bloom(replace(cfg, use_flash_attention=False))
        flash = Bloom(replace(cfg, use_flash_attention=True))
        params = dense.init(jax.random.key(0))
        ids = np.random.RandomState(0).randint(0, 512, (2, 64)).astype(
            np.int32)
        l0 = float(dense.loss(params, {"input_ids": ids}, train=False))
        l1 = float(flash.loss(params, {"input_ids": ids}, train=False))
        assert l1 == pytest.approx(l0, rel=1e-5)


class TestQuantization:
    @pytest.mark.parametrize("use_pallas", [True, False])
    def test_roundtrip_error_bound(self, use_pallas):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(1000) * 3.0, jnp.float32)
        q, s, meta = quantize_blockwise(x, block=256, use_pallas=use_pallas)
        assert q.dtype == jnp.int8
        back = dequantize_blockwise(q, s, meta, use_pallas=use_pallas)
        assert back.shape == x.shape
        # per-block absmax symmetric quant: error <= scale/2 per block
        scales = np.asarray(s).reshape(-1)
        err = np.abs(np.asarray(back) - np.asarray(x))
        blocked = np.pad(err, (0, 1024 - 1000)).reshape(4, 256)
        for b in range(4):
            assert blocked[b].max() <= scales[b] / 2 + 1e-7

    def test_pallas_matches_jnp(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(4, 512), jnp.float32)
        qp, sp, _ = quantize_blockwise(x, block=512, use_pallas=True)
        qr, sr, _ = quantize_blockwise(x, block=512, use_pallas=False)
        np.testing.assert_array_equal(np.asarray(qp), np.asarray(qr))
        np.testing.assert_allclose(np.asarray(sp), np.asarray(sr),
                                   rtol=1e-7)

    def test_zero_block(self):
        x = jnp.zeros((256,), jnp.float32)
        q, s, meta = quantize_blockwise(x, block=256)
        back = dequantize_blockwise(q, s, meta)
        np.testing.assert_array_equal(np.asarray(back), np.zeros(256))

    def test_tiled_grid_matches_jnp(self):
        # more blocks than one VMEM tile (_TILE_ROWS=256) + a ragged tile:
        # exercises the grid/BlockSpec streaming path end to end
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(300 * 64 + 17), jnp.float32)
        qp, sp, meta = quantize_blockwise(x, block=64, use_pallas=True)
        qr, sr, _ = quantize_blockwise(x, block=64, use_pallas=False)
        assert qp.shape[0] == 301  # 300 full + 1 padded block
        np.testing.assert_array_equal(np.asarray(qp), np.asarray(qr))
        np.testing.assert_allclose(np.asarray(sp), np.asarray(sr), rtol=1e-6)
        back = dequantize_blockwise(qp, sp, meta, use_pallas=True)
        backr = dequantize_blockwise(qr, sr, meta, use_pallas=False)
        np.testing.assert_allclose(np.asarray(back), np.asarray(backr),
                                   rtol=1e-6)

    def test_bf16_roundtrip(self):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(512), jnp.bfloat16)
        q, s, meta = quantize_blockwise(x, block=256)
        back = dequantize_blockwise(q, s, meta)
        assert back.dtype == jnp.bfloat16
        assert float(jnp.max(jnp.abs(back.astype(jnp.float32)
                                     - x.astype(jnp.float32)))) < 0.1


class TestQuantizedCollectives:
    def _mesh(self):
        groups.reset()
        topo = groups.initialize(TopologyConfig(data_parallel_size=8),
                                 force=True)
        return topo.mesh

    def test_quantized_all_gather(self):
        mesh = self._mesh()
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 256), jnp.float32)

        def f(x):
            return quantized_all_gather(x[0], "data", block=256)

        with jax.set_mesh(mesh):
            # check_vma off: every rank returns the same gathered value,
            # which the static vma analysis cannot prove
            out = jax.jit(jax.shard_map(
                f, in_specs=P("data"), out_specs=P(),
                axis_names={"data"}, check_vma=False))(x)
        # gathered result approximates the full array on every rank
        np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                                   atol=0.05)

    def test_quantized_psum_scatter(self):
        mesh = self._mesh()
        rng = np.random.RandomState(1)
        # each rank holds (64, 32); reduce-scatter over 8 ranks -> (8, 32)
        x = jnp.asarray(rng.randn(8, 64, 32), jnp.float32)

        def f(xs):
            return quantized_psum_scatter(xs[0], "data", block=256)

        with jax.set_mesh(mesh):
            out = jax.jit(jax.shard_map(
                f, in_specs=P("data"),
                out_specs=P("data"),
                axis_names={"data"}, check_vma=False))(x)
        ref = np.asarray(x).sum(axis=0)  # (64, 32) full reduction
        np.testing.assert_allclose(np.asarray(out).reshape(64, 32), ref,
                                   atol=8 * 0.05)


class TestPagedAttention:
    """Pallas paged-decode kernel vs the dense gather reference
    (reference inference/v2 ragged_ops blocked_flash role)."""

    def _setup(self, B=4, H=8, KVH=8, d=64, NB=32, BS=16, MB=8, seed=0):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(B, H, d), jnp.float32)
        kc = jnp.asarray(rng.randn(NB, KVH, BS, d), jnp.float32)
        vc = jnp.asarray(rng.randn(NB, KVH, BS, d), jnp.float32)
        tbl = jnp.asarray(rng.randint(0, NB, (B, MB)), jnp.int32)
        return q, kc, vc, tbl

    def test_matches_dense_gather(self):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention, paged_decode_attention_reference)
        q, kc, vc, tbl = self._setup()
        lens = jnp.asarray([0, 5, 63, 127], jnp.int32)
        out = paged_decode_attention(q, kc, vc, tbl, lens)
        ref = paged_decode_attention_reference(q, kc, vc, tbl, lens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa_grouping(self):
        """H != KVH: q-head groups share kv heads without repeat_kv."""
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention, paged_decode_attention_reference)
        q, kc, vc, tbl = self._setup(H=8, KVH=2)
        lens = jnp.asarray([10, 40, 80, 120], jnp.int32)
        out = paged_decode_attention(q, kc, vc, tbl, lens)
        ref = paged_decode_attention_reference(q, kc, vc, tbl, lens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_length_isolation(self):
        """A slot's output depends only on its own blocks/length: changing
        another slot's table must not change it."""
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention)
        q, kc, vc, tbl = self._setup()
        lens = jnp.asarray([30, 30, 30, 30], jnp.int32)
        out1 = paged_decode_attention(q, kc, vc, tbl, lens)
        tbl2 = tbl.at[1].set((tbl[1] + 3) % 32)
        out2 = paged_decode_attention(q, kc, vc, tbl2, lens)
        np.testing.assert_array_equal(np.asarray(out1[0]),
                                      np.asarray(out2[0]))
        np.testing.assert_array_equal(np.asarray(out1[2]),
                                      np.asarray(out2[2]))
        assert not np.allclose(np.asarray(out1[1]), np.asarray(out2[1]))


class TestBlockSparseAttention:
    """Pallas block-sparse kernel vs the masked-dense reference
    (reference ops/sparse_attention Triton blocksparse role)."""

    def _qkv(self, B=2, T=256, H=4, d=32, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda s: jnp.asarray(rng.randn(B, T, H, d) * 0.3, jnp.float32)
        return mk(0), mk(1), mk(2)

    @pytest.mark.parametrize("causal", [True, False])
    def test_fixed_layout_parity(self, causal):
        from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig
        from deepspeed_tpu.ops.sparse_attention.sparse_self_attention \
            import SparseSelfAttention
        q, k, v = self._qkv()
        cfg = FixedSparsityConfig(num_heads=4, block=32)
        mk_ = SparseSelfAttention(cfg, causal=causal, use_kernel=True)
        md = SparseSelfAttention(cfg, causal=causal, use_kernel=False)
        assert mk_.density(256) < 1.0
        np.testing.assert_allclose(np.asarray(mk_(q, k, v)),
                                   np.asarray(md(q, k, v)),
                                   rtol=2e-5, atol=2e-5)

    def test_bigbird_grads_parity(self):
        from deepspeed_tpu.ops.sparse_attention import (
            BigBirdSparsityConfig)
        from deepspeed_tpu.ops.sparse_attention.sparse_self_attention \
            import SparseSelfAttention
        q, k, v = self._qkv()
        cfg = BigBirdSparsityConfig(num_heads=4, block=32)
        mk_ = SparseSelfAttention(cfg, causal=True, use_kernel=True)
        md = SparseSelfAttention(cfg, causal=True, use_kernel=False)
        gk = jax.grad(lambda *a: jnp.sum(mk_(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(lambda *a: jnp.sum(md(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gk, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_fully_masked_rows_zero(self):
        """Rows whose every block is absent must output exactly zero
        (masked-dense reference semantics)."""
        from deepspeed_tpu.ops.pallas.block_sparse_attention import (
            block_sparse_attention)
        q, k, v = self._qkv(T=64)
        layout = np.zeros((4, 2, 2), bool)
        layout[:, 1, :] = True          # rows in block 0 fully masked
        out = block_sparse_attention(q, k, v, layout, 32, causal=False)
        np.testing.assert_array_equal(np.asarray(out[:, :32]), 0.0)
        assert float(jnp.max(jnp.abs(out[:, 32:]))) > 0


class TestFlashHeadsMajor:
    """heads_major=True: (B, H, T, d) I/O — the kernel-native layout the
    GPT-2 flash path feeds (no transpose between qkv projection and
    kernel)."""

    def _qkv(self, B=2, T=128, H=4, d=32, dtype=jnp.float32, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda s: jnp.asarray(rng.randn(B, H, T, d), dtype) * 0.3
        return mk(0), mk(1), mk(2)

    def test_matches_default_layout(self):
        q, k, v = self._qkv()
        o = flash_attention(q, k, v, block_q=64, block_k=64,
                            heads_major=True)
        ot = flash_attention(q.transpose(0, 2, 1, 3),
                             k.transpose(0, 2, 1, 3),
                             v.transpose(0, 2, 1, 3),
                             block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(ot.transpose(0, 2, 1, 3)),
                                   rtol=1e-6, atol=1e-6)

    def test_grads_match_dense(self):
        q, k, v = self._qkv(T=64)

        def loss_f(q, k, v):
            o = flash_attention(q, k, v, block_q=32, block_k=32,
                                heads_major=True)
            return jnp.sum(o ** 2)

        def loss_r(q, k, v):
            o = attention_reference(q.transpose(0, 2, 1, 3),
                                    k.transpose(0, 2, 1, 3),
                                    v.transpose(0, 2, 1, 3))
            return jnp.sum(o ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_padded_seq(self):
        q, k, v = self._qkv(T=80)      # pads to the 128 block in-kernel
        o = flash_attention(q, k, v, heads_major=True)
        ref = attention_reference(q.transpose(0, 2, 1, 3),
                                  k.transpose(0, 2, 1, 3),
                                  v.transpose(0, 2, 1, 3))
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(ref.transpose(0, 2, 1, 3)),
                                   rtol=1e-5, atol=1e-5)


class TestFlashTransposedQKV:
    """qkv_t=True: (B, H, d, T) operands — the layout the qkv projection
    einsum naturally emits (T in lanes). Covers both backward delta
    paths (single key block and multi-block) and the small-shape
    fallback to the standard kernel (lane dims must be 128-divisible)."""

    def _qkv(self, B=2, T=256, H=4, d=32, dtype=jnp.float32, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda s: jnp.asarray(rng.randn(B, H, d, T), dtype) * 0.3
        return mk(0), mk(1), mk(2)

    def _tref(self, q, k, v, **kw):
        # (B, H, d, T) -> reference (B, T, H, d)
        t = lambda x: x.transpose(0, 3, 1, 2)
        return attention_reference(t(q), t(k), t(v), **kw)

    @pytest.mark.parametrize("blocks", [(128, 128), (256, 256)])
    def test_forward_matches_dense(self, blocks):
        q, k, v = self._qkv()
        o = flash_attention(q, k, v, qkv_t=True, block_q=blocks[0],
                            block_k=blocks[1])
        ref = self._tref(q, k, v)                # (B, T, H, d)
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(ref.transpose(0, 2, 1, 3)),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("blocks", [(128, 128), (256, 256)])
    def test_grads_match_dense(self, blocks):
        # (128, 128): multi-key-block grid (fp32 dq accumulation);
        # (256, 256): single key block (bf16-direct dq). Both use the
        # in-kernel rowsum(do*o) delta — the precomputed-delta branch is
        # exercised by test_lse_grad_ext_delta below.
        q, k, v = self._qkv()

        def loss_f(q, k, v):
            o = flash_attention(q, k, v, qkv_t=True, block_q=blocks[0],
                                block_k=blocks[1])
            return jnp.sum(o ** 2)

        def loss_r(q, k, v):
            return jnp.sum(self._tref(q, k, v) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_small_seq_falls_back(self):
        # T=64 < 128 lanes cannot lower transposed; the wrapper must
        # fall back to the standard kernel and still be exact
        q, k, v = self._qkv(T=64)
        o = flash_attention(q, k, v, qkv_t=True)
        ref = self._tref(q, k, v)
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(ref.transpose(0, 2, 1, 3)),
                                   rtol=1e-5, atol=1e-5)

    def test_small_blocks_fall_back(self):
        # explicit sub-128 backward blocks: gate must reject the
        # transposed path rather than crash at lowering
        q, k, v = self._qkv(T=256)

        def loss_f(q, k, v):
            o = flash_attention(q, k, v, qkv_t=True, block_q=128,
                                block_k=128, block_q_bwd=64, block_k_bwd=64)
            return jnp.sum(o ** 2)

        def loss_r(q, k, v):
            return jnp.sum(self._tref(q, k, v) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_lse_grad_ext_delta(self):
        # a loss term on the lse output sends a nonzero lse cotangent
        # into the backward -> the precomputed (ext) delta branch of
        # _bwd_kernel_t, otherwise unreachable from flash_attention
        from deepspeed_tpu.ops.pallas.flash_attention import (
            flash_attention_with_lse)
        q, k, v = self._qkv()

        def loss_f(q, k, v):
            o, lse = flash_attention_with_lse(q, k, v, qkv_t=True,
                                              block_q=256, block_k=256)
            return jnp.sum(o ** 2) + 0.1 * jnp.sum(lse ** 2)

        def loss_r(q, k, v):
            t = lambda x: x.transpose(0, 3, 1, 2)
            qq, kk, vv = t(q), t(k), t(v)
            s = jnp.einsum("bthd,bshd->bhts", qq, kk) / np.sqrt(q.shape[2])
            mask = jnp.tril(jnp.ones((s.shape[-2], s.shape[-1]), bool))
            s = jnp.where(mask[None, None], s, -1e30)
            lse = jax.nn.logsumexp(s, axis=-1)          # (B, H, T)
            o = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(s, -1), vv)
            return jnp.sum(o ** 2) + 0.1 * jnp.sum(lse ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            # gf: (B, H, d, T) -> reference layout (B, H, d, T) too (the
            # reference loss takes the same transposed inputs)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_ragged_seq_padded(self):
        # T=200: lane-dim 200 is not 128-divisible -> fallback path with
        # in-kernel pad masking
        q, k, v = self._qkv(T=200)
        o = flash_attention(q, k, v, qkv_t=True, block_q=256, block_k=256)
        ref = self._tref(q, k, v)
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(ref.transpose(0, 2, 1, 3)),
                                   rtol=1e-5, atol=1e-5)


class TestFusedLayerNorm:
    """ops/pallas/layernorm.py parity vs the model's jnp layernorm
    (reference csrc/transformer/normalize_kernels.cu role)."""

    def _ref(self, x, s, b, eps=1e-5):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, -1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mu), -1, keepdims=True)
        return ((x32 - mu) * jax.lax.rsqrt(var + eps)
                * s.astype(jnp.float32)
                + b.astype(jnp.float32)).astype(x.dtype)

    @pytest.mark.parametrize("shape,dt", [
        ((4, 37, 256), jnp.float32),       # padded rows (4*37 % 8 != 0)
        ((2, 128, 128), jnp.bfloat16),
        ((300, 384), jnp.float32),
    ])
    def test_fwd_bwd_parity(self, shape, dt):
        from deepspeed_tpu.ops.pallas.layernorm import fused_layernorm
        rng = np.random.RandomState(0)
        D = shape[-1]
        x = jnp.asarray(rng.randn(*shape), dt)
        s = jnp.asarray(1 + 0.1 * rng.randn(D), dt)
        b = jnp.asarray(0.1 * rng.randn(D), dt)
        tol = 2e-2 if dt == jnp.bfloat16 else 1e-5
        y = fused_layernorm(x, s, b, interpret=True)
        np.testing.assert_allclose(
            np.asarray(y, np.float32),
            np.asarray(self._ref(x, s, b), np.float32),
            rtol=tol, atol=tol)

        def f(x, s, b):
            return jnp.sum(jnp.sin(fused_layernorm(
                x, s, b, interpret=True).astype(jnp.float32)))

        def fr(x, s, b):
            return jnp.sum(jnp.sin(self._ref(x, s, b).astype(jnp.float32)))

        g = jax.grad(f, argnums=(0, 1, 2))(x, s, b)
        gr = jax.grad(fr, argnums=(0, 1, 2))(x, s, b)
        tol2 = 5e-2 if dt == jnp.bfloat16 else 1e-4
        for a, br_ in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(br_, np.float32),
                                       rtol=tol2, atol=tol2)

    def test_rejects_untileable_feature_dim(self):
        from deepspeed_tpu.ops.pallas.layernorm import fused_layernorm
        with pytest.raises(ValueError, match="128"):
            fused_layernorm(jnp.zeros((8, 100)), jnp.ones(100),
                            jnp.zeros(100), interpret=True)


    def test_hybrid_bwd_parity(self):
        """layernorm_fused_bwd: jnp forward + Pallas one-pass backward."""
        from deepspeed_tpu.ops.pallas.layernorm import layernorm_fused_bwd
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(3, 40, 256), jnp.float32)
        s = jnp.asarray(1 + 0.1 * rng.randn(256), jnp.float32)
        b = jnp.asarray(0.1 * rng.randn(256), jnp.float32)
        y = layernorm_fused_bwd(x, s, b, interpret=True)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(self._ref(x, s, b)),
                                   rtol=1e-5, atol=1e-5)

        def f(x, s, b):
            return jnp.sum(jnp.cos(layernorm_fused_bwd(
                x, s, b, interpret=True)))

        def fr(x, s, b):
            return jnp.sum(jnp.cos(self._ref(x, s, b)))

        g = jax.grad(f, argnums=(0, 1, 2))(x, s, b)
        gr = jax.grad(fr, argnums=(0, 1, 2))(x, s, b)
        for a, br_ in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(br_),
                                       rtol=1e-4, atol=1e-4)


class TestFusedRMSNorm:
    def test_matches_jnp(self):
        from deepspeed_tpu.ops.pallas.layernorm import fused_rmsnorm
        from deepspeed_tpu.models.llama import _rms_norm
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(3, 37, 256), jnp.float32)
        s = jnp.asarray(1 + 0.1 * rng.randn(256), jnp.float32)
        y = fused_rmsnorm(x, s, interpret=True)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(_rms_norm(x, s, 1e-5)),
                                   rtol=1e-5, atol=1e-5)



class TestBwdBlockCoverage:
    def _qkv(self, B=2, T=128, H=4, d=32, dtype=jnp.float32, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda s: jnp.asarray(rng.randn(B, H, T, d), dtype) * 0.3
        return mk(0), mk(1), mk(2)

    def test_bwd_blocks_nondividing_padded_seq(self):
        """Backward-only block sizes that do not divide the forward
        padding must still cover every key block (T pads to the lcm of
        ALL block sizes; a miss silently zeroes dk/dv tail blocks)."""
        q, k, v = self._qkv(T=96)       # pads beyond 96

        def loss_f(q, k, v):
            o = flash_attention(q, k, v, block_q=32, block_k=32,
                                block_q_bwd=64, block_k_bwd=48,
                                heads_major=True)
            return jnp.sum(o ** 2)

        def loss_r(q, k, v):
            o = attention_reference(q.transpose(0, 2, 1, 3),
                                    k.transpose(0, 2, 1, 3),
                                    v.transpose(0, 2, 1, 3))
            return jnp.sum(o.transpose(0, 2, 1, 3) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)


class TestFlashSlidingWindow:
    """window > 0: mistral sliding-window masking in the flash kernels
    (standard and transposed), forward and fused backward."""

    def _qkv(self, B=2, T=256, H=4, d=32, layout="btHd", seed=0):
        rng = np.random.RandomState(seed)
        shape = (B, T, H, d) if layout == "btHd" else (B, H, d, T)
        mk = lambda s: jnp.asarray(rng.randn(*shape), jnp.float32) * 0.3
        return mk(0), mk(1), mk(2)

    def _windowed_reference(self, q, k, v, window):
        B, T, H, d = q.shape
        s = jnp.einsum("bthd,bshd->bhts", q, k,
                       preferred_element_type=jnp.float32) / np.sqrt(d)
        i = jnp.arange(T)[:, None]
        j = jnp.arange(T)[None, :]
        mask = (i >= j) & (i - j < window)
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhts,bshd->bthd", p.astype(q.dtype), v)

    @pytest.mark.parametrize("window", [8, 100, 1000])
    def test_forward_matches_windowed_dense(self, window):
        q, k, v = self._qkv()
        o = flash_attention(q, k, v, window=window, block_q=64,
                            block_k=64)
        ref = self._windowed_reference(q, k, v, window)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("blocks", [(64, 64), (128, 256)])
    def test_grads_match_windowed_dense(self, blocks):
        q, k, v = self._qkv()
        window = 40

        def loss_f(q, k, v):
            o = flash_attention(q, k, v, window=window,
                                block_q=blocks[0], block_k=blocks[1])
            return jnp.sum(o ** 2)

        def loss_r(q, k, v):
            return jnp.sum(
                self._windowed_reference(q, k, v, window) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_transposed_layout_window_grads(self):
        qt, kt, vt = self._qkv(layout="bHdT")
        window = 48

        def loss_f(q, k, v):
            o = flash_attention(q, k, v, qkv_t=True, window=window,
                                block_q=128, block_k=128)
            return jnp.sum(o ** 2)

        def loss_r(q, k, v):
            t = lambda x: x.transpose(0, 3, 1, 2)
            return jnp.sum(self._windowed_reference(
                t(q), t(k), t(v), window) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(qt, kt, vt)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(qt, kt, vt)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_window_noncausal_rejected(self):
        q, k, v = self._qkv(T=128)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=8)


class TestPagedWindowAlibi:
    """window/ALiBi knobs of the paged decode kernel vs the dense-gather
    reference (reference inference/v2 blocked attention semantics for
    mistral/bloom)."""

    def _setup(self, B=3, H=4, KVH=2, d=32, NB=12, BS=16, MB=4, seed=0):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention, paged_decode_attention_reference)
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(B, H, d), jnp.float32) * 0.3
        kc = jnp.asarray(rng.randn(NB, KVH, BS, d), jnp.float32) * 0.3
        vc = jnp.asarray(rng.randn(NB, KVH, BS, d), jnp.float32) * 0.3
        tables = jnp.asarray(
            rng.permutation(NB)[:B * MB].reshape(B, MB), jnp.int32)
        lengths = jnp.asarray([5, 37, 60], jnp.int32)
        return (paged_decode_attention, paged_decode_attention_reference,
                q, kc, vc, tables, lengths)

    def test_window_matches_reference(self):
        kern, ref, q, kc, vc, tables, lengths = self._setup()
        got = kern(q, kc, vc, tables, lengths, window=10)
        want = ref(q, kc, vc, tables, lengths, window=10)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_alibi_matches_reference(self):
        from deepspeed_tpu.ops.pallas.paged_attention import alibi_slopes
        kern, ref, q, kc, vc, tables, lengths = self._setup()
        sl = alibi_slopes(q.shape[1])
        got = kern(q, kc, vc, tables, lengths, alibi_slopes=sl)
        want = ref(q, kc, vc, tables, lengths, alibi_slopes=sl)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_alibi_window_combined(self):
        from deepspeed_tpu.ops.pallas.paged_attention import alibi_slopes
        kern, ref, q, kc, vc, tables, lengths = self._setup(seed=3)
        sl = alibi_slopes(q.shape[1])
        got = kern(q, kc, vc, tables, lengths, window=20, alibi_slopes=sl)
        want = ref(q, kc, vc, tables, lengths, window=20,
                   alibi_slopes=sl)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_slopes_formula(self):
        from deepspeed_tpu.ops.pallas.paged_attention import alibi_slopes
        # canonical published values for 8 heads
        np.testing.assert_allclose(
            alibi_slopes(8),
            [2 ** (-(i + 1)) for i in range(8)], rtol=1e-9)
        # non-power-of-two interleave (bloom formula), 6 heads
        s6 = alibi_slopes(6)
        assert s6[:4] == alibi_slopes(4)
        np.testing.assert_allclose(
            s6[4:], [2 ** (-1.0), 2 ** (-3.0)], rtol=1e-9)


class TestFlashBwdQMajor:
    """Query-major fused backward (bwd_qmajor=True): dq written once per
    grid step in the model dtype, dk/dv VMEM-resident fp32 accumulators.
    Must match the k-major kernel (and the dense reference) on every
    covered path; biased paths silently keep the k-major kernel."""

    def _qkv(self, B=2, T=256, H=4, d=32, dtype=jnp.float32, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda s: jnp.asarray(rng.randn(B, H, d, T), dtype) * 0.3
        return mk(0), mk(1), mk(2)

    def _grads(self, q, k, v, qmajor, **kw):
        def loss(q, k, v):
            o = flash_attention(q, k, v, qkv_t=True, bwd_qmajor=qmajor,
                                **kw)
            return jnp.sum(o.astype(jnp.float32) ** 2)
        return jax.grad(loss, (0, 1, 2))(q, k, v)

    @pytest.mark.parametrize("blocks", [(128, 128), (256, 256),
                                        (64, 128)])
    def test_matches_kmajor(self, blocks):
        q, k, v = self._qkv()
        kw = dict(block_q=blocks[0], block_k=blocks[1])
        for a, b, n in zip(self._grads(q, k, v, True, **kw),
                           self._grads(q, k, v, False, **kw), "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{n}")

    def test_matches_dense(self):
        q, k, v = self._qkv()
        t = lambda x: x.transpose(0, 3, 1, 2)

        def ref_loss(q, k, v):
            return jnp.sum(attention_reference(
                t(q), t(k), t(v), causal=True).astype(jnp.float32) ** 2)

        gr = jax.grad(ref_loss, (0, 1, 2))(q, k, v)
        gq = self._grads(q, k, v, True, block_q=128, block_k=128)
        for a, b, n in zip(gq, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{n}")

    def test_sliding_window(self):
        q, k, v = self._qkv()
        kw = dict(block_q=128, block_k=128, window=100)
        for a, b, n in zip(self._grads(q, k, v, True, **kw),
                           self._grads(q, k, v, False, **kw), "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{n}")

    def test_padded_seq(self):
        q, k, v = self._qkv(T=200)
        kw = dict(block_q=128, block_k=128)
        for a, b, n in zip(self._grads(q, k, v, True, **kw),
                           self._grads(q, k, v, False, **kw), "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{n}")

    def test_lse_cotangent_ext_delta(self):
        from deepspeed_tpu.ops.pallas.flash_attention import (
            flash_attention_with_lse)
        q, k, v = self._qkv()

        def loss(qmajor):
            def f(q, k, v):
                o, lse = flash_attention_with_lse(
                    q, k, v, qkv_t=True, block_q=128, block_k=128,
                    bwd_qmajor=qmajor)
                return (jnp.sum(o.astype(jnp.float32) ** 2)
                        + 0.1 * jnp.sum(lse))
            return f

        ga = jax.grad(loss(True), (0, 1, 2))(q, k, v)
        gb = jax.grad(loss(False), (0, 1, 2))(q, k, v)
        for a, b, n in zip(ga, gb, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{n}")

    def test_biased_path_falls_back(self):
        # a bias forces the k-major kernel; result must still be correct
        q, k, v = self._qkv(T=128)
        bias = jnp.asarray(
            np.random.RandomState(3).randn(2, 4, 1, 128), jnp.float32)
        o = flash_attention(q, k, v, qkv_t=True, bias=bias,
                            bwd_qmajor=True, block_q=128, block_k=128)
        t = lambda x: x.transpose(0, 3, 1, 2)
        ref = attention_reference(t(q), t(k), t(v), bias=bias,
                                  causal=True)
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(ref.transpose(0, 2, 1, 3)),
                                   rtol=1e-5, atol=1e-5)

    def test_in_model(self):
        from dataclasses import replace
        from deepspeed_tpu.models.gpt2 import GPT2, GPT2_TINY
        cfg = replace(GPT2_TINY, remat=False, use_flash_attention=True,
                      flash_bwd_qmajor=True)
        dense = GPT2(replace(cfg, use_flash_attention=False))
        flash = GPT2(cfg)
        params = dense.init(jax.random.PRNGKey(0))
        batch = {"input_ids": np.random.RandomState(0)
                 .randint(0, 1024, (2, 128)).astype(np.int32)}
        l0, g0 = jax.value_and_grad(
            lambda p: dense.loss(p, batch, train=False))(params)
        l1, g1 = jax.value_and_grad(
            lambda p: flash.loss(p, batch, train=False))(params)
        assert abs(float(l0) - float(l1)) < 5e-2
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=5e-2, atol=5e-2)
