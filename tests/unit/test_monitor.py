"""Monitor fan-out tests (ISSUE 9 satellites): csv slash-tag
round-trip, wandb per-step batching, MonitorMaster fan-out and rank-0
gating, and import-failure degradation."""

import os
import sys
import types

import pytest

from deepspeed_tpu.monitor.config import (DeepSpeedMonitorConfig,
                                          CSVConfig, WandbConfig)
from deepspeed_tpu.monitor.monitor import (MonitorMaster, Monitor,
                                           csvMonitor, WandbMonitor)


class _StubMonitor(Monitor):
    def __init__(self):
        self.events = []
        self.flushes = 0

    def write_events(self, event_list):
        self.events.extend(event_list)

    def flush(self):
        self.flushes += 1


def _csv_cfg(tmp_path):
    return CSVConfig(enabled=True, output_path=str(tmp_path),
                     job_name="job")


class TestCsvMonitor:
    def test_slash_tags_round_trip(self, tmp_path):
        """Regression (ISSUE 9 satellite): production tags carry '/'
        (Train/Samples/lr, Train/Checkpoint/save_latency_ms) — the
        one-file-per-tag layout must sanitize them instead of open()ing
        into a nonexistent subdirectory."""
        mon = csvMonitor(_csv_cfg(tmp_path))
        events = [("Train/Samples/lr", 0.001, 1),
                  ("Train/Checkpoint/save_latency_ms", 12.5, 1),
                  ("Train/Samples/lr", 0.002, 2)]
        mon.write_events(events)
        mon.flush()
        path = os.path.join(str(tmp_path), "job", "Train_Samples_lr.csv")
        assert os.path.exists(path)
        with open(path) as f:
            rows = [line.strip().split(",") for line in f if line.strip()]
        assert rows == [["1", "0.001"], ["2", "0.002"]]
        ckpt = os.path.join(str(tmp_path), "job",
                            "Train_Checkpoint_save_latency_ms.csv")
        assert os.path.exists(ckpt)

    def test_no_subdirectories_created(self, tmp_path):
        mon = csvMonitor(_csv_cfg(tmp_path))
        mon.write_events([("Train/Telemetry/mfu_pct", 33.3, 5)])
        job_dir = os.path.join(str(tmp_path), "job")
        entries = os.listdir(job_dir)
        assert entries and all(
            os.path.isfile(os.path.join(job_dir, e)) for e in entries), \
            f"slash tags must not create subdirectories: {entries}"


class TestWandbBatching:
    def _fake_wandb(self):
        calls = []
        mod = types.ModuleType("wandb")
        mod.init = lambda **kw: calls.append(("init", kw))
        mod.log = lambda data, step=None: calls.append(
            ("log", dict(data), step))
        return mod, calls

    def test_one_log_call_per_step(self, monkeypatch):
        """ISSUE 9 satellite: all tags of a step batch into ONE
        wandb.log dict — N sequential calls with a repeated step kwarg
        are treated as out-of-order by wandb and silently dropped."""
        mod, calls = self._fake_wandb()
        monkeypatch.setitem(sys.modules, "wandb", mod)
        mon = WandbMonitor(WandbConfig(enabled=True))
        mon.write_events([("Train/Samples/lr", 0.1, 7),
                          ("Train/Samples/train_loss", 2.5, 7),
                          ("Train/Telemetry/mfu_pct", 41.0, 7)])
        logs = [c for c in calls if c[0] == "log"]
        assert len(logs) == 1
        _, data, step = logs[0]
        assert step == 7
        assert data == {"Train/Samples/lr": 0.1,
                        "Train/Samples/train_loss": 2.5,
                        "Train/Telemetry/mfu_pct": 41.0}

    def test_multiple_steps_ordered(self, monkeypatch):
        mod, calls = self._fake_wandb()
        monkeypatch.setitem(sys.modules, "wandb", mod)
        mon = WandbMonitor(WandbConfig(enabled=True))
        mon.write_events([("a/b/c", 1.0, 9), ("a/b/d", 2.0, 8),
                          ("a/b/c", 3.0, 8)])
        logs = [c for c in calls if c[0] == "log"]
        assert [c[2] for c in logs] == [8, 9]
        assert logs[0][1] == {"a/b/d": 2.0, "a/b/c": 3.0}


class TestMonitorMaster:
    def _master_cfg(self, tmp_path):
        return DeepSpeedMonitorConfig.from_dict({
            "csv_monitor": {"enabled": True,
                            "output_path": str(tmp_path),
                            "job_name": "fanout"}})

    def test_fan_out_reaches_every_writer(self, tmp_path):
        master = MonitorMaster(self._master_cfg(tmp_path))
        assert master.enabled
        stub = _StubMonitor()
        master.monitors.append(stub)
        events = [("Train/Samples/lr", 0.5, 3)]
        master.write_events(events)
        master.flush()
        assert stub.events == events
        assert os.path.exists(os.path.join(
            str(tmp_path), "fanout", "Train_Samples_lr.csv"))

    def test_disabled_config_writes_nothing(self):
        master = MonitorMaster(DeepSpeedMonitorConfig.from_dict({}))
        assert not master.enabled
        master.write_events([("a/b/c", 1.0, 1)])   # must not raise

    def test_rank0_gating(self, tmp_path, monkeypatch):
        """Only jax.process_index() == 0 writes (the reference's rank
        gate realized on process index)."""
        import jax
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        master = MonitorMaster(self._master_cfg(tmp_path))
        assert not master.enabled
        assert master.monitors == []

    def test_backend_import_failure_degrades(self, tmp_path,
                                             monkeypatch):
        """An unavailable optional backend downgrades to a warning
        (reference hard-requires the package)."""
        import builtins
        real_import = builtins.__import__

        def failing(name, *a, **kw):
            if name == "wandb":
                raise ImportError("no wandb in this container")
            return real_import(name, *a, **kw)

        monkeypatch.setattr(builtins, "__import__", failing)
        cfg = DeepSpeedMonitorConfig.from_dict({
            "wandb": {"enabled": True},
            "csv_monitor": {"enabled": True,
                            "output_path": str(tmp_path),
                            "job_name": "degrade"}})
        master = MonitorMaster(cfg)
        assert master.enabled           # csv still works
        assert len(master.monitors) == 1
        assert isinstance(master.monitors[0], csvMonitor)


class TestTensorBoardOptional:
    def test_tensorboard_skipped_without_torch(self, tmp_path):
        pytest.importorskip("torch.utils.tensorboard")
        from deepspeed_tpu.monitor.config import TensorBoardConfig
        from deepspeed_tpu.monitor.monitor import TensorBoardMonitor
        mon = TensorBoardMonitor(TensorBoardConfig(
            enabled=True, output_path=str(tmp_path), job_name="tb"))
        mon.write_events([("Train/Samples/lr", 0.1, 1)])
        mon.flush()
