"""checkpoint/universal.py CLI entry point (ISSUE 7 satellite): the
``main`` argv surface round-trips fp32 consolidation, universal
explosion, and inspect — including the sharded per-host tag-dir layout
— without ever building an engine (plain numpy trees through the real
serialization paths, so the whole file stays tier-1 fast)."""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.checkpoint.universal import (load_consolidated,
                                                load_universal_param,
                                                main)
from deepspeed_tpu.runtime.checkpoint_engine import manager
from deepspeed_tpu.runtime.checkpoint_engine import serialization as ser


def _tree(step):
    return {"master": {"wte": np.arange(12, dtype=np.float32).reshape(
        4, 3) + step,
        "blocks": {"w": np.ones((2, 6), np.float32) * step}},
        "opt": {"m": {"wte": np.zeros((4, 3), np.float32)}},
        "step": np.asarray(step, np.int64)}


def _write_monolithic(tmp_path, step=3):
    """Legacy single-writer layout: {dir}/{tag}/state.npz + latest."""
    tag = f"global_step{step}"
    os.makedirs(tmp_path / tag)
    ser.save_file(str(tmp_path / tag / "state.npz"), _tree(step),
                  extra_meta={"global_step": step, "zero_stage": 2})
    manager.publish_latest(str(tmp_path), tag)
    return str(tmp_path), tag


def _write_sharded(tmp_path, step=5, nprocs=2):
    """The sharded per-host tag-dir layout: each writer's chunks +
    reassembly index in its own shard-{p}.npz (hand-built second writer
    — a single test process has one jax process index)."""
    tag = f"global_step{step}"
    tree = _tree(step)
    full = tree["master"]["wte"]
    half = full.shape[0] // 2

    def _shard(pid, rows):
        chunks = {f"master/wte#{pid}.0": full[rows]}
        index = {"master/wte": {
            "shape": list(full.shape), "dtype": "float32",
            "chunks": [{"key": f"master/wte#{pid}.0",
                        "start": [rows.start, 0]}]}}
        if pid == 0:
            for key, arr in (("master/blocks/w",
                              tree["master"]["blocks"]["w"]),
                             ("opt/m/wte", tree["opt"]["m"]["wte"]),
                             ("step", tree["step"])):
                arr = np.asarray(arr)
                chunks[f"{key}#0.0"] = arr
                index[key] = {"shape": list(arr.shape),
                              "dtype": str(arr.dtype),
                              "chunks": [{"key": f"{key}#0.0",
                                          "start": [0] * arr.ndim}]}
        else:
            for key, arr in (("master/blocks/w",
                              tree["master"]["blocks"]["w"]),
                             ("opt/m/wte", tree["opt"]["m"]["wte"]),
                             ("step", tree["step"])):
                arr = np.asarray(arr)
                index[key] = {"shape": list(arr.shape),
                              "dtype": str(arr.dtype), "chunks": []}
        extra = {"index": index, "__tree_meta__": {},
                 "user_extra": {"global_step": step, "zero_stage": 3,
                                "nprocs": nprocs}}
        ser.save_file(str(tmp_path / tag / f"shard-{pid}.npz"),
                      chunks, extra_meta=extra)

    os.makedirs(tmp_path / tag)
    _shard(0, slice(0, half))
    _shard(1, slice(half, full.shape[0]))
    manager.publish_latest(str(tmp_path), tag)
    return str(tmp_path), tag


class TestCLIMonolithic:
    def test_fp32_roundtrip_through_argv(self, tmp_path, capsys):
        ckpt, _ = _write_monolithic(tmp_path / "ck")
        out = str(tmp_path / "fp32.npz")
        assert main(["fp32", ckpt, out]) == 0
        assert "wrote" in capsys.readouterr().out
        weights = load_consolidated(out)
        np.testing.assert_array_equal(weights["wte"],
                                      _tree(3)["master"]["wte"])
        assert all(not k.startswith("opt") for k in weights)

    def test_universal_roundtrip_through_argv(self, tmp_path, capsys):
        ckpt, _ = _write_monolithic(tmp_path / "ck")
        out_dir = str(tmp_path / "uni")
        assert main(["universal", ckpt, out_dir]) == 0
        assert "tensors" in capsys.readouterr().out
        one = load_universal_param(out_dir, "master/wte")
        np.testing.assert_array_equal(one, _tree(3)["master"]["wte"])
        idx = json.load(open(os.path.join(out_dir, "index.json")))
        assert idx["extra"]["zero_stage"] == 2

    def test_inspect_through_argv(self, tmp_path, capsys):
        ckpt, _ = _write_monolithic(tmp_path / "ck")
        assert main(["inspect", ckpt]) == 0
        out = capsys.readouterr().out
        assert "master/wte" in out and "step=3" in out

    def test_bad_command_exits_nonzero(self, capsys):
        with pytest.raises(SystemExit):
            main(["nonsense"])


class TestCLISharded:
    """The per-host tag-dir layout through the same argv surface: the
    CLI reassembles the global logical tensors from the shard chunks."""

    def test_fp32_consolidates_chunked_leaves(self, tmp_path, capsys):
        ckpt, _ = _write_sharded(tmp_path / "ck")
        out = str(tmp_path / "fp32.npz")
        assert main(["fp32", ckpt, out]) == 0
        weights = load_consolidated(out)
        # the wte rows written by TWO different hosts reassemble
        np.testing.assert_array_equal(weights["wte"],
                                      _tree(5)["master"]["wte"])
        np.testing.assert_array_equal(weights["blocks/w"],
                                      _tree(5)["master"]["blocks"]["w"])

    def test_universal_explodes_sharded_tag_dir(self, tmp_path):
        ckpt, tag = _write_sharded(tmp_path / "ck")
        out_dir = str(tmp_path / "uni")
        assert main(["universal", ckpt, out_dir]) == 0
        np.testing.assert_array_equal(
            load_universal_param(out_dir, "master/wte"),
            _tree(5)["master"]["wte"])
        idx = json.load(open(os.path.join(out_dir, "index.json")))
        assert idx["extra"]["zero_stage"] == 3
        assert idx["extra"]["nprocs"] == 2

    def test_direct_tag_dir_without_latest(self, tmp_path, capsys):
        """A bare tag directory (no 'latest' pointer) resolves too —
        the documented escape hatch for inspecting one generation."""
        ckpt, tag = _write_sharded(tmp_path / "ck")
        os.remove(os.path.join(ckpt, "latest"))
        assert main(["inspect", os.path.join(ckpt, tag)]) == 0
        out = capsys.readouterr().out
        assert "master/wte" in out

    def test_torn_sharded_layout_fails_loudly(self, tmp_path):
        """A missing shard file must raise through the CLI, never
        consolidate garbage from a half-covered buffer."""
        ckpt, tag = _write_sharded(tmp_path / "ck")
        os.remove(os.path.join(ckpt, tag, "shard-1.npz"))
        with pytest.raises(ValueError, match="nprocs|covered"):
            main(["fp32", ckpt, str(tmp_path / "out.npz")])
