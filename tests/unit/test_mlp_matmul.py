"""Layout-owning MLP projection matmul kernel parity (interpret mode).

Counterpart of reference tests/unit/ops/ kernel parity for the fused
GEMM tier (csrc/transformer/cublas_wrappers.cu). Covers both operand
orientations (row-major and T-in-lanes), both output orientations, the
fused dx/dw backward epilogues, and the jnp fallback for untileable
shapes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.mlp_matmul import _ref_proj, mlp_matmul

_KW = dict(block_t=128, block_o=128, block_k=256, interpret=True)


def _rand(rng, shape, dt):
    return jax.random.normal(rng, shape, dt)


class TestMlpMatmulForward:
    @pytest.mark.parametrize("d", [64, 128])
    @pytest.mark.parametrize("x_t", [False, True])
    @pytest.mark.parametrize("out_t", [False, True])
    def test_matches_reference(self, d, x_t, out_t):
        """Both layouts at head-dim-scale feature sizes (64 / 128)."""
        B, T, K = 2, 256, 256
        ks = jax.random.split(jax.random.PRNGKey(0), 2)
        x = _rand(ks[0], (B, K, T) if x_t else (B, T, K), jnp.bfloat16)
        w = _rand(ks[1], (K, d), jnp.bfloat16)
        y = mlp_matmul(x, w, x_t=x_t, out_t=out_t, **_KW)
        assert y.shape == ((B, d, T) if out_t else (B, T, d))
        np.testing.assert_allclose(
            np.asarray(y, np.float32),
            np.asarray(_ref_proj(x, w, x_t, out_t), np.float32),
            rtol=2e-2, atol=2e-2)

    def test_fp32_exact(self):
        x = _rand(jax.random.PRNGKey(0), (1, 64, 128), jnp.float32)
        w = _rand(jax.random.PRNGKey(1), (128, 64), jnp.float32)
        y = mlp_matmul(x, w, **_KW)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(_ref_proj(x, w, False, False)),
                                   rtol=1e-5, atol=1e-5)

    def test_untileable_falls_back(self):
        # 100 is not 8/128-tileable -> jnp fallback, same math
        x = _rand(jax.random.PRNGKey(0), (2, 100, 96), jnp.float32)
        w = _rand(jax.random.PRNGKey(1), (96, 100), jnp.float32)
        y = mlp_matmul(x, w, **_KW)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(_ref_proj(x, w, False, False)),
                                   rtol=1e-5, atol=1e-5)

    def test_shape_validation(self):
        with pytest.raises(ValueError, match="mlp_matmul expects"):
            mlp_matmul(jnp.zeros((4, 4)), jnp.zeros((4, 4)))
        with pytest.raises(ValueError, match="contract dim"):
            mlp_matmul(jnp.zeros((1, 8, 16)), jnp.zeros((8, 16)))


class TestMlpMatmulBackward:
    @pytest.mark.parametrize("d", [64, 128])
    @pytest.mark.parametrize("x_t,out_t", [(False, False), (True, False),
                                           (False, True), (True, True)])
    def test_grads_match_reference(self, d, x_t, out_t):
        B, T, K = 2, 256, 256
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        x = _rand(ks[0], (B, K, T) if x_t else (B, T, K), jnp.bfloat16)
        w = _rand(ks[1], (K, d), jnp.bfloat16)
        dy = _rand(ks[2], (B, d, T) if out_t else (B, T, d), jnp.bfloat16)

        def f(x, w):
            return jnp.sum(mlp_matmul(x, w, x_t=x_t, out_t=out_t, **_KW)
                           .astype(jnp.float32) * dy.astype(jnp.float32))

        def fr(x, w):
            return jnp.sum(_ref_proj(x, w, x_t, out_t).astype(jnp.float32)
                           * dy.astype(jnp.float32))

        gx, gw = jax.grad(f, (0, 1))(x, w)
        gxr, gwr = jax.grad(fr, (0, 1))(x, w)
        assert gx.shape == x.shape and gw.shape == w.shape
        np.testing.assert_allclose(np.asarray(gx, np.float32),
                                   np.asarray(gxr, np.float32),
                                   rtol=5e-2, atol=5e-2)
        # dw sums over B*T fp32 both sides; bf16 inputs -> looser atol
        np.testing.assert_allclose(np.asarray(gw, np.float32),
                                   np.asarray(gwr, np.float32),
                                   rtol=5e-2, atol=5e-1)

    @pytest.mark.parametrize("fuse_dw", [True, False])
    def test_gradcheck_fp32_epilogues(self, fuse_dw):
        """Analytic grads through the fused dx/dw epilogue kernels vs
        the autodiff of the jnp reference, fp32 (tight tolerance)."""
        B, T, K, d = 1, 128, 128, 64
        ks = jax.random.split(jax.random.PRNGKey(2), 2)
        x = _rand(ks[0], (B, K, T), jnp.float32)    # T-minor operand
        w = _rand(ks[1], (K, d), jnp.float32)

        def f(x, w):
            return jnp.sum(mlp_matmul(x, w, x_t=True, fuse_dw=fuse_dw,
                                      **_KW) ** 2)

        def fr(x, w):
            return jnp.sum(_ref_proj(x, w, True, False) ** 2)

        for a, b in zip(jax.grad(f, (0, 1))(x, w),
                        jax.grad(fr, (0, 1))(x, w)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)


class TestMlpKernelInModel:
    """cfg.mlp_kernel wiring: loss/grad parity vs the XLA MLP path."""

    pytestmark = pytest.mark.slow

    def _setup(self):
        from dataclasses import replace
        from deepspeed_tpu.models.gpt2 import GPT2, GPT2_TINY
        cfg = replace(GPT2_TINY, remat=False)
        m = GPT2(cfg)
        params = m.init(jax.random.PRNGKey(0))
        batch = {"input_ids": np.random.RandomState(0)
                 .randint(0, 1024, (2, 128)).astype(np.int32)}
        return cfg, m, params, batch

    @pytest.mark.parametrize("mode", ["down", "both"])
    def test_loss_and_grad_parity(self, mode):
        from dataclasses import replace
        from deepspeed_tpu.models.gpt2 import GPT2
        cfg, m0, params, batch = self._setup()
        l0, g0 = jax.value_and_grad(
            lambda p: m0.loss(p, batch, train=False))(params)
        m1 = GPT2(replace(cfg, mlp_kernel=mode))
        l1, g1 = jax.value_and_grad(
            lambda p: m1.loss(p, batch, train=False))(params)
        assert abs(float(l0) - float(l1)) < 3e-2
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=5e-2, atol=5e-2)

    def test_remat_policies_compose(self):
        from dataclasses import replace
        from deepspeed_tpu.models.gpt2 import GPT2
        cfg, m0, params, batch = self._setup()
        l0 = float(m0.loss(params, batch, train=False))
        m1 = GPT2(replace(cfg, mlp_kernel="down", remat=True,
                          remat_policy="save_flash"))
        l1, _ = jax.value_and_grad(
            lambda p: m1.loss(p, batch, train=False))(params)
        assert abs(float(l1) - l0) < 3e-2

    def test_auto_defers_to_measured_dispatch(self):
        """'auto' no longer hand-guesses by platform: it defers to the
        autotune winner cache (resolved in _mlp where the activation
        shape is known), and a cache miss keeps the r05-proven XLA
        path — loss identical to mlp_kernel=False."""
        from dataclasses import replace
        from deepspeed_tpu.autotuning import kernel_dispatch
        from deepspeed_tpu.models.gpt2 import GPT2
        cfg, m0, params, batch = self._setup()
        m = GPT2(replace(cfg, mlp_kernel="auto"))
        assert m._mlp_kernel_mode() == "auto"
        kernel_dispatch.reset()
        kernel_dispatch.configure(mode="cache_only",
                                  cache_path="/nonexistent/at.json")
        try:
            l_auto = float(m.loss(params, batch, train=False))
            l_xla = float(m0.loss(params, batch, train=False))
            assert l_auto == l_xla
        finally:
            kernel_dispatch.reset()
