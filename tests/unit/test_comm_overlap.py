"""Comm-overlap layer (runtime/zero/overlap.py): loss parity with the
annotations on, and HLO-level assertions that the compiled dp>=2 step
carries the collectives the overlap design requests — per-scan-iteration
grad reduction inside the backward loop, the ZeRO-3 gather, hierarchical
placement on the ('data' then 'data_outer') axes — plus the async
start/done pair detector the TPU path relies on (CPU lowers collectives
synchronously, so the detector is exercised on a canned TPU-style
module; the REAL dp>=2 program asserts placement and axes).

Counterpart of the reference's overlap_comm coverage
(tests/unit/runtime/zero/test_zero.py) — there the assertion is "loss
matches DDP with overlap_comm=True"; here XLA lets us additionally
assert the emitted schedule."""

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.runtime.zero import overlap as ov
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

from jax.sharding import PartitionSpec as P  # noqa: E402

CFG = GPT2Config(n_layer=4, n_head=2, d_model=64, max_seq_len=32,
                 vocab_size=256, remat=False, dtype="float32")


def _engine(dp, stage=2, overlap=True, shard=-1, train_batch=4, **co):
    groups.reset()
    topo = groups.initialize(
        TopologyConfig(data_parallel_size=dp, zero_shard_size=shard),
        devices=jax.devices()[:dp], force=True)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2(CFG), topology=topo, config={
            "train_batch_size": train_batch,
            "steps_per_print": 0,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "gradient_clipping": 1.0,
            "zero_optimization": {"stage": stage},
            "comm_overlap": {"enabled": overlap, "bucket_mb": 0, **co},
        })
    return engine


def _batch(n=4):
    rng = np.random.RandomState(0)
    return {"input_ids": rng.randint(0, CFG.vocab_size,
                                     (n, CFG.max_seq_len)).astype(np.int32)}


# --------------------------------------------------------- loss parity

def test_loss_parity_dp1_vs_dp2_overlap_on():
    """The per-layer reduction annotations reorder WHERE collectives are
    emitted, never the math: dp=2 with overlap on must track dp=1 with
    overlap off on the same global batch."""
    batch = _batch()
    e1 = _engine(1, overlap=False)
    base = [float(e1.train_batch(batch)) for _ in range(3)]
    e2 = _engine(2, overlap=True)
    got = [float(e2.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-6)


def test_loss_parity_zero3_prefetch():
    """ZeRO-3 with the explicit per-layer gather (prefetch) on: same
    losses as stage 0, and the engine installed the scan-unroll hint
    that double-buffers the gather."""
    batch = _batch()
    e0 = _engine(2, stage=0, overlap=False)
    base = [float(e0.train_batch(batch)) for _ in range(3)]
    e3 = _engine(2, stage=3, overlap=True)
    assert getattr(e3.model, "_scan_unroll_min", 0) == 2
    assert e3.model._layer_comm_hook is not None
    got = [float(e3.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-6)


def test_loss_parity_hierarchical():
    """Two-stage ('data' then 'data_outer') reduction: same losses as the
    flat dp=4 reduction."""
    batch = _batch(8)
    flat = _engine(4, overlap=False, train_batch=8)
    base = [float(flat.train_batch(batch)) for _ in range(3)]
    hier = _engine(4, shard=2, overlap=True, hierarchical=True,
                   train_batch=8)
    assert hier._overlap_hier
    got = [float(hier.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-6)


def test_dcn_quantize_trains():
    """int8 round-trip on the DCN-stage cotangent (ZeRO++ qgZ numerics)
    perturbs gradients within quantization error — training must still
    converge on a repeated batch."""
    batch = _batch(8)
    eng = _engine(4, shard=2, overlap=True, hierarchical=True,
                  dcn_quantize=True, train_batch=8)
    losses = [float(eng.train_batch(batch)) for _ in range(4)]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


# ------------------------------------------------------ HLO assertions

# A canned TPU-style module: the async start/done pairs TPU emits under
# the overlap flags (CPU never lowers these forms, so the detector is
# pinned against this text).
_ASYNC_HLO = """
HloModule jit_train_step

ENTRY %main (p0: f32[8,16]) -> f32[8,16] {
  %p0 = f32[8,16]{1,0} parameter(0)
  %all-gather-start = (f32[8,16]{1,0}, f32[16,16]{1,0}) all-gather-start(f32[8,16]{1,0} %p0), replica_groups=[1,2]<=[2], dimensions={0}
  %all-gather-done = f32[16,16]{1,0} all-gather-done((f32[8,16]{1,0}, f32[16,16]{1,0}) %all-gather-start)
  %all-reduce-start = f32[16,16]{1,0} all-reduce-start(f32[16,16]{1,0} %all-gather-done), replica_groups={{0,1}}, to_apply=%add
  %all-reduce-done = f32[16,16]{1,0} all-reduce-done(f32[16,16]{1,0} %all-reduce-start)
  ROOT %slice = f32[8,16]{1,0} slice(f32[16,16]{1,0} %all-reduce-done), slice={[0:8], [0:16]}
}
"""

_SYNC_HLO = """
HloModule jit_step

ENTRY %main (p0: f32[16,16]) -> f32[16,16] {
  %p0 = f32[16,16]{1,0} parameter(0)
  ROOT %all-reduce = f32[16,16]{1,0} all-reduce(f32[16,16]{1,0} %p0), replica_groups=[2,2]<=[4], to_apply=%add
}
"""


def test_async_pair_detector():
    rep = ov.overlap_report(_ASYNC_HLO)
    assert rep["async_pairs"] == 2           # one AG pair + one AR pair
    assert rep["n_collectives"] == 4
    rep = ov.overlap_report(_SYNC_HLO)
    assert rep["async_pairs"] == 0
    assert rep["n_collectives"] == 1


def test_replica_group_parsing():
    assert ov.parse_replica_groups(
        "x, replica_groups={{0,1},{2,3}}, y") == [(0, 1), (2, 3)]
    assert ov.parse_replica_groups(
        "replica_groups=[2,2]<=[4]") == [(0, 1), (2, 3)]
    # strided (transposed-iota) groups: the 'data_outer' pattern
    assert ov.parse_replica_groups(
        "replica_groups=[2,2]<=[2,2]T(1,0)") == [(0, 2), (1, 3)]


def test_dp2_step_collectives_in_backward_loop():
    """The compiled dp=2 train step must carry real collectives, and the
    per-layer annotation must place grad reduction INSIDE the scan's
    while body (grad comm for layer i overlapping layer i-1's backward)
    on the 'data' axis."""
    eng = _engine(2, overlap=True)
    rep = eng.verify_comm_overlap(_batch())
    assert rep["n_collectives"] > 0
    assert rep["in_loop"] > 0, "no collective inside a scan body"
    data_groups = ov.expected_axis_groups(eng.mesh, ("data",))
    in_loop_groups = [
        {frozenset(g) for g in c["groups"]}
        for c in rep["collectives"] if c["in_loop"] and c["groups"]]
    assert any(gs == data_groups for gs in in_loop_groups), \
        "no in-loop collective on the 'data' axis"
    # CPU lowers collectives synchronously: async pairs only on TPU/GPU,
    # and require_async must say so rather than pass vacuously
    if rep["async_pairs"] == 0:
        with pytest.raises(RuntimeError, match="async"):
            eng.verify_comm_overlap(_batch(), require_async=True)


def test_zero3_prefetch_emits_gather():
    """Stage 3 + prefetch: the forward gather constraint shows up as
    in-loop all-gather collectives over the partition ('data') axis."""
    eng = _engine(2, stage=3, overlap=True)
    rep = eng.verify_comm_overlap(_batch())
    assert "all-gather" in rep["ops"]
    data_groups = ov.expected_axis_groups(eng.mesh, ("data",))
    gathers = [c for c in rep["collectives"]
               if c["op"] == "all-gather" and c["in_loop"] and c["groups"]]
    assert any({frozenset(g) for g in c["groups"]} == data_groups
               for c in gathers)


def test_hierarchical_collectives_on_both_axes():
    """dp=4 split as data_outer=2 x data=2: the two-stage constraint must
    emit collectives whose replica groups are exactly the 'data' (ICI)
    partition AND exactly the 'data_outer' (DCN) partition — not just
    one flat 4-wide group."""
    eng = _engine(4, shard=2, overlap=True, hierarchical=True,
                  train_batch=8)
    rep = eng.verify_comm_overlap(_batch(8))
    exp_data = ov.expected_axis_groups(eng.mesh, ("data",))
    exp_outer = ov.expected_axis_groups(eng.mesh, ("data_outer",))
    assert exp_data != exp_outer
    found = [{frozenset(g) for g in c["groups"]}
             for c in rep["collectives"] if c["groups"]]
    assert any(gs == exp_data for gs in found), \
        "no collective on the inner 'data' (ICI) axis"
    assert any(gs == exp_outer for gs in found), \
        "no collective on the 'data_outer' (DCN) axis"
    assert ("data",) in rep["axes"] and ("data_outer",) in rep["axes"]


# ------------------------------------------------------- unit helpers

def test_drop_layer_dim_and_split_inner():
    assert ov.drop_layer_dim(P(None, None, "tensor")) == P(None, "tensor")
    assert ov.drop_layer_dim(P("data", None)) == ov.SKIP
    dp = ("data_outer", "data", "expert")
    assert ov.split_inner(P(None, dp)) == P(None, ("data", "expert"))
    assert ov.split_inner(P(None, "data_outer")) == P(None, None)
    assert ov.split_inner(P(None, "data")) == ov.SKIP
    assert ov.split_inner(ov.SKIP) == ov.SKIP


def test_bucket_gate():
    """bucket_mb: layers below the threshold emit no in-scan collective
    (their reduction coalesces into the post-backward one)."""
    import jax.numpy as jnp
    layer = {"w": jnp.zeros((64, 64), jnp.float32)}   # 16 KiB
    small = ov.make_layer_comm_hook({"w": P("data", None)},
                                    bucket_bytes=1 << 20)
    big = ov.make_layer_comm_hook({"w": P("data", None)}, bucket_bytes=0)
    assert not small.should_annotate(layer)
    assert big.should_annotate(layer)
    # gdtype overrides the leaf dtype in the gate accounting
    half = ov.make_layer_comm_hook({"w": P("data", None)},
                                   bucket_bytes=16 * 1024,
                                   gdtype=jnp.bfloat16)
    assert not half.should_annotate(layer)     # 8 KiB as bf16


def test_xla_flags_platform_gated():
    """Names outside the host DebugOptions proto are FATAL in XLA_FLAGS:
    the flag set must be empty off-TPU/GPU, and the TPU set must ride
    LIBTPU_INIT_ARGS (libtpu's own flag registry), never XLA_FLAGS."""
    assert ov.xla_overlap_flags(None) == []
    assert ov.xla_overlap_flags("cpu") == []
    tpu = ov.xla_overlap_flags("tpu", prefetch=True)
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" in tpu
    assert "--xla_tpu_enable_ag_backward_pipelining=true" in tpu
    assert all(f.startswith("--xla_") for f in tpu)
    assert ov.overlap_env_var("tpu") == "LIBTPU_INIT_ARGS"
    assert ov.overlap_env_var("gpu") == "XLA_FLAGS"
    gpu = ov.xla_overlap_flags("gpu", bucket_mb=8)
    assert any("combine_threshold_bytes=8388608" in f for f in gpu)
    # every GPU flag name must be resolvable by the host XLA_FLAGS
    # parser (= exist in the DebugOptions proto); verified by compiling
    # with it as a compile option — 'No such compile option' is exactly
    # the name check XLA_FLAGS fatals on
    import jax
    import jax.numpy as jnp
    low = jax.jit(lambda x: x + 1).lower(jnp.ones((4,)))
    for f in gpu:
        name, val = f.lstrip("-").split("=")
        opt = {name: True if val == "true" else int(val)}
        try:
            low.compile(compiler_options=opt)
        except Exception as e:  # noqa: BLE001
            assert "No such compile option" not in str(e), f
