"""ZeRO-Offload: host CPU-Adam path vs the on-device optimizer.

Mirrors the reference's CPU-offload coverage
(tests/unit/runtime/zero/test_zero.py offload variants + ops/adam
cpu_adam parity tests): a config-only switch must (a) train with loss
parity against the on-device path, (b) hold NO master/optimizer state in
device memory, (c) checkpoint/restore, and (d) work with the state tiered
to NVMe (reference stage3.py:584 _configure_tensor_swapping).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Config
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



def _cfg():
    return GPT2Config(n_layer=2, n_head=2, d_model=64, max_seq_len=32,
                      vocab_size=256, remat=False, dtype="float32")


def _make_engine(offload=None, offload_param=None, dp=1, dtype="float32",
                 zero_stage=0):
    groups.reset()
    topo = groups.initialize(TopologyConfig(data_parallel_size=dp),
                             devices=jax.devices()[:dp])
    from dataclasses import replace
    model = GPT2(replace(_cfg(), dtype=dtype))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 0,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": zero_stage},
    }
    if dtype == "bfloat16":
        config["bf16"] = {"enabled": True}
    if offload is not None:
        config["zero_optimization"]["offload_optimizer"] = offload
    if offload_param is not None:
        config["zero_optimization"]["offload_param"] = offload_param
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, topology=topo, config=config)
    return engine


def _batches(engine, n=6):
    rng = np.random.RandomState(0)
    return [{"input_ids": rng.randint(
        0, 256, (engine.config.train_batch_size, 32)).astype(np.int32)}
        for _ in range(n)]


def _nbytes(tree):
    """Device bytes of a state tree. The rng leaf is a typed PRNG key
    array whose extended dtype implements no ``nbytes`` (raises
    NotImplementedError) — account for it via its uint32 key data
    instead of crashing on it."""
    total = 0
    for x in jax.tree.leaves(tree):
        if jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key):
            total += jax.random.key_data(x).nbytes
        else:
            total += x.nbytes
    return total


class TestOffloadOptimizer:
    def test_loss_parity_with_device_path(self):
        """cpu-offloaded Adam must track the on-device FusedAdam closely
        (fp32 everywhere: only accumulation-order noise)."""
        dev = _make_engine(offload=None)
        losses_dev = [float(dev.train_batch(b)) for b in _batches(dev)]

        off = _make_engine(offload={"device": "cpu"})
        losses_off = [float(off.train_batch(b)) for b in _batches(off)]

        np.testing.assert_allclose(losses_dev, losses_off,
                                   rtol=2e-4, atol=2e-4)
        # repeated steps on ONE batch must reduce its loss
        b = _batches(off, 1)[0]
        repeat = [float(off.train_batch(b)) for _ in range(5)]
        assert repeat[-1] < repeat[0], repeat

    def test_no_device_master_or_opt_state(self):
        off = _make_engine(offload=True)   # bool form -> cpu
        assert off.state["master"] is None
        assert off.state["opt"] is None
        assert off.host_optimizer is not None
        # device state = params + scalars only
        param_bytes = _nbytes(off.state["params"])
        total_bytes = _nbytes(off.state)
        assert total_bytes - param_bytes < 4096  # scalars/rng only

        dev = _make_engine(offload=None)
        dev_bytes = _nbytes(dev.state)
        # fp32: master+m+v = 3x params -> device memory must drop ~4x
        assert total_bytes < dev_bytes / 3

    def test_bf16_offload_trains(self):
        off = _make_engine(offload={"device": "cpu"}, dtype="bfloat16",
                           zero_stage=2, dp=2)
        b = _batches(off, 1)[0]
        losses = [float(off.train_batch(b)) for _ in range(8)]
        assert losses[-1] < losses[0] * 0.9, losses
        assert off.state["params"]["wte"].dtype == jnp.bfloat16

    def test_nvme_tier(self, tmp_path):
        """offload_optimizer.device='nvme' streams m/v through the AIO
        pool; offload_param tiers the fp32 master too."""
        off = _make_engine(
            offload={"device": "nvme", "nvme_path": str(tmp_path / "sw")},
            offload_param={"device": "nvme",
                           "nvme_path": str(tmp_path / "sw")})
        assert off.host_optimizer.state_nvme
        assert off.host_optimizer.master_nvme
        assert off.host_optimizer.master is None  # not RAM-resident
        losses = [float(off.train_batch(b)) for b in _batches(off, 6)]
        # parity vs pure-cpu offload: identical math, different tier
        cpu = _make_engine(offload={"device": "cpu"})
        losses_cpu = [float(cpu.train_batch(b)) for b in _batches(cpu, 6)]
        np.testing.assert_allclose(losses, losses_cpu, rtol=1e-5, atol=1e-5)

    def test_checkpoint_roundtrip(self, tmp_path):
        off = _make_engine(offload={"device": "cpu"})
        batches = _batches(off, 6)
        for b in batches[:3]:
            off.train_batch(b)
        tag = off.save_checkpoint(str(tmp_path))
        cont = [float(off.train_batch(b)) for b in batches[3:]]

        re = _make_engine(offload={"device": "cpu"})
        path, _ = re.load_checkpoint(str(tmp_path), tag)
        assert path is not None
        assert re.host_optimizer.adam.get_step() == 3
        resumed = [float(re.train_batch(b)) for b in batches[3:]]
        np.testing.assert_allclose(cont, resumed, rtol=1e-5, atol=1e-6)

    def test_staged_api(self):
        off = _make_engine(offload={"device": "cpu"})
        ref = _make_engine(offload={"device": "cpu"})
        batches = _batches(off, 2)
        for b in batches:
            off.train_batch(b)
        # staged fwd/bwd/step must produce the same parameters
        for b in batches:
            gas = ref.config.gradient_accumulation_steps
            micro = ref.config.train_micro_batch_size_per_gpu \
                * ref.topology.get_data_parallel_world_size()
            for i in range(gas):
                mb = {k: v[i * micro:(i + 1) * micro]
                      for k, v in b.items()}
                loss = ref.forward(mb)
                ref.backward(loss)
                ref.step()
        a = jax.tree.leaves(off.state["params"])
        bb = jax.tree.leaves(ref.state["params"])
        for x, y in zip(a, bb):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=2e-4, atol=2e-4)

    def test_rejects_non_adam(self):
        groups.reset()
        topo = groups.initialize(TopologyConfig())
        with pytest.raises(ValueError, match="Adam"):
            deepspeed_tpu.initialize(
                model=GPT2(_cfg()), topology=topo,
                config={"train_micro_batch_size_per_gpu": 2,
                        "steps_per_print": 0,
                        "optimizer": {"type": "Lion", "params": {}},
                        "zero_optimization": {
                            "stage": 0, "offload_optimizer": True}})
