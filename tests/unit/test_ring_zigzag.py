"""Zigzag ring attention: tier-1 acceptance tests.

Exact parity of the zigzag flash-kernel ring (sequence/ring.py) against
single-device dense causal attention at ring sizes 1/2/4 on the virtual
mesh — forward AND gradients, kernel path (Pallas interpret mode off-TPU)
— plus the causal-FLOPs assertion (fully-masked chunk pairs are no longer
computed) and the KV-rotation collective-permute placement inside the
scan body.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.sequence import ring_attention_sharded
from deepspeed_tpu.sequence.ring import ring_flops_info
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig


def _dense_ref(q, k, v, causal=True):
    T = q.shape[1]
    s = jnp.einsum("bthd,bshd->bhts", q, k,
                   preferred_element_type=jnp.float32)
    s = s / math.sqrt(q.shape[-1])
    if causal:
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", p, v)


def _qkv(B=2, T=32, H=4, D=8, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype) for k in ks)


def _ring_mesh(sp):
    """Pure seq-parallel mesh over exactly sp devices (data axes stay 1
    so tiny test batches need not divide the full 8-device pool)."""
    groups.reset()
    return groups.initialize(TopologyConfig(seq_parallel_size=sp),
                             devices=jax.devices()[:sp])


@pytest.mark.parametrize("sp", [1, 2, 4])
def test_zigzag_kernel_fwd_matches_dense_bf16(sp):
    """Acceptance: zigzag ring, kernel path, bf16 tolerance, ring sizes
    1/2/4 vs single-device dense causal."""
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ref = _dense_ref(q, k, v, causal=True)
    topo = _ring_mesh(sp)
    with jax.set_mesh(topo.mesh):
        out = jax.jit(lambda a, b, c: ring_attention_sharded(
            a, b, c, topo.mesh, causal=True, layout="zigzag",
            block_kernel=True))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("sp", [1, 2, 4])
def test_zigzag_kernel_grads_match_dense(sp):
    """Acceptance: fwd + grads through the flash-style ring backward
    (per-pair fused bwd kernel from the global lse) vs dense autodiff."""
    q, k, v = _qkv(T=32)
    topo = _ring_mesh(sp)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.square(ring_attention_sharded(
            q, k, v, topo.mesh, causal=True, layout="zigzag",
            block_kernel=True)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.square(_dense_ref(q, k, v)))

    with jax.set_mesh(topo.mesh):
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=3e-4, atol=3e-5)


def test_einsum_backend_and_no_double_buffer_match():
    """The dense-einsum block backend and the serialized rotation order
    are the same math: both must match dense exactly."""
    q, k, v = _qkv()
    ref = _dense_ref(q, k, v)
    topo = _ring_mesh(4)
    with jax.set_mesh(topo.mesh):
        out_e = jax.jit(lambda a, b, c: ring_attention_sharded(
            a, b, c, topo.mesh, block_kernel=False))(q, k, v)
        out_s = jax.jit(lambda a, b, c: ring_attention_sharded(
            a, b, c, topo.mesh, block_kernel=True,
            double_buffer=False))(q, k, v)
    for out in (out_e, out_s):
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6)


def test_causal_flops_skip_static_accounting():
    """Static schedule accounting: zigzag computes exactly the causal-
    necessary chunk pairs; the naive (contiguous) ring computed every
    pair and masked."""
    for R in (2, 4, 8):
        info = ring_flops_info(R, T_local=2 * 8)
        assert info["skipped_pairs"] > 0
        assert info["computed_pairs"] == 4 + 2 * (R - 1)
        assert info["computed_pairs"] + info["skipped_pairs"] \
            == info["total_pairs"] == 4 * R
        naive = ring_flops_info(R, T_local=2 * 8, layout="contiguous")
        assert naive["skipped_pairs"] == 0
        assert naive["computed_pairs"] == info["total_pairs"]


def test_causal_flops_skip_in_lowered_program():
    """Acceptance: the compiled zigzag program's FLOPs show fully-masked
    chunk pairs are NOT computed. At ring=2 neither layout has a
    multi-trip scan (XLA cost analysis counts while bodies once), so the
    totals are exact: zigzag = 3/4 of the compute-then-mask program's
    score work (measured ~0.748 at this shape)."""
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (1, 1024, 2, 64)) for kk in ks)
    topo = _ring_mesh(2)

    def flops(layout):
        with jax.set_mesh(topo.mesh):
            f = jax.jit(lambda a, b, c: ring_attention_sharded(
                a, b, c, topo.mesh, causal=True, layout=layout,
                block_kernel=False))
            ca = f.lower(q, k, v).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        if not ca or "flops" not in ca:
            pytest.skip("cost_analysis has no flops on this backend")
        return float(ca["flops"])

    fz, fc = flops("zigzag"), flops("contiguous")
    assert fz < 0.85 * fc, (fz, fc)


def test_kv_rotation_collective_permute_inside_scan_body():
    """Acceptance: the fused KV rotation is ONE collective-permute and it
    sits INSIDE the scan body (overlap_report in_loop_by_op — the same
    report engine.verify_comm_overlap returns)."""
    from deepspeed_tpu.runtime.zero.overlap import overlap_report
    q, k, v = _qkv(T=64)
    topo = _ring_mesh(4)
    with jax.set_mesh(topo.mesh):
        f = jax.jit(lambda a, b, c: ring_attention_sharded(
            a, b, c, topo.mesh, causal=True, layout="zigzag",
            block_kernel=False))
        hlo = f.lower(q, k, v).compile().as_text()
    rep = overlap_report(hlo)
    assert rep["in_loop_by_op"].get("collective-permute", 0) == 1, rep
    # k and v rotate as one fused stacked buffer: the in-loop rotation
    # is a single collective, not one per tensor
    assert "collective-permute" in rep["ops"]


def test_ring_flops_info_noncausal_and_ring1():
    assert ring_flops_info(1, 16)["skipped_pairs"] == 0
    assert ring_flops_info(4, 16, causal=False)["skipped_pairs"] == 0
