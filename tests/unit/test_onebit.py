"""1-bit optimizer tests (reference tests/unit/runtime/half_precision/
onebit/test_onebit.py): compressed allreduce correctness + error feedback,
warmup-equals-dense-Adam, end-to-end convergence of all three optimizers.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P
import pytest

from deepspeed_tpu.runtime.comm.compressed import (
    CompressionState, compressed_allreduce, pack_signs, unpack_signs)
from deepspeed_tpu.runtime.fp16.onebit import (OneBitAdam, OneBitLamb,
                                               OneBitTrainer, ZeroOneAdam)
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow



def _mesh():
    groups.reset()
    return groups.initialize(TopologyConfig()).mesh


class TestPackUnpack:
    def test_roundtrip(self):
        x = np.random.RandomState(0).randn(128).astype(np.float32)
        packed = pack_signs(jnp.asarray(x))
        assert packed.shape == (16,) and packed.dtype == jnp.uint8
        signs = np.asarray(unpack_signs(packed, 128))
        np.testing.assert_array_equal(signs, np.where(x >= 0, 1.0, -1.0))


def _run_compressed(mesh, x, state, n_iters=1):
    """x: (W, N) per-device values. Returns (out (W, N), final state)."""

    def body(xs, we, se):
        st = CompressionState(worker_error=we[0], server_error=se[0])
        out, st = compressed_allreduce(xs.reshape(-1), st, "data")
        return out[None], st.worker_error[None], st.server_error[None]

    f = jax.jit(lambda x, w, s: shard_map(
        body, mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
        out_specs=(P("data"), P("data"), P("data")), check_vma=False)(
            x, w, s))
    w, s = state
    for _ in range(n_iters):
        out, w, s = f(x, w, s)
    return np.asarray(out), (w, s)


class TestCompressedAllreduce:
    def test_single_call_approximates_mean(self):
        mesh = _mesh()
        W, N = 8, 1024
        x = np.random.RandomState(1).randn(W, N).astype(np.float32)
        w0 = jnp.zeros((W, N)); s0 = jnp.zeros((W, N // 8))
        out, _ = _run_compressed(mesh, x, (w0, s0))
        mean = x.mean(0)
        # every device gets the SAME result
        for d in range(1, W):
            np.testing.assert_array_equal(out[0], out[d])
        # sign-compressed: coarse, but correlated with the true mean
        corr = np.corrcoef(out[0], mean)[0, 1]
        assert corr > 0.5, corr

    def test_error_feedback_accumulates(self):
        """Summing T compressed allreduces of the same value converges to
        T * mean — the error-feedback guarantee (residuals re-enter)."""
        mesh = _mesh()
        W, N = 8, 512
        x = np.random.RandomState(2).randn(W, N).astype(np.float32)
        mean = x.mean(0)
        w = jnp.zeros((W, N)); s = jnp.zeros((W, N // 8))
        acc = np.zeros(N)
        rels = {}
        for t in range(1, 61):
            out, (w, s) = _run_compressed(mesh, x, (w, s))
            acc += out[0]
            if t in (10, 60):
                rels[t] = (np.linalg.norm(acc / t - mean)
                           / np.linalg.norm(mean))
        # residuals re-enter, so the running average keeps improving
        # (without error feedback it plateaus at the one-shot error)
        assert rels[60] < 0.6 * rels[10], rels
        assert rels[60] < 0.15, rels


def _quadratic_problem(n=256, m=512, seed=0):
    rs = np.random.RandomState(seed)
    A = rs.randn(m, n).astype(np.float32) / np.sqrt(n)
    target = rs.randn(n).astype(np.float32)
    y = A @ target
    params = {"w": jnp.zeros((n,), jnp.float32)}

    def loss_fn(params, batch):
        pred = batch["A"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return params, loss_fn, {"A": A, "y": y}


class TestOneBitAdamWarmup:
    def test_warmup_matches_dense_adam(self):
        """During freeze_step warmup the update must equal plain Adam on
        the allreduced gradient."""
        mesh = _mesh()
        groups.reset()
        topo = groups.initialize(TopologyConfig())
        params, loss_fn, data = _quadratic_problem()
        opt = OneBitAdam(lr=1e-2, freeze_step=10**9)  # never compress
        tr = OneBitTrainer(loss_fn, params, opt, topology=topo)
        losses = [tr.step(data) for _ in range(5)]

        # dense reference: full-batch Adam on the same problem. The
        # reference's 1-bit Adam applies NO bias correction in its update
        # (onebit/adam.py:194 update = exp_avg/(sqrt+eps)), so compare
        # against uncorrected Adam.
        from deepspeed_tpu.ops.optimizers import FusedAdam
        dense = FusedAdam(lr=1e-2, bias_correction=False)
        p = {"w": jnp.zeros_like(params["w"])}
        st = dense.init(p)
        ref_losses = []
        for _ in range(5):
            l, g = jax.value_and_grad(lambda p: loss_fn(p, data))(p)
            ref_losses.append(float(l))
            p, st = dense.update(g, st, p)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)

    def test_compression_stage_converges(self):
        groups.reset()
        topo = groups.initialize(TopologyConfig())
        params, loss_fn, data = _quadratic_problem()
        opt = OneBitAdam(lr=1e-2, freeze_step=10)
        tr = OneBitTrainer(loss_fn, params, opt, topology=topo)
        losses = [tr.step(data) for _ in range(60)]
        assert losses[-1] < 0.25 * losses[0], (losses[0], losses[-1])
        # compression really active: error buffers non-zero
        we = np.asarray(tr.opt_state["comp"].worker_error)
        assert np.abs(we).max() > 0


class TestZeroOneAdam:
    def test_converges_without_warmup(self):
        groups.reset()
        topo = groups.initialize(TopologyConfig())
        params, loss_fn, data = _quadratic_problem(seed=3)
        opt = ZeroOneAdam(lr=1e-2, var_freeze_step=20,
                          local_step_scaler=10)
        tr = OneBitTrainer(loss_fn, params, opt, topology=topo)
        losses = [tr.step(data) for _ in range(60)]
        assert losses[-1] < 0.25 * losses[0], (losses[0], losses[-1])


class TestOneBitLamb:
    def test_converges_and_freezes_coeff(self):
        groups.reset()
        topo = groups.initialize(TopologyConfig())
        params, loss_fn, data = _quadratic_problem(seed=4)
        opt = OneBitLamb(lr=3e-3, freeze_step=15)
        tr = OneBitTrainer(loss_fn, params, opt, topology=topo)
        losses = [tr.step(data) for _ in range(20)]
        coeff_at_freeze = np.asarray(tr.opt_state["coeff"]).copy()
        for _ in range(10):
            tr.step(data)
        np.testing.assert_array_equal(
            coeff_at_freeze, np.asarray(tr.opt_state["coeff"]))
        assert losses[-1] < losses[0]


class TestTrainerValidation:
    def test_rejects_model_parallel_topology(self):
        groups.reset()
        topo = groups.initialize(TopologyConfig(tensor_parallel_size=2))
        params, loss_fn, _ = _quadratic_problem()
        with pytest.raises(ValueError, match="data parallelism only"):
            OneBitTrainer(loss_fn, params, OneBitAdam(), topology=topo)
