"""Pipeline parallelism tests.

Mirrors the reference's split (SURVEY §4): pure-python schedule/topology
unit tests (tests/unit/runtime/pipe/test_topology.py style) plus end-to-end
pipelined training on a real multi-device mesh, asserting numerical parity
with the non-pipelined model — a stronger check than the reference's
loss-goes-down test.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.pipe import (
    ProcessTopology, PipeDataParallelTopology, PipelineParallelGrid,
    TrainSchedule, InferenceSchedule, LayerSpec, TiedLayerSpec,
    PipelineModule, ForwardPass, BackwardPass, SendActivation,
    RecvActivation, SendGrad, RecvGrad, ReduceGrads, OptimizerStep,
    spmd_pipeline)
from deepspeed_tpu.runtime.pipe.module import partition_balanced
from deepspeed_tpu.runtime.pipe.spmd import (split_microbatches,
                                             merge_microbatches)
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.groups import TopologyConfig

# compile-heavy: excluded from the fast core set (pytest -m 'not slow')
pytestmark = pytest.mark.slow

# The SPMD-pipelined end-to-end tests below need vma-era jax BECAUSE
# their meshes carry auto (non-pipe) axes > 1: legacy jaxlib cannot
# SPMD-partition the partial-manual shard_map pipeline program
# (XlaRuntimeError: "PartitionId instruction is not supported for SPMD
# partitioning" at the lax.axis_index inside the pipe-manual region),
# regardless of the lax.pcast compat shim (utils/compat.py) that fixes
# the API gap. They pass on current jax (the driver env). The mark is
# scoped to exactly these tests: pipe-ONLY meshes (every auto axis
# size 1) partition fine on legacy jaxlib, so tier-1 schedule-parity
# and pp=2 loss-parity coverage lives unmarked in test_pipe_fast.py.
legacy_jax_pipeline_xfail = pytest.mark.xfail(
    jax.__version_info__ < (0, 6),
    reason="partial-manual shard_map pipelines need vma-era jax/jaxlib; "
           "legacy jaxlib cannot SPMD-partition the manual-pipe program "
           "(passes on driver jax >= 0.9)",
    strict=False)



# ---------------------------------------------------------------- topology
class TestProcessTopology:
    def test_rank_coord_roundtrip(self):
        topo = ProcessTopology(["pipe", "data"], [2, 4])
        assert topo.world_size == 8
        for r in range(8):
            c = topo.get_coord(r)
            assert topo.get_rank(pipe=c.pipe, data=c.data) == r

    def test_row_major(self):
        # first axis slowest — matches Mesh device order
        topo = ProcessTopology(["pipe", "data"], [2, 3])
        assert topo.get_rank(pipe=0, data=0) == 0
        assert topo.get_rank(pipe=0, data=2) == 2
        assert topo.get_rank(pipe=1, data=0) == 3

    def test_comm_lists(self):
        topo = PipeDataParallelTopology(2, 4)
        pipe_groups = topo.get_axis_comm_lists("pipe")
        assert len(pipe_groups) == 4
        for g in pipe_groups:
            assert len(g) == 2
        # each rank in exactly one group
        all_ranks = sorted(r for g in pipe_groups for r in g)
        assert all_ranks == list(range(8))

    def test_filter_match(self):
        topo = PipeDataParallelTopology(2, 4)
        assert topo.filter_match(pipe=1) == [4, 5, 6, 7]

    def test_grid(self):
        topo = PipeDataParallelTopology(4, 2)
        grid = PipelineParallelGrid(topo, rank=5)
        assert grid.get_stage_id() == 2
        assert grid.get_data_parallel_id() == 1
        assert grid.stage_to_global(3) == 7
        assert not grid.is_first_stage() and not grid.is_last_stage()
        assert grid.ppermute_perm() == [(0, 1), (1, 2), (2, 3), (3, 0)]


# ---------------------------------------------------------------- schedule
def _simulate(schedules):
    """Execute per-stage instruction streams against FIFO channels; assert
    the dataflow is deadlock-free and yields each microbatch's F before its
    B on every stage. Returns per-stage executed order."""
    S = len(schedules)
    streams = [list(sched) for sched in schedules]  # lists of steps
    # flatten to instruction queues
    queues = [[i for step in s for i in step] for s in streams]
    acts = [[] for _ in range(S + 1)]   # acts[s] = channel s-1 -> s
    grads = [[] for _ in range(S + 1)]  # grads[s] = channel s -> s-1
    done_f = [set() for _ in range(S)]
    done_b = [set() for _ in range(S)]
    executed = [[] for _ in range(S)]
    pos = [0] * S
    progress = True
    while progress:
        progress = False
        for s in range(S):
            while pos[s] < len(queues[s]):
                ins = queues[s][pos[s]]
                if isinstance(ins, RecvActivation):
                    if not acts[s] or acts[s][0] != ins.micro_batch:
                        break
                    acts[s].pop(0)
                elif isinstance(ins, RecvGrad):
                    if not grads[s + 1] or grads[s + 1][0] != ins.micro_batch:
                        break
                    grads[s + 1].pop(0)
                elif isinstance(ins, SendActivation):
                    acts[s + 1].append(ins.micro_batch)
                elif isinstance(ins, SendGrad):
                    grads[s].append(ins.micro_batch)
                elif isinstance(ins, ForwardPass):
                    assert ins.micro_batch not in done_f[s]
                    if s > 0:
                        assert ins.micro_batch in done_f[s - 1]
                    done_f[s].add(ins.micro_batch)
                elif isinstance(ins, BackwardPass):
                    assert ins.micro_batch in done_f[s], "B before F"
                    if s < S - 1:
                        assert ins.micro_batch in done_b[s + 1]
                    done_b[s].add(ins.micro_batch)
                executed[s].append(ins)
                pos[s] += 1
                progress = True
    for s in range(S):
        assert pos[s] == len(queues[s]), f"stage {s} deadlocked at {pos[s]}"
    return done_f, done_b


class TestTrainSchedule:
    @pytest.mark.parametrize("stages,micro", [(2, 2), (2, 4), (4, 4),
                                              (4, 8), (3, 5), (1, 3)])
    def test_1f1b_dataflow(self, stages, micro):
        scheds = [TrainSchedule(micro, stages, s) for s in range(stages)]
        done_f, done_b = _simulate(scheds)
        for s in range(stages):
            assert done_f[s] == set(range(micro))
            assert done_b[s] == set(range(micro))

    def test_warmup_depth(self):
        # peak in-flight = min(S - s, M): the 1F1B memory property
        sched = TrainSchedule(8, 4, 0)
        assert sched.num_pipe_buffers() == 4
        sched = TrainSchedule(8, 4, 3)
        assert sched.num_pipe_buffers() == 1
        sched = TrainSchedule(2, 4, 0)
        assert sched.num_pipe_buffers() == 2

    def test_last_stage_alternates(self):
        sched = TrainSchedule(4, 4, 3)
        kinds = [type(i).__name__ for step in sched for i in step
                 if isinstance(i, (ForwardPass, BackwardPass))]
        assert kinds == ["ForwardPass", "BackwardPass"] * 4

    def test_ends_with_step(self):
        steps = list(TrainSchedule(2, 2, 0))
        assert steps[-1] == [ReduceGrads(), OptimizerStep()]

    def test_bubble_fraction(self):
        assert TrainSchedule(8, 4, 0).bubble_fraction() == pytest.approx(
            3 / 11)


class TestInferenceSchedule:
    def test_forward_only(self):
        scheds = [InferenceSchedule(4, 3, s) for s in range(3)]
        for sched in scheds:
            for step in sched:
                for ins in step:
                    assert not isinstance(ins, (BackwardPass, SendGrad,
                                                RecvGrad))
        done_f, _ = _simulate(scheds)
        for s in range(3):
            assert done_f[s] == set(range(4))


# ------------------------------------------------------------------ module
class _Affine:
    def __init__(self, dim, scale=1.0):
        self.dim = dim
        self.scale = scale

    def init(self, rng):
        return {"w": jax.random.normal(rng, (self.dim, self.dim)) * 0.1}

    def apply(self, params, x):
        return jnp.tanh(x @ params["w"] * self.scale)


class TestPartitionBalanced:
    def test_uniform(self):
        assert partition_balanced([1] * 8, 4) == [0, 2, 4, 6, 8]

    def test_weighted(self):
        bounds = partition_balanced([10, 1, 1, 1, 1, 10], 2)
        # best split keeps the two heavy layers apart
        assert bounds[0] == 0 and bounds[-1] == 6
        w = [10, 1, 1, 1, 1, 10]
        sums = [sum(w[bounds[i]:bounds[i + 1]]) for i in range(2)]
        assert max(sums) == 12  # optimal: [10,1,1] | [1,1,10]

    def test_each_part_nonempty(self):
        for n, p in [(4, 4), (5, 3), (9, 4)]:
            bounds = partition_balanced([1] * n, p)
            assert len(bounds) == p + 1
            assert all(bounds[i] < bounds[i + 1] for i in range(p))


class TestPipelineModule:
    def test_partition_uniform(self):
        mod = PipelineModule([LayerSpec(_Affine, 8) for _ in range(8)],
                             num_stages=4, partition_method="uniform")
        assert mod.parts == [0, 2, 4, 6, 8]
        assert mod.stage_of_layer(5) == 2

    def test_partition_parameters(self):
        layers = [LayerSpec(_Affine, 32)] + \
                 [LayerSpec(_Affine, 8) for _ in range(3)]
        mod = PipelineModule(layers, num_stages=2,
                             partition_method="parameters")
        # the big layer gets its own stage
        assert mod.parts[1] == 1

    def test_partition_type_regex(self):
        class Marker(_Affine):
            pass
        layers = [LayerSpec(_Affine, 4), LayerSpec(Marker, 4),
                  LayerSpec(_Affine, 4), LayerSpec(Marker, 4)]
        mod = PipelineModule(layers, num_stages=2,
                             partition_method="type:marker")
        counts = [sum(1 for i in mod.stage_layer_indices(s)
                      if isinstance(mod.layers[i], Marker))
                  for s in range(2)]
        assert counts == [1, 1]

    def test_apply_matches_manual(self):
        mod = PipelineModule([LayerSpec(_Affine, 6) for _ in range(4)],
                             num_stages=2)
        params = mod.init(jax.random.key(0))
        x = jnp.ones((2, 6))
        y = mod.apply(params, x)
        # stagewise composition gives the same result
        h = mod.apply_stage(params, x, 0)
        y2 = mod.apply_stage(params, h, 1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y2), rtol=1e-6)

    def test_tied_layers_share_params(self):
        layers = [TiedLayerSpec("emb", _Affine, 6),
                  LayerSpec(_Affine, 6),
                  TiedLayerSpec("emb", _Affine, 6)]
        mod = PipelineModule(layers, num_stages=1)
        params = mod.init(jax.random.key(0))
        assert params[2] is None  # ties back to layer 0
        y = mod.apply(params, jnp.ones((2, 6)))
        assert y.shape == (2, 6)


# ----------------------------------------------------------- spmd executor
def _make_mesh(pipe, data):
    groups.reset()
    topo = groups.initialize(TopologyConfig(
        pipe_parallel_size=pipe, data_parallel_size=data), force=True)
    return topo.mesh


@legacy_jax_pipeline_xfail
class TestSpmdPipeline:
    def test_matches_sequential(self):
        mesh = _make_mesh(pipe=2, data=4)
        L, D, M, B = 4, 16, 3, 8
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(L, D, D) * 0.2, jnp.float32)
        x = jnp.asarray(rng.randn(M, B, D), jnp.float32)

        def block(x, w):
            return jnp.tanh(x @ w)

        def ref(w, x):
            def f(c, wi):
                return block(c, wi), None
            y, _ = jax.lax.scan(f, x, w)
            return y
        expect = jax.vmap(lambda mb: ref(w, mb))(x)

        with jax.set_mesh(mesh):
            ws = jax.device_put(w, NamedSharding(mesh, P("pipe")))
            xs = jax.device_put(x, NamedSharding(mesh, P(None, "data")))
            out = jax.jit(lambda w, x: spmd_pipeline(block, w, x))(ws, xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_sequential(self):
        mesh = _make_mesh(pipe=2, data=4)
        L, D, M, B = 2, 8, 4, 4
        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(L, D, D) * 0.2, jnp.float32)
        x = jnp.asarray(rng.randn(M, B, D), jnp.float32)

        def block(x, w):
            return jnp.tanh(x @ w)

        def ref_loss(w, x):
            def f(c, wi):
                return block(c, wi), None
            def run(mb):
                y, _ = jax.lax.scan(f, mb, w)
                return y
            return jnp.sum(jax.vmap(run)(x) ** 2)

        g_ref = jax.grad(ref_loss)(w, x)
        with jax.set_mesh(mesh):
            ws = jax.device_put(w, NamedSharding(mesh, P("pipe")))
            xs = jax.device_put(x, NamedSharding(mesh, P(None, "data")))
            g = jax.jit(jax.grad(
                lambda w, x: jnp.sum(spmd_pipeline(block, w, x) ** 2)))(
                    ws, xs)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_split_merge_roundtrip(self):
        x = jnp.arange(24.0).reshape(12, 2)
        mb = split_microbatches(x, 3)
        assert mb.shape == (3, 4, 2)
        np.testing.assert_array_equal(np.asarray(merge_microbatches(mb)),
                                      np.asarray(x))


# -------------------------------------------------------------- end-to-end
@legacy_jax_pipeline_xfail
class TestGPT2Pipe:
    def _cfg(self, **kw):
        from deepspeed_tpu.models import GPT2Config
        base = dict(n_layer=4, n_head=4, d_model=64, max_seq_len=32,
                    vocab_size=256, dtype="float32", remat=False,
                    pipe_microbatches=2)
        base.update(kw)
        return GPT2Config(**base)

    def test_loss_matches_dense(self):
        from deepspeed_tpu.models import GPT2, GPT2Pipe
        cfg = self._cfg()
        dense, piped = GPT2(cfg), GPT2Pipe(cfg)
        params = dense.init(jax.random.key(0))
        ids = np.random.RandomState(0).randint(0, 256, (4, 32)).astype(
            np.int32)
        batch = {"input_ids": ids}
        loss_ref = float(dense.loss(params, batch, train=False))

        mesh = _make_mesh(pipe=2, data=4)
        with jax.set_mesh(mesh):
            specs = piped.partition_specs(groups.get_topology())
            sharded = jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                params, specs, is_leaf=lambda x: isinstance(x, P))
            loss = float(jax.jit(
                lambda p: piped.loss(p, batch, train=False))(sharded))
        assert loss == pytest.approx(loss_ref, rel=1e-5)

    def test_engine_train_parity(self):
        """Pipelined engine training matches the dense engine step-for-step
        (same params, same data, fp32)."""
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2, GPT2Pipe

        ids = np.random.RandomState(0).randint(0, 256, (4, 8, 32)).astype(
            np.int32)

        def run(model_cls, pipe):
            groups.reset()
            topo = groups.initialize(TopologyConfig(
                pipe_parallel_size=pipe, data_parallel_size=-1), force=True)
            dp = topo.get_data_parallel_world_size()
            config = {
                # same global batch (8) whatever the pipe/data split
                "train_micro_batch_size_per_gpu": 8 // dp,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 0,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0},
            }
            model = model_cls(self._cfg())
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, topology=topo, config=config)
            losses = []
            for i in range(4):
                losses.append(float(engine.train_batch(
                    {"input_ids": ids[i]})))
            return losses

        ref = run(GPT2, pipe=1)
        got = run(GPT2Pipe, pipe=2)
        np.testing.assert_allclose(got, ref, rtol=2e-4)

    def test_zero_stages_with_pipe(self):
        """ZeRO partitioning composes with pipe sharding."""
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2Pipe

        ids = np.random.RandomState(1).randint(0, 256, (3, 4, 32)).astype(
            np.int32)
        losses = {}
        for stage in [0, 2, 3]:
            groups.reset()
            topo = groups.initialize(TopologyConfig(
                pipe_parallel_size=2, data_parallel_size=-1), force=True)
            model = GPT2Pipe(self._cfg())
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, topology=topo, config={
                    "train_micro_batch_size_per_gpu": 1,  # global batch 4
                    "gradient_accumulation_steps": 1,
                    "steps_per_print": 0,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": stage},
                })
            losses[stage] = [float(engine.train_batch({"input_ids": b}))
                             for b in ids]
        np.testing.assert_allclose(losses[2], losses[0], rtol=2e-4)
        np.testing.assert_allclose(losses[3], losses[0], rtol=2e-4)

    def test_pipe_with_tp(self):
        """pipe=2 x tensor=2 x data=2: 3D parallelism in one program."""
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2Pipe

        groups.reset()
        topo = groups.initialize(TopologyConfig(
            pipe_parallel_size=2, tensor_parallel_size=2,
            data_parallel_size=-1), force=True)
        model = GPT2Pipe(self._cfg())
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, topology=topo, config={
                "train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 0,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
            })
        ids = np.random.RandomState(2).randint(0, 256, (8, 32)).astype(
            np.int32)
        l0 = float(engine.train_batch({"input_ids": ids}))
        l1 = float(engine.train_batch({"input_ids": ids}))
        assert np.isfinite(l0) and np.isfinite(l1)
        assert l1 < l0  # optimizing the same batch must reduce loss


@legacy_jax_pipeline_xfail
class Test1F1BSchedule:
    """pipe_schedule='1f1b': the interleaved executor
    (runtime/pipe/spmd.py pipeline_1f1b_grads; reference
    runtime/pipe/engine.py:1382 _exec_schedule + schedule.py:189
    TrainSchedule as executed behavior, not schedule objects)."""

    def _setup(self, sched, M, n_layer=4, pipe=4, data=2):
        from dataclasses import replace
        from deepspeed_tpu.models import GPT2Pipe
        from deepspeed_tpu.models.gpt2 import GPT2Config
        cfg = GPT2Config(n_layer=n_layer, n_head=4, d_model=128,
                         max_seq_len=32, vocab_size=256, dtype="float32",
                         remat=True, pipe_microbatches=M,
                         pipe_schedule=sched)
        groups.reset()
        topo = groups.initialize(TopologyConfig(data_parallel_size=data,
                                                pipe_parallel_size=pipe))
        model = GPT2Pipe(cfg)
        params = model.init(jax.random.key(0))
        rng = np.random.RandomState(0)
        batch = {"input_ids": jnp.asarray(
            rng.randint(0, 256, (16, 32)), jnp.int32)}
        return topo, model, params, batch

    @pytest.mark.parametrize("steady", ["1f1b", "zb"])
    def test_loss_and_grad_parity_with_gpipe(self, steady):
        res = {}
        for sched in ("gpipe", steady):
            topo, model, params, batch = self._setup(sched, M=8)
            with jax.set_mesh(topo.mesh):
                loss, grads = jax.jit(jax.value_and_grad(
                    lambda p: model.loss(p, batch,
                                         rng=jax.random.key(1))))(params)
            res[sched] = (float(loss), grads)
        l0, g0 = res["gpipe"]
        l1, g1 = res[steady]
        assert abs(l0 - l1) < 1e-5
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4),
            g0, g1)

    def test_zb_live_activations_bounded_by_stages(self):
        """The ZB executor keeps the 1F1B memory class: input ring +
        S-slot dy ring, never O(M) residuals — growing M must not grow
        live temp memory the way GPipe's autodiff residuals do."""
        grown = {}
        for sched in ("gpipe", "zb"):
            temps = []
            for M in (4, 16):
                topo, model, params, batch = self._setup(sched, M=M)
                with jax.set_mesh(topo.mesh):
                    c = jax.jit(jax.value_and_grad(
                        lambda p: model.loss(p, batch,
                                             rng=jax.random.key(1)))
                                ).lower(params).compile()
                temps.append(c.memory_analysis().temp_size_in_bytes)
            grown[sched] = temps[1] - temps[0]
        assert grown["zb"] < 0.5 * grown["gpipe"], grown

    def test_live_activations_bounded_by_stages(self):
        """The property 1F1B exists for: growing the microbatch count
        grows GPipe's live residual memory (every tick's activations are
        saved for autodiff) but NOT 1F1B's (fixed 2S-slot input ring,
        backward chases forward). Measured from XLA's own buffer
        assignment, not inferred."""
        grown = {}
        for sched in ("gpipe", "1f1b"):
            temps = []
            for M in (4, 16):
                topo, model, params, batch = self._setup(sched, M=M)
                with jax.set_mesh(topo.mesh):
                    c = jax.jit(jax.value_and_grad(
                        lambda p: model.loss(p, batch,
                                             rng=jax.random.key(1)))
                                ).lower(params).compile()
                temps.append(c.memory_analysis().temp_size_in_bytes)
            grown[sched] = temps[1] - temps[0]
        # gpipe grows with M; 1f1b must grow far less (ring is
        # M-independent; small scheduling buffers may still vary)
        assert grown["1f1b"] < 0.5 * grown["gpipe"], grown

    def test_ring_capacity_is_stage_bound(self):
        from deepspeed_tpu.runtime.pipe.spmd import _ring_capacity
        assert _ring_capacity(4) == 8      # independent of microbatches


class TestZBOffloadMemory:
    """Backend-gated acceptance check: with a REAL host memory kind
    (TPU), the offloaded zero-bubble step's device temp bytes must
    drop vs offload-off — the live-HBM saving the 13B recipe depends
    on. Skipped where the platform has a single memory space (the CPU
    test mesh: staging is identity by design — host_stage docs)."""

    def test_offload_drops_device_temp_bytes(self):
        from deepspeed_tpu.runtime.swap_tensor import host_stage
        if not host_stage.available():
            pytest.skip("no distinct host memory kind on this backend")
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2Pipe
        from deepspeed_tpu.models.gpt2 import GPT2Config
        cfg = GPT2Config(n_layer=4, n_head=4, d_model=256,
                         max_seq_len=256, vocab_size=512,
                         dtype="float32", remat=True,
                         pipe_microbatches=8)
        temps = {}
        for offload in (False, True):
            groups.reset()
            topo = groups.initialize(
                TopologyConfig(pipe_parallel_size=2,
                               data_parallel_size=1),
                devices=jax.devices()[:2], force=True)
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=GPT2Pipe(cfg), topology=topo, config={
                    "train_micro_batch_size_per_gpu": 16,
                    "gradient_accumulation_steps": 1,
                    "steps_per_print": 0,
                    "optimizer": {"type": "AdamW",
                                  "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 0},
                    "pipeline": {"schedule": "zb",
                                 "offload_activations": offload}})
            ids = np.random.RandomState(0).randint(
                0, 512, (16, 256)).astype(np.int32)
            batch = jax.tree.map(engine._add_gas_dim,
                                 {"input_ids": ids})
            batch = engine._shard_batch(batch, with_gas_dim=True)
            with jax.set_mesh(engine.mesh):
                c = engine._train_step_jit.lower(
                    engine.state, batch, engine._current_lr(),
                    None).compile()
            temps[offload] = c.memory_analysis().temp_size_in_bytes
        assert temps[True] < temps[False], temps
